"""Driver benchmark. Prints ONE JSON line.

Two phases (round-2 verdict: the r1 bench measured a raw jitted loop and
bypassed the serving stack — "no TTFT number exists at all"):

1. kernel: steady-state fused multi-step decode throughput of the jitted
   engine step (the r1 number, kept for continuity).
2. e2e: the FULL serving path — OpenAI HTTP frontend, SSE streaming,
   preprocessor → router pipeline → engine scheduler → paged cache →
   detokenizer — driven closed-loop at fixed concurrency with the
   reference's harness-default workload shape (ISL/OSL from
   docs/benchmarks/benchmarking.md:33, scaled to the 1-chip bench budget).
   Reports decode tok/s through HTTP and p50/p95 TTFT.

The primary metric is the e2e decode throughput; vs_baseline compares
against the north-star 2000 decode tok/s/chip (BASELINE.md). TTFT and the
kernel number ride along in "extra".
"""

import asyncio
import json
import math
import os
import tempfile
import time

import numpy as np

BASELINE_TOK_S = 2000.0


def _p95(vals, default=0.0):
    """Shared interpolated p95 (observability/stats.quantile) — ONE
    estimator for the bench summaries, the flight summaries and the
    autoscaler's histogram tracker, so the three can never disagree about
    the same samples (nearest-rank `sorted[int(n*0.95)]` read an
    8-sample wave's p95 as its max)."""
    from dynamo_tpu.observability.stats import quantile

    q = quantile(list(vals), 0.95)
    return default if q is None else q


def _p50(vals, default=0.0):
    from dynamo_tpu.observability.stats import quantile

    q = quantile(list(vals), 0.50)
    return default if q is None else q
# v5e roofline (How to Scale Your Model / public TPU specs): util fields are
# measured against these even on CPU fallback runs, so numbers stay comparable.
HBM_BW_V5E = 819e9        # bytes/s HBM bandwidth per chip
PEAK_FLOPS_V5E = 197e12   # bf16 FLOP/s per chip


def _roofline(params, tok_s: float, reads_per_s: float, prefix: str) -> dict:
    """MFU / HBM-roofline fields. ``reads_per_s`` = full-model forward
    dispatches per second (each streams every weight byte from HBM once —
    a LOWER bound on traffic: KV-cache reads ride on top). ``tok_s`` must
    count every token that paid a model forward (prefill + decode) so the
    MFU numerator covers the same window as the traffic numerator."""
    import jax

    n_params = 0
    params_bytes = 0
    for path, x in jax.tree_util.tree_leaves_with_path(params):
        key = getattr(path[-1], "key", None) if path else None
        # TPU HBM packs two int4 weights per byte (quant.py); itemsize
        # reports 1, which would overstate hbm_util 2x on int4 runs
        nbytes = x.size // 2 if x.dtype.name == "int4" else x.size * x.dtype.itemsize
        params_bytes += nbytes
        # QTensor scale/zero leaves ('s'/'z') are dequant metadata, not
        # matmul parameters — keep them out of the MFU numerator
        if key not in ("s", "z"):
            n_params += x.size
    return {
        f"{prefix}_hbm_gbps": round(params_bytes * reads_per_s / 1e9, 1),
        f"{prefix}_hbm_util_v5e": round(
            params_bytes * reads_per_s / HBM_BW_V5E, 3),
        f"{prefix}_mfu_v5e": round(2.0 * n_params * tok_s / PEAK_FLOPS_V5E, 4),
        f"{prefix}_params_bytes": int(params_bytes),
    }


# -------------------------------------------------------------- observe smoke

#: span names one mock request through the full stack must produce
#: (acceptance: ≥6 named phases including TTFT and ITL)
OBSERVE_PHASES = (
    "http.request", "preprocess.tokenize", "router.schedule",
    "worker.handle", "engine.ttft", "engine.decode", "ttft", "itl",
)
#: Prometheus series /metrics must expose out of the box
OBSERVE_SERIES = (
    "dynamo_ttft_seconds", "dynamo_itl_seconds", "dynamo_e2e_seconds",
    "dynamo_phase_seconds",
)


async def observe_smoke() -> dict:
    """``bench.py --observe``: one mock request through the full serving
    stack, then assert the stitched trace (/v1/traces/{id}) contains the
    expected span set and /metrics exposes the SLO histograms. No
    accelerator needed (mocker engine) — wired into tier-1 as a fast test
    (tests/test_observability.py)."""
    import aiohttp

    from dynamo_tpu.frontend.http import HttpService
    from dynamo_tpu.llm.discovery import ModelManager, ModelWatcher
    from dynamo_tpu.llm.tokenizer import make_test_tokenizer
    from dynamo_tpu.mocker.engine import MockEngineArgs
    from dynamo_tpu.mocker.main import run_mocker
    from dynamo_tpu.observability import configure_tracer
    from dynamo_tpu.runtime import DistributedRuntime

    configure_tracer(service="observe")  # fresh buffer: hermetic assertions
    rt = await DistributedRuntime.create()
    # setup INSIDE the try: a failing start must not leak engine loops /
    # watcher tasks into the calling process (pytest runs this in-suite)
    engines, handles = [], []
    watcher = service = None
    try:
        args = MockEngineArgs(vocab_size=make_test_tokenizer().vocab_size,
                              block_size=4, num_gpu_blocks=128,
                              speedup_ratio=20.0)
        engines, handles = await run_mocker(rt, "observe", args)
        manager = ModelManager()
        watcher = await ModelWatcher(rt, manager, router_mode="kv").start()
        service = HttpService(manager, port=0, runtime=rt)
        await service.start()
        for _ in range(200):
            if manager.list_models():
                break
            await asyncio.sleep(0.05)
        else:
            raise RuntimeError("model never appeared in discovery")

        rid = "observe-smoke-request"
        base = f"http://127.0.0.1:{service.port}"
        async with aiohttp.ClientSession() as http:
            async with http.post(
                    f"{base}/v1/completions",
                    json={"model": "observe", "prompt": "hello tokens stream",
                          "max_tokens": 8, "stream": True,
                          "ignore_eos": True},
                    headers={"x-request-id": rid}) as resp:
                assert resp.status == 200, await resp.text()
                async for _ in resp.content:
                    pass
            async with http.get(f"{base}/v1/traces/{rid}") as resp:
                assert resp.status == 200, await resp.text()
                trace = await resp.json()
            async with http.get(f"{base}/metrics") as resp:
                assert resp.status == 200
                metrics_text = await resp.text()

        phases = set(trace["phases"])
        missing = [p for p in OBSERVE_PHASES if p not in phases]
        if missing:
            raise AssertionError(
                f"trace missing phases {missing}; got {sorted(phases)}")
        missing_series = [s for s in OBSERVE_SERIES if s not in metrics_text]
        if missing_series:
            raise AssertionError(f"/metrics missing {missing_series}")
        # every span must stitch: a recorded parent id that is absent from
        # the trace means a broken hop in the parenting chain
        ids = {s["span_id"] for s in trace["spans"]}
        orphans = [s["name"] for s in trace["spans"]
                   if s.get("parent_span_id") and s["parent_span_id"] not in ids]
        if orphans:
            raise AssertionError(f"orphaned spans (broken parenting): {orphans}")
        return {"observe": "ok", "spans": len(trace["spans"]),
                "phases": sorted(phases), "trace_id": trace["trace_id"]}
    finally:
        if service is not None:
            await service.stop()
        if watcher is not None:
            await watcher.stop()
        for h in handles:
            await h.stop(graceful=False)
        for e in engines:
            await e.stop()
        await rt.shutdown()


# --------------------------------------------------------------- kernel phase

#: chaos smoke gate: p95 under 1% drop injection must stay within this
#: factor of the clean p95 (completion rate must be exactly 1.0)
CHAOS_P95_BOUND = 5.0


async def chaos_smoke(spec: str = "stream.send:drop=0.01",
                      seed: int = 1234) -> dict:
    """Overload-protection smoke (docs/robustness.md): the same mocker
    stack twice — clean, then with ``spec`` injected (seeded) — asserting
    that every request still completes EXACTLY (migration + backoff absorb
    the faults) and p95 latency degradation stays bounded. No accelerator;
    runs in seconds."""
    import aiohttp

    from dynamo_tpu.frontend.http import HttpService
    from dynamo_tpu.llm.discovery import ModelManager, ModelWatcher
    from dynamo_tpu.llm.tokenizer import make_test_tokenizer
    from dynamo_tpu.mocker.engine import MockEngineArgs
    from dynamo_tpu.mocker.main import run_mocker
    from dynamo_tpu.runtime import DistributedRuntime
    from dynamo_tpu.runtime.chaos import configure_chaos

    N_REQ, OSL = 32, 16
    rt = await DistributedRuntime.create()
    manager = ModelManager()
    watcher = await ModelWatcher(rt, manager, router_mode="kv").start()
    service = HttpService(manager, port=0)
    await service.start()
    args = MockEngineArgs(vocab_size=make_test_tokenizer().vocab_size,
                          block_size=4, num_gpu_blocks=1024,
                          speedup_ratio=50.0)
    engines, handles = await run_mocker(rt, "chaos-bench", args,
                                        migration_limit=100)
    for _ in range(200):
        if manager.list_models():
            break
        await asyncio.sleep(0.05)
    url = f"http://127.0.0.1:{service.port}/v1/completions"

    async def one(session, i):
        t0 = time.perf_counter()
        complete = False
        try:
            async with session.post(url, json={
                    "model": "chaos-bench", "prompt": [10 + i, 11, 12, 13],
                    "max_tokens": OSL, "ignore_eos": True}) as r:
                if r.status == 200:
                    data = await r.json()
                    complete = data["usage"]["completion_tokens"] == OSL
        except Exception:  # noqa: BLE001 — a failed request counts as lost
            pass
        return complete, time.perf_counter() - t0

    async def wave():
        async with aiohttp.ClientSession() as session:
            res = await asyncio.gather(*[one(session, i)
                                         for i in range(N_REQ)])
        lats = sorted(lat for _ok, lat in res)
        rate = sum(1 for ok, _ in res if ok) / len(res)
        return rate, lats

    p95 = _p95  # shared interpolated estimator (observability/stats)

    try:
        clean_rate, clean = await wave()
        inj = configure_chaos(spec, seed=seed)
        try:
            chaos_rate, chaotic = await wave()
        finally:
            configure_chaos(None)
    finally:
        await service.stop()
        await watcher.stop()
        for handle in handles:
            await handle.stop(graceful=False)
        for engine in engines:
            await engine.stop()
        await rt.shutdown()

    ratio = round(p95(chaotic) / max(p95(clean), 1e-9), 2)
    return {
        "chaos_spec": spec,
        "chaos_seed": seed,
        "clean_completion_rate": clean_rate,
        "chaos_completion_rate": chaos_rate,
        "clean_p95_ms": round(p95(clean) * 1000, 1),
        "chaos_p95_ms": round(p95(chaotic) * 1000, 1),
        "chaos_p95_ratio": ratio,
        "chaos_faults_fired": sum(inj.counts.values()),
        "chaos_ok": (chaos_rate == 1.0 and clean_rate == 1.0
                     and ratio <= CHAOS_P95_BOUND),
    }


def kernel_bench(on_tpu: bool, quantization=None, kv_int8=False) -> dict:
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.engine import model as M
    from dynamo_tpu.engine.cache import allocate_device_cache
    from dynamo_tpu.engine.config import ModelConfig

    if on_tpu:
        cfg = ModelConfig.llama3_1b()
        B, kv_len, iters, K = 64, 512, 50, 16
    else:
        cfg = ModelConfig.tiny()
        B, kv_len, iters, K = 8, 64, 10, 4

    block_size = 16
    W = (kv_len + K + block_size - 1) // block_size
    num_blocks = B * W + 1

    params = M.init_params(cfg, jax.random.key(0))
    if quantization:
        from dynamo_tpu.engine.quant import quantize_params

        params = jax.device_put(quantize_params(
            jax.tree.map(np.asarray, params), quantization))
    k_cache, v_cache = allocate_device_cache(
        cfg, num_blocks, block_size, dtype="int8" if kv_int8 else None)

    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (B,)), jnp.int32)
    positions = jnp.full((B,), kv_len - 1, jnp.int32)
    bt = np.zeros((B, W), np.int32)
    for i in range(B):
        bt[i] = 1 + i * W + np.arange(W)
    block_tables = jnp.asarray(bt)
    kv_lens = jnp.full((B,), kv_len, jnp.int32)

    multi = M.make_multi_decode_fn(cfg, block_size, K)
    # packed layout: ints=[last_tokens, positions, kv_lens, top_k],
    # floats=[temp, top_p], rand=[seeds, step0]
    ints = jnp.stack([tokens, positions, kv_lens,
                      jnp.zeros((B,), jnp.int32)], axis=1)
    floats = jnp.stack([jnp.zeros((B,), jnp.float32),
                        jnp.ones((B,), jnp.float32)], axis=1)
    rand = jnp.zeros((B, 2), jnp.uint32)

    def burst(kc, vc):
        return multi(params, ints, floats, rand, block_tables, kc, vc)

    toks, logps, k_cache, v_cache = burst(k_cache, v_cache)  # compile
    int(toks[0, 0])

    t0 = time.perf_counter()
    for _ in range(iters):
        toks, logps, k_cache, v_cache = burst(k_cache, v_cache)
    # block_until_ready alone is unreliable over the remote-chip tunnel; a
    # small device->host fetch forces completion of the donated-cache chain
    int(toks[-1, 0])
    dt = time.perf_counter() - t0
    tok_s = B * K * iters / dt
    tag = ("kernel" if not quantization
           else f"kernel_{quantization.replace('-', '_')}")
    if kv_int8:
        tag += "_kv8"
    return {f"{tag}_tok_s": round(tok_s, 1),
            f"{tag}_shape": f"B={B},kv={kv_len},K={K}",
            **_roofline(params, tok_s, iters * K / dt, tag)}


# ------------------------------------------------------------------ e2e phase

def _write_tokenizer_dir(path: str, vocab_size: int) -> None:
    """WordLevel tokenizer whose vocab covers the model's sampled ids, so
    random-weight outputs detokenize through the production DecodeStream."""
    from tokenizers import Tokenizer
    from tokenizers.models import WordLevel
    from tokenizers.pre_tokenizers import Whitespace

    vocab = {f"w{i}": i for i in range(vocab_size)}
    tk = Tokenizer(WordLevel(vocab, unk_token="w0"))
    tk.pre_tokenizer = Whitespace()
    tk.save(os.path.join(path, "tokenizer.json"))
    with open(os.path.join(path, "tokenizer_config.json"), "w") as f:
        json.dump({"chat_template": "{% for m in messages %}{{ m['content'] }}"
                                    "{% endfor %}"}, f)


async def _e2e(on_tpu: bool) -> dict:
    import aiohttp

    from dynamo_tpu.disagg.handlers import DecodeWorkerHandler
    from dynamo_tpu.engine.config import EngineArgs, ModelConfig
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.frontend.http import HttpService
    from dynamo_tpu.llm.discovery import ModelManager, ModelWatcher
    from dynamo_tpu.llm.model_card import ModelDeploymentCard, register_llm
    from dynamo_tpu.runtime import DistributedRuntime

    if on_tpu:
        cfg = ModelConfig.llama3_1b()
        ISL, OSL, CONC, N_REQ, N_WARM = 1024, 128, 32, 64, 8
        args = EngineArgs(
            block_size=16, max_num_seqs=64, max_num_batched_tokens=2048,
            # K=16: each burst costs one dispatch+fetch round trip
            # (~70-150 ms over the tunnel) regardless of K — 16 halves the
            # per-token overhead vs 8 and divides OSL=128 evenly
            max_model_len=2048, multi_step_decode=16,
            use_pallas_attention=True,
            # pin the shape buckets so the run compiles a handful of programs
            prefill_buckets=(1024, 2048), decode_batch_buckets=(32, 64))
    else:
        cfg = ModelConfig.tiny()
        ISL, OSL, CONC, N_REQ, N_WARM = 64, 16, 4, 8, 2
        args = EngineArgs(block_size=16, num_blocks=256, max_num_seqs=8,
                          max_num_batched_tokens=256, max_model_len=256)

    tmp = tempfile.mkdtemp(prefix="bench-tk-")
    _write_tokenizer_dir(tmp, cfg.vocab_size)

    rt = await DistributedRuntime.create()
    eng = AsyncJaxEngine(cfg, args)
    # AOT bucket warmup at the workload's sequence length: the remaining
    # HTTP warmup loop below then only exercises serving-path caches, not
    # XLA compiles (the old first-request compiles were the TTFT p95 cliff)
    warm_report = await eng.warmup(seq_lens=[ISL + OSL],
                                   prefill_batches=[1, CONC])
    handler = DecodeWorkerHandler(eng)
    ep = rt.namespace("dynamo").component("backend").endpoint("generate")
    handle = await ep.serve_endpoint(handler.generate)
    card = ModelDeploymentCard(
        display_name="bench", kv_cache_block_size=args.block_size,
        eos_token_ids=[], tokenizer_ref=tmp,
        context_length=args.max_model_len)
    card.runtime_config.total_kv_blocks = eng.num_blocks
    await register_llm(rt, ep, card)

    manager = ModelManager()
    watcher = await ModelWatcher(rt, manager, router_mode="kv").start()
    service = HttpService(manager, port=0)
    await service.start()
    for _ in range(200):
        if manager.list_models():
            break
        await asyncio.sleep(0.05)
    else:
        raise RuntimeError("model never appeared in discovery")

    url = f"http://127.0.0.1:{service.port}/v1/completions"
    rng = np.random.default_rng(7)

    async def one_request(session: aiohttp.ClientSession) -> tuple[float, int]:
        """Returns (ttft_seconds, tokens_received). Distinct random prompts
        defeat the prefix cache — every request pays a full prefill."""
        prompt = rng.integers(1, cfg.vocab_size, ISL).tolist()
        t0 = time.perf_counter()
        ttft, n_tok = None, 0
        async with session.post(url, json={
                "model": "bench", "prompt": prompt, "stream": True,
                "max_tokens": OSL, "ignore_eos": True,
                "temperature": 0.0}) as resp:
            assert resp.status == 200, await resp.text()
            async for raw in resp.content:
                line = raw.decode()
                if not line.startswith("data: ") or line.startswith("data: [DONE]"):
                    continue
                payload = json.loads(line[6:])
                if "error" in payload:  # in-band SSE error: fail the bench
                    raise RuntimeError(f"engine error mid-stream: {payload}")
                if ttft is None:
                    ttft = time.perf_counter() - t0
                n_tok += 1
        return ttft, n_tok

    async def closed_loop(session, n_left: list, results: list):
        while True:
            if not n_left:
                return
            n_left.pop()
            results.append(await one_request(session))

    conn = aiohttp.TCPConnector(limit=0)
    async with aiohttp.ClientSession(connector=conn) as session:
        # warmup: trigger the compile set (prefill buckets, decode buckets)
        warm_left, warm_res = [0] * N_WARM, []
        await asyncio.gather(*[closed_loop(session, warm_left, warm_res)
                               for _ in range(CONC)])
        reads0 = eng.param_reads
        t0 = time.perf_counter()
        n_left, results = [0] * N_REQ, []
        await asyncio.gather(*[closed_loop(session, n_left, results)
                               for _ in range(CONC)])
        elapsed = time.perf_counter() - t0
        reads = eng.param_reads - reads0

    await service.stop()
    await watcher.stop()
    await handle.stop(graceful=False)
    await eng.close()
    await rt.shutdown()

    ttfts = sorted(r[0] for r in results if r[0] is not None)
    total_tokens = sum(r[1] for r in results)
    return {
        "e2e_tok_s": round(total_tokens / elapsed, 1),
        "ttft_p50_ms": round(1000 * _p50(ttfts), 1),
        "ttft_p95_ms": round(1000 * _p95(ttfts), 1),
        "workload": f"ISL={ISL},OSL={OSL},conc={CONC},n={N_REQ}",
        # per-step-kind timing aggregates (the first thing to read when e2e
        # trails the kernel — see docs/performance.md) + how much of the
        # decode ran through the pipelined loop
        "step_trace": eng.step_trace_summary(),
        "pipelined_steps": eng.pipelined_steps,
        "warmup": {k: (len(v) if isinstance(v, list) else v)
                   for k, v in warm_report.items()},
        # MFU counts prefill (N_REQ × ISL) + decode tokens — the traffic
        # numerator (param_reads) covers both, so both fields share scope
        **_roofline(eng.params,
                    (total_tokens + N_REQ * ISL) / elapsed,
                    reads / elapsed, "e2e"),
    }


async def _spec_bench(on_tpu: bool) -> dict:
    """Speculative-decode phase: decode throughput with and without
    prompt-lookup drafting on a REPETITIVE workload (where lookup drafts
    land), plus the measured acceptance rate — the SpecDecodeStats
    telemetry surface, on record whenever the bench runs."""
    from dynamo_tpu.engine.config import EngineArgs, ModelConfig
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.protocols import (PreprocessedRequest, SamplingOptions,
                                      StopConditions)

    if on_tpu:
        cfg = ModelConfig.llama3_1b()
        N, OSL, ISL = 8, 64, 256
        base = dict(block_size=16, max_num_seqs=8,
                    max_num_batched_tokens=512, max_model_len=512,
                    num_blocks=512, use_pallas_attention=True,
                    prefill_buckets=(256,), decode_batch_buckets=(8,))
    else:
        cfg = ModelConfig.tiny()
        N, OSL, ISL = 4, 24, 64
        base = dict(block_size=4, max_num_seqs=4,
                    max_num_batched_tokens=64, max_model_len=128,
                    num_blocks=256, prefill_buckets=(64,),
                    decode_batch_buckets=(4,))
    cycle = list(range(5, 21))
    prompts = [((cycle[i:] + cycle[:i]) * ISL)[:ISL] for i in range(N)]

    async def measure(spec: bool, method: str = "prompt_lookup",
                      draft_layers: int = 0):
        eng = AsyncJaxEngine(cfg, EngineArgs(
            **base, speculative_tokens=4 if spec else 0,
            speculative_method=method,
            speculative_draft_layers=draft_layers))

        async def one(p):
            req = PreprocessedRequest(
                model="b", token_ids=p,
                stop_conditions=StopConditions(max_tokens=OSL,
                                               ignore_eos=True),
                sampling_options=SamplingOptions(temperature=0.0))
            n = 0
            async for out in eng.generate(req):
                n += len(out.token_ids)
            return n

        await asyncio.gather(*[one(p) for p in prompts])  # warm compiles
        t0 = time.perf_counter()
        total = sum(await asyncio.gather(*[one(p) for p in prompts]))
        dt = time.perf_counter() - t0
        st = eng.spec_stats
        accept = (st.num_accepted_tokens / st.num_draft_tokens
                  if st.num_draft_tokens else 0.0)
        await eng.close()
        return total / dt, accept

    spec_tok_s, accept = await measure(True)
    plain_tok_s, _ = await measure(False)
    # layer-skip self-drafting (draft_layers): unlike prompt lookup it
    # drafts EVERY step (model-based, works on non-repetitive traffic);
    # cost is draft_layers/num_layers of a forward per drafted token —
    # VERDICT r4 weak #6 wanted this path on the bench record
    dl = max(1, cfg.num_layers // 4)
    draft_tok_s, draft_accept = await measure(True, method="draft_layers",
                                              draft_layers=dl)
    return {
        "spec_decode_tok_s": round(spec_tok_s, 1),
        "nospec_decode_tok_s": round(plain_tok_s, 1),
        "spec_accept_rate": round(accept, 3),
        "spec_gain": round(spec_tok_s / plain_tok_s, 3)
        if plain_tok_s else 0.0,
        "spec_draft_model_tok_s": round(draft_tok_s, 1),
        "spec_draft_model_accept_rate": round(draft_accept, 3),
        "spec_draft_model_gain": round(draft_tok_s / plain_tok_s, 3)
        if plain_tok_s else 0.0,
        "spec_draft_model_layers": dl,
        "spec_workload": f"repetitive ISL={ISL},OSL={OSL},n={N},K=4",
    }


async def mem_pressure_bench(on_tpu: bool = False) -> dict:
    """``bench.py --mem-pressure``: oversubscribed KV scenario (pool sized
    to ~half the working set) run twice on the same seeded workload — with
    preempt-to-swap, then with forced recompute preemption — reporting
    decode tok/s, recomputed-prefill tokens, and the swap counters.

    The acceptance surface for ISSUE 4: swap must recompute strictly fewer
    prefill tokens and hold ≥ the recompute throughput (on hardware the
    target is ≥ 1.2×). Wired into tier-1 via tests/test_swap.py.
    """
    from dynamo_tpu.engine.config import EngineArgs, ModelConfig
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.protocols import (PreprocessedRequest, SamplingOptions,
                                      StopConditions)

    if on_tpu:
        cfg = ModelConfig.llama3_1b()
        N, ISL, OSL, bs, frac = 16, 512, 128, 16, 0.45
        extra = dict(use_pallas_attention=True)
    else:
        cfg = ModelConfig.tiny()
        # long-ish prompts: the recompute path's waste is re-PREFILL work,
        # so the swap advantage scales with ISL (measured 1.26x here)
        N, ISL, OSL, bs, frac = 6, 192, 48, 4, 0.45
        extra = {}
    # pool ≈ half the peak working set → sustained preemption pressure
    working_blocks = N * ((ISL + OSL + bs - 1) // bs)
    num_blocks = max(8, int(working_blocks * frac)) + 1  # +1: NULL block
    base = dict(block_size=bs, num_blocks=num_blocks, max_num_seqs=N,
                max_num_batched_tokens=max(64, ISL),
                max_model_len=2 * (ISL + OSL),
                prefill_buckets=(ISL,), decode_batch_buckets=(N,),
                enable_prefix_caching=False, **extra)
    rng = np.random.default_rng(11)
    prompts = [rng.integers(1, cfg.vocab_size, ISL).tolist() for _ in range(N)]

    async def measure(swap: bool) -> dict:
        eng = AsyncJaxEngine(cfg, EngineArgs(**base, preempt_swap=swap))

        async def one(p):
            req = PreprocessedRequest(
                model="m", token_ids=list(p),
                stop_conditions=StopConditions(max_tokens=OSL,
                                               ignore_eos=True),
                sampling_options=SamplingOptions(temperature=0.0))
            n = 0
            async for out in eng.generate(req):
                n += len(out.token_ids)
            return n

        await asyncio.gather(*[one(p) for p in prompts])  # warm compiles
        t0 = time.perf_counter()
        total = sum(await asyncio.gather(*[one(p) for p in prompts]))
        dt = time.perf_counter() - t0
        stats = eng.swap_stats()
        await eng.close()
        assert total == N * OSL, f"lost tokens: {total} != {N * OSL}"
        return {"tok_s": total / dt, **stats}

    s = await measure(True)
    r = await measure(False)
    return {
        "mem_pressure_workload": (f"ISL={ISL},OSL={OSL},n={N},"
                                  f"blocks={num_blocks}"),
        "swap_tok_s": round(s["tok_s"], 1),
        "recompute_tok_s": round(r["tok_s"], 1),
        "swap_vs_recompute": round(s["tok_s"] / max(r["tok_s"], 1e-9), 3),
        "swap_recomputed_tokens": s["recomputed_tokens"],
        "recompute_recomputed_tokens": r["recomputed_tokens"],
        "swap_preemptions": s["preempt_swap"],
        "recompute_preemptions": r["preempt_recompute"],
        "swap_out_blocks": s["swap_out_blocks"],
        "swap_in_blocks": s["swap_in_blocks"],
    }


async def qos_bench(on_tpu: bool = False, reps: int = 4) -> dict:
    """``bench.py --qos``: multi-tenant isolation under 2x oversubscription
    (docs/qos.md).

    Two tenants share one engine whose KV pool holds ~half the combined
    working set and whose seq slots hold half the offered concurrency: a
    ``batch``-class tenant floods first, then an ``interactive``-class
    tenant arrives. Three runs on the same seeded workload:

    1. unloaded — the interactive workload alone (its baseline TTFT),
    2. qos      — mixed, QoS scheduling on (weighted-fair admission +
                  priority preemption through the swap tier),
    3. fifo     — mixed, QoS scheduling off (the pre-QoS scheduler).

    Acceptance (ISSUE 5): interactive TTFT p95 under QoS stays ≤ 1.2x its
    unloaded value while aggregate decode tok/s holds ≥ 0.9x FIFO, and the
    batch tenant still completes every request (no starvation).
    """
    from dynamo_tpu.engine.config import EngineArgs, ModelConfig
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.protocols import (PreprocessedRequest, SamplingOptions,
                                      StopConditions)
    from dynamo_tpu.runtime.context import Context

    if on_tpu:
        cfg = ModelConfig.llama3_1b()
        bs = 16
        N_I, ISL_I, OSL_I = 8, 128, 32
        N_B, ISL_B, OSL_B = 12, 512, 64
        slots = 10
        extra = dict(use_pallas_attention=True)
    else:
        cfg = ModelConfig.tiny()
        bs = 4
        N_I, ISL_I, OSL_I = 8, 32, 16
        # batch OSL long enough to amortize the swap preemptions the
        # interactive wave triggers — the regime of interest is sustained
        # decode under oversubscription, not a prefill sprint
        N_B, ISL_B, OSL_B = 8, 128, 64
        slots = 8  # 16 offered seqs -> 2x compute oversubscription
        extra = {}
    working = (N_B * ((ISL_B + OSL_B + bs - 1) // bs)
               + N_I * ((ISL_I + OSL_I + bs - 1) // bs))
    num_blocks = working // 2 + 1  # 2x KV oversubscription (+ NULL block)
    base = dict(block_size=bs, num_blocks=num_blocks, max_num_seqs=slots,
                # budget for several prompt-bucket rows per step: an
                # interactive chunk rides the same jitted call as
                # concurrent batch prompt chunks instead of waiting a step
                # behind them
                max_num_batched_tokens=2 * max(ISL_B, 128),
                max_model_len=2 * (ISL_B + OSL_B),
                prefill_buckets=(max(ISL_B, 128),),
                decode_batch_buckets=(1 << (slots - 1).bit_length(),),
                enable_prefix_caching=False, **extra)
    rng = np.random.default_rng(23)
    int_prompts = [rng.integers(1, cfg.vocab_size, ISL_I).tolist()
                   for _ in range(N_I)]
    bat_prompts = [rng.integers(1, cfg.vocab_size, ISL_B).tolist()
                   for _ in range(N_B)]

    def req(tokens, osl):
        return PreprocessedRequest(
            model="m", token_ids=list(tokens),
            stop_conditions=StopConditions(max_tokens=osl, ignore_eos=True),
            sampling_options=SamplingOptions(temperature=0.0))

    async def one(eng, tokens, osl, ctx):
        """(ttft_s, n_tokens) for one request."""
        t0 = time.perf_counter()
        ttft, n = None, 0
        async for out in eng.generate(req(tokens, osl), ctx):
            if ttft is None and out.token_ids:
                ttft = time.perf_counter() - t0
            n += len(out.token_ids)
        return ttft, n

    def ctx(tenant, cls):
        return Context(tenant=tenant, priority=cls)

    async def interactive_wave(eng):
        return await asyncio.gather(*[
            one(eng, p, OSL_I, ctx("tenant-int", "interactive"))
            for p in int_prompts])

    async def mixed(eng):
        """Batch floods first; interactive arrives once batch occupies the
        engine. Returns (int_results, bat_results, elapsed_s)."""
        t0 = time.perf_counter()
        bat = [asyncio.ensure_future(
            one(eng, p, OSL_B, ctx("tenant-bat", "batch")))
            for p in bat_prompts]
        for _ in range(20000):  # wait until batch has occupied the engine
            if (len(eng.scheduler.running) >= min(slots, N_B) - 1
                    and any(s.num_computed > 0
                            for s in eng.scheduler.running)):
                break
            await asyncio.sleep(0.001)
        ints = [asyncio.ensure_future(
            one(eng, p, OSL_I, ctx("tenant-int", "interactive")))
            for p in int_prompts]
        int_res = await asyncio.gather(*ints)
        bat_res = await asyncio.gather(*bat)
        return int_res, bat_res, time.perf_counter() - t0

    p95 = _p95  # shared interpolated estimator (observability/stats)

    async def run_phase(qos: bool, mixed_load: bool):
        """Warm pass (compiles every bucket), then ``reps`` timed passes;
        per-metric best-of — wall-clock noise on a 2-core shared host
        swings single-rep ratios by ±40%, so each metric keeps its best
        rep while the structural counters accumulate across all of them."""
        eng = AsyncJaxEngine(cfg, EngineArgs(**base, qos_scheduling=qos))
        out: dict = {}
        if mixed_load:
            await mixed(eng)
            stats0 = dict(eng.qos_stats()["preemptions"])
            for _ in range(reps):
                int_res, bat_res, dt = await mixed(eng)
                tok_s = (sum(n for _, n in int_res)
                         + sum(n for _, n in bat_res)) / dt
                if not out or tok_s > out["tok_s"]:
                    out["tok_s"] = tok_s
                # pool TTFT samples across reps: the p95 of one 8-request
                # wave is just its max, and a single event-loop hiccup on
                # one request would masquerade as a policy failure
                out.setdefault("int_ttfts", []).extend(
                    t for t, _ in int_res)
                out.setdefault("bat_tokens", []).append(
                    sum(n for _, n in bat_res))
            stats = eng.qos_stats()["preemptions"]
            preempts = {k: v - stats0.get(k, 0) for k, v in stats.items()
                        if v - stats0.get(k, 0)}
            out["preempts_by_class"] = {c: n for (_t, c), n
                                        in preempts.items()}
        else:
            await interactive_wave(eng)
            for _ in range(reps):
                t0 = time.perf_counter()
                int_res = await interactive_wave(eng)
                dt = time.perf_counter() - t0
                tok_s = sum(n for _, n in int_res) / dt
                if not out or tok_s > out["tok_s"]:
                    out["tok_s"] = tok_s
                out.setdefault("int_ttfts", []).extend(
                    t for t, _ in int_res)
        await eng.close()
        return out

    unloaded = await run_phase(qos=True, mixed_load=False)
    qos = await run_phase(qos=True, mixed_load=True)
    fifo = await run_phase(qos=False, mixed_load=True)

    unloaded_p95 = p95(unloaded["int_ttfts"])
    qos_p95 = p95(qos["int_ttfts"])
    fifo_p95 = p95(fifo["int_ttfts"])
    return {
        "qos_workload": (f"int={N_I}x(ISL={ISL_I},OSL={OSL_I}) "
                         f"batch={N_B}x(ISL={ISL_B},OSL={OSL_B}) "
                         f"slots={slots} blocks={num_blocks}"),
        "unloaded_int_ttft_p95_ms": round(unloaded_p95 * 1000, 1),
        "qos_int_ttft_p95_ms": round(qos_p95 * 1000, 1),
        "fifo_int_ttft_p95_ms": round(fifo_p95 * 1000, 1),
        "qos_ttft_vs_unloaded": round(qos_p95 / max(unloaded_p95, 1e-9), 3),
        "fifo_ttft_vs_unloaded": round(fifo_p95 / max(unloaded_p95, 1e-9), 3),
        "qos_tok_s": round(qos["tok_s"], 1),
        "fifo_tok_s": round(fifo["tok_s"], 1),
        "qos_vs_fifo_tok_s": round(qos["tok_s"] / max(fifo["tok_s"], 1e-9),
                                   3),
        "batch_completed": min(qos["bat_tokens"]),  # worst rep: starvation
        "batch_expected": N_B * OSL_B,
        "qos_preempts_by_class": qos["preempts_by_class"],
    }


async def disagg_bench() -> dict:
    """``bench.py`` ``disagg`` phase: the network-aware disaggregation
    A/Bs (ISSUE 9 acceptance; docs/disagg.md).

    1. **Placement**: topology-costed KV routing vs topology-blind over a
       multi-worker in-process fleet (2 prefill + 4 decode, half the
       decode pool a far pod away across an emulated slow link) — same
       workload, same seed. Gate: blind foreground TTFT p95 must be
       ≥ 1.2x the topology-aware arm's (measured ~3.4x on tiny-cpu).
    2. **Layer interleave**: layer-split vs whole-bundle tail transfer on
       one pair, paired per-rep against a free-wire baseline. Gate: the
       split's transfer-exposed TTFT gap must not exceed the whole-bundle
       gap (measured ~0.6x on tiny-cpu).
    """
    from benchmarks.disagg_ab import fleet_ab, layer_ab

    fleet = await fleet_ab(prefill_workers=2, decode_workers=4, fg=12,
                           seed=0)
    layer = await layer_ab(reps=6)
    placement_ratio = fleet.get("ttft_p95_ratio_blind_over_topo") or 0.0
    gap_ratio = layer.get("gap_ratio_split_over_whole")
    ok = placement_ratio >= 1.2 and (gap_ratio is None or gap_ratio <= 1.0)
    return {"fleet": fleet, "layer": layer,
            "placement_ratio": placement_ratio,
            "layer_gap_ratio": gap_ratio, "disagg_ok": ok}


async def migration_bench(on_tpu: bool = False, reps: int = 2,
                          isl: int = 8192, osl: int = 48,
                          streams: int = 4) -> dict:
    """``bench.py --migration``: KV-restore migration under seeded worker
    kills (ISSUE 10 acceptance; docs/robustness.md "stateful migration").

    A 3-worker tiny-cpu fleet (A serves, B holds the shared 8k prefix, C
    is cold) is driven through a seeded ``worker.kill`` chaos death of A
    mid-decode: its streams break on lease expiry, Migration re-issues
    them with restore hints, and C rebuilds the prefix — by peer pull
    from B (restore arm) or by re-prefilling it (recompute arm, restore
    disabled). Arms are interleaved per rep so host drift cancels. The
    recompute arm's N concurrent re-prefills land exactly when the fleet
    is short one worker — the storm stateful migration exists to absorb
    (measured 7.0 s resume p95 vs 1.3 s restored at 8k ISL).

    Gates: 100% stream completion with zero lost/duplicated tokens in
    BOTH arms, restore actually pulled blocks, and the post-kill
    TTFT-to-resume p95 (re-dispatch → first resumed token, excluding the
    identical lease-expiry wait) satisfies restore/recompute ≤ 0.7.
    """
    from dynamo_tpu.disagg.handlers import DecodeWorkerHandler, KvPullHandler
    from dynamo_tpu.disagg.transfer import RestoreConfig
    from dynamo_tpu.engine.config import EngineArgs, ModelConfig
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.llm.pipeline import Migration, is_event
    from dynamo_tpu.protocols import (PreprocessedRequest, SamplingOptions,
                                      StopConditions)
    from dynamo_tpu.router.kv_router import KvPushRouter, KvRouter
    from dynamo_tpu.router.protocols import KvRouterConfig
    from dynamo_tpu.router.publisher import KvEventPublisher
    from dynamo_tpu.runtime import DistributedRuntime
    from dynamo_tpu.runtime.chaos import configure_chaos
    from dynamo_tpu.runtime.config import RuntimeConfig
    from dynamo_tpu.runtime.context import Context

    cfg = ModelConfig.tiny()
    bs = 16
    blocks_needed = (isl + 64 + osl) // bs + 8
    eargs = dict(block_size=bs, num_blocks=2 * blocks_needed + 64,
                 max_num_seqs=streams + 2,
                 max_num_batched_tokens=1024,
                 max_model_len=isl + 64 + osl + bs,
                 enable_prefix_caching=True)
    rng = np.random.default_rng(42)
    prefix = rng.integers(1, cfg.vocab_size, isl).tolist()

    def req(suffix, pin=None, restore=None):
        return PreprocessedRequest(
            model="m", token_ids=prefix + suffix,
            stop_conditions=StopConditions(max_tokens=osl, ignore_eos=True),
            sampling_options=SamplingOptions(temperature=0.0),
            backend_instance_id=pin, restore=restore)

    async def one_rep(restore_on: bool, rep: int) -> dict:
        # TTL high enough that an XLA compile blocking the shared event
        # loop can't starve a healthy worker's keepalive (all in-process
        # workers share one loop); the kill-detection latency this adds
        # is identical in both arms and excluded from the resume metric
        rcfg = RuntimeConfig(lease_ttl=4.0, worker_lost_grace=1.0)
        rt = await DistributedRuntime.create(config=rcfg)
        workers = []
        try:
            for _ in range(3):
                wrt = await DistributedRuntime.create(
                    plane=rt.plane, owns_plane=False, config=rcfg)
                lease = await wrt.primary_lease()
                eng = await asyncio.to_thread(
                    AsyncJaxEngine, cfg, EngineArgs(**eargs))
                pub = KvEventPublisher(wrt.plane, worker_id=lease,
                                       kv_block_size=bs)
                await pub.start_resync_responder()
                eng.event_cb = pub.publish_sync
                comp = wrt.namespace("dynamo").component("backend")
                pull_client = await comp.endpoint(
                    "kv_pull").client().start()
                handler = DecodeWorkerHandler(
                    eng, pull_clients=[pull_client],
                    restore_config=RestoreConfig(enabled=restore_on))
                handler.instance_id = lease
                h_gen = await comp.endpoint("generate").serve_endpoint(
                    handler.generate, lease_id=lease)
                h_pull = await comp.endpoint("kv_pull").serve_endpoint(
                    KvPullHandler(eng).generate, lease_id=lease)
                w = type("W", (), {})()
                w.rt, w.engine, w.lease = wrt, eng, lease
                w.handler, w.pub = handler, pub
                w.handles = [h_gen, h_pull]
                w.killed = False
                workers.append(w)
            a, b, c = workers
            client = await (rt.namespace("dynamo").component("backend")
                            .endpoint("generate").client().start())
            router = await KvRouter(rt.plane, bs, KvRouterConfig()).start()
            push = KvPushRouter(client, router)

            # restore-dispatch instrumentation: re-dispatch → first token
            resume = []

            async def instrumented(r, ctx):
                t0 = time.perf_counter()
                migrated = r.restore is not None
                first = True
                async for out in push.generate(r, ctx):
                    if (first and migrated and not is_event(out)
                            and isinstance(out, dict)
                            and out.get("token_ids")):
                        resume.append(time.perf_counter() - t0)
                        first = False
                    yield out

            mig = Migration(instrumented, migration_limit=3)

            async def drain(r, ctx=None):
                n = 0
                async for out in mig.generate(r, ctx or Context()):
                    if is_event(out):
                        continue
                    n += len(out.token_ids
                             if hasattr(out, "token_ids")
                             else out.get("token_ids") or [])
                return n

            # Warm every worker's compile surface OFF the measured path:
            # a full-ISL request with an UNRELATED prefix (prefill chunk +
            # ragged/decode signatures — the recompute arm's resume must
            # measure re-prefill execution, not XLA compilation on cold
            # C), plus the width-256 gather/scatter programs the restore
            # pull/attach path dispatches (B serves, C scatters).
            warm_prefix = rng.integers(1, cfg.vocab_size, isl).tolist()

            async def warm(w, i):
                await drain(req_raw(warm_prefix + [9500 + i], pin=w.lease))
                from dynamo_tpu.ops.block_copy import (gather_blocks,
                                                       scatter_blocks)
                eng = w.engine
                ids = list(range(1, min(257, eng.num_blocks)))
                kb = np.asarray(gather_blocks(eng.k_cache, ids,
                                              block_size=bs))
                vb = np.asarray(gather_blocks(eng.v_cache, ids,
                                              block_size=bs))
                eng.k_cache = scatter_blocks(eng.k_cache, ids, kb,
                                             block_size=bs)
                eng.v_cache = scatter_blocks(eng.v_cache, ids, vb,
                                             block_size=bs)

            def req_raw(tokens, pin=None):
                return PreprocessedRequest(
                    model="m", token_ids=list(tokens),
                    stop_conditions=StopConditions(max_tokens=4,
                                                   ignore_eos=True),
                    sampling_options=SamplingOptions(temperature=0.0),
                    backend_instance_id=pin)

            for i, w in enumerate(workers):
                await warm(w, i)
                # drop the warm prefix from the pool so it can't shadow
                # the measured restore (and from the radix, via events)
                w.engine.pool.clear()
            # B computes (and keeps) the shared prefix
            await drain(req([9001], pin=b.lease))
            # steer the measured streams onto A
            client.set_busy_instances([b.lease, c.lease])
            restored_blocks = [0]

            async def spy(r, cx, _h=c.handler):
                info = await DecodeWorkerHandler._restore_migrated(
                    _h, r, cx)
                restored_blocks[0] += info.get("restored_blocks", 0)
                return info

            c.handler._restore_migrated = spy

            async def one_stream(i):
                return await drain(req([9100 + rep * 16 + i]))

            async def killer():
                """Arm seeded worker.kill once A is decoding; after it
                fires, steer the migrations to cold C. Bounded waits: a
                missed kill degrades the rep, never hangs the bench."""
                for _ in range(6000):
                    if any(s.generated >= 2
                           for s in a.engine.scheduler.running):
                        break
                    await asyncio.sleep(0.01)
                else:
                    return None
                configure_chaos("worker.kill:error=0.5", seed=100 + rep)
                for _ in range(6000):
                    if a.engine.killed:
                        break
                    await asyncio.sleep(0.01)
                configure_chaos(None)
                if not a.engine.killed:
                    return None
                a.killed = True
                for h in a.handles:
                    await h.kill()
                if a.rt._keepalive_task is not None:
                    a.rt._keepalive_task.cancel()
                client.set_busy_instances([b.lease])
                return time.perf_counter()

            t0 = time.perf_counter()
            kill_task = asyncio.ensure_future(killer())
            counts = await asyncio.gather(
                *[one_stream(i) for i in range(streams)])
            t_kill = await kill_task
            return {
                "counts": list(counts),
                "complete": all(n == osl for n in counts),
                "killed": t_kill is not None,
                "resume_s": list(resume),
                "restored_blocks": restored_blocks[0],
                "wall_s": time.perf_counter() - t0,
                "kill_to_done_s": (time.perf_counter() - t_kill
                                   if t_kill is not None else None),
            }
        finally:
            configure_chaos(None)
            for w in workers:
                for h in w.handles:
                    if not w.killed:
                        await h.stop(graceful=False)
                await w.pub.stop()
                if not w.killed:
                    await w.engine.close()
                else:
                    w.engine._closed = True
                    w.engine._wake.set()
                await w.rt.shutdown()
            try:
                await router.stop()
                await client.stop()
            except UnboundLocalError:
                pass
            await rt.shutdown()

    p95 = _p95  # shared interpolated estimator (observability/stats)

    arms = {"restore": [], "recompute": []}
    for rep in range(reps):  # interleaved per-rep: host drift cancels
        arms["restore"].append(await one_rep(True, rep))
        arms["recompute"].append(await one_rep(False, rep))

    res_resume = [t for r in arms["restore"] for t in r["resume_s"]]
    rec_resume = [t for r in arms["recompute"] for t in r["resume_s"]]
    res_p95, rec_p95 = p95(res_resume), p95(rec_resume)
    complete = (all(r["complete"] for r in arms["restore"])
                and all(r["complete"] for r in arms["recompute"]))
    killed_all = (all(r["killed"] for r in arms["restore"])
                  and all(r["killed"] for r in arms["recompute"]))
    restored = sum(r["restored_blocks"] for r in arms["restore"])
    ratio = res_p95 / max(rec_p95, 1e-9)
    return {
        "migration_workload": (f"{streams}x(ISL={isl},OSL={osl}) shared "
                               f"prefix, 3 workers, {reps} reps/arm"),
        "complete": complete,
        "killed_all_reps": killed_all,
        "counts_restore": [r["counts"] for r in arms["restore"]],
        "counts_recompute": [r["counts"] for r in arms["recompute"]],
        "restore_resume_p95_ms": round(res_p95 * 1000, 1),
        "recompute_resume_p95_ms": round(rec_p95 * 1000, 1),
        "resume_ratio_restore_over_recompute": round(ratio, 3),
        "restored_blocks": restored,
        "recompute_restored_blocks": sum(
            r["restored_blocks"] for r in arms["recompute"]),
        "migration_ok": (complete and killed_all and restored > 0
                         and ratio <= 0.7),
    }


async def onboard_bench(on_tpu: bool = False, reps: int = 2,
                        isl: int = 4096, osl: int = 32,
                        streams: int = 4) -> dict:
    """``bench.py --onboard``: routine cross-worker prefix onboarding
    (ISSUE 11 acceptance; docs/performance.md "prefix onboarding").

    Scenario 1 — shared-system-prompt fleet: worker A holds the hot 4k
    prefix, ``streams`` admissions sharing it land on worker B. Pull arm:
    the router attaches peer plans and B onboards the prefix over
    ``kv_pull`` (one pull, dedupe holds the rest); recompute arm
    (``DYN_ONBOARD=0`` semantics): B re-prefills every stream. Gates:
    100% completion, bit-identical greedy streams across arms, TTFT p95
    ratio ≤ 0.7, AND fewer prefill chip-seconds (B's summed step wall) —
    the pull must win latency without hiding recompute burn elsewhere.

    Scenario 2 — cold start from G4: worker A's re-hit prefix flows up to
    the object store (DYN_G4_PUBLISH_HITS=1) and is sentinel-announced to
    the radix; A leaves; a COLD worker admits the same prefix and warms
    it from G4 (no peer exists) vs recomputing it. Gate: TTFT p95 ratio
    < 1.0 with blocks actually fetched from the store.

    Arms are interleaved per rep so host drift cancels (the migration
    bench discipline).
    """
    from dynamo_tpu.disagg.handlers import DecodeWorkerHandler, KvPullHandler
    from dynamo_tpu.disagg.transfer import OnboardConfig, RestoreConfig
    from dynamo_tpu.engine.config import EngineArgs, ModelConfig
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.kvbm.distributed import (G4PrefixAnnouncer,
                                             ObjectStoreG4Client)
    from dynamo_tpu.protocols import (PreprocessedRequest, SamplingOptions,
                                      StopConditions)
    from dynamo_tpu.router.kv_router import KvPushRouter, KvRouter
    from dynamo_tpu.router.protocols import G4_SOURCE_ID, KvRouterConfig
    from dynamo_tpu.router.publisher import KvEventPublisher
    from dynamo_tpu.runtime import DistributedRuntime
    from dynamo_tpu.runtime.config import RuntimeConfig
    from dynamo_tpu.runtime.context import Context

    cfg = ModelConfig.tiny()
    bs = 16
    blocks_needed = (isl + 64 + osl) // bs + 8
    blk_bytes = 2 * cfg.num_layers * bs * cfg.num_kv_heads * (
        cfg.hidden_size // cfg.num_heads) * 4
    rng = np.random.default_rng(43)
    prefix = rng.integers(1, cfg.vocab_size, isl).tolist()
    warm_prefix = rng.integers(1, cfg.vocab_size, isl).tolist()
    prefix_blocks = isl // bs

    def eargs(**kw):
        base = dict(block_size=bs, num_blocks=2 * blocks_needed + 64,
                    max_num_seqs=streams + 2,
                    max_num_batched_tokens=1024,
                    max_model_len=isl + 64 + osl + bs,
                    enable_prefix_caching=True)
        base.update(kw)
        return EngineArgs(**base)

    def req(suffix, pin=None, osl_=None):
        return PreprocessedRequest(
            model="m", token_ids=prefix + list(suffix),
            stop_conditions=StopConditions(
                max_tokens=osl_ or osl, ignore_eos=True),
            sampling_options=SamplingOptions(temperature=0.0),
            backend_instance_id=pin)

    async def settle(check, timeout=20.0, msg="never settled"):
        for _ in range(int(timeout / 0.02)):
            if check():
                return
            await asyncio.sleep(0.02)
        raise TimeoutError(msg)

    async def make_worker(rt, rcfg, onboard_on, g4_client=None,
                          hot_hits=0, host_blocks=0):
        import os as _os

        wrt = await DistributedRuntime.create(plane=rt.plane,
                                              owns_plane=False, config=rcfg)
        lease = await wrt.primary_lease()
        kw = {}
        if host_blocks:
            kw["kvbm_host_bytes"] = host_blocks * blk_bytes
        prev = _os.environ.get("DYN_G4_PUBLISH_HITS")
        _os.environ["DYN_G4_PUBLISH_HITS"] = str(hot_hits)
        try:
            eng = await asyncio.to_thread(
                AsyncJaxEngine, cfg, eargs(**kw))
        finally:
            if prev is None:
                _os.environ.pop("DYN_G4_PUBLISH_HITS", None)
            else:
                _os.environ["DYN_G4_PUBLISH_HITS"] = prev
        pub = KvEventPublisher(wrt.plane, worker_id=lease, kv_block_size=bs)
        await pub.start_resync_responder()
        eng.event_cb = pub.publish_sync
        announcer = None
        if g4_client is not None:
            eng.kvbm.attach_remote(g4_client, 0)
            if hot_hits:
                announcer = await G4PrefixAnnouncer(
                    wrt.plane, pub, asyncio.get_running_loop()).start()
                eng.kvbm.on_remote_change = announcer.on_remote_change
        comp = wrt.namespace("dynamo").component("backend")
        pull_client = await comp.endpoint("kv_pull").client().start()
        handler = DecodeWorkerHandler(
            eng, pull_clients=[pull_client], metrics=wrt.metrics,
            restore_config=RestoreConfig(enabled=False),
            onboard_config=OnboardConfig(enabled=onboard_on))
        handler.instance_id = lease
        h_gen = await comp.endpoint("generate").serve_endpoint(
            handler.generate, lease_id=lease)
        h_pull = await comp.endpoint("kv_pull").serve_endpoint(
            KvPullHandler(eng).generate, lease_id=lease)
        w = type("W", (), {})()
        w.rt, w.engine, w.lease = wrt, eng, lease
        w.handler, w.pub, w.announcer = handler, pub, announcer
        w.pull_client = pull_client
        w.handles = [h_gen, h_pull]
        return w

    async def close_worker(w, stopped=False):
        if not stopped:
            for h in w.handles:
                await h.stop(graceful=False)
        await w.pull_client.stop()
        if w.announcer is not None:
            await w.announcer.stop()
        await w.pub.stop()
        await w.engine.close()
        await w.rt.shutdown()

    async def warm(w, push, tag):
        """Compile surfaces OFF the measured path: full-ISL prefill +
        decode signatures, plus the width-256 gather/scatter programs the
        pull/attach path dispatches. The warm prefix is then dropped so
        it can't shadow the measurement."""
        from dynamo_tpu.ops.block_copy import gather_blocks, scatter_blocks

        r = PreprocessedRequest(
            model="m", token_ids=warm_prefix + [9700 + tag],
            stop_conditions=StopConditions(max_tokens=4, ignore_eos=True),
            sampling_options=SamplingOptions(temperature=0.0),
            backend_instance_id=w.lease)
        async for _ in push.generate(r, Context()):
            pass
        eng = w.engine
        ids = list(range(1, min(257, eng.num_blocks)))
        kb = np.asarray(gather_blocks(eng.k_cache, ids, block_size=bs))
        vb = np.asarray(gather_blocks(eng.v_cache, ids, block_size=bs))
        eng.k_cache = scatter_blocks(eng.k_cache, ids, kb, block_size=bs)
        eng.v_cache = scatter_blocks(eng.v_cache, ids, vb, block_size=bs)
        eng.pool.clear()

    from dynamo_tpu.runtime.context import Context

    async def measured_streams(push, rep, base):
        """Launch the shared-prefix streams concurrently; returns
        (ttfts, token_streams)."""
        ttfts = []
        outs = []

        async def one(i):
            r = req([base + rep * 16 + i])
            t0 = time.perf_counter()
            first = True
            toks = []
            async for out in push.generate(r, Context()):
                if isinstance(out, dict) and out.get("token_ids"):
                    if first:
                        ttfts.append(time.perf_counter() - t0)
                        first = False
                    toks.extend(out["token_ids"])
            outs.append((i, toks))
            return toks

        await asyncio.gather(*[one(i) for i in range(streams)])
        return ttfts, [t for _, t in sorted(outs)]

    def chip_seconds(eng, mark):
        return sum(e[3] for e in list(eng.step_trace)[mark:]) / 1000.0

    async def peer_rep(onboard_on: bool, rep: int) -> dict:
        rcfg = RuntimeConfig(lease_ttl=8.0)
        rt = await DistributedRuntime.create(config=rcfg)
        a = b = None
        router = client = None
        try:
            a = await make_worker(rt, rcfg, onboard_on)
            b = await make_worker(rt, rcfg, onboard_on)
            client = await (rt.namespace("dynamo").component("backend")
                            .endpoint("generate").client().start())
            router = await KvRouter(rt.plane, bs, KvRouterConfig()).start()
            push = KvPushRouter(client, router)
            await warm(a, push, 0)
            await warm(b, push, 1)
            # A computes (and keeps) the shared prefix
            async for _ in push.generate(req([9001], pin=a.lease),
                                         Context()):
                pass
            await settle(lambda: router.restore_sources(prefix + [1])
                         .get(a.lease, 0) >= prefix_blocks - 1,
                         msg="radix never learned A's prefix")
            client.set_busy_instances([a.lease])  # steer onto B
            mark = len(b.engine.step_trace)
            q0 = b.engine.scheduler.prefix_query_tokens
            h0 = b.engine.scheduler.prefix_hit_tokens
            ttfts, toks = await measured_streams(push, rep, 9100)
            sched = b.engine.scheduler
            return {
                "ttfts": ttfts,
                "tokens": toks,
                "complete": all(len(t) == osl for t in toks),
                "chip_s": chip_seconds(b.engine, mark),
                "prompt_tokens_computed": (
                    (sched.prefix_query_tokens - q0)
                    - (sched.prefix_hit_tokens - h0)),
                "pulled_blocks": b.handler._onboard_blocks._values.get(
                    (("source", "peer"),), 0),
            }
        finally:
            for w in (a, b):
                if w is not None:
                    await close_worker(w)
            if router is not None:
                await router.stop()
            if client is not None:
                await client.stop()
            await rt.shutdown()

    async def g4_rep(onboard_on: bool, rep: int) -> dict:
        rcfg = RuntimeConfig(lease_ttl=8.0)
        rt = await DistributedRuntime.create(config=rcfg)
        loop = asyncio.get_running_loop()
        a = c = None
        a_stopped = False
        router = client = None
        try:
            g4 = ObjectStoreG4Client(rt.plane, loop)
            # A: hot publisher (threshold 1 — first re-hit flows up).
            # Host sized for warm-prefix AND measured-prefix blocks, so
            # warm-block evictions never cascade garbage into G4.
            a = await make_worker(rt, rcfg, onboard_on, g4_client=g4,
                                  hot_hits=1,
                                  host_blocks=2 * prefix_blocks + 32)
            client = await (rt.namespace("dynamo").component("backend")
                            .endpoint("generate").client().start())
            router = await KvRouter(rt.plane, bs, KvRouterConfig()).start()
            push = KvPushRouter(client, router)
            await warm(a, push, 2)
            async for _ in push.generate(req([9001], pin=a.lease),
                                         Context()):
                pass
            # the MEASURED prefix must be G2-resident before the re-hit
            # (warm-prefix blocks would satisfy a bare host_blocks count
            # while the measured offload is still in flight)
            from dynamo_tpu.tokens import KV_HASH_SEED, TokenBlockSequence
            probe_hashes = TokenBlockSequence.from_tokens(
                prefix[:prefix_blocks * bs], bs,
                KV_HASH_SEED).sequence_hashes()
            await settle(lambda: len(a.engine.kvbm.host_resident(
                probe_hashes)) >= prefix_blocks - 1,
                msg="offload to G2 never landed")
            async for _ in push.generate(req([9002], pin=a.lease),
                                         Context()):
                pass
            await settle(lambda: router.restore_sources(prefix + [1])
                         .get(G4_SOURCE_ID, 0) >= prefix_blocks - 1,
                         timeout=60.0,
                         msg="hot prefix never reached G4/radix")
            # A leaves the fleet; the G4 sentinel survives it
            for h in a.handles:
                await h.stop(graceful=False)
            a_stopped = True
            # cold worker joins (own G4 reach, empty caches); host sized
            # so its warm-prefix offload can't evict into G4 mid-measure
            c = await make_worker(rt, rcfg, onboard_on, g4_client=g4,
                                  host_blocks=2 * prefix_blocks + 32)
            await settle(lambda: client.available_ids() == [c.lease])
            await warm(c, push, 3)
            mark = len(c.engine.step_trace)
            ttfts, toks = await measured_streams(push, rep, 9300)
            return {
                "ttfts": ttfts,
                "tokens": toks,
                "complete": all(len(t) == osl for t in toks),
                "chip_s": chip_seconds(c.engine, mark),
                "g4_blocks": c.engine.kvbm.stats()["onboarded_blocks"],
            }
        finally:
            if a is not None:
                await close_worker(a, stopped=a_stopped)
            if c is not None:
                await close_worker(c)
            if router is not None:
                await router.stop()
            if client is not None:
                await client.stop()
            await rt.shutdown()

    p95 = _p95  # shared interpolated estimator (observability/stats)

    peer = {"pull": [], "recompute": []}
    for rep in range(reps):  # interleaved per-rep: host drift cancels
        peer["pull"].append(await peer_rep(True, rep))
        peer["recompute"].append(await peer_rep(False, rep))
    g4 = {"warm": [], "recompute": []}
    g4["warm"].append(await g4_rep(True, 0))
    g4["recompute"].append(await g4_rep(False, 0))

    pull_ttfts = [t for r in peer["pull"] for t in r["ttfts"]]
    rec_ttfts = [t for r in peer["recompute"] for t in r["ttfts"]]
    pull_p95, rec_p95 = p95(pull_ttfts), p95(rec_ttfts)
    ttft_ratio = pull_p95 / max(rec_p95, 1e-9)
    pull_chip = sum(r["chip_s"] for r in peer["pull"])
    rec_chip = sum(r["chip_s"] for r in peer["recompute"])
    identical = all(
        pr["tokens"] == rr["tokens"]
        for pr, rr in zip(peer["pull"], peer["recompute"]))
    complete = (all(r["complete"] for r in peer["pull"] + peer["recompute"]
                    + g4["warm"] + g4["recompute"]))
    g4_p95 = p95([t for r in g4["warm"] for t in r["ttfts"]])
    g4_rec_p95 = p95([t for r in g4["recompute"] for t in r["ttfts"]])
    g4_ratio = g4_p95 / max(g4_rec_p95, 1e-9)
    g4_identical = all(
        wr["tokens"] == rr["tokens"]
        for wr, rr in zip(g4["warm"], g4["recompute"]))
    pulled = sum(r["pulled_blocks"] or 0 for r in peer["pull"])
    g4_warmed = sum(r["g4_blocks"] for r in g4["warm"])
    return {
        "onboard_workload": (f"{streams}x(ISL={isl},OSL={osl}) shared "
                             f"prefix, 2 workers, {reps} reps/arm + G4 "
                             "cold-start x1"),
        "complete": complete,
        "streams_identical_across_arms": identical,
        "pull_ttft_p95_ms": round(pull_p95 * 1000, 1),
        "recompute_ttft_p95_ms": round(rec_p95 * 1000, 1),
        "ttft_ratio_pull_over_recompute": round(ttft_ratio, 3),
        "pull_prefill_chip_s": round(pull_chip, 2),
        "recompute_prefill_chip_s": round(rec_chip, 2),
        "pull_prompt_tokens_computed": sum(
            r["prompt_tokens_computed"] for r in peer["pull"]),
        "recompute_prompt_tokens_computed": sum(
            r["prompt_tokens_computed"] for r in peer["recompute"]),
        "peer_pulled_blocks": pulled,
        "g4_cold_ttft_p95_ms": round(g4_p95 * 1000, 1),
        "g4_recompute_ttft_p95_ms": round(g4_rec_p95 * 1000, 1),
        "g4_ttft_ratio": round(g4_ratio, 3),
        "g4_warmed_blocks": g4_warmed,
        "g4_streams_identical": g4_identical,
        "onboard_ok": (complete and identical and g4_identical
                       and ttft_ratio <= 0.7
                       and pull_chip < rec_chip
                       and pulled > 0
                       and g4_ratio < 1.0 and g4_warmed > 0),
    }


async def sessions_bench(on_tpu: bool = False, n_sessions: int = 3,
                         n_turns: int = 4) -> dict:
    """``bench.py --sessions``: session-native vs sessionless serving A/B
    (ISSUE 20 acceptance; docs/sessions.md).

    A 2-worker tiny-cpu fleet behind the real HTTP frontend serves
    multi-turn conversations. Between turns, churn traffic floods the
    device pool AND the (deliberately small, disk-less) host tier, so by
    the time a session returns its prefix has been evicted from every
    radix-visible tier. The session-native arm rides the full product:
    delta turns over ``previous_response_id``, router affinity, idle-KV
    parking to G4 during think-time, proactive restore on return. The
    sessionless control (``store=false``, full transcript each turn)
    recomputes everything. Gates: bit-identical conversations across
    arms, turn-2+ TTFT p95 ratio ≤ 0.5, strictly fewer computed prompt
    tokens AND prefill chip-seconds per session, concurrent non-session
    QoS TTFT ratio ≤ 1.2, parked+restored G4 blocks actually observed,
    and the TTL reaper collecting an abandoned session."""
    import random

    import aiohttp

    from benchmarks.client import (run_session_trace, session_headers,
                                   stream_request, stream_responses_request)
    from dynamo_tpu.disagg.handlers import DecodeWorkerHandler
    from dynamo_tpu.engine.config import EngineArgs, ModelConfig
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.frontend.http import HttpService
    from dynamo_tpu.kvbm.distributed import ObjectStoreG4Client
    from dynamo_tpu.llm.discovery import ModelManager, ModelWatcher
    from dynamo_tpu.llm.model_card import ModelDeploymentCard, register_llm
    from dynamo_tpu.router.publisher import KvEventPublisher
    from dynamo_tpu.runtime import DistributedRuntime
    from dynamo_tpu.runtime.config import RuntimeConfig
    from dynamo_tpu.sessions import SESSION_ENDPOINT, SessionKvHandler

    # Deliberately beefier than ModelConfig.tiny(): at 2 layers / hidden 64
    # the prefill is so cheap (~1.6ms/block on CPU) that the restore+onboard
    # memcpy (~1ms/block) rivals recompute and the TTFT win saturates near
    # 0.7x. Widening the model raises compute quadratically in hidden size
    # while KV bytes (copy cost) grow only linearly, so FLOPs dominate and
    # the A/B measures what sessions actually buy: skipped prefill compute.
    cfg = ModelConfig(
        vocab_size=256, hidden_size=384, intermediate_size=768,
        num_layers=4, num_heads=8, num_kv_heads=4, rope_theta=10000.0,
        max_position_embeddings=4096, dtype="float32",
    )
    bs = 16
    model = "tiny-sess"
    blk_bytes = 2 * cfg.num_layers * bs * cfg.num_kv_heads * (
        cfg.hidden_size // cfg.num_heads) * 4
    # G2 must hold one full restored session prefix (fetch_remote lands
    # leading→trailing; a host tier smaller than the prefix would LRU the
    # leading blocks before admission probes them) yet still be small
    # enough for a churn gap to evict completely
    host_blocks = 160

    # tokenizer whose vocab covers the model's sampled ids (the _e2e
    # discipline) — the stock "test" tokenizer maps every synthetic word
    # to <unk>, which would fuse all prompts into one shared prefix and
    # void the whole eviction/restore A/B. Space-joined template keeps
    # the token stream of turn N a strict prefix of turn N+1.
    tmp = tempfile.mkdtemp(prefix="bench-sess-tk-")
    _write_tokenizer_dir(tmp, cfg.vocab_size)
    with open(os.path.join(tmp, "tokenizer_config.json"), "w") as f:
        json.dump({"chat_template": "{% for m in messages %}"
                                    "{{ m['content'] }} {% endfor %}"}, f)

    prng = random.Random(202)

    def words(n):
        return " ".join(f"w{prng.randrange(1, cfg.vocab_size)}"
                        for _ in range(n))

    def eargs():
        return EngineArgs(block_size=bs, num_blocks=224, max_num_seqs=12,
                          max_num_batched_tokens=1024, max_model_len=2560,
                          enable_prefix_caching=True,
                          kvbm_host_bytes=host_blocks * blk_bytes)

    async def make_worker(rt, rcfg, g4):
        wrt = await DistributedRuntime.create(plane=rt.plane,
                                              owns_plane=False, config=rcfg)
        lease = await wrt.primary_lease()
        eng = await asyncio.to_thread(AsyncJaxEngine, cfg, eargs())
        pub = KvEventPublisher(wrt.plane, worker_id=lease, kv_block_size=bs)
        await pub.start_resync_responder()
        eng.event_cb = pub.publish_sync
        eng.kvbm.attach_remote(g4, 1 << 30)
        comp = wrt.namespace("dynamo").component("backend")
        handler = DecodeWorkerHandler(eng, metrics=wrt.metrics)
        handler.instance_id = lease
        ep = comp.endpoint("generate")
        h_gen = await ep.serve_endpoint(handler.generate, lease_id=lease)
        h_sess = await comp.endpoint(SESSION_ENDPOINT).serve_endpoint(
            SessionKvHandler(eng, metrics=wrt.metrics).generate,
            lease_id=lease)
        card = ModelDeploymentCard(
            display_name=model, kv_cache_block_size=bs, eos_token_ids=[],
            tokenizer_ref=tmp)
        card.runtime_config.total_kv_blocks = eng.num_blocks
        card.runtime_config.max_num_seqs = 12
        await register_llm(wrt, ep, card, lease_id=lease)
        w = type("W", (), {})()
        w.rt, w.engine, w.lease, w.pub = wrt, eng, lease, pub
        w.handles = [h_gen, h_sess]
        return w

    async def close_worker(w):
        for h in w.handles:
            await h.stop(graceful=False)
        await w.pub.stop()
        await w.engine.close()
        await w.rt.shutdown()

    p95 = _p95
    rcfg = RuntimeConfig(lease_ttl=8.0)
    rt = await DistributedRuntime.create(config=rcfg)
    workers = []
    watcher = service = reap_service = None
    env_keys = {"DYN_SESSION_PARK_AFTER_S": "0.6",
                "DYN_SESSION_REAP_INTERVAL_S": "0.15",
                "DYN_SESSION_RESTORE_WAIT_S": "2.0"}
    saved_env = {k: os.environ.get(k) for k in env_keys}
    try:
        os.environ.update(env_keys)
        g4 = ObjectStoreG4Client(rt.plane, asyncio.get_running_loop())
        workers = [await make_worker(rt, rcfg, g4) for _ in range(2)]
        manager = ModelManager()
        watcher = await ModelWatcher(rt, manager, router_mode="kv").start()
        service = HttpService(manager, port=0)
        await service.start()
        for _ in range(200):
            served = manager.get(model)
            if served is not None and len(served.client.available_ids()) == 2:
                break
            await asyncio.sleep(0.05)
        else:
            raise TimeoutError("fleet never appeared in discovery")
        base = f"http://127.0.0.1:{service.port}"

        # identical conversations AND identical per-gap churn across both
        # arms: the compute comparison is then apples-to-apples and the
        # bit-identity gate is meaningful (greedy + shared weight seed)
        convos = [[words(1400 if t == 0 else 150) for t in range(n_turns)]
                  for s in range(n_sessions)]
        n_gaps = n_sessions * (n_turns - 1)
        churn_sets = [([words(500) for _ in range(12)], words(16))
                      for _ in range(n_gaps)]

        async def churn_and_qos(http, gap, qos_ttfts):
            """Flood both tiers with one-shot strangers while a concurrent
            interactive probe measures non-session QoS TTFT."""
            churn, probe_prompt = churn_sets[gap]

            async def churn_one(p):
                r = await stream_request(http, base, model, p, 4)
                assert r.ok, f"churn failed: {r.error}"

            async def probe():
                # several sequential probes per gap: p95 over 4x gaps
                # samples instead of one max-prone sample per gap
                for suffix in ("", " w9 w8", " w7", " w6 w5 w4"):
                    r = await stream_request(http, base, model,
                                             probe_prompt + suffix, 8)
                    assert r.ok, f"qos probe failed: {r.error}"
                    qos_ttfts.append(r.ttft_s)

            await asyncio.gather(*[churn_one(p) for p in churn], probe())

        async def wait_parked(http, sid, timeout=8.0):
            for _ in range(int(timeout / 0.05)):
                async with http.get(f"{base}/v1/sessions") as r:
                    snap = await r.json()
                for s in snap.get("sessions", []):
                    if s["id"] == sid and s["parked"]:
                        return True
                await asyncio.sleep(0.05)
            return False

        async def warm(http):
            """Compile + fault-in every measured surface off the record on
            BOTH workers (steered via set_busy_instances): single prefills
            at the conversation sizes, a churn-shaped concurrent burst (the
            big ragged token buckets), the turn osl's decode buckets, AND a
            full park→evict→restore→onboard session cycle per worker — the
            first measured restore must not pay one-time scatter compiles
            or cold code paths the control arm never touches. Then flush
            all tiers."""
            for i, w in enumerate(workers):
                others = [x.lease for x in workers if x is not w]
                served.client.set_busy_instances(others)
                for n_words in (2300, 1400, 600, 150, 55, 30):
                    r = await stream_request(http, base, model,
                                             words(n_words), 24)
                    assert r.ok, f"warmup failed: {r.error}"
                burst = await asyncio.gather(
                    *[stream_request(http, base, model, words(500), 4)
                      for _ in range(6)],
                    stream_request(http, base, model, words(16), 8))
                assert all(r.ok for r in burst), "warmup burst failed"
                sid, prev = f"warm-s{i}", None
                for t in range(2):
                    res = await stream_responses_request(
                        http, base, model,
                        [{"role": "user",
                          "content": words(1400 if t == 0 else 250)}],
                        24, previous_response_id=prev,
                        headers=session_headers(sid),
                        sampling={"temperature": 0.0})
                    assert res.ok, f"warm session failed: {res.error}"
                    prev = res.response_id
                    if t == 0:
                        assert await wait_parked(http, sid), "warm park"
                        evict = await asyncio.gather(
                            *[stream_request(http, base, model, words(500),
                                             4) for _ in range(10)])
                        assert all(r.ok for r in evict), "warm evict failed"
            served.client.set_busy_instances([])
            for w in workers:
                w.engine.pool.clear()
                await asyncio.to_thread(w.engine.kvbm.clear)

        async def run_arm(http, native: bool) -> dict:
            marks = [len(w.engine.step_trace) for w in workers]
            c0 = [(w.engine.scheduler.prefix_query_tokens,
                   w.engine.scheduler.prefix_hit_tokens) for w in workers]
            first_ttfts, later_ttfts, qos_ttfts = [], [], []
            texts, turn_hit_blocks, turn_ttfts_ms = [], [], []
            parked_misses = gap = 0
            for s in range(n_sessions):
                sid = f"{'native' if native else 'ctl'}-s{s}"
                transcript, prev, arm_texts = [], None, []
                for t in range(n_turns):
                    item = {"role": "user", "content": convos[s][t]}
                    if native and prev is not None:
                        items = [item]
                    else:
                        items = transcript + [item]
                    sampling = {"temperature": 0.0}
                    if not native:
                        sampling["store"] = False
                    th0 = sum(w.engine.scheduler.prefix_hit_tokens
                              for w in workers)
                    res = await stream_responses_request(
                        http, base, model, items, 24,
                        previous_response_id=prev if native else None,
                        headers=session_headers(sid) if native else None,
                        sampling=sampling)
                    assert res.ok, f"turn failed: {res.error}"
                    turn_hit_blocks.append(
                        (sum(w.engine.scheduler.prefix_hit_tokens
                             for w in workers) - th0) // bs)
                    turn_ttfts_ms.append(round(res.ttft_s * 1000, 1))
                    (first_ttfts if t == 0 else later_ttfts).append(
                        res.ttft_s)
                    arm_texts.append(res.text)
                    transcript += [item,
                                   {"role": "assistant", "content": res.text}]
                    prev = res.response_id
                    if t < n_turns - 1:
                        # think-time: the native arm's session goes idle
                        # long enough for the reaper to park it, THEN the
                        # churn wave hits; the control gets the same wave
                        # after an equivalent pause
                        if native:
                            if not await wait_parked(http, sid):
                                parked_misses += 1
                        else:
                            await asyncio.sleep(0.9)
                        await churn_and_qos(http, gap, qos_ttfts)
                        # identical settle in both arms: let the churn
                        # wave's background offload/cascade tail drain so
                        # turn TTFTs measure the serving path, not copy
                        # traffic the arms share anyway
                        await asyncio.sleep(0.35)
                        gap += 1
                # session boundary: let the reaper's FINAL park of this
                # session (it idles forever now) land before the next
                # session's turns start, so that park's G4 publish burst
                # can't jitter a measured TTFT; control idles equivalently
                if native:
                    if not await wait_parked(http, sid):
                        parked_misses += 1
                else:
                    await asyncio.sleep(0.9)
                texts.append(arm_texts)
            chip_s = sum(
                sum(e[3] for e in list(w.engine.step_trace)[m:]) / 1000.0
                for w, m in zip(workers, marks))
            query = sum(w.engine.scheduler.prefix_query_tokens - q0
                        for w, (q0, _h0) in zip(workers, c0))
            hits = sum(w.engine.scheduler.prefix_hit_tokens - h0
                       for w, (_q0, h0) in zip(workers, c0))
            return {"first_ttfts": first_ttfts, "later_ttfts": later_ttfts,
                    "qos_ttfts": qos_ttfts, "texts": texts,
                    "chip_s": chip_s, "query_tokens": query,
                    "hit_tokens": hits,
                    "computed_prompt_tokens": query - hits,
                    "turn_hit_blocks": turn_hit_blocks,
                    "turn_ttfts_ms": turn_ttfts_ms,
                    "parked_misses": parked_misses}

        timeout = aiohttp.ClientTimeout(total=120)
        async with aiohttp.ClientSession(timeout=timeout) as http:
            await warm(http)
            # control arm first; flush every tier so its residue cannot
            # feed the native arm (G4 is only ever written by parking)
            ctl = await run_arm(http, native=False)
            for w in workers:
                w.engine.pool.clear()
                await asyncio.to_thread(w.engine.kvbm.clear)
            native = await run_arm(http, native=True)

            async with http.get(f"{base}/v1/sessions") as r:
                snap = await r.json()
            native_rows = [s for s in snap.get("sessions", [])
                           if s["id"].startswith("native-")]
            parked_blocks = sum(s["parked_blocks"] for s in native_rows)
            restored_blocks = sum(s["restored_blocks"] for s in native_rows)
            affinity_workers = {s["worker"] for s in native_rows}
            async with http.get(f"{base}/metrics") as r:
                mtext = await r.text()

            # session-realistic trace shapes (client.py satellite): an
            # agent tool-loop session and an abandoned one, driven on a
            # short-TTL frontend so the reaper demonstrably collects it
            os.environ["DYN_SESSION_TTL_S"] = "1.2"
            try:
                reap_service = HttpService(manager, port=0)
                await reap_service.start()
                rbase = f"http://127.0.0.1:{reap_service.port}"
                trace_rng = random.Random(7)
                agent = await run_session_trace(
                    http, [rbase], model, sid="agent", rng=trace_rng,
                    turns=3, words_per_turn=20, osl=8,
                    think_s=(0.05, 0.1), tool_loop_p=1.0,
                    headers=session_headers("agent"),
                    sampling={"temperature": 0.0})
                gone = await run_session_trace(
                    http, [rbase], model, sid="gone", rng=trace_rng,
                    turns=4, words_per_turn=20, osl=8,
                    think_s=(0.05, 0.1), abandon_p=1.0,
                    headers=session_headers("gone"),
                    sampling={"temperature": 0.0})
                await asyncio.sleep(2.0)  # TTL 1.2s + reap sweep
                async with http.get(f"{rbase}/v1/sessions") as r:
                    reap_snap = await r.json()
            finally:
                os.environ.pop("DYN_SESSION_TTL_S", None)

        t2_native, t2_ctl = p95(native["later_ttfts"]), p95(
            ctl["later_ttfts"])
        ttft_ratio = t2_native / max(t2_ctl, 1e-9)
        qos_ratio = (p95(native["qos_ttfts"])
                     / max(p95(ctl["qos_ttfts"]), 1e-9))
        identical = native["texts"] == ctl["texts"]
        reaped = reap_snap["count"] == 0
        sessions_ok = (
            identical
            and ttft_ratio <= 0.5
            and native["computed_prompt_tokens"]
            < ctl["computed_prompt_tokens"]
            and native["chip_s"] < ctl["chip_s"]
            and qos_ratio <= 1.2
            and parked_blocks > 0 and restored_blocks > 0
            and native["parked_misses"] == 0
            and len(affinity_workers) >= 1
            and agent.ok and agent.tool_loops > 0 and gone.abandoned
            and reaped
            and "dynamo_session_parked_blocks_total" in mtext)
        return {
            "sessions_workload": (f"{n_sessions} sessions x {n_turns} "
                                  f"turns, 2 workers, churn-evicted tiers, "
                                  "G4 park/restore"),
            "streams_identical_across_arms": identical,
            "turn2_ttft_p95_ms_native": round(t2_native * 1000, 1),
            "turn2_ttft_p95_ms_sessionless": round(t2_ctl * 1000, 1),
            "turn2_ttft_ratio": round(ttft_ratio, 3),
            "turn1_ttft_p95_ms_native": round(
                p95(native["first_ttfts"]) * 1000, 1),
            "turn1_ttft_p95_ms_sessionless": round(
                p95(ctl["first_ttfts"]) * 1000, 1),
            "computed_prompt_tokens_native":
                native["computed_prompt_tokens"],
            "computed_prompt_tokens_sessionless":
                ctl["computed_prompt_tokens"],
            "prefix_hit_tokens_native": native["hit_tokens"],
            "prefix_hit_tokens_sessionless": ctl["hit_tokens"],
            "turn_hit_blocks_native": native["turn_hit_blocks"],
            "turn_hit_blocks_sessionless": ctl["turn_hit_blocks"],
            "turn_ttfts_ms_native": native["turn_ttfts_ms"],
            "turn_ttfts_ms_sessionless": ctl["turn_ttfts_ms"],
            "prefill_chip_s_native": round(native["chip_s"], 3),
            "prefill_chip_s_sessionless": round(ctl["chip_s"], 3),
            "qos_ttft_ratio": round(qos_ratio, 3),
            "parked_blocks": parked_blocks,
            "restored_blocks": restored_blocks,
            "parked_misses": native["parked_misses"],
            "affinity_workers": sorted(x for x in affinity_workers if x),
            "agent_trace_ok": agent.ok,
            "agent_tool_loops": agent.tool_loops,
            "abandoned_trace": gone.abandoned,
            "abandoned_reaped": reaped,
            "sessions_ok": sessions_ok,
        }
    finally:
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        if reap_service is not None:
            await reap_service.stop()
        if service is not None:
            await service.stop()
        if watcher is not None:
            await watcher.stop()
        for w in workers:
            await close_worker(w)
        await rt.shutdown()


async def ragged_bench(on_tpu: bool = False, reps: int = 2,
                       modes: bool = True) -> dict:
    """``bench.py --ragged``: per-mode A/B ON the packed ragged launch —
    the engine's only step path since ISSUE 17 deleted the bucketed one.

    The same seeded MIXED workload — long-prompt/short-output requests
    arriving while short-prompt/long-output streams are mid-decode, so
    steps genuinely carry prefill chunks AND decode rows — runs as four
    arms on identical packing geometry:

      base:  plain single-step serving (reference greedy streams, tok/s,
             TTFT p95, compiled-signature census, padded-token waste)
      spec:  speculative decoding (prompt-lookup drafts verify as ragged
             rows with q_len = K+1 on the same packed launch)
      multi: multi-step fused decode (K chained steps per dispatch
             through the decode-only ragged variant)
      mla:   the same wave on an MLA config (mla_tiny — latent KV on the
             packed launch), run-to-run determinism

    No-regression gate: spec and multi greedy streams are BIT-IDENTICAL
    to base (they are dispatch-count optimizations, not samplers), the
    MLA arm replays identically, every arm's compiled signatures stay in
    the token-bucket families, no arm's tok/s drops past the CPU-noise
    floor, and the serving signature census stays ≥ 4× below the
    (chunk-bucket + batch-bucket) × table-width lattice the deleted
    bucketed path would have compiled for the same EngineArgs.
    """
    from dynamo_tpu.engine.config import EngineArgs, ModelConfig
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.models import get_model_config
    from dynamo_tpu.protocols import (PreprocessedRequest, SamplingOptions,
                                      StopConditions)

    if on_tpu:
        cfg = ModelConfig.llama3_1b()
        bs = 16
        N_P, ISL_P, OSL_P = 8, 512, 32   # prefill-heavy
        N_D, ISL_D, OSL_D = 8, 64, 128   # decode-heavy
        slots, budget = 16, 1024
        extra = dict(use_pallas_attention=True)
    else:
        cfg = ModelConfig.tiny()
        bs = 4
        N_P, ISL_P, OSL_P = 4, 96, 12
        N_D, ISL_D, OSL_D = 4, 16, 40
        slots, budget = 8, 128
        extra = {}
    max_len = 2 * max(ISL_P + OSL_P, ISL_D + OSL_D)
    working = (N_P * ((ISL_P + OSL_P + bs - 1) // bs)
               + N_D * ((ISL_D + OSL_D + bs - 1) // bs))
    base = dict(block_size=bs, num_blocks=2 * working + 8, max_num_seqs=slots,
                max_num_batched_tokens=budget, max_model_len=max_len,
                enable_prefix_caching=False, **extra)
    rng = np.random.default_rng(37)
    p_prompts = [rng.integers(1, cfg.vocab_size, ISL_P).tolist()
                 for _ in range(N_P)]
    d_prompts = [rng.integers(1, cfg.vocab_size, ISL_D).tolist()
                 for _ in range(N_D)]

    def req(tokens, osl):
        return PreprocessedRequest(
            model="m", token_ids=list(tokens),
            stop_conditions=StopConditions(max_tokens=osl, ignore_eos=True),
            sampling_options=SamplingOptions(temperature=0.0))

    async def one(eng, tokens, osl):
        t0 = time.perf_counter()
        ttft, toks = None, []
        async for out in eng.generate(req(tokens, osl)):
            if ttft is None and out.token_ids:
                ttft = time.perf_counter() - t0
            toks.extend(out.token_ids)
        return ttft, toks

    async def wave(eng):
        """Decode-heavy streams first; prefill-heavy prompts arrive once
        decode is underway — the mixed regime the ragged step targets."""
        t0 = time.perf_counter()
        dec = [asyncio.ensure_future(one(eng, p, OSL_D)) for p in d_prompts]
        for _ in range(20000):
            if any(s.generated > 0 for s in eng.scheduler.running):
                break
            await asyncio.sleep(0.001)
        pre = [asyncio.ensure_future(one(eng, p, OSL_P)) for p in p_prompts]
        res = await asyncio.gather(*dec, *pre)
        return res, time.perf_counter() - t0

    p95 = _p95  # shared interpolated estimator (observability/stats)

    def bucketed_lattice(args) -> int:
        """Signature count the deleted bucketed path would have compiled
        for this geometry: (chunk buckets + batch buckets) × distinct
        block-table widths — the lattice the ragged census is judged
        against now that there is no bucketed arm to measure."""
        widths = {args.bucket_table_width(le)
                  for le in range(args.block_size, args.max_model_len + 1,
                                  args.block_size)}
        return ((len(args.prefill_buckets) + len(args.decode_batch_buckets))
                * len(widths))

    async def measure(arm_cfg, **arm_args) -> dict:
        eng = AsyncJaxEngine(arm_cfg, EngineArgs(**base, **arm_args))
        warm = await eng.warmup(seq_lens=[ISL_P + OSL_P, ISL_D + OSL_D],
                                prefill_batches=[1, N_P])
        warm_sigs = sum(len(v) for v in warm.values() if isinstance(v, list))
        out: dict = {"warmup_s": warm["seconds"], "warmup_sigs": warm_sigs,
                     "lattice": bucketed_lattice(eng.args)}
        res0, _ = await wave(eng)  # serving caches warm (XLA compiled)
        out["streams_first"] = [toks for _, toks in res0]
        for _ in range(reps):
            res, dt = await wave(eng)
            tok_s = sum(len(toks) for _, toks in res) / dt
            if "tok_s" not in out or tok_s > out["tok_s"]:
                out["tok_s"] = tok_s
            # pool TTFT samples across reps (the p95 of one small wave is
            # its max — see qos_bench)
            out.setdefault("ttfts", []).extend(
                t for t, _ in res if t is not None)
            out["streams"] = [toks for _, toks in res]
        out["signatures"] = len(eng.compiled_signatures)
        out["sig_kinds"] = sorted({s[0] for s in eng.compiled_signatures})
        out["padded_tokens"] = eng.padded_tokens_total
        out["step_trace"] = eng.step_trace_summary()
        await eng.close()
        return out

    b = await measure(cfg)
    rep: dict = {
        "ragged_workload": (f"pre={N_P}x(ISL={ISL_P},OSL={OSL_P}) "
                            f"dec={N_D}x(ISL={ISL_D},OSL={OSL_D}) "
                            f"slots={slots} budget={budget}"),
        "base_tok_s": round(b["tok_s"], 1),
        "base_ttft_p95_ms": round(p95(b["ttfts"]) * 1000, 1),
        "base_warmup_s": b["warmup_s"],
        "base_signatures": b["signatures"],
        "base_warmup_signatures": b["warmup_sigs"],
        "base_padded_tokens": b["padded_tokens"],
        "bucketed_lattice_signatures": b["lattice"],
        # census vs the lattice the bucketed path would have compiled —
        # arithmetic now, since there is no bucketed arm left to run
        "signature_reduction": round(
            b["lattice"] / max(b["warmup_sigs"], 1), 2),
    }
    kinds = set(b["sig_kinds"])
    if modes:
        # spec and multi-step are dispatch-count optimizations on the same
        # greedy sampler: their streams must be bit-identical to base
        # (same deterministic param init — same ModelConfig, same seed)
        s = await measure(cfg, speculative_tokens=3)
        m = await measure(cfg, multi_step_decode=4)
        d = await measure(get_model_config("mla_tiny"))
        kinds |= set(s["sig_kinds"]) | set(m["sig_kinds"]) | set(d["sig_kinds"])
        rep.update({
            "spec_tok_s": round(s["tok_s"], 1),
            "spec_vs_base_tok_s": round(s["tok_s"] / max(b["tok_s"], 1e-9),
                                        3),
            "spec_streams_identical": s["streams"] == b["streams"],
            "multi_tok_s": round(m["tok_s"], 1),
            "multi_vs_base_tok_s": round(m["tok_s"] / max(b["tok_s"], 1e-9),
                                         3),
            "multi_streams_identical": m["streams"] == b["streams"],
            "mla_tok_s": round(d["tok_s"], 1),
            "mla_deterministic": d["streams"] == d["streams_first"],
        })
    # every arm must stay in the token-bucket signature families — one
    # stray kind means a mode escaped the packed launch
    rep["signature_kinds"] = sorted(kinds)
    rep["signature_kinds_ok"] = kinds <= {
        "ragged", "ragged_dec", "ragged_mm", "pp", "verify", "verify_fsm",
        "multi", "multi_fsm", "draft"}
    rep["ragged_ok"] = (
        rep["signature_reduction"] >= 4.0
        and rep["signature_kinds_ok"]
        and (not modes or (
            rep["spec_streams_identical"]
            and rep["multi_streams_identical"]
            and rep["mla_deterministic"]
            # CPU-noise floor: spec may be governor-disabled (low
            # acceptance on random tokens) and multi-step only engages on
            # decode-only plans — neither may cost real throughput
            and rep["spec_vs_base_tok_s"] >= 0.7
            and rep["multi_vs_base_tok_s"] >= 0.7)))
    return rep


#: ``--quant`` kernel-arm gates: the int8-weight arm must cash its byte
#: savings in. On TPU the measured wall-clock tok/s ratio is gated
#: directly; on the CPU fallback the 427 KB tiny model is dispatch-bound
#: (weights live in L2 — wall-clock cannot see HBM traffic), so the 1.5x
#: is asserted on the v5e bandwidth-floor tok/s computed from each arm's
#: REAL quantized bytes (a silent full-width fallback in quantize_params
#: fails it) while wall-clock only has to hold the no-regression floor.
QUANT_W8_SPEEDUP = 1.5
QUANT_WALL_FLOOR = 0.8


async def quant_bench(on_tpu: bool = False, reps: int = 2) -> dict:
    """``bench.py --quant``: quantized serving to the bandwidth floor —
    the ISSUE 19 A/B record.

    Kernel arms (round-robin interleaved timed rounds at fixed batch, so
    clock/thermal drift hits every arm equally instead of flattering the
    late ones): bf16 / int8 / int4-g32 weights x bf16 / int8 KV on the
    fused multi-step decode launch. Each arm reports ``quant_<arm>_tok_s``
    plus the roofline block (``*_hbm_gbps`` / ``*_hbm_util_v5e`` /
    ``*_params_bytes``) and its v5e bandwidth-floor tok/s from measured
    bytes (see QUANT_W8_SPEEDUP note for which one the gate reads).

    Engine arms (the ragged_bench mixed prefill+decode wave, shrunk):
    base bf16, int8 KV on the in-kernel dequant path, int8 KV forced onto
    the XLA oracle (``DYN_RAGGED_ORACLE=1`` — the deleted silent fallback
    kept reachable ONLY as this explicit A/B switch), int8 and int4-g32
    weights. Gates:

    - int8-KV greedy AND seeded streams bit-identical to the bf16-KV arm
      and to the oracle arm (cache quantization noise must stay below the
      sampler on the tiny-f32 horizon — docs/performance.md);
    - int8-KV compiled-signature census == bf16 census (zero new
      signatures: quantized KV rides the same packed launch);
    - int8-KV arm no slower than its oracle arm past the noise floor
      (in-kernel dequant must not lose to the fallback it replaced);
    - weight-quant arms deterministic across reps (int4 noise may move
      greedy argmax vs base, but never run-to-run);
    - plan_70b's solved quantized placement still fits under its
      bandwidth ceiling (``assert_quant``, solver half — the compile half
      runs in tests/test_quant_serving.py where 8 host devices exist).
    """
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.engine import model as M
    from dynamo_tpu.engine.cache import allocate_device_cache, tree_nbytes
    from dynamo_tpu.engine.config import EngineArgs, ModelConfig
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.engine.quant import quantize_params
    from dynamo_tpu.protocols import (PreprocessedRequest, SamplingOptions,
                                      StopConditions)

    # ------------------------------------------------- kernel arms (fixed B)
    if on_tpu:
        cfg = ModelConfig.llama3_1b()
        B, kv_len, iters, K = 64, 512, 50, 16
    else:
        cfg = ModelConfig.tiny()
        B, kv_len, iters, K = 8, 64, 10, 4
    block_size = 16
    W = (kv_len + K + block_size - 1) // block_size
    num_blocks = B * W + 1

    params = M.init_params(cfg, jax.random.key(0))
    host = jax.tree.map(np.asarray, params)
    multi = M.make_multi_decode_fn(cfg, block_size, K)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (B,)), jnp.int32)
    bt = np.zeros((B, W), np.int32)
    for i in range(B):
        bt[i] = 1 + i * W + np.arange(W)
    block_tables = jnp.asarray(bt)
    ints = jnp.stack([tokens, jnp.full((B,), kv_len - 1, jnp.int32),
                      jnp.full((B,), kv_len, jnp.int32),
                      jnp.zeros((B,), jnp.int32)], axis=1)
    floats = jnp.stack([jnp.zeros((B,), jnp.float32),
                        jnp.ones((B,), jnp.float32)], axis=1)
    rand = jnp.zeros((B, 2), jnp.uint32)

    arms = [("bf16", None, False), ("w8", "int8", False),
            ("w4g32", "int4-g32", False), ("kv8", None, True),
            ("w4kv8", "int4-g32", True)]
    state: dict = {}
    for name, quant, kv8 in arms:
        p = (jax.device_put(quantize_params(host, quant)) if quant
             else params)
        kc, vc = allocate_device_cache(cfg, num_blocks, block_size,
                                       dtype="int8" if kv8 else None)
        kv_tok = ((tree_nbytes(kc) + tree_nbytes(vc))
                  / (num_blocks * block_size))
        toks, _, kc, vc = multi(p, ints, floats, rand, block_tables, kc, vc)
        int(toks[0, 0])  # compile + settle before any arm's timed round
        state[name] = {"params": p, "kc": kc, "vc": vc, "kv_tok": kv_tok,
                       "tok_s": 0.0}
    for _ in range(max(reps, 2)):
        for name, _, _ in arms:
            st = state[name]
            kc, vc = st["kc"], st["vc"]
            t0 = time.perf_counter()
            for _ in range(iters):
                toks, _, kc, vc = multi(st["params"], ints, floats, rand,
                                        block_tables, kc, vc)
            # a device->host fetch forces completion of the donated chain
            int(toks[-1, 0])
            dt = time.perf_counter() - t0
            st["kc"], st["vc"] = kc, vc
            st["tok_s"] = max(st["tok_s"], B * K * iters / dt)

    rep: dict = {"quant_kernel_shape":
                 f"B={B},kv={kv_len},K={K},iters={iters}"}
    for name, _, _ in arms:
        st = state[name]
        rep[f"quant_{name}_tok_s"] = round(st["tok_s"], 1)
        roof = _roofline(st["params"], st["tok_s"], st["tok_s"] / B,
                         f"quant_{name}")
        rep.update(roof)
        # decode tok/s at the v5e bandwidth floor from MEASURED bytes:
        # every step streams the weights once + each row's KV window
        step_bytes = (roof[f"quant_{name}_params_bytes"]
                      + B * kv_len * st["kv_tok"])
        rep[f"quant_{name}_tok_s_v5e_floor"] = int(
            B / (step_bytes / HBM_BW_V5E))
    del state  # release the donated caches before the engine arms
    rep["quant_w8_vs_bf16"] = round(
        rep["quant_w8_tok_s"] / max(rep["quant_bf16_tok_s"], 1e-9), 3)
    rep["quant_w8_vs_bf16_v5e_floor"] = round(
        rep["quant_w8_tok_s_v5e_floor"]
        / max(rep["quant_bf16_tok_s_v5e_floor"], 1), 3)
    w8_gate = (rep["quant_w8_vs_bf16"] if on_tpu
               else rep["quant_w8_vs_bf16_v5e_floor"])

    # ------------------------------------------ engine arms (mixed wave)
    if on_tpu:
        ecfg = ModelConfig.llama3_1b()
        bs = 16
        N_P, ISL_P, OSL_P = 4, 256, 16
        N_D, ISL_D, OSL_D = 4, 64, 32
        slots, budget = 16, 512
        extra = dict(use_pallas_attention=True)
    else:
        ecfg = ModelConfig.tiny()
        bs = 4
        N_P, ISL_P, OSL_P = 3, 48, 8
        N_D, ISL_D, OSL_D = 3, 12, 16
        slots, budget = 8, 64
        extra = {}
    max_len = 2 * max(ISL_P + OSL_P, ISL_D + OSL_D)
    working = (N_P * ((ISL_P + OSL_P + bs - 1) // bs)
               + N_D * ((ISL_D + OSL_D + bs - 1) // bs))
    base = dict(block_size=bs, num_blocks=2 * working + 8,
                max_num_seqs=slots, max_num_batched_tokens=budget,
                max_model_len=max_len, enable_prefix_caching=False, **extra)
    wrng = np.random.default_rng(41)
    p_prompts = [wrng.integers(1, ecfg.vocab_size, ISL_P).tolist()
                 for _ in range(N_P)]
    d_prompts = [wrng.integers(1, ecfg.vocab_size, ISL_D).tolist()
                 for _ in range(N_D)]

    def req(tokens, osl, seed=None):
        sopt = (SamplingOptions(temperature=0.0) if seed is None else
                SamplingOptions(temperature=0.8, top_p=0.9, seed=seed))
        return PreprocessedRequest(
            model="m", token_ids=list(tokens),
            stop_conditions=StopConditions(max_tokens=osl, ignore_eos=True),
            sampling_options=sopt)

    async def one(eng, tokens, osl, seed=None):
        toks = []
        async for out in eng.generate(req(tokens, osl, seed)):
            toks.extend(out.token_ids)
        return toks

    async def wave(eng, seeded=False):
        """Decode-heavy first; prefill-heavy arrives once decode is
        underway — the mixed regime of ragged_bench, on every arm."""
        t0 = time.perf_counter()
        dec = [asyncio.ensure_future(
            one(eng, p, OSL_D, seed=100 + i if seeded else None))
            for i, p in enumerate(d_prompts)]
        for _ in range(20000):
            if any(s.generated > 0 for s in eng.scheduler.running):
                break
            await asyncio.sleep(0.001)
        pre = [asyncio.ensure_future(
            one(eng, p, OSL_P, seed=200 + i if seeded else None))
            for i, p in enumerate(p_prompts)]
        res = await asyncio.gather(*dec, *pre)
        return res, time.perf_counter() - t0

    async def measure(**arm_args) -> dict:
        eng = AsyncJaxEngine(ecfg, EngineArgs(**base, **arm_args))
        out: dict = {}
        res0, _ = await wave(eng)  # serving caches warm (XLA compiled)
        out["streams_first"] = res0
        for _ in range(reps):
            res, dt = await wave(eng)
            out["tok_s"] = max(out.get("tok_s", 0.0),
                               sum(len(t) for t in res) / dt)
            out["greedy"] = res
        sres, _ = await wave(eng, seeded=True)
        out["seeded"] = sres
        out["signatures"] = sorted(eng.compiled_signatures)
        await eng.close()
        return out

    ebase = await measure()
    ekv8 = await measure(kv_cache_dtype="int8")
    # oracle arm: the SAME int8-KV engine forced onto the XLA ragged
    # reference — the only remaining way to reach the ex-fallback path
    os.environ["DYN_RAGGED_ORACLE"] = "1"
    try:
        eoracle = await measure(kv_cache_dtype="int8")
    finally:
        os.environ.pop("DYN_RAGGED_ORACLE", None)
    ew8 = await measure(quantization="int8")
    ew4 = await measure(quantization="int4-g32")

    rep.update({
        "serve_workload": (f"pre={N_P}x(ISL={ISL_P},OSL={OSL_P}) "
                           f"dec={N_D}x(ISL={ISL_D},OSL={OSL_D}) "
                           f"slots={slots} budget={budget}"),
        "serve_base_tok_s": round(ebase["tok_s"], 1),
        "serve_kv8_tok_s": round(ekv8["tok_s"], 1),
        "serve_kv8_oracle_tok_s": round(eoracle["tok_s"], 1),
        "serve_w8_tok_s": round(ew8["tok_s"], 1),
        "serve_w4_tok_s": round(ew4["tok_s"], 1),
        "kv8_greedy_identical": ekv8["greedy"] == ebase["greedy"],
        "kv8_seeded_identical": ekv8["seeded"] == ebase["seeded"],
        "kv8_oracle_greedy_identical": ekv8["greedy"] == eoracle["greedy"],
        "kv8_oracle_seeded_identical": ekv8["seeded"] == eoracle["seeded"],
        "kv8_new_signatures": [
            list(s) for s in ekv8["signatures"]
            if s not in ebase["signatures"]],
        "kv8_vs_oracle_tok_s": round(
            ekv8["tok_s"] / max(eoracle["tok_s"], 1e-9), 3),
        "w8_deterministic": ew8["greedy"] == ew8["streams_first"],
        "w4_deterministic": ew4["greedy"] == ew4["streams_first"],
    })

    # solver half of the 70B quantized-placement gate (fast, no compile —
    # the bench child has a single initialized CPU device)
    from benchmarks.plan_70b import assert_quant
    plan = assert_quant(run_compile=False)
    rep["plan_70b"] = {k: plan[k] for k in
                       ("combo", "fits", "kernel_hbm_util_v5e", "quant_ok")}

    rep["quant_ok"] = (
        w8_gate >= QUANT_W8_SPEEDUP
        and rep["quant_w8_vs_bf16"] >= QUANT_WALL_FLOOR
        and rep["kv8_greedy_identical"] and rep["kv8_seeded_identical"]
        and rep["kv8_oracle_greedy_identical"]
        and rep["kv8_oracle_seeded_identical"]
        and not rep["kv8_new_signatures"]
        and rep["kv8_vs_oracle_tok_s"] >= QUANT_WALL_FLOOR
        and rep["w8_deterministic"] and rep["w4_deterministic"]
        and plan["quant_ok"])
    return rep


async def flight_bench(on_tpu: bool = False, reps: int = 4) -> dict:
    """``bench.py --flight``: the flight recorder's two contracts (ISSUE 12
    acceptance).

    1. Overhead A/B — the SAME seeded mixed prefill+decode workload runs
       with the recorder on and off (arms interleaved per rep, best-of
       tok/s each); the recorder must cost ≤3% tok/s and the greedy token
       streams must be bit-identical (recording is pure observation).
    2. Anomaly tagging e2e — a second engine with an undersized pool runs
       an oversubscribed wave (seeded preempt storm) and then a long
       prompt that forces a NEW ragged token bucket in steady state; the
       recorder must tag ``preempt-storm`` and ``compile-steady`` records
       and count the compile in engine.compile_events.
    """
    from dynamo_tpu.engine.config import EngineArgs, ModelConfig
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.protocols import (PreprocessedRequest, SamplingOptions,
                                      StopConditions)

    if on_tpu:
        cfg = ModelConfig.llama3_1b()
        bs = 16
        N_P, ISL_P, OSL_P = 6, 512, 32
        N_D, ISL_D, OSL_D = 6, 64, 96
        slots, budget = 12, 1024
        extra = dict(use_pallas_attention=True)
    else:
        cfg = ModelConfig.tiny()
        bs = 4
        # waves long enough that the ~±5% per-0.2s-wave scheduling noise
        # of the shared 2-core host averages out under a 3% gate
        N_P, ISL_P, OSL_P = 3, 96, 24
        N_D, ISL_D, OSL_D = 4, 16, 192
        slots, budget = 8, 128
        extra = {}
    max_len = 2 * max(ISL_P + OSL_P, ISL_D + OSL_D)
    working = (N_P * ((ISL_P + OSL_P + bs - 1) // bs)
               + N_D * ((ISL_D + OSL_D + bs - 1) // bs))
    base = dict(block_size=bs, num_blocks=2 * working + 8, max_num_seqs=slots,
                max_num_batched_tokens=budget, max_model_len=max_len,
                enable_prefix_caching=False, **extra)
    rng = np.random.default_rng(53)
    p_prompts = [rng.integers(1, cfg.vocab_size, ISL_P).tolist()
                 for _ in range(N_P)]
    d_prompts = [rng.integers(1, cfg.vocab_size, ISL_D).tolist()
                 for _ in range(N_D)]

    def req(tokens, osl):
        return PreprocessedRequest(
            model="m", token_ids=list(tokens),
            stop_conditions=StopConditions(max_tokens=osl, ignore_eos=True),
            sampling_options=SamplingOptions(temperature=0.0))

    async def one(eng, tokens, osl):
        toks = []
        async for out in eng.generate(req(tokens, osl)):
            toks.extend(out.token_ids)
        return toks

    async def wave(eng):
        t0 = time.perf_counter()
        dec = [asyncio.ensure_future(one(eng, p, OSL_D)) for p in d_prompts]
        for _ in range(20000):
            if any(s.generated > 0 for s in eng.scheduler.running):
                break
            await asyncio.sleep(0.001)
        pre = [asyncio.ensure_future(one(eng, p, OSL_P)) for p in p_prompts]
        res = await asyncio.gather(*dec, *pre)
        return res, time.perf_counter() - t0

    # ---- 1) overhead A/B: one engine per arm, warmed identically. The
    # gate uses the MEDIAN of per-rep paired on/off ratios: the two arms
    # of a rep run back to back so host drift cancels within the pair,
    # and the median ignores the one rep a background hiccup lands on —
    # best-of-per-arm measured ±6% swings on the 2-core host, far above
    # the recorder's real ~1% cost
    engines = {}
    for flight_on in (True, False):
        eng = AsyncJaxEngine(cfg, EngineArgs(**base))
        eng.flight.enabled = flight_on
        await wave(eng)  # compile surfaces warm, off the measured path
        engines[flight_on] = eng
    out = {"flight_reps": reps}
    streams: dict[bool, list] = {}
    ratios = []
    totals = {True: [0, 0.0], False: [0, 0.0]}  # tokens, seconds per arm
    seq0 = engines[True].flight.summary()["steps_total"]  # warmup records
    for rep in range(reps):
        pair = {}
        # alternate arm order per rep: a systematic first-position
        # penalty (allocator/GC state after the previous arm's wave)
        # would otherwise read as recorder overhead
        order = (True, False) if rep % 2 == 0 else (False, True)
        for flight_on in order:
            res, dt = await wave(engines[flight_on])
            n_tok = sum(len(t) for t in res)
            totals[flight_on][0] += n_tok
            totals[flight_on][1] += dt
            pair[flight_on] = n_tok / dt
            if rep == 0:
                streams[flight_on] = res
        ratios.append(pair[True] / max(pair[False], 1e-9))
    identical = streams[True] == streams[False]
    on_eng = engines[True]
    out["flight_records"] = len(on_eng.flight)
    out["flight_off_records"] = len(engines[False].flight)
    # The ≤3% gate is computed DIRECTLY: measured per-record cost × the
    # workload's observed record rate. The wave A/B above rides along as
    # a sanity ratio but cannot arbitrate 3% — per-wave tok/s on the
    # shared 2-core host swings ±10% while the recorder's true cost
    # measures ~0.1–0.5% (docs/PERF_NOTES.md).
    # records from the MEASURED waves only — the warmup wave's records
    # (seq0) ran outside the timed window and would inflate the rate
    records_per_s = ((on_eng.flight.summary()["steps_total"] - seq0)
                     / max(totals[True][1], 1e-9))
    M = 2000
    t0 = time.perf_counter()
    for _ in range(M):
        on_eng._flight_record("decode_pipe", 1.0, decode_rows=4,
                              prefill_chunks=0, chunk_tokens=0, starved=0)
    cost_s = (time.perf_counter() - t0) / M
    out["flight_record_cost_us"] = round(cost_s * 1e6, 2)
    out["flight_records_per_s"] = round(records_per_s, 1)
    out["flight_overhead_frac"] = round(cost_s * records_per_s, 5)
    for eng in engines.values():
        await eng.close()
    # the gate metric is the AGGREGATE tok/s ratio over every wave of
    # both arms (orders alternated): per-wave ratios still ride along to
    # show the spread the aggregation is averaging out
    out["flight_on_tok_s"] = round(totals[True][0] / totals[True][1], 1)
    out["flight_off_tok_s"] = round(totals[False][0] / totals[False][1], 1)
    out["flight_rep_ratios"] = [round(r, 4) for r in ratios]
    out["flight_overhead_ratio"] = round(
        out["flight_on_tok_s"] / max(out["flight_off_tok_s"], 1e-9), 4)
    out["flight_streams_identical"] = identical

    # ---- 2) anomaly tagging: a SEEDED preempt storm — batch-class
    # streams fill every slot, then an interactive burst lands and QoS
    # admission preemption (docs/qos.md) evicts a batch victim per
    # arrival, recompute-mode so each eviction is a genuine preemption.
    # Then a prompt forcing a NEW ragged token bucket in steady state.
    from dynamo_tpu.runtime.context import Context

    async def one_cls(eng, tokens, osl, cls):
        ctx = Context()
        ctx.priority = cls
        toks = []
        async for out_ in eng.generate(req(tokens, osl), ctx):
            toks.extend(out_.token_ids)
        return toks

    eng = AsyncJaxEngine(cfg, EngineArgs(**base, preempt_swap=False))
    eng.flight.steady_after = 16  # tiny workload: steady state arrives fast
    batch = [asyncio.ensure_future(
        one_cls(eng, rng.integers(1, cfg.vocab_size, 24).tolist(), 48,
                "batch")) for _ in range(slots)]
    for _ in range(20000):  # every slot decoding before the burst lands
        if sum(s.generated > 0 for s in eng.scheduler.running) >= slots:
            break
        await asyncio.sleep(0.001)
    inter = [asyncio.ensure_future(
        one_cls(eng, rng.integers(1, cfg.vocab_size, 12).tolist(), 8,
                "interactive")) for _ in range(max(5, slots - 2))]
    await asyncio.gather(*batch, *inter)
    out["storm_preempts"] = eng.scheduler.preempt_recompute_total
    # steady-state compile probe: a prompt sized to a ragged token bucket
    # the storm never dispatched, sent alone → its one chunk IS the packed
    # total, so the step traces a fresh (ragged, T) signature mid-traffic
    unseen = next((b for b in eng.args.ragged_token_buckets
                   if ("ragged", b) not in eng.compiled_signatures
                   and b <= budget), budget)
    await one(eng, rng.integers(1, cfg.vocab_size, unseen).tolist(), 4)
    anoms = dict(eng.flight.summary()["anomalies"])
    recs = eng.flight.snapshot()
    out["anomaly_counts"] = anoms
    out["preempt_storm_tagged"] = bool(anoms.get("preempt-storm"))
    out["compile_steady_tagged"] = bool(anoms.get("compile-steady"))
    out["compile_events"] = dict(eng.compile_events)
    out["tagged_example"] = next(
        (r for r in reversed(recs) if "compile-steady" in r["tags"]), None)
    await eng.close()

    out["flight_ok"] = (out["flight_overhead_frac"] <= 0.03
                        and identical
                        and out["preempt_storm_tagged"]
                        and out["compile_steady_tagged"])
    return out


async def attribution_bench(on_tpu: bool = False) -> dict:
    """``bench.py --attribution``: the latency-attribution engine's three
    contracts (ISSUE 14 acceptance; docs/observability.md "Attribution").

    1. Falsifiability on a seeded QoS-mixed drive — for every request,
       the decomposition's buckets + residual must equal the measured e2e
       (≥95% of requests within 5%) with the unattributed residual ≤10%
       of e2e at p95.
    2. Pure observation — the SAME seeded workload with attribution
       (flight recording + id linkage) on vs off yields bit-identical
       greedy token streams.
    3. Anomaly-triggered profiling — a seeded preempt storm + forced
       steady-state compiles with DYN_PROFILE_ON_ANOMALY set produce at
       least one real ``jax.profiler`` capture, capped by the
       max-captures budget, with the artifact path on the triggering
       StepRecord.
    """
    from dynamo_tpu.engine.config import EngineArgs, ModelConfig
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.observability import configure_tracer, gather_attribution
    from dynamo_tpu.protocols import (PreprocessedRequest, SamplingOptions,
                                      StopConditions)
    from dynamo_tpu.runtime.context import Context

    configure_tracer(service="attribution-bench", capacity=8192)
    if on_tpu:
        cfg = ModelConfig.llama3_1b()
        bs = 16
        N_I, ISL_I, OSL_I = 6, 128, 24
        N_B, ISL_B, OSL_B = 8, 384, 48
        slots = 8
        extra = dict(use_pallas_attention=True)
    else:
        cfg = ModelConfig.tiny()
        bs = 4
        N_I, ISL_I, OSL_I = 6, 32, 12
        N_B, ISL_B, OSL_B = 6, 96, 32
        slots = 6
        extra = {}
    working = (N_B * ((ISL_B + OSL_B + bs - 1) // bs)
               + N_I * ((ISL_I + OSL_I + bs - 1) // bs))
    base = dict(block_size=bs, num_blocks=working + 8, max_num_seqs=slots,
                max_num_batched_tokens=2 * max(ISL_B, 128),
                max_model_len=2 * (ISL_B + OSL_B),
                enable_prefix_caching=False, **extra)
    rng = np.random.default_rng(31)
    int_prompts = [rng.integers(1, cfg.vocab_size, ISL_I).tolist()
                   for _ in range(N_I)]
    bat_prompts = [rng.integers(1, cfg.vocab_size, ISL_B).tolist()
                   for _ in range(N_B)]

    def req(tokens, osl):
        return PreprocessedRequest(
            model="m", token_ids=list(tokens),
            stop_conditions=StopConditions(max_tokens=osl, ignore_eos=True),
            sampling_options=SamplingOptions(temperature=0.0))

    async def one(eng, tokens, osl, cls, collect=None):
        ctx = Context()
        ctx.priority = cls
        ctx.ensure_traceparent()
        t0 = time.perf_counter()
        toks = []
        async for out in eng.generate(req(tokens, osl), ctx):
            toks.extend(out.token_ids)
        if collect is not None:
            collect.append((ctx.id, time.perf_counter() - t0))
        return toks

    async def drive(eng, collect=None):
        bat = [asyncio.ensure_future(
            one(eng, p, OSL_B, "batch", collect)) for p in bat_prompts]
        for _ in range(20000):
            if any(s.generated > 0 for s in eng.scheduler.running):
                break
            await asyncio.sleep(0.001)
        ints = [asyncio.ensure_future(
            one(eng, p, OSL_I, "interactive", collect))
            for p in int_prompts]
        return await asyncio.gather(*bat, *ints)

    out: dict = {}

    # ---- 1) falsifiability: attribute every request of a seeded drive
    eng = AsyncJaxEngine(cfg, EngineArgs(**base))
    await drive(eng)  # compile surfaces warm, off the measured path
    measured: list = []
    streams_on = await drive(eng, collect=measured)
    within, resid_fracs, incomplete = 0, [], 0
    for rid, wall_s in measured:
        doc = await gather_attribution(rid)
        if doc is None:
            continue
        total = sum(doc["total"].values())
        # the sweep partitions the doc's own window exactly; the 5%
        # contract is against the CLIENT-measured wall clock, which adds
        # sink handoff + generator overhead around the spans
        if abs(total - wall_s * 1000.0) <= 0.05 * wall_s * 1000.0 + 1.0:
            within += 1
        resid_fracs.append(doc["residual_ms"] / max(doc["e2e_ms"], 1e-9))
        incomplete += bool(doc["incomplete"])
    n = len(measured)
    out["attr_requests"] = n
    out["attr_within_5pct_frac"] = round(within / max(n, 1), 4)
    out["attr_residual_p95_frac"] = round(_p95(resid_fracs), 4)
    out["attr_incomplete"] = incomplete
    await eng.close()

    # ---- 2) pure observation: same seed, flight+linkage on vs off
    streams = {}
    for flight_on in (True, False):
        e = AsyncJaxEngine(cfg, EngineArgs(**base))
        e.flight.enabled = flight_on
        await drive(e)  # warm
        streams[flight_on] = await drive(e)
        await e.close()
    out["attr_streams_identical"] = streams[True] == streams[False]
    # re-check the primary drive too (recording was on there)
    out["attr_streams_identical"] &= streams[True] == streams_on

    # ---- 3) anomaly-triggered profiler: seeded storm + steady compiles
    # under a capped capture budget (REAL jax.profiler device traces)
    profile_dir = tempfile.mkdtemp(prefix="dyn-anomaly-")
    old_env = {k: os.environ.get(k) for k in
               ("DYN_PROFILE_ON_ANOMALY", "DYN_PROFILE_MAX_CAPTURES",
                "DYN_PROFILE_COOLDOWN_S", "DYN_PROFILE_STEPS")}
    os.environ.update({"DYN_PROFILE_ON_ANOMALY": profile_dir,
                       "DYN_PROFILE_MAX_CAPTURES": "2",
                       "DYN_PROFILE_COOLDOWN_S": "0",
                       "DYN_PROFILE_STEPS": "4"})
    try:
        eng = AsyncJaxEngine(cfg, EngineArgs(**base, preempt_swap=False))
        eng.flight.steady_after = 16
        batch = [asyncio.ensure_future(
            one(eng, rng.integers(1, cfg.vocab_size, 24).tolist(), 48,
                "batch")) for _ in range(slots)]
        for _ in range(20000):
            if sum(s.generated > 0 for s in eng.scheduler.running) >= slots:
                break
            await asyncio.sleep(0.001)
        inter = [asyncio.ensure_future(
            one(eng, rng.integers(1, cfg.vocab_size, 12).tolist(), 8,
                "interactive")) for _ in range(max(4, slots - 2))]
        await asyncio.gather(*batch, *inter)
        # steady-state compile probes: prompts sized to ragged buckets the
        # storm never dispatched — each traces a fresh signature, tags
        # compile-steady, and (budget permitting) arms a capture
        unseen = [b for b in eng.args.ragged_token_buckets
                  if ("ragged", b) not in eng.compiled_signatures
                  and b <= base["max_num_batched_tokens"]][:4]
        for b in unseen:
            await one(eng, rng.integers(1, cfg.vocab_size, b).tolist(), 2,
                      "standard")
        prof = eng.anomaly_profiler
        out["profiler_captures"] = prof.captures if prof else 0
        out["profiler_paths"] = list(prof.capture_paths) if prof else []
        out["profiler_budget_respected"] = (
            (prof.captures if prof else 0) <= 2)
        # a REAL artifact landed (xplane.pb under the capture dir)
        import glob
        artifacts = glob.glob(os.path.join(profile_dir, "**", "*.pb"),
                              recursive=True)
        out["profiler_artifacts"] = len(artifacts)
        recs = eng.flight.snapshot()
        out["profile_path_on_record"] = any(
            r.get("profile_path") for r in recs)
        anoms = dict(eng.flight.summary()["anomalies"])
        out["storm_tagged"] = bool(anoms.get("preempt-storm"))
        await eng.close()
    finally:
        for k, v in old_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    out["attribution_ok"] = (
        out["attr_within_5pct_frac"] >= 0.95
        and out["attr_residual_p95_frac"] <= 0.10
        and out["attr_streams_identical"]
        and out["profiler_captures"] >= 1
        and out["profiler_budget_respected"]
        and out["profiler_artifacts"] >= 1
        and out["profile_path_on_record"])
    return out


async def tools_bench(on_tpu: bool = False, reps: int = 3,
                      sessions: int = 2, turns: int = 3) -> dict:
    """``bench.py --tools``: the agentic tool-loop as a first-class
    workload (ISSUE 13 acceptance; docs/structured.md).

    1. Constrained-vs-free A/B — multi-turn tool-call sessions where each
       turn's prompt is the previous turn's prompt + the model's tool call
       + a synthetic tool result, so turn 2+ re-hits its own growing
       prefix via the radix cache. The constrained arm enforces
       ``tool_choice: "required"`` through the device-FSM path; the free
       arm decodes unconstrained. Gates: 100% schema-valid constrained
       output, constrained tok/s ≥ 0.9× free (the device path must not
       tax decode), turn-2+ prefix-hit tokens > 0, zero host-oracle
       fallbacks.
    2. Peer provenance — a 2-worker fleet: a session's first turn lands
       on worker A; later turns are steered to worker B, whose admission
       peer-pulls the session's own prefix over the PR 11 onboarding wire
       (constrained throughout). Gate: pulled blocks > 0 with the stream
       complete.
    """
    import json as _json

    from dynamo_tpu.engine.config import EngineArgs, ModelConfig
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.protocols import (PreprocessedRequest, SamplingOptions,
                                      StopConditions)
    from dynamo_tpu.structured.tools import tool_constraint

    cfg = ModelConfig.llama3_1b() if on_tpu else ModelConfig.tiny()
    extra = dict(use_pallas_attention=True) if on_tpu else {}
    bs = 16
    vocab = [""] + [chr(32 + i) for i in range(cfg.vocab_size - 1)]
    eos_id = 2
    tools = [
        {"type": "function", "function": {
            "name": "get", "parameters": {
                "type": "object",
                "properties": {"k": {"enum": ["a", "b"]}}}}},
        {"type": "function", "function": {
            "name": "put", "parameters": {
                "type": "object",
                "properties": {"k": {"enum": ["a", "b"]},
                               "n": {"type": "integer"}}}}},
    ]
    pattern = tool_constraint(tools, "required", None)
    tool_names = {"get", "put"}
    rng = np.random.default_rng(61)
    base_prompt = rng.integers(3, cfg.vocab_size, 96).tolist()
    result_filler = [rng.integers(3, cfg.vocab_size, 48).tolist()
                     for _ in range(turns)]
    OSL = 48

    def req(tokens, constrained):
        return PreprocessedRequest(
            model="m", token_ids=list(tokens),
            stop_conditions=StopConditions(max_tokens=OSL),
            sampling_options=SamplingOptions(
                temperature=0.0,
                guided={"regex": pattern} if constrained else None),
            eos_token_ids=[eos_id])

    def decode_text(toks):
        return "".join(vocab[t] for t in toks if t != eos_id)

    def schema_valid(toks) -> bool:
        try:
            obj = _json.loads(decode_text(toks))
        except Exception:
            return False
        return (isinstance(obj, dict) and obj.get("name") in tool_names
                and isinstance(obj.get("arguments"), dict))

    async def one_turn(eng, tokens, constrained):
        toks = []
        async for out in eng.generate(req(tokens, constrained)):
            toks.extend(out.token_ids)
            if out.finish_reason is not None:
                break
        return toks

    async def run_arm(eng, constrained, rep, n_sessions=None) -> dict:
        """All sessions advance their turns concurrently (each session's
        turns are sequential — the client blocks on every round trip).
        ``n_sessions=1`` doubles as the prefix-provenance probe: with one
        session nothing else touches the scheduler's (global) hit
        counter, so per-turn deltas attribute exactly."""
        ns = sessions if n_sessions is None else n_sessions
        hit0 = eng.scheduler.prefix_hit_tokens
        turn_hits = []

        async def session(si):
            state = base_prompt + [9 + rep * sessions + si]
            gen = 0
            valid = 0
            for t in range(turns):
                h0 = eng.scheduler.prefix_hit_tokens
                toks = await one_turn(eng, state, constrained)
                gen += len(toks)
                valid += schema_valid(toks)
                if t > 0:
                    turn_hits.append(eng.scheduler.prefix_hit_tokens - h0)
                state = state + toks + result_filler[t]
            return gen, valid

        t0 = time.perf_counter()
        res = await asyncio.gather(*[session(i) for i in range(ns)])
        dt = time.perf_counter() - t0
        return {
            "tok_s": sum(g for g, _ in res) / dt,
            "valid": sum(v for _, v in res),
            "total_turns": ns * turns,
            "turn2_hits": sum(turn_hits),
            "hit_tokens": eng.scheduler.prefix_hit_tokens - hit0,
        }

    blocks = (len(base_prompt) + turns * (OSL + 48) + 64) // bs
    eng = AsyncJaxEngine(cfg, EngineArgs(
        block_size=bs, num_blocks=sessions * blocks * 2 * (reps + 1) + 16,
        max_num_seqs=2 * sessions,
        max_num_batched_tokens=512,
        max_model_len=len(base_prompt) + turns * (OSL + 48) + 64,
        enable_prefix_caching=True, **extra), guided_vocab=vocab)
    assert eng.structured is not None, "device FSM arena failed to build"
    # compile surfaces off the measured path (both arms' signatures)
    await run_arm(eng, True, reps)
    await run_arm(eng, False, reps + 1)

    best = {True: None, False: None}
    valid = total = 0
    for rep in range(reps):
        order = (True, False) if rep % 2 == 0 else (False, True)
        for constrained in order:
            r = await run_arm(eng, constrained, rep)
            b = best[constrained]
            if b is None or r["tok_s"] > b["tok_s"]:
                best[constrained] = r
            if constrained:
                valid += r["valid"]
                total += r["total_turns"]
    # provenance probe: ONE session running alone, so the global hit
    # counter's per-turn deltas attribute exactly to that session's own
    # turn-2+ prefix re-hits (concurrent sessions' windows overlap and
    # would double-count each other's hits)
    prov = await run_arm(eng, True, reps * 2 + 5, n_sessions=1)
    turn2_hits = prov["turn2_hits"]
    valid += prov["valid"]
    total += prov["total_turns"]
    st = eng.structured.stats()
    pipelined = eng.pipelined_steps
    await eng.close()

    out = {
        "tools_workload": (f"sessions={sessions},turns={turns},OSL={OSL},"
                           f"reps={reps}"),
        "schema_valid_rate": round(valid / max(total, 1), 4),
        "constrained_tok_s": round(best[True]["tok_s"], 1),
        "free_tok_s": round(best[False]["tok_s"], 1),
        "constrained_vs_free": round(
            best[True]["tok_s"] / max(best[False]["tok_s"], 1e-9), 4),
        "turn2_prefix_hit_tokens": turn2_hits,
        "structured_rows_device": st["rows_device"],
        "structured_rows_host": st["rows_host"],
        "pipelined_steps": pipelined,
    }

    # ---- 2) peer provenance: turn 1 on A, turns 2+ steered to B, whose
    # admission onboards the session's own prefix over kv_pull (PR 11)
    try:
        out["peer"] = await _tools_peer_leg(cfg, vocab, pattern, eos_id,
                                            schema_valid, extra)
    except Exception as e:  # noqa: BLE001 — optional extra datum
        out["peer_error"] = repr(e)[:300]
    peer = out.get("peer") or {}
    out["tools_ok"] = (
        out["schema_valid_rate"] == 1.0
        and out["constrained_vs_free"] >= 0.9
        and out["turn2_prefix_hit_tokens"] > 0
        and out["structured_rows_host"] == 0
        and peer.get("pulled_blocks", 0) > 0
        and peer.get("complete", False))
    return out


async def _tools_peer_leg(cfg, vocab, pattern, eos_id, schema_valid,
                          extra) -> dict:
    """2-worker tool-loop: the session's prefix peer-onboards when its
    later turns land on a different worker (bench --tools scenario 2)."""
    from dynamo_tpu.disagg.handlers import DecodeWorkerHandler, KvPullHandler
    from dynamo_tpu.disagg.transfer import OnboardConfig, RestoreConfig
    from dynamo_tpu.engine.config import EngineArgs
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.protocols import (PreprocessedRequest, SamplingOptions,
                                      StopConditions)
    from dynamo_tpu.router.kv_router import KvPushRouter, KvRouter
    from dynamo_tpu.router.protocols import KvRouterConfig
    from dynamo_tpu.router.publisher import KvEventPublisher
    from dynamo_tpu.runtime import DistributedRuntime
    from dynamo_tpu.runtime.config import RuntimeConfig
    from dynamo_tpu.runtime.context import Context

    bs = 16
    isl = 512  # enough prefix blocks to clear onboard_min_blocks
    OSL = 48   # the char-level tool-call JSON needs ~40 tokens to close
    rng = np.random.default_rng(67)
    prefix = rng.integers(3, cfg.vocab_size, isl).tolist()
    rcfg = RuntimeConfig(lease_ttl=8.0)
    rt = await DistributedRuntime.create(config=rcfg)
    workers = []
    router = client = None

    async def make_worker():
        wrt = await DistributedRuntime.create(plane=rt.plane,
                                              owns_plane=False, config=rcfg)
        lease = await wrt.primary_lease()
        eng = await asyncio.to_thread(
            AsyncJaxEngine, cfg, EngineArgs(
                block_size=bs, num_blocks=4 * (isl // bs) + 64,
                max_num_seqs=4, max_num_batched_tokens=1024,
                max_model_len=isl + 4 * (OSL + 16) + bs,
                enable_prefix_caching=True, **extra), guided_vocab=vocab)
        pub = KvEventPublisher(wrt.plane, worker_id=lease, kv_block_size=bs)
        await pub.start_resync_responder()
        eng.event_cb = pub.publish_sync
        comp = wrt.namespace("dynamo").component("backend")
        pull_client = await comp.endpoint("kv_pull").client().start()
        handler = DecodeWorkerHandler(
            eng, pull_clients=[pull_client], metrics=wrt.metrics,
            restore_config=RestoreConfig(enabled=False),
            onboard_config=OnboardConfig(enabled=True))
        handler.instance_id = lease
        h_gen = await comp.endpoint("generate").serve_endpoint(
            handler.generate, lease_id=lease)
        h_pull = await comp.endpoint("kv_pull").serve_endpoint(
            KvPullHandler(eng).generate, lease_id=lease)
        w = type("W", (), {})()
        w.rt, w.engine, w.lease, w.handler = wrt, eng, lease, handler
        w.pub, w.pull_client, w.handles = pub, pull_client, [h_gen, h_pull]
        workers.append(w)
        return w

    def req(tokens, pin=None):
        return PreprocessedRequest(
            model="m", token_ids=list(tokens),
            stop_conditions=StopConditions(max_tokens=OSL),
            sampling_options=SamplingOptions(
                temperature=0.0, guided={"regex": pattern}),
            eos_token_ids=[eos_id], backend_instance_id=pin)

    try:
        a = await make_worker()
        b = await make_worker()
        client = await (rt.namespace("dynamo").component("backend")
                        .endpoint("generate").client().start())
        router = await KvRouter(rt.plane, bs, KvRouterConfig()).start()
        push = KvPushRouter(client, router)

        async def turn(tokens, pin=None):
            toks = []
            async for out in push.generate(req(tokens, pin), Context()):
                if isinstance(out, dict) and out.get("token_ids"):
                    toks.extend(out["token_ids"])
            return toks

        # turn 1 computes the session prefix on A
        state = prefix + [5]
        t1 = await turn(state, pin=a.lease)
        state = state + t1 + rng.integers(3, cfg.vocab_size, 32).tolist()
        # radix must learn A's prefix before steering away
        for _ in range(400):
            if router.restore_sources(state).get(a.lease, 0) \
                    >= isl // bs - 1:
                break
            await asyncio.sleep(0.02)
        client.set_busy_instances([a.lease])  # turns 2+ land on B
        t2 = await turn(state)
        pulled = b.handler._onboard_blocks._values.get(
            (("source", "peer"),), 0)
        return {
            "pulled_blocks": int(pulled),
            "complete": bool(t1 and t2 and schema_valid(t1)
                             and schema_valid(t2)),
            "turn1_tokens": len(t1), "turn2_tokens": len(t2),
        }
    finally:
        for w in workers:
            for h in w.handles:
                await h.stop(graceful=False)
            await w.pull_client.stop()
            await w.pub.stop()
            await w.engine.close()
            await w.rt.shutdown()
        if router is not None:
            await router.stop()
        if client is not None:
            await client.stop()
        await rt.shutdown()


async def kvaudit_bench(on_tpu: bool = False) -> dict:
    """``bench.py --kvaudit``: the KV index audit plane's contracts
    (ISSUE 15 acceptance; docs/observability.md "KV audit").

    Scenario 1 — mocker fleet under seeded ``plane.publish:drop`` chaos
    on the KV event stream: stored AND removed events are lost before
    the hub assigns a seq (no gap for the indexer to see), leaving the
    radix silently diverged. Gates: the auditor detects within one audit
    interval, classifies phantom vs missing EXACTLY against ground truth
    (worker ledgers + publisher mirrors vs the tree), heals via resync
    to digest equality, and a clean interleaved A/B (audit on vs off,
    same seeded prompts) streams bit-identical with ≤1% audit overhead
    (measured directly: audit cycle wall / the production 30 s interval).

    Scenario 2 — stale-advert demand loop on a real 2-engine fleet:
    worker A's prefix is evicted with its events suppressed (the radix
    keeps advertising it); admissions steered to B plan doomed pulls,
    tagged ``outcome=stale_advert``; the suspicion report wakes the
    router's auditor, which purges + resyncs (the ledger-aware replay
    retracts A's stale mirror entries), after which further admissions
    plan no pulls at A — the stale-advert rate returns to zero.
    """
    import aiohttp

    from dynamo_tpu.frontend.http import HttpService
    from dynamo_tpu.llm.discovery import ModelManager, ModelWatcher
    from dynamo_tpu.llm.tokenizer import make_test_tokenizer
    from dynamo_tpu.mocker.engine import MockEngineArgs
    from dynamo_tpu.mocker.main import run_mocker
    from dynamo_tpu.observability.kvaudit import AuditConfig, KvAuditor
    from dynamo_tpu.router.publisher import reachable_chain
    from dynamo_tpu.runtime import DistributedRuntime
    from dynamo_tpu.runtime.chaos import configure_chaos

    out: dict = {}
    U64 = (1 << 64) - 1
    AUDIT_INTERVAL = 0.6
    rng = np.random.default_rng(77)
    prompts = [rng.integers(10, 200, 24).tolist() for _ in range(8)]
    evictors = [rng.integers(210, 400, 40).tolist() for _ in range(5)]

    async def fleet(name):
        rt = await DistributedRuntime.create()
        args = MockEngineArgs(vocab_size=make_test_tokenizer().vocab_size,
                              block_size=4, num_gpu_blocks=72, dp_size=2,
                              speedup_ratio=50.0)
        engines, handles = await run_mocker(rt, name, args)
        manager = ModelManager()
        watcher = await ModelWatcher(rt, manager, router_mode="kv").start()
        service = HttpService(manager, port=0, runtime=rt)
        await service.start()
        for _ in range(200):
            if manager.list_models():
                break
            await asyncio.sleep(0.05)
        return rt, engines, handles, manager, watcher, service

    async def teardown(rt, engines, handles, watcher, service):
        await service.stop()
        await watcher.stop()
        for h in handles:
            await h.stop(graceful=False)
        for e in engines:
            await e.stop()
        await rt.shutdown()

    async def wave(service, name, ps):
        texts = []
        url = f"http://127.0.0.1:{service.port}/v1/completions"
        async with aiohttp.ClientSession() as session:
            for i, p in enumerate(ps):
                async with session.post(url, json={
                        "model": name, "prompt": list(p),
                        "max_tokens": 12, "ignore_eos": True}) as r:
                    assert r.status == 200, await r.text()
                    data = await r.json()
                    texts.append(data["choices"][0]["text"])
        return texts

    def gt_divergence(engines, tree):
        """Ground truth per worker: (phantom, missing) hash sets from the
        ledgers + mirrors vs the radix — the same taxonomy the auditor
        must reproduce from wire digests alone."""
        gt = {}
        for e in engines:
            wid = e.kv_publisher.worker_id
            resident = {h & U64 for h in e.kv_ledger.servable_hashes()}
            anchored = {bh & U64 for bh, _p, _t in reachable_chain(
                e.kv_publisher.announced_chain(),
                member={h & U64 for h in resident})}
            radix = {h & U64 for h in tree.worker_hashes(wid)}
            gt[wid] = (radix - resident, anchored - radix)
        return gt

    # ---- scenario 1: audit-off arm first (stream identity baseline)
    os.environ["DYN_KV_AUDIT"] = "0"
    try:
        rt2, eng2, h2, man2, wat2, svc2 = await fleet("kvaudit-off")
        try:
            texts_off = await wave(svc2, "kvaudit-off", prompts)
        finally:
            await teardown(rt2, eng2, h2, wat2, svc2)

        # ---- audit-on arm: same prompts, auditor live during the wave
        rt, engines, handles, manager, watcher, service = await fleet(
            "kvaudit-on")
        auditor = detect_auditor = None
        try:
            sm = manager.get("kvaudit-on")
            idx = sm.router.indexer
            acfg = AuditConfig(interval_s=AUDIT_INTERVAL, settle_s=0.05)
            auditor = await KvAuditor(rt.plane, idx, acfg).start()
            texts_on = await wave(service, "kvaudit-on", prompts)
            out["streams_identical"] = texts_on == texts_off
            # clean fleet: one audited cycle must report zero divergence,
            # and its wall time is the DIRECT overhead measurement
            cycle_walls = []
            for _ in range(5):
                t0 = time.perf_counter()
                doc = await auditor.audit_once()
                cycle_walls.append(time.perf_counter() - t0)
            out["clean_divergence"] = sum(
                w["phantom"] + w["missing"]
                for w in doc["workers"].values())
            out["audit_cycle_ms"] = round(
                min(cycle_walls) * 1000.0, 3)
            # production duty cycle: one cycle per DYN_KV_AUDIT_INTERVAL
            # (default 30 s) — overhead is cycle wall over the interval
            out["audit_overhead_frac"] = round(
                min(cycle_walls) / 30.0, 6)
            await auditor.stop()
            auditor = None

            # ---- seeded chaos: KV events lost BEFORE the hub assigns a
            # seq (my stream_publish chaos hook) — gap detection is blind
            configure_chaos("plane.publish:drop=1.0", seed=7)
            try:
                await wave(service, "kvaudit-on", evictors)
            finally:
                configure_chaos(None)
            # settle: drain whatever did reach the stream
            tail = await rt.plane.stream_last_seq("kv_events")
            for _ in range(300):
                if idx._last_seq >= tail:
                    break
                await asyncio.sleep(0.01)
            gt = gt_divergence(engines, idx.tree)
            out["gt_phantom"] = sum(len(p) for p, _m in gt.values())
            out["gt_missing"] = sum(len(m) for _p, m in gt.values())

            # ---- detection + classification: a REPORT-ONLY production
            # auditor (DYN_KV_AUDIT_HEAL=0 semantics) must find the
            # divergence within one interval and classify every worker
            # against ground truth — report-only because a healing
            # auditor's FIRST resync repairs the whole fleet's missing
            # blocks at once, leaving later-audited workers nothing to
            # classify (traffic is quiesced, so gt is static until heal)
            import dataclasses as _dc

            detect_auditor = KvAuditor(
                rt.plane, idx, _dc.replace(acfg, heal_enabled=False))
            diverged_wids = [wid for wid, (p, m) in gt.items() if p or m]
            t0 = time.perf_counter()
            await detect_auditor.start()
            detected = False
            for _ in range(int((AUDIT_INTERVAL + 3.0) / 0.02)):
                if diverged_wids and all(
                        (detect_auditor.worker_state.get(w) or {}).get(
                            "diverged_since") for w in diverged_wids):
                    detected = True
                    break
                await asyncio.sleep(0.02)
            out["detect_latency_s"] = round(time.perf_counter() - t0, 3)
            out["detected_within_interval"] = (
                detected
                and out["detect_latency_s"] <= AUDIT_INTERVAL + 2.0)
            # counts per worker must match gt exactly, samples ⊆ gt sets
            classified_ok = detected
            for e in engines:
                wid = e.kv_publisher.worker_id
                st = detect_auditor.worker_state.get(wid) or {}
                gp, gm = gt.get(wid, (set(), set()))
                if (st.get("phantom", 0), st.get("missing", 0)) \
                        != (len(gp), len(gm)):
                    classified_ok = False
                samp = st.get("samples") or {}
                if not set(samp.get("phantom") or ()) <= gp \
                        or not set(samp.get("missing") or ()) <= gm:
                    classified_ok = False
            out["classified_correctly"] = classified_ok
            await detect_auditor.stop()

            # ---- heal: the healing auditor must drive phantom+missing
            # to zero (dangling — mid-chain LRU holes no resync can
            # re-anchor — is reported, not counted as divergence)
            detect_auditor = await KvAuditor(rt.plane, idx, acfg).start()
            healed = False
            for _ in range(40):
                doc = await detect_auditor.audit_once()
                remaining = sum(w["phantom"] + w["missing"]
                                for w in doc["workers"].values())
                if detect_auditor.heals_total and remaining == 0:
                    healed = True
                    break
                await asyncio.sleep(0.25)
            out["healed"] = healed
            out["heals_total"] = dict(detect_auditor.heals_total)
            out["post_heal_divergence"] = sum(
                w["phantom"] + w["missing"]
                for w in doc["workers"].values())
            out["post_heal_dangling"] = sum(
                w["dangling"] for w in doc["workers"].values())
        finally:
            for a in (auditor, detect_auditor):
                if a is not None:
                    await a.stop()
            await teardown(rt, engines, handles, watcher, service)
    finally:
        os.environ.pop("DYN_KV_AUDIT", None)

    # ---- scenario 2: stale-advert demand loop on a real engine fleet
    out.update(await _kvaudit_stale_advert_leg(AUDIT_INTERVAL))

    out["kvaudit_ok"] = bool(
        out["streams_identical"]
        and out["clean_divergence"] == 0
        and out["audit_overhead_frac"] <= 0.01
        and out["gt_phantom"] > 0
        and out["gt_missing"] > 0
        and out["detected_within_interval"]
        and out["classified_correctly"]
        and out["healed"]
        and out["post_heal_divergence"] == 0
        and out["stale_adverts_pre_heal"] >= 1
        and out["stale_adverts_post_heal"]
        == out["stale_adverts_pre_heal"]
        and out["stale_heal_cause"] == "phantom")
    return out


async def _kvaudit_stale_advert_leg(audit_interval: float) -> dict:
    """kvaudit scenario 2: doomed pulls at a lying advert are tagged
    stale_advert, suspicion wakes the auditor, the heal retracts the
    advert, and subsequent admissions stop planning pulls there."""
    from dynamo_tpu.disagg.handlers import DecodeWorkerHandler, KvPullHandler
    from dynamo_tpu.disagg.transfer import OnboardConfig, RestoreConfig
    from dynamo_tpu.engine.config import EngineArgs, ModelConfig
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.observability.kvaudit import serve_kv_digest
    from dynamo_tpu.protocols import (PreprocessedRequest, SamplingOptions,
                                      StopConditions)
    from dynamo_tpu.router.kv_router import KvPushRouter, KvRouter
    from dynamo_tpu.router.protocols import KvRouterConfig
    from dynamo_tpu.router.publisher import KvEventPublisher
    from dynamo_tpu.runtime import DistributedRuntime
    from dynamo_tpu.runtime.config import RuntimeConfig
    from dynamo_tpu.runtime.context import Context

    cfg = ModelConfig.tiny()
    bs = 16
    isl, OSL = 256, 8
    rng = np.random.default_rng(91)
    prefix = rng.integers(3, cfg.vocab_size, isl).tolist()
    rcfg = RuntimeConfig(lease_ttl=8.0)
    rt = await DistributedRuntime.create(config=rcfg)
    workers = []
    router = client = None

    async def make_worker():
        wrt = await DistributedRuntime.create(plane=rt.plane,
                                              owns_plane=False, config=rcfg)
        lease = await wrt.primary_lease()
        eng = await asyncio.to_thread(
            AsyncJaxEngine, cfg, EngineArgs(
                block_size=bs, num_blocks=4 * (isl // bs) + 64,
                max_num_seqs=4, max_num_batched_tokens=1024,
                max_model_len=isl + 8 * (OSL + 16) + bs,
                enable_prefix_caching=True))
        pub = KvEventPublisher(wrt.plane, worker_id=lease, kv_block_size=bs,
                               ledger=eng.kv_ledger)
        await pub.start_resync_responder()
        eng.event_cb = pub.publish_sync
        comp = wrt.namespace("dynamo").component("backend")
        pull_client = await comp.endpoint("kv_pull").client().start()
        handler = DecodeWorkerHandler(
            eng, pull_clients=[pull_client], metrics=wrt.metrics,
            restore_config=RestoreConfig(enabled=False),
            onboard_config=OnboardConfig(enabled=True), plane=rt.plane)
        handler.instance_id = lease
        h_gen = await comp.endpoint("generate").serve_endpoint(
            handler.generate, lease_id=lease)
        h_pull = await comp.endpoint("kv_pull").serve_endpoint(
            KvPullHandler(eng).generate, lease_id=lease)
        h_dig = await serve_kv_digest(wrt, eng.kv_ledger, lease,
                                      publisher=pub)
        w = type("W", (), {})()
        w.rt, w.engine, w.lease, w.handler = wrt, eng, lease, handler
        w.pub, w.pull_client = pub, pull_client
        w.handles = [h_gen, h_pull]
        w.dig = h_dig
        workers.append(w)
        return w

    def req(suffix, pin=None):
        return PreprocessedRequest(
            model="m", token_ids=prefix + list(suffix),
            stop_conditions=StopConditions(max_tokens=OSL, ignore_eos=True),
            sampling_options=SamplingOptions(temperature=0.0),
            backend_instance_id=pin)

    def stale_count(w):
        return int(w.handler._pull_outcomes._values.get(
            (("outcome", "stale_advert"),), 0))

    out: dict = {}
    os.environ["DYN_KV_AUDIT_INTERVAL"] = str(audit_interval)
    os.environ["DYN_KV_AUDIT_SETTLE"] = "0.05"
    try:
        a = await make_worker()
        b = await make_worker()
        client = await (rt.namespace("dynamo").component("backend")
                        .endpoint("generate").client().start())
        router = await KvRouter(rt.plane, bs, KvRouterConfig()).start()
        push = KvPushRouter(client, router)

        # A computes (and keeps) the shared prefix; the radix learns it
        async for _ in push.generate(req([9001], pin=a.lease), Context()):
            pass
        for _ in range(400):
            if router.restore_sources(prefix + [1]).get(a.lease, 0) \
                    >= isl // bs - 1:
                break
            await asyncio.sleep(0.02)
        # the suppression bug: A's prefix leaves the device pool with its
        # removal events swallowed — ledger truthful, mirror + radix stale
        a.engine.event_cb = None
        a.engine.pool.clear()
        out["advertised_after_evict"] = router.indexer.tree.worker_counts(
            ).get(a.lease, 0)
        client.set_busy_instances([a.lease])  # admissions land on B
        t0 = time.perf_counter()
        async for _ in push.generate(req([9100]), Context()):
            pass
        out["stale_adverts_pre_heal"] = stale_count(b)
        # the suspicion report wakes the router's own auditor: wait for
        # the phantom heal to retract A's adverts from the radix
        healed = False
        for _ in range(int((audit_interval + 8.0) / 0.05)):
            if router.auditor is not None \
                    and router.auditor.heals_total.get("phantom") \
                    and not router.indexer.tree.worker_counts().get(
                        a.lease, 0):
                healed = True
                break
            await asyncio.sleep(0.05)
        out["stale_heal_s"] = round(time.perf_counter() - t0, 3)
        out["stale_heal_cause"] = ("phantom" if healed else "none")
        # post-heal: the radix no longer lies, so fresh admissions plan
        # no pulls at A — the stale-advert rate returns to zero
        for i in range(3):
            async for _ in push.generate(req([9200 + i]), Context()):
                pass
        out["stale_adverts_post_heal"] = stale_count(b)
        out["stale_suspicion_seen"] = bool(
            router.auditor is not None
            and router.auditor.stale_adverts.get(a.lease, 0) >= 1)
        return out
    finally:
        os.environ.pop("DYN_KV_AUDIT_INTERVAL", None)
        os.environ.pop("DYN_KV_AUDIT_SETTLE", None)
        for w in workers:
            for h in w.handles:
                await h.stop(graceful=False)
            await w.dig.stop()
            await w.pull_client.stop()
            await w.pub.stop()
            await w.engine.close()
            await w.rt.shutdown()
        if router is not None:
            await router.stop()
        if client is not None:
            await client.stop()
        await rt.shutdown()


async def autoscale_bench(duration_s: float = 40.0,
                          chaos_spec: str = "stream.send:drop=0.02",
                          chaos_seed: int = 1234) -> dict:
    """``bench.py --autoscale``: the closed loop, end to end, under churn
    (docs/autoscaling.md / ISSUE 6 acceptance).

    A REAL fleet: a control-plane hub, an in-process frontend, and mocker
    workers spawned as operator subprocesses (plannerRole: decode,
    readiness-gated). The autoscale controller fuses frontend /metrics
    scrapes with worker ForwardPassMetrics, runs the predictor + planner,
    and actuates through the VirtualConnector SCALE_KEY the operator
    follows — while a diurnal sine of QoS-mixed traffic (interactive /
    standard / batch headers) runs one full cycle with seeded chaos
    dropping 2% of worker token frames.

    Asserts the Monday-morning contract: the loop scales up AND back down
    autonomously, interactive TTFT p95 holds its SLO through the scale
    events, batch traffic all completes (backlog drains), and usage-exact
    token accounting shows ZERO loss across worker churn (drain +
    migration absorb scale-downs and chaos)."""
    import sys
    import tempfile

    import aiohttp
    import yaml

    from benchmarks.client import Mix, qos_headers, stream_request
    from dynamo_tpu.autoscale import (
        AutoscaleController, AutoscaleRunner, ObservationFuser, SloConfig,
        make_planner, plane_readiness,
    )
    from dynamo_tpu.autoscale.slo import ClassSlo
    from dynamo_tpu.deploy.operator import ProcessOperator
    from dynamo_tpu.frontend.http import HttpService
    from dynamo_tpu.llm.discovery import ModelManager, ModelWatcher
    from dynamo_tpu.planner.perf_interpolation import PerfInterpolator
    from dynamo_tpu.planner.prometheus import PrometheusMetricsSource
    from dynamo_tpu.planner.virtual_connector import VirtualConnector
    from dynamo_tpu.router.publisher import MetricsAggregator
    from dynamo_tpu.runtime import DistributedRuntime
    from dynamo_tpu.runtime.control_plane import ControlPlaneServer

    MODEL, OSL, ISL_WORDS = "autoscale-bench", 24, 48
    PERIOD = 36.0
    BASE_RPS, AMP_RPS = 2.2, 1.8
    INT_TTFT_SLO_MS = 1500.0  # 2-core CPU host: generous but honest

    # one mocker worker ≈ 2 req/s at OSL 24 (speedup 0.05 → ~40ms decode
    # steps, 2 seq slots); the sweeps tell the planner exactly that, so
    # the sine's 0.4→4.0 req/s swing demands 1→2(3)→1 replicas
    prefill_perf = PerfInterpolator([(1.0, 200.0), (2.0, 700.0),
                                     (4.0, 2500.0)])
    decode_perf = PerfInterpolator([(24.0, 10.0), (48.0, 40.0),
                                    (96.0, 300.0)])
    slo = SloConfig(
        class_slos={
            "interactive": ClassSlo(ttft_p95_ms=INT_TTFT_SLO_MS, itl_ms=40.0),
            "standard": ClassSlo(ttft_p95_ms=6000.0, itl_ms=80.0),
            "batch": ClassSlo(),
        },
        min_replicas=1, max_replicas=3,
        cooldown_up_s=2.0, cooldown_down_s=8.0,
        adjustment_interval_s=1.0, predictor="arima",
        backlog_per_replica=3.0)

    server = ControlPlaneServer(port=0)
    addr = await server.start()
    old_plane = os.environ.get("DYN_CONTROL_PLANE")
    os.environ["DYN_CONTROL_PLANE"] = addr

    tmp = tempfile.mkdtemp(prefix="autoscale-bench-")
    spec_path = os.path.join(tmp, "graph.yaml")
    # real worker capacity (~6 req/s) sits WELL above what the planner's
    # sweeps claim a replica holds (~2 req/s): the controller scales
    # proactively on predicted demand with headroom, the way a production
    # SLO loop is provisioned — and completion rate then tracks the sine
    # honestly on both slopes (a saturated fleet's completion rate reads
    # as its own capacity, which would pin the predictor at the peak)
    worker_cmd = [
        sys.executable, "-m", "dynamo_tpu.mocker.main",
        "--model", MODEL, "--component", "mocker",
        "--block-size", "4", "--num-gpu-blocks", "4096",
        "--max-num-seqs", "4", "--speedup-ratio", "0.1",
        "--migration-limit", "50",
    ]
    with open(spec_path, "w") as f:
        yaml.safe_dump({
            "apiVersion": "dynamo.tpu/v1alpha1",
            "kind": "DynamoGraphDeployment",
            "metadata": {"name": "autoscale-bench"},
            "spec": {"services": {"decode": {
                "replicas": 1, "plannerRole": "decode",
                "command": worker_cmd,
                "env": {
                    "DYN_CONTROL_PLANE": addr,
                    "PYTHONPATH": os.pathsep.join(sys.path),
                    "JAX_PLATFORMS": "cpu",
                    # chaos lives in the WORKERS: token-frame drops are
                    # where scale-down churn could lose tokens
                    "DYN_CHAOS": chaos_spec,
                    "DYN_CHAOS_SEED": str(chaos_seed),
                    "DYN_DRAIN_TIMEOUT": "8",
                    "DYN_LOG": "warning",
                }}}},
        }, f)

    rt = await DistributedRuntime.create()
    manager = ModelManager()
    watcher = service = operator = aggregator = runner = None
    results: list = []
    by_class: dict = {}
    replica_timeline: list[tuple[float, int]] = []
    try:
        watcher = await ModelWatcher(rt, manager, router_mode="kv").start()
        service = HttpService(manager, port=0, runtime=rt)
        await service.start()
        operator = await ProcessOperator(
            spec_path, plane=rt.plane, tick_s=0.25, drain_timeout=10.0
        ).start()

        aggregator = await MetricsAggregator(rt.plane,
                                             stale_after_s=3.0).start()
        frontend_url = f"http://127.0.0.1:{service.port}"
        fuser = ObservationFuser(
            PrometheusMetricsSource(frontend_url), aggregator)
        # aggregated fleet: one decode-role service serves prefill+decode,
        # so the prefill dimension is pinned — otherwise its (serviceless)
        # replica math flaps and eats the shared cooldown windows
        planner = make_planner(slo, prefill_perf, decode_perf,
                               min_prefill_replicas=1,
                               max_prefill_replicas=1)

        async def readiness():
            return await plane_readiness(rt.plane, "dynamo")

        controller = AutoscaleController(
            slo, planner, fuser, VirtualConnector(rt.plane),
            readiness=readiness, metrics=rt.metrics, plane=rt.plane)
        runner = await AutoscaleRunner(controller).start()

        for _ in range(300):  # first worker registered + model discovered
            if manager.list_models():
                break
            await asyncio.sleep(0.1)
        else:
            raise RuntimeError("mocker fleet never appeared in discovery")

        mix = Mix("interactive=0.5,standard=0.2,batch=0.3")
        rng = np.random.default_rng(7)
        import random as _random

        prompt_rng = _random.Random(7)
        from benchmarks.client import make_prompt

        inflight: set = set()
        t0 = time.monotonic()
        # after the cycle, overnight-trough traffic trickles on while the
        # loop steps the fleet back down (3→2→1 takes one cooldown window
        # per step) — an abrupt stop would leave the predictors
        # extrapolating from the final drain burst instead of the trough
        tail_budget = 3 * slo.cooldown_down_s + 12.0
        async with aiohttp.ClientSession() as session:
            while (now := time.monotonic() - t0) < duration_s + tail_budget:
                if now < duration_s:
                    # diurnal cycle starting at the trough: ramp → peak at
                    # PERIOD/2 → back down (sin phase-shifted by -π/2)
                    rate = max(0.05, BASE_RPS + AMP_RPS * math.sin(
                        2 * math.pi * now / PERIOD - math.pi / 2))
                else:
                    rate = 0.4  # overnight trickle
                    if (controller.applied.decode_replicas
                            == slo.min_replicas
                            and operator._status()["services"]["decode"]
                            ["ready"] == slo.min_replicas):
                        break  # fleet settled at the floor
                cls = mix.pick(prompt_rng)
                task = asyncio.get_running_loop().create_task(
                    stream_request(
                        session, frontend_url, MODEL,
                        make_prompt(prompt_rng, ISL_WORDS), OSL,
                        headers=qos_headers(None, cls)))
                inflight.add(task)

                def _done(t, cls=cls):
                    inflight.discard(t)
                    results.append(t.result())
                    by_class.setdefault(cls, []).append(t.result())

                task.add_done_callback(_done)
                replica_timeline.append(
                    (round(now, 1), controller.applied.decode_replicas))
                await asyncio.sleep(float(rng.exponential(1.0 / rate)))
            if inflight:
                await asyncio.gather(*inflight, return_exceptions=True)
        final_fused = await fuser()
        final_status = operator._status()
    finally:
        if runner is not None:
            await runner.stop()
        if aggregator is not None:
            await aggregator.stop()
        if operator is not None:
            await operator.stop()  # drains the fleet
        if service is not None:
            await service.stop()
        if watcher is not None:
            await watcher.stop()
        await rt.shutdown()
        await server.stop()
        if old_plane is None:
            os.environ.pop("DYN_CONTROL_PLANE", None)
        else:
            os.environ["DYN_CONTROL_PLANE"] = old_plane

    def p95(vals):  # None default: autoscale summary omits empty arms
        return _p95(vals, default=None)

    ok = [r for r in results if r.ok]
    lost_tokens = sum(OSL - r.completion_tokens for r in ok)
    int_res = by_class.get("interactive", [])
    bat_res = by_class.get("batch", [])
    int_p95 = p95([r.ttft_s for r in int_res if r.ttft_s is not None])
    peak_replicas = max((n for _t, n in replica_timeline), default=1)
    svc = final_status["services"]["decode"]
    out = {
        "workload": (f"sine {BASE_RPS}±{AMP_RPS} req/s period {PERIOD}s "
                     f"x {duration_s}s, OSL {OSL}, mix int/std/batch "
                     f".5/.2/.3, chaos {chaos_spec}"),
        "requests": len(results), "ok": len(ok),
        "failed": len(results) - len(ok),
        "lost_tokens": lost_tokens,
        "int_ttft_p95_ms": (round(int_p95 * 1000, 1)
                            if int_p95 is not None else None),
        "int_ttft_slo_ms": INT_TTFT_SLO_MS,
        "int_requests": len(int_res),
        "batch_ok": sum(1 for r in bat_res if r.ok),
        "batch_requests": len(bat_res),
        "scale_ups": controller.scale_ups,
        "scale_downs": controller.scale_downs,
        "peak_replicas": peak_replicas,
        "final_replicas_ready": svc["ready"],
        "final_queue_depth": final_fused.queue_depth,
        "deferred_for_readiness": controller.deferred_for_readiness,
        "held_for_cooldown": controller.held_for_cooldown,
        "drains_completed": final_status["drainsCompleted"],
        "drains_killed": final_status["drainsKilled"],
        "drain_seconds_total": final_status["drainSecondsTotal"],
    }
    out["autoscale_ok"] = bool(
        out["failed"] == 0
        and lost_tokens == 0
        and out["scale_ups"] >= 1 and out["scale_downs"] >= 1
        and peak_replicas >= 2
        and out["final_replicas_ready"] == slo.min_replicas
        and out["batch_ok"] == out["batch_requests"]
        and out["final_queue_depth"] == 0
        and int_p95 is not None and int_p95 * 1000 <= INT_TTFT_SLO_MS)
    return out


def _device_init_responsive(timeout_s: float = 240.0) -> bool:
    """Probe jax backend init in a SUBPROCESS: a broken TPU tunnel makes
    jax.devices() hang forever (observed: axon UNAVAILABLE wedged for
    hours), which would leave the driver with no metric at all. A hung
    probe -> fall back to the CPU bench in THIS process."""
    import subprocess
    import sys

    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s, capture_output=True)
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def _init_backend() -> tuple[str, bool]:
    """Pick the jax platform WITHOUT being able to kill the bench.

    Failure modes seen in production rounds: (r1) a wedged TPU tunnel makes
    backend init hang forever — caught by the subprocess probe; (r2) backend
    init *errors* in the main process even when JAX_PLATFORMS was set, which
    crashed before any metric line — caught by the try/except → CPU retry."""
    import traceback

    import jax

    from dynamo_tpu.runtime.config import apply_platform_env

    apply_platform_env()  # sitecustomize pins the TPU; honor JAX_PLATFORMS
    # the probe costs one duplicate backend init (~30s healthy); skip it
    # with DYN_BENCH_SKIP_PROBE=1 on hosts known good
    if (not os.environ.get("DYN_BENCH_SKIP_PROBE")
            and os.environ.get("JAX_PLATFORMS", "").lower() != "cpu"
            and not _device_init_responsive()):
        print("device init unresponsive/broken; falling back to CPU bench",
              flush=True)
        jax.config.update("jax_platforms", "cpu")
    try:
        platform = jax.devices()[0].platform
    except Exception:  # noqa: BLE001
        traceback.print_exc()
        print("in-process backend init failed; falling back to CPU bench",
              flush=True)
        jax.config.update("jax_platforms", "cpu")
        platform = jax.devices()[0].platform
    return platform, platform == "tpu"


def main():
    """Parent orchestrator: try the full bench in a CHILD process under a
    hard deadline; if the child hangs or dies without a metric, rerun it
    pinned to CPU. The r3→r4 lesson: the axon tunnel can pass the init
    probe and then wedge mid-compile (observed 2026-07-30: jax.devices()
    answered at 22:39, wedged from 22:40 on), which left the driver with
    rc=124 and NO metric line. A deadline around the whole attempt makes
    that outcome impossible: the driver always gets one JSON line.

    DYN_BENCH_TPU_DEADLINE (default 2700 s) bounds the TPU attempt —
    generous because first compiles of the 1B multi-step program over the
    tunnel are minutes each."""
    import subprocess
    import sys

    if "--observe" in sys.argv:
        # observability smoke: no accelerator, no child orchestration —
        # prints one JSON line and exits nonzero on a missing span/series
        try:
            out = asyncio.run(observe_smoke())
        except Exception as e:  # noqa: BLE001 — smoke must report, not die
            import traceback

            traceback.print_exc()
            print(json.dumps({"observe": "failed",
                              "error": repr(e)[:300]}), flush=True)
            raise SystemExit(1)
        print(json.dumps(out), flush=True)
        return

    if "--mem-pressure" in sys.argv:
        # memory-pressure smoke: oversubscribed pool, swap vs recompute
        # preemption on the same seeded workload — prints one JSON line;
        # exits nonzero when swap stops beating recompute (CPU bar: >= 1.0x
        # and strictly fewer recomputed prefill tokens; hardware target 1.2x)
        try:
            out = asyncio.run(mem_pressure_bench(False))
        except Exception as e:  # noqa: BLE001 — smoke must report, not die
            import traceback

            traceback.print_exc()
            print(json.dumps({"mem_pressure": "failed",
                              "error": repr(e)[:300]}), flush=True)
            raise SystemExit(1)
        print(json.dumps(out), flush=True)
        ok = (out["swap_vs_recompute"] >= 1.0
              and out["swap_recomputed_tokens"]
              < out["recompute_recomputed_tokens"]
              and out["swap_out_blocks"] > 0)
        raise SystemExit(0 if ok else 1)

    if "--qos" in sys.argv:
        # multi-tenant QoS smoke: two tenants at 2x oversubscription —
        # prints one JSON line; exits nonzero when the isolation contract
        # breaks (interactive TTFT p95 > 1.2x unloaded, aggregate tok/s
        # < 0.9x FIFO, batch starved, or a non-batch class was preempted)
        try:
            out = asyncio.run(qos_bench(False))
        except Exception as e:  # noqa: BLE001 — smoke must report, not die
            import traceback

            traceback.print_exc()
            print(json.dumps({"qos": "failed", "error": repr(e)[:300]}),
                  flush=True)
            raise SystemExit(1)
        print(json.dumps(out), flush=True)
        ok = (out["qos_ttft_vs_unloaded"] <= 1.2
              and out["qos_vs_fifo_tok_s"] >= 0.9
              and out["batch_completed"] == out["batch_expected"]
              and set(out["qos_preempts_by_class"]) <= {"batch"})
        raise SystemExit(0 if ok else 1)

    if "--ragged" in sys.argv:
        # per-mode A/B on the packed ragged launch (the only step path) —
        # prints one JSON line; exits nonzero when a mode loses its
        # contract: spec/multi streams not bit-identical to base, MLA not
        # deterministic, a signature kind outside the token-bucket
        # families, census not ≥4× under the bucketed lattice, or a
        # per-mode tok/s regression past the CPU-noise floor
        try:
            out = asyncio.run(ragged_bench(False, modes=True))
        except Exception as e:  # noqa: BLE001 — smoke must report, not die
            import traceback

            traceback.print_exc()
            print(json.dumps({"ragged": "failed", "error": repr(e)[:300]}),
                  flush=True)
            raise SystemExit(1)
        print(json.dumps(out), flush=True)
        raise SystemExit(0 if out["ragged_ok"] else 1)

    if "--sessions" in sys.argv:
        # session-native serving A/B (ISSUE 20): delta turns + affinity +
        # G4 park/restore vs sessionless full resends on a churn-evicted
        # 2-worker fleet — prints one JSON line; exits nonzero when a gate
        # fails (streams not bit-identical across arms, turn-2+ TTFT p95
        # ratio > 0.5, no prefill-compute win, QoS collateral > 1.2x, no
        # blocks actually parked/restored, or the reaper failed to collect
        # an abandoned session)
        try:
            out = asyncio.run(sessions_bench(False))
        except Exception as e:  # noqa: BLE001 — smoke must report, not die
            import traceback

            traceback.print_exc()
            print(json.dumps({"sessions": "failed", "error": repr(e)[:300]}),
                  flush=True)
            raise SystemExit(1)
        print(json.dumps(out), flush=True)
        raise SystemExit(0 if out["sessions_ok"] else 1)

    if "--quant" in sys.argv:
        # quantized-serving A/B (ISSUE 19): interleaved kernel arms with
        # roofline + bandwidth-floor fields, engine arms with the int8-KV
        # vs bf16 / vs DYN_RAGGED_ORACLE stream-identity + signature-census
        # gates, and the plan_70b quantized-placement solver gate — prints
        # one JSON line; exits nonzero when any gate fails
        try:
            out = asyncio.run(quant_bench(False))
        except Exception as e:  # noqa: BLE001 — smoke must report, not die
            import traceback

            traceback.print_exc()
            print(json.dumps({"quant": "failed", "error": repr(e)[:300]}),
                  flush=True)
            raise SystemExit(1)
        print(json.dumps(out), flush=True)
        raise SystemExit(0 if out["quant_ok"] else 1)

    if "--tools" in sys.argv:
        # structured tool-loop smoke: constrained-vs-free multi-turn
        # sessions + peer onboarding — prints one JSON line; exits nonzero
        # when schema validity drops below 100%, constrained decode loses
        # ≥10% to free on the device path, turn 2+ stops re-hitting its
        # prefix, or the peer leg pulled nothing (docs/structured.md)
        try:
            out = asyncio.run(tools_bench(False))
        except Exception as e:  # noqa: BLE001 — smoke must report, not die
            import traceback

            traceback.print_exc()
            print(json.dumps({"tools": "failed", "error": repr(e)[:300]}),
                  flush=True)
            raise SystemExit(1)
        print(json.dumps(out), flush=True)
        raise SystemExit(0 if out["tools_ok"] else 1)

    if "--migration" in sys.argv:
        # KV-restore migration under seeded worker kills: restore vs
        # recompute arms interleaved per rep — prints one JSON line; exits
        # nonzero when streams lose/duplicate tokens, no kill landed,
        # restore pulled nothing, or the post-kill TTFT-to-resume ratio
        # breaches the 0.7 gate (docs/robustness.md)
        try:
            out = asyncio.run(migration_bench(False))
        except Exception as e:  # noqa: BLE001 — smoke must report, not die
            import traceback

            traceback.print_exc()
            print(json.dumps({"migration": "failed",
                              "error": repr(e)[:300]}), flush=True)
            raise SystemExit(1)
        print(json.dumps(out), flush=True)
        raise SystemExit(0 if out["migration_ok"] else 1)

    if "--onboard" in sys.argv:
        # routine cross-worker prefix onboarding A/B: peer-pull vs
        # recompute on a shared-prefix fleet + G4 cold-start warmup —
        # prints one JSON line; exits nonzero when streams diverge,
        # pull stops beating recompute on TTFT p95 (≤0.7) or prefill
        # chip-seconds, or the G4 warm loses to cold recompute
        # (docs/performance.md "prefix onboarding")
        try:
            out = asyncio.run(onboard_bench(False))
        except Exception as e:  # noqa: BLE001 — smoke must report, not die
            import traceback

            traceback.print_exc()
            print(json.dumps({"onboard": "failed",
                              "error": repr(e)[:300]}), flush=True)
            raise SystemExit(1)
        print(json.dumps(out), flush=True)
        raise SystemExit(0 if out["onboard_ok"] else 1)

    if "--disagg" in sys.argv:
        # network-aware disagg A/Bs: topology-costed placement vs blind +
        # layer-interleaved vs whole-bundle tail — prints one JSON line;
        # exits nonzero when placement stops beating blind by the margin
        # or the layer split regresses the transfer-exposed gap
        # (docs/disagg.md)
        try:
            out = asyncio.run(disagg_bench())
        except Exception as e:  # noqa: BLE001 — smoke must report, not die
            import traceback

            traceback.print_exc()
            print(json.dumps({"disagg": "failed", "error": repr(e)[:300]}),
                  flush=True)
            raise SystemExit(1)
        print(json.dumps(out), flush=True)
        raise SystemExit(0 if out["disagg_ok"] else 1)

    if "--flight" in sys.argv:
        # flight recorder gates: recorder-on/off overhead ≤3% with
        # bit-identical streams, plus the seeded preempt storm and forced
        # steady-state compile both tagged (docs/observability.md)
        try:
            out = asyncio.run(flight_bench(False))
        except Exception as e:  # noqa: BLE001 — smoke must report, not die
            import traceback

            traceback.print_exc()
            print(json.dumps({"flight": "failed", "error": repr(e)[:300]}),
                  flush=True)
            raise SystemExit(1)
        print(json.dumps(out), flush=True)
        raise SystemExit(0 if out["flight_ok"] else 1)

    if "--kvaudit" in sys.argv:
        # KV index audit gates: seeded kv-event drop chaos → divergence
        # detected within one audit interval, classified phantom/missing
        # against ground truth, healed via resync; stale-advert pulls
        # tagged + driven to zero; clean A/B bit-identical with ≤1%
        # audit overhead (docs/observability.md "KV audit")
        try:
            out = asyncio.run(kvaudit_bench(False))
        except Exception as e:  # noqa: BLE001 — smoke must report, not die
            import traceback

            traceback.print_exc()
            print(json.dumps({"kvaudit": "failed", "error": repr(e)[:300]}),
                  flush=True)
            raise SystemExit(1)
        print(json.dumps(out), flush=True)
        raise SystemExit(0 if out["kvaudit_ok"] else 1)

    if "--attribution" in sys.argv:
        # latency-attribution gates: per-request bucket sums + residual
        # equal measured e2e, streams bit-identical with attribution on
        # vs off, and the seeded storm produces one budget-capped
        # anomaly-triggered profile capture (docs/observability.md
        # "Attribution")
        try:
            out = asyncio.run(attribution_bench(False))
        except Exception as e:  # noqa: BLE001 — smoke must report, not die
            import traceback

            traceback.print_exc()
            print(json.dumps({"attribution": "failed",
                              "error": repr(e)[:300]}), flush=True)
            raise SystemExit(1)
        print(json.dumps(out), flush=True)
        raise SystemExit(0 if out["attribution_ok"] else 1)

    if "--autoscale" in sys.argv:
        # closed-loop SLA autoscaling proof: a real operator-managed
        # mocker fleet through a full diurnal cycle with chaos on — prints
        # one JSON line; exits nonzero when the loop fails to scale both
        # ways, loses tokens across churn, strands backlog, or breaches
        # the interactive TTFT SLO (docs/autoscaling.md)
        try:
            out = asyncio.run(autoscale_bench())
        except Exception as e:  # noqa: BLE001 — smoke must report, not die
            import traceback

            traceback.print_exc()
            print(json.dumps({"autoscale": "failed",
                              "error": repr(e)[:300]}), flush=True)
            raise SystemExit(1)
        print(json.dumps(out), flush=True)
        raise SystemExit(0 if out["autoscale_ok"] else 1)

    if "--flagship" in sys.argv:
        # flagship fleet drive: the plan_70b placement as a live mocker
        # fleet (2xTP8 prefill + 6xTP8 decode) through one diurnal
        # QoS-mixed cycle with disagg, autoscaling, KV audit and seeded
        # chaos kills all on — prints one JSON line; exits nonzero when
        # completion, token accounting, scorecard checks, scale events,
        # or audit convergence fail (docs/observability.md "Fleet
        # scorecard")
        from benchmarks.flagship_drive import drive as flagship_drive
        try:
            out = asyncio.run(flagship_drive())
            out.pop("scorecard", None)  # full doc is too big for one line
        except Exception as e:  # noqa: BLE001 — smoke must report, not die
            import traceback

            traceback.print_exc()
            print(json.dumps({"flagship": "failed",
                              "error": repr(e)[:300]}), flush=True)
            raise SystemExit(1)
        print(json.dumps(out), flush=True)
        raise SystemExit(0 if out["flagship_ok"] else 1)

    if "--chaos" in sys.argv:
        # chaos smoke: no accelerator, no child orchestration — prints one
        # JSON line; exits nonzero when completion rate or p95 degradation
        # breaks the bound (the recovery paths regressed)
        idx = sys.argv.index("--chaos")
        spec = (sys.argv[idx + 1] if idx + 1 < len(sys.argv)
                and not sys.argv[idx + 1].startswith("-")
                else "stream.send:drop=0.01")
        try:
            out = asyncio.run(chaos_smoke(spec))
        except Exception as e:  # noqa: BLE001 — smoke must report, not die
            import traceback

            traceback.print_exc()
            print(json.dumps({"chaos": "failed", "error": repr(e)[:300]}),
                  flush=True)
            raise SystemExit(1)
        print(json.dumps(out), flush=True)
        raise SystemExit(0 if out["chaos_ok"] else 1)

    if os.environ.get("DYN_BENCH_CHILD"):
        _child_main()
        return

    deadline = int(os.environ.get("DYN_BENCH_TPU_DEADLINE", "2700"))
    # r4 verdict: three rounds of CPU-fallback records because the axon
    # tunnel happened to be down at the driver's bench instant. Spend up
    # to DYN_BENCH_WAIT seconds (default 20 min) waiting for the device
    # to answer before burning the one TPU attempt — a flapping tunnel
    # should cost latency, not the round's only hardware number.
    wait_budget = int(os.environ.get("DYN_BENCH_WAIT", "1200"))
    if os.environ.get("JAX_PLATFORMS", "").lower() != "cpu":
        t0 = time.time()
        while not _device_init_responsive(timeout_s=150):
            waited = time.time() - t0
            if waited + 120 > wait_budget:
                print(f"device still unresponsive after {waited:.0f}s wait; "
                      f"proceeding (child will fall back)", file=sys.stderr,
                      flush=True)
                break
            print(f"device unresponsive; retrying ({waited:.0f}s/"
                  f"{wait_budget}s waited)", file=sys.stderr, flush=True)
            time.sleep(120)
        else:
            # device answered — the child's own probe is now redundant
            os.environ["DYN_BENCH_SKIP_PROBE"] = "1"
    attempts = [({}, deadline)]
    if os.environ.get("JAX_PLATFORMS", "").lower() != "cpu":
        attempts.append(({"JAX_PLATFORMS": "cpu"}, 1800))
    for extra_env, tmo in attempts:
        env = {**os.environ, "DYN_BENCH_CHILD": "1", **extra_env}
        try:
            r = subprocess.run([sys.executable, os.path.abspath(__file__)],
                               env=env, timeout=tmo, capture_output=True,
                               text=True)
        except subprocess.TimeoutExpired:
            print(f"bench child timed out after {tmo}s "
                  f"(env {extra_env}); falling back", file=sys.stderr,
                  flush=True)
            continue
        lines = r.stdout.splitlines()
        idx = next((i for i in range(len(lines) - 1, -1, -1)
                    if lines[i].startswith("{")), None)
        if idx is not None:
            # replay the child's non-metric output for the log, then the
            # ONE metric line last (driver parses the tail)
            for i, ln in enumerate(lines):
                if i != idx:
                    print(ln, flush=True)
            sys.stderr.write(r.stderr[-4000:])
            print(lines[idx], flush=True)
            return
        sys.stderr.write(r.stderr[-4000:])
    print(json.dumps({"metric": "bench_failed", "value": 0.0,
                      "unit": "tok/s", "vs_baseline": 0.0,
                      "extra": {"error": "all bench children hung/died"}}),
          flush=True)


class _PhaseSkipped(Exception):
    """Raised to skip the e2e phase under DYN_BENCH_PHASES."""


def _child_main():
    """Always prints exactly ONE JSON metric line, whatever breaks.

    Result quality degrades in stages instead of vanishing: full e2e metric →
    kernel-only metric (e2e died) → bench_failed metric (init/kernel died).
    The r2 driver run recorded rc=1/parsed=null; that is now impossible short
    of the interpreter itself dying."""
    import traceback

    out = {"metric": "bench_failed", "value": 0.0, "unit": "tok/s",
           "vs_baseline": 0.0, "extra": {}}
    rc = 1
    # DYN_BENCH_PHASES: comma list of {kernel,spec,e2e} to run (default all)
    # — perf iteration on one phase shouldn't pay the full suite each time
    phases = {p.strip() for p in
              os.environ.get("DYN_BENCH_PHASES",
                             "kernel,spec,e2e,chaos,mem,qos,autoscale,"
                             "ragged,raggedmodes,disagg,migration,onboard,"
                             "flight,tools,attribution,kvaudit,flagship,"
                             "frontdoor,quant,sessions"
                             ).split(",")
              if p.strip()}
    unknown = phases - {"kernel", "spec", "e2e", "chaos", "mem", "qos",
                        "autoscale", "ragged", "raggedmodes", "disagg",
                        "migration", "onboard", "flight", "tools",
                        "attribution", "kvaudit", "flagship", "frontdoor",
                        "quant", "sessions"}
    if unknown:
        # a typo'd phase must not masquerade as a 100% perf regression
        raise SystemExit(f"DYN_BENCH_PHASES: unknown phase(s) "
                         f"{sorted(unknown)} (valid: kernel, spec, e2e, "
                         f"chaos, mem, qos, autoscale, ragged, raggedmodes, "
                         f"disagg, migration, onboard, flight, tools, "
                         f"attribution, kvaudit, flagship, frontdoor, "
                         f"quant, sessions)")
    try:
        platform, on_tpu = _init_backend()
        model = "llama3-1b" if on_tpu else "tiny-cpu"
        if "kernel" in phases:
            kern = kernel_bench(on_tpu)
            # quantization variants, each an optional extra datum:
            # int8 halves weight traffic (bandwidth-bound ceiling 2x),
            # int8 KV halves the other half, int4-g32+kv8 is the 70B
            # plan's BEST config (plan_70b: 1599 tok/s/chip roofline) —
            # chip-only, a CPU fallback run shouldn't pay a 4th compile
            variants = [("kernel_int8_error", "int8", False, True),
                        ("kernel_kv8_error", "int8", True, True),
                        ("kernel_int4_error", "int4-g32", True, on_tpu)]
            for err_key, quant, kv8, run in variants:
                if not run:
                    continue
                try:
                    kern.update(kernel_bench(on_tpu, quantization=quant,
                                             kv_int8=kv8))
                except Exception as e:  # noqa: BLE001 — optional datum
                    kern[err_key] = repr(e)[:200]
        else:
            kern = {"kernel_tok_s": 0.0, "kernel_skipped": True}
        if "spec" in phases:
            try:
                # before the out={} snapshot below: spec numbers must survive
                # an e2e failure (extra holds a copy of kern, not a reference)
                kern.update(asyncio.run(_spec_bench(on_tpu)))
            except Exception as e:  # noqa: BLE001 — optional extra datum
                kern["spec_error"] = repr(e)[:200]
        if "chaos" in phases:
            # chaos smoke (mocker-based, seconds): completion rate + p95
            # degradation under 1% drop injection, in the gains block every
            # round so a recovery-path regression is visible immediately
            try:
                kern["chaos_smoke"] = asyncio.run(chaos_smoke())
            except Exception as e:  # noqa: BLE001 — optional extra datum
                kern["chaos_error"] = repr(e)[:200]
        if "mem" in phases:
            # memory-pressure phase: swap-based vs recompute preemption on
            # an oversubscribed pool — recomputed-prefill tokens and the
            # tok/s ratio on record every round (ISSUE 4 acceptance)
            try:
                kern["mem_pressure"] = asyncio.run(mem_pressure_bench(on_tpu))
            except Exception as e:  # noqa: BLE001 — optional extra datum
                kern["mem_error"] = repr(e)[:200]
        if "qos" in phases:
            # multi-tenant isolation phase: interactive TTFT under 2x
            # oversubscription vs unloaded + aggregate tok/s vs FIFO —
            # the differentiated-service record (ISSUE 5 acceptance)
            try:
                kern["qos"] = asyncio.run(qos_bench(on_tpu))
            except Exception as e:  # noqa: BLE001 — optional extra datum
                kern["qos_error"] = repr(e)[:200]
        if "ragged" in phases or "raggedmodes" in phases:
            # packed-launch phase on the mixed prefill+decode workload:
            # census-vs-lattice signature arithmetic, padded-token waste,
            # tok/s and TTFT on record every round (ISSUE 7 acceptance);
            # "raggedmodes" additionally runs the per-mode A/B arms —
            # spec-verify, multi-step fused decode, MLA — with the
            # stream-identity no-regression gate (ISSUE 17 acceptance)
            try:
                kern["ragged"] = asyncio.run(
                    ragged_bench(on_tpu, modes="raggedmodes" in phases))
            except Exception as e:  # noqa: BLE001 — optional extra datum
                kern["ragged_error"] = repr(e)[:200]
        if "disagg" in phases:
            # network-aware disagg phase: topology-costed placement vs
            # blind + layer-interleaved vs whole-bundle tail transfer —
            # the A/B margins on record every round (ISSUE 9 acceptance)
            try:
                kern["disagg"] = asyncio.run(disagg_bench())
            except Exception as e:  # noqa: BLE001 — optional extra datum
                kern["disagg_error"] = repr(e)[:200]
        if "autoscale" in phases:
            # closed-loop autoscaling phase: diurnal QoS-mixed cycle over
            # an operator-managed mocker fleet with chaos on — scale
            # events, SLO hold, and zero-loss token accounting on record
            # every round (ISSUE 6 acceptance)
            try:
                kern["autoscale"] = asyncio.run(autoscale_bench())
            except Exception as e:  # noqa: BLE001 — optional extra datum
                kern["autoscale_error"] = repr(e)[:200]
        if "migration" in phases:
            # KV-restore migration phase: seeded worker kills, restore vs
            # recompute resume latency + exact token accounting on record
            # every round (ISSUE 10 acceptance)
            try:
                kern["migration"] = asyncio.run(migration_bench(on_tpu))
            except Exception as e:  # noqa: BLE001 — optional extra datum
                kern["migration_error"] = repr(e)[:200]
        if "onboard" in phases:
            # routine prefix onboarding phase: shared-prefix peer-pull vs
            # recompute + G4 cold-start warmup — TTFT p95 ratio, prefill
            # chip-seconds, and exact stream identity on record every
            # round (ISSUE 11 acceptance)
            try:
                kern["onboard"] = asyncio.run(onboard_bench(on_tpu))
            except Exception as e:  # noqa: BLE001 — optional extra datum
                kern["onboard_error"] = repr(e)[:200]
        if "flight" in phases:
            # flight recorder phase: recorder-on/off overhead + stream
            # identity, seeded preempt storm + forced steady-state compile
            # tagging — the observability substrate's own regression gate
            # (ISSUE 12 acceptance)
            try:
                kern["flight"] = asyncio.run(flight_bench(on_tpu))
            except Exception as e:  # noqa: BLE001 — optional extra datum
                kern["flight_error"] = repr(e)[:200]
        if "tools" in phases:
            # structured tool-loop phase: constrained-vs-free tok/s,
            # schema-validity, per-turn prefix-hit provenance + the
            # 2-worker peer-onboard leg (ISSUE 13 acceptance)
            try:
                kern["tools"] = asyncio.run(tools_bench(on_tpu))
            except Exception as e:  # noqa: BLE001 — optional extra datum
                kern["tools_error"] = repr(e)[:200]
        if "attribution" in phases:
            # latency-attribution phase: residual falsifiability on the
            # seeded QoS drive, attribution-on/off stream identity, and
            # the budget-capped anomaly-triggered profile capture
            # (ISSUE 14 acceptance)
            try:
                kern["attribution"] = asyncio.run(attribution_bench(on_tpu))
            except Exception as e:  # noqa: BLE001 — optional extra datum
                kern["attribution_error"] = repr(e)[:200]
        if "kvaudit" in phases:
            # KV index audit phase: seeded kv-event drop chaos →
            # detection within one interval, ground-truth phantom/missing
            # classification, resync heal, stale-advert rate to zero, and
            # the ≤1% clean-overhead + stream-identity A/B (ISSUE 15
            # acceptance)
            try:
                kern["kvaudit"] = asyncio.run(kvaudit_bench(on_tpu))
            except Exception as e:  # noqa: BLE001 — optional extra datum
                kern["kvaudit_error"] = repr(e)[:200]
        if "flagship" in phases:
            # flagship fleet drive: the 70B placement live as a mocker
            # fleet through one diurnal cycle with everything on —
            # completion, zero-loss accounting, scorecard checks, scale
            # events, audit convergence and hub saturation headroom on
            # record every round (ISSUE 16 acceptance)
            try:
                from benchmarks.flagship_drive import drive as _flagship

                flag = asyncio.run(_flagship())
                flag.pop("scorecard", None)  # keep the metric line bounded
                kern["flagship"] = flag
            except Exception as e:  # noqa: BLE001 — optional extra datum
                kern["flagship_error"] = repr(e)[:200]
        if "quant" in phases:
            # quantized-serving phase: interleaved weight/KV-quant kernel
            # arms (roofline + bandwidth-floor), int8-KV stream identity
            # vs the bf16 arm and the DYN_RAGGED_ORACLE arm, signature
            # census, and the plan_70b quantized-placement solver gate —
            # the bandwidth-floor record every round (ISSUE 19 acceptance)
            try:
                kern["quant"] = asyncio.run(quant_bench(on_tpu))
            except Exception as e:  # noqa: BLE001 — optional extra datum
                kern["quant_error"] = repr(e)[:200]
        if "frontdoor" in phases:
            # front-door chaos phase: 3 frontend replicas on one KV-fed
            # routing view, one SIGKILLed mid-peak + hub primary killed
            # under live load — 100% completion with bounded client
            # retries, zero lost/dup tokens, cross-replica radix digest
            # agreement, zero leaked seqs/blocks, auditor + autoscale loop
            # surviving promotion (ISSUE 18 acceptance)
            try:
                from benchmarks.flagship_drive import frontdoor_drive

                kern["frontdoor"] = asyncio.run(frontdoor_drive(22.0))
            except Exception as e:  # noqa: BLE001 — optional extra datum
                kern["frontdoor_error"] = repr(e)[:200]
        if "sessions" in phases:
            # session-native serving phase: delta turns + router affinity
            # + idle-KV G4 park/restore vs sessionless full resends on a
            # churn-evicted 2-worker fleet — bit-identical streams,
            # turn-2+ TTFT p95 ratio ≤ 0.5, strict prefill-compute win,
            # QoS collateral ≤ 1.2x, reaper collecting abandonment
            # (ISSUE 20 acceptance)
            try:
                kern["sessions"] = asyncio.run(sessions_bench(on_tpu))
            except Exception as e:  # noqa: BLE001 — optional extra datum
                kern["sessions_error"] = repr(e)[:200]
        tok_s = kern["kernel_tok_s"]
        if "kernel" in phases:
            fallback_metric = (f"kernel_decode_tok_s_per_chip[{model},"
                               f"{platform},e2e-failed]")
            fallback_vs = round(tok_s / BASELINE_TOK_S, 3)
        else:
            # a skipped kernel must not read as a 0.0 tok/s regression
            fallback_metric = f"kernel_phase_skipped[{model},{platform}]"
            fallback_vs = 0.0
        out = {
            "metric": fallback_metric,
            "value": tok_s,
            "unit": "tok/s",
            "vs_baseline": fallback_vs,
            "extra": dict(kern),
        }
        rc = 0
        try:
            if "e2e" not in phases:
                raise _PhaseSkipped()
            e2e = asyncio.run(_e2e(on_tpu))
        except _PhaseSkipped:
            out["extra"]["e2e_skipped"] = True
        except Exception as e:  # noqa: BLE001 — keep the kernel metric
            traceback.print_exc()
            out["extra"]["e2e_error"] = repr(e)[:300]
        else:
            tok_s = e2e["e2e_tok_s"]
            extra = {**kern, **e2e}
            # the kernel→e2e gap, on the record every round: 1.0 means the
            # serving stack adds no overhead over the raw jitted loop
            if kern.get("kernel_tok_s"):
                extra["e2e_vs_kernel_ratio"] = round(
                    tok_s / kern["kernel_tok_s"], 4)
            out = {
                "metric": f"e2e_http_decode_tok_s_per_chip"
                          f"[{model},{e2e['workload']},{platform}]",
                "value": tok_s,
                "unit": "tok/s",
                "vs_baseline": round(tok_s / BASELINE_TOK_S, 3),
                "extra": extra,
            }
    except Exception as e:  # noqa: BLE001 — bench_failed line beats none
        traceback.print_exc()
        out["extra"]["error"] = repr(e)[:500]
    finally:
        print(json.dumps(out), flush=True)
        # a mid-flight e2e failure leaves service/engine/runtime threads
        # alive, which would keep the interpreter (and the driver's timeout)
        # hanging after the metric printed — hard-exit once the line is out
        os._exit(rc)


if __name__ == "__main__":
    main()
