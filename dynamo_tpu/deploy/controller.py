"""DynamoGraphDeployment controller: a real reconcile loop over the k8s API.

The in-cluster counterpart of the reference's Go operator
(ref: deploy/cloud/operator/internal/controller/dynamographdeployment_controller.go,
api/v1alpha1/dynamographdeployment_types.go:30). Same machinery, Python:

- **informer**: list + watch the CR and owned pods, maintain a local cache,
  coalesce changes into a work queue keyed by CR name (client-go reflector
  + workqueue pattern); 410-expired or dropped watches trigger a relist;
- **reconcile**: diff desired (spec.services[*].replicas) against owned
  pods (label-selected), create missing pods (ownerReferences set), delete
  excess newest-first — the same scale-down order the process operator
  uses, so planner-driven shrink kills the youngest worker;
- **status subresource**: observedGeneration + per-service desired/ready +
  a Ready condition, written via PUT …/status with resourceVersion
  conflict-retry (the UpdateStatus + RetryOnConflict idiom);
- CR deletion → owned pods deleted (no server-side GC in the fake server;
  against a real apiserver ownerReferences make this a no-op backstop).

Runs against any API endpoint KubeClient can reach: the in-repo
FakeKubeApiServer in tests (real HTTP, real watch streams), a genuine
apiserver via KubeClient.in_cluster() in production.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from dynamo_tpu.deploy.kube_api import (
    Conflict,
    KubeClient,
    NotFound,
    WatchExpired,
)

logger = logging.getLogger("dynamo.controller")

GROUP, VERSION = "dynamo.tpu", "v1alpha1"
PLURAL = "dynamographdeployments"
LABEL_GRAPH = "dynamo.tpu/graph"
LABEL_SERVICE = "dynamo.tpu/service"


def pod_name(graph: str, service: str, index: int) -> str:
    return f"{graph}-{service}-{index}"


class DynamoGraphController:
    def __init__(self, client: KubeClient, namespace: str = "default"):
        self.client = client
        self.namespace = namespace
        self.crs = client.resource(GROUP, VERSION, namespace, PLURAL)
        self.pods = client.resource("", "v1", namespace, "pods")
        self._cache: dict[str, dict] = {}
        self._queue: asyncio.Queue = asyncio.Queue()
        self._queued: set[str] = set()
        self._tasks: list[asyncio.Task] = []
        self._stopping = False
        self.reconciles = 0
        self.status_conflicts_retried = 0
        self.relists = 0

    # ------------------------------------------------------------ lifecycle
    async def start(self) -> "DynamoGraphController":
        rv = await self._relist()
        loop = asyncio.get_running_loop()
        self._tasks = [
            loop.create_task(self._watch_crs(rv)),
            loop.create_task(self._watch_pods()),
            loop.create_task(self._worker()),
        ]
        return self

    async def stop(self):
        self._stopping = True
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass

    # ------------------------------------------------------------- informer
    def _enqueue(self, name: str):
        if name not in self._queued:
            self._queued.add(name)
            self._queue.put_nowait(name)

    async def _relist(self) -> str:
        """Full list → rebuild cache, enqueue everything, return the list
        resourceVersion to resume watching from."""
        lst = await self.crs.list()
        self.relists += 1
        self._cache = {o["metadata"]["name"]: o for o in lst["items"]}
        for name in self._cache:
            self._enqueue(name)
        return lst["metadata"]["resourceVersion"]

    async def _watch_crs(self, rv: str):
        while not self._stopping:
            try:
                async for ev_type, obj in self.crs.watch(resource_version=rv):
                    name = obj["metadata"]["name"]
                    rv = obj["metadata"]["resourceVersion"]
                    if ev_type == "DELETED":
                        self._cache.pop(name, None)
                    else:
                        self._cache[name] = obj
                    self._enqueue(name)
                # server closed the stream: resume from last seen rv
            except WatchExpired:
                logger.info("CR watch expired; relisting")
                rv = await self._relist()
            except asyncio.CancelledError:
                return
            except Exception:
                logger.exception("CR watch failed; relisting after backoff")
                await asyncio.sleep(1.0)
                try:
                    rv = await self._relist()
                except Exception:
                    logger.exception("relist failed; retrying")

    async def _watch_pods(self):
        rv = "0"
        while not self._stopping:
            try:
                async for ev_type, obj in self.pods.watch(resource_version=rv):
                    rv = obj["metadata"]["resourceVersion"]
                    graph = obj["metadata"].get("labels", {}).get(LABEL_GRAPH)
                    if graph:
                        self._enqueue(graph)
            except WatchExpired:
                rv = "0"
            except asyncio.CancelledError:
                return
            except Exception:
                logger.exception("pod watch failed; retrying")
                await asyncio.sleep(1.0)
                rv = "0"

    async def _worker(self):
        while not self._stopping:
            name = await self._queue.get()
            self._queued.discard(name)
            try:
                await self.reconcile(name)
                self.reconciles += 1
            except asyncio.CancelledError:
                return
            except Exception:
                logger.exception("reconcile(%s) failed; requeueing", name)
                await asyncio.sleep(0.5)
                self._enqueue(name)

    # ------------------------------------------------------------ reconcile
    async def reconcile(self, name: str):
        cr = self._cache.get(name)
        owned = await self.pods.list(label_selector=f"{LABEL_GRAPH}={name}")
        by_service: dict[str, list[dict]] = {}
        for pod in owned["items"]:
            svc = pod["metadata"].get("labels", {}).get(LABEL_SERVICE, "")
            by_service.setdefault(svc, []).append(pod)

        if cr is None:
            # CR gone: delete every owned pod (GC backstop)
            for pods in by_service.values():
                for pod in pods:
                    await self._delete_pod(pod["metadata"]["name"])
            return

        services = (cr.get("spec") or {}).get("services") or {}
        status_services = {}
        all_ready = True
        for svc, spec in services.items():
            desired = int(spec.get("replicas", 1))

            def _index(pod):
                # numeric replica index, NOT lexicographic name order —
                # "-10" must sort after "-9" or scale-down kills the wrong pod
                try:
                    return int(pod["metadata"]["name"].rsplit("-", 1)[1])
                except (IndexError, ValueError):
                    return -1
            have = sorted(by_service.pop(svc, []), key=_index)
            # create missing replicas at the first free indices
            used = {p["metadata"]["name"] for p in have}
            idx = 0
            while len(have) < desired:
                pname = pod_name(name, svc, idx)
                idx += 1
                if pname in used:
                    continue
                pod = self._pod_for(cr, svc, spec, pname)
                try:
                    created = await self.pods.create(pod)
                    have.append(created)
                except Conflict:
                    pass  # another worker got there; next reconcile settles
            # delete excess, newest-first (planner scale-down contract)
            while len(have) > desired:
                victim = have.pop()
                await self._delete_pod(victim["metadata"]["name"])
            ready = sum(1 for p in have
                        if (p.get("status") or {}).get("phase") == "Running")
            status_services[svc] = {"desired": desired, "ready": ready}
            if ready < desired:
                all_ready = False
        # pods whose service vanished from the spec
        for pods in by_service.values():
            for pod in pods:
                await self._delete_pod(pod["metadata"]["name"])

        status = {
            "observedGeneration": cr["metadata"].get("generation", 1),
            "services": status_services,
            "conditions": [{
                "type": "Ready",
                "status": "True" if all_ready else "False",
            }],
        }
        await self._update_status(name, status)

    def _pod_for(self, cr: dict, svc: str, spec: dict, pname: str) -> dict:
        return {
            "metadata": {
                "name": pname,
                "labels": {LABEL_GRAPH: cr["metadata"]["name"],
                           LABEL_SERVICE: svc},
                "ownerReferences": [{
                    "apiVersion": f"{GROUP}/{VERSION}",
                    "kind": "DynamoGraphDeployment",
                    "name": cr["metadata"]["name"],
                    "uid": cr["metadata"].get("uid", ""),
                    "controller": True,
                }],
            },
            "spec": {"containers": [{
                "name": svc,
                "command": spec.get("command", []),
                "env": [{"name": k, "value": str(v)}
                        for k, v in (spec.get("env") or {}).items()],
            }]},
        }

    async def _delete_pod(self, pname: str):
        try:
            await self.pods.delete(pname)
        except NotFound:
            pass

    async def _update_status(self, name: str, status: dict):
        """UpdateStatus with RetryOnConflict: PUT …/status carries the read
        resourceVersion; a 409 means someone wrote between our read and
        write — re-read and retry."""
        for _ in range(5):
            try:
                cur = await self.crs.get(name)
            except NotFound:
                return
            if cur.get("status") == status:
                # No-op writes matter: every status PUT emits a MODIFIED
                # event that re-enqueues this very reconcile — writing
                # unconditionally turns the controller into a hot loop
                # chasing its own updates.
                return
            # the UpdateStatus idiom: PUT the FULL read object with status
            # replaced — a real apiserver rejects a metadata+status stub
            # (apiVersion/kind are required for typed PUTs)
            obj = dict(cur)
            obj["status"] = status
            sess = await self.client.session()
            url = f"{self.crs.prefix}/{name}/status"
            async with sess.put(url, json=obj) as resp:
                if resp.status == 409:
                    self.status_conflicts_retried += 1
                    continue
                if resp.status == 404:
                    return
                if resp.status >= 400:
                    body = await resp.json(content_type=None)
                    raise RuntimeError(f"status update failed: {body}")
                return
        logger.warning("status update for %s lost 5 conflicts; giving up "
                       "until next reconcile", name)
