"""Test harness config: force JAX onto a virtual 8-device CPU mesh.

Multi-chip hardware is not available in CI; sharding logic is validated on a
virtual CPU mesh (the same pattern the driver's dryrun_multichip uses).
This must run before the first `import jax` anywhere in the test session.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("DYN_LOG", "warning")

import pytest  # noqa: E402


@pytest.fixture
def anyio_backend():
    return "asyncio"
