"""Network-aware disaggregation (docs/disagg.md): topology-costed KV
routing, layer-interleaved tail transfer, and the QoS-aware prefill pool.

The key properties: (1) the routing transfer term prefers near decode
workers exactly when locality labels exist and vanishes otherwise
(topology-blind default recoverable by config); (2) layer-split transfer
is bit-exact against aggregated serving on every transport, and a torn
layer assembly degrades to local recompute with exact token accounting;
(3) the prefill pool serves best-class-first and the claim fallback
prefers same-pod instances.
"""

import asyncio
import random

import pytest

from dynamo_tpu.disagg.handlers import (
    DecodeWorkerHandler, KV_LAYERS_ANNOTATION, PrefillWorkerHandler,
)
from dynamo_tpu.disagg.protocols import (
    DisaggConfig, KvBundle, KvChunkFrame, KvLayerFrame, PrefillResponse,
)
from dynamo_tpu.router.indexer import OverlapScores
from dynamo_tpu.router.protocols import KvRouterConfig
from dynamo_tpu.router.scheduler import KvScheduler
from dynamo_tpu.router.topology import (
    DEFAULT_GBPS, TopologyCostModel, TopologyLabels, link_class, link_costs,
)
from tests.test_disagg import collect_engine, make_engine, req

pytestmark = pytest.mark.anyio


# ------------------------------------------------------------- topology model

def test_link_class_matrix():
    a = TopologyLabels(host="h1", slice_id="s1", pod="p1")
    assert link_class(a, TopologyLabels(host="h1", slice_id="s1",
                                        pod="p1")) == "proc"
    assert link_class(a, TopologyLabels(host="h2", slice_id="s1",
                                        pod="p1")) == "ici"
    assert link_class(a, TopologyLabels(host="h2", slice_id="s2",
                                        pod="p1")) == "dcn"
    assert link_class(a, TopologyLabels(host="h2", slice_id="s2",
                                        pod="p2")) == "host"
    # unknown locality on either side is the conservative host class
    assert link_class(a, TopologyLabels()) == "host"
    assert link_class(TopologyLabels(), a) == "host"


def test_labels_env_and_metadata_roundtrip(monkeypatch):
    monkeypatch.delenv("DYN_TOPO_HOST", raising=False)
    monkeypatch.delenv("DYN_TOPO_SLICE", raising=False)
    monkeypatch.delenv("DYN_TOPO_POD", raising=False)
    assert not TopologyLabels.from_env()  # unset env = unlabeled fleet
    monkeypatch.setenv("DYN_TOPO_SLICE", "s7")
    monkeypatch.setenv("DYN_TOPO_POD", "p3")
    labels = TopologyLabels.from_env()
    assert labels and labels.slice_id == "s7" and labels.pod == "p3"
    assert labels.host  # defaults to the hostname when slice/pod are set
    meta = {"topo": labels.to_metadata()}
    back = TopologyLabels.from_metadata(meta)
    assert back.slice_id == "s7" and back.pod == "p3"
    assert not TopologyLabels.from_metadata(None)
    assert not TopologyLabels.from_metadata({"topo": "garbage"})


def test_cost_model_env_overrides(monkeypatch):
    m = TopologyCostModel()
    assert m.gbps == DEFAULT_GBPS
    assert m.rel_cost("ici") == 1.0
    assert m.rel_cost("host") > m.rel_cost("dcn") > m.rel_cost("ici")
    monkeypatch.setenv("DYN_TOPO_GBPS", "dcn=25, host=5")
    m2 = TopologyCostModel()
    assert m2.gbps["dcn"] == 25.0 and m2.gbps["host"] == 5.0
    assert m2.gbps["ici"] == DEFAULT_GBPS["ici"]
    # constructor overrides beat env
    m3 = TopologyCostModel({"dcn": 100.0})
    assert m3.gbps["dcn"] == 100.0
    monkeypatch.setenv("DYN_TOPO_GBPS", "warp=9")
    with pytest.raises(ValueError):
        TopologyCostModel()
    monkeypatch.setenv("DYN_TOPO_GBPS", "dcn=-1")
    with pytest.raises(ValueError):
        TopologyCostModel()


def test_link_costs_min_over_sources_and_blind_default():
    near = TopologyLabels(host="d1", slice_id="s0", pod="p0")
    far = TopologyLabels(host="d2", slice_id="s9", pod="p9")
    sources = [TopologyLabels(host="pp", slice_id="s0", pod="p0")]
    costs = link_costs(sources, {1: near, 2: far})
    assert costs[1] < costs[2]  # ici vs host
    # a second, far source must not worsen worker 1 (min over sources)
    costs2 = link_costs(sources + [far], {1: near, 2: far})
    assert costs2[1] == costs[1]
    assert costs2[2] < costs[2]  # far worker is proc-local to the far source
    # nobody labeled → None → the scheduler term vanishes (blind default)
    assert link_costs([TopologyLabels()], {1: near}) is None


# --------------------------------------------------------- scheduler term

def _schedule(link, weight=None, temp=0.0):
    cfg = KvRouterConfig(router_temperature=temp)
    if weight is not None:
        cfg.transfer_cost_weight = weight
    sched = KvScheduler(4, cfg, rng=random.Random(0))
    return sched.schedule("r1", isl_tokens=64, seq_hashes=None,
                          overlaps=OverlapScores(), worker_ids=[1, 2],
                          link_costs=link)


def test_scheduler_transfer_term_prefers_near_worker():
    for _ in range(8):  # no tie-break luck: near must win every time
        d = _schedule({1: 1.0, 2: 25.0})
        assert d.worker_id == 1
        assert d.logits[2] > d.logits[1]


def test_scheduler_blind_without_link_costs_and_weight_zero():
    d = _schedule(None)
    assert d.logits[1] == d.logits[2]  # no term at all
    d2 = _schedule({1: 1.0, 2: 25.0}, weight=0.0)
    assert d2.logits[1] == d2.logits[2]  # config kill-switch


def test_scheduler_missing_worker_prices_at_worst_link():
    """A worker that joined worker_ids after the topology snapshot (so it
    is absent from the cost map) must price at the WORST known link, not
    zero — unknown is conservatively far, never free."""
    for _ in range(8):
        # worker 2 is absent from the map; the worst known link is 25.0
        d = _schedule({1: 1.0, 3: 25.0})
        assert d.worker_id == 1
        assert d.logits[2] > d.logits[1]


def test_scheduler_transfer_term_override():
    cfg = KvRouterConfig()
    sched = KvScheduler(4, cfg, rng=random.Random(0))
    d = sched.schedule("r1", isl_tokens=64, seq_hashes=None,
                       overlaps=OverlapScores(), worker_ids=[1, 2],
                       router_config_override={"transfer_cost_weight": 0.0},
                       link_costs={1: 1.0, 2: 25.0})
    assert d.logits[1] == d.logits[2]


# ------------------------------------------------- layer-interleaved transfer

async def test_layer_bundle_wire_roundtrip():
    import msgpack
    import numpy as np

    k = np.arange(3 * 2 * 4 * 2 * 8, dtype=np.float32).reshape(3, 2, 4, 2, 8)
    b = KvBundle(k=k, v=k + 1, num_tokens=8, block_size=4, start_block=5,
                 start_layer=6, total_layers=12)
    w = msgpack.unpackb(msgpack.packb(KvLayerFrame(b).to_wire()), raw=False)
    assert KvLayerFrame.is_wire(w) and not KvChunkFrame.is_wire(w)
    b2 = KvLayerFrame.from_wire(w).bundle
    np.testing.assert_array_equal(b2.k, k)
    assert (b2.start_layer, b2.total_layers, b2.start_block) == (6, 12, 5)
    # full-depth bundles stay wire-identical to the pre-layer-split format
    plain = KvBundle(k=k, v=k, num_tokens=8, block_size=4).to_wire()
    assert "start_layer" not in plain and "total_layers" not in plain


class _SpyPrefillClient:
    """Routes to an in-process prefill handler, counting frame kinds."""

    def __init__(self, ph):
        self.ph = ph
        self.seen = {"layer": 0, "chunk": 0, "direct": 0}

    def available_ids(self):
        return [1]

    async def generate(self, request, ctx=None, mode="round_robin",
                       instance_id=None):
        from dynamo_tpu.disagg.transfer import KvDirectFrame

        async def stream():
            async for f in self.ph.generate(request, None):
                if KvLayerFrame.is_wire(f):
                    self.seen["layer"] += 1
                elif KvChunkFrame.is_wire(f):
                    self.seen["chunk"] += 1
                elif KvDirectFrame.is_wire(f):
                    self.seen["direct"] += 1
                yield f
        return stream()


async def test_layer_split_host_staged_bit_exact():
    """Host-staged layer frames reassemble to the exact aggregated tokens,
    and the final chunk rides layer frames (not a full-depth bundle)."""
    prompt = list(range(1, 151))
    agg = make_engine()
    want = await collect_engine(agg, req(prompt))
    await agg.close()

    pre = make_engine(kv_transfer_direct=False)
    dec = make_engine(kv_transfer_direct=False)
    spy = _SpyPrefillClient(PrefillWorkerHandler(pre))
    dh = DecodeWorkerHandler(dec, spy,
                             DisaggConfig(max_local_prefill_length=8))
    got = []
    async for frame in dh.generate(req(prompt).to_wire(), None):
        got.extend(frame.get("token_ids", []))
    assert got == want
    # tiny has L=2 → min(4, 2) = 2 layer groups, and mid chunks still flow
    assert spy.seen["layer"] == 2 and spy.seen["chunk"] >= 1
    await pre.close()
    await dec.close()


async def test_layer_split_disabled_by_config():
    """kv_transfer_layer_groups<=1 on the decode side drops the capability
    annotation → the prefill side ships whole-bundle tails (recoverable
    topology-blind behavior, acceptance criterion)."""
    prompt = list(range(1, 151))
    agg = make_engine()
    want = await collect_engine(agg, req(prompt))
    await agg.close()

    pre = make_engine(kv_transfer_direct=False)
    dec = make_engine(kv_transfer_direct=False, kv_transfer_layer_groups=0)
    spy = _SpyPrefillClient(PrefillWorkerHandler(pre))
    dh = DecodeWorkerHandler(dec, spy,
                             DisaggConfig(max_local_prefill_length=8))
    got = []
    async for frame in dh.generate(req(prompt).to_wire(), None):
        got.extend(frame.get("token_ids", []))
    assert got == want
    assert spy.seen["layer"] == 0 and spy.seen["chunk"] >= 2
    await pre.close()
    await dec.close()


async def test_layer_split_int8_host_staged_bit_exact():
    """Packed int8 layer slices over the host-staged wire scatter
    bit-exactly (the _scatter_packed_layers path)."""
    prompt = list(range(1, 151))
    agg = make_engine(kv_cache_dtype="int8")
    want = await collect_engine(agg, req(prompt))
    await agg.close()

    pre = make_engine(kv_cache_dtype="int8", kv_transfer_direct=False)
    dec = make_engine(kv_cache_dtype="int8", kv_transfer_direct=False)
    spy = _SpyPrefillClient(PrefillWorkerHandler(pre))
    dh = DecodeWorkerHandler(dec, spy,
                             DisaggConfig(max_local_prefill_length=8))
    got = []
    async for frame in dh.generate(req(prompt).to_wire(), None):
        got.extend(frame.get("token_ids", []))
    assert got == want
    assert spy.seen["layer"] >= 1
    await pre.close()
    await dec.close()


async def test_torn_layer_assembly_recomputes_locally():
    """Dropping one layer frame tears the tail assembly: the decode worker
    must recompute locally with exact tokens and leak no blocks."""
    prompt = list(range(1, 151))
    agg = make_engine()
    want = await collect_engine(agg, req(prompt))
    await agg.close()

    pre = make_engine(kv_transfer_direct=False)
    dec = make_engine(kv_transfer_direct=False)
    free0 = dec.pool.num_free_blocks
    ph = PrefillWorkerHandler(pre)

    class DroppingClient:
        def available_ids(self):
            return [1]

        async def generate(self, request, ctx=None, mode="round_robin",
                           instance_id=None):
            async def stream():
                dropped = False
                async for f in ph.generate(request, None):
                    if KvLayerFrame.is_wire(f) and not dropped:
                        dropped = True
                        continue  # lose the first layer group
                    yield f
            return stream()

    dh = DecodeWorkerHandler(dec, DroppingClient(),
                             DisaggConfig(max_local_prefill_length=8))
    got = []
    async for frame in dh.generate(req(prompt).to_wire(), None):
        got.extend(frame.get("token_ids", []))
    assert got == want  # exact token accounting through the fallback
    for _ in range(50):
        if dec.pool.num_free_blocks == free0 and not dec.scheduler.has_work:
            break
        await asyncio.sleep(0.02)
    assert dec.pool.num_free_blocks == free0
    await pre.close()
    await dec.close()


# --------------------------------------------- transfer fallback matrix

async def test_chaos_injected_pull_failure_recomputes_exactly(chaos):
    """Chaos at kv.direct_pull: every direct pull fails → the decode side
    drains, recomputes prefill locally, tokens match aggregated exactly,
    and the degradation is counted on /metrics."""
    from dynamo_tpu.runtime.metrics import MetricsRegistry

    chaos("kv.direct_pull:error=1.0", seed=3)
    prompt = list(range(1, 151))
    agg = make_engine()
    want = await collect_engine(agg, req(prompt))
    await agg.close()

    pre = make_engine()
    dec = make_engine()
    free0 = dec.pool.num_free_blocks
    reg = MetricsRegistry()
    spy = _SpyPrefillClient(PrefillWorkerHandler(pre))
    dh = DecodeWorkerHandler(dec, spy,
                             DisaggConfig(max_local_prefill_length=8),
                             metrics=reg)
    got = []
    async for frame in dh.generate(req(prompt).to_wire(), None):
        got.extend(frame.get("token_ids", []))
    assert got == want
    assert spy.seen["direct"] >= 1  # the direct path was really offered
    assert dec.direct_transfer.stats["pull_failures"] >= 1
    text = reg.render()
    assert "dynamo_kv_direct_pull_failures_total" in text
    failures = [ln for ln in text.splitlines()
                if ln.startswith("dynamo_kv_direct_pull_failures_total ")]
    assert failures and float(failures[0].split()[-1]) >= 1
    for _ in range(50):
        if dec.pool.num_free_blocks == free0 and not dec.scheduler.has_work:
            break
        await asyncio.sleep(0.02)
    assert dec.pool.num_free_blocks == free0
    await pre.close()
    await dec.close()


async def test_unplaceable_stream_retracts_direct_offers():
    """When the decode side cannot place pages (alloc failure), the drained
    direct offers are retracted immediately — no pages pinned until the
    TTL sweep — and the request completes via local prefill."""
    from dynamo_tpu.disagg import transfer as T

    T._offers.clear()
    prompt = list(range(1, 151))
    agg = make_engine()
    want = await collect_engine(agg, req(prompt))
    await agg.close()

    pre = make_engine()
    dec = make_engine()
    dec.alloc_inject = lambda n: None  # injection always refused
    spy = _SpyPrefillClient(PrefillWorkerHandler(pre))
    dh = DecodeWorkerHandler(dec, spy,
                             DisaggConfig(max_local_prefill_length=8))
    got = []
    async for frame in dh.generate(req(prompt).to_wire(), None):
        got.extend(frame.get("token_ids", []))
    assert got == want
    assert spy.seen["direct"] >= 2
    assert not T._offers  # every unclaimed offer was retracted
    await pre.close()
    await dec.close()


async def test_kv_transfer_metrics_host_path():
    """dynamo_kv_transfer_bytes_total{path=host} and the seconds histogram
    populate from a host-staged transfer."""
    from dynamo_tpu.runtime.metrics import MetricsRegistry

    prompt = list(range(1, 151))
    pre = make_engine(kv_transfer_direct=False)
    dec = make_engine(kv_transfer_direct=False)
    reg = MetricsRegistry()
    dh = DecodeWorkerHandler(dec, _layer_client(pre),
                             DisaggConfig(max_local_prefill_length=8),
                             metrics=reg)
    async for _ in dh.generate(req(prompt).to_wire(), None):
        pass
    text = reg.render()
    byte_lines = [ln for ln in text.splitlines()
                  if ln.startswith("dynamo_kv_transfer_bytes_total{")]
    assert byte_lines and 'path="host"' in byte_lines[0]
    assert float(byte_lines[0].split()[-1]) > 0
    assert 'dynamo_kv_transfer_seconds_count{path="host"} 1' in text
    await pre.close()
    await dec.close()


def _layer_client(pre):
    return _SpyPrefillClient(PrefillWorkerHandler(pre))


# ------------------------------------------------- QoS-aware prefill pool

async def test_prefill_queue_best_class_first():
    """A capacity-1 worker must claim interactive → standard → batch no
    matter the enqueue order."""
    from dynamo_tpu.disagg.queue import (
        PrefillQueueClient, PrefillQueueWorker, prefill_queue_depth,
    )
    from dynamo_tpu.runtime.context import Context
    from dynamo_tpu.runtime.control_plane import LocalControlPlane

    plane = LocalControlPlane()
    client = PrefillQueueClient(plane, claim_timeout=5.0)

    order = []
    gate_open = asyncio.Event()

    acquires = []
    for prio in ("batch", "standard", "interactive"):  # worst first
        ctx = Context()
        ctx.priority = None if prio == "standard" else prio
        acquires.append(asyncio.ensure_future(client.acquire(ctx)))
        await asyncio.sleep(0.05)  # deterministic enqueue order
    assert await prefill_queue_depth(plane) == 3  # split queues still sum

    claimed = asyncio.Event()

    class RecordingWorker(PrefillQueueWorker):
        async def _pop_best_class(self):
            await gate_open.wait()
            item = await super()._pop_best_class()
            if item is not None:
                import msgpack

                order.append(msgpack.unpackb(item, raw=False).get(
                    "qos", "standard"))
                if len(order) == 3:
                    claimed.set()
            return item

    w = await RecordingWorker(plane, instance_id=42).start()
    gate_open.set()
    await asyncio.wait_for(claimed.wait(), 10.0)
    assert order == ["interactive", "standard", "batch"]
    for f in acquires:
        assert await f == 42
    await w.stop()
    await plane.close()


async def test_claim_fallback_prefers_same_pod_and_counts():
    """Claim timeout → fallback dispatch goes DIRECT to the near (same-pod)
    prefill instance, and the degradation is counted by reason."""
    from types import SimpleNamespace

    from dynamo_tpu.disagg.queue import PrefillQueueClient
    from dynamo_tpu.runtime.control_plane import LocalControlPlane
    from dynamo_tpu.runtime.metrics import MetricsRegistry

    plane = LocalControlPlane()
    prompt = list(range(1, 151))
    agg = make_engine()
    want = await collect_engine(agg, req(prompt))
    await agg.close()

    pre = make_engine()
    dec = make_engine()
    ph = PrefillWorkerHandler(pre)
    NEAR, FAR = 11, 22
    calls = []

    class LabeledClient:
        def available_ids(self):
            return [NEAR, FAR]

        def instances(self):
            return [
                SimpleNamespace(instance_id=NEAR, metadata={
                    "topo": {"host": "other", "slice": "s1", "pod": "p0"}}),
                SimpleNamespace(instance_id=FAR, metadata={
                    "topo": {"host": "far", "slice": "s9", "pod": "p9"}}),
            ]

        async def generate(self, request, ctx=None, mode="round_robin",
                           instance_id=None):
            calls.append((mode, instance_id))

            async def stream():
                async for f in ph.generate(request, None):
                    yield f
            return stream()

    reg = MetricsRegistry()
    dh = DecodeWorkerHandler(
        dec, LabeledClient(), DisaggConfig(max_local_prefill_length=8),
        prefill_queue=PrefillQueueClient(plane, claim_timeout=0.05),
        metrics=reg,
        topo_labels=TopologyLabels(host="me", slice_id="s1", pod="p0"))
    got = []
    async for frame in dh.generate(req(prompt).to_wire(), None):
        got.extend(frame.get("token_ids", []))
    assert got == want
    assert calls == [("direct", NEAR)]  # near preferred, not round robin
    line = next(ln for ln in reg.render().splitlines()
                if ln.startswith('dynamo_prefill_claim_fallback_total{'))
    assert 'reason="timeout"' in line and float(line.split()[-1]) == 1.0
    await pre.close()
    await dec.close()
    await plane.close()


async def test_nearest_pick_handles_mixed_labeled_pool():
    """Unlabeled prefill instances price at the host class, so a mixed
    pool still prefers the strictly-nearer labeled instance — and with NO
    queue configured the near preference must not run at all (a standing
    pin with no load signal would hot-spot one instance)."""
    from types import SimpleNamespace

    dec = make_engine()
    NEAR, BARE = 5, 6

    class MixedClient:
        def available_ids(self):
            return [NEAR, BARE]

        def instances(self):
            return [
                SimpleNamespace(instance_id=NEAR, metadata={
                    "topo": {"host": "x", "slice": "s1", "pod": "p0"}}),
                SimpleNamespace(instance_id=BARE, metadata={}),
            ]

    dh = DecodeWorkerHandler(
        dec, MixedClient(), DisaggConfig(max_local_prefill_length=8),
        topo_labels=TopologyLabels(host="me", slice_id="s1", pod="p0"))
    assert dh._nearest_prefill_instance() == NEAR
    await dec.close()


async def test_no_queue_deployment_keeps_round_robin():
    """prefill_queue=None (the r1 dispatch path): even a labeled pool must
    be served round robin — the near preference is a CLAIM-FALLBACK
    behavior only."""
    from types import SimpleNamespace

    pre = make_engine()
    dec = make_engine()
    ph = PrefillWorkerHandler(pre)
    calls = []

    class LabeledClient:
        def available_ids(self):
            return [1, 2]

        def instances(self):
            return [SimpleNamespace(instance_id=i, metadata={
                "topo": {"host": f"h{i}", "slice": "s1", "pod": "p0"}})
                for i in (1, 2)]

        async def generate(self, request, ctx=None, mode="round_robin",
                           instance_id=None):
            calls.append(mode)

            async def stream():
                async for f in ph.generate(request, None):
                    yield f
            return stream()

    dh = DecodeWorkerHandler(
        dec, LabeledClient(), DisaggConfig(max_local_prefill_length=8),
        topo_labels=TopologyLabels(host="h1", slice_id="s1", pod="p0"))
    got = []
    async for frame in dh.generate(req(list(range(1, 151))).to_wire(), None):
        got.extend(frame.get("token_ids", []))
    assert len(got) == 8
    assert calls == ["round_robin"]
    await pre.close()
    await dec.close()


async def test_claim_fallback_unlabeled_pool_stays_round_robin():
    from dynamo_tpu.disagg.queue import PrefillQueueClient
    from dynamo_tpu.runtime.control_plane import LocalControlPlane

    plane = LocalControlPlane()
    pre = make_engine()
    dec = make_engine()
    ph = PrefillWorkerHandler(pre)
    modes = []

    class PlainClient:
        def available_ids(self):
            return [1]

        async def generate(self, request, ctx=None, mode="round_robin",
                           instance_id=None):
            modes.append(mode)

            async def stream():
                async for f in ph.generate(request, None):
                    yield f
            return stream()

    dh = DecodeWorkerHandler(
        dec, PlainClient(), DisaggConfig(max_local_prefill_length=8),
        prefill_queue=PrefillQueueClient(plane, claim_timeout=0.05),
        topo_labels=TopologyLabels(host="me", slice_id="s1", pod="p0"))
    got = []
    async for frame in dh.generate(req(list(range(1, 151))).to_wire(), None):
        got.extend(frame.get("token_ids", []))
    assert len(got) == 8
    assert modes == ["round_robin"]
    await pre.close()
    await dec.close()
    await plane.close()


# ------------------------------------------------------ router integration

async def test_push_router_link_costs_from_instance_metadata():
    """KvPushRouter folds prefill-pool + decode-worker labels into link
    costs; unlabeled pools and the weight kill-switch return None."""
    from types import SimpleNamespace

    from dynamo_tpu.router.kv_router import KvPushRouter

    def fake_client(instances):
        c = SimpleNamespace()
        c.instances = lambda: instances
        return c

    near = SimpleNamespace(instance_id=1, metadata={
        "topo": {"host": "a", "slice": "s0", "pod": "p0"}})
    far = SimpleNamespace(instance_id=2, metadata={
        "topo": {"host": "b", "slice": "s8", "pod": "p8"}})
    pool = [SimpleNamespace(instance_id=9, metadata={
        "topo": {"host": "pp", "slice": "s0", "pod": "p0"}})]

    router = SimpleNamespace(config=KvRouterConfig())
    pr = KvPushRouter.__new__(KvPushRouter)
    pr.client = fake_client([near, far])
    pr.router = router
    pr.prefill_client = fake_client(pool)
    pr._topo_model = None
    pr._link_cache = None
    costs = pr._link_costs()
    assert costs[1] < costs[2]
    assert pr._link_costs() is costs  # memoized on instance identity

    pr.router = SimpleNamespace(config=KvRouterConfig(
        transfer_cost_weight=0.0))
    assert pr._link_costs() is None  # config kill-switch

    pr.router = router
    pr.prefill_client = fake_client([SimpleNamespace(
        instance_id=9, metadata={})])
    assert pr._link_costs() is None  # unlabeled pool: blind default

    pr.prefill_client = None
    assert pr._link_costs() is None  # aggregated deployment


async def test_serve_endpoint_stamps_topo_metadata(monkeypatch):
    """Workers publish DYN_TOPO_* locality labels in their instance record
    at registration (runtime/component.py)."""
    from dynamo_tpu.runtime import DistributedRuntime

    monkeypatch.setenv("DYN_TOPO_SLICE", "s5")
    monkeypatch.setenv("DYN_TOPO_POD", "p5")
    rt = await DistributedRuntime.create()
    try:
        ep = rt.namespace("topo-test").component("w").endpoint("generate")

        async def handler(request, ctx):
            yield {"ok": True}

        handle = await ep.serve_endpoint(handler)
        client = await ep.client().start()
        inst = client.instances()[0]
        assert inst.metadata["topo"] == {
            "host": TopologyLabels.from_env().host,
            "slice": "s5", "pod": "p5"}
        await client.stop()
        await handle.stop(graceful=False)
    finally:
        await rt.shutdown()


# ------------------------------------------------------------ bench smoke

async def test_fleet_ab_smoke():
    """The multi-worker placement A/B runs on CPU and topology-aware
    placement lands every foreground request on the near pod."""
    from benchmarks.disagg_ab import fleet_ab

    out = await fleet_ab(prefill_workers=1, decode_workers=2, isl=64,
                         osl=4, fg=4, seed=0)
    assert out["topo_near_share"] == 1.0
    assert out["blind_ttft_p95_s"] > 0 and out["topo_ttft_p95_s"] > 0
    # the far link is ~25x slower; even p50 should separate cleanly, but
    # gate the smoke loosely (the bench phase gates the real margin)
    assert out["ttft_p95_ratio_blind_over_topo"] is not None
