"""Multi-host meshes: jax.distributed init, global arrays, step replication.

The v5e-64 north star spans 16 hosts; JAX is multi-controller SPMD — every
process must issue the SAME jitted computations in the same order on global
arrays (scaling-book multi-host recipe). This module supplies the three
pieces the engine needs (ref parity: the reference's MultiNodeConfig
node_rank/num_nodes/leader wiring, lib/llm/src/engines.rs:28, and the
engine-internal multi-host TP it delegates to vLLM/TRT-LLM):

- :func:`init_multihost` — ``jax.distributed.initialize`` (explicit
  coordinator/rank for CPU tests and GKE, auto-detect on TPU pods).
- :func:`make_global_mesh` / :func:`global_put` / :func:`global_zeros` —
  a ("dp","sp","tp") mesh over ALL processes' devices and array creation
  that works when shards live on non-addressable devices (device_put
  cannot place remote shards; a callback/jit creation can).
- :class:`StepBroadcaster` / :class:`StepFollower` — the leader rank runs
  the real scheduler and, per engine step, publishes the step's host
  inputs over the control plane; follower ranks replay the identical
  jitted call so the SPMD program stays in lockstep. Decode-side state
  (caches, PRNG seeds) evolves identically because the inputs are
  identical.

Follower scope: tp/sp may span hosts; dp must stay within one leader's
engine (multi-host DP uses separate engines per rank — the DP fleet path).
"""

from __future__ import annotations

import asyncio
import logging
import os
from typing import Callable, Optional

import msgpack
import numpy as np

from dynamo_tpu.parallel.mesh import MeshConfig

logger = logging.getLogger("dynamo.multihost")

#: KV prefix where follower ranks advertise their step-stream endpoints
#: (the ONLY hub traffic step replication generates — one write per
#: follower at fleet start; the steps themselves ride direct TCP)
STEP_STREAM_PREFIX = "mh_steps/{namespace}/"

#: single source of truth for step operand names/order — the leader's pack,
#: the follower's replay, and the engine's dispatch must agree or the fleet
#: silently desyncs
STEP_KEYS = {
    # packed RAGGED layouts (model.make_ragged_step_fn /
    # make_ragged_verify_fn / make_multi_decode_fn): ints5 [5,T] i32 =
    # tokens/positions/slot_map/grid_row/grid_col, rows3 [R,3] i32 =
    # q_start/q_len/kv_len, grid_rows [C] i32, ints [B,4] i32 =
    # last_tokens/positions/kv_lens/top_k, floats [B,2] f32 = temp/top_p,
    # rand [B,2] u32 = seeds/step0, mask_words [T, ceil(V/32)] u32
    "ragged": ("ints5", "rows3", "grid_rows", "block_tables"),
    "ragged_dec": ("ints5", "rows3", "grid_rows", "block_tables"),
    "ragged_mm": ("ints5", "rows3", "grid_rows", "block_tables",
                  "mm_vec", "mm_mask"),
    "pp": ("ints5", "rows3", "grid_rows", "block_tables"),
    "multi": ("ints", "floats", "rand", "block_tables"),
    "verify": ("ints5", "rows3", "grid_rows", "block_tables"),
    "verify_fsm": ("ints5", "rows3", "grid_rows", "block_tables",
                   "mask_words"),
    "draft": ("ints", "block_tables"),  # ints [B,3] = last_tokens/positions/kv_lens
    "embed": ("tokens", "lengths"),
}


def init_multihost(coordinator: Optional[str] = None,
                   num_processes: Optional[int] = None,
                   process_id: Optional[int] = None) -> tuple[int, int]:
    """Join the multi-controller JAX cluster; returns (rank, world_size).

    With no arguments, TPU pods auto-detect topology from the environment;
    CPU tests and GKE pass coordinator/num/rank explicitly.
    """
    import jax

    kw = {}
    if coordinator:
        kw = dict(coordinator_address=coordinator,
                  num_processes=num_processes, process_id=process_id)
    jax.distributed.initialize(**kw)
    rank, world = jax.process_index(), jax.process_count()
    logger.info("multihost up: rank %d/%d, %d global devices",
                rank, world, len(jax.devices()))
    return rank, world


def make_global_mesh(cfg: MeshConfig):
    """Mesh over ALL processes' devices, tp innermost (tp collectives ride
    ICI within a host/slice before crossing DCN)."""
    import jax
    from jax.sharding import Mesh

    devices = jax.devices()
    if len(devices) != cfg.size:
        raise ValueError(
            f"mesh {cfg} needs exactly {cfg.size} devices, cluster has "
            f"{len(devices)}")
    arr = np.asarray(devices, dtype=object).reshape(
        cfg.pp, cfg.dp, cfg.sp, cfg.tp)
    return Mesh(arr, cfg.axis_names)


def is_multihost(mesh) -> bool:
    """True when the mesh holds devices this process cannot address."""
    import jax

    local = set(d.id for d in jax.local_devices())
    return any(d.id not in local for d in mesh.devices.flat)


def global_put(arr, sharding):
    """Host array → global device array, valid across processes.

    Every process passes the SAME full array; the callback hands each
    addressable shard its slice (jax.device_put cannot place shards on
    another host's devices — make_array_from_callback can).
    """
    import jax

    arr = np.asarray(arr)
    return jax.make_array_from_callback(arr.shape, sharding,
                                        lambda idx: arr[idx])


def global_zeros(shape, dtype, sharding):
    """Zeros materialized ON the (possibly multi-host) devices via a jitted
    creation — never staged through one host's memory."""
    import jax
    import jax.numpy as jnp

    return jax.jit(lambda: jnp.zeros(shape, dtype),
                   out_shardings=sharding)()


# -- step replication --------------------------------------------------------


def _pack_step(kind: str, seq: int, arrays: dict) -> bytes:
    assert set(arrays) == set(STEP_KEYS[kind]), \
        f"step operands {sorted(arrays)} drifted from schema"
    wire = {"kind": kind, "seq": seq, "arrays": {
        k: {"b": v.tobytes(), "dtype": str(v.dtype), "shape": list(v.shape)}
        for k, v in arrays.items()}}
    return msgpack.packb(wire)


def _unpack_step(payload: bytes) -> tuple[str, int, dict]:
    wire = msgpack.unpackb(payload, raw=False)
    arrays = {
        k: np.frombuffer(d["b"], np.dtype(d["dtype"])).reshape(d["shape"])
        for k, d in wire["arrays"].items()}
    return wire["kind"], wire.get("seq", -1), arrays


class StepBroadcaster:
    """Leader side: ship each engine step's host inputs to every follower
    over a DIRECT leader→follower TCP stream (the response plane's framed
    connections) — NOT control-plane pub/sub.

    The hub's single asyncio loop tops out around ~11.7k rpc/s SHARED with
    discovery, KV events and metrics (benchmarks/hub_bench.py); riding it
    per decode step put the fleet's hot path behind that ceiling and a hub
    round-trip (the r2 verdict's weak #4). Now the hub carries only the
    rendezvous — followers advertise stream endpoints under
    ``mh_steps/<ns>/`` once — and steps flow over per-follower sockets
    with TCP's own ordering and backpressure: hub traffic per step is
    ZERO messages.

    Installed as ``engine.broadcast_cb``; the engine calls it synchronously
    right before each jitted dispatch. A single sender task drains an
    internal queue so followers observe steps in EXACTLY dispatch order —
    replayed steps out of order would desynchronize the SPMD cache state."""

    def __init__(self, plane, namespace: str = "dynamo"):
        self.plane = plane
        self.namespace = namespace
        self.steps_sent = 0
        self._senders: list = []
        self._q: asyncio.Queue = asyncio.Queue()
        self._task = asyncio.get_event_loop().create_task(self._sender())

    async def connect(self, expect: Optional[int] = None,
                      timeout: float = 120.0) -> "StepBroadcaster":
        """Dial every follower advertised under the rendezvous prefix.
        Call AFTER the fleet barrier (with ``expect`` = follower count the
        barrier guaranteed): the set must be complete before the first
        step — a late joiner starts gapped and dies by contract."""
        import time as _time

        from dynamo_tpu.runtime.response_plane import (
            ConnectionInfo, StreamSender,
        )

        prefix = STEP_STREAM_PREFIX.format(namespace=self.namespace)
        deadline = _time.monotonic() + timeout
        connected: dict = {}
        dial_failures: dict = {}
        while True:
            infos = await self.plane.kv_get_prefix(prefix)
            for key in sorted(infos):
                if key in connected:
                    continue
                info = ConnectionInfo.from_wire(
                    msgpack.unpackb(infos[key], raw=False))
                try:
                    connected[key] = await StreamSender.connect(info)
                    dial_failures.pop(key, None)
                except Exception:
                    # could be a previous fleet incarnation's endpoint whose
                    # lease has not expired yet — OR a live follower hit by a
                    # transient TCP failure. Deleting a live follower's key
                    # makes the expected count unreachable while that
                    # follower waits forever, so only conclude "stale" after
                    # several consecutive failed dials across poll rounds.
                    dial_failures[key] = dial_failures.get(key, 0) + 1
                    if dial_failures[key] < 3:
                        logger.warning(
                            "follower step endpoint %s failed dial %d/3 — "
                            "will retry", key, dial_failures[key])
                        continue
                    logger.warning(
                        "stale follower step endpoint %s — deleting", key)
                    try:
                        await self.plane.kv_delete(key)
                    except Exception:  # noqa: BLE001
                        pass
            if expect is None or len(connected) >= expect:
                break
            if _time.monotonic() > deadline:
                raise TimeoutError(
                    f"only {len(connected)}/{expect} follower step streams "
                    "connected")
            await asyncio.sleep(0.1)
        self._senders = [connected[k] for k in sorted(connected)]
        logger.info("step broadcaster: %d direct follower streams",
                    len(self._senders))
        return self

    def __call__(self, kind: str, arrays: dict) -> None:
        self.steps_sent += 1
        self._q.put_nowait(_pack_step(
            kind, self.steps_sent,
            {k: np.asarray(v) for k, v in arrays.items()}))

    async def _sender(self):
        while True:
            payload = await self._q.get()
            try:
                # concurrent fan-out: per-connection FIFO holds (each
                # sender's writes stay in dispatch order), but the step
                # pays the SLOWEST follower's latency, not the sum
                await asyncio.gather(
                    *(s.send(payload) for s in self._senders))
            except Exception:
                # a LOST step is unrecoverable: followers would replay a
                # gapped stream against stale cache state — and in SPMD a
                # single dead follower wedges the next collective anyway.
                # Die loudly; the supervisor restarts the fleet in sync.
                logger.critical("step broadcast failed — the follower fleet "
                                "is now desynced; exiting", exc_info=True)
                self._q.task_done()
                os._exit(13)
            self._q.task_done()

    async def stop(self):
        await self._q.join()  # sender finished SHIPPING every step
        self._task.cancel()
        for s in self._senders:
            try:
                await s.complete()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass


class StepFollower:
    """Follower rank: replay the leader's step stream against identical
    jitted functions so the multi-controller program stays in lockstep.

    The follower owns its own global param/cache arrays (created with the
    same seeds/checkpoint and shardings as the leader's); only the per-step
    HOST inputs travel — KV pages never cross DCN twice.
    """

    def __init__(self, engine, plane, namespace: str = "dynamo",
                 on_fatal: Optional[Callable] = None):
        self.engine = engine
        self.plane = plane
        self.namespace = namespace
        self.steps_replayed = 0
        #: called on an unrecoverable desync (gap in the stream or a failed
        #: replay); default kills the process — a follower that keeps
        #: replaying after a miss diverges silently forever
        self.on_fatal = on_fatal or (lambda: os._exit(13))
        self._server = None
        self._recv = None
        self._key: Optional[str] = None
        self._task: Optional[asyncio.Task] = None

    async def start(self, lease_id: Optional[int] = None) -> "StepFollower":
        """Open a local stream server, advertise its endpoint at the
        rendezvous prefix (under ``lease_id`` so a dead follower's entry
        expires), and wait for the leader's direct connection."""
        import uuid as _uuid

        from dynamo_tpu.runtime.context import Context
        from dynamo_tpu.runtime.response_plane import ResponseStreamServer

        self._server = ResponseStreamServer()
        await self._server.start()
        info, self._recv = self._server.register_stream(Context())
        self._key = (STEP_STREAM_PREFIX.format(namespace=self.namespace)
                     + _uuid.uuid4().hex)
        await self.plane.kv_put(self._key, msgpack.packb(info.to_wire()),
                                lease_id=lease_id)
        self._task = asyncio.get_running_loop().create_task(self._loop())
        return self

    async def _loop(self):
        eng = self.engine
        async for payload in self._recv:
            try:
                kind, seq, a = _unpack_step(payload)
                if seq != self.steps_replayed + 1:
                    # gap/reorder in the stream: replaying past it would
                    # evolve the cache from the wrong state — unrecoverable
                    logger.critical(
                        "step stream gap: expected seq %d got %d — "
                        "follower desynced", self.steps_replayed + 1, seq)
                    self.on_fatal()
                    return
                keys = STEP_KEYS[kind]
                if kind == "embed":  # /v1/embeddings scratch forward
                    eng._embed_forward(a["tokens"], a["lengths"])
                else:
                    # every cache-evolving kind shares one calling shape:
                    # fn(params, *operands, k_cache, v_cache) -> (..., kc, vc).
                    # Resolve the attribute LAZILY — an eager dict would
                    # touch fns the engine never built (no spec/multi
                    # configured) and crash the replay for unrelated kinds.
                    if kind == "ragged_mm":
                        fn = eng._get_ragged_mm_fn()
                    elif kind == "verify_fsm":
                        fn = eng._get_verify_masked_fn()
                    else:
                        fn = getattr(eng, {"ragged": "ragged_fn",
                                           "ragged_dec": "ragged_dec_fn",
                                           "pp": "pp_fn",
                                           "verify": "verify_fn",
                                           "draft": "draft_fn",
                                           "multi": "multi_fn"}[kind])
                    outs = fn(eng.params,
                              *(eng._put_batch(k, a[k]) for k in keys),
                              eng.k_cache, eng.v_cache)
                    eng.k_cache, eng.v_cache = outs[-2], outs[-1]
                self.steps_replayed += 1
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.critical("follower step replay failed — rank is "
                                "desynced; exiting", exc_info=True)
                self.on_fatal()
                return

    async def stop(self):
        if self._task:
            self._task.cancel()
        if self._key:
            try:
                await self.plane.kv_delete(self._key)
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        if self._server:
            await self._server.stop()
