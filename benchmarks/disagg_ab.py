"""Agg vs disagg A/B at long ISL — the TTFT-interference experiment —
plus the network-aware fleet scenarios (docs/disagg.md):

- ``--prefill-workers N --decode-workers M`` builds an in-process fleet
  with SYNTHETIC topology labels (prefill pool in pod p0/slice s0; decode
  workers half near, half in a far pod) and an emulated per-link bandwidth
  (router/topology.TopologyCostModel.seconds applied per frame). The A/B:
  topology-costed routing (scheduler link_costs term) vs topology-blind,
  same workload, same seed — foreground TTFT p95 is the placement signal.
- ``--layer-ab`` compares layer-interleaved tail streaming
  (kv_transfer_layer_groups) against whole-bundle tails on one
  prefill→decode pair over the same emulated link: the measured
  ``tail exposure`` (first decode token wall − prefill-complete wall at
  the producer) is the transfer-serialized gap the split shrinks.

VERDICT r4 #4: e2e TTFT p95 ≫ p50 and PERF_NOTES blames prefill/decode
interference, but nothing measured it. This harness does the A/B the
moment a chip is available (and validates itself on CPU):

- **background load**: ``--bg`` long-running decode streams saturate the
  decode batch for the whole window;
- **foreground probes**: ``--fg`` long-ISL requests arrive one at a time;
  their TTFT is the interference signal.

A (agg): one engine does both — every foreground prefill chunk steals
step time from the background decode bursts.
B (disagg): a second engine prefills and hands the KV over via the
chunk-pipelined transfer path (PrefillWorkerHandler → DecodeWorkerHandler
— the same code the distributed deployment runs, minus the network);
the decode engine only ever decodes plus injects.

Reports TTFT p50/p95 and background decode tok/s for both arms, using
the perf recording framework (perf/recording.py) for the timelines.

Usage: python -m benchmarks.disagg_ab [--arch llama3_1b|tiny] [--isl 4096]
       [--bg 24] [--fg 8] [--platform cpu]
Prints one JSON line.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import time


def make_args(EngineArgs, cfg, isl: int, conc: int, on_tpu: bool):
    return EngineArgs(
        block_size=16 if on_tpu else 4,
        max_num_seqs=max(conc + 8, 16),
        max_num_batched_tokens=2048 if on_tpu else 256,
        max_model_len=isl + 512,
        multi_step_decode=8 if on_tpu else 2,
        use_pallas_attention=on_tpu,
        prefill_buckets=(1024, 2048, 4096) if on_tpu else (64, 128),
        decode_batch_buckets=(8, 16, 32) if on_tpu else (4, 8),
    )


async def run_arm(cfg, args, *, disagg: bool, isl: int, osl: int, bg: int,
                  fg: int, DisaggConfig, handlers, protocols, recording):
    from dynamo_tpu.engine.engine import AsyncJaxEngine

    PreprocessedRequest, SamplingOptions, StopConditions = protocols
    record_stream, summarize = recording
    PrefillWorkerHandler, DecodeWorkerHandler = handlers

    dec = AsyncJaxEngine(cfg, args)
    pre = None
    if disagg:
        pre = AsyncJaxEngine(cfg, args)
        ph = PrefillWorkerHandler(pre)

        class LocalPrefill:
            def available_ids(self):
                return [1]

            async def generate(self, request, mode="round_robin"):
                async def stream():
                    async for frame in ph.generate(request, None):
                        yield frame
                return stream()

        # threshold scales with the workload so the remote-prefill path
        # runs even on the CPU-clamped self-validation sizes
        dh = DecodeWorkerHandler(dec, LocalPrefill(), DisaggConfig(
            max_local_prefill_length=min(256, isl // 2)))

        async def serve(req):
            async for frame in dh.generate(req.to_wire(), None):
                yield frame
    else:
        async def serve(req):
            async for out in dec.generate(req):
                yield {"token_ids": out.token_ids}

    def req(tokens, max_tokens):
        return PreprocessedRequest(
            model="b", token_ids=tokens,
            stop_conditions=StopConditions(max_tokens=max_tokens,
                                           ignore_eos=True),
            sampling_options=SamplingOptions(temperature=0.0))

    # warm the compile set: one long prefill + a decode burst through
    # the arm's own path
    async for _ in serve(req(list(range(2, isl + 2)), 4)):
        pass

    stop_bg = asyncio.Event()
    bg_tokens = [0]

    async def bg_stream(i):
        # long steady decode: the batch the foreground interferes with.
        # max_tokens must stay admissible under max_model_len — the
        # stream is ended by stop_bg, not by the limit
        r = req([3 + i % 50] * min(256, isl // 2), args.max_model_len // 2)
        async for frame in serve(r):
            bg_tokens[0] += len(frame.get("token_ids", []))
            if stop_bg.is_set():
                break

    async def bg_forever(i):
        while not stop_bg.is_set():
            await bg_stream(i)

    bg_tasks = [asyncio.get_running_loop().create_task(bg_forever(i))
                for i in range(bg)]
    await asyncio.sleep(1.0)  # bg decode reaches steady state

    # warm the CONCURRENT shape set (bg + one fg in flight hits decode
    # buckets the solo warmup never compiled) — unwarmed, the first
    # measured probe's compile time corrupts exactly the p95 this A/B
    # exists to compare
    for i in range(2):
        async for _ in serve(req([(11 * i + j) % 997 + 2
                                  for j in range(isl)], 4)):
            pass
    t_bg0, n_bg0 = time.perf_counter(), bg_tokens[0]

    fg_recs = []
    for i in range(fg):
        prompt = [(7 * i + j) % 997 + 2 for j in range(isl)]
        rec = record_stream(serve(req(prompt, osl)), request_id=f"fg{i}")
        async for _ in rec:
            pass
        fg_recs.append(rec.recording)

    bg_window = time.perf_counter() - t_bg0
    bg_rate = (bg_tokens[0] - n_bg0) / bg_window
    stop_bg.set()
    for t in bg_tasks:
        t.cancel()
    await asyncio.gather(*bg_tasks, return_exceptions=True)
    await dec.close()
    if pre is not None:
        await pre.close()

    s = summarize(fg_recs)
    return {
        "fg_ttft_p50_s": round(s.ttft_p50, 3),
        "fg_ttft_p95_s": round(s.ttft_p95, 3),
        "fg_duration_p50_s": round(s.duration_p50, 3),
        "bg_decode_tok_s": round(bg_rate, 1),
    }


# ------------------------------------------------------- fleet scenarios

_DONE = object()


def _frame_bytes(frame: dict) -> int:
    """Wire size of a disagg frame for link emulation (page frames carry
    their raw bytes; descriptors/responses are control-path sized)."""
    d = frame.get("kv_chunk") or frame.get("kv_layer")
    if d is not None:
        return len(d["k"]) + len(d["v"])
    kv = frame.get("kv")
    if isinstance(kv, dict):  # whole-bundle tail inside PrefillResponse
        return len(kv["k"]) + len(kv["v"])
    return 256


class EmulatedPrefillClient:
    """In-process prefill pool with an emulated network.

    Frames flow through a bounded queue pump (the response-plane analog —
    the producer stages ahead while the consumer is busy) and each frame is
    charged the wire time of the (prefill, decode) link class via
    ``TopologyCostModel.seconds``. The topology IS the emulation; the
    placement policy under test decides who pays which link.
    """

    def __init__(self, handlers, labels, my_labels, model, record=None):
        self.handlers = handlers          # instance_id -> PrefillWorkerHandler
        self.labels = labels              # instance_id -> TopologyLabels
        self.my = my_labels
        self.model = model
        self.record = record              # optional (t_produced, frame) sink
        self._rr = 0

    def available_ids(self):
        return sorted(self.handlers)

    def instances(self):
        from types import SimpleNamespace

        return [SimpleNamespace(instance_id=i,
                                metadata={"topo": self.labels[i].to_metadata()})
                for i in sorted(self.handlers)]

    async def generate(self, request, ctx=None, mode="round_robin",
                       instance_id=None):
        import time as _time

        ids = self.available_ids()
        if mode == "direct" and instance_id is not None:
            pid = instance_id
        else:
            self._rr += 1
            pid = ids[self._rr % len(ids)]
        from dynamo_tpu.router.topology import link_class

        link = link_class(self.labels[pid], self.my)
        ph = self.handlers[pid]
        q: asyncio.Queue = asyncio.Queue(maxsize=8)

        async def pump():
            try:
                async for frame in ph.generate(request, None):
                    await q.put((_time.perf_counter(), frame))
            finally:
                await q.put((0.0, _DONE))

        task = asyncio.get_running_loop().create_task(pump())
        model, rec = self.model, self.record

        async def stream():
            # absolute link clock: frame f starts transferring when the
            # link frees up (or when produced, whichever is later) and is
            # DELIVERED wire-time later; the consumer only sleeps if that
            # instant has not already passed. A frame's wire time thus
            # elapses WHILE the consumer scatters earlier frames — what a
            # real NIC does, and exactly the overlap layer-interleaving
            # exists to exploit.
            link_free = 0.0
            try:
                while True:
                    t_prod, frame = await q.get()
                    if frame is _DONE:
                        return
                    start = max(link_free, t_prod)
                    deliver = start + model.seconds(link,
                                                    _frame_bytes(frame))
                    link_free = deliver
                    wait = deliver - _time.perf_counter()
                    if wait > 0:
                        await asyncio.sleep(wait)
                    if rec is not None:
                        rec(t_prod, frame)
                    yield frame
            finally:
                task.cancel()

        return stream()


async def fleet_ab(prefill_workers: int = 2, decode_workers: int = 4,
                   isl: int = 96, osl: int = 8, fg: int = 12,
                   seed: int = 0, gbps=None):
    """Topology-aware vs topology-blind decode placement at fleet scale.

    Builds P prefill + M decode engines in one process. The prefill pool
    lives in pod ``p0``/slice ``s0``; decode workers alternate near
    (same slice) and far (pod ``p1`` — the host-staged link class). Both
    arms run the same foreground workload over the same emulated links,
    differing ONLY in whether the router's cost function sees link costs.
    Returns TTFT stats per arm + the placement split.
    """
    import random as _random

    from dynamo_tpu.disagg.handlers import (
        DecodeWorkerHandler, DisaggConfig, PrefillWorkerHandler,
    )
    from dynamo_tpu.engine.config import EngineArgs, ModelConfig
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.perf import record_stream, summarize
    from dynamo_tpu.protocols import (
        PreprocessedRequest, SamplingOptions, StopConditions,
    )
    from dynamo_tpu.router.indexer import OverlapScores
    from dynamo_tpu.router.protocols import KvRouterConfig
    from dynamo_tpu.router.scheduler import KvScheduler
    from dynamo_tpu.router.topology import (
        TopologyCostModel, TopologyLabels, link_costs,
    )

    # emulation-scaled bandwidths (not real-link values): the tiny-cpu KV
    # payload is ~50 KB, so links are slowed until the near/far delta
    # dominates scheduler noise while keeping the 25x ici:host ratio of
    # the real default table
    model = TopologyCostModel(gbps or {"proc": 0.2, "ici": 0.05,
                                       "dcn": 0.01, "host": 0.002})
    cfg = ModelConfig.tiny()
    args = EngineArgs(block_size=4, num_blocks=256, max_num_seqs=16,
                      max_num_batched_tokens=64, max_model_len=isl + 64,
                      kv_transfer_direct=False,  # force the emulated wire
                      prefill_buckets=(32, 64), decode_batch_buckets=(2, 4))

    pre_handlers, pre_labels = {}, {}
    pres = []
    for i in range(prefill_workers):
        eng = AsyncJaxEngine(cfg, args)
        pres.append(eng)
        pre_handlers[7000 + i] = PrefillWorkerHandler(eng)
        pre_labels[7000 + i] = TopologyLabels(
            host=f"ph{i}", slice_id="s0", pod="p0")

    decode = []  # (wid, engine, handler, labels)
    for j in range(decode_workers):
        near = j % 2 == 0
        labels = (TopologyLabels(host=f"dh{j}", slice_id="s0", pod="p0")
                  if near else
                  TopologyLabels(host=f"dh{j}", slice_id=f"s9{j}", pod="p1"))
        eng = AsyncJaxEngine(cfg, args)
        dh = DecodeWorkerHandler(
            eng, EmulatedPrefillClient(pre_handlers, pre_labels, labels,
                                       model),
            DisaggConfig(max_local_prefill_length=16))
        decode.append((8000 + j, eng, dh, labels))

    def req(tokens, max_tokens):
        return PreprocessedRequest(
            model="b", token_ids=tokens,
            stop_conditions=StopConditions(max_tokens=max_tokens,
                                           ignore_eos=True),
            sampling_options=SamplingOptions(temperature=0.0))

    # warm every engine's compile set through its own serving path (the
    # warm prompt also pays the emulated wire once, which is fine — it is
    # outside the measured window)
    for _, _eng, dh, _ in decode:
        async for _ in dh.generate(req(list(range(2, isl + 2)), 2).to_wire(),
                                   None):
            pass

    worker_ids = [w for w, *_ in decode]
    wl = {w: labels for w, _, _, labels in decode}
    sources = list(pre_labels.values())
    arms = {}
    for arm in ("blind", "topo"):
        sched = KvScheduler(args.block_size, KvRouterConfig(),
                            rng=_random.Random(seed))
        costs = (link_costs(sources, wl, model) if arm == "topo" else None)
        by_worker = {w: 0 for w in worker_ids}
        recs = []
        base = 200 if arm == "topo" else 500  # disjoint prompt spaces
        for i in range(fg):
            prompt = [(base + 7 * i + j) % 997 + 2 for j in range(isl)]
            rid = f"{arm}-{i}"
            d = sched.schedule(rid, isl_tokens=isl, seq_hashes=None,
                               overlaps=OverlapScores(),
                               worker_ids=worker_ids, link_costs=costs)
            by_worker[d.worker_id] += 1
            dh = next(h for w, _, h, _ in decode if w == d.worker_id)
            rec = record_stream(dh.generate(req(prompt, osl).to_wire(), None),
                                request_id=rid)
            async for _ in rec:
                pass
            sched.mark_prefill_completed(rid)
            sched.free(rid)
            recs.append(rec.recording)
        s = summarize(recs)
        near_ids = {w for w, _, _, labels in decode if labels.pod == "p0"}
        arms[arm] = {
            "ttft_p50_s": round(s.ttft_p50, 4),
            "ttft_p95_s": round(s.ttft_p95, 4),
            "near_share": round(sum(v for w, v in by_worker.items()
                                    if w in near_ids) / max(1, fg), 3),
        }
    for eng in pres:
        await eng.close()
    for _, eng, _, _ in decode:
        await eng.close()
    out = {
        "workload": f"P={prefill_workers} M={decode_workers} ISL={isl} "
                    f"OSL={osl} fg={fg}",
        **{f"{a}_{k}": v for a, st in arms.items() for k, v in st.items()},
        "ttft_p95_ratio_blind_over_topo": round(
            arms["blind"]["ttft_p95_s"] / arms["topo"]["ttft_p95_s"], 2)
        if arms["topo"]["ttft_p95_s"] else None,
    }
    return out


async def layer_ab(isl: int = 256, osl: int = 4, reps: int = 8,
                   gbps: float = 0.5, groups: int = 4):
    """Layer-interleaved vs whole-bundle tail transfer on one
    prefill→decode pair over the same emulated link.

    The signal is the **transfer-exposed TTFT gap**: TTFT with the link
    emulated minus TTFT of a no-link baseline (same pair, near-infinite
    bandwidth) — i.e. the wall the tail transfer adds on top of compute.
    Whole-bundle pays staging, wire and scatter strictly serialized after
    prefill; the layer split starts the wire after ONE group's staging and
    overlaps the rest, so its gap should be smaller.
    """
    import statistics
    import time as _time

    from dynamo_tpu.disagg.handlers import (
        DecodeWorkerHandler, DisaggConfig, PrefillWorkerHandler,
    )
    from dynamo_tpu.engine.config import EngineArgs, ModelConfig
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.protocols import (
        PreprocessedRequest, SamplingOptions, StopConditions,
    )
    from dynamo_tpu.router.topology import TopologyCostModel, TopologyLabels

    # deep model, NARROW matmuls but wide KV heads (hd=64): the tail
    # bundle is ~8 MB while prefill compute (the noise floor) stays
    # small. The prompt fits ONE chunk, so the ENTIRE prompt's KV is the
    # tail — the maximally transfer-serialized case the split targets.
    cfg = ModelConfig(vocab_size=256, hidden_size=256,
                      intermediate_size=256, num_layers=16, num_heads=4,
                      num_kv_heads=4, rope_theta=10000.0,
                      max_position_embeddings=isl + 64, dtype="float32")
    labels = TopologyLabels(host="d0", slice_id="sd", pod="p0")
    plabels = {7100: TopologyLabels(host="p1", slice_id="sp", pod="p0")}

    def req(tokens, max_tokens):
        return PreprocessedRequest(
            model="b", token_ids=tokens,
            stop_conditions=StopConditions(max_tokens=max_tokens,
                                           ignore_eos=True),
            sampling_options=SamplingOptions(temperature=0.0))

    chunk = isl  # single-chunk prompts: the whole prompt KV is the tail

    def make_pair(g):
        args = EngineArgs(block_size=4, num_blocks=256, max_num_seqs=8,
                          max_num_batched_tokens=chunk,
                          max_model_len=isl + 64,
                          kv_transfer_direct=False,
                          kv_transfer_layer_groups=g,
                          prefill_buckets=(chunk // 2, chunk),
                          decode_batch_buckets=(1, 2))
        return AsyncJaxEngine(cfg, args), AsyncJaxEngine(cfg, args)

    def handler(pre, dec, bw):
        return DecodeWorkerHandler(
            dec, EmulatedPrefillClient({7100: PrefillWorkerHandler(pre)},
                                       plabels, labels,
                                       TopologyCostModel({"dcn": bw})),
            DisaggConfig(max_local_prefill_length=16))

    split_pair = make_pair(groups)
    whole_pair = make_pair(0)
    # each arm gets a free-wire baseline ON ITS OWN PAIR — a gap computed
    # against the other pair's baseline folds pair-to-pair engine
    # differences into the transfer signal
    arms = {"split": handler(*split_pair, gbps),
            "split0": handler(*split_pair, 1e6),
            "whole": handler(*whole_pair, gbps),
            "whole0": handler(*whole_pair, 1e6)}

    async def one(dh, prompt):
        t0 = _time.perf_counter()
        t_first = None
        async for frame in dh.generate(req(prompt, osl).to_wire(), None):
            if t_first is None and frame.get("token_ids"):
                t_first = _time.perf_counter()
        return t_first - t0

    ttfts: dict[str, list] = {t: [] for t in arms}
    # arms interleave WITHIN each rep so machine drift (the dominant noise
    # on a shared CPU host) hits all three equally and the per-rep paired
    # differences stay clean; rep 0 warms every pair and is discarded
    for i in range(reps + 1):
        for j, (tag, dh) in enumerate(arms.items()):
            prompt = [(300 * j + 11 * i + k) % 997 + 2 for k in range(isl)]
            t = await one(dh, prompt)
            if i > 0:
                ttfts[tag].append(t)
    for eng in (*split_pair, *whole_pair):
        await eng.close()
    gaps_split = [s - n for s, n in zip(ttfts["split"], ttfts["split0"])]
    gaps_whole = [w - n for w, n in zip(ttfts["whole"], ttfts["whole0"])]
    gap_split = statistics.median(gaps_split)
    gap_whole = statistics.median(gaps_whole)
    out = {
        "ttft_p50_s": {t: round(statistics.median(v), 4)
                       for t, v in ttfts.items()},
        "gap_split_s": round(gap_split, 4),
        "gap_whole_s": round(gap_whole, 4),
        "gap_ratio_split_over_whole": round(gap_split / gap_whole, 3)
        if gap_whole > 0 else None,
        "workload": f"ISL={isl} chunk={chunk} L=16 KV=4 hd=64 "
                    f"groups={groups} gbps={gbps}",
    }
    return out


async def amain():
    ap = argparse.ArgumentParser(description="agg vs disagg TTFT A/B")
    ap.add_argument("--arch", default="llama3_1b")
    ap.add_argument("--isl", type=int, default=4096)
    ap.add_argument("--osl", type=int, default=32)
    ap.add_argument("--bg", type=int, default=24)
    ap.add_argument("--fg", type=int, default=8)
    ap.add_argument("--platform", default=None)
    ap.add_argument("--prefill-workers", type=int, default=0,
                    help="run the multi-worker topology A/B with this many "
                         "prefill workers (with --decode-workers)")
    ap.add_argument("--decode-workers", type=int, default=4)
    ap.add_argument("--layer-ab", action="store_true",
                    help="run the layer-interleaved vs whole-bundle tail "
                         "transfer A/B")
    ap.add_argument("--seed", type=int, default=0)
    cli = ap.parse_args()

    import jax

    if cli.platform:
        jax.config.update("jax_platforms", cli.platform)

    if cli.prefill_workers > 0 or cli.layer_ab:
        out = {"platform": jax.default_backend()}
        if cli.prefill_workers > 0:
            out["fleet"] = await fleet_ab(
                prefill_workers=cli.prefill_workers,
                decode_workers=cli.decode_workers, seed=cli.seed)
        if cli.layer_ab:
            out["layer"] = await layer_ab()
        print(json.dumps(out), flush=True)
        return

    on_tpu = jax.default_backend() == "tpu"
    if cli.arch == "tiny" or not on_tpu:
        from dynamo_tpu.engine.config import ModelConfig

        cfg = ModelConfig.tiny()
        cli.isl = min(cli.isl, 96)
        cli.bg, cli.fg, cli.osl = min(cli.bg, 6), min(cli.fg, 4), 16
    else:
        from dynamo_tpu.models import get_model_config

        cfg = get_model_config(cli.arch)

    from dynamo_tpu.disagg.handlers import (
        DecodeWorkerHandler, DisaggConfig, PrefillWorkerHandler,
    )
    from dynamo_tpu.engine.config import EngineArgs
    from dynamo_tpu.perf import record_stream, summarize
    from dynamo_tpu.protocols import (
        PreprocessedRequest, SamplingOptions, StopConditions,
    )

    kw = dict(
        isl=cli.isl, osl=cli.osl, bg=cli.bg, fg=cli.fg,
        DisaggConfig=DisaggConfig,
        handlers=(PrefillWorkerHandler, DecodeWorkerHandler),
        protocols=(PreprocessedRequest, SamplingOptions, StopConditions),
        recording=(record_stream, summarize),
    )
    args = make_args(EngineArgs, cfg, cli.isl, cli.bg + cli.fg, on_tpu)
    print("running agg arm...", flush=True)
    agg = await run_arm(cfg, args, disagg=False, **kw)
    print("agg done:", agg, flush=True)
    dis = await run_arm(cfg, args, disagg=True, **kw)
    print("disagg done:", dis, flush=True)

    out = {
        "arch": cli.arch, "platform": jax.default_backend(),
        "workload": f"ISL={cli.isl} OSL={cli.osl} bg={cli.bg} fg={cli.fg}",
        "agg": agg, "disagg": dis,
        "ttft_p95_improvement": round(
            agg["fg_ttft_p95_s"] / dis["fg_ttft_p95_s"], 2)
        if dis["fg_ttft_p95_s"] else None,
    }
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    asyncio.run(amain())
