"""Pallas TPU ragged paged-attention kernel: mixed prefill+decode, one launch.

One packed token batch serves every sequence in the step — decode rows
(q_len=1) and prefill chunks (q_len>1) ride the SAME kernel with per-row
``(q_start, q_len, kv_len)`` metadata, so the engine no longer pads decode
batches and prefill chunks to separate compiled buckets (the Ragged Paged
Attention design, PAPERS.md arxiv 2604.15464; the bucket-lattice tax it
kills is quantified in docs/performance.md).

Contract (one layer; the stacked-cache wiring lives in engine/model.py):
  q            [T, H, hd]        packed queries, row-major by sequence; a
                                 row's tokens are consecutive positions
                                 ending at kv_len-1 (the engine's chunk
                                 layout), so per-token positions are pure
                                 index math: pos = kv_len - q_len + j
  k/v cache    [slots, KV, hd]   flat paged layout (slot = block·bs + off)
  block_tables [R, W] int32      per ROW (0 = reserved null block)
  rows3        [R, 3] int32      (q_start, q_len, kv_len) per row; padding
                                 rows carry q_len = 0 and are skipped
  → out        [T, H, hd]

TPU mapping: the same flattened [slots, KV·hd] page-DMA machinery as the
decode kernel in ops/paged_attention.py — pages stream HBM→VMEM once per
query tile through a D-deep rotating DMA pipeline, scores come from one MXU
matmul of the block-expanded query tile [TQ·H, KV·hd] (head h carries its q
only in its own KV segment, so contraction over KV·hd is the per-group
dot), and an online softmax folds pages as they land. Query tiles DMA from
HBM at dynamic offsets (q_start is data), so T never enters VMEM whole and
the compiled signature depends ONLY on (T, R, W) — one program per token
budget, not per (chunk × batch × width) bucket.

Sliding windows and attention sinks match the decode kernel. int8 KV pages
dequantize IN the kernel: per-(slot, head) f32 scales ride as constant-block
VMEM operands in the lane-packed TRANSPOSED ``[KV, padded_slots]`` layout
(slots on the lane dim), rebased per layer via ``scale_slot_base`` — the
§4b design the bucketed decode kernel proved (docs/PERF_NOTES.md; the
4-DMA HBM-scale variant measured 2.9× slower on-chip). Scores dequant in
the [TQ·H, bs] domain through one tiny seg_oh matmul per page, and v-scales
fold into p before the PV matmul, so int8 pages cost the same two DMAs per
page as bf16 at half the bytes. The only remaining degrades to
:func:`ragged_attention_xla` are non-lane-aligned KV·hd and scale tables
past the VMEM budget — both static shape facts the engine counts and logs
(``dynamo_ragged_fallback_total``), never a silent data-dependent branch.
``DYN_RAGGED_ORACLE=1`` routes to the XLA oracle explicitly (bench/test
A/B arms only).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.ops.paged_attention import _LANE, _NEG, _hbm_space


def ragged_pallas_supported(num_kv_heads: int, head_dim: int) -> bool:
    """Same lane-alignment condition as the decode kernel (flattened
    [slots, KV·hd] DMA view)."""
    return (num_kv_heads * head_dim) % _LANE == 0


def ragged_int8_kernel_supported(num_kv_heads: int, sc_slots: int) -> bool:
    """True when the per-layer k/v scale tables fit the VMEM-resident
    budget in the lane-packed transposed [KV, padded_slots] layout
    (sublane pads KV→8, lane pads slots→128) — same accounting as the
    decode kernel's gate. ``sc_slots`` is the PER-LAYER slot count (the
    layer-stacked caller passes one layer's slice + scale_slot_base)."""
    padded_slots = -(-sc_slots // _LANE) * _LANE
    scale_bytes = 2 * (-(-num_kv_heads // 8) * 8) * padded_slots * 4
    return scale_bytes <= int(os.environ.get("DYN_KV_SCALE_VMEM_BYTES",
                                             32 << 20))


def _ragged_kernel(rows3_ref, block_tables_ref, win_ref,  # scalar prefetch
                   sbase_ref,  # scalar pf; sbase = scale-table slot base
                   sink_ref,   # [1, H, 1] VMEM (zeros when has_sink=False)
                   q_ref,      # [Tpad, H·KVhd] HBM (block-expanded, scaled)
                   kcache_ref, vcache_ref,  # [slots, KVhd] HBM
                   *rest,  # [ksc_ref, vsc_ref ([KV, padded_slots] VMEM),]
                           # out_ref, qbuf, obuf, kbuf, vbuf, qo_sem, dma_sem
                   bs: int, tq: int, H: int, has_sink: bool, quant: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if quant:
        (ksc_ref, vsc_ref, out_ref, qbuf, obuf, kbuf, vbuf,
         qo_sem, dma_sem) = rest
    else:
        out_ref, qbuf, obuf, kbuf, vbuf, qo_sem, dma_sem = rest
        ksc_ref = vsc_ref = None

    r = pl.program_id(0)
    q_start = rows3_ref[r, 0]
    q_len = rows3_ref[r, 1]
    kv_len = rows3_ref[r, 2]
    win = win_ref[0]
    KVhd = qbuf.shape[-1] // H
    D = kbuf.shape[0]

    def start_page_dma(w):
        blk = block_tables_ref[r, w]
        slot = w % D
        pltpu.make_async_copy(
            kcache_ref.at[pl.ds(blk * bs, bs)], kbuf.at[slot],
            dma_sem.at[slot, 0]).start()
        pltpu.make_async_copy(
            vcache_ref.at[pl.ds(blk * bs, bs)], vbuf.at[slot],
            dma_sem.at[slot, 1]).start()

    def wait_page_dma(w):
        slot = w % D
        pltpu.make_async_copy(kbuf.at[slot], kbuf.at[slot],
                              dma_sem.at[slot, 0]).wait()
        pltpu.make_async_copy(vbuf.at[slot], vbuf.at[slot],
                              dma_sem.at[slot, 1]).wait()

    n_tiles = (q_len + tq - 1) // tq

    if quant:
        # static head→segment one-hot [H, KV]: head h's per-key scale is
        # seg_oh @ scale-page — one tiny MXU matmul instead of
        # lane-expanding scales into the [bs, KVhd] domain (same trick as
        # the decode kernel)
        KV = ksc_ref.shape[0]
        G = H // KV
        oh_rows = jax.lax.broadcasted_iota(jnp.int32, (H, KV), 0)
        oh_cols = jax.lax.broadcasted_iota(jnp.int32, (H, KV), 1)
        seg_oh = (oh_cols == oh_rows // G).astype(jnp.float32)

    def tile_body(t, _carry):
        tok0 = q_start + t * tq
        # query tile in: the packed array is padded by TQ rows, so the
        # fixed-size copy can never run off the end
        pltpu.make_async_copy(q_ref.at[pl.ds(tok0, tq)], qbuf,
                              qo_sem.at[0]).start()
        pltpu.make_async_copy(qbuf, qbuf, qo_sem.at[0]).wait()

        # positions of this tile: pos0 .. pos0+tq-1 (chunk tokens occupy
        # the tail of the kv range — the engine's packing contract)
        pos0 = kv_len - q_len + t * tq
        hi_pos = jnp.minimum(pos0 + tq - 1, kv_len - 1)
        num_pages = jnp.minimum((hi_pos + bs) // bs, (kv_len + bs - 1) // bs)
        # sliding window: the EARLIEST key any tile position can see is
        # pos0 - win + 1; pages wholly before it are never fetched
        first_key = jnp.where(win > 0, jnp.maximum(pos0 - win + 1, 0), 0)
        start_page = first_key // bs

        prefill_n = jnp.minimum(num_pages, start_page + D)
        jax.lax.fori_loop(start_page, prefill_n,
                          lambda w, c: (start_page_dma(w), c)[1], 0)

        # [TQ·H, KVhd] query tile: row j·H+h is token j's block-expanded
        # query for head h (same MXU trick as the decode kernel)
        qt = qbuf[...].reshape(tq * H, KVhd).astype(jnp.float32)

        def page_body(w, carry):
            m, l, acc = carry  # [TQ·H,1] f32 ×2, [TQ·H,KVhd] f32
            wait_page_dma(w)
            kpage = kbuf[w % D].astype(jnp.float32)  # [bs, KVhd]
            vpage = vbuf[w % D].astype(jnp.float32)

            s = jax.lax.dot_general(
                qt, kpage, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)  # [TQ·H, bs]
            if quant:
                # dequant scores before masking: the VMEM-resident scale
                # tables are TRANSPOSED [KV, padded_slots] (slots on the
                # lane dim), sliced per page and rebased onto the caller's
                # per-layer scale slice
                blk = block_tables_ref[r, w]
                soff = blk * bs - sbase_ref[0]
                ksc = jax.lax.dot_general(
                    seg_oh, ksc_ref[:, pl.ds(soff, bs)],
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)  # [H, bs]
                s = s * jnp.broadcast_to(
                    ksc[None], (tq, H, bs)).reshape(tq * H, bs)

            rows = jax.lax.broadcasted_iota(jnp.int32, (tq * H, bs), 0)
            cols = jax.lax.broadcasted_iota(jnp.int32, (tq * H, bs), 1)
            q_pos = pos0 + rows // H
            key_pos = w * bs + cols
            mask = (key_pos <= q_pos) & (key_pos < kv_len)
            mask = mask & ((win <= 0) | (key_pos > q_pos - win))
            s = jnp.where(mask, s, _NEG)

            chunk_max = jnp.max(s, axis=1, keepdims=True)
            new_m = jnp.maximum(m, chunk_max)
            corr = jnp.exp(m - new_m)
            p = jnp.exp(s - new_m)
            new_l = l * corr + jnp.sum(p, axis=1, keepdims=True)
            pv_p = p
            if quant:
                # fold per-key v-scales into p (head h's own segment; other
                # segments become garbage the caller discards anyway)
                vsc = jax.lax.dot_general(
                    seg_oh, vsc_ref[:, pl.ds(soff, bs)],
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)  # [H, bs]
                pv_p = p * jnp.broadcast_to(
                    vsc[None], (tq, H, bs)).reshape(tq * H, bs)
            pv = jax.lax.dot_general(
                pv_p, vpage, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)  # [TQ·H, KVhd]

            @pl.when(w + D < num_pages)
            def _():
                start_page_dma(w + D)

            return new_m, new_l, acc * corr + pv

        if has_sink:
            # sink slot: seeds the online softmax, contributes no value
            sk = sink_ref[0].astype(jnp.float32)  # [H, 1]
            m0 = jnp.broadcast_to(sk[None], (tq, H, 1)).reshape(tq * H, 1)
            l0 = jnp.ones((tq * H, 1), jnp.float32)
        else:
            m0 = jnp.full((tq * H, 1), _NEG, jnp.float32)
            l0 = jnp.zeros((tq * H, 1), jnp.float32)
        acc0 = jnp.zeros((tq * H, KVhd), jnp.float32)
        m, l, acc = jax.lax.fori_loop(start_page, num_pages, page_body,
                                      (m0, l0, acc0))

        obuf[...] = (acc / jnp.maximum(l, 1e-30)).reshape(
            tq, H * KVhd).astype(obuf.dtype)
        # tile out: overruns past q_len land in the NEXT row's region,
        # which that row's own (later, sequential) grid step overwrites;
        # the last row's overrun lands in the TQ-row output padding
        pltpu.make_async_copy(obuf, out_ref.at[pl.ds(tok0, tq)],
                              qo_sem.at[1]).start()
        pltpu.make_async_copy(obuf, obuf, qo_sem.at[1]).wait()
        return 0

    @pl.when(q_len > 0)
    def _():
        jax.lax.fori_loop(0, n_tiles, tile_body, 0)


def ragged_paged_attention(q, k_cache, v_cache, block_tables, rows3, *,
                           block_size: int, interpret: bool = False,
                           window=None, sinks=None, tq: int = 8,
                           k_scales=None, v_scales=None,
                           scale_slot_base=None):
    """Ragged paged attention over a packed token batch. See module
    docstring for the contract.

    ``k_scales``/``v_scales`` [sc_slots, KV] f32 (int8 caches): pages are
    int8 and dequantize IN the kernel — scales go VMEM-resident in the
    lane-packed transposed layout, fetched once for the whole grid.
    ``scale_slot_base`` (traced scalar, default 0): slot offset of the
    scale tables relative to the page cache — layer-stacked callers pass
    one layer's scale slice plus ``lidx·slots`` so the VMEM budget is
    per-layer, not ×L.

    Routes to :func:`ragged_attention_xla` only for non-lane-aligned
    KV·hd, scale tables past the VMEM budget, or the explicit
    ``DYN_RAGGED_ORACLE=1`` bench/test oracle switch."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    T, H, hd = q.shape
    slots, KV, _ = k_cache.shape
    G = H // KV
    KVhd = KV * hd
    bs = block_size
    quant = k_scales is not None
    sc_slots = k_scales.shape[0] if quant else 0
    if (not ragged_pallas_supported(KV, hd)
            or (quant and not ragged_int8_kernel_supported(KV, sc_slots))
            or os.environ.get("DYN_RAGGED_ORACLE") == "1"):
        return ragged_attention_xla(
            q, k_cache, v_cache, block_tables, rows3, block_size=bs,
            window=window, sinks=sinks, k_scales=k_scales,
            v_scales=v_scales, scale_slot_base=scale_slot_base)
    interpret = interpret or jax.default_backend() != "tpu"
    R, W = block_tables.shape
    has_sink = sinks is not None
    win_arr = jnp.asarray([0 if window is None else window],
                          jnp.int32).reshape(1)
    sbase_arr = jnp.asarray([0 if scale_slot_base is None
                             else scale_slot_base], jnp.int32).reshape(1)
    sink_in = (jnp.zeros((1, H, 1), q.dtype) if not has_sink
               else sinks.reshape(1, H, 1).astype(q.dtype))

    # block-expand q (head h's vector in its own KV segment) + fold the
    # softmax scale; pad by one tile so fixed-size tile DMAs never overrun
    seg = jnp.arange(H) // G
    onehot = jax.nn.one_hot(seg, KV, dtype=q.dtype)
    qexp = jnp.einsum("thd,hk->thkd", q, onehot).reshape(T, H * KVhd)
    qexp = qexp * jnp.asarray(1.0 / np.sqrt(hd), q.dtype)
    qexp = jnp.pad(qexp, ((0, tq), (0, 0)))

    D = min(W, 8)  # page-pipeline depth (VMEM: 2·D·bs·KVhd·dtype bytes)
    kernel = functools.partial(_ragged_kernel, bs=bs, tq=tq, H=H,
                               has_sink=has_sink, quant=quant)
    in_specs = [
        pl.BlockSpec((1, H, 1), lambda r, *_: (0, 0, 0)),
        pl.BlockSpec(memory_space=_hbm_space(pltpu)),  # qexp
        pl.BlockSpec(memory_space=_hbm_space(pltpu)),  # k pages
        pl.BlockSpec(memory_space=_hbm_space(pltpu)),  # v pages
    ]
    operands = [sink_in, qexp, k_cache.reshape(slots, KVhd),
                v_cache.reshape(slots, KVhd)]
    if quant:
        # constant block index → Pallas fetches the scale tables once and
        # keeps them resident across the whole (R,) grid. Transposed so
        # slots ride the (cheap) lane dim — see the decode kernel's budget
        # note for why [slots, KV] would tile-pad KV→128.
        padded_slots = -(-sc_slots // _LANE) * _LANE

        def lane_pack_t(s):
            s = s.astype(jnp.float32).T  # [KV, sc_slots]
            return jnp.pad(s, ((0, 0), (0, padded_slots - sc_slots)))

        in_specs += [
            pl.BlockSpec((KV, padded_slots), lambda r, *_: (0, 0)),
            pl.BlockSpec((KV, padded_slots), lambda r, *_: (0, 0))]
        operands += [lane_pack_t(k_scales), lane_pack_t(v_scales)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(R,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(memory_space=_hbm_space(pltpu)),
        scratch_shapes=[
            pltpu.VMEM((tq, H * KVhd), q.dtype),       # qbuf
            pltpu.VMEM((tq, H * KVhd), q.dtype),       # obuf
            pltpu.VMEM((D, bs, KVhd), k_cache.dtype),  # kbuf
            pltpu.VMEM((D, bs, KVhd), v_cache.dtype),  # vbuf
            pltpu.SemaphoreType.DMA((2,)),             # q-in / out tiles
            pltpu.SemaphoreType.DMA((D, 2)),           # page pipeline
        ],
    )
    out_full = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((T + tq, H * KVhd), q.dtype),
        interpret=interpret,
    )(rows3.astype(jnp.int32), block_tables.astype(jnp.int32), win_arr,
      sbase_arr, *operands)

    # pick each head's own KV segment back out of the expanded domain
    out_full = out_full[:T].reshape(T, H, KV, hd)
    return jnp.take_along_axis(
        out_full, seg[None, :, None, None], axis=2).reshape(T, H, hd)


def ragged_attention_xla(q, k_cache, v_cache, block_tables, rows3, *,
                         block_size: int, window=None, sinks=None,
                         k_scales=None, v_scales=None,
                         scale_slot_base=None):
    """Reference/oracle path: per-token dense gather through XLA, same
    masking semantics as the kernel — the oracle the kernel tests pin, and
    the path non-lane-aligned shapes take. int8 caches dequantize in the
    gather with the same ``k_scales``/``v_scales``/``scale_slot_base``
    contract as the kernel."""
    T, H, hd = q.shape
    KV = k_cache.shape[1]
    G = H // KV
    R, W = block_tables.shape
    bs = block_size
    Tk = W * bs

    q_start = rows3[:, 0]
    q_len = rows3[:, 1]
    kv_len = rows3[:, 2]
    # token → row membership from the contiguous packing. Padding rows
    # (q_len == 0) carry zero q_start/q_len, which would break
    # searchsorted's sorted-input precondition — push their end markers
    # past every real token so the search only ever lands real rows (or
    # the first padding row, for padding tokens; its kv_len 0 masks all).
    ends = jnp.where(q_len > 0, q_start + q_len, jnp.int32(1 << 30))
    tok = jnp.arange(T)
    row_ids = jnp.clip(
        jnp.searchsorted(ends, tok, side="right"), 0, R - 1)
    positions = kv_len[row_ids] - (q_start + q_len)[row_ids] + tok

    slot_idx = (block_tables[:, :, None] * bs
                + jnp.arange(bs)[None, None, :]).reshape(R, Tk)
    k = k_cache[slot_idx].astype(jnp.float32)  # [R, Tk, KV, hd]
    v = v_cache[slot_idx].astype(jnp.float32)
    if k_scales is not None:
        # int8 pages: dequant in the gather, rebasing slot ids onto the
        # caller's (possibly per-layer) scale slice
        sidx = slot_idx - (0 if scale_slot_base is None else scale_slot_base)
        k = k * k_scales[sidx].astype(jnp.float32)[..., None]
        v = v * v_scales[sidx].astype(jnp.float32)[..., None]
    k = k[row_ids]  # [T, Tk, KV, hd]
    v = v[row_ids]

    qg = q.reshape(T, KV, G, hd).astype(jnp.float32)
    s = jnp.einsum("tkgd,tskd->tkgs", qg, k) / np.sqrt(hd)
    key_pos = jnp.arange(Tk)
    mask = (key_pos[None, :] <= positions[:, None]) & (
        key_pos[None, :] < kv_len[row_ids][:, None])
    if window is not None:
        win = jnp.asarray(window)
        mask = mask & ((win <= 0)
                       | (key_pos[None, :] > positions[:, None] - win))
    s = jnp.where(mask[:, None, None, :], s, _NEG)
    if sinks is not None:
        sk = sinks.astype(jnp.float32).reshape(KV, G)[None, :, :, None]
        m = jnp.maximum(s.max(-1), sk[..., 0])[..., None]
        e = jnp.exp(s - m)
        p = e / (e.sum(-1, keepdims=True) + jnp.exp(sk - m))
    else:
        p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("tkgs,tskd->tkgd", p, v)
    return o.reshape(T, H, hd).astype(q.dtype)
