"""Multi-host engine lockstep: two REAL JAX processes, one global mesh.

The closest a single machine gets to a v5e multi-host deployment: two
processes × 2 virtual CPU devices form a global tp=4 mesh via
jax.distributed; rank 0 runs the engine, rank 1 replays the broadcast step
stream (parallel/multihost.py), and both must end with bit-identical global
cache state. Also asserts rank 0's tokens match a plain single-process run
(multi-host sharding must not change numerics)."""

import asyncio
import json
import os
import re
import sys

import pytest

pytestmark = pytest.mark.anyio

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


async def _single_process_reference() -> list[int]:
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.protocols import (
        PreprocessedRequest, SamplingOptions, StopConditions,
    )
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "mh_worker", os.path.join(REPO, "tests", "mh_worker.py"))
    mh = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mh)

    eng = AsyncJaxEngine(mh.mh_model_cfg(), mh.mh_engine_args())
    req = PreprocessedRequest(
        model="t", token_ids=list(range(1, 13)),
        stop_conditions=StopConditions(max_tokens=6, ignore_eos=True),
        sampling_options=SamplingOptions(temperature=0.0))
    toks = []
    async for out in eng.generate(req):
        toks.extend(out.token_ids)
    await eng.close()
    return toks


async def test_two_process_global_mesh_lockstep(unused_tcp_port_factory=None):
    from dynamo_tpu.runtime.control_plane import ControlPlaneServer

    import socket

    server = ControlPlaneServer(port=0)
    plane_addr = await server.start()
    with socket.socket() as s:  # ephemeral coordinator port (no collisions)
        s.bind(("127.0.0.1", 0))
        coord = f"127.0.0.1:{s.getsockname()[1]}"

    env = dict(os.environ, PYTHONPATH=REPO, DYN_LOG="warning")
    env.pop("XLA_FLAGS", None)  # worker sets its own device count
    env.pop("JAX_PLATFORMS", None)

    procs = [await asyncio.create_subprocess_exec(
        sys.executable, os.path.join(REPO, "tests", "mh_worker.py"),
        str(rank), coord, plane_addr, env=env,
        stdout=asyncio.subprocess.PIPE, stderr=asyncio.subprocess.STDOUT)
        for rank in (0, 1)]
    outs = []
    try:
        for p in procs:
            out, _ = await asyncio.wait_for(p.communicate(), 300)
            outs.append(out.decode())
            assert p.returncode == 0, out.decode()
    finally:
        for p in procs:
            if p.returncode is None:
                p.kill()
        await server.stop()

    toks = json.loads(re.search(r"TOKENS (\[.*\])", outs[0]).group(1))
    assert len(toks) == 6
    replayed = int(re.search(r"REPLAYED (\d+)", outs[1]).group(1))
    assert replayed >= 6  # 1 prefill chunk (samples token 1) + 5 decodes

    cks = [float(re.search(r"CKSUM ([0-9.]+)", o).group(1)) for o in outs]
    assert cks[0] == cks[1] > 0.0  # bit-identical global cache on both ranks

    # multi-host sharding must not change the numerics
    ref = await _single_process_reference()
    assert toks == ref
