"""models/ registry + MoE engine path (EP-shardable token-choice experts)."""

import asyncio

import pytest

from dynamo_tpu import models
from dynamo_tpu.engine.config import EngineArgs
from dynamo_tpu.engine.engine import AsyncJaxEngine
from dynamo_tpu.protocols import (
    PreprocessedRequest, SamplingOptions, StopConditions,
)

pytestmark = pytest.mark.anyio


def test_presets_resolve():
    for name in models.PRESETS:
        cfg = models.get_model_config(name)
        assert cfg.num_layers > 0 and cfg.vocab_size > 0
    with pytest.raises(KeyError):
        models.get_model_config("nope")


def test_unsupported_arch_fails_loudly():
    with pytest.raises(NotImplementedError):
        models.from_hf_config(
            {"architectures": ["DeepseekV3ForCausalLM"], "vocab_size": 100})


def test_hf_mapping_round_trip():
    cfg = models.from_hf_config({
        "architectures": ["MixtralForCausalLM"], "vocab_size": 32000,
        "hidden_size": 4096, "intermediate_size": 14336,
        "num_hidden_layers": 32, "num_attention_heads": 32,
        "num_key_value_heads": 8, "num_local_experts": 8,
        "num_experts_per_tok": 2,
    })
    assert cfg.is_moe and cfg.num_experts == 8


async def test_moe_engine_generates_deterministically():
    cfg = models.get_model_config("moe_tiny")
    args = EngineArgs(block_size=4, num_blocks=64, max_num_seqs=4,
                      max_num_batched_tokens=64, max_model_len=128,
                      prefill_buckets=(8, 16, 32, 64),
                      decode_batch_buckets=(1, 2, 4))
    req = PreprocessedRequest(
        model="moe", token_ids=list(range(1, 18)),
        stop_conditions=StopConditions(max_tokens=6, ignore_eos=True),
        sampling_options=SamplingOptions())

    async def run():
        eng = AsyncJaxEngine(cfg, args)
        toks = []
        async for out in eng.generate(req):
            toks.extend(out.token_ids)
        await eng.close()
        return toks

    t1, t2 = await run(), await run()
    assert t1 == t2 and len(t1) == 6
