"""Deploy layer: process operator reconciliation, Kubernetes connector,
Prometheus metrics source (ref: deploy/cloud/operator reconcilers,
planner kubernetes_connector.py, planner/utils/prometheus.py)."""

import asyncio
import json
import os
import sys
import time

import pytest

from dynamo_tpu.deploy.kubernetes_connector import KubernetesConnector
from dynamo_tpu.deploy.operator import ProcessOperator, parse_spec
from dynamo_tpu.planner.planner_core import Decision, Observation
from dynamo_tpu.planner.prometheus import (
    PrometheusMetricsSource, parse_prometheus_text,
)

pytestmark = pytest.mark.anyio

SLEEPER = [sys.executable, "-c",
           "import time\nwhile True: time.sleep(0.2)"]


def write_spec(path, services: dict) -> None:
    import yaml

    doc = {"apiVersion": "dynamo.tpu/v1alpha1",
           "kind": "DynamoGraphDeployment",
           "metadata": {"name": "t"},
           "spec": {"services": services}}
    with open(path, "w") as f:
        yaml.safe_dump(doc, f)


def alive(op: ProcessOperator, svc: str) -> int:
    return sum(1 for r in op.replicas[svc] if r.proc.poll() is None)


async def test_operator_scale_and_crash_restart(tmp_path):
    spec = str(tmp_path / "graph.yaml")
    write_spec(spec, {"work": {"replicas": 2, "command": SLEEPER,
                               "env": {"X_TEST": "1"}}})
    op = ProcessOperator(spec, tick_s=0.1)
    try:
        op.reconcile_once()
        assert alive(op, "work") == 2
        status = json.load(open(spec + ".status.json"))
        assert status["services"]["work"]["ready"] == 2

        # crash one replica → reaped, restart counted, respawned (after
        # backoff; force the clock past it)
        op.replicas["work"][0].proc.kill()
        op.replicas["work"][0].proc.wait()
        op.reconcile_once()
        assert op.restarts["work"] == 1
        op._next_start["work"] = 0.0
        op.reconcile_once()
        assert alive(op, "work") == 2

        # spec edit → scale down to 1 (newest killed first)
        write_spec(spec, {"work": {"replicas": 1, "command": SLEEPER}})
        os.utime(spec, (time.time() + 2, time.time() + 2))
        op.reconcile_once()
        assert alive(op, "work") == 1
    finally:
        await op.stop()
    assert alive(op, "work") == 0  # drained


async def test_operator_follows_planner_target(tmp_path):
    from dynamo_tpu.planner.virtual_connector import VirtualConnector
    from dynamo_tpu.runtime import DistributedRuntime

    spec = str(tmp_path / "graph.yaml")
    write_spec(spec, {
        "decode": {"replicas": 1, "command": SLEEPER, "plannerRole": "decode"},
        "aux": {"replicas": 1, "command": SLEEPER},
    })
    rt = await DistributedRuntime.create()
    op = await ProcessOperator(spec, plane=rt.plane, tick_s=0.05).start()
    try:
        for _ in range(40):
            if alive(op, "decode") == 1:
                break
            await asyncio.sleep(0.05)
        assert alive(op, "decode") == 1

        # the planner writes a target; the operator must realize it
        await VirtualConnector(rt.plane).apply(
            Decision(prefill_replicas=0, decode_replicas=3))
        for _ in range(100):
            if alive(op, "decode") == 3:
                break
            await asyncio.sleep(0.05)
        assert alive(op, "decode") == 3
        assert alive(op, "aux") == 1  # non-planner service untouched
    finally:
        await op.stop()
        await rt.shutdown()


def test_spec_validation(tmp_path):
    bad = tmp_path / "bad.yaml"
    bad.write_text("kind: Nope\n")
    with pytest.raises(ValueError):
        parse_spec(str(bad))
    bad.write_text(
        "kind: DynamoGraphDeployment\nspec:\n  services:\n    a: {replicas: 1}\n")
    with pytest.raises(ValueError):  # no command
        parse_spec(str(bad))


async def test_kubernetes_connector_patches():
    calls = []
    state = {"prefill": 1, "decode": 1}

    async def fake_kubectl(argv):
        calls.append(argv)
        if argv[2] == "patch":
            patch = json.loads(argv[-1])
            for name, svc in patch["spec"]["services"].items():
                state[name] = svc["replicas"]
            return 0, "patched"
        if argv[2] == "get":
            return 0, json.dumps({"spec": {"services": {
                n: {"replicas": r} for n, r in state.items()}}})
        return 1, "unknown"

    c = KubernetesConnector("graph", k8s_namespace="serving",
                            runner=fake_kubectl)
    await c.apply(Decision(prefill_replicas=2, decode_replicas=5))
    assert state == {"prefill": 2, "decode": 5}
    assert calls[0][:2] == ["-n", "serving"]

    # unchanged decision → no second patch
    await c.apply(Decision(prefill_replicas=2, decode_replicas=5))
    assert len(calls) == 1
    assert await c.read_replicas() == {"prefill": 2, "decode": 5}

    # failed patch keeps .applied unset so the next tick retries
    async def failing(argv):
        return 1, "rbac denied"

    c2 = KubernetesConnector("graph", runner=failing)
    await c2.apply(Decision(prefill_replicas=3, decode_replicas=3))
    assert c2.applied is None


async def test_prometheus_source_deltas():
    samples = []

    def text(finished, prompt, completion, lat_sum, lat_cnt, ttft_sum, ttft_cnt):
        return "\n".join([
            f'dynamo_llm_requests_finished_total{{model="m"}} {finished}',
            f'dynamo_llm_prompt_tokens_total{{model="m"}} {prompt}',
            f'dynamo_llm_completion_tokens_total{{model="m"}} {completion}',
            f"dynamo_http_request_duration_seconds_sum {lat_sum}",
            f"dynamo_http_request_duration_seconds_count {lat_cnt}",
            f"dynamo_http_time_to_first_token_seconds_sum {ttft_sum}",
            f"dynamo_http_time_to_first_token_seconds_count {ttft_cnt}",
        ])

    src = PrometheusMetricsSource("http://unused:0")

    async def fake_fetch():
        return parse_prometheus_text(samples.pop(0))

    src._fetch = fake_fetch
    samples.append(text(10, 5000, 1000, 10.0, 10, 1.0, 10))
    assert await src() is None  # first sample: no deltas
    # +20 requests, +16000 prompt tokens, +4000 completion tokens
    samples.append(text(30, 21000, 5000, 110.0, 30, 3.0, 30))
    src._prev_t -= 10.0  # pretend 10s elapsed
    obs = await src()
    assert obs is not None
    assert abs(obs.request_rate - 2.0) < 0.2
    assert abs(obs.isl - 800.0) < 1e-6
    assert abs(obs.osl - 200.0) < 1e-6
    assert abs(obs.ttft_ms - 100.0) < 1e-6  # 2s Δsum / 20 Δcount
    # mean latency 5000ms; (5000-100)/(200-1) ≈ 24.6ms ITL
    assert 20.0 < obs.itl_ms < 30.0


def test_recipes_parse():
    for name in ("mocker-demo", "llama3-70b-v5e64-disagg",
                 "deepseek-r1-wideep"):
        svcs = parse_spec(f"deploy/recipes/{name}.yaml")
        assert svcs and all(s.command for s in svcs.values())
    assert parse_spec(
        "deploy/recipes/llama3-70b-v5e64-disagg.yaml")["decode"].planner_role == "decode"


async def test_operator_restarts_on_command_change(tmp_path):
    spec = str(tmp_path / "graph.yaml")
    write_spec(spec, {"work": {"replicas": 1, "command": SLEEPER}})
    op = ProcessOperator(spec, tick_s=0.1)
    try:
        op.reconcile_once()
        pid_before = op.replicas["work"][0].proc.pid
        # change the env (same replica count): replica must be replaced
        write_spec(spec, {"work": {"replicas": 1, "command": SLEEPER,
                                   "env": {"NEW": "cfg"}}})
        os.utime(spec, (time.time() + 2, time.time() + 2))
        op.reconcile_once()
        assert alive(op, "work") == 1
        assert op.replicas["work"][0].proc.pid != pid_before
    finally:
        await op.stop()
