"""KvbmManager: offload/onboard orchestration across tiers.

Offload path (ref: block_manager/offload.rs:4-34 — offload on registration,
bounded in-flight): when the engine registers full blocks, their pages are
gathered device→host once and inserted into G2; G2 evictions cascade into
G3 when a disk tier is configured.

Onboard path (ref: block_manager.rs:144-150): at admission, prompt prefix
blocks missing from the device pool but present in G2/G3 are scattered back
into freshly allocated device blocks, extending the prefix hit without
recompute — the "KV offload TTFT win" the reference reports
(docs/architecture/architecture.md:95).
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

import numpy as np

from dynamo_tpu.kvbm.tiers import DiskTier, HostTier, RemoteTier

logger = logging.getLogger("dynamo.kvbm")


class KvbmManager:
    """Thread-safe: disk promotion runs in worker threads while the engine's
    event loop serves the host tier, so every tier access takes the lock.

    Tier order: G2 host DRAM → G3 disk → G4 remote object store (armed via
    :meth:`attach_remote` after the runtime connects). G4 I/O never runs
    under the lock: mutating methods queue remote put/delete ops and drain
    them after release — put()/get() callers are worker threads (the engine
    offload/onboard paths run in asyncio.to_thread), so the drain's
    blocking round-trips are safe there."""

    def __init__(self, host_bytes: int, disk_dir: Optional[str] = None,
                 disk_bytes: int = 0, on_change=None, ledger=None):
        self.host = HostTier(host_bytes)
        self.disk = DiskTier(disk_dir, disk_bytes) if (disk_dir and disk_bytes) else None
        self.remote: Optional[RemoteTier] = None
        #: optional WorkerKvLedger (observability/kvaudit.py): per-tier
        #: residency digests for the audit plane. The G2/G3 tiers fold
        #: their own membership changes (tiers.py); owned-G4 entries are
        #: folded here at the _remote_owned mutation sites — all under
        #: this manager's lock, so digest and tier state move together.
        self.ledger = ledger
        if ledger is not None:
            self.host.ledger = ledger
            if self.disk is not None:
                self.disk.ledger = ledger
        self._remote_ops: list = []  # (op, hash, payload|None), lock-guarded
        #: failed deletes awaiting their next attempt (merged into the op
        #: queue at the START of each drain, so retries span drain calls)
        self._remote_retry: list = []
        #: hashes whose G4 put is queued but not yet written: fetches must
        #: treat them as misses WITHOUT discarding the index entry, or the
        #: later write leaks an orphaned object
        self._pending_puts: set = set()
        #: hashes THIS worker wrote to G4 (offload cascade / flow-up) —
        #: the only ones its budget evictions may delete remotely. Index
        #: entries recorded by fetch_remote are residency facts about
        #: FLEET-shared objects other workers own and still advertise;
        #: deleting those would poison every peer's index and the
        #: sentinel radix with no retraction path.
        self._remote_owned: set = set()
        #: serializes drains end-to-end so a delete queued after a put can
        #: never execute before it (two offload threads draining)
        self._drain_lock = threading.Lock()
        self.offloaded_blocks = 0
        self.onboarded_blocks = 0
        self._lock = threading.Lock()
        #: on_change(stored_hashes, removed_hashes) — removed=None means
        #: cleared-all. Feeds the distributed KVBM leader's ownership map
        #: (ref: block_manager/events.rs block store/evict events).
        self.on_change = on_change
        #: on_remote_change(stored_hashes, removed_hashes) — fired from
        #: the drain, OUTSIDE every lock, only after the G4 object store
        #: round trip actually succeeded (a stored announcement for an
        #: unwritten object would send cold workers fetching a miss).
        #: Feeds the G4PrefixAnnouncer's sentinel radix events
        #: (kvbm/distributed.py) so the FLEET knows G4-resident prefixes.
        self.on_remote_change = None

    def _notify(self, stored: list[int], removed) -> None:
        """Fire on_change. MUST be called with the lock held: mutation and
        notification stay atomic so events reach the distributed leader in
        tier-state order (a notify after lock release can interleave with a
        concurrent re-insert and leave the ownership map wrong). The
        callback must therefore be non-blocking (the worker service's is:
        pack + call_soon_threadsafe)."""
        if self.on_change is not None and (stored or removed or removed is None):
            try:
                self.on_change(stored, removed)
            except Exception:
                logger.exception("kvbm on_change callback failed")

    def attach_remote(self, client, capacity_bytes: int = 0) -> None:
        """Arm the G4 tier (ref: block_manager.rs:62-75 CacheLevel::G4).
        Called after runtime startup — the engine is constructed before the
        control plane connects, so the object-store client arrives late."""
        with self._lock:
            self.remote = RemoteTier(client, capacity_bytes)

    def _drain_remote(self) -> None:
        """Perform queued G4 I/O. MUST be called WITHOUT the lock held."""
        with self._drain_lock:
            with self._lock:
                # failed deletes parked by a PREVIOUS drain get their next
                # attempt now — retrying within the same drain loop would
                # burn the whole budget inside one transient plane outage
                if self._remote_retry:
                    self._remote_ops.extend(self._remote_retry)
                    self._remote_retry.clear()
            while True:
                with self._lock:
                    if not self._remote_ops or self.remote is None:
                        return
                    op, h, payload, *rest = self._remote_ops.pop(0)
                    attempts = rest[0] if rest else 0
                    client = self.remote.client
                failed = False
                try:
                    if op == "put":
                        client.put(h, payload)
                    else:
                        client.delete(h)
                except Exception:
                    logger.exception("kvbm G4 %s failed for %x", op, h)
                    failed = True
                if op == "put":
                    with self._lock:
                        self._pending_puts.discard(h)
                        if failed and self.remote is not None:
                            self.remote.discard(h)
                            self._disown_g4(h)
                            self._notify_if_gone(h)
                    if not failed:
                        self._fire_remote_change([h], [])
                elif not failed:
                    self._fire_remote_change([], [h])
                elif failed:
                    # the index entry is already gone — dropping the delete
                    # would orphan the object in the plane's store forever
                    # on a flaky plane; park it for the NEXT drain (retrying
                    # in this loop would exhaust the budget in milliseconds)
                    with self._lock:
                        gave_up = not (attempts + 1 < 5
                                       and self.remote is not None)
                        if not gave_up:
                            self._remote_retry.append(
                                ("delete", h, None, attempts + 1))
                        else:
                            logger.error(
                                "kvbm G4 delete for %x gave up after %d "
                                "attempts — object orphaned in the store",
                                h, attempts + 1)
                    if gave_up:
                        # nothing tracks the orphan anymore — stop
                        # advertising it to the fleet
                        self._fire_remote_change([], [h])

    def _fire_remote_change(self, stored: list, removed: list) -> None:
        """Fire on_remote_change. MUST be called WITHOUT the lock — the
        callback publishes to the control plane (G4PrefixAnnouncer) and
        must never be able to deadlock a tier mutation."""
        cb = self.on_remote_change
        if cb is not None and (stored or removed):
            try:
                cb(stored, removed)
            except Exception:
                logger.exception("kvbm on_remote_change callback failed")

    def _own_g4(self, h: int) -> None:
        self._remote_owned.add(h)
        if self.ledger is not None:
            self.ledger.add("g4", h)

    def _disown_g4(self, h: int) -> None:
        self._remote_owned.discard(h)
        if self.ledger is not None:
            self.ledger.remove("g4", h)

    def _notify_if_gone(self, h: int) -> None:
        """Announce removal when ``h`` left its LAST tier (lock held) —
        a silent drop would leave the distributed leader's map stale."""
        if h not in self.host and (self.disk is None or h not in self.disk):
            self._notify([], [h])

    # -- queries -------------------------------------------------------------

    def __contains__(self, h: int) -> bool:
        with self._lock:
            return (h in self.host
                    or (self.disk is not None and h in self.disk)
                    or (self.remote is not None and h in self.remote))

    def in_disk(self, h: int) -> bool:
        with self._lock:
            return self.disk is not None and h in self.disk

    def in_host(self, h: int) -> bool:
        """Host-tier residency WITHOUT an LRU touch — the restore path's
        'synchronously recoverable here' probe."""
        with self._lock:
            return h in self.host

    def in_local(self, h: int) -> bool:
        """Resident in a tier this worker can actually SERVE (host or
        disk) — G4 is an index over the shared object store, not local
        bytes, and neither restore pulls nor admission onboarding read it
        synchronously."""
        with self._lock:
            return (h in self.host
                    or (self.disk is not None and h in self.disk))

    def host_resident(self, hashes) -> set:
        """The subset of ``hashes`` in the HOST tier, under one lock
        acquisition — the restore residency probe walks hundreds of
        hashes and must not pay a lock round trip per block."""
        with self._lock:
            return {h for h in hashes if h in self.host}

    def filter_not_local(self, hashes) -> list[int]:
        """The subset of ``hashes`` in NO locally-servable tier, under a
        single lock acquisition — the engine's eviction-event filter runs
        on its hot loop and a big LRU churn batch must not pay one lock
        round trip per hash."""
        with self._lock:
            return [h for h in hashes
                    if h not in self.host
                    and (self.disk is None or h not in self.disk)]

    def in_lower_tier(self, h: int) -> bool:
        """Resident below host (G3 disk or G4 remote) — the admission path
        schedules a background promotion for these instead of blocking."""
        with self._lock:
            return ((self.disk is not None and h in self.disk)
                    or (self.remote is not None and h in self.remote))

    def match_prefix(self, seq_hashes: list[int]) -> int:
        """Longest leading run of hashes resident in any tier."""
        n = 0
        for h in seq_hashes:
            if h not in self:
                break
            n += 1
        return n

    # -- offload (G1 → G2 → G3) ----------------------------------------------

    def put(self, h: int, k: np.ndarray, v: np.ndarray) -> None:
        with self._lock:
            if h in self.host:
                return
            self.offloaded_blocks += 1
            removed = self._cascade(self.host.put(h, k, v))
            self._notify([h], removed)
        self._drain_remote()

    def resident_hashes(self) -> list[int]:
        """Host-tier contents snapshot (for fleet-join announcements)."""
        with self._lock:
            return list(self.host._store)

    # -- G4 as the fleet-global prefix store (docs/performance.md) -----------

    def remote_resident(self, hashes) -> set:
        """The subset of ``hashes`` already in the G4 index, LRU-touched,
        under one lock — the flow-up's cheap skip: an already-remote hot
        block needs its LRU slot refreshed, not a tier byte read."""
        with self._lock:
            if self.remote is None:
                return set()
            out = set()
            for h in hashes:
                if h in self.remote:
                    self.remote.touch(h)
                    out.add(h)
            return out

    def publish_remote(self, h: int, k: np.ndarray, v: np.ndarray,
                       drain: bool = True) -> bool:
        """Proactively push one HOT block up to G4 (prefix flow-up): unlike
        the eviction cascade, the block keeps its local copies — G4 gains a
        fleet-readable replica. True = a write was queued; False = G4 not
        armed or the block is already remote (its LRU slot is refreshed so
        hot prefixes stay resident under a byte budget). ``drain=False``
        lets a multi-block run queue writes and flush once via
        :meth:`drain_remote` instead of paying a drain cycle per block."""
        with self._lock:
            if self.remote is None:
                return False
            if h in self.remote:
                self.remote.touch(h)
                return False
            removed = self._to_remote(h, k, v)
            if removed:
                self._notify([], removed)
        if drain:
            self._drain_remote()
        return True

    def drain_remote(self) -> None:
        """Flush queued G4 I/O — the batch counterpart to
        ``publish_remote(..., drain=False)``. Blocking round trips: never
        call on the event loop."""
        self._drain_remote()

    def fetch_remote(self, hashes, max_blocks: Optional[int] = None) -> int:
        """Read a LEADING run of ``hashes`` out of the G4 object store into
        the host tier (cold-start warmup). BYPASSES the local index for
        misses: a cold worker's RemoteTier index is empty even when the
        fleet's G4 store is warm — the router's sentinel radix entries are
        the authority that sent us here. Stops at the first miss
        (onboarding attaches contiguous prefixes only). Blocking I/O: run
        in a worker thread, never on the event loop."""
        budget = len(hashes) if max_blocks is None else int(max_blocks)
        landed = 0
        # fetch in chunks so a prefix restore pays one gathered object-store
        # round trip per ~32 blocks instead of one per block (a chunk past
        # the first miss wastes at most one chunk of reads, and the landing
        # loop below still stops at the hole so contiguity holds)
        chunk_size = 32
        i, stop = 0, False
        while i < len(hashes) and landed < budget and not stop:
            chunk = hashes[i:i + min(chunk_size, budget - landed)]
            i += len(chunk)
            with self._lock:
                client = (self.remote.client if self.remote is not None
                          else None)
                have = {h for h in chunk
                        if h in self.host
                        or (self.disk is not None and h in self.disk)}
            if client is None:
                break
            need = [h for h in chunk if h not in have]
            fetched: dict = {}
            if need:
                getter = getattr(client, "get_many", None)
                if getter is not None:
                    try:
                        fetched = dict(zip(need, getter(need)))
                    except Exception:
                        logger.exception("kvbm G4 warm batch fetch failed")
                else:
                    for h in need:
                        try:
                            fetched[h] = client.get(h)
                        except Exception:
                            logger.exception(
                                "kvbm G4 warm fetch failed for %x", h)
                            break
            for h in chunk:
                if h in have:
                    landed += 1
                    continue
                data = fetched.get(h)
                if data is None:
                    stop = True
                    break
                from dynamo_tpu.kvbm.tiers import RemoteTier

                try:
                    k, v = RemoteTier.decode(data)
                except Exception:
                    logger.exception("kvbm G4 payload for %x undecodable", h)
                    stop = True
                    break
                with self._lock:
                    if self.remote is None:
                        stop = True
                        break
                    # record the proven remote residency in the local index.
                    # Budget evictions here drop INDEX entries only — NEVER
                    # queue object deletes: a cold warmer does not own the
                    # fleet's shared objects, and deleting them would poison
                    # every peer's index and the sentinel radix (the
                    # announcer that advertised them could never retract).
                    # The one exception: our OWN queued-but-unwritten put,
                    # which is cancelled outright so it can't orphan an
                    # object the index just forgot.
                    for rh in self.remote.reserve(h, len(data)):
                        if rh in self._pending_puts:
                            self._remote_ops = [
                                op for op in self._remote_ops
                                if not (op[0] == "put" and op[1] == rh)]
                            self._pending_puts.discard(rh)
                            self._disown_g4(rh)
                    removed = self._cascade(self.host.put(h, k, v))
                    self._notify([h], removed)
                landed += 1
        self._drain_remote()
        return landed

    def _cascade(self, host_evicted) -> list[int]:
        """Push host evictions down the tiers (G2→G3→G4); return hashes
        gone from ALL tiers. Caller holds the lock. Evictions out of a
        deeper tier are checked against the shallower ones: a promoted
        block lives in several tiers at once, and evicting one copy must
        not report the block removed while another still serves it.
        Remote writes/deletes only QUEUE here (drained outside the lock)."""
        removed: list[int] = []
        for eh, ek, ev in host_evicted:
            if self.disk is not None:
                for d in self.disk.put(eh, ek, ev,
                                       capture=self.remote is not None):
                    if isinstance(d, tuple):
                        removed.extend(self._to_remote(*d))
                    elif d not in self.host and (
                            self.remote is None or d not in self.remote):
                        removed.append(d)
                if eh not in self.disk:  # too big for the disk budget:
                    # G4 (unbounded-entry object store) still takes it
                    if self.remote is not None:
                        removed.extend(self._to_remote(eh, ek, ev))
                    else:
                        removed.append(eh)
            elif self.remote is not None:
                removed.extend(self._to_remote(eh, ek, ev))
            else:
                removed.append(eh)
        return removed

    def _to_remote(self, h: int, k: np.ndarray, v: np.ndarray) -> list[int]:
        """Queue a G4 write (lock held); returns hashes LRU-evicted out of
        every tier by the G4 budget."""
        from dynamo_tpu.kvbm.tiers import RemoteTier

        payload = RemoteTier.encode(k, v)
        gone = []
        for rh in self.remote.reserve(h, len(payload)):
            self._pending_puts.discard(rh)
            if rh in self._remote_owned:
                # only objects this worker wrote may be deleted remotely;
                # fetched (shared) entries leave the index silently
                self._disown_g4(rh)
                self._remote_ops.append(("delete", rh, None))
            if rh not in self.host and (self.disk is None
                                        or rh not in self.disk):
                gone.append(rh)
        self._remote_ops.append(("put", h, payload))
        self._pending_puts.add(h)
        self._own_g4(h)
        return gone

    # -- runtime controller surface (ref: block_manager/controller.rs) -------

    def clear(self) -> None:
        """Drop every tier (admin reset)."""
        with self._lock:
            self.host.clear()
            if self.disk is not None:
                self.disk.clear()
            if self.remote is not None:
                # admin reset drops the whole local index but deletes
                # only objects THIS worker wrote — fetched entries are
                # fleet-shared objects some other worker still advertises
                self._remote_ops.extend(
                    ("delete", h, None) for h in self.remote.clear()
                    if h in self._remote_owned)
                self._remote_owned.clear()
                if self.ledger is not None:
                    self.ledger.remove_all("g4")
            self._notify([], None)
        self._drain_remote()

    def make_host_room(self, target_bytes: int) -> None:
        """Evict host-tier LRU entries until ``used <= target_bytes``
        (cascading into disk/remote like any other eviction), WITHOUT
        changing the configured capacity. The preempt-to-swap path calls
        this when a swap reservation doesn't fit the shared DRAM
        allowance: G2 entries are redundant cache copies (re-fetchable or
        merely re-computable), strictly less valuable than a live
        sequence's KV that would otherwise be discarded and re-prefilled."""
        with self._lock:
            removed = self._cascade(
                self.host.evict_to_capacity(max(0, int(target_bytes))))
            self._notify([], removed)
        self._drain_remote()

    def resize_host(self, capacity_bytes: int) -> None:
        """Change the host-tier byte budget at runtime; shrinking evicts LRU
        entries (cascading into disk when configured)."""
        with self._lock:
            self.host.capacity = max(0, int(capacity_bytes))
            removed = self._cascade(
                self.host.evict_to_capacity(self.host.capacity))
            self._notify([], removed)
        self._drain_remote()

    # -- onboard (G2/G3 → caller) --------------------------------------------

    def get_host(self, h: int) -> Optional[tuple[np.ndarray, np.ndarray]]:
        """Host-tier-only lookup — cheap enough for the admission path."""
        with self._lock:
            return self.host.get(h)

    def get_local(self, h: int) -> Optional[tuple[np.ndarray, np.ndarray]]:
        """G2/G3-only lookup for PEER-SERVING paths (KV-restore pulls,
        docs/robustness.md): host DRAM, then disk, never G4 — a pull
        request bounded by a migration deadline must not block on an
        object-store round trip, and a disk read stays off this worker's
        own serving hot path only because callers run it in a thread.
        Disk hits are NOT promoted to host: serving a peer's restore must
        not churn the local G2 working set."""
        with self._lock:
            e = self.host.get(h)
            if e is None and self.disk is not None:
                e = self.disk.get(h)
            return e

    def get(self, h: int) -> Optional[tuple[np.ndarray, np.ndarray]]:
        with self._lock:
            e = self.host.get(h)
            if e is not None:
                return e
            if self.disk is not None:
                e = self.disk.get(h)
                if e is not None:
                    # promote back to host (it is hot again); evictions the
                    # promotion forces out of ALL tiers must be announced
                    # like any other, or the leader's map goes stale
                    removed = self._cascade(self.host.put(h, e[0], e[1]))
                    self._notify([], removed)
            # a queued-but-unwritten put must read as a MISS without
            # discarding the index entry (the write is still coming)
            hit_remote = (e is None and self.remote is not None
                          and h in self.remote
                          and h not in self._pending_puts)
            client = self.remote.client if hit_remote else None
        if e is not None or not hit_remote:
            self._drain_remote()  # a promotion may have queued G4 writes
            return e
        # G4 fetch OUTSIDE the lock (network round trip); the index entry
        # may race an eviction — a miss is handled like any cold block
        try:
            data = client.get(h)
        except Exception:
            logger.exception("kvbm G4 fetch failed for %x", h)
            data = None
        if data is None:
            with self._lock:
                if (self.remote is not None
                        and h not in self._pending_puts):
                    self.remote.discard(h)
                    self._notify_if_gone(h)
            return None
        from dynamo_tpu.kvbm.tiers import RemoteTier

        k, v = RemoteTier.decode(data)
        with self._lock:
            self.remote.touch(h)
            removed = self._cascade(self.host.put(h, k, v))
            self._notify([], removed)
        self._drain_remote()
        return k, v

    def stats(self) -> dict:
        return {
            "host_blocks": len(self.host),
            "host_bytes": self.host.used,
            "disk_blocks": len(self.disk) if self.disk is not None else 0,
            "disk_bytes": self.disk.used if self.disk is not None else 0,
            "remote_blocks": len(self.remote) if self.remote is not None else 0,
            "remote_bytes": self.remote.used if self.remote is not None else 0,
            "offloaded_blocks": self.offloaded_blocks,
            "onboarded_blocks": self.onboarded_blocks,
        }
