"""Structured decoding subsystem (docs/structured.md).

Compiles guided-decoding constraints (regex / JSON schema / choice /
tool-call grammars) into dense device tables and runs the FSM inside the
sampling dispatch, so constrained rows ride the ragged step and the
pipelined decode loop with no host sync. The host DFA (llm/guided.py)
remains the semantics oracle and the fallback for constraints whose
tables exceed the byte budget.
"""

from __future__ import annotations

from typing import Optional

from dynamo_tpu.structured.compiler import (  # noqa: F401
    COMPILE_STATS,
    CompiledFsm,
    FsmBudgetError,
    compile_fsm,
    get_compiled,
)
from dynamo_tpu.structured.runtime import (  # noqa: F401
    FsmCursor,
    FsmSegment,
    StructuredRuntime,
    arena_states,
    env_enabled,
    table_budget_bytes,
)
from dynamo_tpu.structured.tools import tool_constraint  # noqa: F401


def build_guided_state(guided: dict, vocab: list, eos_ids: list,
                       runtime: Optional[StructuredRuntime] = None,
                       want_device: bool = True):
    """The engine's ONE entry for attaching a constraint to a sequence.

    Returns an :class:`FsmCursor` (device path: table mask fused into the
    sampling dispatch, O(1) host mirror advance) when the runtime can hold
    the compiled machine, else the host-oracle ``GuidedState``. Every
    admission counts one ``hit``/``miss`` into :data:`COMPILE_STATS` —
    a hit means NO DFA or table compile work ran (both caches warm).
    """
    from dynamo_tpu.llm.guided import GuidedState, get_machine, guided_pattern
    from dynamo_tpu.runtime.context import InvalidRequestError

    pattern = guided_pattern(guided)
    machine, hit = get_machine(pattern, vocab)
    if not machine.token_live(machine.start):
        # same compile-time refusal as llm/guided.compile_guided, but
        # TYPED: the rejection is deterministic across the fleet (every
        # worker serves the same vocabulary), so it must not burn
        # migration retries and must surface as the caller's 400
        raise InvalidRequestError(
            "guided constraint cannot be satisfied by any token sequence "
            "over this model's vocabulary")
    cursor = None
    if want_device and runtime is not None and runtime.cap > 0:
        compiled, c_hit = get_compiled(machine, pattern, vocab, eos_ids,
                                       runtime.V, runtime.cap - 1)
        hit = hit and c_hit
        if compiled is not None:
            seg = runtime.acquire((pattern, tuple(sorted(
                e for e in eos_ids if 0 <= e < runtime.V))), compiled)
            if seg is not None:
                cursor = FsmCursor(seg, runtime)
    COMPILE_STATS["hit" if hit else "miss"] += 1
    if cursor is not None:
        runtime.rows_device += 1
        return cursor
    if runtime is not None:
        runtime.rows_host += 1
    return GuidedState(machine, eos_ids)
