"""Mocker: a simulated LLM engine for distributed tests without accelerators
(rebuild of lib/llm/src/mocker/, SURVEY.md §2.2 "Mocker")."""

from dynamo_tpu.mocker.engine import MockEngine, MockEngineArgs

__all__ = ["MockEngine", "MockEngineArgs"]
