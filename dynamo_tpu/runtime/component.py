"""Namespace → Component → Endpoint service model with discovery.

Mirrors the reference's component model and path scheme exactly so ops tooling
carries over (ref: lib/runtime/src/component.rs:75-110,460-467,520):

- instance key:   ``instances/<ns>/<comp>/<ep>:<lease-hex>``
- request subject: ``<ns>_<comp>.<ep>-<lease-hex>``

A served endpoint registers a control-plane request handler on its subject and
writes its instance key under its process's primary lease; lease loss (crash,
network partition, shutdown) deletes the key, and every client's prefix watch
drops the instance — that is the failure-detection path.

Requests carry a response-plane ``ConnectionInfo`` so token streams flow
worker→requester directly (ref: egress/addressed_router.rs:60-230); the
control-plane reply is only an acceptance ack. In-process endpoints
short-circuit through asyncio queues with no sockets or hub round-trip.
"""

from __future__ import annotations

import asyncio
import logging
import os
import random
from dataclasses import dataclass
from typing import Any, AsyncIterator, Callable, Optional

import msgpack

from dynamo_tpu.runtime.chaos import ChaosError, get_chaos
from dynamo_tpu.runtime.context import (
    Context,
    DeadlineExceededError,
    OverloadedError,
    StreamError,
    STREAM_ERR_MSG,
    stream_error_from_wire,
)
from dynamo_tpu.runtime.control_plane import NoRespondersError, Watch
from dynamo_tpu.runtime.response_plane import (
    ConnectionInfo,
    ResponseReceiver,
    StreamSender,
    make_local_stream,
)
from dynamo_tpu.runtime.streams import batched

logger = logging.getLogger("dynamo.component")

INSTANCE_ROOT = "instances"

#: handler(request, context) -> async iterator of response payloads
EndpointHandler = Callable[[Any, Context], AsyncIterator[Any]]


def instance_key(ns: str, comp: str, ep: str, lease_id: int) -> str:
    return f"{INSTANCE_ROOT}/{ns}/{comp}/{ep}:{lease_id:x}"


def instance_subject(ns: str, comp: str, ep: str, lease_id: int) -> str:
    return f"{ns}_{comp}.{ep}-{lease_id:x}"


@dataclass(frozen=True)
class Instance:
    namespace: str
    component: str
    endpoint: str
    instance_id: int  # lease id
    #: free-form worker-provided info (e.g. dp_rank, served model) readable by
    #: clients for selection logic
    metadata: Optional[dict] = None

    @property
    def subject(self) -> str:
        return instance_subject(self.namespace, self.component, self.endpoint, self.instance_id)

    def to_wire(self) -> dict:
        return {
            "namespace": self.namespace,
            "component": self.component,
            "endpoint": self.endpoint,
            "instance_id": self.instance_id,
            "metadata": self.metadata or {},
        }

    @staticmethod
    def from_wire(d: dict) -> "Instance":
        return Instance(
            d["namespace"],
            d["component"],
            d["endpoint"],
            d["instance_id"],
            d.get("metadata") or {},
        )


class Namespace:
    def __init__(self, runtime, name: str):
        self._runtime = runtime
        self.name = name

    def component(self, name: str) -> "Component":
        return Component(self._runtime, self, name)


class Component:
    def __init__(self, runtime, namespace: Namespace, name: str):
        self._runtime = runtime
        self.namespace = namespace
        self.name = name

    def endpoint(self, name: str) -> "Endpoint":
        return Endpoint(self._runtime, self, name)

    @property
    def path(self) -> str:
        return f"{self.namespace.name}/{self.name}"


class ServeHandle:
    """Handle to a live served endpoint; ``stop()`` deregisters it."""

    def __init__(self, endpoint: "Endpoint", lease_id: int, cancel_serve, inflight: set):
        self.endpoint = endpoint
        self.lease_id = lease_id
        self._cancel_serve = cancel_serve
        self._inflight = inflight
        self._stopped = asyncio.Event()

    async def _deregister(self, kv_delete: bool):
        """Shared teardown for stop() and kill(): forget the replay
        record, stop serving, drop the in-process short-circuit entry.
        ``kv_delete`` is the goodbye — stop() says it, kill() leaves the
        instance key to die with the lease TTL."""
        rt = self.endpoint._runtime
        ns = self.endpoint.component.namespace.name
        comp = self.endpoint.component.name
        ep = self.endpoint.name
        key = instance_key(ns, comp, ep, self.lease_id)
        rt.drop_registration(key)
        if kv_delete:
            await rt.plane.kv_delete(key)
        if self._cancel_serve:
            await self._cancel_serve()
        rt._local_endpoints.pop(
            instance_subject(ns, comp, ep, self.lease_id), None)

    async def stop(self, graceful: bool = True,
                   timeout: Optional[float] = None):
        """Deregister, then (graceful) wait for in-flight streams to finish.

        ``timeout`` bounds the graceful drain (``DYN_DRAIN_TIMEOUT`` at the
        mains): streams still running when it expires are cancelled instead
        of holding shutdown hostage.
        """
        await self._deregister(kv_delete=True)
        if graceful and self._inflight:
            tasks = list(self._inflight)
            if timeout is not None:
                done, pending = await asyncio.wait(tasks, timeout=timeout)
                if pending:
                    logger.warning(
                        "drain timeout (%.1fs): cancelling %d in-flight "
                        "streams", timeout, len(pending))
                    for t in pending:
                        t.cancel()
                    await asyncio.gather(*pending, return_exceptions=True)
            else:
                await asyncio.gather(*tasks, return_exceptions=True)
        self._stopped.set()

    async def wait(self):
        await self._stopped.wait()

    async def kill(self):
        """SIGKILL-grade in-process death (chaos ``worker.kill``): stop
        serving and drop the local short-circuit entry WITHOUT deleting
        the instance key, draining, or completing in-flight streams —
        exactly what a killed process looks like from outside. Discovery
        learns of the death only when the lease TTL expires, which is the
        path proactive death handling (docs/robustness.md) must cover."""
        await self._deregister(kv_delete=False)
        self._stopped.set()


class Endpoint:
    def __init__(self, runtime, component: Component, name: str):
        self._runtime = runtime
        self.component = component
        self.name = name

    @property
    def path(self) -> str:
        return f"{self.component.path}/{self.name}"

    async def serve_endpoint(
        self,
        handler: EndpointHandler,
        metadata: Optional[dict] = None,
        lease_id: Optional[int] = None,
        max_inflight: Optional[int] = None,
    ) -> ServeHandle:
        """Register this endpoint and start handling requests.

        ``handler(request, context)`` must return an async iterator of
        msgpack-serializable responses (ref: component/endpoint.rs:61).

        ``max_inflight`` bounds concurrent requests on this endpoint
        (default ``DYN_WORKER_MAX_INFLIGHT``; 0 = unbounded): excess is
        rejected at the ack with a typed TERMINAL "overloaded" stream error
        — retryable-vs-terminal is what stops Migration from re-sending
        into a saturated fleet (docs/robustness.md).
        """
        rt = self._runtime
        ns, comp, ep = self.component.namespace.name, self.component.name, self.name
        lease = lease_id if lease_id is not None else await rt.primary_lease()
        subject = instance_subject(ns, comp, ep, lease)
        inflight: set[asyncio.Task] = set()
        if max_inflight is None:
            max_inflight = getattr(rt.config, "worker_max_inflight", 0)

        # slots reserved between admission and task creation: the awaited
        # response-stream connect below yields, so a concurrent ack burst
        # would otherwise all pass the len(inflight) check before any of
        # them lands in the set — exceeding the cap exactly when it matters
        reserved = [0]

        async def on_request(payload: bytes) -> bytes:
            envelope = msgpack.unpackb(payload, raw=False)
            ctx = Context.from_wire(envelope.get("ctx", {}))
            # admission BEFORE opening the response stream: a shed request
            # must be cheap for the worker (no socket, no handler task)
            if max_inflight and len(inflight) + reserved[0] >= max_inflight:
                return msgpack.packb({
                    "ok": False, "code": "overloaded", "retryable": False,
                    "error": f"worker at capacity ({max_inflight} in flight)"})
            if ctx.expired:
                return msgpack.packb({
                    "ok": False, "code": "deadline", "retryable": False,
                    "error": "request deadline expired before dispatch"})
            info = ConnectionInfo.from_wire(envelope["conn"])
            # Connect the response stream BEFORE acking so a worker that
            # cannot reach the requester fails the request instead of
            # leaving the requester waiting on a stream that never opens.
            reserved[0] += 1
            try:
                sender = await StreamSender.connect(info, ctx)
            except Exception as e:
                logger.exception("failed to open response stream to %s:%s", info.host, info.port)
                return msgpack.packb({"ok": False, "error": f"response stream connect failed: {e!r}"})
            finally:
                reserved[0] -= 1
            task = asyncio.get_running_loop().create_task(
                _pump_handler(handler, envelope.get("req"), ctx, sender)
            )
            inflight.add(task)
            task.add_done_callback(inflight.discard)
            return msgpack.packb({"ok": True})

        cancel_serve = await rt.plane.serve(subject, on_request)
        # in-process short-circuit path
        rt._local_endpoints[subject] = (handler, inflight, max_inflight)

        meta = dict(metadata or {})
        # under the k8s operator every pod gets DYN_POD_NAME; stamping it
        # into the instance record lets the controller delete THIS pod's
        # discovery keys the moment it scales the pod away, instead of
        # waiting out the lease TTL (ref role: operator/internal/etcd/)
        pod = os.environ.get("DYN_POD_NAME")
        if pod and "pod" not in meta:
            meta["pod"] = pod
        # locality labels (DYN_TOPO_HOST/SLICE/POD) ride the instance record
        # so the KV router and the disagg claim fallback can cost transfers
        # by link class (router/topology.py); unset env = no key published
        # and the whole fleet stays topology-blind
        if "topo" not in meta:
            from dynamo_tpu.router.topology import TopologyLabels

            topo = TopologyLabels.from_env()
            if topo:
                meta["topo"] = topo.to_metadata()
        inst = Instance(ns, comp, ep, lease, metadata=meta)
        value = msgpack.packb(inst.to_wire())
        key = instance_key(ns, comp, ep, lease)
        created = await rt.plane.kv_create(key, value, lease_id=lease)
        if not created:
            await rt.plane.kv_put(key, value, lease_id=lease)
        rt.record_registration(key, value)  # replayed after a hub restart
        logger.info("serving %s (instance %x)", subject, lease)
        return ServeHandle(self, lease, cancel_serve, inflight)

    def client(self) -> "Client":
        return Client(self._runtime, self)


async def _pump_handler(handler: EndpointHandler, request: Any, ctx: Context, sender: StreamSender):
    """Drive one request through a handler, pumping output into a sender.

    Shared by the remote (socket) and in-process (queue) paths so their
    error/cancellation semantics cannot diverge.
    """
    from dynamo_tpu.observability import get_tracer
    from dynamo_tpu.runtime.context import CURRENT_REQUEST

    CURRENT_REQUEST.set(ctx)  # worker-side log lines carry the request id
    logger.debug("handling request (traceparent=%s)", ctx.traceparent)
    # worker-side root span: parents to the sender's rpc hop (remote) or
    # the caller's live span (in-process short-circuit)
    with get_tracer().span("worker.handle", ctx, service="worker") as sp:
        # handler output rides batched(): items that pile up while a send
        # is in flight coalesce into one send_many() — one transport write
        # per batch over the corked response plane
        stream = batched(handler(request, ctx), maxsize=64)
        try:
            n_items = 0
            async for items in stream:
                if ctx.cancelled:
                    break
                n_items += len(items)
                await sender.send_many(items)
            sp.set(items=n_items, cancelled=ctx.cancelled)
            await sender.complete()
        except asyncio.CancelledError:
            await sender.error("worker shutting down")
            raise
        except ChaosError as e:
            # injected transport loss: retryable by definition (migration's
            # recovery path is exactly what chaos exists to exercise)
            sp.status = "error"
            sp.set(error=repr(e)[:200])
            try:
                await sender.error(f"chaos: {e}", retryable=True)
            except Exception:
                pass
        except StreamError as e:
            # typed failure from the handler (overload/deadline/transport):
            # preserve its taxonomy across the hop
            sp.status = "error"
            sp.set(error=repr(e)[:200])
            try:
                await sender.error(str(e), code=e.code, retryable=e.retryable)
            except Exception:
                pass
        except Exception as e:
            logger.exception("endpoint handler failed")
            sp.status = "error"
            sp.set(error=repr(e)[:200])
            try:
                await sender.error(f"handler error: {e!r}")
            except Exception:
                pass
        finally:
            # deterministic teardown of the pump task + handler generator
            # (a cancel-break above must not leave them draining into the
            # bounded queue until GC)
            await stream.aclose()


class Client:
    """Endpoint client: discovery watch + random/round-robin/direct routing.

    Combines the reference's endpoint ``Client`` (ref: component/client.rs) and
    ``PushRouter`` (ref: pipeline/network/egress/push_router.rs:33): it watches
    the instance prefix, keeps live/down sets, and on ``NoResponders`` or a
    broken stream reports the instance down so the next pick avoids it.
    """

    def __init__(self, runtime, endpoint: Endpoint):
        self._runtime = runtime
        self.endpoint = endpoint
        self._instances: dict[int, Instance] = {}
        self._down: set[int] = set()
        # per-instance circuit breaker: consecutive transport failures; at
        # _breaker_threshold the breaker is OPEN (instance also in _down).
        # The canary success path (report_instance_up) HALF-closes an open
        # breaker — one more failure reopens immediately, a real success
        # (record_success) closes it.
        self._fail_streak: dict[int, int] = {}
        self._half_open: set[int] = set()
        self._breaker_threshold = max(
            1, getattr(runtime.config, "circuit_threshold", 3))
        # load-saturated workers (WorkerMonitor): skipped by rr/random
        # routing but NOT dead — distinct from _down so a recovered canary
        # can't accidentally clear a load signal or vice versa
        self._busy: set[int] = set()
        self._watch: Optional[Watch] = None
        self._watch_task: Optional[asyncio.Task] = None
        self._ready = asyncio.Event()
        self._rr = 0
        #: instance_id -> live ResponseReceivers streaming FROM it. When
        #: the instance's key is deleted (lease expiry / deregistration)
        #: each live stream gets a GRACE window (worker_lost_grace): a
        #: gracefully-draining worker deregisters first and keeps
        #: streaming — its frames keep arriving and the stream completes
        #: untouched — while a lease-expired corpse's stream stays silent
        #: and is failed RETRYABLY when the window closes, so Migration
        #: fires on the lease TTL instead of a long transport timeout
        #: (docs/robustness.md "proactive death handling"). A SIGKILLed
        #: remote worker's TCP reset usually beats this; the in-process
        #: short-circuit path and a silently-wedged worker have no other
        #: death signal at all.
        self._live_streams: dict[int, set] = {}
        self._lost_grace = max(
            0.0, getattr(runtime.config, "worker_lost_grace", 5.0))
        self._break_tasks: set = set()  # strong refs for grace monitors
        #: listeners fn(typ, instance_id) fired on discovery put/delete —
        #: the KV router purges radix/link-cost state through this
        self._instance_listeners: list = []
        # Trailing ':' so an endpoint name that is a prefix of a sibling
        # ("gen" vs "generate") cannot absorb the sibling's instances.
        self._prefix = (
            f"{INSTANCE_ROOT}/{endpoint.component.namespace.name}/"
            f"{endpoint.component.name}/{endpoint.name}:"
        )

    def add_instance_listener(self, fn) -> None:
        """Register fn(typ, instance_id) for discovery events ('put' on
        registration, 'delete' on lease expiry/deregistration)."""
        self._instance_listeners.append(fn)

    def _track_stream(self, instance_id: int, receiver) -> None:
        live = self._live_streams.setdefault(instance_id, set())
        live.add(receiver)

        def done(iid=instance_id, r=receiver):
            s = self._live_streams.get(iid)
            if s is not None:
                s.discard(r)
                if not s:
                    self._live_streams.pop(iid, None)

        receiver.on_done = done

    def _break_streams(self, instance_id: int) -> None:
        live = self._live_streams.pop(instance_id, None)
        if not live:
            return
        if self._lost_grace <= 0:
            for r in live:
                self._fail_stream(r, instance_id)
            return
        logger.warning(
            "instance %x deregistered with %d live streams; breaking any "
            "still silent after %.1fs", instance_id, len(live),
            self._lost_grace)
        task = asyncio.get_running_loop().create_task(
            self._grace_break(instance_id, live))
        self._break_tasks.add(task)
        task.add_done_callback(self._break_tasks.discard)

    @staticmethod
    def _fail_stream(r, instance_id: int) -> None:
        r.fail(f"instance {instance_id:x} deregistered (lease lost)",
               retryable=True, code="worker_lost")

    #: extra silent windows granted to a stream that has produced NO
    #: frames yet: a drain-accepted request mid-prefill (or mid-XLA-
    #: compile) legitimately emits nothing for a TTFT-scale interval,
    #: which one decode-scale window would misread as death. A stream
    #: that HAS streamed and goes silent is dead after one window.
    PRE_FIRST_FRAME_WINDOWS = 4

    async def _grace_break(self, instance_id: int, live: set) -> None:
        """Fail only streams with NO frame arrivals across a grace
        window: a draining worker's streams keep producing (and complete
        on their own); a dead worker's are silent since the kill. Streams
        still active keep being watched — a worker dying MID-drain must
        not leave them hanging forever (iteration cap is a backstop far
        above any drain timeout)."""
        marks = {r: (r.activity(), 0) for r in live}
        for _ in range(240):
            await asyncio.sleep(self._lost_grace)
            nxt = {}
            for r, (mark, silent) in marks.items():
                if r.on_done is None:
                    continue  # stream finished cleanly
                act = r.activity()
                if act != mark:
                    nxt[r] = (act, 0)  # producing: re-watch
                    continue
                silent += 1
                budget = (self.PRE_FIRST_FRAME_WINDOWS if act == 0 else 1)
                if silent >= budget:
                    self._fail_stream(r, instance_id)
                else:
                    nxt[r] = (mark, silent)
            marks = nxt
            if not marks:
                return

    async def start(self) -> "Client":
        self._watch = await self._runtime.plane.watch_prefix(self._prefix)
        for k, v in self._watch.snapshot.items():
            self._apply("put", k, v)
        self._ready.set()
        self._watch_task = asyncio.get_running_loop().create_task(self._watch_loop())
        return self

    async def stop(self):
        if self._watch_task:
            self._watch_task.cancel()
        if self._watch:
            await self._watch.cancel()

    async def _watch_loop(self):
        try:
            async for ev in self._watch:
                try:
                    self._apply(ev.type, ev.key, ev.value)
                except Exception:
                    # One bad instance value must not kill discovery.
                    logger.exception("ignoring malformed instance event for %s", ev.key)
        except asyncio.CancelledError:
            pass

    def _apply(self, typ: str, key: str, value: bytes):
        # key = instances/<ns>/<comp>/<ep>:<lease-hex>
        try:
            lease_hex = key.rsplit(":", 1)[1]
            iid = int(lease_hex, 16)
        except (IndexError, ValueError):
            return
        if typ == "put":
            d = msgpack.unpackb(value, raw=False)
            self._instances[iid] = Instance.from_wire(d)
            self._down.discard(iid)
            self._fail_streak.pop(iid, None)  # fresh registration: closed
            self._half_open.discard(iid)
        else:
            self._instances.pop(iid, None)
            self._down.discard(iid)
            self._fail_streak.pop(iid, None)
            self._half_open.discard(iid)
            # the authoritative death signal: break every stream still
            # flowing from this instance so migration starts NOW
            self._break_streams(iid)
        for fn in self._instance_listeners:
            try:
                fn("put" if typ == "put" else "delete", iid)
            except Exception:
                logger.exception("instance listener failed for %x", iid)

    def instance_ids(self) -> list[int]:
        return sorted(self._instances)

    def instances(self) -> list[Instance]:
        return [self._instances[i] for i in sorted(self._instances)]

    def instance(self, instance_id: int) -> Optional[Instance]:
        return self._instances.get(instance_id)

    def available_ids(self) -> list[int]:
        # the busy set may come from a SHARED monitor spanning several
        # models' clients — only ids this client actually owns count
        busy = self._busy & set(self._instances)
        ids = set(self._instances) - self._down - busy
        if not ids and busy:
            # every worker saturated: routing to a busy worker beats
            # NoResponders (the reference degrades the same way — busy is
            # backpressure, not failure)
            ids = set(self._instances) - self._down
        if not ids and self._instances:
            # every REGISTERED instance is marked down. Down-marking is a
            # soft signal (a blipped stream under fault injection marks a
            # perfectly live worker); lease loss is the authoritative death
            # signal and would have removed the instance entirely. Routing
            # to a down-but-registered instance as a last resort beats
            # leaving the fleet unreachable until a canary runs — a real
            # success then clears the mark (record_success).
            ids = set(self._instances)
        return sorted(ids)

    def set_busy_instances(self, instance_ids) -> None:
        """Replace the load-busy set (ref: worker_monitor.rs
        update_free_instances) — typically called by WorkerMonitor."""
        self._busy = set(instance_ids)

    def report_instance_down(self, instance_id: int):
        logger.warning("instance %x reported down", instance_id)
        self._down.add(instance_id)
        if instance_id in self._half_open:
            # trial traffic failed: reopen immediately, no fresh streak
            self._half_open.discard(instance_id)
            self._fail_streak[instance_id] = self._breaker_threshold
            logger.warning("instance %x circuit breaker RE-OPENED "
                           "(half-open trial failed)", instance_id)
            return
        streak = self._fail_streak.get(instance_id, 0) + 1
        self._fail_streak[instance_id] = streak
        if streak == self._breaker_threshold:
            logger.warning("instance %x circuit breaker OPEN "
                           "(%d consecutive failures)", instance_id, streak)

    def report_instance_up(self, instance_id: int):
        """Restore a previously-down instance to the routable set (the
        canary success path). An OPEN breaker only HALF-closes here: the
        instance takes trial traffic, but a single further failure reopens
        it; a real success (record_success) closes it."""
        if instance_id in self._down:
            logger.info("instance %x restored", instance_id)
        self._down.discard(instance_id)
        if self._fail_streak.get(instance_id, 0) >= self._breaker_threshold:
            self._half_open.add(instance_id)

    def record_success(self, instance_id: int):
        """Real traffic reached the instance: fully close its breaker and
        clear any stale down mark (self-healing without waiting for the
        canary when last-resort routing succeeded)."""
        self._fail_streak.pop(instance_id, None)
        self._half_open.discard(instance_id)
        self._down.discard(instance_id)

    def breaker_state(self, instance_id: int) -> str:
        """closed | half-open | open — for tests, metrics and dynctl."""
        if instance_id in self._half_open:
            return "half-open"
        if self._fail_streak.get(instance_id, 0) >= self._breaker_threshold:
            return "open"
        return "closed"

    async def start_health_checks(self, payload=None):
        """Start a canary health-check manager on this client, with cadence
        and threshold from the layered RuntimeConfig
        (``DYN_HEALTH_CHECK_INTERVAL`` / ``DYN_HEALTH_CHECK_FAILURES`` —
        ref: health_check.rs driven by DYN_* config). Returns the manager
        (caller stops it via ``await mgr.stop()``)."""
        from dynamo_tpu.runtime.health_check import (
            HealthCheckConfig, HealthCheckManager,
        )

        cfg = HealthCheckConfig.from_runtime(self._runtime.config, payload)
        return await HealthCheckManager(self, cfg).start()

    async def wait_for_instances(self, timeout: float = 30.0) -> list[int]:
        deadline = asyncio.get_running_loop().time() + timeout
        while True:
            ids = self.available_ids()
            if ids:
                return ids
            if asyncio.get_running_loop().time() > deadline:
                raise TimeoutError(f"no instances for {self._prefix}")
            await asyncio.sleep(0.05)

    # -- routing --
    def _pick(self, mode: str, instance_id: Optional[int]) -> Instance:
        if mode == "direct":
            assert instance_id is not None
            inst = self._instances.get(instance_id)
            if inst is None:
                raise NoRespondersError(f"instance {instance_id:x} not found")
            return inst
        ids = self.available_ids()
        if not ids:
            raise NoRespondersError(self._prefix)
        if mode == "random":
            return self._instances[random.choice(ids)]
        # round robin
        self._rr += 1
        return self._instances[ids[self._rr % len(ids)]]

    async def generate(
        self,
        request: Any,
        ctx: Optional[Context] = None,
        mode: str = "round_robin",
        instance_id: Optional[int] = None,
        retries: int = 1,
    ) -> ResponseReceiver:
        """Issue a request; returns a receiver over the response stream."""
        ctx = ctx or Context()
        if ctx.expired:
            raise DeadlineExceededError(
                "request deadline expired before dispatch")
        attempts = 0
        while True:
            inst = self._pick(mode, instance_id)
            try:
                return await self._generate_to(inst, request, ctx)
            except OverloadedError:
                # the worker is alive and SHED the request — not a failure
                # signal: don't mark it down / feed its breaker, just try
                # another instance while the budget lasts
                attempts += 1
                if mode == "direct" or attempts > retries:
                    raise
            except DeadlineExceededError:
                raise  # no instance can beat an expired clock
            except (NoRespondersError, StreamError):
                # StreamError here is pre-stream (ack failed / worker could
                # not open the response path) — safe to fail over, nothing
                # was generated yet.
                self.report_instance_down(inst.instance_id)
                attempts += 1
                if mode == "direct" or attempts > retries:
                    raise

    async def _generate_to(self, inst: Instance, request: Any, ctx: Context) -> ResponseReceiver:
        rt = self._runtime
        chaos = get_chaos()
        if chaos is not None:
            # request-dispatch hook: pre-stream, so failover is always safe
            try:
                await chaos.pre("request.dispatch")
                if chaos.should_drop("request.dispatch"):
                    raise ChaosError("injected drop at request.dispatch")
            except ChaosError as e:
                raise StreamError(f"chaos: {e}") from e
        local = rt._local_endpoints.get(inst.subject)
        if local is not None:
            handler, inflight, max_inflight = local
            # same admission/deadline contract as the remote ack path —
            # in-process short-circuiting must not bypass overload shedding
            if max_inflight and len(inflight) >= max_inflight:
                raise OverloadedError(
                    f"worker at capacity ({max_inflight} in flight)")
            if ctx.expired:
                raise DeadlineExceededError(
                    "request deadline expired before dispatch")
            info, receiver, queue = make_local_stream(ctx)
            sender = StreamSender.local(queue)
            task = asyncio.get_running_loop().create_task(
                _pump_handler(handler, request, ctx, sender)
            )
            inflight.add(task)
            task.add_done_callback(inflight.discard)
            self.record_success(inst.instance_id)
            self._track_stream(inst.instance_id, receiver)
            return receiver

        server = await rt.response_server()
        info, receiver = server.register_stream(ctx)
        ctx_wire = ctx.to_wire()
        envelope = msgpack.packb(
            {"ctx": ctx_wire, "conn": info.to_wire(), "req": request}
        )
        # record the wire hop's fresh span id as an rpc.send span so the
        # remote worker's spans (which parent to that id) stitch back here
        from dynamo_tpu.observability import get_tracer

        get_tracer().record_hop(ctx, ctx_wire.get("traceparent"),
                                target=inst.subject)
        try:
            ack = await rt.plane.request(inst.subject, envelope,
                                         timeout=rt.config.request_timeout)
        except NoRespondersError:
            server.abandon_stream(info)
            raise
        except StreamError:
            server.abandon_stream(info)
            raise
        except (asyncio.TimeoutError, TimeoutError, RuntimeError,
                ConnectionError) as e:
            # Dispatch-ack failure to a worker whose lease hasn't expired
            # yet (e.g. SIGKILL'd corpse still advertised): the hub's
            # forward times out and relays a generic error. This is
            # PRE-STREAM by construction — no token was produced — so
            # surface it as a retryable StreamError: generate()'s failover
            # marks the instance down and re-picks instead of bubbling a
            # client-visible 500.
            server.abandon_stream(info)
            raise StreamError(f"dispatch ack failed: {e!r}") from e
        except Exception:
            server.abandon_stream(info)
            raise
        resp = msgpack.unpackb(ack, raw=False)
        if not resp.get("ok"):
            server.abandon_stream(info)
            raise stream_error_from_wire(
                resp.get("error", STREAM_ERR_MSG), resp.get("code"),
                resp.get("retryable", True))
        self.record_success(inst.instance_id)
        self._track_stream(inst.instance_id, receiver)
        return receiver
