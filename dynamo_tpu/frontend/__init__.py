"""OpenAI-compatible HTTP frontend (rebuild of lib/llm/src/http/service/)."""

from dynamo_tpu.frontend.http import HttpService

__all__ = ["HttpService"]
