"""Test harness config: force JAX onto a virtual 8-device CPU mesh.

Multi-chip hardware is not available in CI; sharding logic is validated on a
virtual CPU mesh (the same pattern the driver's dryrun_multichip uses).

The container's sitecustomize imports jax at interpreter startup and pins the
real single TPU chip (JAX_PLATFORMS=axon), so env vars alone are too late —
we must override via jax.config before the first backend use.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("DYN_LOG", "warning")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def anyio_backend():
    return "asyncio"


# ---------------------------------------------------------------- test tiers
# Default `pytest tests/` = fast tier (< ~8 min): the slow tier
# (tests/slow_tier.txt — heavy sharding/parity variants with faster siblings)
# is deselected. DYN_TEST_FULL=1 runs everything (the pre-snapshot gate).
# Explicitly-named tests always run: `pytest tests/test_mla.py::x` works
# regardless of tier.

def _slow_tier() -> set:
    path = os.path.join(os.path.dirname(__file__), "slow_tier.txt")
    try:
        with open(path) as f:
            return {ln.strip() for ln in f
                    if ln.strip() and not ln.startswith("#")}
    except OSError:
        return set()


def pytest_collection_modifyitems(config, items):
    if os.environ.get("DYN_TEST_FULL"):
        return
    if any("::" in a for a in config.args):
        return  # explicit node selection overrides tiering
    slow = _slow_tier()
    # node ids are root-relative when run from the repo root; normalize so
    # `cd tests && pytest` keeps the same tier
    def in_slow(item):
        nid = item.nodeid
        return nid in slow or f"tests/{nid}" in slow

    dropped = [it for it in items if in_slow(it)]
    if dropped:
        config.hook.pytest_deselected(items=dropped)
        items[:] = [it for it in items if not in_slow(it)]


# ------------------------------------------------------------- chaos fixture
# Seeded fault injection (dynamo_tpu/runtime/chaos.py). Usage:
#
#     async def test_x(chaos):
#         inj = chaos("stream.send:drop=0.1;engine.step:error=0.05", seed=7)
#         ... drive the stack; assert inj.counts afterwards ...
#
# The injector is GLOBAL (the hooks live in hot paths); the fixture
# guarantees it is removed again so no other test inherits the faults.

@pytest.fixture
def chaos():
    from dynamo_tpu.runtime.chaos import configure_chaos

    def _install(spec: str, seed: int = 0):
        return configure_chaos(spec, seed=seed)

    try:
        yield _install
    finally:
        configure_chaos(None)
