"""Multimodal encode worker: media refs → prompt-embedding segments.

Rebuild of the reference's multimodal split (ref: the TRT-LLM backend's
multimodal encode helper + ``nixl_connect`` typed embedding transfer,
lib/bindings/python/src/dynamo/nixl_connect/__init__.py): a separate encode
component turns media references into embedding tensors; the LLM worker
fetches them over the response plane (the DCN analog of the NIXL read) and
injects them at the prompt's placeholder positions
(PreprocessedRequest.mm_embeds → engine/model.forward mm_vec/mm_mask).

The encoder itself is pluggable: production plugs a vision tower (a jitted
JAX ViT fits the ``encode(ref, n_tokens, dim)`` contract); the shipped
:class:`StubEncoder` is deterministic-from-ref, which is exactly what the
transfer/injection/caching machinery needs for tests — including the
prefix-cache property that the same image yields the same embeddings (and
therefore the same mm-salted block hashes).
"""

from __future__ import annotations

import hashlib
import logging
from typing import Optional

import numpy as np

logger = logging.getLogger("dynamo.multimodal")

ENCODE_COMPONENT = "encoder"


class StubEncoder:
    """Deterministic embeddings derived from the ref string (content-stable:
    same ref → same vectors, different refs → different vectors)."""

    def encode(self, ref: str, n_tokens: int, dim: int) -> np.ndarray:
        seed = int.from_bytes(hashlib.sha256(ref.encode()).digest()[:8],
                              "little")
        rng = np.random.default_rng(seed)
        return rng.standard_normal((n_tokens, dim), np.float32) * 0.02


class EncodeWorker:
    """Serves the ``encode`` endpoint on the encoder component: request
    {"refs": [str], "tokens": n, "dim": d} → one frame per ref
    {"ref", "embeds": [[...]]}."""

    def __init__(self, runtime, encoder=None, namespace: str = "dynamo"):
        self.runtime = runtime
        self.encoder = encoder or StubEncoder()
        self.namespace = namespace
        self._handle = None

    async def start(self) -> "EncodeWorker":
        ep = self.runtime.namespace(self.namespace).component(
            ENCODE_COMPONENT).endpoint("encode")
        self._handle = await ep.serve_endpoint(self._encode)
        return self

    async def _encode(self, request, ctx):
        import asyncio

        n = int(request.get("tokens", 16))
        dim = int(request.get("dim", 0))
        for ref in request.get("refs", []):
            emb = await asyncio.to_thread(self.encoder.encode, ref, n, dim)
            yield {"ref": ref, "embeds": [row.tolist() for row in emb]}

    async def stop(self, graceful: bool = False):
        if self._handle is not None:
            await self._handle.stop(graceful=graceful)


async def resolve_mm_refs(req, client, dim: int) -> None:
    """Fill ``req.mm_embeds`` from ``req.mm_refs`` by fetching embeddings
    from the encode component (in place; clears mm_refs). Duplicate refs
    are fetched once."""
    refs = req.mm_refs or []
    if not refs:
        return
    unique = sorted({seg["ref"] for seg in refs})
    tokens = max(int(seg.get("tokens", 16)) for seg in refs)
    recv = await client.generate({"refs": unique, "tokens": tokens,
                                  "dim": dim})
    by_ref: dict[str, list] = {}
    async for frame in recv:
        by_ref[frame["ref"]] = frame["embeds"]
    missing = [seg["ref"] for seg in refs if seg["ref"] not in by_ref]
    if missing:
        raise RuntimeError(f"encoder returned no embeddings for {missing}")
    req.mm_embeds = [
        {"start": int(seg["start"]),
         "embeds": by_ref[seg["ref"]][: int(seg.get("tokens", tokens))]}
        for seg in refs]
    req.mm_refs = None
