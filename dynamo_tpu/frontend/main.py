"""``python -m dynamo_tpu.frontend.main`` — run the OpenAI HTTP frontend.

Equivalent of ``python -m dynamo.frontend`` in the reference: joins the
control plane, watches model registrations, serves OpenAI HTTP with the chosen
routing mode.
"""

from __future__ import annotations

import argparse
import asyncio
import signal

from dynamo_tpu.frontend.http import HttpService
from dynamo_tpu.llm.discovery import ModelManager, ModelWatcher
from dynamo_tpu.router.protocols import KvRouterConfig
from dynamo_tpu.runtime import DistributedRuntime
from dynamo_tpu.runtime.config import setup_logging


async def amain():
    ap = argparse.ArgumentParser(description="dynamo-tpu OpenAI frontend")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=8000)
    ap.add_argument("--tls-cert-path", default=None,
                    help="serve HTTPS with this certificate chain (ref: "
                         "service_v2.rs enable_tls)")
    ap.add_argument("--tls-key-path", default=None)
    ap.add_argument("--admin-token", default=None,
                    help="bearer token required on destructive admin routes "
                         "(/clear_kv_blocks); also via DYN_ADMIN_TOKEN")
    ap.add_argument("--replica-id", default=None,
                    help="front-door replica identity (docs/robustness.md "
                         "'Front door'): registers frontends/<ns>/<id> "
                         "with drain-aware readiness and stamps a replica "
                         "label on every /metrics sample; also via "
                         "DYN_FRONTEND_REPLICA / DYN_POD_NAME. Unset = "
                         "classic single-frontend mode")
    ap.add_argument("--router-mode", choices=["kv", "round_robin", "random"], default="kv")
    ap.add_argument("--kv-overlap-score-weight", type=float, default=1.0)
    ap.add_argument("--router-temperature", type=float, default=0.0)
    ap.add_argument("--router-replica-sync", action="store_true",
                    help="broadcast routing decisions to other frontend "
                         "replicas (ref: sequence.rs:283-340)")
    ap.add_argument("--router-snapshot-threshold", type=int, default=10000,
                    help="radix snapshot to the object store every N events "
                         "(0 = off; ref: subscriber.rs:30-65)")
    ap.add_argument("--router-reset-states", action="store_true",
                    help="ignore any persisted radix snapshot on start")
    ap.add_argument("--transfer-cost-weight", type=float, default=1.0,
                    help="weight on the topology-costed KV-transfer term "
                         "of the routing logit (docs/disagg.md); active "
                         "only when the prefill pool publishes DYN_TOPO_* "
                         "locality labels. 0 = topology-blind")
    ap.add_argument("--prefill-component", default="prefill",
                    help="component whose instances are the KV source "
                         "pool for the transfer term ('' disables the "
                         "pool watch)")
    ap.add_argument("--grpc-port", type=int, default=0,
                    help="also serve the KServe gRPC frontend on this port "
                         "(0 = disabled; ref: grpc/service/kserve.rs:31)")
    args = ap.parse_args()

    runtime = await DistributedRuntime.create()
    manager = ModelManager()
    watcher = await ModelWatcher(
        runtime,
        manager,
        router_mode=args.router_mode,
        kv_router_config=KvRouterConfig(
            overlap_score_weight=args.kv_overlap_score_weight,
            router_temperature=args.router_temperature,
            router_replica_sync=args.router_replica_sync,
            router_snapshot_threshold=args.router_snapshot_threshold or None,
            router_reset_states=args.router_reset_states,
            transfer_cost_weight=args.transfer_cost_weight,
            prefill_component=args.prefill_component,
        ),
    ).start()
    service = HttpService(manager, host=args.host, port=args.port,
                          tls_cert_path=args.tls_cert_path,
                          tls_key_path=args.tls_key_path,
                          runtime=runtime, replica=args.replica_id)
    if args.admin_token:
        service.admin_token = args.admin_token
    await service.start()
    # register this process's span buffer so `dynctl trace` sees the
    # frontend-side phases (http.request / tokenize / route / ttft / itl)
    from dynamo_tpu.observability import ensure_trace_endpoint

    await ensure_trace_endpoint(runtime)
    grpc_service = None
    if args.grpc_port:
        from dynamo_tpu.frontend.grpc import KserveGrpcService

        grpc_service = KserveGrpcService(manager, host=args.host,
                                         port=args.grpc_port)
        await grpc_service.start()
    print(f"FRONTEND_READY port={service.port}", flush=True)

    loop = asyncio.get_running_loop()
    stop = asyncio.Event()
    got_sig: dict = {}

    def on_sig(s):
        got_sig["sig"] = s
        stop.set()

    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, on_sig, sig)
    await stop.wait()
    if got_sig.get("sig") == signal.SIGTERM:
        # graceful drain (bounded by DYN_DRAIN_TIMEOUT via RuntimeConfig):
        # stop admitting — new requests get 503 + Retry-After and /health
        # flips to draining so load balancers pull this replica — then let
        # in-flight streams finish. Ctrl-C (SIGINT) skips the drain: an
        # operator at the keyboard wants the process gone now.
        await service.drain(runtime.config.drain_timeout)
    await service.stop()
    if grpc_service is not None:
        await grpc_service.stop()
    await watcher.stop()
    await runtime.shutdown()


def main():
    setup_logging()
    asyncio.run(amain())


if __name__ == "__main__":
    main()
