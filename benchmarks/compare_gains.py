"""Compare a bench-gains.json against the checked-in bench-baseline.json.

CI runs the mocker-based gains phases every push and uploads the JSON as
an artifact (``.github/workflows/tier1.yml``); this script turns that
record into an INFORMATIONAL per-PR annotation stream: the perf
trajectory the attribution layer explains (docs/observability.md) is
itself tracked, but a regression annotates the run rather than failing it
— the tier-1 test step stays the only gate.

Comparison heuristics (documented because they ARE the contract):

- booleans and ``*_ok`` / ``*_identical`` / ``*_tagged`` keys: a
  true→false flip is a regression (a gate the bench itself computes).
- ``*tok_s`` (throughput): lower is worse; annotate past ``--tolerance``.
- ``*_ms`` / ``*_seconds`` (latency): higher is worse, same tolerance.
- every other shared numeric key: drifted values are listed in the
  summary but carry no direction (a ratio can legitimately move either
  way between rounds).

Exit code is 0 unless ``--strict`` is passed (then regressions exit 1).
Output lines use GitHub workflow commands (``::warning``/``::notice``)
so they surface as annotations; a markdown table lands in
``$GITHUB_STEP_SUMMARY`` when set.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _flatten(obj, prefix="") -> dict:
    out: dict = {}
    if isinstance(obj, dict):
        for k, v in obj.items():
            out.update(_flatten(v, f"{prefix}.{k}" if prefix else str(k)))
    elif isinstance(obj, (bool, int, float)):
        out[prefix] = obj
    return out


def _direction(key: str):
    """'up'-is-good, 'down'-is-good, or None (no direction). Generic
    ``_frac`` keys carry no direction (a fraction can name coverage OR
    cost); only the specific cost/coverage fractions the bench emits are
    classified — e.g. ``flight_overhead_frac`` is lower-is-better and a
    blanket up-is-good rule would invert its regression detection."""
    leaf = key.rsplit(".", 1)[-1]
    if leaf.endswith("tok_s") or leaf.endswith("within_5pct_frac"):
        return "up"
    if (leaf.endswith(("_ms", "_seconds", "_s"))
            or "overhead" in leaf or "residual" in leaf):
        return "down"
    return None


def compare(baseline: dict, current: dict, tolerance: float) -> tuple[list, list]:
    """→ (regressions, drifts): lists of human-readable lines."""
    base = _flatten(baseline.get("extra") or baseline)
    cur = _flatten(current.get("extra") or current)
    regressions, drifts = [], []
    for key in sorted(set(base) & set(cur)):
        b, c = base[key], cur[key]
        if isinstance(b, bool) or isinstance(c, bool):
            if bool(b) and not bool(c):
                regressions.append(f"{key}: gate flipped true → false")
            continue
        if not b:
            continue  # zero/absent baseline: no ratio to compare
        ratio = c / b
        d = _direction(key)
        if d == "up" and ratio < 1.0 - tolerance:
            regressions.append(
                f"{key}: {b:g} → {c:g} ({(1 - ratio) * 100:.0f}% worse)")
        elif d == "down" and ratio > 1.0 + tolerance:
            regressions.append(
                f"{key}: {b:g} → {c:g} ({(ratio - 1) * 100:.0f}% worse)")
        elif d is None and abs(ratio - 1.0) > tolerance:
            drifts.append(f"{key}: {b:g} → {c:g}")
    # phase-granular presence accounting: a whole bench phase appearing
    # (a new subsystem's phase lands before the baseline refresh) or
    # disappearing (phase skipped this run) must collapse to ONE line per
    # phase, not a warning per key — only keys missing from phases BOTH
    # sides ran are per-key news
    def phase(key: str) -> str:
        return key.split(".", 1)[0]

    base_phases = {phase(k) for k in base}
    cur_phases = {phase(k) for k in cur}
    new_phases = sorted(cur_phases - base_phases)
    if new_phases:
        drifts.append(f"phase(s) not in baseline yet (refresh it): "
                      f"{new_phases}")
    for p in sorted(base_phases - cur_phases):
        drifts.append(f"baseline phase '{p}' absent from this run")
    missing = sorted(k for k in set(base) - set(cur)
                     if phase(k) in cur_phases)
    if missing:
        drifts.append(f"{len(missing)} baseline keys absent from this run "
                      f"(first: {missing[:3]})")
    return regressions, drifts


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--baseline", default="bench-baseline.json")
    ap.add_argument("--current", default="bench-gains.json")
    ap.add_argument("--tolerance", type=float, default=0.30,
                    help="relative change annotated as regression/drift "
                         "(default 0.30 — shared-CI-runner noise on "
                         "sub-second mocker phases is large)")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 on regressions (default: informational)")
    args = ap.parse_args()

    def load(path):
        try:
            with open(path) as f:
                return json.loads(f.read().strip().splitlines()[-1])
        except (OSError, ValueError, IndexError) as e:
            print(f"::notice::compare_gains: cannot read {path} ({e}); "
                  "skipping comparison")
            return None

    baseline, current = load(args.baseline), load(args.current)
    if baseline is None or current is None:
        return 0
    regressions, drifts = compare(baseline, current, args.tolerance)
    for line in regressions:
        print(f"::warning title=bench regression vs baseline::{line}")
    for line in drifts:
        print(f"::notice title=bench drift::{line}")
    if not regressions:
        print(f"::notice::bench gains: no regressions vs "
              f"{args.baseline} (tolerance {args.tolerance:.0%}, "
              f"{len(drifts)} undirected drifts)")
    summary_path = os.environ.get("GITHUB_STEP_SUMMARY")
    if summary_path:
        with open(summary_path, "a") as f:
            f.write("## Bench gains vs baseline\n\n")
            f.write(f"- regressions: **{len(regressions)}**, drifts: "
                    f"{len(drifts)} (tolerance {args.tolerance:.0%})\n")
            for line in regressions:
                f.write(f"- ⚠️ {line}\n")
            for line in drifts[:20]:
                f.write(f"- {line}\n")
    return 1 if (args.strict and regressions) else 0


if __name__ == "__main__":
    sys.exit(main())
