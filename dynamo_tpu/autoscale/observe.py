"""Observation feed for the autoscaler: frontend scrapes ⊕ worker metrics.

The planner's historical feed (``planner/prometheus.py``) sees only the
frontend's edge counters — rates and mean latencies of *completed*
requests. That signal goes blind exactly when scaling matters most: under
saturation, requests queue instead of completing, and the completion-rate
"demand" estimate reads LOW while the real demand is piling up in worker
queues. This module fuses two feeds into one :class:`FusedObservation`:

- **frontend** (``PrometheusMetricsSource``): request rate, ISL/OSL, mean
  TTFT/ITL — the proactive signal the ``SeasonalPredictor``/
  ``ArimaPredictor`` forecast from — plus per-QoS-class TTFT p95 estimated
  from the ``dynamo_http_ttft_class_seconds`` histogram deltas (the SLO
  compliance signal);
- **workers** (``ForwardPassMetrics`` over the control plane, the same
  subject the KV router consumes): waiting+swapped depth and slot
  occupancy — the reactive signal that sees saturation the edge cannot.

Either feed may fail a tick without breaking the loop: a dead frontend
scrape still yields worker depth (reactive scaling keeps working), and a
quiet metrics subject still yields edge rates.
"""

from __future__ import annotations

import logging
import re
from dataclasses import dataclass, field
from typing import Optional

from dynamo_tpu.planner.planner_core import Observation
from dynamo_tpu.planner.prometheus import _LINE

logger = logging.getLogger("dynamo.autoscale")

_LABEL = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')

#: frontend histogram family carrying per-class TTFT (frontend/http.py)
TTFT_CLASS_METRIC = "dynamo_http_ttft_class_seconds"
#: frontend gauges from the latency-attribution layer
#: (docs/observability.md "Attribution"): rolling error-budget burn per
#: class, and the EWMA compile share of breached requests' TTFT
BURN_RATE_METRIC = "dynamo_slo_burn_rate"
BREACH_COMPILE_METRIC = "dynamo_slo_breach_compile_share"


def parse_class_ttft_buckets(text: str) -> dict[str, dict[float, float]]:
    """``{qos_class: {le_upper_bound: cumulative_count}}`` from one
    /metrics exposition (``+Inf`` maps to ``float('inf')``).

    Duplicate (class, bound) samples — the replica-labeled series of a
    multi-frontend scrape (``MultiPrometheusSource.last_text``) — are
    SUMMED: cumulative histogram counts across replicas add, so the fleet
    p95 is computed over all replicas' traffic rather than whichever
    replica's line parsed last."""
    out: dict[str, dict[float, float]] = {}
    prefix = TTFT_CLASS_METRIC + "_bucket"
    for line in text.splitlines():
        if not line.startswith(prefix):
            continue
        m = _LINE.match(line.strip())
        if not m or m.group(1) != prefix:
            continue
        labels = dict(_LABEL.findall(m.group(2) or ""))
        le, cls = labels.get("le"), labels.get("qos")
        if le is None or cls is None:
            continue
        try:
            bound = float("inf") if le == "+Inf" else float(le)
            d = out.setdefault(cls, {})
            d[bound] = d.get(bound, 0.0) + float(m.group(3))
        except ValueError:
            continue
    return out


def histogram_p95(delta: dict[float, float]) -> Optional[float]:
    """p95 (seconds) from per-bucket cumulative-count deltas — the shared
    estimator (observability/stats.py histogram_quantile; one
    implementation serves this tracker, the flight summaries and the bench
    percentiles, so the three can never drift apart). None when the
    interval recorded nothing."""
    from dynamo_tpu.observability.stats import histogram_quantile

    return histogram_quantile(delta, 0.95)


def parse_gauge_by_class(text: Optional[str], metric: str
                         ) -> dict[str, float]:
    """``{class: value}`` for one ``<metric>{class="..."} v`` gauge family
    out of a /metrics exposition (the frontend's burn-rate and
    breach-cause signals ride the same scrape the TTFT tracker reads).

    Duplicate class samples (replica-labeled series of a multi-frontend
    scrape) take the MAX — burn rate and breach share are worst-case
    signals, and summing gauges across replicas would fabricate burn no
    single replica observed."""
    out: dict[str, float] = {}
    if not text:
        return out
    for line in text.splitlines():
        if not line.startswith(metric):
            continue
        m = _LINE.match(line.strip())
        if not m or m.group(1) != metric:
            continue
        labels = dict(_LABEL.findall(m.group(2) or ""))
        cls = labels.get("class") or labels.get("qos")
        if cls is None:
            continue
        try:
            v = float(m.group(3))
        except ValueError:
            continue
        out[cls] = max(out[cls], v) if cls in out else v
    return out


class ClassTtftTracker:
    """Interval p95 per QoS class from successive /metrics scrapes."""

    def __init__(self):
        self._prev: Optional[dict[str, dict[float, float]]] = None

    def feed(self, text: Optional[str]) -> dict[str, float]:
        """→ ``{class: ttft_p95_ms}`` for the classes that completed first
        tokens this interval. A counter reset (frontend restart) SKIPS the
        class for one interval and rebases — clamping per-bucket deltas
        at 0 is not enough, because post-restart traffic can push high
        buckets past their pre-restart counts while low buckets stay
        under, shape-skewing the delta toward a false SLO breach."""
        if not text:
            return {}
        cur = parse_class_ttft_buckets(text)
        prev, self._prev = self._prev, cur
        if prev is None:
            return {}
        out: dict[str, float] = {}
        for cls, buckets in cur.items():
            pb = prev.get(cls, {})
            if any(c < pb.get(b, 0.0) for b, c in buckets.items()):
                continue  # reset: rebase on the fresh counters
            delta = {b: c - pb.get(b, 0.0) for b, c in buckets.items()}
            p95 = histogram_p95(delta)
            if p95 is not None:
                out[cls] = round(p95 * 1000.0, 3)
        return out


@dataclass
class FusedObservation:
    """One controller tick's fused view of the system."""

    #: edge-traffic sample for the predictors; None when the frontend
    #: scrape failed or the interval was idle
    observation: Optional[Observation] = None
    #: waiting+swapped sequences across the worker fleet (ForwardPassMetrics
    #: num_requests_waiting — includes swapped since PR 4)
    queue_depth: int = 0
    active_slots: int = 0
    total_slots: int = 0
    #: workers currently reporting metrics
    workers: int = 0
    #: per-QoS-class TTFT p95 (ms) over the scrape interval
    ttft_p95_ms: dict = field(default_factory=dict)
    #: rolling SLO burn rate per class (frontend attribution layer;
    #: empty when the frontend predates the signal or is idle)
    slo_burn: dict = field(default_factory=dict)
    #: EWMA compile share of breached requests' TTFT per class — the
    #: compile-cliff-vs-load discriminator for the breach term
    breach_compile_share: dict = field(default_factory=dict)
    #: True when the frontend scrape itself failed this tick (vs idle)
    frontend_down: bool = False


class ObservationFuser:
    """async () -> FusedObservation over a frontend source + worker feed.

    ``frontend_source`` is any ``async () -> Observation|None`` (usually
    :class:`~dynamo_tpu.planner.prometheus.PrometheusMetricsSource`; its
    ``last_text`` attribute, when present, feeds the per-class p95
    tracker). ``aggregator`` is a started
    :class:`~dynamo_tpu.router.publisher.MetricsAggregator` (or anything
    with ``.aggregate() -> dict``); None runs edge-only.
    """

    def __init__(self, frontend_source, aggregator=None):
        self.frontend = frontend_source
        self.aggregator = aggregator
        self.ttft_tracker = ClassTtftTracker()
        self.scrape_failures = 0
        self.ticks = 0

    async def __call__(self) -> FusedObservation:
        self.ticks += 1
        obs: Optional[Observation] = None
        frontend_down = False
        # PrometheusMetricsSource swallows its own fetch errors (returns
        # None) and counts them internally — fold that counter in, or a
        # dead frontend reads as "0 scrape failures" in the status view
        before = getattr(self.frontend, "scrape_failures", 0)
        try:
            obs = await self.frontend()
            failed = getattr(self.frontend, "scrape_failures", 0) - before
            if failed > 0:
                frontend_down = True
                self.scrape_failures += failed
        except Exception:
            # a scrape failure must not kill the loop: the reactive
            # (worker-depth) half still scales the fleet
            logger.warning("frontend observation failed", exc_info=True)
            frontend_down = True
            self.scrape_failures += 1
        fused = FusedObservation(observation=obs, frontend_down=frontend_down)
        text = getattr(self.frontend, "last_text", None)
        fused.ttft_p95_ms = self.ttft_tracker.feed(text)
        fused.slo_burn = parse_gauge_by_class(text, BURN_RATE_METRIC)
        fused.breach_compile_share = parse_gauge_by_class(
            text, BREACH_COMPILE_METRIC)
        if self.aggregator is not None:
            try:
                agg = self.aggregator.aggregate()
                fused.queue_depth = int(agg.get("requests_waiting", 0))
                fused.active_slots = int(agg.get("requests_active", 0))
                fused.workers = int(agg.get("workers", 0))
                fused.total_slots = int(agg.get("total_slots", 0) or 0)
            except Exception:
                logger.warning("worker metrics aggregation failed",
                               exc_info=True)
        if obs is not None:
            # thread the fleet-depth signal into the planner's Observation
            # so corrections and (future) demand terms can see it
            obs.queue_depth = fused.queue_depth
            # the burn-rate signal rides the Observation too: the planner's
            # corrections/demand terms see error-budget consumption, not
            # just point-in-time latency (docs/autoscaling.md)
            obs.slo_burn = dict(fused.slo_burn)
        return fused
