"""Sampling penalties: presence/frequency (OpenAI) + repetition (nvext/HF).

Ref surface: the reference's sampling options carry all three through to
its engines (lib/llm/src/protocols/common.rs; nvext repetition_penalty in
lib/async-openai/src/types/nvext.rs) — here they are applied as sparse
logit edits in AsyncJaxEngine._sample.
"""

import asyncio

import numpy as np
import pytest

from dynamo_tpu.engine.config import EngineArgs, ModelConfig
from dynamo_tpu.engine.engine import AsyncJaxEngine, _has_penalties
from dynamo_tpu.engine.scheduler import SeqState
from dynamo_tpu.protocols import PreprocessedRequest, SamplingOptions


def _req(tokens, **sampling):
    return PreprocessedRequest(
        model="tiny", token_ids=list(tokens),
        sampling_options=SamplingOptions(temperature=0.0, **sampling))


def _seq(req, tokens, prompt_len):
    s = SeqState(request_id="r0", req=req, ctx=None, sink=None)
    s.tokens = list(tokens)
    s.prompt_len = prompt_len
    return s


@pytest.fixture(scope="module")
def engine():
    eng = AsyncJaxEngine(ModelConfig.tiny(), EngineArgs(
        block_size=16, num_blocks=64, max_num_seqs=4,
        max_num_batched_tokens=128, max_model_len=128))
    yield eng
    asyncio.run(eng.close())


def _sample_one(engine, seq, logits_row):
    logits = np.asarray([logits_row], np.float32)
    toks, _, _ = asyncio.run(engine._sample([seq], logits))
    return int(toks[0])


def test_no_penalty_is_plain_argmax(engine):
    seq = _seq(_req([1, 2]), [1, 2, 3], prompt_len=2)
    assert _sample_one(engine, seq, [0.0, 1.0, 2.0, 5.0, 0.0]) == 3
    assert not _has_penalties(seq)


def test_presence_penalty_demotes_generated_tokens(engine):
    # token 3 was generated (prompt_len=2, tokens=[1,2,3]); presence=4
    # drops its logit 5.0 -> 1.0 ([0,1,2,5,0] -> [0,1,2,1,0]), so argmax
    # moves to token 2
    seq = _seq(_req([1, 2], presence_penalty=4.0), [1, 2, 3], prompt_len=2)
    assert _has_penalties(seq)
    assert _sample_one(engine, seq, [0.0, 1.0, 2.0, 5.0, 0.0]) == 2


def test_presence_ignores_prompt_tokens(engine):
    # token 3 is in the PROMPT, nothing generated yet — OpenAI presence
    # penalty counts only generated text, so argmax is unchanged
    seq = _seq(_req([1, 2, 3], presence_penalty=4.0), [1, 2, 3], prompt_len=3)
    assert _sample_one(engine, seq, [0.0, 1.0, 2.0, 5.0, 0.0]) == 3


def test_frequency_penalty_scales_with_count(engine):
    # token 3 generated twice: 5.0 - 2*2.0 = 1.0 < 2.0 -> argmax 2
    seq = _seq(_req([1], frequency_penalty=2.0), [1, 3, 3], prompt_len=1)
    assert _sample_one(engine, seq, [0.0, 1.0, 2.0, 5.0, 0.0]) == 2
    # generated once: 5.0 - 2.0 = 3.0 still wins
    seq = _seq(_req([1], frequency_penalty=2.0), [1, 3], prompt_len=1)
    assert _sample_one(engine, seq, [0.0, 1.0, 2.0, 5.0, 0.0]) == 3


def test_repetition_penalty_hf_semantics(engine):
    # HF: over prompt+generated; logit>0 -> /p, logit<0 -> *p
    # tokens seen: {1, 3}. row [-1, 4, 2.5, 6, 0], p=3:
    #   token 1: 4/3 = 1.33, token 3: 6/3 = 2.0 -> argmax token 2 (2.5)
    seq = _seq(_req([1, 3], repetition_penalty=3.0), [1, 3], prompt_len=2)
    assert _has_penalties(seq)
    assert _sample_one(engine, seq, [-1.0, 4.0, 2.5, 6.0, 0.0]) == 2
    # negative logits get MORE negative: token 0 at -1 -> -3
    seq = _seq(_req([0], repetition_penalty=3.0), [0], prompt_len=1)
    r = _sample_one(engine, seq, [-1.0, -2.5, -9.0, -9.0, -9.0])
    assert r == 1  # -2.5 now beats -3.0


def test_repetition_one_is_neutral(engine):
    seq = _seq(_req([3], repetition_penalty=1.0), [3], prompt_len=1)
    assert not _has_penalties(seq)
    assert _sample_one(engine, seq, [0.0, 1.0, 2.0, 5.0, 0.0]) == 3


def test_penalties_compose_with_logit_bias(engine):
    # bias +10 on token 0 outweighs everything; presence demotes token 3
    seq = _seq(_req([1], presence_penalty=4.0, logit_bias={0: 10.0}),
               [1, 3], prompt_len=1)
    assert _sample_one(engine, seq, [0.0, 1.0, 2.0, 5.0, 0.0]) == 0


@pytest.mark.anyio
async def test_e2e_presence_penalty_forbids_repeats():
    """Greedy decode on random weights repeats tokens; an overwhelming
    presence penalty must make every generated token distinct — and the
    request must NOT take the fused burst path (which can't apply it)."""
    from dynamo_tpu.protocols import StopConditions
    from dynamo_tpu.runtime.context import Context

    cfg = ModelConfig.tiny()
    eng = AsyncJaxEngine(cfg, EngineArgs(
        block_size=16, num_blocks=64, max_num_seqs=4,
        max_num_batched_tokens=128, max_model_len=128,
        multi_step_decode=4))
    try:
        async def run(penalty):
            req = PreprocessedRequest(
                model="tiny", token_ids=[1, 2, 3, 4],
                sampling_options=SamplingOptions(
                    temperature=0.0, presence_penalty=penalty),
                stop_conditions=StopConditions(max_tokens=12, ignore_eos=True))
            out = []
            async for o in eng.generate(req, Context()):
                out.extend(o.token_ids)
            return out

        toks = await run(100.0)
        assert len(toks) == 12
        assert len(set(toks)) == len(toks), f"repeats under penalty: {toks}"
        base = await run(0.0)
        assert len(set(base)) < len(base), "tiny greedy model should repeat"
    finally:
        await eng.close()
