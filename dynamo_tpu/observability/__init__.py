"""Distributed request tracing + request-lifecycle SLO metrics.

Span/Tracer recorder keyed by the runtime's existing W3C trace ids
(tracing.py), cross-process stitching over the control plane (collector.py),
and the env-gated jax.profiler correlation hook (profiler.py).
See docs/observability.md.
"""

from dynamo_tpu.observability.tracing import (
    CURRENT_SPAN,
    Span,
    Tracer,
    configure_tracer,
    get_tracer,
    parse_traceparent,
    stitch,
)
from dynamo_tpu.observability.collector import (
    TRACER_PREFIX,
    ensure_trace_endpoint,
    fetch_trace,
    serve_traces,
)

__all__ = [
    "CURRENT_SPAN", "Span", "Tracer", "configure_tracer", "get_tracer",
    "parse_traceparent", "stitch", "TRACER_PREFIX",
    "ensure_trace_endpoint", "fetch_trace", "serve_traces",
]
