"""Disagg worker handlers: decode-first conditional disaggregation.

Mirrors the reference's decode/prefill handler pair (ref:
components/backends/vllm/src/dynamo/vllm/handlers.py:89-250): the decode
worker receives the routed request; when a prefill fleet exists and the
prompt is long enough (DisaggConfig.max_local_prefill_length), it issues a
max_tokens=1 prefill request round-robin to the prefill component, receives
the first token + KV bundle, injects the pages into its own cache, and
decodes. Prefill worker downtime degrades gracefully to local prefill.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Optional

from dynamo_tpu.disagg.protocols import (
    DisaggConfig, KvChunkFrame, PrefillResponse,
)
from dynamo_tpu.observability import get_tracer
from dynamo_tpu.protocols import (FinishReason, LLMEngineOutput,
                                  PreprocessedRequest)
from dynamo_tpu.runtime.control_plane import NoRespondersError

logger = logging.getLogger("dynamo.disagg")

#: request annotation by which a decode worker advertises that it can
#: consume mid-prefill KvChunkFrames (pipelined transfer)
KV_CHUNKS_ANNOTATION = "kv_chunks"


class PrefillWorkerHandler:
    """Serves the prefill component's ``generate`` endpoint.

    Streams KvChunkFrame wires while prefill is still computing (pipelined
    transfer), then the final PrefillResponse with the tail pages.
    """

    def __init__(self, engine):
        self.engine = engine

    async def generate(self, request: dict, ctx):
        req = PreprocessedRequest.from_wire(request)
        # capability negotiation: chunk frames only when the decode side
        # asked for them — an older decode worker that parses the first
        # frame as PrefillResponse keeps working (whole-bundle path)
        if KV_CHUNKS_ANNOTATION in (req.annotations or []):
            async for frame in self.engine.prefill_extract_stream(req, ctx):
                yield frame
        else:
            resp = await self.engine.prefill_extract(req, ctx)
            yield resp.to_wire()


class DisaggConfigWatcher:
    """Watches the conditional-disagg threshold in the control-plane KV
    store and updates a DisaggConfig live (ref: disagg_router.rs:26-80 —
    the reference watches etcd for DisaggRouterConf changes at runtime).

    Write ``DisaggConfig.KEY`` with an integer payload to retune the
    local-vs-remote prefill decision without restarting decode workers.
    """

    def __init__(self, plane, config: DisaggConfig):
        self.plane = plane
        self.config = config
        self._watch = None
        self._task = None

    async def start(self) -> "DisaggConfigWatcher":
        self._watch = await self.plane.watch_prefix(DisaggConfig.KEY)
        for _k, v in self._watch.snapshot.items():
            self._apply(v)
        self._task = asyncio.get_running_loop().create_task(self._loop())
        return self

    async def stop(self):
        if self._task:
            self._task.cancel()
        if self._watch:
            await self._watch.cancel()

    def _apply(self, value: bytes):
        try:
            n = int(value.decode())
        except (ValueError, AttributeError):
            logger.warning("ignoring bad disagg threshold payload %r", value)
            return
        if n != self.config.max_local_prefill_length:
            logger.info("disagg max_local_prefill_length: %d -> %d",
                        self.config.max_local_prefill_length, n)
            self.config.max_local_prefill_length = n

    async def _loop(self):
        try:
            async for ev in self._watch:
                if ev.type == "put":
                    self._apply(ev.value)
        except asyncio.CancelledError:
            pass


class DecodeWorkerHandler:
    """Serves the decode (or aggregated) component's ``generate`` endpoint.

    ``prefill_client`` is a runtime Client bound to the prefill component's
    generate endpoint, or None for pure aggregated serving.
    """

    def __init__(self, engine, prefill_client=None,
                 config: Optional[DisaggConfig] = None, prefill_queue=None,
                 mm_client=None):
        self.engine = engine
        self.prefill_client = prefill_client
        self.config = config or DisaggConfig()
        #: optional PrefillQueueClient: queued dispatch with claim/fallback
        self.prefill_queue = prefill_queue
        #: optional encode-component Client: resolves mm_refs → mm_embeds
        #: before generation (the nixl_connect embedding-read analog)
        self.mm_client = mm_client

    def _use_remote_prefill(self, req: PreprocessedRequest) -> bool:
        if self.prefill_client is None:
            return False
        if not self.prefill_client.available_ids():
            return False  # no prefill workers up: serve locally (elastic xPyD)
        return len(req.token_ids) > self.config.max_local_prefill_length

    async def generate(self, request: dict, ctx):
        req = PreprocessedRequest.from_wire(request)
        if req.mm_refs:
            if self.mm_client is None:
                yield LLMEngineOutput(
                    finish_reason=FinishReason.ERROR,
                    text="request carries multimodal content but no encoder "
                         "component is configured (--mm-encode)").to_wire()
                return
            from dynamo_tpu.multimodal import resolve_mm_refs

            try:
                await resolve_mm_refs(req, self.mm_client,
                                      self.engine.cfg.hidden_size)
            except Exception as e:  # same graceful surface as no-encoder
                yield LLMEngineOutput(
                    finish_reason=FinishReason.ERROR,
                    text=f"multimodal encode failed: {e}").to_wire()
                return
        if self._use_remote_prefill(req):
            yielded = False
            try:
                async for out in self._generate_disagg(req, ctx):
                    yielded = True
                    yield out
                return
            except Exception:
                if yielded:  # mid-stream failure: surface, don't duplicate
                    raise
                logger.exception("remote prefill failed; falling back local")
        async for out in self.engine.generate(req, ctx):
            yield out.to_wire()

    async def _generate_disagg(self, req: PreprocessedRequest, ctx):
        import dataclasses

        logger.debug("remote prefill: %d prompt tokens → prefill fleet",
                     len(req.token_ids))
        caps = [KV_CHUNKS_ANNOTATION]
        direct_cap = getattr(self.engine, "direct_capability", lambda: None)()
        if direct_cap:
            caps.append(direct_cap)
        preq = dataclasses.replace(
            req, annotations=list(req.annotations or []) + caps)
        instance_id = None
        if self.prefill_queue is not None:
            instance_id = await self.prefill_queue.acquire(ctx)
            if (instance_id is not None
                    and instance_id not in self.prefill_client.available_ids()):
                # claim raced ahead of discovery, or the claimant just died
                logger.warning("claimed prefill instance %x not routable; "
                               "falling back to round robin", instance_id)
                instance_id = None
        stream = None
        # pass ctx so the prefill hop keeps the request's trace identity —
        # a fresh Context here would land every prefill-side span
        # (worker.handle / prefill.extract / kv.direct_pull) in a
        # disconnected trace invisible to /v1/traces/{request_id}
        if instance_id is not None:
            try:
                stream = await self.prefill_client.generate(
                    preq.to_wire(), ctx=ctx, mode="direct",
                    instance_id=instance_id)
            except NoRespondersError:
                logger.warning("claimed prefill instance %x unreachable; "
                               "falling back to round robin", instance_id)
        if stream is None:  # no queue, claim timeout, or dead claimant
            stream = await self.prefill_client.generate(
                preq.to_wire(), ctx=ctx, mode="round_robin")
        eng = self.engine
        bs = eng.args.block_size
        total = (len(req.token_ids) + bs - 1) // bs
        ids = None  # decode-side blocks, allocated on the first chunk frame
        placed = True  # False → recompute locally after draining the stream
        next_block = 0
        presp = None
        owned = False  # ids ownership not yet transferred to a sequence
        t_xfer0 = time.time()  # remote-prefill stream + KV placement phase
        try:
            from dynamo_tpu.disagg.transfer import KvDirectFrame, pull_bundle

            async for frame in stream:
                if KvChunkFrame.is_wire(frame) or KvDirectFrame.is_wire(frame):
                    if not placed:
                        # keep draining: the final frame has the token. Drop
                        # unclaimed same-process offers now instead of
                        # pinning gathered pages until the TTL sweep
                        if (KvDirectFrame.is_wire(frame)
                                and eng.direct_transfer is not None):
                            eng.direct_transfer.retract(
                                KvDirectFrame.from_wire(frame).desc)
                        continue
                    if KvDirectFrame.is_wire(frame):
                        try:
                            # device-to-device pull (disagg/transfer.py) —
                            # the descriptor frame carries no page bytes
                            ch = pull_bundle(eng.direct_transfer,
                                             KvDirectFrame.from_wire(frame))
                        except Exception:
                            logger.exception("direct KV pull failed; will "
                                             "recompute prefill locally")
                            placed = False
                            continue
                    else:
                        ch = KvChunkFrame.from_wire(frame).bundle
                    n = ch.num_blocks
                    if (not eng.check_bundle_dims(ch)
                            or ch.start_block != next_block
                            or ch.start_block + n > total):
                        placed = False
                        continue
                    if ids is None:
                        ids = eng.alloc_inject(total)
                        if ids is None:
                            placed = False
                            continue
                        owned = True
                    try:
                        eng.scatter_chunk(
                            ids[ch.start_block:ch.start_block + n], ch.k, ch.v)
                        next_block += n
                    except Exception:
                        logger.exception("KV chunk scatter failed")
                        placed = False
                else:
                    presp = PrefillResponse.from_wire(frame)
            if presp is None:
                raise RuntimeError("prefill worker returned no response")
            # per-tier transfer timing as a first-class signal (KV-cache
            # survey): covers the prefill stream + chunk scatters
            get_tracer().record(
                "kv.transfer", ctx, start=t_xfer0, end=time.time(),
                service="disagg", blocks_placed=next_block,
                total_blocks=total, placed=placed,
                direct=self.engine.direct_transfer is not None
                if hasattr(self.engine, "direct_transfer") else False)

            if presp.token_id < 0 or not placed:
                if owned:
                    owned = False
                    eng.release_inject(ids)
                async for out in eng.generate(req, ctx):
                    yield out.to_wire()
                return

            if ids is None:
                # no chunk frames arrived: the whole-bundle (unpipelined) path
                async for out in eng.generate_injected(req, presp, ctx):
                    yield out.to_wire()
                return

            tail = presp.bundle
            if tail is not None:
                n = tail.num_blocks
                if (eng.check_bundle_dims(tail)
                        and tail.start_block == next_block
                        and tail.start_block + n <= total):
                    try:
                        eng.scatter_chunk(
                            ids[tail.start_block:tail.start_block + n],
                            tail.k, tail.v)
                        next_block += n
                    except Exception:
                        logger.exception("KV tail scatter failed")
                        placed = False
                else:
                    placed = False
            if not placed or next_block < total:
                owned = False
                eng.release_inject(ids)
                async for out in eng.generate(req, ctx):
                    yield out.to_wire()
                return
            owned = False  # ownership transfers to the sequence
            async for out in eng.generate_prefilled(req, presp.token_id,
                                                    presp.logprob, ids, ctx):
                yield out.to_wire()
        finally:
            # exception/cancellation escape hatch: injected blocks must never
            # leak when the stream dies after alloc_inject
            if owned and ids is not None:
                eng.release_inject(ids)
