"""Deploy/control layer: operator reconciler + Kubernetes connector +
recipes (ref: deploy/cloud/operator, components/planner k8s connector)."""
