"""``python -m dynamo_tpu.metrics.main`` — cluster metrics aggregator.

Rebuild of the reference's metrics component (ref: components/metrics/src/
main.rs:1-251): subscribes to worker ForwardPassMetrics and KV events,
aggregates load/capacity + KV-hit-rate, and exposes them as Prometheus
gauges on ``/metrics`` for dashboards and the planner.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import signal

import msgpack
from aiohttp import web

from dynamo_tpu.router.protocols import KV_EVENTS_STREAM
from dynamo_tpu.router.publisher import MetricsAggregator
from dynamo_tpu.runtime import DistributedRuntime
from dynamo_tpu.runtime.config import setup_logging

logger = logging.getLogger("dynamo.metrics")


class MetricsService:
    def __init__(self, runtime: DistributedRuntime):
        self.runtime = runtime
        self.agg = MetricsAggregator(runtime.plane)
        self.kv_stored = 0
        self.kv_removed = 0
        self._kv_task = None
        self._kv_sub = None

    async def start(self):
        await self.agg.start()
        self._kv_sub = await self.runtime.plane.stream_subscribe(KV_EVENTS_STREAM)

        async def kv_loop():
            try:
                async for _seq, payload in self._kv_sub:
                    try:
                        ev = msgpack.unpackb(payload, raw=False)
                        data = ev.get("event") or {}
                        if "stored" in data:
                            self.kv_stored += len(
                                data["stored"].get("blocks") or [])
                        elif "removed" in data:
                            self.kv_removed += len(
                                data["removed"].get("block_hashes") or [])
                    except Exception:
                        logger.exception("bad kv event ignored")
            except asyncio.CancelledError:
                pass

        self._kv_task = asyncio.get_running_loop().create_task(kv_loop())
        return self

    async def stop(self):
        if self._kv_task:
            self._kv_task.cancel()
        if self._kv_sub:
            await self._kv_sub.cancel()
        await self.agg.stop()

    async def sample_queue_depth(self) -> int:
        """Current global prefill-queue backlog (planner scaling signal).
        A slow/absent control plane must not break the whole /metrics
        endpoint — local gauges still serve; depth reads 0."""
        from dynamo_tpu.disagg.queue import prefill_queue_depth

        try:
            # sums the QoS class-split queues — the split must not hide
            # backlog from the planner (docs/disagg.md)
            return await asyncio.wait_for(
                prefill_queue_depth(self.runtime.plane), 2.0)
        except Exception:
            logger.warning("prefill queue depth unavailable; reporting 0")
            return 0

    async def sample_hub_stats(self):
        """The hub's self-instrumentation, when the plane exposes it (the
        dynctl hub and the in-process plane both do); None on failure —
        /metrics must keep serving through a hub hiccup."""
        plane = self.runtime.plane
        if not hasattr(plane, "hub_stats"):
            return None
        try:
            return await asyncio.wait_for(plane.hub_stats(), 2.0)
        except Exception:
            logger.warning("hub stats unavailable")
            return None

    def render(self, prefill_queue_depth: int = 0, hub: dict = None) -> str:
        a = self.agg.aggregate()
        lines = []

        def metric(name, value, help_, type_):
            lines.append(f"# HELP dynamo_{name} {help_}")
            lines.append(f"# TYPE dynamo_{name} {type_}")
            lines.append(f"dynamo_{name} {value}")

        def gauge(name, value, help_):
            metric(name, value, help_, "gauge")

        def counter(name, value, help_):
            # monotonically increasing series: advertising them as gauges
            # breaks every rate()/increase() query downstream
            metric(name, value, help_, "counter")

        gauge("workers", a["workers"], "live workers reporting metrics")
        gauge("kv_active_blocks", a["kv_active_blocks"], "in-use KV blocks")
        gauge("kv_total_blocks", a["kv_total_blocks"], "total KV blocks")
        gauge("kv_cache_usage_perc", a["gpu_cache_usage_perc"],
              "cluster KV usage fraction")
        gauge("requests_active", a["requests_active"], "in-flight requests")
        gauge("requests_waiting", a["requests_waiting"], "queued requests")
        counter("kv_blocks_stored_total", self.kv_stored,
                "KV stored events observed")
        counter("kv_blocks_removed_total", self.kv_removed,
                "KV removed events observed")
        gauge("prefill_queue_depth", prefill_queue_depth,
              "tickets waiting in the global prefill queue")
        if hub:
            # hub event-path instrumentation (docs/observability.md): the
            # fleet-bench batching ceiling (docs/PERF_NOTES.md) as live
            # series instead of a one-off bench note
            lines.append("# HELP dynamo_hub_events_total control-plane "
                         "ops handled by the hub, by kind")
            lines.append("# TYPE dynamo_hub_events_total counter")
            for kind, v in sorted((hub.get("events") or {}).items()):
                lines.append(f'dynamo_hub_events_total{{kind="{kind}"}} {v}')
            pub = hub.get("publish_seconds") or {}
            lines.append("# HELP dynamo_hub_publish_seconds hub event "
                         "fan-out latency (publish + stream_publish)")
            lines.append("# TYPE dynamo_hub_publish_seconds histogram")
            for le, cum in (pub.get("buckets") or {}).items():
                lines.append(
                    f'dynamo_hub_publish_seconds_bucket{{le="{le}"}} {cum}')
            lines.append(f"dynamo_hub_publish_seconds_sum "
                         f"{pub.get('sum', 0.0)}")
            lines.append(f"dynamo_hub_publish_seconds_count "
                         f"{pub.get('count', 0)}")
        return "\n".join(lines) + "\n"


async def amain():
    ap = argparse.ArgumentParser(description="dynamo-tpu metrics aggregator")
    ap.add_argument("--port", type=int, default=9091)
    cli = ap.parse_args()

    runtime = await DistributedRuntime.create()
    svc = await MetricsService(runtime).start()

    async def metrics(_req):
        depth = await svc.sample_queue_depth()
        hub = await svc.sample_hub_stats()
        return web.Response(text=svc.render(depth, hub=hub),
                            content_type="text/plain")

    app = web.Application()
    app.router.add_get("/metrics", metrics)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "0.0.0.0", cli.port)
    await site.start()
    print(f"metrics aggregator on :{cli.port}", flush=True)

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    await svc.stop()
    await runner.cleanup()
    await runtime.shutdown()


def main():
    setup_logging()
    asyncio.run(amain())


if __name__ == "__main__":
    main()
