"""Optional ``jax.profiler`` correlation hook (env-gated).

With ``DYN_JAX_PROFILER=1`` the engine wraps each jitted step dispatch in a
``jax.profiler.TraceAnnotation``, so device traces captured with
``jax.profiler.start_trace`` carry the serving-layer phase names
(``dynamo.prefill_step`` / ``dynamo.decode_step``) and line up with the
request spans recorded by the tracer. Off by default: the annotation is a
per-dispatch host-side cost the steady-state serving loop should not pay.
"""

from __future__ import annotations

import contextlib
import os

_enabled: bool | None = None


def enabled() -> bool:
    """Gate, computed once per process (the engine loop is hot)."""
    global _enabled
    if _enabled is None:
        _enabled = os.environ.get(
            "DYN_JAX_PROFILER", "").lower() not in ("", "0", "false")
    return _enabled


def _reset_for_tests() -> None:
    global _enabled
    _enabled = None


@contextlib.contextmanager
def annotate(name: str):
    """``with annotate("dynamo.decode_step"): <dispatch>`` — no-op unless
    DYN_JAX_PROFILER is set and jax's profiler is importable."""
    if not enabled():
        yield
        return
    try:
        from jax.profiler import TraceAnnotation
    except Exception:  # jax absent/old: gating must never break serving
        yield
        return
    with TraceAnnotation(name):
        yield
