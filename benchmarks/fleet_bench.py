"""Hub ceiling under a fleet-shaped load — VERDICT r3 next-step #5: "benchmark
the hub at a 100-mocker fleet ... a published hub-ceiling number."

Simulates what N workers actually put on the dynctl hub during serving
(each over its own TCP connection, like a real fleet):

- KV events: chained stored + removed publishes to the ``kv_events`` stream
  (the router feed — the highest-rate producer in a real deployment);
- metrics: ForwardPassMetrics pub/sub at a fixed cadence per worker;
- discovery heartbeats: lease keepalives.

One KvIndexer consumes the event stream concurrently (the router's actual
code path, radix apply included). Reported:

- ``events_per_s``: aggregate stored/removed publishes the hub sustained;
- ``indexer_lag_events``: how far the router's single consumer task was
  behind at the end (0 = the router keeps up at this fleet size);
- ``indexer_applied_per_s``: radix apply throughput.

Usage: python -m benchmarks.fleet_bench [--workers 100] [--seconds 5]
Prints one JSON line.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import time

from dynamo_tpu.router.indexer import KvIndexer
from dynamo_tpu.router.protocols import ForwardPassMetrics, KvStats, StoredBlock, WorkerStats
from dynamo_tpu.router.publisher import KvEventPublisher, WorkerMetricsPublisher
from dynamo_tpu.runtime.control_plane import ControlPlaneServer, RemoteControlPlane

BLOCK_SIZE = 16
#: blocks announced per stored event. The engine now batches per REQUEST
#: by default (scheduler.commit_computed; DYN_KV_EVENT_PER_CHUNK=1 restores
#: per-chunk), so production traffic looks like --chain 125 (an ISL-2000
#: prefill). 8 = the old per-128-token-chunk behavior, kept as the default
#: here so the CONSERVATIVE ceiling stays on record; pass --chain 125 for
#: the deployed shape (docs/PERF_NOTES.md has both measurements).
CHAIN = 8


async def _worker_load(i: int, plane, stop_at: float, counts: list[int],
                       stored_counts: list[int], chain: int = CHAIN):
    """One worker's steady-state hub traffic: publish a stored chain, later
    remove it (LRU churn), heartbeat the lease, publish metrics."""
    kv = KvEventPublisher(plane, worker_id=i + 1, kv_block_size=BLOCK_SIZE)
    metrics = WorkerMetricsPublisher(plane, worker_id=i + 1)
    lease = await plane.lease_create(ttl=5.0)
    base = (i + 1) << 32
    gen = 0
    while time.perf_counter() < stop_at:
        hashes = [base + gen * chain + j for j in range(chain)]
        await kv.publish_stored(None, [
            StoredBlock(block_hash=h, tokens_hash=h) for h in hashes])
        counts[i] += 1
        stored_counts[i] += 1
        if gen % 4 == 3:  # evict an older chain: 3:1 store:remove mix
            old = [base + (gen - 3) * chain + j for j in range(chain)]
            await kv.publish_removed(old)
            counts[i] += 1
        if gen % 8 == 0:
            await metrics.publish(ForwardPassMetrics(
                worker_stats=WorkerStats(request_active_slots=4, request_total_slots=64),
                kv_stats=KvStats(kv_active_blocks=chain * 4, kv_total_blocks=1024,
                                 gpu_cache_usage_perc=0.1)))
            await plane.lease_keepalive(lease)
        gen += 1


async def amain():
    ap = argparse.ArgumentParser(description="fleet-shaped hub ceiling bench")
    ap.add_argument("--workers", type=int, default=100)
    ap.add_argument("--seconds", type=float, default=5.0)
    ap.add_argument("--chain", type=int, default=CHAIN,
                    help="blocks per stored event (publish batching)")
    cli = ap.parse_args()

    server = ControlPlaneServer(port=0)
    addr = await server.start()
    planes = [await RemoteControlPlane(addr).connect() for _ in range(cli.workers)]
    router_plane = await RemoteControlPlane(addr).connect()
    indexer = await KvIndexer(router_plane, kv_block_size=BLOCK_SIZE).start()

    counts = [0] * cli.workers
    stored_counts = [0] * cli.workers
    t0 = time.perf_counter()
    stop_at = t0 + cli.seconds
    await asyncio.gather(*(
        _worker_load(i, p, stop_at, counts, stored_counts, cli.chain)
        for i, p in enumerate(planes)))
    dt = time.perf_counter() - t0

    published = sum(counts)
    stored = sum(stored_counts)
    last = await router_plane.stream_last_seq("kv_events")
    lag = last - indexer._last_seq
    # give the consumer a moment to drain, then measure apply throughput
    drain_t0 = time.perf_counter()
    while indexer._last_seq < last and time.perf_counter() - drain_t0 < 10:
        await asyncio.sleep(0.05)
    out = {
        "workers": cli.workers,
        "events_per_s": round(published / dt, 1),
        "stored_blocks_per_s": round(stored * cli.chain / dt, 1),
        "removed_blocks_per_s": round((published - stored) * cli.chain / dt, 1),
        "chain": cli.chain,
        "indexer_lag_events": int(lag),
        "indexer_applied": indexer.events_applied,
        "indexer_applied_per_s": round(
            indexer.events_applied / (time.perf_counter() - t0), 1),
        "gaps_detected": indexer.gaps_detected,
        "seconds": round(dt, 3),
    }
    await indexer.stop()
    for p in planes + [router_plane]:
        await p.close()
    await server.stop()
    print(json.dumps(out))


if __name__ == "__main__":
    asyncio.run(amain())
