/* C ABI for external engines: KV-event publishing into the dynamo-tpu
 * router (ref surface: lib/bindings/c/src/lib.rs:40-326).
 *
 * Link against libdynamo_native.so (python -m dynamo_tpu.native_build).
 * All functions return 0 on success, non-zero on error (details on stderr).
 */
#ifndef DYNAMO_LLM_H
#define DYNAMO_LLM_H

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

/* Connect to the control plane ("host:port"; NULL reads DYN_CONTROL_PLANE)
 * and create the process-wide KV publisher. ns/component are accepted for
 * parity with the reference ABI; events are attributed by worker_id. */
int dynamo_llm_init(const char* addr, const char* ns, const char* component,
                    uint64_t worker_id, uint32_t kv_block_size);

int dynamo_llm_shutdown(void);

/* Publish KV-stored: token_ids is the flat token array; num_block_tokens[i]
 * (each == kv_block_size) describes how token_ids splits into blocks;
 * block_ids are the blocks' external identities; parent_hash may be NULL
 * (no parent). lora_id accepted for ABI parity, ignored. */
int dynamo_kv_event_publish_stored(uint64_t event_id,
                                   const uint32_t* token_ids,
                                   const size_t* num_block_tokens,
                                   const uint64_t* block_ids,
                                   size_t num_blocks,
                                   const uint64_t* parent_hash,
                                   uint64_t lora_id);

int dynamo_kv_event_publish_removed(uint64_t event_id,
                                    const uint64_t* block_ids,
                                    size_t num_blocks);

#ifdef __cplusplus
}
#endif

#endif /* DYNAMO_LLM_H */
