"""models/ registry + MoE engine path (EP-shardable token-choice experts)."""

import asyncio

import pytest

from dynamo_tpu import models
from dynamo_tpu.engine.config import EngineArgs
from dynamo_tpu.engine.engine import AsyncJaxEngine
from dynamo_tpu.protocols import (
    PreprocessedRequest, SamplingOptions, StopConditions,
)

pytestmark = pytest.mark.anyio


def test_presets_resolve():
    for name in models.PRESETS:
        cfg = models.get_model_config(name)
        assert cfg.num_layers > 0 and cfg.vocab_size > 0
    with pytest.raises(KeyError):
        models.get_model_config("nope")


def test_unsupported_arch_fails_loudly():
    with pytest.raises(NotImplementedError):
        models.from_hf_config(
            {"architectures": ["MambaForCausalLM"], "vocab_size": 100})


def test_deepseek_arch_now_supported():
    """DeepSeek graduated from the UNSUPPORTED map in round 2 (MLA)."""
    cfg = models.from_hf_config({
        "architectures": ["DeepseekV3ForCausalLM"], "vocab_size": 100,
        "kv_lora_rank": 512, "q_lora_rank": 1536, "n_routed_experts": 256,
        "n_shared_experts": 1, "first_k_dense_replace": 3,
        "norm_topk_prob": True, "routed_scaling_factor": 2.5,
        "n_group": 8, "topk_group": 4, "moe_intermediate_size": 2048,
    })
    assert cfg.is_mla and cfg.scoring_func == "sigmoid"
    assert cfg.n_group == 8 and cfg.n_shared_experts == 1


def test_hf_mapping_round_trip():
    cfg = models.from_hf_config({
        "architectures": ["MixtralForCausalLM"], "vocab_size": 32000,
        "hidden_size": 4096, "intermediate_size": 14336,
        "num_hidden_layers": 32, "num_attention_heads": 32,
        "num_key_value_heads": 8, "num_local_experts": 8,
        "num_experts_per_tok": 2,
    })
    assert cfg.is_moe and cfg.num_experts == 8


async def test_moe_engine_generates_deterministically():
    cfg = models.get_model_config("moe_tiny")
    args = EngineArgs(block_size=4, num_blocks=64, max_num_seqs=4,
                      max_num_batched_tokens=64, max_model_len=128,
                      prefill_buckets=(8, 16, 32, 64),
                      decode_batch_buckets=(1, 2, 4))
    req = PreprocessedRequest(
        model="moe", token_ids=list(range(1, 18)),
        stop_conditions=StopConditions(max_tokens=6, ignore_eos=True),
        sampling_options=SamplingOptions())

    async def run():
        eng = AsyncJaxEngine(cfg, args)
        toks = []
        async for out in eng.generate(req):
            toks.extend(out.token_ids)
        await eng.close()
        return toks

    t1, t2 = await run(), await run()
    assert t1 == t2 and len(t1) == 6


def test_moe_ep_matches_dense_einsum():
    """The shard_map EP dispatch (capacity-bounded one-hot + psum) must
    reproduce the dense all-experts formulation when capacity is ample."""
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from dynamo_tpu.engine import model as M
    from dynamo_tpu.engine.config import ModelConfig
    from dynamo_tpu.parallel import MeshConfig, make_mesh

    cfg = ModelConfig(vocab_size=64, hidden_size=16, intermediate_size=32,
                      num_layers=1, num_heads=2, num_kv_heads=2,
                      num_experts=4, num_experts_per_tok=2, dtype="float32",
                      moe_capacity_factor=100.0)  # no drops → exact
    key = jax.random.key(0)
    B, S, D = 2, 8, cfg.hidden_size
    E, F = cfg.num_experts, cfg.intermediate_size
    ks = jax.random.split(key, 5)
    x = jax.random.normal(ks[0], (B, S, D), jnp.float32)
    lp = {
        "router": jax.random.normal(ks[1], (D, E)) * 0.5,
        "router_bias": jnp.zeros((E,), jnp.float32),
        "w_gate": jax.random.normal(ks[2], (E, D, F)) / np.sqrt(D),
        "w_up": jax.random.normal(ks[3], (E, D, F)) / np.sqrt(D),
        "w_down": jax.random.normal(ks[4], (E, F, D)) / np.sqrt(F),
    }
    want = M._mlp_moe(x, lp, cfg)

    mesh = make_mesh(MeshConfig(dp=1, sp=1, tp=2))
    fn = M.make_moe_ep_fn(cfg, mesh)  # the production wiring
    got = fn(x, lp["router"], lp["router_bias"], lp["w_gate"], lp["w_up"],
             lp["w_down"])
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_moe_ep_capacity_bounds_flops():
    """With a tight capacity factor, dispatch shapes are bounded by
    N*K/E-scale capacity, not by N (the structural FLOPs claim)."""
    from dynamo_tpu.engine.model import moe_capacity

    # at scale the average-load formula dominates: C << N
    assert moe_capacity(1024, 64, 2, 2.0) == 64
    assert moe_capacity(4096, 64, 2, 2.0) == 256
    assert moe_capacity(16, 8, 2, 100.0) == 16  # clamped at N (no drops)
    # decode-sized batches run dropless (floor at min(N, 16)): a C=1-2
    # capacity would silently drop colliding expert assignments
    assert moe_capacity(4, 64, 1, 1.0) == 4
    assert moe_capacity(16, 8, 2, 2.0) == 16


@pytest.mark.slow
async def test_moe_engine_on_mesh_matches_single_device():
    """Greedy MoE generation through the engine on a tp=2 mesh (EP path)
    equals the single-device run when capacity is ample."""
    import dataclasses

    import jax

    from dynamo_tpu.engine import model as M
    from dynamo_tpu.parallel import MeshConfig, make_mesh

    cfg = dataclasses.replace(models.get_model_config("moe_tiny"),
                              moe_capacity_factor=100.0)
    params = M.init_params(cfg, jax.random.key(0))
    args = EngineArgs(block_size=4, num_blocks=64, max_num_seqs=4,
                      max_num_batched_tokens=64, max_model_len=128,
                      prefill_buckets=(8, 16, 32, 64),
                      decode_batch_buckets=(1, 2, 4))
    req = PreprocessedRequest(
        model="moe", token_ids=list(range(1, 30)),
        stop_conditions=StopConditions(max_tokens=6, ignore_eos=True),
        sampling_options=SamplingOptions(temperature=0.0))

    async def run(mesh):
        eng = AsyncJaxEngine(cfg, args, params=params, mesh=mesh)
        toks = []
        async for out in eng.generate(req):
            toks.extend(out.token_ids)
        await eng.close()
        return toks

    base = await run(None)
    ep = await run(make_mesh(MeshConfig(dp=1, sp=1, tp=2)))
    assert ep == base


def test_moe_ep_indivisible_batch_falls_back():
    """B not divisible by dp must fall back to the dense path at trace
    time, not crash the shard_map (review regression)."""
    import functools

    import jax
    import jax.numpy as jnp

    from dynamo_tpu.engine import model as M
    from dynamo_tpu.parallel import MeshConfig, make_mesh

    cfg = models.get_model_config("moe_tiny")
    mesh = make_mesh(MeshConfig(dp=2, sp=1, tp=2))
    params = M.init_params(cfg, jax.random.key(0), dtype=jnp.float32)
    bs, nb = 4, 16
    kshape = (cfg.num_layers, nb * bs, cfg.num_kv_heads, cfg.head_dim)
    kc = jnp.zeros(kshape, jnp.float32)
    vc = jnp.zeros(kshape, jnp.float32)
    B, S, W = 1, 4, 2  # B=1 with dp=2 → indivisible
    step = jax.jit(functools.partial(M.forward, cfg=cfg, block_size=bs,
                                     mesh=mesh))
    logits, _, _ = step(
        params, jnp.zeros((B, S), jnp.int32),
        jnp.tile(jnp.arange(S, dtype=jnp.int32), (B, 1)),
        jnp.arange(B * S, dtype=jnp.int32).reshape(B, S) + bs,
        jnp.ones((B, W), jnp.int32), jnp.full((B,), S, jnp.int32),
        jnp.full((B,), S - 1, jnp.int32), kc, vc)
    assert logits.shape == (B, cfg.vocab_size)


def test_moe_ep_skew_invariance_and_structure():
    """Hot-expert skew must NOT change outputs when capacity can hold the
    worst case (cf >= E/K), the dispatch must be all-to-all (token-sharded),
    and capacity overflow must COUNT drops instead of silently changing
    numerics (round-2 verdict #4 / weak #3)."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from dynamo_tpu.engine import model as M
    from dynamo_tpu.engine.config import ModelConfig
    from dynamo_tpu.parallel import MeshConfig, make_mesh

    cfg = ModelConfig(vocab_size=64, hidden_size=16, intermediate_size=32,
                      num_layers=1, num_heads=2, num_kv_heads=2,
                      num_experts=4, num_experts_per_tok=2, dtype="float32",
                      moe_capacity_factor=2.0)  # E/K = 2 → dropless
    key = jax.random.key(3)
    # N_loc = 4*64/4 shards = 64 local tokens: past the dropless floor, so
    # the tight-capacity arm below really drops
    B, S, D = 4, 64, cfg.hidden_size
    E, F = cfg.num_experts, cfg.intermediate_size
    ks = jax.random.split(key, 5)
    x = jax.random.normal(ks[0], (B, S, D), jnp.float32)
    lp = {
        # heavy bias on expert 0: EVERY token routes its top-1 there
        "router": jax.random.normal(ks[1], (D, E)) * 0.05,
        "router_bias": jnp.asarray([8.0, 0.0, 0.0, 0.0], jnp.float32),
        "w_gate": jax.random.normal(ks[2], (E, D, F)) / np.sqrt(D),
        "w_up": jax.random.normal(ks[3], (E, D, F)) / np.sqrt(D),
        "w_down": jax.random.normal(ks[4], (E, F, D)) / np.sqrt(F),
    }
    cfg_biased = dataclasses.replace(cfg, router_logit_bias=True)
    want = M._mlp_moe(x, lp, cfg_biased)

    mesh = make_mesh(MeshConfig(dp=2, sp=1, tp=2))
    fn = M.make_moe_ep_fn(cfg_biased, mesh)
    args = (x, lp["router"], lp["router_bias"], lp["w_gate"], lp["w_up"],
            lp["w_down"])
    got = fn(*args)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)

    # structural claim: the dispatch is an all-to-all exchange, not a
    # replicated-tokens psum (no all-reduce in the compiled module)
    hlo = jax.jit(fn).lower(*args).compile().as_text()
    assert "all-to-all" in hlo
    assert "all-reduce" not in hlo

    # tight capacity + skew → drops are COUNTED, not silent
    M.MOE_DROPS["total"] = 0
    cfg_tight = dataclasses.replace(cfg_biased, moe_capacity_factor=0.26)
    got_t = M.make_moe_ep_fn(cfg_tight, mesh)(*args)
    jax.effects_barrier()
    assert M.MOE_DROPS["total"] > 0
    # and with drops the output really differs (that is WHY they count)
    assert not np.allclose(np.asarray(got_t), np.asarray(want), atol=1e-5)


def test_moe_ep_quantized_experts_shard_through():
    """QTensor expert stacks pass the shard_map boundary whole and
    dequantize inside the shard — output equals the dense path on the
    dequantized weights."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dynamo_tpu.engine import model as M
    from dynamo_tpu.engine import quant as Q
    from dynamo_tpu.engine.config import ModelConfig
    from dynamo_tpu.parallel import MeshConfig, make_mesh

    cfg = ModelConfig(vocab_size=64, hidden_size=16, intermediate_size=32,
                      num_layers=1, num_heads=2, num_kv_heads=2,
                      num_experts=4, num_experts_per_tok=2, dtype="float32",
                      moe_capacity_factor=100.0)
    key = jax.random.key(7)
    B, S, D = 2, 8, cfg.hidden_size
    E, F = cfg.num_experts, cfg.intermediate_size
    ks = jax.random.split(key, 5)
    x = jax.random.normal(ks[0], (B, S, D), jnp.float32)
    router = jax.random.normal(ks[1], (D, E)) * 0.5
    rbias = jnp.zeros((E,), jnp.float32)
    wq = {n: Q.quantize(jax.random.normal(k, sh) / np.sqrt(sh[-2]),
                        bits=8, group=16)
          for n, k, sh in [("w_gate", ks[2], (E, D, F)),
                           ("w_up", ks[3], (E, D, F)),
                           ("w_down", ks[4], (E, F, D))]}
    lp_deq = {"router": router, "router_bias": rbias,
              **{n: Q.dequantize(v, jnp.float32) for n, v in wq.items()}}
    want = M._mlp_moe(x, lp_deq, cfg)

    mesh = make_mesh(MeshConfig(dp=1, sp=1, tp=2))
    got = M.make_moe_ep_fn(cfg, mesh)(
        x, router, rbias, wq["w_gate"], wq["w_up"], wq["w_down"])
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)
