"""``python -m dynamo_tpu.runtime.dynctl`` — run the control-plane server.

Single self-contained process replacing the reference's etcd + NATS pair for
TPU-VM deployments. Point every other process at it with
``DYN_CONTROL_PLANE=host:port``.
"""

from __future__ import annotations

import argparse
import asyncio

from dynamo_tpu.runtime.config import setup_logging
from dynamo_tpu.runtime.control_plane import ControlPlaneServer


async def amain(host: str, port: int):
    server = ControlPlaneServer(host, port)
    addr = await server.start()
    print(f"dynctl listening on {addr}", flush=True)
    try:
        await asyncio.Event().wait()
    finally:
        await server.stop()


def main():
    setup_logging()
    ap = argparse.ArgumentParser(description="dynamo-tpu control plane server")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=6650)
    args = ap.parse_args()
    asyncio.run(amain(args.host, args.port))


if __name__ == "__main__":
    main()
