"""Pallas TPU flash-attention kernel for chunked prefill over paged KV.

The round-1 XLA prefill path materialized an O(S·T) f32 score tensor through
HBM — at the BASELINE workload (ISL 8192: S=2048 chunk, T=8192 kv) that is
~2 GB per layer and blows both memory and TTFT. This kernel computes the
same attention with an online softmax so scores never leave VMEM.

Design (TPU-first, not a CUDA translation):
- The paged gather K/V [B,T,KV,hd] is left to XLA — at bf16 it is ~2·T·KV·hd
  bytes (tens of MB), a fused dynamic-gather XLA does well; the quadratic
  score tensor was the problem, not the gather.
- Grid (B, KV, S/TQ, T/TK), innermost axis = k-tiles. Online-softmax state
  (m, l, acc) lives in VMEM scratch which persists across grid steps on
  TPU; it is initialized at tk==0 and the output tile written at the last
  k-tile. Query tiles are processed per KV-head group so the MXU matmul is
  [G·TQ, hd] × [hd, TK] with zero wasted FLOPs (contrast: the decode
  kernel's block-expanded q, fine there because decode is DMA-bound).
- Causality is pure index math: chunked-prefill rows are consecutive
  positions (engine/_run_prefill), so q_pos = pos_base[b] + tq·TQ + row,
  key_pos = tk·TK + col; tiles entirely in the future are skipped.
- Sliding-window masking (mistral) supported via the same index math.

Contract (matches engine/model._paged_attention for one layer):
  q        [B, S, H, hd]
  k, v     [B, T, KV, hd]   (gathered pages, logically ordered)
  pos_base [B] int32        (absolute position of each row's first token)
  kv_lens  [B] int32        (valid kv length incl. the current chunk)
  → out    [B, S, H, hd]

ref parity: this stands in for the engine-side fused prefill attention the
reference delegates to vLLM (components/backends/vllm); SURVEY §7 names it
a "hard part" of the TPU build.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

_NEG = -1e30


def _prefill_kernel(pos_base_ref, kv_lens_ref, window_ref,  # scalar prefetch
                    q_ref,  # [1, 1, G, TQ, hd] VMEM
                    sink_ref,  # [1, 1, G, 1] VMEM (zeros when has_sink=False)
                    k_ref, v_ref,  # [1, 1, TK, hd] VMEM
                    o_ref,  # [1, 1, G, TQ, hd] VMEM
                    m_sc, l_sc, acc_sc,  # [G·TQ, 1], [G·TQ, 1], [G·TQ, hd]
                    *, scale: float, has_sink: bool):
    from jax.experimental import pallas as pl

    b = pl.program_id(0)
    tq = pl.program_id(2)
    tk = pl.program_id(3)
    n_tk = pl.num_programs(3)

    # hd (score width, = k width) and hdv (value/output width) may differ:
    # MLA attends in latent space where K carries the rope tail V lacks
    G, TQ, hd = q_ref.shape[2], q_ref.shape[3], q_ref.shape[4]
    hdv = v_ref.shape[3]
    TK = k_ref.shape[2]
    kv_len = kv_lens_ref[b]
    pos0 = pos_base_ref[b]
    # sliding window as a traced scalar: static for mistral, a per-layer
    # value for gpt-oss; 0 = full attention
    win = window_ref[0]

    @pl.when(tk == 0)
    def _init():
        if has_sink:
            # seed the online softmax with the sink slot (zero value):
            # row r of the [G·TQ] flattening belongs to head g = r // TQ
            s = sink_ref[0, 0].astype(jnp.float32)  # [G, 1]
            m_sc[...] = jnp.repeat(s, TQ, axis=0)
            l_sc[...] = jnp.ones_like(l_sc)
        else:
            m_sc[...] = jnp.full_like(m_sc, _NEG)
            l_sc[...] = jnp.zeros_like(l_sc)
        acc_sc[...] = jnp.zeros_like(acc_sc)

    k_start = tk * TK
    q_hi = pos0 + tq * TQ + TQ - 1  # highest query position in this tile
    # tile is live unless entirely in the future, past kv_len, or (window)
    # entirely before every query's window
    live = (k_start <= q_hi) & (k_start < kv_len)
    q_lo = pos0 + tq * TQ
    live = live & ((win <= 0) | (k_start + TK - 1 > q_lo - win))

    # f32 inputs (CPU parity tests) need full-precision MXU passes; bf16
    # serving inputs take the native single-pass MXU path
    prec = (jax.lax.Precision.HIGHEST
            if q_ref.dtype == jnp.float32 else None)

    @pl.when(live)
    def _body():
        q = q_ref[0, 0].reshape(G * TQ, hd)
        k = k_ref[0, 0]  # [TK, hd]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=prec) * scale  # [G·TQ, TK]

        rows = jax.lax.broadcasted_iota(jnp.int32, (G * TQ, TK), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (G * TQ, TK), 1)
        q_pos = pos0 + tq * TQ + jax.lax.rem(rows, TQ)
        key_pos = k_start + cols
        mask = (key_pos <= q_pos) & (key_pos < kv_len)
        mask = mask & ((win <= 0) | (key_pos > q_pos - win))
        s = jnp.where(mask, s, _NEG)

        m_prev, l_prev = m_sc[...], l_sc[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)  # [G·TQ, TK]
        l_sc[...] = l_prev * corr + jnp.sum(p, axis=1, keepdims=True)
        m_sc[...] = m_new
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0, 0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=prec)  # [G·TQ, hd]
        acc_sc[...] = acc_sc[...] * corr + pv

    @pl.when(tk == n_tk - 1)
    def _finalize():
        out = acc_sc[...] / jnp.maximum(l_sc[...], 1e-30)
        o_ref[0, 0] = out.reshape(G, TQ, hdv).astype(o_ref.dtype)


def flash_prefill(q, k, v, pos_base, kv_lens, *, sliding_window=None,
                  sinks=None, scale=None, interpret: bool = False):
    """Flash attention for a prefill chunk. See module docstring.

    ``sliding_window`` may be a traced scalar (per-layer gpt-oss windows);
    ``sinks`` [H] are optional attention-sink logits seeded into the online
    softmax with zero value contribution. ``v``'s trailing dim (= output
    width) may differ from q/k's (MLA latent attention); ``scale`` defaults
    to 1/√hd."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, S, H, hd = q.shape
    T, KV = k.shape[1], k.shape[2]
    hdv = v.shape[3]
    G = H // KV

    TQ = min(S, max(1, 512 // max(G, 1)))
    while S % TQ:
        TQ //= 2
    TK = min(T, 512)
    while T % TK:
        TK //= 2

    interpret = interpret or jax.default_backend() != "tpu"

    # group-major views: q5 [B,KV,G,S,hd], k4/v4 [B,KV,T,hd]
    q5 = q.reshape(B, S, KV, G, hd).transpose(0, 2, 3, 1, 4)
    k4 = k.transpose(0, 2, 1, 3)
    v4 = v.transpose(0, 2, 1, 3)

    has_sink = sinks is not None
    win_arr = jnp.asarray(
        [0 if sliding_window is None else sliding_window],
        jnp.int32).reshape(1)
    sink_in = (jnp.zeros((1, KV, G, 1), q.dtype) if not has_sink
               else sinks.reshape(1, KV, G, 1).astype(q.dtype))
    kernel = functools.partial(
        _prefill_kernel,
        scale=float(scale if scale is not None else 1.0 / np.sqrt(hd)),
        has_sink=has_sink)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B, KV, S // TQ, T // TK),
        in_specs=[
            pl.BlockSpec((1, 1, G, TQ, hd), lambda b, kk, tq, tk, *_: (b, kk, 0, tq, 0)),
            pl.BlockSpec((1, 1, G, 1), lambda b, kk, tq, tk, *_: (0, kk, 0, 0)),
            pl.BlockSpec((1, 1, TK, hd), lambda b, kk, tq, tk, *_: (b, kk, tk, 0)),
            pl.BlockSpec((1, 1, TK, hdv), lambda b, kk, tq, tk, *_: (b, kk, tk, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, G, TQ, hdv), lambda b, kk, tq, tk, *_: (b, kk, 0, tq, 0)),
        scratch_shapes=[
            pltpu.VMEM((G * TQ, 1), jnp.float32),
            pltpu.VMEM((G * TQ, 1), jnp.float32),
            pltpu.VMEM((G * TQ, hdv), jnp.float32),
        ],
    )
    out5 = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, KV, G, S, hdv), q.dtype),
        interpret=interpret,
    )(pos_base.astype(jnp.int32), kv_lens.astype(jnp.int32), win_arr,
      q5, sink_in, k4, v4)

    # [B,KV,G,S,hdv] → [B,S,H,hdv]
    return out5.transpose(0, 3, 1, 2, 4).reshape(B, S, H, hdv)


def flash_mla_prefill(q_eff, q_rot, c, k_rot, pos_base, kv_lens, *,
                      scale: float, interpret: bool = False):
    """Flash prefill over the compressed MLA latent cache — scores in
    latent space, O(S·T) never leaves VMEM.

    MLA attention is exactly single-KV-head attention once absorbed: every
    query head shares the one latent stream, Q=[q_eff|q_rot] against
    K=[c|k_rot] (the rope tail rides only the scores), V=c (output stays in
    latent space; the caller expands through W_UV). The generalized flash
    kernel runs it with KV=1, G=H, hd=r+pr, hdv=r — killing the [B,H,S,T]
    HBM score tensor the XLA path materializes (r2 verdict #3; DeepSeek at
    ISL 8192 is the reference's wide-EP flagship workload,
    ref: recipes/deepseek-r1/sglang-wideep/tep16p-dep16d-disagg.yaml:61).

    Args: q_eff [B,S,H,r] (absorbed), q_rot [B,S,H,pr] (rope, padded like
    the cache), c [B,T,r], k_rot [B,T,pr]; → [B,S,H,r] latent output.
    """
    q_cat = jnp.concatenate([q_eff, q_rot], axis=-1)
    k_cat = jnp.concatenate([c, k_rot], axis=-1)[:, :, None, :]
    return flash_prefill(q_cat, k_cat, c[:, :, None, :], pos_base, kv_lens,
                         scale=scale, interpret=interpret)


def flash_prefill_paged(q, k_cache, v_cache, lidx, block_tables, positions,
                        kv_lens, *, block_size: int, sliding_window=None,
                        sinks=None, interpret: bool = False):
    """Gather pages at layer ``lidx`` (XLA fused gather), then flash-attend.

    Same signature family as engine/model._paged_attention; q [B,S,H,hd],
    caches [L, slots, KV, hd].
    """
    from dynamo_tpu.engine.cache import gather_pages

    B = q.shape[0]
    W = block_tables.shape[1]
    slot_idx = (block_tables[:, :, None] * block_size
                + jnp.arange(block_size)[None, None, :]).reshape(B, W * block_size)
    # int8 caches dequantize in the gather (fused); the kernel then runs on
    # the q-dtype values exactly as with a plain cache
    k = gather_pages(k_cache, lidx, slot_idx).astype(q.dtype)  # [B,T,KV,hd]
    v = gather_pages(v_cache, lidx, slot_idx).astype(q.dtype)
    return flash_prefill(q, k, v, positions[:, 0], kv_lens,
                         sliding_window=sliding_window, sinks=sinks,
                         interpret=interpret)
