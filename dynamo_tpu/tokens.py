"""Token block hashing: salted xxh3 block hashes and chained sequence hashes.

Behavior-parity with the reference token library (ref: lib/tokens/src/lib.rs:16-29,
lib/llm/src/kv_router/indexer.rs:55,89-137):

- A token is a u32.
- ``salt_hash = xxh3_64(salt_bytes, seed=0)`` (or a caller-provided u64 seed).
- ``block_hash = xxh3_64(le_bytes(tokens), seed=salt_hash)`` over exactly
  ``block_size`` tokens.
- ``sequence_hash`` of the first block is its ``block_hash``; each subsequent
  block chains ``xxh3_64(le_bytes([parent_sequence_hash, block_hash]), seed=salt_hash)``.
- The KV router hashes with the fixed seed ``KV_HASH_SEED = 1337``
  (ref: lib/llm/src/kv_router/indexer.rs:55) so that frontend-side hashes and
  engine-side KV-event hashes agree across the cluster.

These hashes are the *identity* of a KV block everywhere in the system: the
radix index, KV events, the block manager's reuse pool, and the prefix cache in
the JAX engine all key on them.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Iterable, Optional, Sequence

import xxhash

Token = int  # u32
BlockHash = int  # u64
SequenceHash = int  # u64
SaltHash = int  # u64

#: Fixed seed used by the KV-router hash domain (ref: kv_router/indexer.rs:55).
KV_HASH_SEED: SaltHash = 1337

_U64_MASK = (1 << 64) - 1


def compute_hash(data: bytes, seed: int = KV_HASH_SEED) -> int:
    """xxh3_64 with seed (ref: lib/tokens/src/lib.rs:32)."""
    return xxhash.xxh3_64_intdigest(data, seed=seed & _U64_MASK)


def compute_salt_hash(salt: bytes) -> SaltHash:
    """Hash of a salt, seeded with 0 (ref: lib/tokens/src/lib.rs:23)."""
    return xxhash.xxh3_64_intdigest(salt, seed=0)


def _tokens_le_bytes(tokens: Sequence[int]) -> bytes:
    return struct.pack(f"<{len(tokens)}I", *tokens)


def compute_block_hash(tokens: Sequence[int], salt_hash: SaltHash = KV_HASH_SEED) -> BlockHash:
    """Hash of the tokens local to one block (ref: kv_router/indexer.rs:102)."""
    return compute_hash(_tokens_le_bytes(tokens), seed=salt_hash)


def compute_block_hash_for_seq(
    tokens: Sequence[int], kv_block_size: int, salt_hash: SaltHash = KV_HASH_SEED
) -> list[BlockHash]:
    """Per-block hashes for a token sequence, one per *complete* block.

    Trailing tokens that do not fill a block are ignored, matching
    ``chunks_exact`` in the reference (ref: kv_router/indexer.rs:125-137).
    Uses the native C++ batch path when built (one call for all blocks).
    """
    from dynamo_tpu import _native

    res = _native.block_hashes(tokens, kv_block_size, salt_hash)
    if res is not None:
        return res[0]
    n = len(tokens) // kv_block_size
    out = []
    for i in range(n):
        chunk = tokens[i * kv_block_size : (i + 1) * kv_block_size]
        out.append(compute_hash(_tokens_le_bytes(chunk), seed=salt_hash))
    return out


def chain_sequence_hash(
    parent: Optional[SequenceHash], block_hash: BlockHash, salt_hash: SaltHash = KV_HASH_SEED
) -> SequenceHash:
    """Combine a parent sequence hash with a block hash (ref: lib/tokens/src/lib.rs:226-247)."""
    if parent is None:
        return block_hash
    return compute_hash(struct.pack("<2Q", parent & _U64_MASK, block_hash & _U64_MASK), seed=salt_hash)


def compute_seq_hash_for_block(
    block_hashes: Sequence[BlockHash], salt_hash: SaltHash = KV_HASH_SEED
) -> list[SequenceHash]:
    """Rolling sequence hashes for a list of block hashes (ref: kv_router/indexer.rs:139-160)."""
    out: list[SequenceHash] = []
    parent: Optional[SequenceHash] = None
    for bh in block_hashes:
        parent = chain_sequence_hash(parent, bh, salt_hash)
        out.append(parent)
    return out


@dataclass(frozen=True)
class TokenBlock:
    """A complete, immutable block of tokens with its hashes."""

    tokens: tuple[int, ...]
    block_hash: BlockHash
    sequence_hash: SequenceHash
    parent_sequence_hash: Optional[SequenceHash]

    @staticmethod
    def from_tokens(
        tokens: Sequence[int],
        parent_sequence_hash: Optional[SequenceHash],
        salt_hash: SaltHash,
    ) -> "TokenBlock":
        bh = compute_block_hash(tokens, salt_hash)
        sh = chain_sequence_hash(parent_sequence_hash, bh, salt_hash)
        return TokenBlock(tuple(tokens), bh, sh, parent_sequence_hash)


@dataclass
class TokenBlockSequence:
    """Splits a growing token stream into hash-chained fixed-size blocks.

    Mirrors the reference's ``TokenBlockSequence`` (ref: lib/tokens/src/lib.rs:288):
    complete blocks carry ``(block_hash, sequence_hash)``; the tail lives in
    ``current_tokens`` until it fills.
    """

    block_size: int
    salt_hash: SaltHash = KV_HASH_SEED
    blocks: list[TokenBlock] = field(default_factory=list)
    current_tokens: list[int] = field(default_factory=list)

    @staticmethod
    def from_tokens(
        tokens: Iterable[int], block_size: int, salt_hash: SaltHash = KV_HASH_SEED
    ) -> "TokenBlockSequence":
        seq = TokenBlockSequence(block_size=block_size, salt_hash=salt_hash)
        seq.extend(tokens)
        return seq

    def __len__(self) -> int:
        return len(self.blocks) * self.block_size + len(self.current_tokens)

    @property
    def all_tokens(self) -> list[int]:
        out: list[int] = []
        for b in self.blocks:
            out.extend(b.tokens)
        out.extend(self.current_tokens)
        return out

    def push_token(self, token: int) -> Optional[TokenBlock]:
        """Append one token; returns the newly-completed block, if any."""
        self.current_tokens.append(token)
        if len(self.current_tokens) == self.block_size:
            parent = self.blocks[-1].sequence_hash if self.blocks else None
            block = TokenBlock.from_tokens(self.current_tokens, parent, self.salt_hash)
            self.blocks.append(block)
            self.current_tokens = []
            return block
        return None

    def extend(self, tokens: Iterable[int]) -> list[TokenBlock]:
        """Append many tokens; returns all newly-completed blocks.

        When the native core is built and the append is block-aligned, the
        whole-blocks prefix hashes in one C++ call.
        """
        tokens = list(tokens)
        new_blocks: list[TokenBlock] = []
        if not self.current_tokens and len(tokens) >= self.block_size:
            from dynamo_tpu import _native

            res = _native.block_hashes(tokens, self.block_size, self.salt_hash)
            if res is not None:
                bhs, shs = res
                fresh_chain = not self.blocks  # native chain starts at None
                parent = self.blocks[-1].sequence_hash if self.blocks else None
                for i, bh in enumerate(bhs):
                    sh = shs[i] if fresh_chain else chain_sequence_hash(
                        parent, bh, self.salt_hash)
                    blk = TokenBlock(
                        tuple(tokens[i * self.block_size:(i + 1) * self.block_size]),
                        bh, sh, parent)
                    self.blocks.append(blk)
                    new_blocks.append(blk)
                    parent = sh
                tokens = tokens[len(bhs) * self.block_size:]
        for t in tokens:
            b = self.push_token(t)
            if b is not None:
                new_blocks.append(b)
        return new_blocks

    def truncate(self, num_tokens: int) -> None:
        """Drop tokens from the end so that len(self) == num_tokens."""
        if num_tokens >= len(self):
            return
        keep_blocks, rem = divmod(num_tokens, self.block_size)
        all_toks = self.all_tokens[:num_tokens]
        self.blocks = self.blocks[:keep_blocks]
        self.current_tokens = list(all_toks[keep_blocks * self.block_size :])
        assert len(self.current_tokens) == rem

    def sequence_hashes(self) -> list[SequenceHash]:
        return [b.sequence_hash for b in self.blocks]

    def block_hashes(self) -> list[BlockHash]:
        return [b.block_hash for b in self.blocks]
