"""Serve a real-sized (8B-class) checkpoint end to end on the TPU.

VERDICT r3 weak #2: "Nothing real-sized has ever been served" — HBM sizing,
compile time at 8B scale, and bucket-churn recompilation were all unproven.
This benchmark:

1. materializes a llama-3-8B-GEOMETRY random checkpoint on disk (safetensors
   shards + config.json + WordLevel tokenizer covering the full 128k vocab —
   random weights exercise identical compute/memory paths; only the text is
   gibberish), cached under .bench_cache/ across runs;
2. loads it through the PRODUCTION path (ModelConfig.from_pretrained →
   load_hf_params → AsyncJaxEngine with --quantization int8), timing load,
   quantize, and device transfer;
3. reports the engine's auto HBM sizing (hbm_sized_num_blocks on a 16 GB
   v5e: ~8 GB int8 weights + KV capacity from the remainder);
4. serves streaming completions over real HTTP with the reference harness
   default workload shape (ISL 2000 / OSL 256, docs/benchmarks/
   benchmarking.md:33) and reports TTFT p50/p95 + decode tok/s + compile
   counts (bucket churn = compiles after warmup, which must be 0).

Usage: python -m benchmarks.real_size_bench [--fixture-only] [--kv-int8]
       [--isl 2000] [--osl 256] [--conc 16] [--n 32]
Prints one JSON line. Needs the real chip (8B does not fit a CPU host in
reasonable time; use bench.py's CPU fallback shapes for plumbing checks).
"""

from __future__ import annotations

import argparse
import asyncio
import gc
import json
import os
import time

import numpy as np

FIXTURE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), ".bench_cache", "llama8b-rand")

# llama-3-8B geometry (config.json fields the loader honors)
LLAMA8B = {
    "architectures": ["LlamaForCausalLM"],
    "hidden_size": 4096,
    "intermediate_size": 14336,
    "num_hidden_layers": 32,
    "num_attention_heads": 32,
    "num_key_value_heads": 8,
    "vocab_size": 128256,
    "max_position_embeddings": 8192,
    "rms_norm_eps": 1e-05,
    "rope_theta": 500000.0,
    "tie_word_embeddings": False,
    "torch_dtype": "bfloat16",
    "model_type": "llama",
    "eos_token_id": 128001,
    "bos_token_id": 128000,
}


def build_fixture(cfg: dict, path: str, *, seed: int = 0) -> float:
    """Write a random checkpoint with real HF names/shapes/dtype. Returns
    seconds spent. Weights are N(0, 0.02) bf16 — inference-stable garbage."""
    import torch
    from safetensors.torch import save_file

    os.makedirs(path, exist_ok=True)
    t0 = time.perf_counter()
    H, I = cfg["hidden_size"], cfg["intermediate_size"]
    L = cfg["num_hidden_layers"]
    NH, NKV = cfg["num_attention_heads"], cfg["num_key_value_heads"]
    hd = H // NH
    V = cfg["vocab_size"]

    gen = torch.Generator().manual_seed(seed)

    def rand(*shape):
        return (torch.randn(*shape, generator=gen, dtype=torch.float32)
                .mul_(0.02).to(torch.bfloat16))

    shard, shard_idx, shard_bytes = {}, 1, 0

    def flush():
        # no index.json needed: the loader discovers shards by globbing
        # *.safetensors (engine/loader.py)
        nonlocal shard, shard_idx, shard_bytes
        if not shard:
            return
        save_file(shard, os.path.join(path, f"model-{shard_idx:05d}.safetensors"))
        shard, shard_idx, shard_bytes = {}, shard_idx + 1, 0

    def put(name, tensor):
        nonlocal shard_bytes
        shard[name] = tensor
        shard_bytes += tensor.numel() * tensor.element_size()
        if shard_bytes > 4 << 30:
            flush()

    put("model.embed_tokens.weight", rand(V, H))
    for i in range(L):
        p = f"model.layers.{i}."
        put(p + "self_attn.q_proj.weight", rand(NH * hd, H))
        put(p + "self_attn.k_proj.weight", rand(NKV * hd, H))
        put(p + "self_attn.v_proj.weight", rand(NKV * hd, H))
        put(p + "self_attn.o_proj.weight", rand(H, NH * hd))
        put(p + "mlp.gate_proj.weight", rand(I, H))
        put(p + "mlp.up_proj.weight", rand(I, H))
        put(p + "mlp.down_proj.weight", rand(H, I))
        put(p + "input_layernorm.weight", torch.ones(H, dtype=torch.bfloat16))
        put(p + "post_attention_layernorm.weight",
            torch.ones(H, dtype=torch.bfloat16))
    put("model.norm.weight", torch.ones(H, dtype=torch.bfloat16))
    put("lm_head.weight", rand(V, H))
    flush()

    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump(cfg, f, indent=1)
    with open(os.path.join(path, "generation_config.json"), "w") as f:
        json.dump({"eos_token_id": cfg["eos_token_id"],
                   "bos_token_id": cfg["bos_token_id"]}, f)
    _write_tokenizer(path, cfg["vocab_size"])
    with open(os.path.join(path, ".complete"), "w") as f:
        f.write("ok")
    return time.perf_counter() - t0


def _write_tokenizer(path: str, vocab_size: int) -> None:
    from tokenizers import Tokenizer
    from tokenizers.models import WordLevel
    from tokenizers.pre_tokenizers import Whitespace

    vocab = {f"w{i}": i for i in range(vocab_size)}
    tk = Tokenizer(WordLevel(vocab, unk_token="w0"))
    tk.pre_tokenizer = Whitespace()
    tk.save(os.path.join(path, "tokenizer.json"))
    with open(os.path.join(path, "tokenizer_config.json"), "w") as f:
        json.dump({"chat_template": "{% for m in messages %}{{ m['content'] }}"
                                    "{% endfor %}"}, f)


async def serve_bench(path: str, *, kv_int8: bool, isl: int, osl: int,
                      conc: int, n_req: int,
                      prefill_buckets=(1024, 2048, 4096)) -> dict:
    import aiohttp
    import jax

    from dynamo_tpu.disagg.handlers import DecodeWorkerHandler
    from dynamo_tpu.engine.config import EngineArgs
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.engine.loader import load_model
    from dynamo_tpu.frontend.http import HttpService
    from dynamo_tpu.llm.discovery import ModelManager, ModelWatcher
    from dynamo_tpu.llm.model_card import ModelDeploymentCard, register_llm
    from dynamo_tpu.runtime import DistributedRuntime

    out: dict = {}
    t0 = time.perf_counter()
    cfg, params = load_model(path)
    out["load_s"] = round(time.perf_counter() - t0, 1)

    args = EngineArgs(
        block_size=16, max_num_seqs=max(conc, 8),
        max_num_batched_tokens=2048, max_model_len=isl + osl + 64,
        multi_step_decode=8, use_pallas_attention=True,
        quantization="int8",
        kv_cache_dtype="int8" if kv_int8 else None,
        prefill_buckets=prefill_buckets,
        decode_batch_buckets=(8, 16, 32))
    t0 = time.perf_counter()
    eng = AsyncJaxEngine(cfg, args, params=params)
    del params
    gc.collect()
    out["quantize_and_put_s"] = round(time.perf_counter() - t0, 1)
    out["num_blocks_auto"] = eng.num_blocks
    out["kv_capacity_tokens"] = eng.num_blocks * args.block_size
    try:
        from dynamo_tpu.engine.cache import bounded_memory_stats
        stats = bounded_memory_stats(jax.local_devices()[0])
        out["hbm_in_use_gb"] = round(stats.get("bytes_in_use", 0) / 2**30, 2)
        out["hbm_limit_gb"] = round(stats.get("bytes_limit", 0) / 2**30, 2)
    except Exception:
        pass

    rt = await DistributedRuntime.create()
    handler = DecodeWorkerHandler(eng)
    ep = rt.namespace("dynamo").component("backend").endpoint("generate")
    handle = await ep.serve_endpoint(handler.generate)
    with open(os.path.join(path, "config.json")) as f:
        geom = json.load(f)
    card = ModelDeploymentCard(
        display_name="llama8b-rand", kv_cache_block_size=args.block_size,
        eos_token_ids=[geom["eos_token_id"]], tokenizer_ref=path,
        context_length=args.max_model_len)
    card.runtime_config.total_kv_blocks = eng.num_blocks
    await register_llm(rt, ep, card)
    manager = ModelManager()
    watcher = await ModelWatcher(rt, manager, router_mode="kv").start()
    service = HttpService(manager, port=0)
    await service.start()
    for _ in range(200):
        if manager.list_models():
            break
        await asyncio.sleep(0.05)

    url = f"http://127.0.0.1:{service.port}/v1/completions"
    rng = np.random.default_rng(11)

    async def one(session):
        prompt = rng.integers(1, geom["vocab_size"], isl).tolist()
        t0 = time.perf_counter()
        ttft, n_tok = None, 0
        async with session.post(url, json={
                "model": "llama8b-rand", "prompt": prompt, "stream": True,
                "max_tokens": osl, "ignore_eos": True,
                "temperature": 0.0}) as resp:
            assert resp.status == 200, await resp.text()
            async for raw in resp.content:
                line = raw.decode()
                if not line.startswith("data: ") or line.startswith("data: [DONE]"):
                    continue
                payload = json.loads(line[6:])
                if "error" in payload:
                    raise RuntimeError(f"engine error: {payload}")
                if ttft is None:
                    ttft = time.perf_counter() - t0
                n_tok += 1
        return ttft, n_tok

    async def closed_loop(session, n_left, results):
        while n_left:
            n_left.pop()
            results.append(await one(session))

    conn = aiohttp.TCPConnector(limit=0)
    async with aiohttp.ClientSession(connector=conn) as session:
        t0 = time.perf_counter()
        warm_left, warm_res = [0] * max(conc // 2, 2), []
        await asyncio.gather(*[closed_loop(session, warm_left, warm_res)
                               for _ in range(conc)])
        out["warmup_s"] = round(time.perf_counter() - t0, 1)  # ≈ compile set
        compiles0 = eng.compile_count if hasattr(eng, "compile_count") else None
        t0 = time.perf_counter()
        n_left, results = [0] * n_req, []
        await asyncio.gather(*[closed_loop(session, n_left, results)
                               for _ in range(conc)])
        elapsed = time.perf_counter() - t0
        if compiles0 is not None:
            out["compiles_after_warmup"] = eng.compile_count - compiles0

    await service.stop()
    await watcher.stop()
    await handle.stop(graceful=False)
    await eng.close()
    await rt.shutdown()

    ttfts = sorted(r[0] for r in results if r[0] is not None)
    total = sum(r[1] for r in results)
    out.update({
        "decode_tok_s": round(total / elapsed, 1),
        "ttft_p50_ms": round(1000 * ttfts[len(ttfts) // 2], 1),
        "ttft_p95_ms": round(1000 * ttfts[min(int(len(ttfts) * 0.95),
                                              len(ttfts) - 1)], 1),
        "workload": f"ISL={isl},OSL={osl},conc={conc},n={n_req}",
        "kv_int8": kv_int8,
    })
    return out


# tiny geometry for --smoke: same code path, CPU-feasible sizes — proves
# the WHOLE chain (fixture → from_pretrained → load → int8 quantize →
# HTTP serve → metrics) before a scarce chip window is spent on it
SMOKE = {**LLAMA8B, "hidden_size": 256, "intermediate_size": 512,
         "num_hidden_layers": 4, "num_attention_heads": 8,
         "num_key_value_heads": 4, "vocab_size": 2048,
         "eos_token_id": 2000, "bos_token_id": 1}


def main():
    ap = argparse.ArgumentParser(description="8B-class real-size serve bench")
    ap.add_argument("--fixture-only", action="store_true")
    ap.add_argument("--kv-int8", action="store_true")
    ap.add_argument("--isl", type=int, default=2000)
    ap.add_argument("--osl", type=int, default=256)
    ap.add_argument("--conc", type=int, default=16)
    ap.add_argument("--n", type=int, default=32)
    ap.add_argument("--smoke", action="store_true",
                    help="tiny-geometry CPU run of the full harness")
    ap.add_argument("--platform", default=None,
                    help="cpu = force backend before first device touch "
                         "(a dead axon tunnel wedges bare jax init)")
    cli = ap.parse_args()

    if cli.platform:
        import jax

        jax.config.update("jax_platforms", cli.platform)

    geom, fdir = LLAMA8B, FIXTURE_DIR
    if cli.smoke:
        geom, fdir = SMOKE, FIXTURE_DIR + "-smoke"
        cli.isl, cli.osl = min(cli.isl, 128), min(cli.osl, 16)
        cli.conc, cli.n = min(cli.conc, 4), min(cli.n, 8)

    out = {"model": ("llama-3-8B-geometry (random weights)"
                     if not cli.smoke else "smoke-geometry (random weights)")}
    if not os.path.exists(os.path.join(fdir, ".complete")):
        out["fixture_build_s"] = round(build_fixture(geom, fdir), 1)
    if cli.fixture_only:
        print(json.dumps(out))
        return
    buckets = (1024, 2048, 4096)
    if cli.smoke:
        # padded-to-1024 prefills would 8x the smoke run's CPU wall time
        b0 = max(128, 1 << (cli.isl - 1).bit_length())
        buckets = (b0, b0 * 2)
    out.update(asyncio.run(serve_bench(
        fdir, kv_int8=cli.kv_int8, isl=cli.isl, osl=cli.osl,
        conc=cli.conc, n_req=cli.n, prefill_buckets=buckets)))
    print(json.dumps(out))


if __name__ == "__main__":
    main()
