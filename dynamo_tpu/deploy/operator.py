"""Process operator: reconcile a DynamoGraphDeployment spec into processes.

Analog of the reference's Kubernetes operator (ref: deploy/cloud/operator —
Go CRDs + reconcilers realizing DynamoGraphDeployment/
DynamoComponentDeployment as pods): the same desired-state → observe →
reconcile loop, realized as local processes so the operator semantics run
(and test) anywhere — a TPU-VM, a dev box, CI — without a cluster. On GKE
the real scheduler is Kubernetes itself (deploy/recipes/k8s/); this
reconciler is the single-host / bare-TPU-VM deployment path and the
operator's testbed.

Spec (YAML, CRD-shaped — ref: api/v1alpha1/dynamographdeployment_types.go):

    apiVersion: dynamo.tpu/v1alpha1
    kind: DynamoGraphDeployment
    metadata: {name: my-graph}
    spec:
      services:
        frontend:
          replicas: 1
          command: [python, -m, dynamo_tpu.frontend.main, --port, "8000"]
          env: {DYN_LOG: info}
        decode:
          replicas: 2
          command: [python, -m, dynamo_tpu.engine.main, --role, decode]
          plannerRole: decode        # planner target overrides replicas

Reconcile behavior:

- spec file changes are picked up each tick (mtime watch);
- missing replicas are spawned (env merged over os.environ, with
  DYN_REPLICA_INDEX and a unique DYN_POD_NAME set), excess replicas are
  **drained, not killed**: victims get SIGTERM and the PR 3
  ``DYN_DRAIN_TIMEOUT`` window to finish their in-flight streams
  ASYNCHRONOUSLY — the reconcile loop keeps ticking while they drain, and
  only a victim that outlives the window is SIGKILLed (migration absorbs
  whatever it was still holding). The old behavior (fixed blocking
  ``wait(timeout=10)`` then SIGKILL) both froze reconcile mid-drain and
  cut streams ~20 s before the configured drain window;
- scale-down victims are chosen by **fewest in-flight streams** (worker
  ``ForwardPassMetrics`` matched to replicas via their DYN_POD_NAME
  instance metadata), newest-first on ties — so shedding capacity
  disturbs the least work;
- crashed replicas restart with exponential backoff, counted in status;
- services marked ``plannerRole: prefill|decode`` follow the planner's
  VirtualConnector target key on the control plane — the SLA planner /
  autoscaler drives real scale-up/down end-to-end without Kubernetes
  (ref intent: planner → operator → pods);
- **readiness gating** (``readinessGate``, default on for planner-role
  services when a control plane is attached): a replica only counts as
  ``ready`` once it has REGISTERED on the control plane — for engine
  workers that happens strictly after AOT warmup (engine/main.py warms up
  before joining), so the autoscaler never sees phantom capacity during a
  compile cliff. ``alive`` (process up) is reported separately;
- observed state is written to ``<spec>.status.json`` every tick (the CRD
  status subresource analog, atomically via temp file + ``os.replace`` so
  readers never observe a torn file) and mirrored to the control-plane
  key ``public/operator/<ns>/status`` for the autoscale controller and
  ``dynctl autoscale``; dead workers' leases expire, which is the
  reference's etcd-cleanup-on-scale-down contract (internal/etcd/)
  falling out of lease semantics.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import signal
import subprocess
import time
from dataclasses import dataclass, field
from typing import Optional

import yaml

logger = logging.getLogger("dynamo.operator")

_BACKOFF = (1.0, 2.0, 5.0, 10.0, 30.0)


@dataclass
class ServiceSpec:
    name: str
    replicas: int
    command: list[str]
    env: dict = field(default_factory=dict)
    planner_role: Optional[str] = None  # "prefill" | "decode"
    #: None = auto (gate when planner_role is set and a plane is attached)
    readiness_gate: Optional[bool] = None


@dataclass
class Replica:
    proc: subprocess.Popen
    index: int
    started: float
    #: (command, env) the process was started with — a spec edit that
    #: changes either makes the replica stale and it is restarted
    config: tuple = ()
    #: unique per-spawn identity; workers stamp it into their instance
    #: metadata (DYN_POD_NAME → component.serve_endpoint), which is how
    #: the operator matches control-plane registrations back to processes
    pod_name: str = ""
    # -- drain bookkeeping (only meaningful once the replica is a victim)
    drain_started: float = 0.0
    drain_deadline: float = 0.0
    killed: bool = False


def parse_spec(path: str) -> dict[str, ServiceSpec]:
    with open(path) as f:
        doc = yaml.safe_load(f)
    if not isinstance(doc, dict) or doc.get("kind") != "DynamoGraphDeployment":
        raise ValueError(f"{path}: expected kind DynamoGraphDeployment")
    out: dict[str, ServiceSpec] = {}
    for name, svc in (doc.get("spec", {}).get("services") or {}).items():
        cmd = svc.get("command")
        if not cmd or not isinstance(cmd, list):
            raise ValueError(f"service {name}: 'command' list is required")
        gate = svc.get("readinessGate")
        out[name] = ServiceSpec(
            name=name,
            replicas=int(svc.get("replicas", 1)),
            command=[str(c) for c in cmd],
            env={str(k): str(v) for k, v in (svc.get("env") or {}).items()},
            planner_role=svc.get("plannerRole"),
            readiness_gate=None if gate is None else bool(gate),
        )
    if not out:
        raise ValueError(f"{path}: no services in spec")
    return out


class ProcessOperator:
    def __init__(self, spec_path: str, plane=None, namespace: str = "dynamo",
                 tick_s: float = 1.0, drain_timeout: Optional[float] = None):
        self.spec_path = spec_path
        self.plane = plane  # control plane for planner-target watching
        self.namespace = namespace
        self.tick_s = tick_s
        if drain_timeout is None:
            raw = os.environ.get("DYN_DRAIN_TIMEOUT", "30")
            try:
                drain_timeout = float(raw)
            except ValueError:
                raise ValueError(
                    f"DYN_DRAIN_TIMEOUT: expected seconds, got {raw!r}"
                ) from None
        #: graceful window a scale-down victim gets between SIGTERM and
        #: SIGKILL (the PR 3 drain contract, honored asynchronously)
        self.drain_timeout = max(0.0, drain_timeout)
        self.services: dict[str, ServiceSpec] = parse_spec(spec_path)
        self.replicas: dict[str, list[Replica]] = {s: [] for s in self.services}
        self.restarts: dict[str, int] = {s: 0 for s in self.services}
        #: crash backoff is PER REPLICA SLOT (service, index), not per
        #: service: independent chaos/hardware deaths spread across a pool
        #: must not accumulate into one service-wide streak that freezes
        #: ALL respawns (observed in the flagship drive: the decode pool
        #: collapsed to 1 alive while desired was 4, every kill anywhere
        #: bumping the shared streak). Only a slot that itself crash-loops
        #: earns a growing delay — Kubernetes backs off per pod the same
        #: way.
        self._crash_streak: dict[tuple, int] = {}
        self._next_start: dict[tuple, float] = {}
        #: victims mid-drain: no longer capacity, still alive processes
        self._draining: dict[str, list[Replica]] = {s: [] for s in self.services}
        self._spec_mtime = os.path.getmtime(spec_path)
        self._planner_target: Optional[dict] = None
        self._spawn_seq = 0
        #: pod name -> instance id, from the control plane's instances/
        #: prefix (refreshed each async tick; empty without a plane)
        self._registered_pods: dict[str, int] = {}
        #: instance id -> in-flight streams, from worker ForwardPassMetrics
        self._inflight_by_instance: dict[int, int] = {}
        #: instance ids whose latest stats report warmed_up=False: the
        #: worker registered but its AOT warmup was skipped (multi-host
        #: step replication) and no real step has compiled yet — it must
        #: not count as ready capacity while it pays the compile cliff
        #: (the 'registered subsumes warm' invariant below does not hold
        #: for such workers). Self-healing: the flag flips on the worker's
        #: first served step.
        self._cold_instances: set = set()
        self._metrics_agg = None  # MetricsAggregator when plane is set
        # drain telemetry (mirrored into status → dynamo_autoscale_drain_seconds)
        self.drain_seconds_total = 0.0
        self.drains_completed = 0
        self.drains_killed = 0
        self._stop = asyncio.Event()
        self._task: Optional[asyncio.Task] = None

    # -- desired state -----------------------------------------------------

    def _desired(self, svc: ServiceSpec) -> int:
        if svc.planner_role and self._planner_target:
            t = self._planner_target.get(svc.planner_role)
            if t is not None:
                return max(0, int(t))
        return svc.replicas

    async def _refresh_planner_target(self) -> None:
        if self.plane is None:
            return
        from dynamo_tpu.planner.virtual_connector import SCALE_KEY

        try:
            v = await self.plane.kv_get(
                SCALE_KEY.format(namespace=self.namespace))
            self._planner_target = json.loads(v) if v else None
        except Exception:
            logger.exception("planner target read failed")

    def _maybe_reload_spec(self) -> None:
        try:
            mtime = os.path.getmtime(self.spec_path)
        except OSError:
            return
        if mtime == self._spec_mtime:
            return
        self._spec_mtime = mtime
        try:
            new = parse_spec(self.spec_path)
        except ValueError as e:
            logger.error("spec reload rejected: %s", e)
            return
        for name in list(self.replicas):
            if name not in new:  # service removed: drain it
                self._scale_to(self.services[name], 0)
                del self.replicas[name]
        for name, svc in new.items():
            self.replicas.setdefault(name, [])
            self.restarts.setdefault(name, 0)
            self._draining.setdefault(name, [])
        self.services = new
        logger.info("spec reloaded: %s",
                    {n: s.replicas for n, s in new.items()})

    # -- reconcile ---------------------------------------------------------

    @staticmethod
    def _svc_config(svc: ServiceSpec) -> tuple:
        return (tuple(svc.command), tuple(sorted(svc.env.items())))

    def _spawn(self, svc: ServiceSpec, index: int) -> Replica:
        env = dict(os.environ)
        env.update(svc.env)
        env["DYN_REPLICA_INDEX"] = str(index)
        self._spawn_seq += 1
        # unique per spawn: a crashed replica's successor must not inherit
        # the stale registration of its predecessor's still-leased keys
        pod_name = f"{svc.name}-{index}-{self._spawn_seq}"
        env["DYN_POD_NAME"] = pod_name
        proc = subprocess.Popen(svc.command, env=env)
        logger.info("started %s[%d] pid=%d pod=%s", svc.name, index,
                    proc.pid, pod_name)
        return Replica(proc=proc, index=index, started=time.monotonic(),
                       config=self._svc_config(svc), pod_name=pod_name)

    # -- drain-safe scale-down --------------------------------------------

    def _begin_drain(self, svc_name: str, r: Replica, why: str) -> None:
        """SIGTERM the victim and give it the drain window ASYNCHRONOUSLY:
        it leaves the capacity set now, the reconcile loop keeps ticking,
        and _reap_draining SIGKILLs only a victim that outlives
        ``drain_timeout``. (The old fixed blocking ``wait(timeout=10)``
        both froze reconcile and ignored DYN_DRAIN_TIMEOUT — in-flight
        streams died ~20 s before their configured window.)"""
        now = time.monotonic()
        r.drain_started = now
        r.drain_deadline = now + self.drain_timeout
        logger.info("draining %s[%d] pid=%d (%s, window %.1fs)", svc_name,
                    r.index, r.proc.pid, why, self.drain_timeout)
        try:
            r.proc.terminate()
        except ProcessLookupError:
            pass
        self._draining.setdefault(svc_name, []).append(r)

    def _reap_draining(self) -> None:
        """Advance every in-progress drain (non-blocking, every tick)."""
        now = time.monotonic()
        for name in list(self._draining):
            keep = []
            for r in self._draining[name]:
                if r.proc.poll() is not None:
                    took = now - r.drain_started
                    self.drain_seconds_total += took
                    if r.killed:
                        self.drains_killed += 1
                    else:
                        self.drains_completed += 1
                        logger.info("%s[%d] drained in %.1fs", name,
                                    r.index, took)
                    continue
                if not r.killed and now >= r.drain_deadline:
                    logger.warning("%s[%d] outlived its %.1fs drain window; "
                                   "SIGKILL", name, r.index,
                                   self.drain_timeout)
                    try:
                        r.proc.kill()
                    except ProcessLookupError:
                        pass
                    r.killed = True
                keep.append(r)
            if keep or name in self.services:
                self._draining[name] = keep
            else:
                del self._draining[name]  # removed service fully drained

    def _inflight_of(self, r: Replica) -> int:
        """In-flight streams on a replica per its last ForwardPassMetrics
        (matched through the pod-name instance metadata). Unregistered
        replicas report -1: a worker that never joined the plane holds no
        streams and is the cheapest possible victim."""
        iid = self._registered_pods.get(r.pod_name)
        if iid is None:
            return -1
        return self._inflight_by_instance.get(iid, 0)

    def _scale_to(self, svc: ServiceSpec, want: int) -> None:
        reps = self.replicas[svc.name]
        # replicas running an outdated command/env are stale: drain them
        # (the scale-up below respawns with the current spec) — a spec
        # edit must converge, not just adjust counts
        cur = self._svc_config(svc)
        for r in [r for r in reps if r.config != cur and r.proc.poll() is None]:
            reps.remove(r)
            self._begin_drain(svc.name, r, "spec changed")
        # reap exited replicas (crash → restart with backoff)
        alive = []
        for r in reps:
            if r.proc.poll() is None:
                alive.append(r)
            else:
                logger.warning("%s[%d] exited rc=%s", svc.name, r.index,
                               r.proc.returncode)
                self.restarts[svc.name] += 1
                slot = (svc.name, r.index)
                streak = self._crash_streak.get(slot, 0)
                if time.monotonic() - r.started > 60:
                    streak = 0  # ran long enough: reset the backoff
                self._crash_streak[slot] = streak + 1
                delay = _BACKOFF[min(streak, len(_BACKOFF) - 1)]
                self._next_start[slot] = time.monotonic() + delay
        reps[:] = alive
        # scale down: fewest in-flight streams first (disturb the least
        # work), newest-first on ties (the historical order; leases expire
        # → discovery forgets the victims)
        if len(reps) > want:
            victims = sorted(reps, key=lambda r: (self._inflight_of(r),
                                                  -r.started))
            for r in victims[: len(reps) - want]:
                reps.remove(r)
                self._begin_drain(svc.name, r, "scale down")
        # scale up (respecting each SLOT's crash backoff: a crash-looping
        # slot waits out its delay while the rest of the pool refills)
        used = {r.index for r in reps}
        now = time.monotonic()
        for index in range(want):
            if len(reps) >= want:
                break
            if index in used or now < self._next_start.get(
                    (svc.name, index), 0.0):
                continue
            reps.append(self._spawn(svc, index))
            used.add(index)

    # -- readiness ---------------------------------------------------------

    def _gated(self, svc: ServiceSpec) -> bool:
        if svc.readiness_gate is not None:
            return svc.readiness_gate and self.plane is not None
        return self.plane is not None and svc.planner_role is not None

    def _alive(self, name: str) -> list[Replica]:
        return [r for r in self.replicas[name] if r.proc.poll() is None]

    def _ready_count(self, svc: ServiceSpec) -> int:
        """Replicas that count toward capacity: alive AND (when gated)
        registered on the control plane AND not reporting themselves cold.
        Engine workers register strictly after AOT warmup, so 'registered'
        normally subsumes 'warm' — EXCEPT when warmup was skipped
        (multi-host step replication): those workers publish
        WorkerStats.warmed_up=False until their first real step compiles,
        and counting them ready would hand the autoscale loop phantom
        capacity mid-compile-cliff."""
        alive = self._alive(svc.name)
        if not self._gated(svc):
            return len(alive)
        n = 0
        for r in alive:
            iid = self._registered_pods.get(r.pod_name)
            if iid is not None and iid not in self._cold_instances:
                n += 1
        return n

    def _cold_count(self, svc: ServiceSpec) -> int:
        """Registered-but-cold replicas (status surface for the skipped-
        warmup case — dynctl autoscale and the readiness gate both see
        why ready < alive)."""
        if not self._gated(svc):
            return 0
        return sum(1 for r in self._alive(svc.name)
                   if self._registered_pods.get(r.pod_name)
                   in self._cold_instances)

    def reconcile_once(self) -> None:
        self._maybe_reload_spec()
        self._reap_draining()
        for svc in self.services.values():
            self._scale_to(svc, self._desired(svc))
        self._write_status()

    def _status(self) -> dict:
        status = {
            "observedAt": time.time(),
            "services": {
                name: {
                    "desired": self._desired(svc),
                    "alive": len(self._alive(name)),
                    "ready": self._ready_count(svc),
                    "cold": self._cold_count(svc),
                    "draining": len(self._draining.get(name, [])),
                    "restarts": self.restarts[name],
                    "plannerRole": svc.planner_role,
                    "readinessGated": self._gated(svc),
                    "pids": [r.proc.pid for r in self._alive(name)],
                }
                for name, svc in self.services.items()
            },
            "drainSecondsTotal": round(self.drain_seconds_total, 3),
            "drainsCompleted": self.drains_completed,
            "drainsKilled": self.drains_killed,
        }
        if self._planner_target:
            status["plannerTarget"] = self._planner_target
        return status

    def _write_status(self) -> None:
        # temp file + os.replace: a concurrent reader (dynctl, the
        # autoscale loop, tests tailing the file) must never observe a
        # torn/partial JSON document
        status = self._status()
        tmp = self.spec_path + ".status.json.tmp"
        with open(tmp, "w") as f:
            json.dump(status, f, indent=2)
        os.replace(tmp, self.spec_path + ".status.json")

    async def _publish_status(self) -> None:
        """Mirror observed state to the control plane so the autoscale
        controller's readiness gate and ``dynctl autoscale`` see it
        without filesystem access."""
        if self.plane is None:
            return
        from dynamo_tpu.autoscale.controller import OPERATOR_STATUS_KEY

        try:
            await self.plane.kv_put(
                OPERATOR_STATUS_KEY.format(namespace=self.namespace),
                json.dumps(self._status()).encode())
        except Exception:
            logger.exception("operator status publish failed")

    async def _refresh_observed(self) -> None:
        """Refresh the pod→instance map (readiness) and per-instance
        in-flight counts (victim selection) from the control plane."""
        if self.plane is None:
            return
        try:
            import msgpack

            regs = await self.plane.kv_get_prefix("instances/")
            pods: dict[str, int] = {}
            for v in regs.values():
                try:
                    d = msgpack.unpackb(v, raw=False)
                    pod = (d.get("metadata") or {}).get("pod")
                    if pod:
                        pods[pod] = int(d["instance_id"])
                except Exception:
                    continue
            self._registered_pods = pods
        except Exception:
            logger.exception("instance registry read failed")
        if self._metrics_agg is not None:
            # snapshot(), not .latest: workers publish only while
            # stepping, so an idle replica's final busy report must age
            # out or victim selection drains a genuinely-busy peer first
            snap = self._metrics_agg.snapshot()
            self._inflight_by_instance = {
                wid: m.worker_stats.request_active_slots
                for wid, m in snap.items()}
            self._cold_instances = {
                wid for wid, m in snap.items()
                if m.worker_stats.warmed_up is False}

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> "ProcessOperator":
        if self.plane is not None and self._metrics_agg is None:
            from dynamo_tpu.router.publisher import MetricsAggregator

            self._metrics_agg = await MetricsAggregator(
                self.plane, stale_after_s=10.0).start()
        self._task = asyncio.get_running_loop().create_task(self._loop())
        return self

    async def _loop(self):
        while not self._stop.is_set():
            await self._refresh_planner_target()
            await self._refresh_observed()
            await asyncio.to_thread(self.reconcile_once)
            await self._publish_status()
            try:
                await asyncio.wait_for(self._stop.wait(), self.tick_s)
            except asyncio.TimeoutError:
                pass

    async def stop(self, drain: bool = True):
        self._stop.set()
        if self._task is not None:
            await self._task
        if self._metrics_agg is not None:
            await self._metrics_agg.stop()
            self._metrics_agg = None
        if drain:
            for svc in self.services.values():
                self._scale_to(svc, 0)
            # bounded graceful shutdown: give every victim its drain
            # window (they all drain CONCURRENTLY), then force the rest
            deadline = time.monotonic() + self.drain_timeout + 2.0
            while (any(self._draining.values())
                   and time.monotonic() < deadline):
                self._reap_draining()
                if not any(self._draining.values()):
                    break
                await asyncio.sleep(0.05)
            for name in list(self._draining):
                for r in self._draining[name]:
                    if r.proc.poll() is None:
                        try:
                            r.proc.kill()
                        except ProcessLookupError:
                            pass
                        r.proc.wait()
                        self.drains_killed += 1
                        self.drain_seconds_total += (
                            time.monotonic() - r.drain_started)
            self._draining = {s: [] for s in self.services}
            self._write_status()
            await self._publish_status()


async def amain():
    import argparse

    from dynamo_tpu.runtime.config import setup_logging

    ap = argparse.ArgumentParser(
        description="dynamo-tpu process operator (DynamoGraphDeployment)")
    ap.add_argument("spec", help="DynamoGraphDeployment YAML")
    ap.add_argument("--namespace", default="dynamo")
    ap.add_argument("--tick", type=float, default=1.0)
    ap.add_argument("--follow-planner", action="store_true",
                    help="watch the planner's target-replicas key on the "
                         "control plane (DYN_CONTROL_PLANE)")
    args = ap.parse_args()
    setup_logging()

    plane = None
    runtime = None
    if args.follow_planner:
        from dynamo_tpu.runtime import DistributedRuntime

        runtime = await DistributedRuntime.create()
        plane = runtime.plane
    op = await ProcessOperator(args.spec, plane=plane,
                               namespace=args.namespace,
                               tick_s=args.tick).start()
    print("OPERATOR_READY", flush=True)
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    await op.stop()
    if runtime is not None:
        await runtime.shutdown()


def main():
    asyncio.run(amain())


if __name__ == "__main__":
    main()
