"""Fleet scorecard (observability/scorecard.py + the frontend join).

The scorecard's value is falsifiability: every rollup is cross-checked
against an independent instrument fed from the same events. These tests
exercise the join math exactly — histogram-vs-tracker count equality,
the bucket-derived breach BRACKET, attribution reconciliation — plus the
HTTP route, the ``dynctl fleet`` renderer, and a bounded flagship-drive
smoke (the scaled-down ISSUE 16 cycle: operator-spawned mocker fleet,
chaos kills, audit heals, live saturation gauge).
"""

import asyncio
from types import SimpleNamespace

import aiohttp
import pytest

from dynamo_tpu.frontend.http import HttpService
from dynamo_tpu.llm.discovery import ModelManager
from dynamo_tpu.observability.scorecard import (
    HubSaturationTracker, class_hist_stats, hub_rpc_total, render_scorecard,
)
from dynamo_tpu.runtime.metrics import MetricsRegistry

pytestmark = pytest.mark.anyio


# ------------------------------------------------------------- unit: hub


def test_hub_rpc_total_excludes_stream_publish():
    # stream appends scale separately from the rpc ceiling (PERF_NOTES) —
    # they must not count against it
    events = {"request": 10, "kv_put": 5, "publish": 2,
              "stream_publish": 10_000}
    assert hub_rpc_total(events) == 17
    assert hub_rpc_total({}) == 0
    assert hub_rpc_total(None) == 0


def test_saturation_tracker_window_math():
    clock = [0.0]
    t = HubSaturationTracker(rpc_ceiling=100.0, blocks_ceiling=1000.0,
                             now_fn=lambda: clock[0])
    t.sample({"events": {"request": 10}}, blocks_stored=100)
    # one sample spans no interval: no rate, no ratio
    assert t.rates() == {"rpc": None, "blocks": None}
    assert t.ratios() == {"rpc": None, "blocks": None}
    clock[0] = 10.0
    t.sample({"events": {"request": 100, "stream_publish": 9999,
                         "kv_put": 10}}, blocks_stored=600)
    # rpc: (110 - 10) / 10s (stream_publish excluded); blocks: 500 / 10s
    assert t.rates() == {"rpc": 10.0, "blocks": 50.0}
    assert t.ratios() == {"rpc": 0.1, "blocks": 0.05}


def test_saturation_tracker_counter_regression_resets_window():
    clock = [0.0]
    t = HubSaturationTracker(rpc_ceiling=100.0, blocks_ceiling=1000.0,
                             now_fn=lambda: clock[0])
    t.sample({"events": {"request": 50}}, blocks_stored=500)
    clock[0] = 5.0
    t.sample({"events": {"request": 100}}, blocks_stored=600)
    assert t.rates()["rpc"] == 10.0
    # hub restarted: cumulative totals regressed — the window must reset
    # instead of reporting a negative rate
    clock[0] = 6.0
    t.sample({"events": {"request": 3}}, blocks_stored=10)
    assert t.rates() == {"rpc": None, "blocks": None}


def test_saturation_ceilings_from_env(monkeypatch):
    monkeypatch.setenv("DYN_HUB_CEILING_RPC", "123.5")
    monkeypatch.setenv("DYN_HUB_CEILING_BLOCKS", "not-a-number")
    t = HubSaturationTracker()
    assert t.rpc_ceiling == 123.5
    from dynamo_tpu.observability.scorecard import DEFAULT_BLOCKS_CEILING
    assert t.blocks_ceiling == DEFAULT_BLOCKS_CEILING


# ------------------------------------------- unit: histogram breach math


def test_class_hist_stats_breach_bracket_exact():
    hist = MetricsRegistry().histogram(
        "t", buckets=(0.05, 0.15, 0.3, 0.6, 1.2))
    # interactive @ 200ms target: 0.04/0.1 below, 0.25/0.5/2.0 above
    for v in (0.04, 0.1, 0.25, 0.5, 2.0):
        hist.observe(v, qos="interactive")
    hist.observe(9.0, qos="batch")  # no target: no bracket
    out = class_hist_stats(hist, {"interactive": 200.0, "batch": None})
    s = out["interactive"]
    assert s["count"] == 5
    assert s["sum_s"] == pytest.approx(2.89)
    # above the smallest edge >= 0.2s (0.3): the 0.5 and 2.0 obs → lower
    # bound 2; above the largest edge <= 0.2s (0.15): also 0.25 → upper
    # bound 3. The true breach count (3) provably lies inside.
    assert s["breach_bracket"] == [2, 3]
    assert s["target_ms"] == 200.0
    assert s["p95_s_le"] is None  # 95th-percentile obs sits in +Inf
    assert "breach_bracket" not in out["batch"]


# ------------------------------------- the frontend join (no fleet needed)


def _svc() -> HttpService:
    return HttpService(ModelManager(), port=0)


def _feed(svc: HttpService, cls: str, ttft_s: float) -> None:
    """Both halves of the first-token callback, exactly as the SSE path
    does it (http.py: _ttft_class.observe + _note_slo)."""
    svc._ttft_class.observe(ttft_s, qos=cls)
    svc._note_slo(SimpleNamespace(priority=cls, id="req-x"), ttft_s)


async def test_slo_join_checks_pass_when_paths_agree():
    svc = _svc()
    for dt in (0.01, 0.02, 5.0):  # one clear breach of the 200ms default
        _feed(svc, "interactive", dt)
    doc = await svc.scorecard.document()
    s = doc["now"]["slo"]["interactive"]
    assert s["requests_hist"] == s["requests_tracker"] == 3
    assert s["breaches_tracker"] == 1
    lo, hi = s["breach_bracket_hist"]
    assert lo <= 1 <= hi
    names = {c["name"]: c["ok"] for c in doc["checks"]}
    assert names["slo_count[interactive]"]
    assert names["slo_breaches[interactive]"]
    assert doc["ok"]


async def test_slo_join_desync_is_flagged():
    svc = _svc()
    _feed(svc, "interactive", 0.01)
    # a path losing samples: histogram observed, tracker never told
    svc._ttft_class.observe(0.02, qos="interactive")
    doc = await svc.scorecard.document()
    bad = [c for c in doc["checks"] if not c["ok"]]
    assert [c["name"] for c in bad] == ["slo_count[interactive]"]
    assert "hist 2 vs tracker 1" in bad[0]["detail"]
    assert not doc["ok"]


async def test_breach_undercount_fails_bracket_check():
    svc = _svc()
    # tracker claims zero breaches while the histogram PROVES >= 1:
    # 5.0s sits above every edge <= the 200ms target
    svc._ttft_class.observe(5.0, qos="interactive")
    svc._burn.note("interactive", 0.01)  # same count, wrong latency
    doc = await svc.scorecard.document()
    names = {c["name"]: c["ok"] for c in doc["checks"]}
    assert names["slo_count[interactive]"]          # counts still agree
    assert not names["slo_breaches[interactive]"]   # bracket refutes it
    assert not doc["ok"]


async def test_phase_cards_delta_math():
    svc = _svc()
    _feed(svc, "interactive", 0.01)
    await svc.scorecard.mark_phase("peak")
    for dt in (0.02, 0.03):
        _feed(svc, "interactive", dt)
    card = await svc.scorecard.mark_phase(None)
    # the card carries the PHASE's deltas, not the cumulative totals
    assert card["phase"] == "peak"
    assert card["slo"]["interactive"]["requests_hist"] == 2
    assert card["slo"]["interactive"]["requests_tracker"] == 2
    assert card["slo"]["interactive"]["breaches_tracker"] == 0
    assert all(c["ok"] for c in card["checks"])
    assert svc.scorecard.phases == [card]
    doc = await svc.scorecard.document()
    assert doc["phases"][0]["phase"] == "peak"
    assert doc["ok"]


async def test_attribution_reconciliation_check():
    svc = _svc()
    good = {"request_id": "r1", "e2e_ms": 100.0, "residual_ms": 2.0,
            "total": {"prefill": 60.0, "decode": 38.0, "unattributed": 2.0}}
    bad = {"request_id": "r2", "e2e_ms": 100.0,
           "total": {"prefill": 60.0}}  # 40ms of e2e unexplained
    svc.scorecard.note_attribution(good)
    doc = await svc.scorecard.document()
    names = {c["name"]: c for c in doc["checks"]}
    assert names["attr_reconcile"]["ok"]
    svc.scorecard.note_attribution(bad)
    doc = await svc.scorecard.document()
    names = {c["name"]: c for c in doc["checks"]}
    assert not names["attr_reconcile"]["ok"]
    assert "1/2" in names["attr_reconcile"]["detail"]
    assert svc.scorecard.attr_failures[0]["request_id"] == "r2"


async def test_render_scorecard_text():
    svc = _svc()
    for dt in (0.01, 5.0):
        _feed(svc, "interactive", dt)
    doc = await svc.scorecard.document()
    text = render_scorecard(doc)
    assert "fleet scorecard  [OK]" in text
    assert "interactive" in text and "200ms" in text
    assert text.rstrip().endswith("passed")
    # now a desynced doc: the renderer must surface the failed check
    svc._ttft_class.observe(0.02, qos="interactive")
    text = render_scorecard(await svc.scorecard.document())
    assert "CHECK FAILURES" in text
    assert "FAILED slo_count[interactive]" in text


# --------------------------------------------- HTTP route + dynctl fleet


async def test_scorecard_route_and_dynctl_fleet(capsys):
    from dynamo_tpu.runtime import DistributedRuntime
    from dynamo_tpu.runtime.dynctl import fleet_amain

    rt = await DistributedRuntime.create()
    service = HttpService(ModelManager(), port=0, runtime=rt)
    await service.start()
    try:
        _feed(service, "interactive", 0.01)
        base = f"http://127.0.0.1:{service.port}"
        async with aiohttp.ClientSession() as http:
            async with http.get(f"{base}/v1/fleet/scorecard") as r:
                assert r.status == 200
                doc = await r.json()
        assert doc["ok"]
        assert doc["now"]["slo"]["interactive"]["requests_hist"] == 1
        assert {c["name"] for c in doc["checks"]} >= {
            "slo_count[interactive]", "slo_breaches[interactive]"}
        assert "saturation" in doc["now"]["hub"]
        # dynctl fleet: fetch + render the same route
        await fleet_amain(base, as_json=False)
        out = capsys.readouterr().out
        assert "fleet scorecard  [OK]" in out
        assert "interactive" in out
    finally:
        await service.stop()
        await rt.shutdown()


# ------------------------------------------- bounded flagship-drive smoke


async def test_flagship_drive_smoke():
    """Scaled-down ISSUE 16 cycle: 1+3 mocker fleet at the plan's step
    economics, pinned (no autoscaler), seeded decode kills, audit + attr
    sampler + scorecard phases live. Bounded: ~12s wall."""
    from benchmarks.flagship_drive import drive

    out = await asyncio.wait_for(
        drive(duration_s=8.0, scale=0.5, seed=7, kill_error=0.004,
              autoscale=False),
        timeout=180.0)
    assert out["requests"] > 0
    assert out["failed"] == 0, out
    assert out["lost_tokens"] == 0, out
    assert out["audit_divergence_end"] == 0, out
    assert out["scorecard_failed_checks"] == [], out
    assert out["scorecard_phases"] >= 3
    assert out["saturation_gauge_live"], "gauge never appeared on /metrics"
    assert out["hub_rpc_per_s"] and out["hub_rpc_per_s"] > 0
    assert out["flagship_ok"], {k: v for k, v in out.items()
                                if k != "scorecard"}
