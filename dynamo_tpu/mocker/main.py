"""``python -m dynamo_tpu.mocker.main`` — run a mocker worker.

Equivalent of the reference's ``components/backends/mocker`` CLI: joins the
control plane, serves the ``generate`` endpoint, registers the model, and
emits KV events + load metrics like a real engine.
"""

from __future__ import annotations

import argparse
import asyncio
import signal
from typing import Optional

from dynamo_tpu.llm.model_card import ModelDeploymentCard, register_llm
from dynamo_tpu.mocker.engine import MockEngine, MockEngineArgs
from dynamo_tpu.router.publisher import KvEventPublisher, WorkerMetricsPublisher
from dynamo_tpu.runtime import DistributedRuntime
from dynamo_tpu.runtime.config import setup_logging


async def run_mocker(
    runtime: DistributedRuntime,
    model_name: str,
    args: MockEngineArgs,
    namespace: str = "dynamo",
    component: str = "mocker",
    endpoint: str = "generate",
    lease_id=None,
    migration_limit: Optional[int] = None,
    topo: Optional[dict] = None,
):
    """Start ``args.dp_size`` simulated ranks on one endpoint.

    Each rank gets its own lease, scheduler, KV-event publisher and
    metrics publisher (ref: mocker/engine.rs:115-127,199-296 — one of each
    per DP rank), so the router observes the same per-rank event
    interleaving a real DP fleet produces. Returns (engines, handles);
    single-rank callers get 1-element lists."""
    if args.startup_time:
        await asyncio.sleep(args.startup_time)
    ep = runtime.namespace(namespace).component(component).endpoint(endpoint)
    engines, handles = [], []
    # start the runtime keepalive loop unconditionally — extra-rank leases
    # are adopted into it so they cannot silently expire mid-run
    primary = await runtime.primary_lease()
    lease0 = None
    for rank in range(max(1, args.dp_size)):
        if rank == 0 and lease_id is not None:
            lease = lease_id
            runtime.adopt_lease(lease)
        elif rank == 0:
            lease = primary
        else:
            lease = await runtime.plane.lease_create(
                runtime.config.lease_ttl)
            runtime.adopt_lease(lease)
        lease0 = lease0 if lease0 is not None else lease
        kv_pub = KvEventPublisher(runtime.plane, worker_id=lease,
                                  kv_block_size=args.block_size)
        await kv_pub.start_resync_responder()
        metrics_pub = WorkerMetricsPublisher(runtime.plane, worker_id=lease)
        engine = await MockEngine(args, kv_pub, metrics_pub).start()
        # KV audit plane parity (docs/observability.md "KV audit"): each
        # rank serves its residency digests under its own lease, exactly
        # like a real engine worker (caching-off ranks have no residency
        # contract to audit — engine/main.py parity)
        if args.enable_prefix_caching:
            from dynamo_tpu.observability.kvaudit import serve_kv_digest
            await serve_kv_digest(runtime, engine.kv_ledger, lease,
                                  publisher=kv_pub)
        # synthetic locality labels ({"host":…,"slice":…,"pod":…}) let fleet
        # tests/benches exercise topology-costed routing without real slices
        meta = {"dp_rank": rank}
        if topo:
            meta["topo"] = dict(topo)
        handle = await ep.serve_endpoint(engine.generate, lease_id=lease,
                                         metadata=meta)
        # kv_session stub (docs/sessions.md): mockers have no KVBM tiers,
        # so park/restore report honest zeros — fleet drives still carry
        # session traffic end-to-end (frontend registry, affinity routing,
        # reaper park calls) without wire errors. The stub handle rides
        # the generate handle's stop() so callers' (engines, handles)
        # unpacking contract stays exactly one handle per rank.
        from dynamo_tpu.sessions import SESSION_ENDPOINT, SessionKvHandler
        session_handle = await runtime.namespace(namespace).component(
            component).endpoint(SESSION_ENDPOINT).serve_endpoint(
            SessionKvHandler(None).generate, lease_id=lease)
        _orig_stop = handle.stop

        async def _stop(*a, _o=_orig_stop, _s=session_handle, **kw):
            try:
                await _s.stop(graceful=False)
            except Exception:
                pass
            return await _o(*a, **kw)

        handle.stop = _stop
        engines.append(engine)
        handles.append(handle)
    card = ModelDeploymentCard(
        display_name=model_name,
        kv_cache_block_size=args.block_size,
        eos_token_ids=[2],
        tokenizer_ref="test",
    )
    if migration_limit is not None:
        card.migration_limit = migration_limit
    card.runtime_config.total_kv_blocks = args.num_gpu_blocks
    card.runtime_config.max_num_seqs = args.max_num_seqs
    card.runtime_config.max_num_batched_tokens = args.max_num_batched_tokens
    await register_llm(runtime, ep, card, lease_id=lease0)
    # expose this process's span buffer to /v1/traces/{id} + dynctl trace
    from dynamo_tpu.observability import ensure_trace_endpoint

    await ensure_trace_endpoint(runtime)
    # per-rank flight recorders → /v1/fleet/steps + dynctl top/timeline
    from dynamo_tpu.observability.flight import (
        ensure_flight_endpoint, register_recorder,
    )
    for rank, engine in enumerate(engines):
        name = component if len(engines) == 1 else f"{component}-r{rank}"
        engine.flight.service = name
        engine._flight_name = register_recorder(name, engine.flight)
    await ensure_flight_endpoint(runtime)
    return engines, handles


async def amain():
    ap = argparse.ArgumentParser(description="dynamo-tpu mocker worker")
    ap.add_argument("--model", default="mock-model")
    ap.add_argument("--namespace", default="dynamo")
    ap.add_argument("--component", default="mocker")
    ap.add_argument("--block-size", type=int, default=16)
    ap.add_argument("--num-gpu-blocks", type=int, default=8192)
    ap.add_argument("--max-num-seqs", type=int, default=256)
    ap.add_argument("--max-num-batched-tokens", type=int, default=8192)
    ap.add_argument("--speedup-ratio", type=float, default=1.0)
    # step-timing model knobs: let a drive instantiate PLAN-derived
    # per-step costs (benchmarks/plan_70b.py --emit-placement → solved
    # step_ms) instead of the generic tiny-model defaults
    ap.add_argument("--prefill-base-ms", type=float, default=None,
                    help="fixed prefill step cost (MockEngineArgs default "
                         "5.0)")
    ap.add_argument("--prefill-per-token-ms", type=float, default=None,
                    help="per-prefill-token step cost (default 0.02)")
    ap.add_argument("--decode-base-ms", type=float, default=None,
                    help="fixed decode step cost (default 2.0)")
    ap.add_argument("--decode-per-seq-ms", type=float, default=None,
                    help="per-running-sequence decode cost (default 0.05)")
    ap.add_argument("--dp-size", type=int, default=1,
                    help="simulated DP ranks (one scheduler + KV event "
                         "stream + metrics stream per rank)")
    ap.add_argument("--startup-time", type=float, default=None)
    ap.add_argument("--no-prefix-caching", action="store_true")
    ap.add_argument("--no-token-budget-plan", dest="token_budget_plan",
                    action="store_false", default=True,
                    help="restore independent prefill/decode step budgets "
                         "(the pre-ragged engine timing model) instead of "
                         "one co-scheduled token budget per step")
    ap.add_argument("--migration-limit", type=int, default=None,
                    help="max stream migrations per request (model card "
                         "migration_limit; raise under chaos/worker churn)")
    ap.add_argument("--topo-host", default=None,
                    help="locality label: host (default DYN_TOPO_HOST)")
    ap.add_argument("--topo-slice", default=None,
                    help="locality label: slice (default DYN_TOPO_SLICE)")
    ap.add_argument("--topo-pod", default=None,
                    help="locality label: pod (default DYN_TOPO_POD)")
    ap.add_argument(
        "--vocab-size", type=int, default=0,
        help="0 = derive from the model tokenizer so outputs decode to text",
    )
    cli = ap.parse_args()

    vocab_size = cli.vocab_size
    if vocab_size <= 0:
        from dynamo_tpu.llm.tokenizer import make_test_tokenizer

        vocab_size = make_test_tokenizer().vocab_size

    runtime = await DistributedRuntime.create()
    args = MockEngineArgs(
        num_gpu_blocks=cli.num_gpu_blocks,
        block_size=cli.block_size,
        max_num_seqs=cli.max_num_seqs,
        max_num_batched_tokens=cli.max_num_batched_tokens,
        speedup_ratio=cli.speedup_ratio,
        enable_prefix_caching=not cli.no_prefix_caching,
        vocab_size=vocab_size,
        dp_size=cli.dp_size,
        startup_time=cli.startup_time,
        token_budget_plan=cli.token_budget_plan,
    )
    for flag, field in (("prefill_base_ms", "prefill_base_ms"),
                        ("prefill_per_token_ms", "prefill_per_token_ms"),
                        ("decode_base_ms", "decode_base_ms"),
                        ("decode_per_seq_ms", "decode_per_seq_ms")):
        v = getattr(cli, flag)
        if v is not None:
            setattr(args, field, v)
    topo = {k: v for k, v in (("host", cli.topo_host),
                              ("slice", cli.topo_slice),
                              ("pod", cli.topo_pod)) if v}
    engines, handles = await run_mocker(
        runtime, cli.model, args, cli.namespace, cli.component,
        migration_limit=cli.migration_limit, topo=topo or None,
    )
    # chaos worker.kill = SIGKILL-grade process death: no drain, no lease
    # revoke — the fleet learns only when the lease TTL expires
    import os as _os

    for engine in engines:
        engine.on_kill.append(lambda: _os._exit(137))
    print("MOCKER_READY", flush=True)

    loop = asyncio.get_running_loop()
    stop = asyncio.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    # SIGTERM drain (same contract as engine/main.py): deregister first so
    # routers stop picking this worker, then give in-flight streams the
    # DYN_DRAIN_TIMEOUT window instead of holding shutdown open forever —
    # the operator's drain-safe scale-down counts on this bound
    for handle in handles:
        await handle.stop(graceful=True,
                          timeout=runtime.config.drain_timeout)
    for engine in engines:
        await engine.stop()
    await runtime.shutdown()


def main():
    setup_logging()
    asyncio.run(amain())


if __name__ == "__main__":
    main()
