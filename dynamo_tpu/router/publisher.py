"""Worker-side publishers: KV cache events and load metrics.

Rebuild of the reference's ``KvEventPublisher``/``WorkerMetricsPublisher``
(ref: lib/llm/src/kv_router/publisher.rs:48-223, protocols.rs:48-84): engines
report block stored/removed/cleared to the ``kv_events`` durable stream and
``ForwardPassMetrics`` on the ``kv_metrics`` subject; routers and the metrics
aggregator consume them.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

import msgpack

from dynamo_tpu.router.protocols import (
    KV_EVENTS_STREAM,
    KV_METRICS_SUBJECT,
    KV_RESYNC_SUBJECT,
    ForwardPassMetrics,
    KvCacheEvent,
    RouterEvent,
    StoredBlock,
)

logger = logging.getLogger("dynamo.kv_publisher")


def _spawn_publish(owner, coro) -> None:
    """Task-spawn that survives GC (asyncio keeps only weak task refs) and
    logs failures instead of dropping them as never-retrieved exceptions."""
    tasks = getattr(owner, "_inflight_publishes", None)
    if tasks is None:
        tasks = owner._inflight_publishes = set()
    task = asyncio.get_running_loop().create_task(coro)
    tasks.add(task)

    def _done(t):
        tasks.discard(t)
        if not t.cancelled() and t.exception() is not None:
            logger.warning("publish failed: %r", t.exception())

    task.add_done_callback(_done)


def reachable_chain(entries: dict[int, tuple[Optional[int], int]],
                    member: Optional[set] = None
                    ) -> list[tuple[int, Optional[int], int]]:
    """Root-anchored ordered subset of a publisher mirror: the blocks a
    resync replay can re-announce, parents before children.

    ``entries`` is ``{block_hash: (parent_hash | None, tokens_hash)}``;
    ``member`` (optional) restricts anchoring to hashes actually resident
    per the worker's KV ledger (observability/kvaudit.py) — a mirror
    entry whose block left every servable tier must neither be replayed
    nor anchor its children. Iterates to fixpoint: mirror order USUALLY
    has parents first, but a remove-then-re-store moves a parent behind
    its children (dict re-insertion), so one pass could drop valid
    chains. Entries never reached are dangling (ancestor evicted while
    the child survives) — unroutable anyway, since find_matches walks
    from the root."""
    reachable: set[int] = set()
    pending = list(entries.items())
    ordered: list[tuple[int, Optional[int], int]] = []
    while True:
        still = []
        for bh, (parent, tokens_hash) in pending:
            if member is not None and bh not in member:
                continue  # stale mirror entry: cannot anchor anything
            if parent is None or parent in reachable:
                reachable.add(bh)
                ordered.append((bh, parent, tokens_hash))
            else:
                still.append((bh, (parent, tokens_hash)))
        if len(still) == len(pending):
            break  # the rest are genuinely dangling
        pending = still
    return ordered


class KvEventPublisher:
    """Publishes KV cache deltas to the durable stream AND mirrors what it
    has announced, so a router that detects a stream gap can ask for a full
    re-announcement instead of serving a silently-stale radix index.

    ``ledger`` (observability/kvaudit.WorkerKvLedger, optional): the
    worker's tier-residency ground truth. When attached, a resync replay
    reconciles the mirror against it — mirror entries whose blocks left
    every servable tier (an eviction whose removal event a bug or the
    wire lost) are dropped from the mirror AND published as removals, so
    the replay heals phantom adverts at every replica, not just the one
    that purged (docs/observability.md "KV audit")."""

    def __init__(self, plane, worker_id: int, kv_block_size: int,
                 stream: str = KV_EVENTS_STREAM, ledger=None):
        self.plane = plane
        self.worker_id = worker_id
        self.kv_block_size = kv_block_size
        self.stream = stream
        self.ledger = ledger
        self._event_id = 0
        # block_hash -> (parent_block_hash | None, tokens_hash), insertion-
        # ordered so a replay announces parents before children
        self._announced: dict[int, tuple[Optional[int], int]] = {}
        self._resync_sub = None
        self._resync_task = None
        self.resyncs_served = 0
        # Serializes stream appends so a resync replay is atomic w.r.t.
        # concurrent delta publishes: without it, a removed(h) landing
        # between two replay chains that re-announce h would leave the
        # router believing h exists after the worker evicted it (the
        # mirror is mutated synchronously, so snapshot-then-replay under
        # the lock always converges to the worker's true state).
        self._publish_lock = asyncio.Lock()

    def _next_id(self) -> int:
        self._event_id += 1
        return self._event_id

    async def publish(self, event: KvCacheEvent) -> None:
        async with self._publish_lock:
            await self._publish_unlocked(event)

    async def _publish_unlocked(self, event: KvCacheEvent) -> None:
        wire = RouterEvent(self.worker_id, event).to_wire()
        await self.plane.stream_publish(self.stream, msgpack.packb(wire))

    async def publish_stored(
        self,
        parent_hash: Optional[int],
        blocks: list[StoredBlock],
    ) -> None:
        prev = parent_hash
        for b in blocks:
            self._announced[b.block_hash] = (prev, b.tokens_hash)
            prev = b.block_hash
        await self.publish(KvCacheEvent.stored(self._next_id(), parent_hash, blocks))

    async def publish_removed(self, block_hashes: list[int]) -> None:
        for h in block_hashes:
            self._announced.pop(h, None)
        await self.publish(KvCacheEvent.removed(self._next_id(), block_hashes))

    async def publish_cleared(self) -> None:
        self._announced.clear()
        await self.publish(KvCacheEvent.clear(self._next_id()))

    def publish_sync(self, event: KvCacheEvent) -> None:
        """Fire-and-forget adapter for engines' synchronous event callbacks."""
        # keep the mirror coherent for events routed around the typed helpers
        if event.stored_blocks:
            prev = event.stored_parent_hash
            for b in event.stored_blocks:
                self._announced[b.block_hash] = (prev, b.tokens_hash)
                prev = b.block_hash
        elif event.removed_hashes:
            for h in event.removed_hashes:
                self._announced.pop(h, None)
        elif event.cleared:
            self._announced.clear()
        _spawn_publish(self, self.publish(event))

    # -- resync (gap recovery) ------------------------------------------
    async def start_resync_responder(self) -> "KvEventPublisher":
        """Answer router gap-resync requests by re-announcing every block
        this worker currently holds. Stored events are idempotent upserts in
        the radix tree, so healthy routers consuming the same stream just
        re-confirm what they already know."""
        self._resync_sub = await self.plane.subscribe(f"{KV_RESYNC_SUBJECT}.{self.stream}")
        self._resync_task = asyncio.get_running_loop().create_task(self._resync_loop())
        return self

    async def stop(self):
        if self._resync_task:
            self._resync_task.cancel()
        if self._resync_sub:
            await self._resync_sub.cancel()

    async def _resync_loop(self):
        try:
            async for _subject, _payload in self._resync_sub:
                try:
                    await self._replay_announced()
                    self.resyncs_served += 1
                except Exception:
                    logger.exception("kv resync replay failed")
        except asyncio.CancelledError:
            pass

    def announced_chain(self) -> dict[int, tuple[Optional[int], int]]:
        """Snapshot of the announce mirror (block → (parent, tokens_hash))
        — the chain structure the kv_digest diff op serves."""
        return dict(self._announced)

    async def _replay_announced(self):
        """Re-publish the mirror as chained stored events. Consecutive blocks
        whose parent is the previous block collapse into one event. Holds the
        publish lock for the WHOLE replay: the mirror snapshot and its stream
        appends form one atomic unit, and any delta publish racing with the
        replay lands after it — so the stream's final word on every block
        matches the mirror's."""
        async with self._publish_lock:
            # Only replay blocks REACHABLE from a root-anchored chain
            # (see reachable_chain): a dangling entry can't be routed to
            # anyway, and emitting it would be an eternal orphan at every
            # indexer, re-triggering a fleet-wide replay each time.
            snapshot = list(self._announced.items())
            member = None
            if self.ledger is not None:
                # ledger reconciliation (the audit plane's phantom heal):
                # mirror entries no servable tier holds anymore were
                # announced but never retracted — a suppression bug or a
                # wire-lost removal. Replaying them would resurrect the
                # phantom at every purged replica; instead retract them
                # here, so the replay's final word matches RESIDENCY, not
                # just past announcements.
                member = set(self.ledger.servable_hashes())
                stale = [bh for bh, _ in snapshot if bh not in member]
                if stale:
                    logger.warning(
                        "kv resync: retracting %d announced-but-not-"
                        "resident blocks (lost/suppressed removals)",
                        len(stale))
                    for bh in stale:
                        self._announced.pop(bh, None)
                    snapshot = [e for e in snapshot if e[0] in member]
                    await self._publish_unlocked(KvCacheEvent.removed(
                        self._next_id(), stale))
            items = reachable_chain(dict(snapshot), member=member)
            chain_parent: Optional[int] = None
            chain: list[StoredBlock] = []
            prev_hash: Optional[int] = None
            for bh, parent, tokens_hash in items:
                if chain and parent != prev_hash:
                    await self._publish_unlocked(
                        KvCacheEvent.stored(self._next_id(), chain_parent, chain))
                    chain = []
                if not chain:
                    chain_parent = parent
                chain.append(StoredBlock(block_hash=bh, tokens_hash=tokens_hash))
                prev_hash = bh
            if chain:
                await self._publish_unlocked(
                    KvCacheEvent.stored(self._next_id(), chain_parent, chain))


class WorkerMetricsPublisher:
    def __init__(self, plane, worker_id: int, subject: str = KV_METRICS_SUBJECT):
        self.plane = plane
        self.worker_id = worker_id
        self.subject = subject

    async def publish(self, metrics: ForwardPassMetrics) -> None:
        wire = {"worker_id": self.worker_id, "metrics": metrics.to_wire()}
        await self.plane.publish(self.subject, msgpack.packb(wire))

    def publish_sync(self, metrics: ForwardPassMetrics) -> None:
        _spawn_publish(self, self.publish(metrics))


def parse_load_event(payload: bytes) -> tuple[int, ForwardPassMetrics]:
    """Decode one ``kv_metrics`` message → (worker_id, metrics). The ONE
    place that knows the wire shape — MetricsAggregator and the runtime's
    WorkerMonitor both ride it, so a format change can't silently diverge."""
    d = msgpack.unpackb(payload, raw=False)
    return d["worker_id"], ForwardPassMetrics.from_wire(d["metrics"])


class MetricsAggregator:
    """Collects the latest ForwardPassMetrics per worker (ref: metrics_aggregator.rs).

    Workers that stop reporting (crash, scale-down drain) age out of the
    aggregate after ``stale_after_s`` — without expiry a drained worker's
    last report would count as phantom load/backlog forever, which the
    autoscale loop would read as demand that never drains. Expiry is
    OPT-IN (default off): workers publish only while actively stepping,
    so an expiring aggregate reads a healthy-but-idle fleet as empty —
    wrong for the cluster /metrics view, right for the autoscaler (which
    cares about load, not liveness)."""

    def __init__(self, plane, subject: str = KV_METRICS_SUBJECT,
                 stale_after_s: float = 0.0):
        self.plane = plane
        self.subject = subject
        self.stale_after_s = stale_after_s
        self.latest: dict[int, ForwardPassMetrics] = {}
        self._seen_at: dict[int, float] = {}
        self._sub = None
        self._task: Optional[asyncio.Task] = None

    async def start(self) -> "MetricsAggregator":
        self._sub = await self.plane.subscribe(self.subject)
        self._task = asyncio.get_running_loop().create_task(self._loop())
        return self

    async def stop(self):
        if self._task:
            self._task.cancel()
        if self._sub:
            await self._sub.cancel()

    async def _loop(self):
        import time as _time

        try:
            async for _subject, payload in self._sub:
                try:
                    worker_id, metrics = parse_load_event(payload)
                    self.latest[worker_id] = metrics
                    self._seen_at[worker_id] = _time.monotonic()
                except Exception:
                    logger.exception("bad metrics payload ignored")
        except asyncio.CancelledError:
            pass

    def _expire_stale(self) -> None:
        import time as _time

        if not self.stale_after_s:
            return
        cutoff = _time.monotonic() - self.stale_after_s
        for wid in [w for w, t in self._seen_at.items() if t < cutoff]:
            self._seen_at.pop(wid, None)
            self.latest.pop(wid, None)

    def snapshot(self) -> dict:
        """Per-worker latest metrics with staleness expiry applied —
        readers of per-worker state (the operator's victim selection)
        must use this, not ``.latest`` directly, or a long-idle worker's
        final busy report reads as current load forever."""
        self._expire_stale()
        return dict(self.latest)

    def aggregate(self) -> dict:
        self._expire_stale()
        total_active = sum(m.kv_stats.kv_active_blocks for m in self.latest.values())
        total_blocks = sum(m.kv_stats.kv_total_blocks for m in self.latest.values())
        return {
            "workers": len(self.latest),
            "kv_active_blocks": total_active,
            "kv_total_blocks": total_blocks,
            "gpu_cache_usage_perc": (total_active / total_blocks) if total_blocks else 0.0,
            "requests_active": sum(
                m.worker_stats.request_active_slots for m in self.latest.values()
            ),
            "requests_waiting": sum(
                m.worker_stats.num_requests_waiting for m in self.latest.values()
            ),
            "total_slots": sum(
                m.worker_stats.request_total_slots for m in self.latest.values()
            ),
        }
