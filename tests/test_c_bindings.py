"""C ABI bindings e2e: an "external engine" (ctypes driving the C ABI the
way a C++ runtime would) publishes KV events over the control-plane TCP
protocol; the router-side indexer must see them exactly like native-engine
events (ref: lib/bindings/c/src/lib.rs:40-326)."""

import asyncio
import ctypes
import os

import pytest

from dynamo_tpu.router.indexer import RadixTree
from dynamo_tpu.router.protocols import KV_EVENTS_STREAM, RouterEvent
from dynamo_tpu.runtime.control_plane import ControlPlaneServer
from dynamo_tpu.tokens import compute_block_hash_for_seq

pytestmark = pytest.mark.anyio

_SO = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "dynamo_tpu", "libdynamo_native.so")


@pytest.fixture
def clib():
    if not os.path.exists(_SO):
        from dynamo_tpu.native_build import build

        build(verbose=False)
    lib = ctypes.CDLL(_SO)
    lib.dynamo_llm_init.restype = ctypes.c_int
    lib.dynamo_llm_init.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_uint64, ctypes.c_uint32]
    lib.dynamo_llm_shutdown.restype = ctypes.c_int
    lib.dynamo_kv_event_publish_stored.restype = ctypes.c_int
    lib.dynamo_kv_event_publish_stored.argtypes = [
        ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint32),
        ctypes.POINTER(ctypes.c_size_t), ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_size_t, ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint64]
    lib.dynamo_kv_event_publish_removed.restype = ctypes.c_int
    lib.dynamo_kv_event_publish_removed.argtypes = [
        ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint64), ctypes.c_size_t]
    return lib


async def test_c_publish_feeds_router(clib):
    server = ControlPlaneServer(port=0)
    addr = await server.start()
    WORKER = 0xBEEF
    BS = 4
    tokens = list(range(1, 13))  # 3 full blocks
    # external ids double as the blocks' identity (chained hashes here so
    # the radix tree sees a real lineage)
    seq_hashes = [101, 102, 103]

    def c_init():
        return clib.dynamo_llm_init(addr.encode(), b"dynamo", b"backend",
                                    WORKER, BS)

    def c_stored():
        tok = (ctypes.c_uint32 * len(tokens))(*tokens)
        nbt = (ctypes.c_size_t * 3)(BS, BS, BS)
        ids = (ctypes.c_uint64 * 3)(*seq_hashes)
        return clib.dynamo_kv_event_publish_stored(
            1, tok, nbt, ids, 3, None, 0)

    def c_removed():
        ids = (ctypes.c_uint64 * 1)(seq_hashes[2])
        return clib.dynamo_kv_event_publish_removed(2, ids, 1)

    try:
        # the C client is blocking: run it off the event loop
        assert await asyncio.to_thread(c_init) == 0
        assert await asyncio.to_thread(c_stored) == 0

        # read the durable stream like the router background task does
        sub = await server.core.stream_subscribe(KV_EVENTS_STREAM, 0)
        seq, payload = await asyncio.wait_for(sub.__aiter__().__anext__(), 5)
        import msgpack

        ev = RouterEvent.from_wire(msgpack.unpackb(payload, raw=False))
        assert ev.worker_id == WORKER
        assert [b.block_hash for b in ev.event.stored_blocks] == seq_hashes
        # tokens_hash computed C-side must be bit-identical to tokens.py
        want = compute_block_hash_for_seq(tokens, BS)
        assert [b.tokens_hash for b in ev.event.stored_blocks] == want
        assert ev.event.stored_parent_hash is None

        assert await asyncio.to_thread(c_removed) == 0
        _, payload = await asyncio.wait_for(sub.__aiter__().__anext__(), 5)
        ev2 = RouterEvent.from_wire(msgpack.unpackb(payload, raw=False))
        assert ev2.event.removed_hashes == [seq_hashes[2]]
        await sub.cancel()

        # and the radix tree folds them like any native worker's events
        tree = RadixTree()
        tree.apply_event(ev)
        tree.apply_event(ev2)
        scores = tree.find_matches(want[:2]).scores
        assert scores.get(WORKER) == 2

        # partial block must be rejected loudly (ref: lib.rs checks)
        tok = (ctypes.c_uint32 * 3)(1, 2, 3)
        nbt = (ctypes.c_size_t * 1)(3)
        ids = (ctypes.c_uint64 * 1)(7)
        rc = await asyncio.to_thread(
            lambda: clib.dynamo_kv_event_publish_stored(3, tok, nbt, ids, 1,
                                                        None, 0))
        assert rc != 0
    finally:
        await asyncio.to_thread(clib.dynamo_llm_shutdown)
        await server.stop()


async def test_c_long_component_names(clib):
    """Component/namespace strings >255 bytes must produce valid msgpack
    str16 frames (round-2 advisor: the str8 length byte silently wrapped)."""
    server = ControlPlaneServer(port=0)
    addr = await server.start()
    long_ns = ("n" * 300).encode()
    tokens = list(range(1, 5))
    try:
        rc = await asyncio.to_thread(
            lambda: clib.dynamo_llm_init(addr.encode(), long_ns, b"backend",
                                         0xF00D, 4))
        assert rc == 0
        tok = (ctypes.c_uint32 * 4)(*tokens)
        nbt = (ctypes.c_size_t * 1)(4)
        ids = (ctypes.c_uint64 * 1)(42)
        rc = await asyncio.to_thread(
            lambda: clib.dynamo_kv_event_publish_stored(1, tok, nbt, ids, 1,
                                                        None, 0))
        assert rc == 0
        sub = await server.core.stream_subscribe(KV_EVENTS_STREAM, 0)
        _, payload = await asyncio.wait_for(sub.__aiter__().__anext__(), 5)
        import msgpack

        ev = RouterEvent.from_wire(msgpack.unpackb(payload, raw=False))
        assert ev.worker_id == 0xF00D
        assert [b.block_hash for b in ev.event.stored_blocks] == [42]
        await sub.cancel()
    finally:
        await asyncio.to_thread(clib.dynamo_llm_shutdown)
        await server.stop()
