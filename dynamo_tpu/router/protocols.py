"""KV-router wire protocols: cache events, load metrics, router config.

Rebuild of the reference's kv_router protocol types (ref: lib/llm/src/kv_router/
protocols.rs:109-240 for events, :48-84 for ForwardPassMetrics; config defaults
kv_router.rs:95-131). Hashes:

- ``tokens_hash``  (LocalBlockHash): salted xxh3 of the block's tokens only —
  the radix tree's edge key, computable frontend-side from token ids.
- ``block_hash``   (ExternalSequenceBlockHash): the engine's chained sequence
  hash identifying the physical stored block — the removal key.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass, field, fields
from typing import Optional

#: durable stream carrying RouterEvents (ref: kv_router.rs:59 "kv_events")
KV_EVENTS_STREAM = "kv_events"
#: pub/sub subject carrying ForwardPassMetrics (ref: "kv_metrics")
KV_METRICS_SUBJECT = "kv_metrics"
#: subject prefix a gapped router publishes on to ask workers to re-announce
KV_RESYNC_SUBJECT = "kv_resync"
#: object-store bucket for radix snapshots (ref: kv_router.rs:68-71)
RADIX_STATE_BUCKET = "radix-bucket"
#: sentinel "worker" id under which G4-resident prefix blocks are announced
#: on the kv_events stream (kvbm/distributed.G4PrefixAnnouncer). The radix
#: tree treats it like any worker, which is exactly what prefix_sources
#: needs — but it is NOT a routable instance: the scheduler only scores ids
#: from the discovery set, and plan builders must pop it from pull-source
#: candidates (a kv_pull aimed at it would burn a peer's attempt, the
#: failure mode PR 10's review ruled out). Negative by construction: real
#: worker ids are control-plane leases, which are non-negative.
G4_SOURCE_ID = -4


@dataclass
class StoredBlock:
    block_hash: int  # external sequence hash (engine identity)
    tokens_hash: int  # local block hash (router identity)


@dataclass
class KvCacheEvent:
    """One engine cache mutation: stored / removed / cleared."""

    event_id: int = 0
    stored_parent_hash: Optional[int] = None
    stored_blocks: list[StoredBlock] = field(default_factory=list)
    removed_hashes: list[int] = field(default_factory=list)
    cleared: bool = False

    @staticmethod
    def stored(event_id: int, parent_hash: Optional[int], blocks: list[StoredBlock]) -> "KvCacheEvent":
        return KvCacheEvent(event_id=event_id, stored_parent_hash=parent_hash, stored_blocks=blocks)

    @staticmethod
    def removed(event_id: int, hashes: list[int]) -> "KvCacheEvent":
        return KvCacheEvent(event_id=event_id, removed_hashes=hashes)

    @staticmethod
    def clear(event_id: int) -> "KvCacheEvent":
        return KvCacheEvent(event_id=event_id, cleared=True)

    def to_wire(self) -> dict:
        d: dict = {"event_id": self.event_id}
        if self.stored_blocks:
            d["stored"] = {
                "parent_hash": self.stored_parent_hash,
                "blocks": [{"block_hash": b.block_hash, "tokens_hash": b.tokens_hash} for b in self.stored_blocks],
            }
        elif self.removed_hashes:
            d["removed"] = {"block_hashes": self.removed_hashes}
        elif self.cleared:
            d["cleared"] = True
        return d

    @staticmethod
    def from_wire(d: dict) -> "KvCacheEvent":
        ev = KvCacheEvent(event_id=d.get("event_id", 0))
        if "stored" in d:
            s = d["stored"]
            ev.stored_parent_hash = s.get("parent_hash")
            ev.stored_blocks = [
                StoredBlock(b["block_hash"], b["tokens_hash"]) for b in s.get("blocks", [])
            ]
        elif "removed" in d:
            ev.removed_hashes = list(d["removed"].get("block_hashes", []))
        elif d.get("cleared"):
            ev.cleared = True
        return ev


@dataclass
class RouterEvent:
    """A KvCacheEvent attributed to a worker (ref: indexer.rs RouterEvent)."""

    worker_id: int
    event: KvCacheEvent

    def to_wire(self) -> dict:
        return {"worker_id": self.worker_id, "event": self.event.to_wire()}

    @staticmethod
    def from_wire(d: dict) -> "RouterEvent":
        return RouterEvent(d["worker_id"], KvCacheEvent.from_wire(d["event"]))


@dataclass
class WorkerStats:
    request_active_slots: int = 0
    request_total_slots: int = 0
    num_requests_waiting: int = 0
    data_parallel_rank: Optional[int] = None
    #: cumulative MoE token-expert assignments dropped at EP capacity
    #: (model.MOE_DROPS) — nonzero means routing skew is changing numerics
    moe_dropped_tokens: int = 0
    #: AOT-warmup state: False = warmup was requested but could not run
    #: (multi-host step replication skips it) and no real step has landed
    #: yet — the operator's readiness gate treats such a worker as cold
    #: (deploy/operator.py). None = unknown/legacy publisher (counts warm).
    warmed_up: Optional[bool] = None


@dataclass
class KvStats:
    kv_active_blocks: int = 0
    kv_total_blocks: int = 0
    gpu_cache_usage_perc: float = 0.0
    gpu_prefix_cache_hit_rate: float = 0.0


@dataclass
class SpecDecodeStats:
    num_spec_tokens: int = 0
    num_drafts: int = 0
    num_draft_tokens: int = 0
    num_accepted_tokens: int = 0


@dataclass
class ForwardPassMetrics:
    """Per-forward-pass load report (ref: kv_router/protocols.rs:48-84)."""

    worker_stats: WorkerStats = field(default_factory=WorkerStats)
    kv_stats: KvStats = field(default_factory=KvStats)
    spec_decode_stats: Optional[SpecDecodeStats] = None

    def to_wire(self) -> dict:
        ws = asdict(self.worker_stats)
        if ws.get("warmed_up") is None:
            # same interop discipline as the QoS wire fields (PR 5): the
            # new field rides only when set, so peers that predate it
            # never see an unknown key unless the feature is in use
            ws.pop("warmed_up", None)
        d = {"worker_stats": ws, "kv_stats": asdict(self.kv_stats)}
        if self.spec_decode_stats:
            d["spec_decode_stats"] = asdict(self.spec_decode_stats)
        return d

    @staticmethod
    def from_wire(d: dict) -> "ForwardPassMetrics":
        def known(cls, payload):
            # drop unrecognized keys: a NEWER peer's extra stats fields
            # must not crash an older receiver (forward wire compat)
            names = {f.name for f in fields(cls)}
            return {k: v for k, v in (payload or {}).items() if k in names}

        return ForwardPassMetrics(
            worker_stats=WorkerStats(**known(WorkerStats,
                                             d.get("worker_stats"))),
            kv_stats=KvStats(**known(KvStats, d.get("kv_stats"))),
            spec_decode_stats=(
                SpecDecodeStats(**d["spec_decode_stats"]) if d.get("spec_decode_stats") else None
            ),
        )


@dataclass
class KvRouterConfig:
    """ref: kv_router.rs:95-131 (same defaults)."""

    overlap_score_weight: float = 1.0
    router_temperature: float = 0.0
    use_kv_events: bool = True
    router_replica_sync: bool = False
    router_track_active_blocks: bool = True
    router_snapshot_threshold: Optional[int] = 10000
    router_reset_states: bool = False
    #: multi-tenant QoS (docs/qos.md): multiplier on the LOAD term of the
    #: cost function by priority class. Interactive requests weigh a
    #: worker's active decode load heavier (they flee saturated workers
    #: even at the cost of some prefix-cache overlap); batch requests
    #: discount it (they chase cache hits and tolerate queueing). 1.0 for
    #: both disables the bias. The standard class always uses 1.0.
    qos_interactive_load_factor: float = 2.0
    qos_batch_load_factor: float = 0.5
    #: session-native serving (docs/sessions.md): bonus subtracted from the
    #: session's affinity worker's logit, scaled by that worker's potential
    #: prefill blocks. The affinity worker likely holds the session's
    #: prefix in tiers the radix undercounts (host-tier after device
    #: eviction, parked G4 blocks mid-restore), so its true prefill cost is
    #: far below the radix estimate — but the bonus stays bounded by the
    #: request size, so the load and link terms can still SHED a returning
    #: session off a saturated worker. 0.0 disables the term.
    session_affinity_weight: float = 1.0
    #: network-aware disagg (docs/disagg.md, NetKV arxiv 2606.03910):
    #: weight on the ``transfer_blocks × link_cost`` term of the routing
    #: logit. The term only exists when the prefill pool publishes
    #: locality labels (router/topology.py), so the default deployment is
    #: topology-blind with zero added cost; 0.0 disables the term even
    #: with labels present.
    transfer_cost_weight: float = 1.0
    #: per-link-class bandwidth overrides (GB/s), e.g.
    #: {"ici": 50, "dcn": 10, "host": 2}; None = topology defaults +
    #: DYN_TOPO_GBPS env overrides (router/topology.DEFAULT_GBPS)
    link_gbps: Optional[dict] = None
    #: component whose instances are the KV source pool for the transfer
    #: term (the prefill fleet in a disagg deployment); "" disables the
    #: source watch entirely
    prefill_component: str = "prefill"
    #: routine prefix onboarding (docs/performance.md): attach a peer-pull
    #: plan to ordinary admissions whose prefix some peer holds more of
    #: than the chosen worker. False — or DYN_ONBOARD=0 in the router
    #: process — keeps every payload byte-identical to pre-onboard builds.
    onboard_enabled: bool = True
    #: don't plan a pull for less than this many missing prefix blocks —
    #: below it the round trip costs more than it saves
    onboard_min_blocks: int = 4
    #: admission-time pull-vs-recompute cost model (NetKV-style): a pull
    #: costs ``blocks × onboard_pull_ms_per_block × link rel_cost`` (rel
    #: cost normalized to ici=1, router/topology.py), a recompute costs
    #: ``blocks × block_size × onboard_recompute_ms_per_token``. Defaults
    #: from docs/PERF_NOTES.md measurements (export 256 blocks ≈ 5 ms +
    #: attach ≈ 3 ms → ~0.03 ms/block same-host; tiny-cpu prefill ≈
    #: 0.5 ms/token): pull wins by orders of magnitude on proc/ici links
    #: and loses only on links priced hundreds of times worse.
    onboard_pull_ms_per_block: float = 0.05
    onboard_recompute_ms_per_token: float = 0.5
    #: per-block cost of warming from the G4 object store (two plane round
    #: trips + host staging — slower than a peer pull, still far cheaper
    #: than recompute)
    onboard_g4_ms_per_block: float = 0.5


@dataclass
class KVHitRateEvent:
    worker_id: int
    isl_blocks: int
    overlap_blocks: int
