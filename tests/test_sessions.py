"""Session-native serving (docs/sessions.md, PR 20).

Coverage, per the issue's falsifiable list:
  * delta-turn streams bit-identical (greedy/seeded) to full-prompt resends
  * affinity-vs-load tradeoff: a saturated affinity worker sheds the session
  * park → return restore through G4 (KVBM tier ladder round trip)
  * abandoned-session reaping (TTL) + registry cap guard
  * typed 404 on unknown/superseded/disabled previous_response_id
  * mocker parity: fleet drives carry session traffic end-to-end
"""

import asyncio
import json

import aiohttp
import numpy as np
import pytest

from dynamo_tpu.frontend.http import HttpService
from dynamo_tpu.kvbm import KvbmManager
from dynamo_tpu.llm.discovery import ModelManager, ModelWatcher
from dynamo_tpu.llm.tokenizer import make_test_tokenizer
from dynamo_tpu.mocker.engine import MockEngineArgs
from dynamo_tpu.mocker.main import run_mocker
from dynamo_tpu.router.indexer import OverlapScores
from dynamo_tpu.router.protocols import KvRouterConfig
from dynamo_tpu.router.scheduler import KvScheduler
from dynamo_tpu.runtime import DistributedRuntime
from dynamo_tpu.sessions import (
    SessionConfig, SessionEntry, SessionKvHandler, SessionRegistry,
    UnknownResponseError, session_prefix_hashes,
)

pytestmark = pytest.mark.anyio

MODEL = "mock-model"
TK = make_test_tokenizer()


# -- registry lifecycle (unit, injected clock) -------------------------------


class Clock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def make_registry(**cfg):
    clock = Clock()
    defaults = dict(ttl_s=60.0, park_after_s=10.0, max_sessions=8)
    defaults.update(cfg)
    reg = SessionRegistry(SessionConfig(**defaults), clock=clock)
    return reg, clock


def test_registry_turn_and_response_chain():
    reg, clock = make_registry()
    e = reg.get_or_create("s1", MODEL)
    assert reg.begin_turn(e, kind="first") is False
    reg.note_routed(e, worker_id=0xAB, token_ids=[1, 2, 3])
    reg.complete_turn(e, "resp-1", [{"role": "user", "content": "hi"}],
                      "hello", delta_chars_saved=0)
    assert reg.resolve_response("resp-1") is e
    assert e.messages[-1] == {"role": "assistant", "content": "hello"}
    # a later turn supersedes the id: only the latest resolves
    reg.begin_turn(e, kind="delta")
    reg.complete_turn(e, "resp-2", list(e.messages), "again")
    assert reg.resolve_response("resp-2") is e
    with pytest.raises(UnknownResponseError):
        reg.resolve_response("resp-1")
    with pytest.raises(UnknownResponseError):
        reg.resolve_response("resp-never-existed")


def test_registry_ttl_reaps_abandoned_sessions():
    reg, clock = make_registry(ttl_s=60.0)
    e = reg.get_or_create("abandoned", MODEL)
    reg.begin_turn(e)
    reg.complete_turn(e, "resp-a", [{"role": "user", "content": "x"}], "y")
    clock.t += 59
    assert reg.reap() == []          # not yet
    clock.t += 2
    dead = reg.reap()
    assert [d.sid for d in dead] == ["abandoned"]
    assert len(reg) == 0
    with pytest.raises(UnknownResponseError):
        reg.resolve_response("resp-a")  # the chain died with the session


def test_registry_ttl_spares_inflight_turns():
    reg, clock = make_registry(ttl_s=60.0)
    e = reg.get_or_create("slow", MODEL)
    reg.begin_turn(e)                 # turn in flight, never completed
    clock.t += 120
    assert reg.reap() == []           # active turns are never reaped
    reg.abort_turn(e)                 # abort refreshes last_seen
    clock.t += 61
    assert [d.sid for d in reg.reap()] == ["slow"]


def test_registry_cap_guard_serves_statelessly():
    reg, clock = make_registry(max_sessions=2)
    assert reg.get_or_create("a", MODEL) is not None
    assert reg.get_or_create("b", MODEL) is not None
    assert reg.get_or_create("c", MODEL) is None     # at the cap: stateless
    assert reg.get_or_create("a", MODEL).sid == "a"  # existing still resolves
    # reaping frees a slot
    clock.t += 100
    reg.reap()
    assert reg.get_or_create("c", MODEL) is not None


def test_registry_park_candidates_and_affinity_ledger():
    reg, clock = make_registry(park_after_s=10.0)
    e = reg.get_or_create("s", MODEL)
    reg.begin_turn(e)
    clock.t += 50
    assert reg.park_candidates() == []   # active turn: never parked
    reg.note_routed(e, worker_id=7, token_ids=list(range(12)))
    reg.complete_turn(e, "resp-1", [], "ok")
    clock.t += 11
    assert reg.park_candidates() == [e]
    reg.note_parked(e, 3)
    assert e.parked and e.parked_blocks == 3
    assert reg.park_candidates() == []   # parked once, not re-fired
    # the returning turn reports it was parked exactly once
    assert reg.begin_turn(e, kind="delta") is True
    assert reg.begin_turn(e, kind="delta") is False
    # affinity ledger follows the router hook
    assert e.worker_id == 7
    reg.note_routed(e, worker_id=9)      # shed to another worker
    assert e.worker_id == 9


# -- router affinity term (unit) ---------------------------------------------


def _sched(**cfg):
    import random
    defaults = dict(router_temperature=0.0)
    defaults.update(cfg)
    return KvScheduler(block_size=4, config=KvRouterConfig(**defaults),
                       rng=random.Random(0))


def test_scheduler_affinity_breaks_tie_toward_session_worker():
    """Equal load, zero overlap: the affinity term is the deciding vote."""
    workers = [1, 2]
    for _ in range(20):
        s = _sched(session_affinity_weight=1.0)
        d = s.schedule("r", isl_tokens=64, seq_hashes=None,
                       overlaps=OverlapScores(), worker_ids=workers,
                       affinity_worker=2)
        assert d.worker_id == 2


def test_scheduler_affinity_sheds_under_load():
    """A saturated affinity worker loses to an idle one: the discount is
    bounded by the request's own prefill size, so the decode-load term can
    outvote it — sessions are soft state, not pinning."""
    s = _sched(session_affinity_weight=1.0)
    # pile active decode blocks onto worker 2 (the affinity worker)
    for i in range(32):
        blocks = list(range(i * 64, i * 64 + 64))
        s.slots.add_request(f"busy{i}", 2, blocks, 256, 0)
    d = s.schedule("r", isl_tokens=64, seq_hashes=None,
                   overlaps=OverlapScores(), worker_ids=[1, 2],
                   affinity_worker=2)
    assert d.worker_id == 1


def test_scheduler_affinity_weight_zero_disables_term():
    import random
    picks = set()
    for seed in range(10):
        s = KvScheduler(block_size=4,
                        config=KvRouterConfig(router_temperature=0.0,
                                              session_affinity_weight=0.0),
                        rng=random.Random(seed))
        d = s.schedule("r", isl_tokens=64, seq_hashes=None,
                       overlaps=OverlapScores(), worker_ids=[1, 2],
                       affinity_worker=2)
        picks.add(d.worker_id)
    assert picks == {1, 2}  # pure tie-break: both workers show up


# -- park → restore through G4 (KVBM tier ladder) ----------------------------


class _FakeG4Client:
    def __init__(self):
        self.store: dict = {}

    def put(self, h, data):
        self.store[h] = data

    def get(self, h):
        return self.store.get(h)

    def delete(self, h):
        self.store.pop(h, None)


class _FakeEngine:
    """Just enough engine surface for SessionKvHandler: .kvbm + .args."""

    def __init__(self, kvbm, block_size=4):
        self.kvbm = kvbm
        from types import SimpleNamespace
        self.args = SimpleNamespace(block_size=block_size)


def _page(i, nbytes=256):
    return np.full((nbytes // 4,), i, np.float32)


async def _session_op(handler, op, token_ids):
    out = []
    async for frame in handler.generate({"op": op, "token_ids": token_ids}):
        out.append(frame)
    assert len(out) == 1
    return out[0]


async def test_park_restore_through_g4(tmp_path):
    token_ids = list(range(17))         # 4 complete blocks + ragged tail
    hashes = session_prefix_hashes(token_ids, 4)
    assert len(hashes) == 4

    g4 = _FakeG4Client()
    m = KvbmManager(host_bytes=8 * 512, disk_dir=str(tmp_path / "a"),
                    disk_bytes=16 * 512)
    m.attach_remote(g4, capacity_bytes=1 << 20)
    for h in hashes:
        m.put(h, _page(h & 0xFF), _page(h & 0xFF))

    handler = SessionKvHandler(_FakeEngine(m))
    parked = await _session_op(handler, "park", token_ids)
    assert parked["ok"] and parked["op"] == "park"
    assert parked["blocks"] == 4 and parked["published"] == 4
    assert len(g4.store) == 4           # the chain actually landed in G4
    # re-park is idempotent: already remote, nothing re-published
    parked2 = await _session_op(handler, "park", token_ids)
    assert parked2["blocks"] == 4 and parked2["published"] == 0

    # the session returns at a cold worker: fresh local tiers, same G4
    m2 = KvbmManager(host_bytes=8 * 512, disk_dir=str(tmp_path / "b"),
                     disk_bytes=16 * 512)
    m2.attach_remote(g4, capacity_bytes=1 << 20)
    assert m2.match_prefix(hashes) == 0
    restored = await _session_op(handler.__class__(_FakeEngine(m2)),
                                 "restore", token_ids)
    assert restored["ok"] and restored["blocks"] == 4
    assert m2.match_prefix(hashes) == 4  # host-resident again
    k, _ = m2.get(hashes[0])
    np.testing.assert_array_equal(k, _page(hashes[0] & 0xFF))


async def test_park_stops_at_first_gap(tmp_path):
    """A hole in the local chain truncates the park: G4 onboarding attaches
    contiguous prefixes only, so blocks behind the gap would be stranded."""
    token_ids = list(range(16))
    hashes = session_prefix_hashes(token_ids, 4)
    g4 = _FakeG4Client()
    m = KvbmManager(host_bytes=8 * 512, disk_dir=str(tmp_path),
                    disk_bytes=16 * 512)
    m.attach_remote(g4, capacity_bytes=1 << 20)
    for h in (hashes[0], hashes[2], hashes[3]):   # hashes[1] missing
        m.put(h, _page(1), _page(1))
    parked = await _session_op(SessionKvHandler(_FakeEngine(m)),
                               "park", token_ids)
    assert parked["blocks"] == 1 and parked["published"] == 1
    assert set(g4.store) == {hashes[0]}


async def test_session_kv_handler_stub_and_errors():
    h = SessionKvHandler(None)           # mocker arm: no engine at all
    out = await _session_op(h, "park", list(range(8)))
    assert out == {"ok": True, "op": "park", "blocks": 0, "stub": True}
    out = await _session_op(h, "restore", list(range(8)))
    assert out["stub"] and out["blocks"] == 0
    frames = []
    async for f in h.generate({"op": "evict"}):
        frames.append(f)
    assert "error" in frames[0]


# -- e2e: frontend + mocker fleet (mocker parity) ----------------------------


def mock_args(**kw):
    kw.setdefault("vocab_size", TK.vocab_size)
    kw.setdefault("block_size", 4)
    kw.setdefault("num_gpu_blocks", 256)
    kw.setdefault("speedup_ratio", 20.0)
    return MockEngineArgs(**kw)


@pytest.fixture
async def stack():
    rt = await DistributedRuntime.create()
    manager = ModelManager()
    watcher = await ModelWatcher(rt, manager, router_mode="kv").start()
    service = HttpService(manager, port=0)
    await service.start()
    engines = []

    async def add_mocker(**kw):
        lease = await rt.plane.lease_create(30)
        (engine,), (handle,) = await run_mocker(
            rt, MODEL, mock_args(**kw), lease_id=lease)
        engines.append((engine, handle))
        return engine, handle

    try:
        yield rt, service, add_mocker, manager
    finally:
        await service.stop()
        await watcher.stop()
        for engine, handle in engines:
            await handle.stop(graceful=False)
            await engine.stop()
        await rt.shutdown()


async def wait_for_model(manager: ModelManager, timeout=5.0):
    for _ in range(int(timeout / 0.05)):
        if manager.get(MODEL):
            return
        await asyncio.sleep(0.05)
    raise TimeoutError("model never appeared")


async def _responses_text(http, base, body, headers=None):
    async with http.post(f"{base}/v1/responses", json=body,
                         headers=headers or {}) as r:
        assert r.status == 200, await r.text()
        out = await r.json()
        return out["id"], out["output"][0]["content"][0]["text"]


async def _responses_sse_text(http, base, body, headers=None):
    """Drive the streaming arm; returns (response_id, concatenated deltas)."""
    parts, rid = [], None
    async with http.post(f"{base}/v1/responses", json=body,
                         headers=headers or {}) as r:
        assert r.status == 200, await r.text()
        async for line in r.content:
            line = line.decode().strip()
            if not line.startswith("data: "):
                continue
            payload = line[len("data: "):]
            if payload == "[DONE]":
                break
            ev = json.loads(payload)
            if ev.get("type") == "response.output_text.delta":
                parts.append(ev.get("delta") or "")
            elif ev.get("type") in ("response.completed",
                                    "response.incomplete"):
                rid = ev["response"]["id"]
    return rid, "".join(parts)


async def test_unknown_previous_response_id_is_typed_404(stack):
    rt, service, add_mocker, manager = stack
    await add_mocker()
    await wait_for_model(manager)
    base = f"http://127.0.0.1:{service.port}"
    async with aiohttp.ClientSession() as http:
        body = {"model": MODEL, "input": "continue please",
                "previous_response_id": "resp-does-not-exist",
                "max_output_tokens": 4}
        async with http.post(f"{base}/v1/responses", json=body) as r:
            assert r.status == 404
            err = (await r.json())["error"]
            assert err["type"] == "previous_response_not_found"
        # malformed id shape is a 400, not a silent fallback either
        body["previous_response_id"] = ""
        async with http.post(f"{base}/v1/responses", json=body) as r:
            assert r.status == 400


async def test_delta_turns_bit_identical_to_full_resend(stack):
    """The tentpole correctness gate: a session's delta turn (server-side
    history + new input only) must produce the byte-identical stream a
    sessionless client resending the whole conversation gets. Greedy
    sampling; the mocker derives its stream deterministically from the
    reconstructed prompt token ids, so any prompt divergence shows."""
    rt, service, add_mocker, manager = stack
    await add_mocker()
    await add_mocker()
    await wait_for_model(manager)
    base = f"http://127.0.0.1:{service.port}"
    sampling = {"temperature": 0.0, "max_output_tokens": 8}

    user_turns = ["the quick brown fox jumps over the lazy dog",
                  "now tell me about rivers and stones",
                  "and finally sum it all up briefly"]

    async with aiohttp.ClientSession() as http:
        # session arm: turn 1 full, turns 2..n ship only the delta
        prev, transcript, session_texts = None, [], []
        for turn in user_turns:
            item = {"role": "user", "content": turn}
            body = {"model": MODEL, "input": [item], **sampling}
            if prev:
                body["previous_response_id"] = prev
            prev, text = await _responses_text(http, base, body)
            transcript += [item, {"role": "assistant", "content": text}]
            session_texts.append(text)

        # sessionless arm: full transcript every turn (store=false keeps
        # this arm out of the registry entirely)
        replay, sessionless_texts = [], []
        for turn in user_turns:
            replay.append({"role": "user", "content": turn})
            body = {"model": MODEL, "input": list(replay), "store": False,
                    **sampling}
            _, text = await _responses_text(http, base, body)
            replay.append({"role": "assistant", "content": text})
            sessionless_texts.append(text)

        assert session_texts == sessionless_texts  # bit-identical turns

        # and the streaming path agrees with the aggregate path
        body = {"model": MODEL, "input": list(replay) + [
            {"role": "user", "content": "one more thing"}],
            "store": False, "stream": True, **sampling}
        _, sse_text = await _responses_sse_text(http, base, body)
        body.pop("stream")
        _, agg_text = await _responses_text(http, base, body)
        assert sse_text == agg_text


async def test_session_registry_view_and_metrics(stack):
    """Mocker parity: session traffic over a fleet shows up in
    /v1/sessions and dynamo_session_* metrics, and the affinity worker is
    learned from the router's on_routed hook."""
    rt, service, add_mocker, manager = stack
    await add_mocker()
    await add_mocker()
    await wait_for_model(manager)
    base = f"http://127.0.0.1:{service.port}"
    sampling = {"temperature": 0.0, "max_output_tokens": 6}

    async with aiohttp.ClientSession() as http:
        prev = None
        workers = set()
        for i in range(3):
            body = {"model": MODEL,
                    "input": [{"role": "user", "content": f"turn {i}: "
                               "the quick brown fox jumps over the dog"}],
                    **sampling}
            if prev:
                body["previous_response_id"] = prev
            async with http.post(f"{base}/v1/responses", json=body) as r:
                assert r.status == 200, await r.text()
                prev = (await r.json())["id"]
            async with http.get(f"{base}/v1/sessions") as r:
                snap = await r.json()
                assert snap["enabled"] and snap["count"] >= 1
                sess = snap["sessions"][0]
                if sess["worker"]:
                    workers.add(sess["worker"])
        assert snap["sessions"][0]["turns"] == 3
        assert workers                      # on_routed stamped a worker
        # a returning session keeps its affinity worker on a calm fleet
        assert len(workers) == 1

        # chat route rides the same registry via the soft header
        chat = {"model": MODEL, "max_tokens": 4,
                "messages": [{"role": "user", "content": "hello session"}]}
        async with http.post(f"{base}/v1/chat/completions", json=chat,
                             headers={"x-dynamo-session": "chat-s1"}) as r:
            assert r.status == 200, await r.text()
        async with http.get(f"{base}/v1/sessions") as r:
            snap = await r.json()
            assert any(s["id"] == "chat-s1" for s in snap["sessions"])

        async with http.get(f"{base}/metrics") as r:
            text = await r.text()
            assert "dynamo_session_active" in text
            assert 'dynamo_session_turns_total{kind="delta"}' in text
            assert 'kind="chat"' in text
            assert "dynamo_session_affinity_total" in text


async def test_reaper_parks_idle_session_via_worker_endpoint(stack,
                                                             monkeypatch):
    """End-to-end park loop on a mocker fleet: the frontend reaper calls
    the affinity worker's kv_session endpoint (the mocker stub answers
    blocks=0) and the session flips to parked; the returning turn fires
    the proactive restore and un-parks it."""
    monkeypatch.setenv("DYN_SESSION_PARK_AFTER_S", "0.3")
    monkeypatch.setenv("DYN_SESSION_REAP_INTERVAL_S", "0.1")
    rt = await DistributedRuntime.create()
    manager = ModelManager()
    watcher = await ModelWatcher(rt, manager, router_mode="kv").start()
    service = HttpService(manager, port=0)
    await service.start()
    lease = await rt.plane.lease_create(30)
    (engine,), (handle,) = await run_mocker(rt, MODEL, mock_args(),
                                            lease_id=lease)
    try:
        await wait_for_model(manager)
        base = f"http://127.0.0.1:{service.port}"
        async with aiohttp.ClientSession() as http:
            body = {"model": MODEL, "max_output_tokens": 4,
                    "input": "park me when I go idle"}
            async with http.post(f"{base}/v1/responses", json=body) as r:
                assert r.status == 200, await r.text()
                prev = (await r.json())["id"]

            async def parked_state():
                async with http.get(f"{base}/v1/sessions") as r:
                    snap = await r.json()
                return snap["sessions"][0] if snap["sessions"] else None

            for _ in range(100):                 # reaper parks after ~0.3s
                s = await parked_state()
                if s and s["parked"]:
                    break
                await asyncio.sleep(0.05)
            assert s and s["parked"]

            # the session returns: delta turn un-parks + fires restore
            body = {"model": MODEL, "max_output_tokens": 4,
                    "input": "I am back", "previous_response_id": prev}
            async with http.post(f"{base}/v1/responses", json=body) as r:
                assert r.status == 200, await r.text()
            s = await parked_state()
            assert s and not s["parked"] and s["turns"] == 2
    finally:
        await service.stop()
        await watcher.stop()
        await handle.stop(graceful=False)
        await engine.stop()
        await rt.shutdown()


async def test_sessions_disabled_is_stateless(stack, monkeypatch):
    """DYN_SESSIONS=0: no registry, /v1/sessions says disabled, and a
    previous_response_id is a typed 404 (never a silent fallback)."""
    monkeypatch.setenv("DYN_SESSIONS", "0")
    rt, service0, add_mocker, manager = stack
    service = HttpService(manager, port=0)
    await service.start()
    try:
        await add_mocker()
        await wait_for_model(manager)
        base = f"http://127.0.0.1:{service.port}"
        async with aiohttp.ClientSession() as http:
            async with http.get(f"{base}/v1/sessions") as r:
                assert (await r.json())["enabled"] is False
            body = {"model": MODEL, "input": "hi", "max_output_tokens": 4,
                    "previous_response_id": "resp-x"}
            async with http.post(f"{base}/v1/responses", json=body) as r:
                assert r.status == 404
                assert (await r.json())["error"]["type"] == \
                    "previous_response_not_found"
    finally:
        await service.stop()
