"""KV-restore migration (stateful migration, ISSUE 10 / docs/robustness.md):
a decode worker dying mid-stream breaks its streams on LEASE EXPIRY (not a
transport timeout); Migration re-issues with a restore hint; the router
attaches a plan of surviving sources from the radix index; the receiving
worker pulls the recoverable (prompt ‖ emitted) prefix over ``kv_pull`` and
recomputes only the unrecoverable tail — bit-identical to an unbroken run,
degrading to plain recompute with exact token accounting on every failure.
"""

import asyncio
import dataclasses
import time
from types import SimpleNamespace

import numpy as np
import pytest

from dynamo_tpu.disagg.handlers import DecodeWorkerHandler, KvPullHandler
from dynamo_tpu.disagg.transfer import RestoreConfig, restore_pull_timeout
from dynamo_tpu.engine.config import EngineArgs, ModelConfig
from dynamo_tpu.engine.engine import AsyncJaxEngine
from dynamo_tpu.llm.pipeline import Migration, is_event
from dynamo_tpu.protocols import (LLMEngineOutput, PreprocessedRequest,
                                  SamplingOptions, StopConditions)
from dynamo_tpu.router.indexer import RadixTree
from dynamo_tpu.router.kv_router import KvPushRouter, KvRouter
from dynamo_tpu.router.protocols import (KvCacheEvent, KvRouterConfig,
                                         RouterEvent, StoredBlock)
from dynamo_tpu.router.publisher import KvEventPublisher
from dynamo_tpu.runtime import DistributedRuntime
from dynamo_tpu.runtime.chaos import configure_chaos
from dynamo_tpu.runtime.config import RuntimeConfig
from dynamo_tpu.runtime.context import Context, StreamError

pytestmark = pytest.mark.anyio

BS = 4
CFG = ModelConfig.tiny()
VOCAB = CFG.vocab_size


def eargs(**kw):
    base = dict(block_size=BS, num_blocks=256, max_num_seqs=8,
                max_num_batched_tokens=256, max_model_len=512,
                enable_prefix_caching=True)
    base.update(kw)
    return EngineArgs(**base)


def req(tokens, osl, seed=None, temp=0.0, pin=None):
    return PreprocessedRequest(
        model="m", token_ids=list(tokens),
        stop_conditions=StopConditions(max_tokens=osl, ignore_eos=True),
        sampling_options=SamplingOptions(temperature=temp, seed=seed),
        backend_instance_id=pin)


async def _settle(check, timeout=8.0, msg="condition never settled"):
    for _ in range(int(timeout / 0.05)):
        if check():
            return
        await asyncio.sleep(0.05)
    raise TimeoutError(msg)


# --------------------------------------------------------------- fleet rig


async def make_fleet(n=2, lease_ttl=5.0, engine_kw=None, restore_cfg=None):
    """n decode workers (own runtime/lease each) + a KV-routed frontend
    pipeline, all over one in-process control plane with REAL response-
    plane sockets between runtimes (so a killed worker's streams hang
    exactly like a SIGKILLed process's would)."""
    cfg = RuntimeConfig(lease_ttl=lease_ttl, worker_lost_grace=0.4)
    rt = await DistributedRuntime.create(config=cfg)
    fleet = SimpleNamespace(rt=rt, workers=[], infos=[])
    for _ in range(n):
        wrt = await DistributedRuntime.create(plane=rt.plane,
                                              owns_plane=False, config=cfg)
        lease = await wrt.primary_lease()
        # off the loop: a blocking construct would starve the keepalives
        # of already-built workers and fake a lease expiry mid-test
        eng = await asyncio.to_thread(
            AsyncJaxEngine, CFG, eargs(**(engine_kw or {})))
        pub = KvEventPublisher(wrt.plane, worker_id=lease, kv_block_size=BS)
        await pub.start_resync_responder()
        eng.event_cb = pub.publish_sync
        comp = wrt.namespace("dynamo").component("backend")
        pull_client = await comp.endpoint("kv_pull").client().start()
        handler = DecodeWorkerHandler(
            eng, metrics=wrt.metrics, pull_clients=[pull_client],
            restore_config=restore_cfg)
        handler.instance_id = lease

        async def spy(r, c, _h=handler):
            out = await DecodeWorkerHandler._restore_migrated(_h, r, c)
            fleet.infos.append(out)
            return out

        handler._restore_migrated = spy
        h_gen = await comp.endpoint("generate").serve_endpoint(
            handler.generate, lease_id=lease)
        h_pull = await comp.endpoint("kv_pull").serve_endpoint(
            KvPullHandler(eng).generate, lease_id=lease)
        fleet.workers.append(SimpleNamespace(
            rt=wrt, engine=eng, lease=lease, handler=handler, pub=pub,
            handles=[h_gen, h_pull], pull_client=pull_client, killed=False))
    client = await (rt.namespace("dynamo").component("backend")
                    .endpoint("generate").client().start())
    router = await KvRouter(rt.plane, BS, KvRouterConfig()).start()
    fleet.client = client
    fleet.router = router
    fleet.push = KvPushRouter(client, router)
    fleet.mig = Migration(fleet.push.generate, migration_limit=3)
    return fleet


async def kill_worker(w):
    """SIGKILL-grade in-process death: serving stops, the engine loop
    freezes with its sinks unresolved, the lease keepalive dies — the
    fleet learns only when the lease TTL expires."""
    w.killed = True
    w.engine._closed = True
    w.engine._wake.set()
    for h in w.handles:
        await h.kill()
    if w.rt._keepalive_task is not None:
        w.rt._keepalive_task.cancel()


async def stop_fleet(fleet):
    configure_chaos(None)
    await fleet.router.stop()
    await fleet.client.stop()
    for w in fleet.workers:
        for h in w.handles:
            if not w.killed:
                await h.stop(graceful=False)
        await w.pull_client.stop()
        await w.pub.stop()
        if not w.killed:
            await w.engine.close()
        await w.rt.shutdown()
    await fleet.rt.shutdown()


async def seed_prefix(fleet, prefix, workers=None, salt=900):
    """Selected workers compute (and prefix-register) the shared prefix via
    a pinned 1-token request; waits until the radix index knows them."""
    workers = fleet.workers if workers is None else workers
    for i, w in enumerate(workers):
        r = req(list(prefix) + [salt + i], 1, pin=w.lease)
        async for _ in fleet.mig.generate(r, Context()):
            pass
    want = len(prefix) // BS

    def indexed():
        src = fleet.router.restore_sources(list(prefix))
        return all(src.get(w.lease, 0) >= want for w in workers)

    await _settle(indexed, msg="radix index never learned the seed prefix")


async def run_stream(fleet, r, ctx=None, kill_at=None, after_kill=None):
    """Drive one stream through Migration; optionally kill the serving
    worker once ``kill_at`` tokens have been emitted (then run the
    ``after_kill`` hook — e.g. re-steer the busy set)."""
    ctx = ctx or Context()
    toks = []
    killed = False
    async for out in fleet.mig.generate(r, ctx):
        if is_event(out):
            continue
        toks.extend(out.token_ids)
        if kill_at is not None and not killed and len(toks) >= kill_at:
            victims = [w for w in fleet.workers
                       if not w.killed and w.engine.scheduler.running]
            assert victims, "no worker is serving the stream"
            await kill_worker(victims[0])
            killed = True
            if after_kill is not None:
                after_kill()
    return toks, killed


def steer_to(fleet, target):
    """Mark every OTHER live worker busy so routing picks ``target``."""
    fleet.client.set_busy_instances(
        [w.lease for w in fleet.workers
         if not w.killed and w is not target])


async def reference_tokens(r):
    """The unbroken run, on a standalone engine with identical weights
    (same cfg + seed → deterministic init on CPU)."""
    eng = await asyncio.to_thread(AsyncJaxEngine, CFG, eargs())
    toks = []
    async for out in eng.generate(dataclasses.replace(
            r, backend_instance_id=None), Context()):
        toks.extend(out.token_ids)
    await eng.close()
    return toks


def fleet_restore_stats(fleet):
    restored = sum(i.get("restored_blocks", 0) for i in fleet.infos)
    outcomes = [i["outcome"] for i in fleet.infos]
    return restored, outcomes


# ------------------------------------------------------------------ units


def _stored_event(eid, parent, hashes, locals_):
    blocks = [StoredBlock(block_hash=h, tokens_hash=l)
              for h, l in zip(hashes, locals_)]
    return KvCacheEvent.stored(eid, parent, blocks)


def test_radix_prefix_sources_contiguity():
    tree = RadixTree()
    # worker 1 holds blocks 0..3; worker 2 holds 0..1; worker 3 holds a
    # mid-chain run only (anchored under worker 1's chain)
    tree.apply_event(RouterEvent(1, _stored_event(
        1, None, [10, 11, 12, 13], [100, 101, 102, 103])))
    tree.apply_event(RouterEvent(2, _stored_event(
        2, None, [20, 21], [100, 101])))
    tree.apply_event(RouterEvent(3, _stored_event(
        3, 11, [32, 33], [102, 103])))
    src = tree.prefix_sources([100, 101, 102, 103])
    assert src == {1: 4, 2: 2}
    # read-only: no frequency bumps
    assert tree.find_matches([100]).frequencies == [1]


def test_restore_pull_timeout_clamp():
    # no deadline → the cap; generous budget → half of it; thin → None
    assert restore_pull_timeout(5.0, None) == 5.0
    assert restore_pull_timeout(5.0, 8.0) == 4.0
    assert restore_pull_timeout(1.0, 8.0) == 1.0
    assert restore_pull_timeout(5.0, 0.01) is None
    assert restore_pull_timeout(5.0, -1.0) is None


async def test_migration_sets_restore_hint():
    calls = []

    async def downstream(r, ctx):
        calls.append(r)
        if len(calls) == 1:
            yield LLMEngineOutput(token_ids=[5, 6])
            raise StreamError("boom", retryable=True)
        yield LLMEngineOutput(token_ids=[7], finish_reason="length")

    mig = Migration(downstream, migration_limit=2)
    toks = []
    async for out in mig.generate(req(list(range(8)), 8), Context()):
        toks.extend(out.token_ids)
    assert toks == [5, 6, 7]
    assert calls[0].restore is None
    assert calls[1].restore == {"emitted": 2, "attempt": 1}
    assert calls[1].token_ids == list(range(8)) + [5, 6]


def _stub_push_router():
    class StubClient:
        def __init__(self):
            self.listener = None

        def add_instance_listener(self, fn):
            self.listener = fn

        def instances(self):
            return []

    router = KvRouter(None, BS, KvRouterConfig(use_kv_events=False))
    client = StubClient()
    return KvPushRouter(client, router), router, client


def test_dead_instance_purges_radix_and_reregistration_is_clean():
    push, router, client = _stub_push_router()
    tokens = list(range(4 * BS))
    router.indexer.process_routing_decision_for_request(tokens, 7)
    assert router.restore_sources(tokens).get(7, 0) > 0
    # lease expiry → delete event → the worker's blocks leave the tree
    client.listener("delete", 7)
    assert router.restore_sources(tokens) == {}
    # a stale replay repopulates the tree while the id is dead...
    router.indexer.process_routing_decision_for_request(tokens, 7)
    # ...then the SAME id re-registers: stale entries must NOT resurrect
    client.listener("put", 7)
    assert router.restore_sources(tokens) == {}
    # events from the new life land normally
    router.indexer.process_routing_decision_for_request(tokens, 7)
    assert router.restore_sources(tokens).get(7, 0) > 0


def test_worker_monitor_purge_tombstones_late_metrics():
    from dynamo_tpu.runtime.worker_monitor import (WorkerLoadState,
                                                   WorkerMonitor)

    class StubClient:
        def __init__(self):
            self.busy = None

        def set_busy_instances(self, ids):
            self.busy = set(ids)

    mon = WorkerMonitor(plane=object())
    c = StubClient()
    mon.register_client(c)
    mon.load_states[5] = WorkerLoadState(kv_active_blocks=99,
                                         kv_total_blocks=100)
    mon._recompute()
    assert c.busy == {5}
    mon.purge(5)
    assert c.busy == set()
    assert mon._is_dead(5)  # late kv_metrics for 5 are now ignored
    # re-registration clears the tombstone
    mon._dead[5] = time.monotonic() - 1.0
    assert not mon._is_dead(5)


async def test_pull_timeout_respects_deadline(monkeypatch):
    """The restore pull budget is min(cap, remaining/2) — a slow pull must
    never eat the whole deadline and then recompute anyway."""
    import dynamo_tpu.disagg.transfer as T

    seen = {}

    async def fake_pull(client, iid, hashes, timeout_s, reason="restore"):
        seen["timeout"] = timeout_s
        return []

    monkeypatch.setattr(T, "pull_restore_blocks", fake_pull)
    eng = AsyncJaxEngine(CFG, eargs())

    class OneInstanceClient:
        def instance(self, iid):
            return object()

    h = DecodeWorkerHandler(eng, pull_clients=[OneInstanceClient()],
                            restore_config=RestoreConfig(
                                pull_timeout_cap_s=5.0))
    h.instance_id = 1
    r = req(list(range(8 * BS)), 4)
    r.restore = {"emitted": 2, "sources": [[2, 6, 1.0]], "block_size": BS}
    ctx = Context()
    ctx.set_timeout_ms(4000)
    info = await h._restore_migrated(r, ctx)
    assert info["pulls"] == 1
    assert seen["timeout"] <= min(5.0, 2.0) + 1e-6
    # thin budget: no pull is even attempted
    seen.clear()
    ctx2 = Context()
    ctx2.set_timeout_ms(30)
    info = await h._restore_migrated(r, ctx2)
    assert info["reason"] == "deadline" and not seen
    await eng.close()


async def test_chaos_worker_kill_hard_death():
    """worker.kill chaos: the engine loop dies mid-decode without resolving
    in-flight sinks, and on_kill hooks fire."""
    configure_chaos("worker.kill:error=1", seed=3)
    try:
        eng = AsyncJaxEngine(CFG, eargs())
        fired = []
        eng.on_kill.append(lambda: fired.append(1))

        async def drive():
            async for _ in eng.generate(req(list(range(8)), 8), Context()):
                pass

        task = asyncio.ensure_future(drive())
        await _settle(lambda: eng.killed, msg="worker.kill never fired")
        assert fired == [1]
        # SIGKILL semantics: the stream hangs (no error frame, no finish)
        done, _ = await asyncio.wait([task], timeout=0.3)
        assert not done
        task.cancel()
        with pytest.raises(asyncio.CancelledError):
            await task
    finally:
        configure_chaos(None)


# ------------------------------------------------------------- fleet e2e


async def test_kill_midstream_restores_bit_identical_greedy():
    """Flagship: worker A dies mid-decode; the stream migrates to COLD
    worker C, which pulls the shared prefix from peer B and resumes
    bit-identical to an unbroken run; the victim leaves the radix index."""
    fleet = await make_fleet(3)
    try:
        a, b, c = fleet.workers
        prefix = np.random.default_rng(0).integers(1, VOCAB, 6 * BS).tolist()
        await seed_prefix(fleet, prefix, workers=[b])
        r = req(prefix + [401], 10)
        ref = await reference_tokens(r)
        steer_to(fleet, a)  # the stream starts on A (the victim-to-be)
        toks, killed = await run_stream(
            fleet, r, kill_at=3,
            after_kill=lambda: steer_to(fleet, c))  # migrate to cold C
        assert killed and a.killed
        assert toks == ref, f"restored stream diverged: {toks} != {ref}"
        restored, outcomes = fleet_restore_stats(fleet)
        assert restored > 0, f"nothing restored (outcomes={outcomes})"
        assert outcomes[-1] in ("restored", "partial")
        # C now owns the restored prefix in its own prefix cache
        probe = c.engine.restore_probe(req(prefix + [401], 1))
        assert c.engine.resident_prefix_blocks(probe) >= len(prefix) // BS
        # the restore phase is a first-class trace span (dynctl trace
        # renders it on migrated requests)
        from dynamo_tpu.observability import get_tracer
        spans = [s for s in get_tracer().all_spans()
                 if s.name == "kv.restore"]
        assert spans, "no kv.restore span recorded"
        assert spans[-1].attributes.get("outcome") in ("restored", "partial")
        # dead-instance hygiene: the victim left the radix index
        await _settle(lambda: a.lease not in
                      fleet.router.restore_sources(prefix),
                      msg="victim never purged from radix")
    finally:
        await stop_fleet(fleet)


async def test_kill_midstream_restores_bit_identical_seeded():
    """Seeded sampling resumes bit-identical across migration (the PRNG
    step is position-anchored, so the tail draws the unbroken run's keys)."""
    fleet = await make_fleet(3)
    try:
        a, b, c = fleet.workers
        prefix = np.random.default_rng(1).integers(1, VOCAB, 6 * BS).tolist()
        await seed_prefix(fleet, prefix, workers=[b])
        r = req(prefix + [402], 10, seed=1234, temp=0.9)
        ref = await reference_tokens(r)
        steer_to(fleet, a)
        toks, killed = await run_stream(
            fleet, r, kill_at=3, after_kill=lambda: steer_to(fleet, c))
        assert killed
        assert toks == ref, f"seeded stream diverged: {toks} != {ref}"
        restored, _ = fleet_restore_stats(fleet)
        assert restored > 0
    finally:
        await stop_fleet(fleet)


@pytest.mark.parametrize("kv_dtype", [None, "int8"])
async def test_restore_from_peer_host_tier(kv_dtype):
    """A stream whose recoverable prefix lives only in the PEER's G2 host
    tier (device copies evicted) still restores bit-identical — the pull
    serves out of the KVBM, and the hierarchy-aware removed events kept
    the radix advertising the blocks."""
    kw = dict(kvbm_host_bytes=64 << 20)
    if kv_dtype:
        kw["kv_cache_dtype"] = kv_dtype
    fleet = await make_fleet(3, engine_kw=kw)
    try:
        a, b, c = fleet.workers
        prefix = np.random.default_rng(2).integers(1, VOCAB, 6 * BS).tolist()
        await seed_prefix(fleet, prefix, workers=[b])
        # evict B's device prefix copies: its G2 tier is now the only
        # holder (offloads drained first so the tier actually has them)
        if b.engine._offload_tasks:
            await asyncio.gather(*list(b.engine._offload_tasks),
                                 return_exceptions=True)
        pool = b.engine.pool
        ids = pool.allocate(pool.num_free_blocks)
        assert ids is not None
        pool.release(ids)
        assert not pool._lru, "device prefix cache not drained"
        want = len(prefix) // BS
        probe = b.engine.restore_probe(req(prefix + [999], 1))
        assert all(pool.lookup(h) is None
                   for h in probe.sequence_hashes()[:want])
        # the radix must STILL know the blocks (they live in B's G2)
        src = fleet.router.restore_sources(prefix)
        assert src.get(b.lease, 0) >= want, src
        if kv_dtype:  # reference engine must match the fleet's cache dtype
            eng = await asyncio.to_thread(AsyncJaxEngine, CFG, eargs(**kw))
            ref = []
            async for out in eng.generate(req(prefix + [403], 10),
                                          Context()):
                ref.extend(out.token_ids)
            await eng.close()
        else:
            ref = await reference_tokens(req(prefix + [403], 10))
        steer_to(fleet, a)
        toks, killed = await run_stream(
            fleet, req(prefix + [403], 10), kill_at=3,
            after_kill=lambda: steer_to(fleet, c))
        assert killed
        assert toks == ref
        restored, outcomes = fleet_restore_stats(fleet)
        assert restored > 0, outcomes
    finally:
        await stop_fleet(fleet)


async def test_victim_swapped_stream_restores_bit_identical():
    """Migration × swap interplay: the victim's stream is sitting in the
    SWAP tier (preempted-to-swap, KV in the victim's host DRAM) when the
    worker dies — its host tier dies with it, the stream breaks on lease
    expiry like any other, and the migrated request still resumes
    bit-identical via peer restore + tail recompute."""
    fleet = await make_fleet(
        3, engine_kw=dict(max_num_seqs=1, qos_scheduling=True))
    try:
        a, b, c = fleet.workers
        prefix = np.random.default_rng(4).integers(1, VOCAB, 6 * BS).tolist()
        await seed_prefix(fleet, prefix, workers=[b])
        # long OSL: the batch stream must still be DECODING when the
        # interloper's dispatch lands, or nothing is left to preempt
        r = req(prefix + [405], 96)
        ref = await reference_tokens(r)
        steer_to(fleet, a)
        ctx = Context(tenant="t-batch", priority="batch")
        toks = []
        killed = False

        async def interloper():
            """A pinned interactive arrival claims A's single slot — the
            batch stream swap-preempts into A's host tier."""
            ictx = Context(tenant="t-int", priority="interactive")
            # long enough that the batch victim stays parked in the swap
            # tier across several poll windows before the kill lands
            r2 = req(np.random.default_rng(5).integers(
                1, VOCAB, 2 * BS).tolist(), 48, pin=a.lease)
            try:
                async for _ in fleet.mig.generate(r2, ictx):
                    pass
            except Exception:
                pass  # dies with A; only the batch stream is asserted on

        async for out in fleet.mig.generate(r, ctx):
            if is_event(out):
                continue
            toks.extend(out.token_ids)
            if len(toks) >= 2 and not killed:
                asyncio.ensure_future(interloper())
                # wait for the swap preemption to land, then kill A with
                # the victim stream's KV parked in its host swap tier
                await _settle(lambda: len(a.engine.scheduler.swapped) > 0,
                              timeout=6.0,
                              msg="stream never swap-preempted")
                await kill_worker(a)
                steer_to(fleet, c)
                killed = True
        assert killed
        assert toks == ref, f"swapped-victim stream diverged: {toks} != {ref}"
        restored, outcomes = fleet_restore_stats(fleet)
        assert restored > 0, outcomes
    finally:
        await stop_fleet(fleet)


async def test_pull_chaos_degrades_to_recompute_exact():
    """Acceptance: with kv.direct_pull erroring at 100%, every migration
    falls back to recompute, completes with exact token accounting, and
    leaks no blocks."""
    fleet = await make_fleet(3)
    try:
        a, b, c = fleet.workers
        prefix = np.random.default_rng(3).integers(1, VOCAB, 6 * BS).tolist()
        await seed_prefix(fleet, prefix, workers=[b])
        r = req(prefix + [404], 10)
        ref = await reference_tokens(r)
        configure_chaos("kv.direct_pull:error=1", seed=0)
        steer_to(fleet, a)
        toks, killed = await run_stream(
            fleet, r, kill_at=3, after_kill=lambda: steer_to(fleet, c))
        assert killed
        assert toks == ref  # greedy recompute is still bit-identical
        restored, outcomes = fleet_restore_stats(fleet)
        assert restored == 0
        assert outcomes and all(o in ("recomputed", "partial")
                                for o in outcomes)
        # no partial-scatter leak: every surviving engine is fully idle
        # (all blocks free or parked in the LRU prefix cache)
        for w in fleet.workers:
            if w.killed:
                continue
            assert not w.engine.scheduler.running
            assert w.engine.pool.num_active_blocks == 0
    finally:
        await stop_fleet(fleet)


async def test_lease_expiry_breaks_streams_promptly():
    """The victim's streams fail RETRYABLY within ~lease TTL + sweep, not
    a long transport timeout — Migration fires on the TTL."""
    fleet = await make_fleet(1, lease_ttl=1.5)
    try:
        w = fleet.workers[0]
        # warm first: the initial request's XLA compile blocks the worker
        # loop long enough to starve a sub-second lease all by itself
        async for _ in fleet.push.generate(
                req(list(range(1, 2 * BS)), 2, pin=w.lease), Context()):
            pass
        r = req(list(range(1, 2 * BS)), 64)
        ctx = Context()
        t_broken = None
        t_kill = None
        with pytest.raises(StreamError) as ei:
            async for out in fleet.push.generate(r, ctx):
                if is_event(out):
                    continue
                if t_kill is None:
                    await kill_worker(w)
                    t_kill = time.monotonic()
        t_broken = time.monotonic()
        assert ei.value.retryable
        assert t_kill is not None
        # TTL 1.5 + sweep ≤1s + margin; a transport-timeout path would
        # take ≥10s (request_timeout) or hang outright
        assert t_broken - t_kill < 6.0
    finally:
        await stop_fleet(fleet)


async def test_graceful_drain_streams_not_broken():
    """A gracefully-DRAINING worker deletes its instance key first and
    keeps streaming; the worker-lost grace window must let those streams
    complete instead of breaking them (a broken drain would turn every
    rolling restart into a migration storm)."""
    fleet = await make_fleet(1)
    try:
        w = fleet.workers[0]
        # warm (compile off the measured path)
        async for _ in fleet.push.generate(
                req(list(range(1, 2 * BS)), 2, pin=w.lease), Context()):
            pass
        toks = []
        stopped = [False]

        async def drain_stop():
            await w.handles[0].stop(graceful=True, timeout=30.0)
            stopped[0] = True

        stop_task = None
        async for out in fleet.push.generate(
                req(list(range(1, 2 * BS)), 48), Context()):
            if is_event(out):
                continue
            toks.extend(out.token_ids if hasattr(out, "token_ids")
                        else out.get("token_ids") or [])
            if stop_task is None and len(toks) >= 2:
                stop_task = asyncio.ensure_future(drain_stop())
        assert stop_task is not None
        await stop_task
        assert stopped[0]
        assert len(toks) == 48, f"drained stream truncated at {len(toks)}"
        w.killed = True  # handle already stopped; skip double-stop
    finally:
        await stop_fleet(fleet)


async def test_restore_failure_falls_through_to_remote_prefill():
    """When restore recovers nothing (disabled) and the unrecovered
    region is past the local-prefill threshold, the migrated request goes
    through the prefill pool like the pre-restore migration path did."""
    from dynamo_tpu.disagg.protocols import DisaggConfig

    eng = AsyncJaxEngine(CFG, eargs())

    class FakePrefillClient:
        def available_ids(self):
            return [1]

    h = DecodeWorkerHandler(
        eng, prefill_client=FakePrefillClient(),
        config=DisaggConfig(max_local_prefill_length=4 * BS),
        restore_config=RestoreConfig(enabled=False))
    h.instance_id = 9
    routed = []

    async def fake_disagg(r, cx):
        routed.append(len(r.token_ids))
        yield LLMEngineOutput(token_ids=[1],
                              finish_reason="length").to_wire()

    h._generate_disagg = fake_disagg
    r = req(list(range(1, 8 * BS)), 4)
    r.restore = {"emitted": 2, "sources": [], "block_size": BS}
    out = [o async for o in h.generate(r.to_wire(), Context())]
    assert routed, "migrated request never reached the prefill pool"
    assert out
    # short unrecovered region (below threshold): served locally
    routed.clear()
    r2 = req(list(range(1, 2 * BS)), 2)
    r2.restore = {"emitted": 1, "sources": [], "block_size": BS}
    out2 = [o async for o in h.generate(r2.to_wire(), Context())]
    assert not routed and out2
    await eng.close()


async def test_restore_budget_cap_bounded_wait_then_recompute():
    """With every restore slot busy the migration waits at most the pull
    budget for one to free (a peer's restore may make the prefix local),
    then recomputes — it never queues unboundedly."""
    eng = AsyncJaxEngine(CFG, eargs())
    h = DecodeWorkerHandler(eng, restore_config=RestoreConfig(
        max_concurrent=1, pull_timeout_cap_s=0.2))
    h.instance_id = 1
    await h._restore_slots.acquire()  # saturate the budget
    r = req(list(range(8 * BS)), 4)
    r.restore = {"emitted": 2, "sources": [[2, 6, 1.0]], "block_size": BS}
    t0 = time.monotonic()
    info = await h._restore_migrated(r, Context())
    waited = time.monotonic() - t0
    assert info["outcome"] == "recomputed"
    assert info["reason"] == "budget"
    assert 0.15 <= waited < 1.5  # bounded by the pull budget, not forever
    await eng.close()
