"""Observability spine: span recorder, trace propagation/stitching, SLO
histograms, Prometheus exposition correctness, and the bench --observe
smoke (one mock request → complete stitched trace + /metrics series)."""

import contextvars
import json

import pytest

from dynamo_tpu.observability import (
    Span,
    Tracer,
    fetch_trace,
    parse_traceparent,
    serve_traces,
    stitch,
)
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.metrics import (
    Histogram,
    MetricsRegistry,
    _fmt_labels,
    render_registries,
)

pytestmark = pytest.mark.anyio


# ------------------------------------------------- Prometheus exposition


def test_label_value_escaping():
    """Backslash, double-quote, and newline in label values must be escaped
    or the exposition format is corrupt (satellite fix)."""
    out = _fmt_labels({"model": 'a"b\\c\nd'})
    assert out == '{model="a\\"b\\\\c\\nd"}'
    # escaped output is a single physical line
    assert "\n" not in out

    reg = MetricsRegistry()
    reg.counter("reqs", "requests").inc(model='we"ird\nname\\x')
    text = reg.render()
    line = next(ln for ln in text.splitlines()
                if ln.startswith("dynamo_reqs{"))
    assert '\\"' in line and "\\n" in line and "\\\\" in line


def test_histogram_bucket_math():
    """Bucket counts are CUMULATIVE, +Inf equals the total count, and sum
    accumulates the raw values (satellite test coverage)."""
    h = Histogram("dynamo_t", "t", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.observe(v)
    text = h.render()
    lines = dict(
        ln.rsplit(" ", 1) for ln in text.splitlines()
        if not ln.startswith("#"))
    assert lines['dynamo_t_bucket{le="0.1"}'] == "1"
    assert lines['dynamo_t_bucket{le="1.0"}'] == "3"
    assert lines['dynamo_t_bucket{le="10.0"}'] == "4"
    assert lines['dynamo_t_bucket{le="+Inf"}'] == "5"
    assert lines["dynamo_t_count"] == "5"
    assert abs(float(lines["dynamo_t_sum"]) - 56.05) < 1e-9

    # labeled series keep independent bucket vectors
    h2 = Histogram("dynamo_p", "p", buckets=(1.0,))
    h2.observe(0.5, phase="a")
    h2.observe(2.0, phase="b")
    t2 = h2.render()
    assert 'dynamo_p_bucket{le="1.0",phase="a"} 1' in t2
    assert 'dynamo_p_bucket{le="1.0",phase="b"} 0' in t2


def test_uptime_help_and_merged_registries():
    """dynamo_uptime_seconds carries a # HELP line, and rendering two
    registries together emits each # TYPE/# HELP header (and the unlabeled
    uptime sample) exactly once (satellite fixes)."""
    a, b = MetricsRegistry(), MetricsRegistry()
    a.counter("reqs", "requests").inc(route="x")
    b.counter("reqs", "requests").inc(route="y")
    b.histogram("ttft_seconds", "ttft").observe(0.1)

    single = a.render()
    assert "# HELP dynamo_uptime_seconds" in single

    merged = render_registries(a, b)
    assert merged.count("# TYPE dynamo_uptime_seconds gauge") == 1
    assert merged.count("# TYPE dynamo_reqs counter") == 1
    assert merged.count("# HELP dynamo_reqs") == 1
    # both registries' labeled series survive the merge
    assert 'dynamo_reqs{route="x"}' in merged
    assert 'dynamo_reqs{route="y"}' in merged
    # exactly one unlabeled uptime sample
    ups = [ln for ln in merged.splitlines()
           if ln.startswith("dynamo_uptime_seconds ")]
    assert len(ups) == 1
    assert "dynamo_ttft_seconds" in merged


def test_merged_registries_duplicate_unlabeled_histogram():
    """Two registries sharing an unlabeled histogram must not emit
    duplicate _bucket/_sum/_count series (Prometheus rejects the scrape)."""
    a, b = MetricsRegistry(), MetricsRegistry()
    a.histogram("ttft_seconds", "t", buckets=(1.0,)).observe(0.5)
    b.histogram("ttft_seconds", "t", buckets=(1.0,)).observe(0.7)
    merged = render_registries(a, b)
    assert merged.count('dynamo_ttft_seconds_bucket{le="1.0"}') == 1
    assert len([ln for ln in merged.splitlines()
                if ln.startswith("dynamo_ttft_seconds_sum")]) == 1
    # labeled histograms from a later registry still merge through
    b2 = MetricsRegistry()
    b2.histogram("phase_seconds", "p", buckets=(1.0,)).observe(0.5, phase="x")
    merged2 = render_registries(a, b2)
    assert 'phase="x"' in merged2


def test_malformed_traceparent_still_traces():
    """A malformed client traceparent is replaced (W3C ignore-invalid), so
    tracing/SLO recording survives instead of silently no-opping."""
    ctx = Context(traceparent="garbage")
    tp = ctx.ensure_traceparent()
    assert parse_traceparent(tp) is not None
    assert ctx.traceparent_synthesized  # the frontend keys root adoption on this
    tracer = Tracer(service="t", capacity=8)
    with tracer.span("http.request", ctx,
                     adopt_wire_span=ctx.traceparent_synthesized) as root:
        pass
    assert len(tracer.all_spans()) == 1
    assert root.parent_span_id is None  # no phantom parent
    # a VALID inbound traceparent is preserved and stays the remote parent
    good = Context(traceparent="00-" + "a" * 32 + "-" + "b" * 16 + "-01")
    good.ensure_traceparent()
    assert not good.traceparent_synthesized
    with tracer.span("http.request", good,
                     adopt_wire_span=good.traceparent_synthesized) as r2:
        pass
    assert r2.trace_id == "a" * 32 and r2.parent_span_id == "b" * 16


def test_future_version_traceparent_accepted():
    """W3C: parsers must accept the first four fields of higher-version
    traceparent headers (which may carry extra dash-separated fields)."""
    tp = "cc-" + "a" * 32 + "-" + "b" * 16 + "-01-extrafield"
    ctx = Context(traceparent=tp)
    assert ctx.ensure_traceparent() == tp  # preserved, not replaced
    assert not ctx.traceparent_synthesized
    assert parse_traceparent(tp) == ("a" * 32, "b" * 16)
    # the next hop rewrites to the 4-field form we understand
    hop = ctx.child_traceparent()
    parts = hop.split("-")
    assert len(parts) == 4 and parts[1] == "a" * 32 and parts[2] != "b" * 16


def test_rpc_hop_spans_stay_out_of_histograms():
    """rpc.send markers (start==end) are stored for stitching but excluded
    from dynamo_phase_seconds — an always-zero phase is dashboard noise."""
    tracer = Tracer(service="t", capacity=8)
    ctx = Context()
    ctx.ensure_traceparent()
    hop = tracer.record_hop(ctx, ctx.child_traceparent())
    assert any(s.span_id == hop.span_id for s in tracer.all_spans())
    assert 'phase="rpc.send"' not in tracer.metrics.render()


async def test_metrics_aggregator_counter_types():
    """kv_blocks_{stored,removed}_total render as counters, not gauges
    (satellite fix in metrics/main.py)."""
    from dynamo_tpu.metrics.main import MetricsService
    from dynamo_tpu.runtime import DistributedRuntime

    rt = await DistributedRuntime.create()
    try:
        svc = MetricsService(rt)
        svc.kv_stored, svc.kv_removed = 7, 3
        text = svc.render(prefill_queue_depth=2)
        assert "# TYPE dynamo_kv_blocks_stored_total counter" in text
        assert "# TYPE dynamo_kv_blocks_removed_total counter" in text
        assert "dynamo_kv_blocks_stored_total 7" in text
        # non-monotonic series stay gauges
        assert "# TYPE dynamo_prefill_queue_depth gauge" in text
    finally:
        await rt.shutdown()


# ---------------------------------------------------- tracer + propagation


def test_traceparent_roundtrip_and_span_parenting():
    """Trace ids survive to_wire/from_wire, and the rpc.send hop span
    recorded by the sender stitches the receiver's spans back to the
    sender's chain (frontend→worker hop, simulated)."""
    frontend = Tracer(service="frontend", capacity=64)
    worker = Tracer(service="worker", capacity=64)

    ctx = Context()
    with frontend.span("http.request", ctx) as root:
        assert root.trace_id == parse_traceparent(ctx.traceparent)[0]
        ctx_wire = ctx.to_wire()
        hop = frontend.record_hop(ctx, ctx_wire["traceparent"])
        # wire round-trip: same trace id, fresh span id
        w_trace, w_span = parse_traceparent(ctx_wire["traceparent"])
        assert w_trace == root.trace_id and w_span != root.span_id
        assert hop.span_id == w_span
        assert hop.parent_span_id == root.span_id

        # "worker process": fresh contextvars (no inherited CURRENT_SPAN)
        wctx = Context.from_wire(ctx_wire)

        def worker_side():
            with worker.span("worker.handle", wctx) as sp:
                pass
            return sp

        wspan = contextvars.Context().run(worker_side)
    assert wspan.trace_id == root.trace_id
    assert wspan.parent_span_id == hop.span_id  # stitches through the hop

    # the full set stitches into one rooted tree with no orphans
    spans = [s.to_dict() for s in
             frontend.spans_for(ctx.id) + worker.spans_for(ctx.id)]
    assert {s["name"] for s in spans} == {"http.request", "rpc.send",
                                          "worker.handle"}
    tree = stitch(spans)
    assert [t["name"] for t in tree] == ["http.request", "rpc.send",
                                         "worker.handle"]
    assert [t["depth"] for t in tree] == [0, 1, 2]


def test_tracer_same_task_nesting_and_noop():
    tracer = Tracer(service="t", capacity=8)
    ctx = Context()
    with tracer.span("outer", ctx) as outer:
        with tracer.span("inner", ctx) as inner:
            inner.set(k=1)
        assert inner.parent_span_id == outer.span_id
    # ring buffer bound: capacity 8 keeps only the newest 8
    for i in range(20):
        tracer.record("x", ctx, start=float(i), end=float(i))
    assert len(tracer.all_spans()) == 8

    # trace-less contexts no-op instead of raising
    class NullCtx:
        id = "local"
        cancelled = False

    with tracer.span("nope", NullCtx()) as sp:
        sp.set(a=1)
        sp.status = "error"  # noop spans swallow attribute writes
    assert all(s.name != "nope" for s in tracer.all_spans())


def test_span_histograms_and_jsonl_export(tmp_path):
    """Span end feeds dynamo_phase_seconds{phase=...} (+ the per-name SLO
    histograms), and the buffer exports as JSONL."""
    tracer = Tracer(service="t", capacity=32)
    ctx = Context()
    tracer.record("ttft", ctx, start=100.0, end=100.5)
    tracer.record("http.request", ctx, start=100.0, end=101.0)
    text = tracer.metrics.render()
    assert 'dynamo_phase_seconds_bucket{le="0.5",phase="ttft"} 1' in text
    assert "dynamo_ttft_seconds_count 1" in text
    assert "dynamo_e2e_seconds_count 1" in text
    assert "dynamo_itl_seconds" in text  # pre-created, present when empty

    path = tmp_path / "spans.jsonl"
    n = tracer.export_jsonl(str(path))
    lines = [json.loads(ln) for ln in path.read_text().splitlines()]
    assert n == len(lines) == 2
    assert {d["name"] for d in lines} == {"ttft", "http.request"}
    assert Span.from_dict(lines[0]).trace_id == lines[0]["trace_id"]


async def test_trace_collector_over_control_plane():
    """serve_traces registers under the primary lease; fetch_trace fans out
    and merges (the transport behind /v1/traces and dynctl trace)."""
    from dynamo_tpu.runtime import DistributedRuntime

    rt = await DistributedRuntime.create()
    try:
        tracer = Tracer(service="workerA", capacity=32)
        ctx = Context(id="req-1")
        tracer.record("engine.ttft", ctx, start=1.0, end=1.2)
        tracer.record("engine.decode", ctx, start=1.2, end=2.0)
        handle = await serve_traces(rt, tracer)

        spans = await fetch_trace(rt.plane, "req-1")
        assert {s["name"] for s in spans} == {"engine.ttft", "engine.decode"}
        assert spans[0]["start"] <= spans[1]["start"]
        assert await fetch_trace(rt.plane, "no-such-request") == []

        await handle.stop()
        assert await fetch_trace(rt.plane, "req-1") == []
    finally:
        await rt.shutdown()


# ------------------------------------------------------ end-to-end smoke


async def test_observe_smoke_full_stack():
    """The tier-1 wiring of ``bench.py --observe``: one mock request yields
    a complete stitched trace (≥6 named phases incl. TTFT and ITL) via
    /v1/traces/{request_id}, and /metrics exposes the SLO histograms."""
    import bench

    out = await bench.observe_smoke()
    assert out["observe"] == "ok"
    assert len(out["phases"]) >= 6
    for phase in ("ttft", "itl", "http.request", "router.schedule"):
        assert phase in out["phases"]
