"""Logprobs: engine top-k capture → OpenAI logprobs surface → sensitivity
analysis (ref: lib/llm/src/perf/logprobs.rs)."""

import asyncio
import json
import math

import numpy as np
import pytest

from dynamo_tpu.perf.logprobs import (
    analyze_logprob_sensitivity, compare_runs,
)

pytestmark = pytest.mark.anyio


async def test_engine_emits_top_logprobs():
    from dynamo_tpu.engine.config import EngineArgs, ModelConfig
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.protocols import (
        OutputOptions, PreprocessedRequest, SamplingOptions, StopConditions,
    )

    eng = AsyncJaxEngine(ModelConfig.tiny(), EngineArgs(
        block_size=4, num_blocks=64, max_num_seqs=4,
        max_num_batched_tokens=32, max_model_len=128,
        prefill_buckets=(8, 16, 32), decode_batch_buckets=(1, 2, 4),
        multi_step_decode=4))  # burst enabled: logprobs must bypass it
    req = PreprocessedRequest(
        model="t", token_ids=list(range(1, 9)),
        stop_conditions=StopConditions(max_tokens=5, ignore_eos=True),
        sampling_options=SamplingOptions(temperature=0.0),
        output_options=OutputOptions(logprobs=3))
    outs = []
    async for out in eng.generate(req):
        outs.append(out)
    toks = [t for o in outs for t in o.token_ids]
    tops = [tp for o in outs for tp in (o.top_logprobs or [])]
    assert len(tops) == len(toks) == 5
    for tok, alts in zip(toks, tops):
        assert 1 <= len(alts) <= 3
        # sorted descending, and greedy's choice is the argmax entry
        lps = [p for _, p in alts]
        assert lps == sorted(lps, reverse=True)
        assert alts[0][0] == tok  # temperature=0 → selected is the best
        assert all(p <= 0.0 for p in lps)  # logprobs, normalized
    await eng.close()


async def test_logprobs_through_openai_surface():
    """in-process pipeline: chat request with logprobs → chunks carry
    logprobs.content; aggregation folds them into the final choice."""
    from dynamo_tpu.engine.config import EngineArgs, ModelConfig
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.llm.pipeline import aggregate_chat_stream, build_pipeline
    from dynamo_tpu.llm.tokenizer import make_test_tokenizer
    from dynamo_tpu.protocols import PreprocessedRequest
    from dynamo_tpu.protocols.openai import parse_chat_request
    from dynamo_tpu.runtime.context import Context

    tk = make_test_tokenizer()
    cfg = ModelConfig.tiny(vocab_size=tk.vocab_size)
    eng = AsyncJaxEngine(cfg, EngineArgs(
        block_size=4, num_blocks=64, max_num_seqs=4,
        max_num_batched_tokens=32, max_model_len=128,
        prefill_buckets=(8, 16, 32), decode_batch_buckets=(1, 2, 4)))

    async def engine_fn(request, ctx):
        req = PreprocessedRequest.from_wire(request) \
            if isinstance(request, dict) else request
        async for out in eng.generate(req, ctx):
            yield out.to_wire()

    mdc = ModelDeploymentCard(display_name="t", kv_cache_block_size=4,
                              eos_token_ids=[], tokenizer_ref="test")
    pipe = build_pipeline(mdc, tk, engine_fn)
    parsed = parse_chat_request({
        "model": "t", "messages": [{"role": "user", "content": "hello hi"}],
        "max_tokens": 4, "temperature": 0.0,
        "logprobs": True, "top_logprobs": 2,
    })
    result = await aggregate_chat_stream(pipe.generate(parsed, Context()))
    lp = result["choices"][0].get("logprobs")
    assert lp and len(lp["content"]) == 4
    entry = lp["content"][0]
    assert "token" in entry and isinstance(entry["logprob"], float)
    assert 1 <= len(entry["top_logprobs"]) <= 2
    await eng.close()

    # the analysis module consumes exactly this shape
    analysis = analyze_logprob_sensitivity([result])
    ca = analysis.choices[0]
    assert ca.num_positions == 4
    assert ca.greedy_percentage == 100.0 and ca.likely_greedy


def _resp(tokens_with_alts, index=0):
    content = []
    for tok, lp, alts in tokens_with_alts:
        content.append({
            "token": tok, "logprob": lp,
            "top_logprobs": [{"token": t, "logprob": p}
                             for t, p in [(tok, lp)] + alts]})
    return {"choices": [{"index": index, "logprobs": {"content": content},
                         "message": {}, "finish_reason": "stop"}]}


def test_sensitivity_math():
    resp = _resp([
        ("a", -0.1, [("b", -0.15)]),   # gap 0.05 — a close call
        ("c", -0.2, [("d", -3.0)]),    # gap 2.8 — decisive
        ("e", -1.0, [("f", -0.5)]),    # negative gap — NOT greedy
    ])
    analysis = analyze_logprob_sensitivity([resp])
    ca = analysis.choices[0]
    assert ca.num_positions == 3
    assert len(ca.close_positions(0.1)) == 1
    assert len(ca.close_positions(1.0)) == 2  # |gap| 0.05 and 0.5
    assert abs(ca.greedy_percentage - 200 / 3) < 1e-6
    assert not ca.likely_greedy
    m = ca.min_gap
    assert m.position == 0 and m.closest_alternative == "b"
    d = analysis.to_dict()
    assert d["choices"][0]["positions"] == 3


def test_compare_runs():
    a = _resp([("x", -0.1, []), ("y", -0.2, []), ("z", -0.3, [])])
    b = _resp([("x", -0.1, []), ("y", -0.25, []), ("w", -0.3, [])])
    cmp_res = compare_runs(a, b)
    assert cmp_res.first_divergence == 2
    assert cmp_res.num_compared == 2
    assert abs(cmp_res.max_logprob_delta - 0.05) < 1e-9

    same = compare_runs(a, a)
    assert same.first_divergence is None
    assert same.max_logprob_delta == 0.0


def test_cli_on_jsonl(tmp_path, capsys):
    from dynamo_tpu.perf.logprobs import main

    p = tmp_path / "resp.jsonl"
    p.write_text(json.dumps(_resp([("a", -0.1, [("b", -0.12)])])) + "\n")
    assert main([str(p), "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["choices"][0]["positions"] == 1


def test_chat_logprobs_true_without_top_logprobs():
    """OpenAI semantics: logprobs=true alone returns the selected token's
    logprob with NO alternatives; top_logprobs=N adds N alternatives
    (round-2 advisor: true alone mapped to one alternative)."""
    from dynamo_tpu.protocols.openai import parse_chat_request

    body = {"model": "m", "messages": [{"role": "user", "content": "x"}]}
    assert parse_chat_request({**body, "logprobs": True}).output.logprobs == 0
    assert parse_chat_request(
        {**body, "logprobs": True, "top_logprobs": 3}).output.logprobs == 3
    assert parse_chat_request({**body, "logprobs": False}).output.logprobs is None
    assert parse_chat_request(body).output.logprobs is None


# ------------------------------------------------- stream recording (r5)

@pytest.mark.anyio
async def test_record_stream_passthrough_and_timing():
    """Passthrough recording is invisible to the consumer and captures a
    faithful timeline (ref: perf.rs RecordingMode::Passthrough)."""
    import asyncio

    from dynamo_tpu.perf import record_stream, summarize

    async def gen():
        for i in range(5):
            await asyncio.sleep(0.02)
            yield {"token": i}

    rec = record_stream(gen(), request_id="r1")
    got = [item async for item in rec]
    assert got == [{"token": i} for i in range(5)]

    r = rec.recording
    assert r.response_count == 5 and r.request_id == "r1"
    assert r.ttft == pytest.approx(0.02, abs=0.05)
    gaps = r.inter_arrival_gaps
    assert len(gaps) == 4
    assert all(0.005 < g < 0.2 for g in gaps)
    assert r.total_duration >= 5 * 0.015
    assert r.responses_per_s > 0

    s = summarize([r])
    assert s.count == 1 and s.ttft_p50 == pytest.approx(r.ttft)


@pytest.mark.anyio
async def test_record_stream_sink_and_jsonl_roundtrip(tmp_path):
    from dynamo_tpu.perf import record_stream, summarize
    from dynamo_tpu.perf.recording import dump_jsonl, load_jsonl

    async def gen(n):
        for i in range(n):
            yield i

    recs = []
    for n in (3, 7):
        recs.append(await record_stream(gen(n)).sink())
    assert [r.response_count for r in recs] == [3, 7]

    path = str(tmp_path / "recs.jsonl")
    dump_jsonl(recs, path)
    loaded = load_jsonl(path)
    assert [r.response_count for r in loaded] == [3, 7]
    # timelines survive the roundtrip; payloads default to dropped
    assert loaded[1].responses[6].t_rel == recs[1].responses[6].t_rel
    assert loaded[0].responses[0].data is None
    s = summarize(loaded)
    assert s.count == 2


@pytest.mark.anyio
async def test_record_stream_partial_consumption_still_closes_timing():
    """A consumer that abandons the stream mid-way still gets a coherent
    recording (total_duration set in the finally)."""
    from dynamo_tpu.perf import record_stream

    async def gen():
        for i in range(100):
            yield i

    rec = record_stream(gen())
    agen = rec.__aiter__()
    for _ in range(3):
        await agen.__anext__()
    await agen.aclose()
    assert rec.recording.response_count == 3
    assert rec.recording.total_duration > 0
