"""Control-plane (dynctl) ceiling benchmark — VERDICT r1 weak #7: "request
ingress, KV events, and metrics all share one asyncio hub with no benchmark
of its ceiling."

Measures, against a real TCP ControlPlaneServer with N concurrent client
processes' worth of connections:

- **rpc**: request/reply round-trips/s through a served endpoint subject
  (the request-plane hop every inference request pays once — the response
  stream itself rides direct worker↔frontend TCP, not the hub);
- **kv_put**: discovery-write ops/s;
- **stream_publish**: KV-event appends/s (the router feed).

Usage: python -m benchmarks.hub_bench [--clients 8] [--seconds 3]
Prints one JSON line per op kind.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import time

import msgpack

from dynamo_tpu.runtime.control_plane import (
    ControlPlaneServer, RemoteControlPlane,
)


async def _timed(clients, seconds: float, op) -> dict:
    stop = time.perf_counter() + seconds
    counts = [0] * len(clients)

    async def worker(i, plane):
        while time.perf_counter() < stop:
            await op(i, counts[i], plane)
            counts[i] += 1

    t0 = time.perf_counter()
    await asyncio.gather(*(worker(i, p) for i, p in enumerate(clients)))
    dt = time.perf_counter() - t0
    total = sum(counts)
    return {"ops": total, "seconds": round(dt, 3),
            "ops_per_s": round(total / dt, 1)}


async def amain():
    ap = argparse.ArgumentParser(description="dynctl hub ceiling bench")
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--seconds", type=float, default=3.0)
    cli = ap.parse_args()

    server = ControlPlaneServer(port=0)
    addr = await server.start()
    clients = [await RemoteControlPlane(addr).connect()
               for _ in range(cli.clients)]

    # an echo service on the hub's request plane
    async def echo(payload: bytes) -> bytes:
        return payload

    await clients[0].serve("bench.echo", echo)
    payload = msgpack.packb({"tokens": list(range(64))})

    results = {}

    async def rpc(i, n, plane):
        await plane.request("bench.echo", payload, timeout=30.0)

    results["rpc_roundtrips"] = await _timed(clients, cli.seconds, rpc)

    async def kv(i, n, plane):
        await plane.kv_put(f"bench/{i}/{n % 512}", payload)

    results["kv_put"] = await _timed(clients, cli.seconds, kv)

    async def pub(i, n, plane):
        await plane.stream_publish("bench_events", payload)

    results["stream_publish"] = await _timed(clients, cli.seconds, pub)

    for name, r in results.items():
        print(json.dumps({"metric": f"hub_{name}", "clients": cli.clients,
                          **r}), flush=True)

    for c in clients:
        await c.close()
    await server.stop()


if __name__ == "__main__":
    asyncio.run(amain())
