"""KServe v2 gRPC frontend (open inference protocol).

Rebuild of the reference's tonic KServe service (ref: lib/llm/src/grpc/
service/kserve.rs:31+, protos/kserve.proto): text-in/text-out LLM inference
over the standard ``inference.GRPCInferenceService``:

- ``ServerLive`` / ``ServerReady`` / ``ModelReady`` — health surface.
- ``ServerMetadata`` / ``ModelMetadata`` — model discovery; every served
  model advertises ``text_input`` (BYTES, [1]), ``streaming`` (BOOL, [1])
  inputs and a ``text_output`` (BYTES) output, matching the reference's
  tensor contract (kserve.rs:344-402).
- ``ModelInfer`` — unary: decodes ``text_input`` (bytes_contents or
  length-prefixed raw form), lowers onto the completion pipeline, folds the
  stream, returns one ``text_output`` tensor. A truthy ``streaming`` tensor
  is rejected like the reference (kserve.rs:190).
- ``ModelStreamInfer`` — one request in, a ``ModelStreamInferResponse`` per
  generated delta out; engine errors ride ``error_message``.

Sampling knobs arrive as request ``parameters`` (max_tokens, temperature,
top_p, seed) — InferParameter int64/double values.

The service stubs are hand-wired through ``grpc.method_handlers_generic_handler``
(message classes come from protoc's ``kserve_pb2``; the grpc codegen plugin
is not in the image).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

import grpc
from grpc import aio

from dynamo_tpu.frontend import kserve_pb2 as pb
from dynamo_tpu.llm.discovery import ModelManager
from dynamo_tpu.llm.pipeline import aggregate_completion_stream
from dynamo_tpu.protocols import Annotated
from dynamo_tpu.protocols.openai import RequestError, parse_completion_request
from dynamo_tpu.runtime.context import Context

logger = logging.getLogger("dynamo.grpc")

_SERVICE = "inference.GRPCInferenceService"


def _param_value(p: pb.InferParameter):
    which = p.WhichOneof("parameter_choice")
    return getattr(p, which) if which else None


class _ParsedInfer:
    def __init__(self):
        self.text_input: Optional[str] = None
        self.streaming = False


def _parse_infer_request(req: pb.ModelInferRequest) -> _ParsedInfer:
    """Decode the tensor contract (ref: kserve.rs:442-527)."""
    out = _ParsedInfer()
    raw_idx = 0  # tensors without inline contents consume raw slots in order
    for t in req.inputs:
        raw = None
        if not t.contents.ListFields():
            if raw_idx < len(req.raw_input_contents):
                raw = req.raw_input_contents[raw_idx]
            raw_idx += 1
        if t.name == "text_input":
            if t.contents.bytes_contents:
                if t.datatype not in ("", "BYTES"):
                    raise RequestError(
                        f"'text_input' must be BYTES, got {t.datatype}")
                out.text_input = t.contents.bytes_contents[0].decode(
                    "utf-8", "replace")
            elif raw is not None:
                if len(raw) < 4:  # length-prefixed string encoding
                    raise RequestError(
                        "'text_input' raw input must be length-prefixed")
                out.text_input = raw[4:].decode("utf-8", "replace")
            else:
                raise RequestError("missing contents for 'text_input'")
        elif t.name in ("streaming", "stream"):
            if t.contents.bool_contents:
                out.streaming = bool(t.contents.bool_contents[0])
            elif raw:  # raw BOOL: one byte per element
                out.streaming = raw[0] != 0
        else:
            raise RequestError(
                f"invalid input name: {t.name}; supported inputs are "
                "'text_input', 'streaming'")
    if out.text_input is None:
        raise RequestError("missing required input: 'text_input'")
    return out


def _completion_body(req: pb.ModelInferRequest, parsed: _ParsedInfer) -> dict:
    body = {"model": req.model_name, "prompt": parsed.text_input,
            "stream": parsed.streaming}
    params = {k: _param_value(v) for k, v in req.parameters.items()}
    for k in ("max_tokens", "temperature", "top_p", "seed", "top_k",
              "frequency_penalty", "presence_penalty"):
        if params.get(k) is not None:
            body[k] = params[k]
    if isinstance(body.get("max_tokens"), float):
        body["max_tokens"] = int(body["max_tokens"])
    if isinstance(params.get("stop"), str):
        body["stop"] = params["stop"]
    return body


def _text_response(model: str, rid: str, texts: list[str],
                   finished: bool = True) -> pb.ModelInferResponse:
    resp = pb.ModelInferResponse(model_name=model, id=rid)
    tensor = resp.outputs.add()
    tensor.name = "text_output"
    tensor.datatype = "BYTES"
    tensor.shape.append(len(texts))
    tensor.contents.bytes_contents.extend(t.encode() for t in texts)
    if finished:
        resp.parameters["triton_final_response"].bool_param = True
    return resp


class KserveGrpcService:
    """gRPC server fronting the same ModelManager as the HTTP service."""

    def __init__(self, manager: ModelManager, host: str = "0.0.0.0",
                 port: int = 8787):
        self.manager = manager
        self.host = host
        self.port = port
        self._server: Optional[aio.Server] = None

    # -- rpc handlers ------------------------------------------------------

    async def server_live(self, request, context) -> pb.ServerLiveResponse:
        return pb.ServerLiveResponse(live=True)

    async def server_ready(self, request, context) -> pb.ServerReadyResponse:
        return pb.ServerReadyResponse(ready=bool(self.manager.list_models()))

    async def model_ready(self, request, context) -> pb.ModelReadyResponse:
        return pb.ModelReadyResponse(
            ready=self.manager.get(request.name) is not None)

    async def server_metadata(self, request, context):
        return pb.ServerMetadataResponse(
            name="dynamo-tpu", version="0.2", extensions=["llm"])

    async def model_metadata(self, request, context):
        if self.manager.get(request.name) is None:
            await context.abort(grpc.StatusCode.NOT_FOUND,
                                f"model '{request.name}' not found")
        md = pb.ModelMetadataResponse(
            name=request.name, versions=["1"], platform="dynamo")
        md.inputs.add(name="text_input", datatype="BYTES", shape=[1])
        md.inputs.add(name="streaming", datatype="BOOL", shape=[1])
        md.outputs.add(name="text_output", datatype="BYTES", shape=[-1])
        return md

    async def model_infer(self, request, context) -> pb.ModelInferResponse:
        served = self.manager.get(request.model_name)
        if served is None:
            await context.abort(grpc.StatusCode.NOT_FOUND,
                                f"model '{request.model_name}' not found")
        try:
            parsed_in = _parse_infer_request(request)
            if parsed_in.streaming:
                raise RequestError(
                    "streaming is not supported by ModelInfer; use "
                    "ModelStreamInfer")
            parsed = parse_completion_request(
                _completion_body(request, parsed_in))
        except RequestError as e:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        ctx = Context()
        try:
            result = await aggregate_completion_stream(
                served.pipeline.generate(parsed, ctx))
        except asyncio.CancelledError:
            # client cancelled / deadline exceeded: stop the worker too
            ctx.cancel()
            raise
        except Exception as e:
            ctx.cancel()
            await context.abort(grpc.StatusCode.INTERNAL, repr(e))
        texts = [c.get("text", "") for c in result["choices"]]
        return _text_response(request.model_name, request.id, texts)

    async def model_stream_infer(self, request_iterator, context):
        """One inbound request drives one outbound delta stream (the
        reference demuxes the same way — kserve.rs:242)."""
        request = await request_iterator.__anext__()
        served = self.manager.get(request.model_name)
        if served is None:
            yield pb.ModelStreamInferResponse(
                error_message=f"model '{request.model_name}' not found")
            return
        try:
            parsed_in = _parse_infer_request(request)
            body = _completion_body(request, parsed_in)
            body["stream"] = True
            parsed = parse_completion_request(body)
        except RequestError as e:
            yield pb.ModelStreamInferResponse(error_message=str(e))
            return
        ctx = Context()
        try:
            async for wire in served.pipeline.generate(parsed, ctx):
                ann = Annotated.from_wire(wire)
                if ann.is_error():
                    yield pb.ModelStreamInferResponse(
                        error_message="; ".join(ann.comment or ["error"]))
                    return
                if ann.event is not None or ann.data is None:
                    continue
                chunk = ann.data
                texts = [c.get("text", "") for c in chunk.get("choices", [])]
                done = any(c.get("finish_reason")
                           for c in chunk.get("choices", []))
                yield pb.ModelStreamInferResponse(
                    infer_response=_text_response(
                        request.model_name, request.id, texts, finished=done))
        except BaseException:
            ctx.cancel()  # client went away or engine died: stop the worker
            raise

    # -- lifecycle ---------------------------------------------------------

    def _handlers(self) -> grpc.GenericRpcHandler:
        def unary(fn, req_cls, resp_cls):
            # grpc.aio servers accept coroutine handlers through the plain
            # grpc method-handler constructors
            return grpc.unary_unary_rpc_method_handler(
                fn, request_deserializer=req_cls.FromString,
                response_serializer=resp_cls.SerializeToString)

        handlers = {
            "ServerLive": unary(self.server_live, pb.ServerLiveRequest,
                                pb.ServerLiveResponse),
            "ServerReady": unary(self.server_ready, pb.ServerReadyRequest,
                                 pb.ServerReadyResponse),
            "ModelReady": unary(self.model_ready, pb.ModelReadyRequest,
                                pb.ModelReadyResponse),
            "ServerMetadata": unary(self.server_metadata,
                                    pb.ServerMetadataRequest,
                                    pb.ServerMetadataResponse),
            "ModelMetadata": unary(self.model_metadata,
                                   pb.ModelMetadataRequest,
                                   pb.ModelMetadataResponse),
            "ModelInfer": unary(self.model_infer, pb.ModelInferRequest,
                                pb.ModelInferResponse),
            "ModelStreamInfer": grpc.stream_stream_rpc_method_handler(
                self.model_stream_infer,
                request_deserializer=pb.ModelInferRequest.FromString,
                response_serializer=pb.ModelStreamInferResponse.SerializeToString),
        }
        return grpc.method_handlers_generic_handler(_SERVICE, handlers)

    async def start(self) -> int:
        self._server = aio.server()
        self._server.add_generic_rpc_handlers((self._handlers(),))
        self.port = self._server.add_insecure_port(f"{self.host}:{self.port}")
        await self._server.start()
        logger.info("KServe gRPC frontend on %s:%d", self.host, self.port)
        return self.port

    async def stop(self):
        if self._server:
            await self._server.stop(grace=2.0)
