"""Simulated engine: continuous batching, chunked prefill, prefix cache,
genuine KV events and load metrics — no accelerator needed.

Rebuild of the reference's mocker (ref: lib/llm/src/mocker/{engine.rs:48,
scheduler.rs:240,kv_manager.rs,evictor.rs,protocols.rs:67-100}): the mocker is
the backbone of router/planner/frontend tests because it emits *real* KV
events (same hash domain as the frontend) and real ForwardPassMetrics while
modeling engine timing (prefill cost, chunked prefill, decode batching,
watermark-based admission, LRU prefix-cache eviction).

The token stream it produces is deterministic per request (seeded by the
prompt) so tests can assert determinism.
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from dataclasses import dataclass, field
from typing import AsyncIterator, Optional

from dynamo_tpu.protocols import FinishReason, LLMEngineOutput, PreprocessedRequest
from dynamo_tpu.router.protocols import ForwardPassMetrics, KvStats, StoredBlock, WorkerStats
from dynamo_tpu.router.publisher import KvEventPublisher, WorkerMetricsPublisher
from dynamo_tpu.runtime.chaos import get_chaos
from dynamo_tpu.runtime.context import Context, StreamError
from dynamo_tpu.tokens import TokenBlockSequence

logger = logging.getLogger("dynamo.mocker")


@dataclass
class MockEngineArgs:
    """ref: mocker/protocols.rs:67-100 (same knobs, same defaults where sane)."""

    num_gpu_blocks: int = 8192
    block_size: int = 16
    max_num_seqs: int = 256
    max_num_batched_tokens: int = 8192
    enable_prefix_caching: bool = True
    enable_chunked_prefill: bool = True
    watermark: float = 0.01
    speedup_ratio: float = 1.0
    #: base + per-token prefill cost (ms), divided by speedup_ratio
    prefill_base_ms: float = 5.0
    prefill_per_token_ms: float = 0.02
    #: base + per-seq decode cost (ms) per iteration
    decode_base_ms: float = 2.0
    decode_per_seq_ms: float = 0.05
    vocab_size: int = 1000
    #: data-parallel ranks simulated by ONE mocker process (ref:
    #: mocker/protocols.rs:95 + engine.rs:115-127 — one scheduler, KV-event
    #: stream and metrics publisher per rank). Ranks surface as separate
    #: instances on the endpoint, so the router sees per-rank event
    #: interleaving exactly as it would from a real DP fleet.
    dp_size: int = 1
    #: simulated engine-initialization delay before serving (ref:
    #: protocols.rs:98 startup_time)
    startup_time: Optional[float] = None
    #: token-budget planning (the real engine's ragged-step mode,
    #: docs/performance.md): decode rows and prefill chunks co-schedule
    #: under ONE max_num_batched_tokens budget per step — decode rows
    #: reserve a token each first, prefill fills the remainder — and a
    #: mixed step costs a SINGLE launch (one base latency, not
    #: prefill_base + decode_base). Fleet-level tests (autoscale, QoS,
    #: chaos) therefore exercise the new planning mode without a real
    #: model; False restores the independent prefill/decode budgets.
    token_budget_plan: bool = True


#: the mocker's constraint alphabet (structured-decoding parity): token id
#: i decodes to one printable char, id 0 reserved — the same shape the
#: engine-level guided tests use, so fleet tests can assert schema-valid
#: canned output by decoding the token stream against it
_GUIDED_VOCAB: list = []


def mock_guided_vocab() -> list[str]:
    global _GUIDED_VOCAB
    if not _GUIDED_VOCAB:
        _GUIDED_VOCAB = [""] + [chr(32 + i) for i in range(95)]
    return _GUIDED_VOCAB


@dataclass
class _Seq:
    request_id: str
    req: PreprocessedRequest
    ctx: Context
    out_queue: "asyncio.Queue[Optional[LLMEngineOutput]]"
    blocks: TokenBlockSequence = None  # full sequence incl. generated
    prefill_pos: int = 0  # tokens prefilled so far
    cached_tokens: int = 0  # tokens skipped via prefix cache
    generated: int = 0
    rng: random.Random = None
    owned_block_hashes: list[int] = field(default_factory=list)
    finished: Optional[str] = None
    #: guided-decoding cursor over mock_guided_vocab (llm/guided
    #: GuidedState via structured.build_guided_state) — None = free decode
    guided: object = None

    @property
    def isl(self) -> int:
        return len(self.req.token_ids)

    @property
    def in_prefill(self) -> bool:
        return self.prefill_pos < self.isl


class KvCacheSim:
    """Block pool with active refcounts + inactive LRU prefix cache.

    Mirrors the reference's KvManager+evictor semantics (ref: mocker/
    kv_manager.rs, evictor.rs): blocks are keyed by chained sequence hash;
    completed requests' blocks drop into an LRU reuse pool; admission needs
    free = capacity - active - watermark; storing evicts LRU inactive blocks.
    """

    def __init__(self, capacity: int, watermark: float):
        self.capacity = capacity
        self.watermark_blocks = int(capacity * watermark)
        self.active: dict[int, int] = {}  # seq_hash -> refcount
        self.inactive: dict[int, float] = {}  # seq_hash -> last_use (LRU)
        #: optional WorkerKvLedger (observability/kvaudit.py) — real-
        #: engine parity: membership mirrors active ∪ inactive, so the
        #: KV audit plane measures mocker fleets too
        self.ledger = None

    @property
    def used_blocks(self) -> int:
        return len(self.active) + len(self.inactive)

    @property
    def free_blocks(self) -> int:
        return self.capacity - self.used_blocks

    def can_allocate(self, n: int) -> bool:
        return self.free_blocks + len(self.inactive) - self.watermark_blocks >= n

    def lookup_prefix(self, seq_hashes: list[int]) -> int:
        """Longest cached prefix (active or inactive), in blocks."""
        n = 0
        for h in seq_hashes:
            if h in self.active or h in self.inactive:
                n += 1
            else:
                break
        return n

    def acquire(self, seq_hash: int) -> tuple[bool, list[int]]:
        """Activate a block; returns (is_new_block, evicted_hashes)."""
        evicted: list[int] = []
        if seq_hash in self.active:
            self.active[seq_hash] += 1
            return False, evicted
        if seq_hash in self.inactive:
            del self.inactive[seq_hash]
            self.active[seq_hash] = 1
            return False, evicted
        while self.free_blocks < 1 and self.inactive:
            lru = min(self.inactive, key=self.inactive.get)
            del self.inactive[lru]
            if self.ledger is not None:
                self.ledger.remove("g1", lru)
            evicted.append(lru)
        self.active[seq_hash] = 1
        if self.ledger is not None:
            self.ledger.add("g1", seq_hash)
        return True, evicted

    def release(self, seq_hash: int, cache: bool) -> Optional[int]:
        """Drop one reference; returns the hash if the block left the pool."""
        rc = self.active.get(seq_hash)
        if rc is None:
            return None
        if rc > 1:
            self.active[seq_hash] = rc - 1
            return None
        del self.active[seq_hash]
        if cache:
            self.inactive[seq_hash] = time.monotonic()
            return None
        if self.ledger is not None:
            self.ledger.remove("g1", seq_hash)
        return seq_hash


class MockEngine:
    """Async continuous-batching simulator serving PreprocessedRequests."""

    def __init__(
        self,
        args: MockEngineArgs,
        kv_publisher: Optional[KvEventPublisher] = None,
        metrics_publisher: Optional[WorkerMetricsPublisher] = None,
    ):
        self.args = args
        self.kv_publisher = kv_publisher
        self.metrics_publisher = metrics_publisher
        self.cache = KvCacheSim(args.num_gpu_blocks, args.watermark)
        #: KV audit plane parity (observability/kvaudit.py): the mocker
        #: keeps the same residency ledger a real engine does, served by
        #: run_mocker via the kv_digest wire op; wiring it into the
        #: publisher makes resync replays ledger-reconciling here too
        from dynamo_tpu.observability.kvaudit import WorkerKvLedger
        self.kv_ledger = WorkerKvLedger()
        self.cache.ledger = self.kv_ledger
        if (args.enable_prefix_caching and kv_publisher is not None
                and kv_publisher.ledger is None):
            # caching-off mockers announce blocks they release silently
            # (pre-existing advert semantics) — a ledger-reconciling
            # replay there would retract every advert, so the audit
            # plane only covers prefix-caching workers (engine parity)
            kv_publisher.ledger = self.kv_ledger
        self.waiting: list[_Seq] = []
        self.running: list[_Seq] = []
        self._task: Optional[asyncio.Task] = None
        self._wake = asyncio.Event()
        self._stopped = False
        self.iterations = 0
        #: step flight recorder parity with the real engine
        #: (observability/flight.py): every simulated step appends one
        #: tagged record, so fleet-level tests and `dynctl top` see the
        #: same timeline shape without an accelerator. run_mocker
        #: registers it per rank for the fan-out endpoint.
        from dynamo_tpu.observability.flight import FlightRecorder
        self.flight = FlightRecorder(service="mocker")
        self._last_empty_rec = 0.0
        #: chaos worker.kill (runtime/chaos.py): hard-died mid-step —
        #: in-flight queues never resolve, death reaches the fleet only
        #: via lease expiry (same contract as the real engine)
        self.killed = False
        self.on_kill: list = []

    async def start(self) -> "MockEngine":
        self._task = asyncio.get_running_loop().create_task(self._engine_loop())
        return self

    async def stop(self):
        self._stopped = True
        self._wake.set()
        if self._task:
            await self._task
        name = getattr(self, "_flight_name", None)
        if name is not None:  # set by run_mocker's per-rank registration
            from dynamo_tpu.observability.flight import unregister_recorder
            unregister_recorder(name)

    # -- public engine interface ------------------------------------------
    async def generate(self, req, ctx: Context) -> AsyncIterator[dict]:
        """Endpoint handler: yields LLMEngineOutput wire dicts."""
        if isinstance(req, dict):
            req = PreprocessedRequest.from_wire(req)
        if getattr(ctx, "expired", False):
            # an expired request must never enter the scheduler
            yield LLMEngineOutput(
                finish_reason=FinishReason.DEADLINE).to_wire()
            return
        seq = _Seq(
            request_id=ctx.id,
            req=req,
            ctx=ctx,
            out_queue=asyncio.Queue(),
            blocks=TokenBlockSequence.from_tokens(req.token_ids, self.args.block_size),
            rng=random.Random(req.sampling_options.seed if req.sampling_options.seed is not None
                              else hash(tuple(req.token_ids)) & 0xFFFFFFFF),
        )
        if req.sampling_options.guided:
            # structured-decoding parity: fleet tests (QoS/autoscale/chaos)
            # carry constrained traffic through the mocker too — compile
            # the constraint over the mock alphabet (cached + counted like
            # the real engine's admissions) and emit schema-valid output
            from dynamo_tpu.structured import build_guided_state
            seq.guided = await asyncio.to_thread(
                build_guided_state, req.sampling_options.guided,
                mock_guided_vocab(), req.eos_token_ids or [], None)
        self.waiting.append(seq)
        self._wake.set()
        # same engine-side phase spans the real engine records, so the
        # mock path yields a full stitched trace in accelerator-less tests
        # — including the flight identity + step-seq interval attributes
        # the attribution join keys on (observability/attribution.py)
        from dynamo_tpu.observability import get_tracer
        from dynamo_tpu.observability.flight import flight_instance

        tracer = get_tracer()
        t0 = time.time()
        seq0 = self.flight.seq_now
        seq_first = None
        t_first = None
        n_tokens = 0
        try:
            while True:
                out = await seq.out_queue.get()
                if out is None:
                    return
                if isinstance(out, Exception):
                    raise out  # chaos step failure → retryable stream error
                if t_first is None and out.token_ids:
                    t_first = time.time()
                    seq_first = self.flight.seq_now
                    tracer.record("engine.ttft", ctx, start=t0, end=t_first,
                                  service="engine",
                                  prompt_tokens=len(req.token_ids),
                                  cached_tokens=seq.cached_tokens,
                                  flight_instance=flight_instance(),
                                  flight_name=getattr(
                                      self, "_flight_name", "mocker"),
                                  seq0=seq0, seq1=seq_first)
                    out.flight = {"worker": flight_instance(),
                                  "recorder": getattr(
                                      self, "_flight_name", "mocker"),
                                  "seq": seq_first}
                n_tokens += len(out.token_ids)
                yield out.to_wire()
                if out.finish_reason is not None:
                    return
        finally:
            if t_first is not None:
                tracer.record("engine.decode", ctx, start=t_first,
                              end=time.time(), service="engine",
                              tokens=n_tokens,
                              flight_instance=flight_instance(),
                              flight_name=getattr(
                                  self, "_flight_name", "mocker"),
                              seq0=seq_first, seq1=self.flight.seq_now)

    # -- engine loop -------------------------------------------------------
    async def _engine_loop(self):
        try:
            while not self._stopped:
                if not self.running and not self.waiting:
                    self._wake.clear()
                    try:
                        await asyncio.wait_for(self._wake.wait(), timeout=0.5)
                    except asyncio.TimeoutError:
                        continue
                    continue
                await self._step()
        except asyncio.CancelledError:
            pass
        except Exception:
            logger.exception("mocker engine loop crashed")

    async def _step(self):
        self.iterations += 1
        chaos = get_chaos()
        if (chaos is not None and self.running
                and chaos.should_error("worker.kill")):
            # seeded hard death (SIGKILL-grade): stop the loop without
            # resolving any in-flight queue — no drain, no goodbye
            logger.warning("chaos: worker.kill fired — mocker hard-dying "
                           "with %d running seqs", len(self.running))
            self.killed = True
            self._stopped = True
            for cb in list(self.on_kill):
                try:
                    cb()
                except Exception:
                    logger.exception("on_kill hook failed")
            return
        if (chaos is not None and self.running
                and chaos.should_error("engine.step")):
            # injected step crash: in-flight streams fail RETRYABLY so the
            # frontend's Migration operator re-issues them elsewhere — same
            # contract as the real engine's chaos hook
            for seq in self.running:
                if seq.finished is None:
                    seq.finished = FinishReason.ERROR
                    seq.out_queue.put_nowait(StreamError(
                        "chaos: injected engine step error"))
            self._reap_finished()
            return
        self._admit()
        # plan-time deadline enforcement: an expired sequence spends no
        # further simulated step and finishes with the "deadline" reason.
        # The WAITING queue is swept too (same contract as the real
        # scheduler): a request starved behind a saturated batch must not
        # hang past its budget waiting for an admission slot.
        for seq in self.running:
            if seq.finished is None and getattr(seq.ctx, "expired", False):
                seq.finished = FinishReason.DEADLINE
                seq.out_queue.put_nowait(LLMEngineOutput(
                    finish_reason=FinishReason.DEADLINE))
        for seq in list(self.waiting):
            if getattr(seq.ctx, "expired", False):
                self.waiting.remove(seq)
                seq.out_queue.put_nowait(LLMEngineOutput(
                    finish_reason=FinishReason.DEADLINE))
                seq.out_queue.put_nowait(None)
        # orphan-cancellation sweep (front-door kill hygiene, docs/
        # robustness.md): a cancelled context must free its slot whether
        # the row is decoding, MID-PREFILL, or still WAITING. Response-
        # plane peer death cancels a dead frontend's seqs; without this
        # sweep a prefilling/queued orphan would keep burning budget and
        # holding blocks until it finished naturally, so the BlockPool
        # would not return to its pre-request count.
        for seq in self.running:
            if seq.finished is None and seq.ctx.cancelled:
                seq.finished = FinishReason.CANCELLED
                seq.out_queue.put_nowait(LLMEngineOutput.cancelled())
        for seq in list(self.waiting):
            if seq.ctx.cancelled:
                self.waiting.remove(seq)
                seq.out_queue.put_nowait(LLMEngineOutput.cancelled())
                seq.out_queue.put_nowait(None)
        if self.args.token_budget_plan:
            # ragged-style step: decode rows spend the shared budget first
            # (one token each), prefill chunks fill what remains, and the
            # whole step is ONE launch — one base cost covers both kinds
            budget = self.args.max_num_batched_tokens
            decoded = await self._run_decode(
                max_rows=min(budget, self.args.max_num_seqs))
            prefill_tokens = await self._run_prefill_chunk(
                budget=budget - decoded)
            ms = 0.0
            if prefill_tokens or decoded:
                ms = (max(self.args.prefill_base_ms if prefill_tokens else 0.0,
                          self.args.decode_base_ms if decoded else 0.0)
                      + prefill_tokens * self.args.prefill_per_token_ms
                      + decoded * self.args.decode_per_seq_ms)
        else:
            prefill_tokens = await self._run_prefill_chunk()
            decoded = await self._run_decode()
            # simulated iteration latency: two independent launches
            ms = 0.0
            if prefill_tokens:
                ms += self.args.prefill_base_ms + prefill_tokens * self.args.prefill_per_token_ms
            if decoded:
                ms += self.args.decode_base_ms + decoded * self.args.decode_per_seq_ms
        if ms:
            await asyncio.sleep(ms / 1000.0 / self.args.speedup_ratio)
        else:
            await asyncio.sleep(0)
        self._flight_record(prefill_tokens, decoded, ms)
        self._reap_finished()
        await self._publish_metrics()

    def _flight_record(self, prefill_tokens: int, decoded: int,
                       ms: float) -> None:
        """Real-engine flight parity: one record per simulated step. An
        admission-blocked spin (work queued, nothing runnable — the memory
        bubble) records ``empty`` at most every 10 ms so the busy-wait
        cannot flood the ring with identical bubbles."""
        if not self.flight.enabled:
            return
        if not prefill_tokens and not decoded:
            if not (self.waiting or self.running):
                return
            now = time.monotonic()
            if now - self._last_empty_rec < 0.01:
                return
            self._last_empty_rec = now
            self.flight.record(
                "empty", 0.0, waiting=len(self.waiting),
                running=len(self.running),
                kv_tiers={"g1": self.cache.used_blocks})
            return
        chunks = sum(1 for s in self.running if s.in_prefill)
        self.flight.record(
            "mock", ms / self.args.speedup_ratio,
            decode_rows=decoded, prefill_chunks=chunks,
            chunk_tokens=prefill_tokens,
            waiting=len(self.waiting), running=len(self.running),
            # per-row constraint shape parity with the real engine's
            # records (docs/structured.md): fleet views show constrained
            # traffic on mocker fleets too
            constrained_rows=sum(1 for s in self.running
                                 if s.guided is not None
                                 and not s.in_prefill and not s.finished),
            kv_tiers={"g1": self.cache.used_blocks},
            # step↔request linkage parity (attribution join): the mocker's
            # request_id IS the Context id
            decode_ids=[s.request_id for s in self.running
                        if not s.in_prefill and s.finished is None],
            prefill_ids=[s.request_id for s in self.running
                         if s.in_prefill])

    def _admit(self):
        while self.waiting and len(self.running) < self.args.max_num_seqs:
            seq = self.waiting[0]
            needed = len(seq.blocks.blocks) + 1
            if not self.cache.can_allocate(needed):
                break
            self.waiting.pop(0)
            if self.args.enable_prefix_caching:
                cached = self.cache.lookup_prefix(seq.blocks.sequence_hashes())
                seq.cached_tokens = cached * self.args.block_size
                seq.prefill_pos = min(seq.cached_tokens, seq.isl)
            self.running.append(seq)

    async def _run_prefill_chunk(self, budget: Optional[int] = None) -> int:
        if budget is None:
            budget = self.args.max_num_batched_tokens
        total = 0
        for seq in self.running:
            if budget <= 0:
                break
            if not seq.in_prefill or seq.finished:
                continue
            chunk = min(seq.isl - seq.prefill_pos, budget) if self.args.enable_chunked_prefill else (
                seq.isl - seq.prefill_pos
            )
            start_block = seq.prefill_pos // self.args.block_size
            seq.prefill_pos += chunk
            budget -= chunk
            total += chunk
            end_block = seq.prefill_pos // self.args.block_size
            await self._store_blocks(seq, start_block, end_block)
        return total

    async def _store_blocks(self, seq: _Seq, start_block: int, end_block: int):
        """Acquire+announce newly-filled complete blocks [start, end)."""
        blocks = seq.blocks.blocks[start_block:end_block]
        if not blocks:
            return
        stored: list[StoredBlock] = []
        evicted_all: list[int] = []
        parent = seq.blocks.blocks[start_block - 1].sequence_hash if start_block > 0 else None
        for b in blocks:
            is_new, evicted = self.cache.acquire(b.sequence_hash)
            seq.owned_block_hashes.append(b.sequence_hash)
            evicted_all.extend(evicted)
            if is_new:
                stored.append(StoredBlock(block_hash=b.sequence_hash, tokens_hash=b.block_hash))
        if self.kv_publisher:
            if evicted_all:
                await self.kv_publisher.publish_removed(evicted_all)
            if stored:
                await self.kv_publisher.publish_stored(parent, stored)

    async def _run_decode(self, max_rows: Optional[int] = None) -> int:
        n = 0
        for seq in self.running:
            if seq.in_prefill or seq.finished:
                continue
            if max_rows is not None and n >= max_rows:
                break  # token budget spent: the row waits one step
            if seq.ctx.cancelled:
                seq.finished = FinishReason.CANCELLED
                seq.out_queue.put_nowait(LLMEngineOutput.cancelled())
                continue
            n += 1
            max_tokens = seq.req.stop_conditions.max_tokens or 64
            min_tokens = seq.req.stop_conditions.min_tokens or 0
            eos = False
            guided_stop = False
            if seq.guided is not None:
                # constrained row: deterministic greedy walk of the mask —
                # lowest allowed id each step, so the emitted stream is
                # schema-valid by construction (EOS joins the set only
                # where the constraint can terminate)
                gs = seq.guided
                hi = min(len(mock_guided_vocab()), self.args.vocab_size)
                ids = gs.allowed_token_ids(hi)
                if min_tokens > seq.generated:
                    non_eos = [t for t in ids if t not in gs.eos_ids]
                    ids = non_eos or ids
                if not ids:
                    # stranded (possible only past the liveness cap):
                    # finish like the real scheduler would
                    seq.finished = FinishReason.STOP
                    seq.out_queue.put_nowait(LLMEngineOutput(
                        finish_reason=FinishReason.STOP))
                    continue
                tok = ids[0]
                gs.advance(tok)
                eos = (tok in gs.eos_ids
                       and not seq.req.stop_conditions.ignore_eos)
                guided_stop = (gs.exhausted
                               or (gs.done and seq.generated >= min_tokens))
            else:
                tok = seq.rng.randint(10, self.args.vocab_size - 1)
                if seq.req.eos_token_ids and seq.generated >= min_tokens and not seq.req.stop_conditions.ignore_eos:
                    # small chance of sampling EOS to model natural stops
                    if seq.rng.random() < 0.02:
                        tok = seq.req.eos_token_ids[0]
                        eos = True
            new_block = seq.blocks.push_token(tok)
            if new_block is not None:
                await self._store_blocks(
                    seq, len(seq.blocks.blocks) - 1, len(seq.blocks.blocks)
                )
            seq.generated += 1
            finish = None
            if eos:
                finish = FinishReason.EOS
            elif guided_stop and seq.generated >= min_tokens:
                # constraint completed/exhausted: stop instead of free-
                # running past it (scheduler.check_finish parity)
                finish = FinishReason.STOP
            elif seq.generated >= max_tokens:
                finish = FinishReason.LENGTH
            seq.finished = finish
            seq.out_queue.put_nowait(LLMEngineOutput(token_ids=[tok], finish_reason=finish))
        return n

    def _reap_finished(self):
        still = []
        for seq in self.running:
            if seq.finished is None:
                still.append(seq)
                continue
            cache = self.args.enable_prefix_caching
            for h in seq.owned_block_hashes:
                gone = self.cache.release(h, cache)
                # release without caching: block disappears silently; events
                # for disappeared blocks are published on next eviction sweep
            seq.out_queue.put_nowait(None)
        self.running = still

    async def _publish_metrics(self):
        if not self.metrics_publisher or self.iterations % 8:
            return
        m = ForwardPassMetrics(
            worker_stats=WorkerStats(
                request_active_slots=len(self.running),
                request_total_slots=self.args.max_num_seqs,
                num_requests_waiting=len(self.waiting),
            ),
            kv_stats=KvStats(
                kv_active_blocks=len(self.cache.active),
                kv_total_blocks=self.cache.capacity,
                gpu_cache_usage_perc=self.cache.used_blocks / self.cache.capacity,
            ),
        )
        try:
            await self.metrics_publisher.publish(m)
        except Exception:
            logger.exception("metrics publish failed")
