"""Global prefill queue: backlog-controlled dispatch to the prefill fleet.

The reference queues disagg prefill work on a JetStream work queue that
prefill workers pull from (ref: NatsQueue transports/nats.rs:426,
docs/architecture/disagg_serving.md:62-101) — pull beats push-round-robin
because a busy prefill worker simply doesn't pop, and the queue depth is a
direct autoscaling signal for the planner.

Here the queue carries small JOB TICKETS only; the KV pages still flow over
the direct response plane (the fast path). Flow:

  decode worker:  subscribe claim.<job> → queue_push(ticket) → wait claim
                  → client.generate(mode="direct", instance_id=claimed)
  prefill worker: [capacity gate] → queue_pop → publish claim.<job>

A claim timeout on the decode side falls back to round-robin dispatch, so a
fleet without queue-popping workers (or an empty fleet) degrades to the r1
behavior instead of stalling.

QoS-aware pool (docs/disagg.md): tickets are class-split by the request's
priority and workers drain best-class-first; the standard class rides the
legacy plain queue so pre-QoS workers keep serving default traffic.
"""

from __future__ import annotations

import asyncio
import logging
import time
import uuid
from typing import Optional

import msgpack

logger = logging.getLogger("dynamo.prefill_queue")

PREFILL_QUEUE = "prefill_queue"
CLAIM_SUBJECT = "prefill_claim"

#: QoS-aware prefill pool (docs/disagg.md): tickets are class-split so
#: workers pop best-class-first. The STANDARD class rides the legacy plain
#: queue — a pre-QoS worker fleet keeps serving default traffic unchanged;
#: only interactive/batch tickets need upgraded workers. Pop order is
#: interactive → legacy/standard → batch.
QOS_QUEUE_CLASSES = ("interactive", "batch")


def qos_queue_name(queue: str, priority) -> str:
    """Queue a ticket of this priority class lands on."""
    if priority in QOS_QUEUE_CLASSES:
        return f"{queue}.{priority}"
    return queue  # standard/unknown: the legacy queue


def pop_order(queue: str) -> list[str]:
    """Queues a worker drains, best class first."""
    return [f"{queue}.interactive", queue, f"{queue}.batch"]


async def prefill_queue_depth(plane, queue: str = PREFILL_QUEUE) -> int:
    """Total backlog across the class-split queues (the autoscaling /
    metrics signal — a class split must not hide depth)."""
    total = 0
    for q in pop_order(queue):
        total += await plane.queue_depth(q)
    return total


class PrefillQueueClient:
    """Decode-worker side: acquire a prefill worker through the queue."""

    def __init__(self, plane, queue: str = PREFILL_QUEUE,
                 claim_timeout: float = 10.0, metrics=None):
        self.plane = plane
        self.queue = queue
        self.claim_timeout = claim_timeout
        #: claim waits that timed out (fell back to round robin) — mirrored
        #: to ``dynamo_prefill_claim_timeouts_total`` when a registry is given
        self.claim_timeouts = 0
        self._timeout_counter = (
            metrics.counter("prefill_claim_timeouts_total",
                            "prefill queue claim waits that timed out")
            if metrics is not None else None)

    def _budget_s(self, ctx) -> float:
        """Claim wait + ticket TTL derived from the request's remaining
        deadline instead of the flat default: a request with 200 ms left
        must not park a ticket for 10 s, and its ticket must expire the
        moment the decode side would have fallen back anyway."""
        budget = self.claim_timeout
        remaining = ctx.remaining_s() if ctx is not None and hasattr(
            ctx, "remaining_s") else None
        if remaining is not None:
            budget = max(0.0, min(budget, remaining))
        return budget

    async def acquire(self, ctx=None) -> Optional[int]:
        """Enqueue a ticket; returns the claiming prefill worker's instance
        id, or None on timeout (caller falls back to round robin).

        ``ctx`` (optional request Context) attributes the queue wait to the
        request's trace as a ``prefill.queue_wait`` span — the per-phase
        latency signal NetKV-style decode-instance selection hinges on."""
        from dynamo_tpu.observability import get_tracer

        budget = self._budget_s(ctx)
        if budget <= 0:
            return None  # deadline already spent: no point queueing
        job_id = uuid.uuid4().hex
        priority = getattr(ctx, "priority", None)
        tenant = getattr(ctx, "tenant", None)
        sub = await self.plane.subscribe(f"{CLAIM_SUBJECT}.{job_id}")
        span = get_tracer().span("prefill.queue_wait", ctx,
                                 service="disagg")
        try:
            with span as sp:
                # expires_at lets workers discard tickets whose decode side
                # has already fallen back — a stale ticket must not count
                # as work. Tickets are class-split (qos_queue_name) so the
                # prefill pool serves best-class-first; tenant/qos ride the
                # ticket for observability.
                ticket = {"job_id": job_id,
                          "expires_at": time.time() + budget}
                if priority:
                    ticket["qos"] = priority
                if tenant:
                    ticket["tenant"] = tenant
                await self.plane.queue_push(
                    qos_queue_name(self.queue, priority),
                    msgpack.packb(ticket))

                async def first_claim():
                    async for _subject, payload in sub:
                        return msgpack.unpackb(payload, raw=False)
                    return None

                try:
                    claim = await asyncio.wait_for(first_claim(), budget)
                except asyncio.TimeoutError:
                    logger.warning("prefill queue claim timed out after "
                                   "%.1fs; falling back to round robin",
                                   budget)
                    self.claim_timeouts += 1
                    if self._timeout_counter is not None:
                        self._timeout_counter.inc()
                    sp.set(claimed=False, timeout=True)
                    return None
                iid = claim["instance_id"] if claim else None
                sp.set(claimed=iid is not None,
                       instance=f"{iid:x}" if iid is not None else None)
                return iid
        finally:
            await sub.cancel()

    async def depth(self) -> int:
        return await prefill_queue_depth(self.plane, self.queue)


class PrefillQueueWorker:
    """Prefill-worker side: pop tickets when the engine has capacity.

    ``capacity_gate`` is a plain (synchronous) callable returning True when
    this worker should take more work (typically: engine not backlogged).
    The pop loop is the backlog control: a saturated worker stops popping
    and tickets wait in the queue, where the planner can see them.
    """

    def __init__(self, plane, instance_id: int, capacity_gate=None,
                 queue: str = PREFILL_QUEUE, poll: float = 0.2,
                 metrics=None):
        self.plane = plane
        self.instance_id = instance_id
        self.capacity_gate = capacity_gate
        self.queue = queue
        self.poll = poll
        self._task: Optional[asyncio.Task] = None
        self._stop = False
        #: last wall time a class-split (interactive/batch) ticket was
        #: popped — governs the adaptive blocking tail in _pop_best_class
        self._class_seen_at = 0.0
        self.claims = 0
        #: expired tickets popped and dropped — a rising rate means decode
        #: workers are giving up before this fleet can claim (undersized
        #: prefill fleet or too-tight deadlines); mirrored to
        #: ``dynamo_prefill_tickets_discarded_total`` when a registry is given
        self.discarded = 0
        self._discard_counter = (
            metrics.counter("prefill_tickets_discarded_total",
                            "expired prefill queue tickets discarded")
            if metrics is not None else None)

    async def start(self) -> "PrefillQueueWorker":
        self._task = asyncio.get_running_loop().create_task(self._loop())
        return self

    async def stop(self):
        self._stop = True
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass

    async def _pop_best_class(self) -> Optional[bytes]:
        """Best-class-first drain (docs/disagg.md): sweep interactive →
        legacy/standard → batch with near-nonblocking pops, then block on
        the legacy queue (the common case) so an idle worker is not
        spinning. The blocking tail ADAPTS: while class-split traffic has
        been seen recently the block is short (an interactive ticket waits
        at most ~1s behind a standard pop); a fleet that has only ever
        seen legacy/standard tickets blocks long, keeping idle-poll RPC
        volume against the control plane near the pre-QoS rate."""
        for i, q in enumerate(pop_order(self.queue)):
            item = await self.plane.queue_pop(q, timeout=0.02)
            if item is not None:
                if i != 1:  # a class-split (non-legacy) queue produced
                    self._class_seen_at = time.time()
                return item
        recent = time.time() - self._class_seen_at < 60.0
        return await self.plane.queue_pop(self.queue,
                                          timeout=1.0 if recent else 5.0)

    async def _loop(self):
        while not self._stop:
            try:
                if self.capacity_gate is not None and not self.capacity_gate():
                    await asyncio.sleep(self.poll)
                    continue
                item = await self._pop_best_class()
                if item is None:
                    continue
                ticket = msgpack.unpackb(item, raw=False)
                exp = ticket.get("expires_at")
                if exp is not None and exp < time.time():
                    # decode side already fell back; discard — but LOUDLY:
                    # silent drops hid fleet-undersizing from operators
                    self.discarded += 1
                    if self._discard_counter is not None:
                        self._discard_counter.inc()
                    logger.warning(
                        "discarding expired prefill ticket %s (%.1fs stale; "
                        "%d discarded total)", ticket.get("job_id", "?")[:16],
                        time.time() - exp, self.discarded)
                    continue
                await self.plane.publish(
                    f"{CLAIM_SUBJECT}.{ticket['job_id']}",
                    msgpack.packb({"instance_id": self.instance_id}))
                self.claims += 1
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("prefill queue worker loop error; retrying")
                await asyncio.sleep(1.0)


def engine_capacity_gate(engine, max_waiting: int = 0):
    """Default gate: take work only while the engine's waiting queue is at
    or below ``max_waiting`` (admission backlog = stop popping). Swapped
    sequences count as backlog too — they hold no device blocks but WILL
    reclaim capacity before new admissions, so claiming more prefill
    tickets while the swapped queue is populated only deepens the KV
    pressure that parked them."""

    def gate() -> bool:
        sched = engine.scheduler
        return (sched.num_waiting() + len(sched.swapped)) <= max_waiting

    return gate
