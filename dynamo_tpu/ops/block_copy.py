"""Paged-KV block gather/scatter (the reference CUDA kernel's TPU analog).

The reference ships one CUDA kernel — a dimension-aware strided block copy
used for KV transfer and (de)fragmentation (ref: lib/llm/src/kernels/
block_copy.cu:40-758). On TPU the same jobs are XLA dynamic gathers/scatters
over the flat paged cache: XLA already emits single-pass DMA programs for
these, so the kernels below are thin, jit-friendly contracts used by the
KVBM offload path (device→host staging) and disagg KV transfer:

  gather_blocks:  cache [L, slots, KV, hd] + ids [n] → [L, n, bs, KV, hd]
  scatter_blocks: writes such a bundle back into (possibly different) slots

A layout transpose between prefill-TP and decode-TP shardings is the
``reshard`` helper: gather → logical reshape → device_put under the target
sharding (XLA inserts the all-to-all).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def gather_blocks(cache: jax.Array, block_ids, *, block_size: int) -> jax.Array:
    """Pull whole blocks out of the flat paged cache.

    cache: [L, num_slots, KV, hd]; block_ids: [n] int32.
    Returns [L, n, block_size, KV, hd] (contiguous bundle, transfer-ready).
    """
    L, slots, KV, hd = cache.shape
    block_ids = jnp.asarray(block_ids, jnp.int32)
    paged = cache.reshape(L, slots // block_size, block_size, KV, hd)
    return jnp.take(paged, block_ids, axis=1)


import functools


@functools.partial(jax.jit, static_argnames=("block_size",), donate_argnums=(0,))
def _scatter(cache, block_ids, bundle, *, block_size):
    L, slots, KV, hd = cache.shape
    paged = cache.reshape(L, slots // block_size, block_size, KV, hd)
    return paged.at[:, block_ids].set(bundle).reshape(L, slots, KV, hd)


def scatter_blocks(cache: jax.Array, block_ids, bundle: jax.Array, *,
                   block_size: int) -> jax.Array:
    """Write a gathered bundle into blocks of the cache; returns new cache.

    Shapes as in gather_blocks. The flat cache is donated at the jit
    boundary (reshapes live inside it), so the write is in-place in HBM —
    no transient second cache.
    """
    return _scatter(cache, jnp.asarray(block_ids, jnp.int32),
                    bundle.astype(cache.dtype), block_size=block_size)


def reshard_bundle(bundle: jax.Array, sharding) -> jax.Array:
    """Re-lay a KV bundle onto a different sharding (prefill-TP ≠ decode-TP).

    XLA lowers the device_put to the needed collective (all-to-all /
    all-gather over ICI) — the TPU counterpart of the reference's
    layout-transpose copy between prefill and decode workers
    (ref: docs/architecture/disagg_serving.md:103).
    """
    return jax.device_put(bundle, sharding)
