"""Model registry: named architecture presets + HF config mapping.

The engine's forward pass (engine/model.py) natively covers the llama
decoder family — RoPE + RMSNorm + GQA paged attention, SwiGLU MLP — plus
token-choice MoE (Mixtral-style, experts shardable over "tp" = EP),
sliding-window attention (Mistral), and QKV bias (Qwen2). Presets below are
the shapes used by the reference's recipes (ref: recipes/llama-3-70b,
recipes/deepseek-r1, recipes/gpt-oss-120b) where the architecture is
supported; unsupported attention variants (DeepSeek MLA) are documented as
gaps rather than approximated silently.
"""

from __future__ import annotations

from dynamo_tpu.engine.config import ModelConfig


def mistral_7b() -> ModelConfig:
    return ModelConfig(
        vocab_size=32000, hidden_size=4096, intermediate_size=14336,
        num_layers=32, num_heads=32, num_kv_heads=8, rope_theta=10000.0,
        max_position_embeddings=32768, sliding_window=4096)


def qwen2_7b() -> ModelConfig:
    return ModelConfig(
        vocab_size=152064, hidden_size=3584, intermediate_size=18944,
        num_layers=28, num_heads=28, num_kv_heads=4, rope_theta=1000000.0,
        max_position_embeddings=32768, qkv_bias=True)


def mixtral_8x7b() -> ModelConfig:
    return ModelConfig(
        vocab_size=32000, hidden_size=4096, intermediate_size=14336,
        num_layers=32, num_heads=32, num_kv_heads=8, rope_theta=1000000.0,
        max_position_embeddings=32768, num_experts=8, num_experts_per_tok=2)


def moe_tiny() -> ModelConfig:
    """Small MoE for tests/benches of the EP path."""
    return ModelConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128, num_layers=2,
        num_heads=4, num_kv_heads=2, dtype="float32",
        num_experts=4, num_experts_per_tok=2, max_position_embeddings=512)


PRESETS = {
    "tiny": ModelConfig.tiny,
    "moe_tiny": moe_tiny,
    "llama3_1b": ModelConfig.llama3_1b,
    "llama3_8b": ModelConfig.llama3_8b,
    "llama3_70b": ModelConfig.llama3_70b,
    "mistral_7b": mistral_7b,
    "qwen2_7b": qwen2_7b,
    "mixtral_8x7b": mixtral_8x7b,
}

#: architectures the forward pass does NOT cover yet (round-1 gaps —
#: listed so callers fail loudly instead of serving wrong numerics)
UNSUPPORTED = {
    "DeepseekV2ForCausalLM": "MLA attention not implemented",
    "DeepseekV3ForCausalLM": "MLA attention not implemented",
}


def get_model_config(name: str) -> ModelConfig:
    if name in PRESETS:
        return PRESETS[name]()
    raise KeyError(f"unknown model preset '{name}' (have {sorted(PRESETS)})")


def from_hf_config(d: dict) -> ModelConfig:
    arch = (d.get("architectures") or [""])[0]
    if arch in UNSUPPORTED:
        raise NotImplementedError(f"{arch}: {UNSUPPORTED[arch]}")
    return ModelConfig.from_hf_config(d)
