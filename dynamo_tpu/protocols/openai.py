"""OpenAI-compatible API types: request validation + response/chunk builders.

Rebuild of the reference's OpenAI protocol layer (ref: lib/llm/src/protocols/
openai/, lib/async-openai fork). Requests/responses are handled as plain dicts
(the HTTP edge is JSON); this module centralizes validation, defaulting, and
the ``nvext`` extension block (ref: nvext.rs) that carries Dynamo-specific
per-request knobs (annotations, ignore_eos, backend_instance_id,
router config overrides).
"""

from __future__ import annotations

import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Optional

from dynamo_tpu.protocols import (
    OutputOptions,
    SamplingOptions,
    StopConditions,
)


class RequestError(ValueError):
    """400-level request validation error."""


def _as_stop_list(stop) -> Optional[list[str]]:
    if stop is None:
        return None
    if isinstance(stop, str):
        return [stop]
    if isinstance(stop, list) and all(isinstance(s, str) for s in stop):
        return stop[:16]
    raise RequestError("'stop' must be a string or list of strings")


@dataclass
class ParsedRequest:
    """Normalized view of a chat-completion or completion request."""

    model: str
    messages: Optional[list[dict]] = None  # chat
    prompt: Optional[Any] = None  # completions: str | list[str] | list[int]
    stream: bool = False
    stream_usage: bool = False
    n: int = 1
    sampling: SamplingOptions = field(default_factory=SamplingOptions)
    stop: StopConditions = field(default_factory=StopConditions)
    output: OutputOptions = field(default_factory=OutputOptions)
    tools: Optional[list[dict]] = None
    tool_choice: Optional[Any] = None
    response_format: Optional[dict] = None
    annotations: list[str] = field(default_factory=list)
    backend_instance_id: Optional[int] = None
    router_config_override: Optional[dict] = None
    #: responses API: continue the server-held conversation that produced
    #: this id (docs/sessions.md) — the input is the TURN DELTA only
    previous_response_id: Optional[str] = None
    raw: dict = field(default_factory=dict)


def parse_chat_request(body: dict) -> ParsedRequest:
    if not isinstance(body, dict):
        raise RequestError("request body must be a JSON object")
    model = body.get("model")
    if not model or not isinstance(model, str):
        raise RequestError("'model' is required")
    messages = body.get("messages")
    if not isinstance(messages, list) or not messages:
        raise RequestError("'messages' must be a non-empty array")
    for m in messages:
        if not isinstance(m, dict) or "role" not in m:
            raise RequestError("each message must be an object with a 'role'")
    return _parse_common(body, ParsedRequest(model=model, messages=messages, raw=body))


def parse_completion_request(body: dict) -> ParsedRequest:
    if not isinstance(body, dict):
        raise RequestError("request body must be a JSON object")
    model = body.get("model")
    if not model or not isinstance(model, str):
        raise RequestError("'model' is required")
    prompt = body.get("prompt")
    if prompt is None:
        raise RequestError("'prompt' is required")
    return _parse_common(body, ParsedRequest(model=model, prompt=prompt, raw=body))


def _parse_common(body: dict, req: ParsedRequest) -> ParsedRequest:
    req.stream = bool(body.get("stream", False))
    so = body.get("stream_options") or {}
    req.stream_usage = bool(so.get("include_usage", False))
    req.n = int(body.get("n") or 1)
    if req.n < 1 or req.n > 16:
        raise RequestError("'n' must be in [1, 16]")

    temperature = body.get("temperature")
    if temperature is not None and not (0.0 <= float(temperature) <= 2.0):
        raise RequestError("'temperature' must be in [0, 2]")
    top_p = body.get("top_p")
    if top_p is not None and not (0.0 < float(top_p) <= 1.0):
        raise RequestError("'top_p' must be in (0, 1]")

    logit_bias = body.get("logit_bias")
    if logit_bias is not None:
        if not isinstance(logit_bias, dict):
            raise RequestError("'logit_bias' must be an object")
        try:  # keys stay STRINGS end-to-end (the wire codec rejects int
            # map keys); the engine converts at application time
            logit_bias = {str(int(k)): float(v)
                          for k, v in logit_bias.items()}
        except (TypeError, ValueError):
            raise RequestError(
                "'logit_bias' keys must be token ids, values numbers")
        if any(not -100.0 <= v <= 100.0 for v in logit_bias.values()):
            raise RequestError("'logit_bias' values must be in [-100, 100]")
        if len(logit_bias) > 300:
            raise RequestError("'logit_bias' supports at most 300 tokens")

    nvext = body.get("nvext") or {}
    # guided decoding: accepted at top level AND in nvext (ref:
    # common_ext.rs CommonExt is flattened into both request types);
    # nvext wins per field, and exactly ONE option may be set
    guided = {}
    for key in ("json", "regex", "choice", "grammar"):
        v = nvext.get(f"guided_{key}", body.get(f"guided_{key}"))
        if v is not None:
            guided[key] = v
    if len(guided) > 1:
        raise RequestError(
            "only one of guided_json / guided_regex / guided_choice / "
            "guided_grammar may be set")
    # OpenAI response_format maps onto the same constraint machinery;
    # explicit guided_* options win when both are present
    rf = body.get("response_format")
    if not guided and isinstance(rf, dict):
        rft = rf.get("type")
        if rft == "json_schema":
            js = rf.get("json_schema")
            schema = js.get("schema") if isinstance(js, dict) else None
            if schema is None:
                raise RequestError(
                    "response_format json_schema requires "
                    "json_schema.schema")
            guided["json"] = schema
        elif rft == "json_object":
            guided["json"] = {"type": "object"}  # any (depth-bounded) object
        elif rft not in (None, "text"):
            raise RequestError(f"unsupported response_format type: {rft!r}")
    if "choice" in guided and (not isinstance(guided["choice"], list)
                               or not guided["choice"]):
        raise RequestError("'guided_choice' must be a non-empty list")
    if guided:
        from dynamo_tpu.llm.guided import validate_guided
        try:
            validate_guided(guided)  # 400 here, not a worker-side error
        except ValueError as e:
            raise RequestError(str(e))
        except Exception as e:  # malformed schema json etc.
            raise RequestError(f"invalid guided-decoding options: {e}")
    req.sampling = SamplingOptions(
        logit_bias=logit_bias,
        guided=guided or None,
        n=req.n,
        temperature=None if temperature is None else float(temperature),
        top_p=None if top_p is None else float(top_p),
        top_k=body.get("top_k") or nvext.get("top_k"),
        seed=body.get("seed"),
        presence_penalty=body.get("presence_penalty"),
        frequency_penalty=body.get("frequency_penalty"),
        repetition_penalty=nvext.get("repetition_penalty"),
    )
    max_tokens = body.get("max_completion_tokens", body.get("max_tokens"))
    if max_tokens is not None and int(max_tokens) < 1:
        raise RequestError("'max_tokens' must be >= 1")
    req.stop = StopConditions(
        max_tokens=None if max_tokens is None else int(max_tokens),
        stop=_as_stop_list(body.get("stop")),
        min_tokens=nvext.get("min_tokens"),
        # vLLM-style top-level extension accepted too; nvext wins when both set
        ignore_eos=nvext.get("ignore_eos", body.get("ignore_eos")),
    )
    logprobs = body.get("logprobs")
    top_logprobs = body.get("top_logprobs")
    if top_logprobs is not None and not (
            isinstance(top_logprobs, int) and 0 <= top_logprobs <= 20):
        raise RequestError("'top_logprobs' must be an integer in [0, 20]")
    if isinstance(logprobs, int) and not isinstance(logprobs, bool) \
            and not 0 <= logprobs <= 20:
        raise RequestError("'logprobs' must be in [0, 20]")
    # chat: logprobs=true (+optional top_logprobs N); completions:
    # logprobs=N. Stored as the requested alternatives count (None = off;
    # 0 = selected-token logprobs only).
    if isinstance(logprobs, bool):
        # OpenAI: logprobs=true alone returns ONLY the selected token's
        # logprob (no alternatives list); top_logprobs adds N alternatives
        lp_count = (top_logprobs if top_logprobs is not None else 0) \
            if logprobs else None
    else:
        lp_count = logprobs if isinstance(logprobs, int) else None
    req.output = OutputOptions(
        logprobs=lp_count,
        echo=bool(body.get("echo", False)),
    )
    req.tools = _validate_tools(body.get("tools"))
    req.tool_choice = _validate_tool_choice(body.get("tool_choice"),
                                            req.tools, guided)
    req.response_format = body.get("response_format")
    req.annotations = list(nvext.get("annotations") or [])
    req.backend_instance_id = nvext.get("backend_instance_id")
    req.router_config_override = nvext.get("router_config_override")
    return req


def _validate_tools(tools) -> Optional[list[dict]]:
    if tools is None:
        return None
    if not isinstance(tools, list) or not all(
            isinstance(t, dict) for t in tools):
        raise RequestError("'tools' must be an array of tool objects")
    for t in tools:
        fn = t.get("function")
        if (t.get("type") not in (None, "function")
                or not isinstance(fn, dict)
                or not isinstance(fn.get("name"), str) or not fn["name"]):
            raise RequestError(
                "each tool must be {'type': 'function', 'function': "
                "{'name': ...}}")
    return tools


def _validate_tool_choice(tc, tools, guided):
    """Shape-validate ``tool_choice`` at the API boundary so enforcement
    failures are 400s here, not worker-side errors. The PIPELINE enforces
    it (docs/structured.md): "none" strips tools from the template,
    "required"/named compiles a constraint grammar — it is never silently
    ignored."""
    if tc is None:
        return None
    named = (isinstance(tc, dict) and tc.get("type") in (None, "function")
             and isinstance(tc.get("function"), dict)
             and isinstance(tc["function"].get("name"), str))
    if tc not in ("auto", "none", "required") and not named:
        raise RequestError(
            "'tool_choice' must be 'auto', 'none', 'required', or "
            "{'type': 'function', 'function': {'name': ...}}")
    if tc in ("required",) or named:
        if not tools:
            raise RequestError(f"tool_choice {tc!r} requires 'tools'")
        if guided:
            # one sampling constraint per request: an explicit guided_* /
            # response_format schema cannot coexist with tool enforcement
            raise RequestError(
                "tool_choice 'required'/named cannot be combined with "
                "guided_* options or response_format constraints")
        if named:
            names = {(t.get("function") or {}).get("name") for t in tools}
            if tc["function"]["name"] not in names:
                raise RequestError(
                    f"tool_choice names unknown tool "
                    f"{tc['function']['name']!r}")
    return tc


# ---------------------------------------------------------------------------
# Response builders
# ---------------------------------------------------------------------------


def gen_request_id(prefix: str = "chatcmpl") -> str:
    return f"{prefix}-{uuid.uuid4().hex}"


def usage_block(prompt_tokens: int, completion_tokens: int) -> dict:
    return {
        "prompt_tokens": prompt_tokens,
        "completion_tokens": completion_tokens,
        "total_tokens": prompt_tokens + completion_tokens,
    }


def chat_chunk(
    request_id: str,
    model: str,
    created: int,
    *,
    index: int = 0,
    role: Optional[str] = None,
    content: Optional[str] = None,
    tool_calls: Optional[list] = None,
    reasoning_content: Optional[str] = None,
    finish_reason: Optional[str] = None,
    usage: Optional[dict] = None,
    logprobs: Optional[dict] = None,
) -> dict:
    delta: dict = {}
    if role is not None:
        delta["role"] = role
    if content is not None:
        delta["content"] = content
    if tool_calls is not None:
        delta["tool_calls"] = tool_calls
    if reasoning_content is not None:
        delta["reasoning_content"] = reasoning_content
    chunk = {
        "id": request_id,
        "object": "chat.completion.chunk",
        "created": created,
        "model": model,
        "choices": [{"index": index, "delta": delta,
                     "logprobs": logprobs,
                     "finish_reason": finish_reason}],
    }
    if usage is not None:
        chunk["usage"] = usage
    return chunk


def chat_response(
    request_id: str,
    model: str,
    created: int,
    choices: list[dict],
    usage: dict,
) -> dict:
    return {
        "id": request_id,
        "object": "chat.completion",
        "created": created,
        "model": model,
        "choices": choices,
        "usage": usage,
    }


def chat_choice(
    index: int,
    content: str,
    finish_reason: Optional[str],
    tool_calls: Optional[list] = None,
    reasoning_content: Optional[str] = None,
) -> dict:
    message: dict = {"role": "assistant", "content": content}
    if tool_calls:
        message["tool_calls"] = tool_calls
        message["content"] = content or None
    if reasoning_content:
        message["reasoning_content"] = reasoning_content
    return {"index": index, "message": message, "finish_reason": finish_reason}


def completion_chunk(
    request_id: str,
    model: str,
    created: int,
    *,
    index: int = 0,
    text: str = "",
    finish_reason: Optional[str] = None,
    usage: Optional[dict] = None,
    logprobs: Optional[dict] = None,
) -> dict:
    chunk = {
        "id": request_id,
        "object": "text_completion",
        "created": created,
        "model": model,
        "choices": [{"index": index, "text": text, "finish_reason": finish_reason, "logprobs": logprobs}],
    }
    if usage is not None:
        chunk["usage"] = usage
    return chunk


def completion_response(
    request_id: str, model: str, created: int, choices: list[dict], usage: dict
) -> dict:
    return {
        "id": request_id,
        "object": "text_completion",
        "created": created,
        "model": model,
        "choices": choices,
        "usage": usage,
    }


def model_entry(model_id: str, created: Optional[int] = None) -> dict:
    return {
        "id": model_id,
        "object": "model",
        "created": created or int(time.time()),
        "owned_by": "dynamo-tpu",
    }


def error_body(message: str, err_type: str = "invalid_request_error", code: int = 400) -> dict:
    return {"error": {"message": message, "type": err_type, "code": code}}


# -- Responses API (ref: lib/llm/src/http/service/openai.rs:1005) ------------


def parse_responses_request(body: dict) -> ParsedRequest:
    """Parse a /v1/responses body by lowering it onto the chat pipeline.

    The responses API is a superset of chat; the serving semantics here map
    ``input`` (string or message-item list) + ``instructions`` onto chat
    messages and reuse the chat operator chain end-to-end — same as the
    reference, whose responses route drives the chat engines.
    """
    if not isinstance(body, dict):
        raise RequestError("request body must be a JSON object")
    model = body.get("model")
    if not model or not isinstance(model, str):
        raise RequestError("'model' is required")
    raw = body.get("input")
    messages: list[dict] = []
    if instructions := body.get("instructions"):
        messages.append({"role": "system", "content": str(instructions)})
    if isinstance(raw, str):
        messages.append({"role": "user", "content": raw})
    elif isinstance(raw, list) and raw:
        for item in raw:
            if not isinstance(item, dict) or "role" not in item:
                raise RequestError(
                    "each input item must be an object with a 'role'")
            content = item.get("content")
            if isinstance(content, list):  # content parts → concatenated text
                texts = []
                for part in content:
                    if isinstance(part, dict) and "text" in part:
                        texts.append(str(part["text"]))
                    else:
                        raise RequestError(
                            "input content parts must carry 'text' "
                            "(input_text/output_text)")
                content = "".join(texts)
            messages.append({"role": item["role"], "content": content or ""})
    else:
        raise RequestError("'input' must be a string or a non-empty array")
    chat_body = dict(body)
    chat_body["messages"] = messages
    if "max_output_tokens" in body:
        chat_body["max_tokens"] = body["max_output_tokens"]
    req = parse_chat_request(chat_body)
    # session continuation (docs/sessions.md): the id is resolved by the
    # frontend's session registry — parsing only validates the shape. The
    # messages above are then the DELTA the registry prepends history to.
    prev = body.get("previous_response_id")
    if prev is not None:
        if not isinstance(prev, str) or not prev:
            raise RequestError(
                "'previous_response_id' must be a non-empty string")
        req.previous_response_id = prev
    return req


def response_msg_id(request_id: str) -> str:
    """Output-item id for a response id ('resp-<hex>' → 'msg-<hex>')."""
    return "msg-" + request_id.split("-", 1)[-1]


def response_object(request_id: str, model: str, created: int, text: str,
                    status: str, usage: Optional[dict] = None) -> dict:
    u = usage or {}
    return {
        "id": request_id,
        "object": "response",
        "created_at": created,
        "status": status,
        "model": model,
        "output": [{
            "type": "message",
            "id": response_msg_id(request_id),
            "status": status,
            "role": "assistant",
            "content": [{"type": "output_text", "text": text,
                         "annotations": []}],
        }],
        "usage": {
            "input_tokens": u.get("prompt_tokens", 0),
            "output_tokens": u.get("completion_tokens", 0),
            "total_tokens": u.get("total_tokens", 0),
        },
    }
