"""Response-stream recording: timestamps on a live stream, latency analysis.

Rebuild of the reference's perf recording framework (ref:
lib/llm/src/perf.rs:32-336 — TimestampedResponse / RecordedStream /
RecordingStream with Sink vs Passthrough modes and the record_stream
constructors): wrap any async response stream so every item is
timestamped as it leaves the engine, then analyze the recording —
TTFT, inter-token gaps, duration, token rate — or aggregate many
recordings into the percentile summary a load harness needs.

The recorder is transport-agnostic: it wraps the async iterators the
pipeline and frontend already pass around (engine outputs, SSE deltas,
router streams), adds no buffering in passthrough mode, and defers all
analysis to after the stream closes.
"""

from __future__ import annotations

import json
import math
import time
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Callable, Iterable, Optional


@dataclass
class TimestampedResponse:
    """One stream item + when it arrived (ref: perf.rs:32 — sequence
    number and elapsed-since-start, not wall clock, so recordings are
    comparable across hosts)."""

    data: Any
    sequence: int
    t_rel: float  # seconds since the stream was wrapped


@dataclass
class RecordedStream:
    """A finished stream's timeline (ref: perf.rs:84-135)."""

    responses: list[TimestampedResponse] = field(default_factory=list)
    start_time: float = 0.0          # wall clock, informational
    total_duration: float = 0.0      # first wrap → stream close
    request_id: Optional[str] = None

    @property
    def response_count(self) -> int:
        return len(self.responses)

    # -- latency views ----------------------------------------------------
    @property
    def ttft(self) -> Optional[float]:
        """Time to the FIRST response (the stream-level TTFT analog)."""
        return self.responses[0].t_rel if self.responses else None

    @property
    def inter_arrival_gaps(self) -> list[float]:
        """Gaps between consecutive responses (the ITL analog when one
        response ≈ one token)."""
        ts = [r.t_rel for r in self.responses]
        return [b - a for a, b in zip(ts, ts[1:])]

    @property
    def responses_per_s(self) -> float:
        if self.total_duration <= 0 or not self.responses:
            return 0.0
        return len(self.responses) / self.total_duration

    # -- serialization (offline analysis / recorder integration) ----------
    def to_obj(self, data_fn: Callable[[Any], Any] = lambda d: d) -> dict:
        return {
            "request_id": self.request_id,
            "start_time": self.start_time,
            "total_duration": self.total_duration,
            "responses": [
                {"seq": r.sequence, "t": r.t_rel, "data": data_fn(r.data)}
                for r in self.responses
            ],
        }

    @staticmethod
    def from_obj(d: dict) -> "RecordedStream":
        return RecordedStream(
            responses=[TimestampedResponse(r.get("data"), r["seq"], r["t"])
                       for r in d.get("responses", [])],
            start_time=d.get("start_time", 0.0),
            total_duration=d.get("total_duration", 0.0),
            request_id=d.get("request_id"),
        )


class StreamRecorder:
    """Wraps an async iterator; the recording fills in as items flow.

    ``passthrough`` (default) re-yields every item to the caller —
    recording is invisible to the consumer (ref RecordingMode::
    Passthrough). ``sink()`` consumes the stream internally and returns
    the finished recording (ref RecordingMode::Sink)."""

    def __init__(self, stream: AsyncIterator, request_id: Optional[str] = None,
                 keep_data: bool = True):
        self._stream = stream
        self.recording = RecordedStream(start_time=time.time(),
                                        request_id=request_id)
        self._keep_data = keep_data
        self._t0 = time.perf_counter()

    async def __aiter__(self):
        seq = 0
        try:
            async for item in self._stream:
                self.recording.responses.append(TimestampedResponse(
                    item if self._keep_data else None, seq,
                    time.perf_counter() - self._t0))
                seq += 1
                yield item
        finally:
            self.recording.total_duration = time.perf_counter() - self._t0

    async def sink(self) -> RecordedStream:
        async for _ in self:
            pass
        return self.recording


def record_stream(stream: AsyncIterator, request_id: Optional[str] = None,
                  keep_data: bool = True) -> StreamRecorder:
    """Passthrough-record ``stream`` (ref: perf.rs:272 record_stream).

    Use ``async for item in recorder: ...`` then read
    ``recorder.recording``; or ``await recorder.sink()`` to consume."""
    return StreamRecorder(stream, request_id=request_id, keep_data=keep_data)


# -------------------------------------------------------------- aggregation

def _percentile(sorted_xs: list[float], q: float) -> float:
    if not sorted_xs:
        return math.nan
    idx = min(len(sorted_xs) - 1, max(0, int(round(q * (len(sorted_xs) - 1)))))
    return sorted_xs[idx]


@dataclass
class LatencySummary:
    """Fleet/run-level percentile table over many recordings — the
    genai-perf-style summary (ref methodology:
    docs/benchmarks/benchmarking.md:33)."""

    count: int
    ttft_p50: float
    ttft_p95: float
    ttft_p99: float
    gap_p50: float
    gap_p95: float
    duration_p50: float
    duration_p95: float
    responses_per_s_mean: float

    def to_obj(self) -> dict:
        return {k: (round(v, 6) if isinstance(v, float) else v)
                for k, v in self.__dict__.items()}


def summarize(recordings: Iterable[RecordedStream]) -> LatencySummary:
    recs = [r for r in recordings if r.response_count]
    ttfts = sorted(r.ttft for r in recs)
    gaps = sorted(g for r in recs for g in r.inter_arrival_gaps)
    durs = sorted(r.total_duration for r in recs)
    rates = [r.responses_per_s for r in recs]
    return LatencySummary(
        count=len(recs),
        ttft_p50=_percentile(ttfts, 0.50),
        ttft_p95=_percentile(ttfts, 0.95),
        ttft_p99=_percentile(ttfts, 0.99),
        gap_p50=_percentile(gaps, 0.50),
        gap_p95=_percentile(gaps, 0.95),
        duration_p50=_percentile(durs, 0.50),
        duration_p95=_percentile(durs, 0.95),
        responses_per_s_mean=(sum(rates) / len(rates)) if rates else 0.0,
    )


def dump_jsonl(recordings: Iterable[RecordedStream], path: str,
               data_fn: Callable[[Any], Any] = lambda d: None) -> None:
    """One recording per line; ``data_fn`` controls payload serialization
    (default drops payloads — timelines are usually what analysis needs)."""
    with open(path, "w") as f:
        for rec in recordings:
            f.write(json.dumps(rec.to_obj(data_fn)) + "\n")


def load_jsonl(path: str) -> list[RecordedStream]:
    with open(path) as f:
        return [RecordedStream.from_obj(json.loads(line))
                for line in f if line.strip()]
