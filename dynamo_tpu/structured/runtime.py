"""Engine-side structured-decoding runtime: the device FSM arena.

One worker serves many constraints concurrently, and a decode batch may
mix rows under DIFFERENT constraints. Per-constraint device tables would
change the sampling dispatch's operand shapes per batch composition and
re-trace the jit; instead every compiled machine is uploaded into ONE
pair of fixed-shape arena arrays:

  mask_arena  uint32 [S_cap, ceil(V/32)]
  next_arena  int32  [S_cap, V]

Row 0 is the global FREE state: all tokens allowed, self-loop — an
unconstrained row carries state 0 and the fused mask is an exact identity
for it. A compiled machine occupies a contiguous segment at ``offset``;
its local DONE row 0 lands at ``offset`` and every local transition
shifts by ``offset`` uniformly, so a row's per-step state is one int32
riding the sampled-state arrays (and the pipelined loop's device-to-
device feed) with a single static-shape gather per step.

Segments are refcounted by live sequences and LRU-evicted at zero refs;
an arena too full for a new machine falls back to the host oracle for
that request (never an error). ``S_cap`` derives from the
``DYN_STRUCTURED_TABLE_MB`` byte budget (default 64 MiB) — the next
table costs 4·V bytes per state, so huge-vocab models get a small arena
and big schemas fall back, exactly the budget rule docs/structured.md
describes.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Optional

import numpy as np

from dynamo_tpu.structured.compiler import CompiledFsm

logger = logging.getLogger("dynamo.structured")

#: arena row ceiling regardless of budget (tiny vocabs would otherwise
#: allocate absurdly tall tables)
MAX_ARENA_STATES = 4096
#: below this many rows the arena is useless (a trivial choice constraint
#: needs a handful of states; give up and run host-side)
MIN_ARENA_STATES = 32


def env_enabled() -> bool:
    return os.environ.get("DYN_STRUCTURED", "1").lower() not in (
        "0", "false", "off", "no")


def table_budget_bytes(override_mb: Optional[float] = None) -> int:
    if override_mb is None:
        raw = os.environ.get("DYN_STRUCTURED_TABLE_MB", "")
        if raw:
            try:
                override_mb = float(raw)
            except ValueError:
                raise ValueError(
                    f"bad DYN_STRUCTURED_TABLE_MB={raw!r}") from None
        else:
            override_mb = 64.0
    return int(override_mb * (1 << 20))


def arena_states(vocab_size: int, budget_bytes: int) -> int:
    """States the byte budget buys at this logits width (0 = disabled)."""
    row_bytes = 4 * vocab_size + 4 * ((vocab_size + 31) // 32)
    cap = min(MAX_ARENA_STATES, budget_bytes // max(1, row_bytes))
    return int(cap) if cap >= MIN_ARENA_STATES else 0


class FsmSegment:
    __slots__ = ("offset", "size", "key", "fsm", "refs", "last_use")

    def __init__(self, offset: int, size: int, key, fsm: CompiledFsm):
        self.offset = offset
        self.size = size
        self.key = key
        self.fsm = fsm
        self.refs = 0
        self.last_use = 0.0


class FsmCursor:
    """Per-sequence constraint cursor over a compiled machine — the
    drop-in replacement for ``GuidedState`` on the device path. Same
    interface (``done``/``exhausted``/``eos_ids``/``advance``/
    ``allowed_token_ids``) but ``advance`` is one numpy table lookup, so
    every path (pipelined commit, fused-burst delivery, spec verify) can
    afford it inline. ``state`` is the GLOBAL arena index the device
    kernels gather with.
    """

    __slots__ = ("seg", "runtime", "state", "done", "exhausted", "eos_ids",
                 "_eos_set", "_released")

    #: engines key their fast-path eligibility on this (duck-typed so the
    #: scheduler never imports structured)
    device = True

    def __init__(self, seg: FsmSegment, runtime: "StructuredRuntime"):
        self.seg = seg
        self.runtime = runtime
        self.state = seg.offset + seg.fsm.start
        self.done = False
        self.exhausted = False
        self.eos_ids = list(seg.fsm.eos_ids)
        self._eos_set = set(self.eos_ids)
        self._released = False

    @property
    def _local(self) -> int:
        return self.state - self.seg.offset

    def advance(self, token_id: int) -> None:
        if self.done:
            return
        t = int(token_id)
        if t in self._eos_set:
            self.done = True
            return
        fsm = self.seg.fsm
        nxt = int(fsm.next[self._local, t]) if 0 <= t < fsm.V else 0
        if nxt == 0:
            # off-mask token (shouldn't happen when masked) or constraint
            # completed into DONE via an EOS-mapped transition
            self.done = True
            return
        self.state = self.seg.offset + nxt
        if fsm.exhausted[nxt]:
            self.exhausted = True

    def allowed_token_ids(self, max_id: Optional[int] = None) -> list[int]:
        """Host-side unpack of the current mask row (multi-host fallback
        sampling and tests; the device path never calls this)."""
        return self.seg.fsm.allowed_ids(self._local if not self.done else 0,
                                        max_id)

    def release(self) -> None:
        """Drop this sequence's arena reference (scheduler.finish)."""
        if not self._released:
            self._released = True
            self.runtime.release(self.seg)


class StructuredRuntime:
    """Per-engine arena of compiled constraint tables."""

    def __init__(self, vocab_size: int, capacity: int):
        self.V = vocab_size
        self.W32 = (vocab_size + 31) // 32
        self.cap = capacity
        self._mask_np = np.zeros((capacity, self.W32), np.uint32)
        self._mask_np[0] = np.uint32(0xFFFFFFFF)  # FREE: all allowed
        self._next_np = np.zeros((capacity, vocab_size), np.int32)  # FREE: 0
        self._segments: dict = {}     # key -> FsmSegment
        self._lock = threading.Lock()
        self._dirty = True
        self._mask_dev = None
        self._next_dev = None
        self._clock = 0
        #: telemetry: admissions that landed on the device path vs fell
        #: back to the host oracle (budget/arena-full/min_tokens/multihost)
        self.rows_device = 0
        self.rows_host = 0
        self.evictions = 0

    # ---------------------------------------------------------- allocation

    def _gaps(self):
        """Free extents as (offset, size), FREE row 0 excluded."""
        used = sorted((s.offset, s.size) for s in self._segments.values())
        gaps, cur = [], 1
        for off, size in used:
            if off > cur:
                gaps.append((cur, off - cur))
            cur = off + size
        if cur < self.cap:
            gaps.append((cur, self.cap - cur))
        return gaps

    def _try_place(self, size: int) -> Optional[int]:
        for off, gap in self._gaps():
            if gap >= size:
                return off
        return None

    def acquire(self, key, fsm: CompiledFsm) -> Optional[FsmSegment]:
        """Place (or ref) a compiled machine; None = doesn't fit even
        after evicting every idle segment (host-oracle fallback)."""
        with self._lock:
            self._clock += 1
            seg = self._segments.get(key)
            if seg is not None:
                seg.refs += 1
                seg.last_use = self._clock
                return seg
            if fsm.n_states + 1 > self.cap:
                return None
            off = self._try_place(fsm.n_states)
            while off is None:
                idle = [s for s in self._segments.values() if s.refs == 0]
                if not idle:
                    return None
                victim = min(idle, key=lambda s: s.last_use)
                del self._segments[victim.key]
                self.evictions += 1
                off = self._try_place(fsm.n_states)
            self._mask_np[off:off + fsm.n_states] = fsm.mask
            # uniform shift: local DONE 0 lands at the segment's own row,
            # so global = local + offset holds for every entry
            self._next_np[off:off + fsm.n_states] = (
                fsm.next + np.int32(off))
            seg = FsmSegment(off, fsm.n_states, key, fsm)
            seg.refs = 1
            seg.last_use = self._clock
            self._segments[key] = seg
            self._dirty = True
            return seg

    def release(self, seg: FsmSegment) -> None:
        with self._lock:
            seg.refs = max(0, seg.refs - 1)
            seg.last_use = self._clock

    # ------------------------------------------------------------- device

    def device_tables(self):
        """(mask_arena, next_arena) as device arrays; re-uploaded only
        when a segment changed since the last dispatch."""
        import jax.numpy as jnp

        with self._lock:
            if self._dirty or self._mask_dev is None:
                self._mask_dev = jnp.asarray(self._mask_np)
                self._next_dev = jnp.asarray(self._next_np)
                self._dirty = False
            return self._mask_dev, self._next_dev

    def stats(self) -> dict:
        with self._lock:
            return {
                "segments": len(self._segments),
                "states_used": sum(s.size for s in self._segments.values()),
                "states_cap": self.cap,
                "rows_device": self.rows_device,
                "rows_host": self.rows_host,
                "evictions": self.evictions,
            }
