"""Distributed runtime: control plane, component model, streaming data plane.

TPU-native rebuild of the reference's ``lib/runtime`` crate (SURVEY.md §2.1).
The reference composes etcd (discovery/leases) + NATS (request plane/events) +
direct TCP (response streams). This runtime keeps the same *semantics* behind a
single self-contained control-plane service (``dynctl``) so a TPU-VM pod needs
no external infrastructure, while the token hot path still flows over direct
worker→requester TCP streams exactly like the reference's response plane
(ref: lib/runtime/src/pipeline/network/tcp/server.rs:62).
"""

from dynamo_tpu.runtime.control_plane import (
    ControlPlane,
    LocalControlPlane,
    NoRespondersError,
    RemoteControlPlane,
    ControlPlaneServer,
)
from dynamo_tpu.runtime.runtime import DistributedRuntime
from dynamo_tpu.runtime.component import Component, Endpoint, Namespace, Client, Instance
from dynamo_tpu.runtime.context import Context, StreamError

__all__ = [
    "ControlPlane",
    "LocalControlPlane",
    "RemoteControlPlane",
    "ControlPlaneServer",
    "NoRespondersError",
    "DistributedRuntime",
    "Namespace",
    "Component",
    "Endpoint",
    "Client",
    "Instance",
    "Context",
    "StreamError",
]
