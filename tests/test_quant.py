"""On-device quantized serving: weights resident int8/int4 in HBM with
dequant riding the matmul (engine/quant.py).

Ref capability: the reference's flagship recipes serve quantized
checkpoints (FP8 70B: recipes/llama-3-70b/vllm/disagg-single-node/
deploy.yaml:21-86; MXFP4 gpt-oss: recipes/gpt-oss-120b/trtllm/agg/).
"""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine import model as M
from dynamo_tpu.engine import quant as Q
from dynamo_tpu.engine.config import EngineArgs, ModelConfig
from dynamo_tpu.engine.engine import AsyncJaxEngine
from dynamo_tpu.protocols import (
    PreprocessedRequest, SamplingOptions, StopConditions,
)

pytestmark = pytest.mark.anyio


def test_quantize_roundtrip_per_channel():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.standard_normal((64, 48)), jnp.float32)
    qt = Q.quantize(w, bits=8)
    assert qt["q"].dtype == jnp.int8
    assert qt["s"].shape == (1, 48)
    back = Q.dequantize(qt)
    # 8-bit symmetric round-trip: ~qstep/2 of the channel max
    err = np.abs(np.asarray(back) - np.asarray(w))
    ceil = np.max(np.abs(np.asarray(w)), axis=0) / 127
    assert (err <= ceil[None, :] * 0.51).all()


def test_quantize_grouped_and_int4():
    rng = np.random.default_rng(1)
    w = jnp.asarray(rng.standard_normal((64, 32)), jnp.float32)
    g8 = Q.quantize(w, bits=8, group=16)
    assert g8["s"].shape == (4, 32)
    assert np.abs(np.asarray(Q.dequantize(g8)) - np.asarray(w)).max() < 0.05
    g4 = Q.quantize(w, bits=4, group=16)
    assert g4["q"].dtype == jnp.int4
    # 4-bit: coarse but bounded by group-max/7
    err = np.abs(np.asarray(Q.dequantize(g4)) - np.asarray(w))
    assert err.max() < np.abs(np.asarray(w)).max() / 7 * 0.51 + 1e-6


def test_qmm_matches_dequant():
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((5, 64)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((64, 48)), jnp.float32)
    for kw in (dict(bits=8), dict(bits=8, group=16), dict(bits=4, group=16)):
        qt = Q.quantize(w, **kw)
        np.testing.assert_allclose(np.asarray(Q.qmm(x, qt)),
                                   np.asarray(x @ Q.dequantize(qt)),
                                   rtol=2e-5, atol=2e-5)
    # stacked-layer shape [n, I, O] (scan slices feed qmm per layer)
    ws = jnp.asarray(rng.standard_normal((3, 64, 48)), jnp.float32)
    qt = Q.quantize(ws, bits=8)
    assert qt["s"].shape == (3, 1, 48)
    np.testing.assert_allclose(
        np.asarray(Q.qmm(x, {"q": qt["q"][1], "s": qt["s"][1]})),
        np.asarray(x @ Q.dequantize(qt)[1]), rtol=2e-5, atol=2e-5)


def test_affine_zero_point():
    """GGUF K-quants are affine (w = s·q − z): the z path must dequantize
    exactly."""
    rng = np.random.default_rng(3)
    q = rng.integers(0, 15, (32, 8)).astype(np.float32)
    s = rng.uniform(0.01, 0.1, (2, 8)).astype(np.float32)
    z = rng.uniform(0, 0.5, (2, 8)).astype(np.float32)
    qt = {"q": jnp.asarray(q, jnp.int8), "s": jnp.asarray(s),
          "z": jnp.asarray(z)}
    want = q * np.repeat(s, 16, axis=0) - np.repeat(z, 16, axis=0)
    np.testing.assert_allclose(np.asarray(Q.dequantize(qt)), want, rtol=1e-6)
    x = jnp.asarray(rng.standard_normal((4, 32)), jnp.float32)
    np.testing.assert_allclose(np.asarray(Q.qmm(x, qt)),
                               np.asarray(x) @ want, rtol=1e-4, atol=1e-4)


def test_spec_parsing():
    assert Q.parse_spec("int8") == (8, None)
    assert Q.parse_spec("int8-g128") == (8, 128)
    assert Q.parse_spec("int4-g32") == (4, 32)
    with pytest.raises(ValueError):
        Q.parse_spec("int4")  # groups required at 4 bits
    with pytest.raises(ValueError):
        Q.parse_spec("fp8")


@pytest.mark.parametrize("spec", ["int8", "int8-g16"])
def test_forward_parity_quantized(spec):
    """Quantized forward ≈ forward against the host-dequantized weights —
    the dequant-in-matmul path must introduce NO error beyond quantization
    itself (compared exactly, not loosely)."""
    cfg = ModelConfig.tiny()
    params = M.init_params(cfg, jax.random.key(0), dtype=jnp.float32)
    qparams = Q.quantize_params(jax.tree.map(np.asarray, params), spec)
    deq = {k: ({kk: (Q.dequantize(vv, jnp.float32) if Q.is_qtensor(vv) else vv)
                for kk, vv in v.items()} if isinstance(v, dict) else
               (Q.dequantize(v, jnp.float32) if Q.is_qtensor(v) else v))
           for k, v in qparams.items()}

    B, S = 2, 8
    block_size = 4
    W = 4
    nb = B * W + 1
    tokens = jnp.asarray(np.random.default_rng(0).integers(
        1, cfg.vocab_size, (B, S)), jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(S)[None], (B, S)).astype(jnp.int32)
    bt = np.zeros((B, W), np.int32)
    for i in range(B):
        bt[i] = 1 + i * W + np.arange(W)
    slot = (jnp.asarray(bt)[:, :, None] * block_size
            + jnp.arange(block_size)[None, None, :]).reshape(B, W * block_size)
    slot_map = slot[:, :S]
    kv_lens = jnp.full((B,), S, jnp.int32)
    last_idx = jnp.full((B,), S - 1, jnp.int32)
    shape = (cfg.num_layers, nb * block_size, cfg.num_kv_heads, cfg.head_dim)

    def run(p):
        kc = jnp.zeros(shape, jnp.float32)
        vc = jnp.zeros(shape, jnp.float32)
        logits, _, _ = M.forward(
            p, tokens, positions, slot_map, jnp.asarray(bt), kv_lens,
            last_idx, kc, vc, cfg=cfg, block_size=block_size)
        return np.asarray(logits)

    np.testing.assert_allclose(run(qparams), run(deq), rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("spec", ["int8", "int8-g16"])
async def test_engine_serves_quantized(spec):
    """Full engine e2e with int8 weights: deterministic generation, and the
    params tree really is int8-resident."""
    cfg = ModelConfig.tiny()
    args = EngineArgs(block_size=4, num_blocks=128, max_num_seqs=4,
                      max_num_batched_tokens=64, max_model_len=128,
                      quantization=spec)
    eng = AsyncJaxEngine(cfg, args)
    try:
        qleaves = [v for v in eng.params["layers"].values()
                   if Q.is_qtensor(v)]
        assert qleaves, "no quantized leaves in served params"
        assert all(v["q"].dtype == jnp.int8 for v in qleaves)
        r = PreprocessedRequest(
            model="tiny", token_ids=list(range(1, 17)),
            stop_conditions=StopConditions(max_tokens=8, ignore_eos=True),
            sampling_options=SamplingOptions(temperature=0.0))
        outs = []
        async for out in eng.generate(r):
            outs.extend(out.token_ids)
        assert len(outs) == 8
        outs2 = []
        async for out in eng.generate(PreprocessedRequest(
                model="tiny", token_ids=list(range(1, 17)),
                stop_conditions=StopConditions(max_tokens=8, ignore_eos=True),
                sampling_options=SamplingOptions(temperature=0.0))):
            outs2.extend(out.token_ids)
        assert outs == outs2
    finally:
        await eng.close()


async def test_engine_quantized_under_mesh():
    """Quantized params shard over a (dp, tp) mesh: quant_shardings mirrors
    the weight sharding onto q and replicates the group dim of s."""
    from dynamo_tpu.parallel import MeshConfig, make_mesh

    cfg = ModelConfig.tiny()
    args = EngineArgs(block_size=4, num_blocks=128, max_num_seqs=4,
                      max_num_batched_tokens=64, max_model_len=128,
                      quantization="int8")
    params = M.init_params(cfg, jax.random.key(0))
    prompt = list(range(1, 17))
    mk = lambda: PreprocessedRequest(  # noqa: E731
        model="t", token_ids=list(prompt),
        stop_conditions=StopConditions(max_tokens=8, ignore_eos=True),
        sampling_options=SamplingOptions(temperature=0.0))

    async def run(mesh):
        eng = AsyncJaxEngine(cfg, args, params=jax.tree.map(np.copy, params),
                             mesh=mesh)
        got = []
        async for out in eng.generate(mk()):
            got.extend(out.token_ids)
        await eng.close()
        return got

    base = await run(None)
    tp = await run(make_mesh(MeshConfig(dp=1, tp=2)))
    assert tp == base
