"""Pre-deployment SLA profiler: sweep one worker, emit interpolation tables.

ref: benchmarks/profiler/profile_sla.py — the planner inverts these sweeps
(planner/perf_interpolation.py) to size prefill/decode fleets. Output JSON:

    {"prefill": [[req_per_s, ttft_ms], ...],
     "decode":  [[tok_per_s, itl_ms], ...],
     "isl_words": N, "osl": M}

Usage: python -m benchmarks.profile_sla --url http://localhost:8000 \
           --model demo --out profile.json
"""

from __future__ import annotations

import argparse
import asyncio
import json

from benchmarks.client import run_closed_loop, summarize


async def sweep(url: str, model: str, isl_words: int, osl: int,
                concurrencies: list[int], requests_per_level: int):
    prefill_pts, decode_pts = [], []
    results = []
    for c in concurrencies:
        results = await run_closed_loop(
            url, model, concurrency=c, num_requests=requests_per_level,
            isl_words=isl_words, osl=osl)
        ok = [r for r in results if r.ok]
        if not ok:
            break
        s = summarize(results)
        wall = sum(r.latency_s for r in ok) / max(1, c)  # per-worker stream time
        req_rate = len(ok) / max(1e-9, wall)
        tok_rate = sum(r.tokens for r in ok) / max(1e-9, wall)
        prefill_pts.append([round(req_rate, 3), s["ttft_p50_ms"]])
        decode_pts.append([round(tok_rate, 1), s["itl_p50_ms"]])
        print(f"concurrency={c}: {s}", flush=True)
    # measured TOKEN ISL (from response usage) — the planner's Prometheus
    # observations are in tokens, so curves must be keyed the same way
    with_tok = [r for r in results if r.ok and r.prompt_tokens] if results else []
    isl_tokens = (sum(r.prompt_tokens for r in with_tok) / len(with_tok)
                  if with_tok else None)
    return prefill_pts, decode_pts, isl_tokens


async def amain():
    ap = argparse.ArgumentParser(description="SLA profiling sweep")
    ap.add_argument("--url", default="http://127.0.0.1:8000")
    ap.add_argument("--model", required=True)
    ap.add_argument("--isl-words", type=int, default=512)
    ap.add_argument("--isl-sweep", default=None,
                    help="comma-separated ISLs for the 2D TTFT table "
                         "(ref: perf_interpolation.py:48 — TTFT depends on "
                         "ISL too; default: just --isl-words)")
    ap.add_argument("--osl", type=int, default=64)
    ap.add_argument("--concurrencies", default="1,2,4,8,16,32")
    ap.add_argument("--requests-per-level", type=int, default=16)
    ap.add_argument("--out", default="profile.json")
    cli = ap.parse_args()

    cs = [int(x) for x in cli.concurrencies.split(",")]
    isls = ([int(x) for x in cli.isl_sweep.split(",")] if cli.isl_sweep
            else [cli.isl_words])
    prefill_by_isl = {}
    decode = []
    tok_isl_by_words = {}
    for isl in isls:
        print(f"--- ISL sweep @ {isl} words ---", flush=True)
        prefill, dec, isl_tok = await sweep(cli.url, cli.model, isl, cli.osl,
                                            cs, cli.requests_per_level)
        # key curves by the MEASURED token ISL (falls back to words) so the
        # planner's token-denominated observations query the right curve
        tok_isl_by_words[isl] = round(isl_tok) if isl_tok else isl
        prefill_by_isl[tok_isl_by_words[isl]] = prefill
        if isl == isls[len(isls) // 2] or len(isls) == 1:
            decode = dec  # ITL barely depends on ISL; keep the middle sweep
    base_words = cli.isl_words if cli.isl_words in isls else isls[0]
    base_isl = tok_isl_by_words[base_words]
    out = {"prefill": prefill_by_isl[base_isl],
           "prefill_by_isl": prefill_by_isl,
           "decode": decode,
           "isl_words": base_words, "osl": cli.osl}
    if base_isl != base_words:  # only when actually MEASURED in tokens —
        # a word count mislabeled as tokens would defeat the planner's
        # tokens-per-word fallback conversion
        out["isl_tokens"] = base_isl
    with open(cli.out, "w") as f:
        json.dump(out, f, indent=2)
    print(f"wrote {cli.out}")


if __name__ == "__main__":
    asyncio.run(amain())
