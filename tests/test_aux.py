"""Aux subsystems: canary health checks, recorders, metrics aggregation."""

import asyncio
import json

import pytest

from dynamo_tpu.llm.recorder import Recorder, KvRecorder, load_events, replay
from dynamo_tpu.runtime.health_check import HealthCheckConfig, HealthCheckManager

pytestmark = pytest.mark.anyio


class FakeClient:
    """Minimal Client surface for the health manager."""

    def __init__(self, healthy: set, all_ids):
        self.healthy = healthy
        self.ids = list(all_ids)
        self._down = set()

    def instance_ids(self):
        return list(self.ids)

    def report_instance_down(self, iid):
        self._down.add(iid)

    async def generate(self, payload, mode="direct", instance_id=None):
        if instance_id not in self.healthy:
            raise RuntimeError("no responders")

        async def stream():
            yield {"ok": True}
        return stream()


async def test_health_check_marks_down_and_restores():
    client = FakeClient(healthy={1}, all_ids=[1, 2])
    cfg = HealthCheckConfig(check_interval_s=0.05, timeout_s=0.5,
                            failure_threshold=2)
    mgr = await HealthCheckManager(client, cfg).start()
    for _ in range(100):
        if 2 in client._down:
            break
        await asyncio.sleep(0.02)
    assert 2 in client._down and 1 not in client._down

    client.healthy.add(2)  # instance recovers → canary restores routing
    for _ in range(100):
        if 2 not in client._down:
            break
        await asyncio.sleep(0.02)
    assert 2 not in client._down
    await mgr.stop()


async def test_recorder_roundtrip(tmp_path):
    path = str(tmp_path / "events.jsonl")
    r = Recorder(path)
    r.record("request", {"prompt": "hi"})
    r.record("response", {"token_ids": [1, 2]})
    r.flush()
    evs = load_events(path)
    assert [e["kind"] for e in evs] == ["request", "response"]
    got = []
    async for ev in replay(path):
        got.append(ev["data"])
    assert got[0] == {"prompt": "hi"}


async def test_kv_recorder_captures_stream(tmp_path):
    import msgpack

    from dynamo_tpu.router.protocols import KvCacheEvent, RouterEvent, StoredBlock
    from dynamo_tpu.runtime.control_plane import LocalControlPlane

    plane = LocalControlPlane()
    path = str(tmp_path / "kv.jsonl")
    rec = await KvRecorder(plane, path).start()
    ev = RouterEvent(7, KvCacheEvent.stored(
        1, None, [StoredBlock(block_hash=11, tokens_hash=22)]))
    await plane.stream_publish("kv_events", msgpack.packb(ev.to_wire()))
    for _ in range(50):
        await asyncio.sleep(0.01)
        rec.recorder.flush()
        if load_events(path):
            break
    await rec.stop()
    evs = load_events(path)
    assert evs and evs[0]["data"]["worker_id"] == 7
