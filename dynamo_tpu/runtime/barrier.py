"""Leader-worker startup barrier over the control-plane KV store.

Multi-host/multi-rank engine startup needs a rendezvous: the leader posts
shared bootstrap data, waits until all N workers have checked in, then
releases everyone at once (ref behavior contract:
lib/runtime/src/utils/leader_worker_barrier.rs:14 — etcd-based; here the
same semantics ride dynctl's KV + prefix watches).

Key scheme (all under ``barriers/<barrier_id>/``):

- ``leader``            — leader's payload; create-if-absent makes double
                          leadership a loud failure.
- ``workers/<worker>``  — one key per checked-in worker (lease-attached, so
                          a dead worker disappears rather than wedging a
                          future barrier of the same id).
- ``ready``             — written by the leader once all N workers are
                          present; workers block on it and then read the
                          payload.
"""

from __future__ import annotations

import asyncio
from typing import Optional

from dynamo_tpu.runtime.control_plane import ControlPlane


class BarrierError(Exception):
    pass


class LeaderWorkerBarrier:
    def __init__(self, plane: ControlPlane, barrier_id: str,
                 lease_id: Optional[int] = None):
        self.plane = plane
        self.prefix = f"barriers/{barrier_id}/"
        self.lease_id = lease_id

    async def leader_enter(self, data: bytes, num_workers: int,
                           timeout: float = 120.0) -> None:
        """Post ``data``, wait for ``num_workers`` check-ins, release."""
        created = await self.plane.kv_create(self.prefix + "leader", data,
                                             lease_id=self.lease_id)
        if not created:
            raise BarrierError(
                f"barrier {self.prefix}: a leader is already registered")
        watch = await self.plane.watch_prefix(self.prefix + "workers/")
        try:
            seen = set(watch.snapshot)

            async def wait_workers():
                if len(seen) >= num_workers:
                    return
                async for ev in watch:
                    if ev.type == "put":
                        seen.add(ev.key)
                    else:
                        seen.discard(ev.key)
                    if len(seen) >= num_workers:
                        return

            try:
                await asyncio.wait_for(wait_workers(), timeout)
            except asyncio.TimeoutError:
                raise BarrierError(
                    f"barrier {self.prefix}: {len(seen)}/{num_workers} "
                    f"workers after {timeout}s")
        finally:
            await watch.cancel()
        await self.plane.kv_put(self.prefix + "ready", b"1",
                                lease_id=self.lease_id)

    async def worker_enter(self, worker_id: str,
                           timeout: float = 120.0) -> bytes:
        """Check in and block until the leader releases; returns its data."""
        await self.plane.kv_put(self.prefix + f"workers/{worker_id}", b"1",
                                lease_id=self.lease_id)
        watch = await self.plane.watch_prefix(self.prefix + "ready")
        try:
            async def wait_ready():
                if watch.snapshot:
                    return
                async for ev in watch:
                    if ev.type == "put":
                        return

            try:
                await asyncio.wait_for(wait_ready(), timeout)
            except asyncio.TimeoutError:
                raise BarrierError(
                    f"barrier {self.prefix}: leader never released "
                    f"within {timeout}s")
        finally:
            await watch.cancel()
        data = await self.plane.kv_get(self.prefix + "leader")
        if data is None:
            raise BarrierError(
                f"barrier {self.prefix}: leader key vanished (lease expiry?)")
        return data
