"""Per-chip capacity interpolation from pre-deployment profiling.

ref: planner/utils/perf_interpolation.py + benchmarks/profiler/profile_sla.py
— the profiler sweeps a single prefill replica (TTFT vs request rate) and a
single decode replica (ITL vs per-chip token throughput at varying
concurrency); the planner inverts those curves: "what per-replica load keeps
us inside the SLA?"
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np


@dataclass
class ProfilePoint:
    load: float  # requests/s (prefill) or tokens/s (decode) per replica
    latency_ms: float  # TTFT (prefill) or ITL (decode)


@dataclass
class PerfInterpolator:
    """Monotone latency-vs-load curve with inversion."""

    points: list = field(default_factory=list)

    def __post_init__(self):
        self.points = sorted(
            (p if isinstance(p, ProfilePoint) else ProfilePoint(*p)
             for p in self.points),
            key=lambda p: p.load)

    @property
    def loads(self):
        return np.asarray([p.load for p in self.points])

    @property
    def lats(self):
        return np.asarray([p.latency_ms for p in self.points])

    def latency_at(self, load: float) -> float:
        """Interpolated latency at a per-replica load (clamped to the sweep)."""
        return float(np.interp(load, self.loads, self.lats))

    def max_load_under(self, latency_target_ms: float) -> float:
        """Largest per-replica load whose latency stays ≤ target.

        0 means even an idle replica misses the SLA (impossible target);
        the last sweep point means the target never binds in-range.
        """
        loads, lats = self.loads, self.lats
        if latency_target_ms < lats[0]:
            return 0.0
        if latency_target_ms >= lats[-1]:
            return float(loads[-1])
        # walk segments; curve is assumed non-decreasing in load
        idx = int(np.searchsorted(lats, latency_target_ms, side="right")) - 1
        lo, hi = self.points[idx], self.points[idx + 1]
        if hi.latency_ms == lo.latency_ms:
            return float(hi.load)
        frac = (latency_target_ms - lo.latency_ms) / (hi.latency_ms - lo.latency_ms)
        return float(lo.load + frac * (hi.load - lo.load))
