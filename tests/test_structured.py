"""Structured decoding subsystem (docs/structured.md): device-compiled
constraint FSMs in the sampling dispatch, tool-call enforcement, and the
agentic tool-loop workload (ISSUE 13 acceptance).
"""

import asyncio
import json
import re

import numpy as np
import pytest

from dynamo_tpu.engine.config import EngineArgs, ModelConfig
from dynamo_tpu.engine.engine import AsyncJaxEngine
from dynamo_tpu.llm.guided import CharDfa, GuidedState, TokenMachine
from dynamo_tpu.protocols import (
    PreprocessedRequest, SamplingOptions, StopConditions,
)
from dynamo_tpu.structured import (
    COMPILE_STATS,
    FsmCursor,
    StructuredRuntime,
    build_guided_state,
    compile_fsm,
    tool_constraint,
)

pytestmark = pytest.mark.anyio

CFG = ModelConfig.tiny()
VOCAB = [""] + [chr(32 + i) for i in range(CFG.vocab_size - 1)]
CHAR_VOCAB = [""] + [chr(32 + i) for i in range(95)]


# ------------------------------------------------------------ compiler unit

@pytest.mark.parametrize("pattern", [
    r"[ab]{3}", r"yes|no|maybe", r"a(xy|b)", r"\d+",
    r'"([^"\\]|\\["\\nrt])*"',
])
def test_compiled_tables_mirror_host_oracle(pattern):
    """Every reachable state's mask and transition must equal what the
    host oracle (GuidedState) computes — walked adversarially from both
    ends of the allowed set."""
    tm = TokenMachine(CharDfa(pattern), CHAR_VOCAB)
    V = len(CHAR_VOCAB)
    eos = [2]
    fsm = compile_fsm(tm, eos, V, 2000)
    for pick_last in (False, True):
        gs = GuidedState(tm, eos)
        rt = StructuredRuntime(V, 512)
        seg = rt.acquire(("t", pattern, pick_last), fsm)
        cur = FsmCursor(seg, rt)
        for step in range(48):
            a = sorted(gs.allowed_token_ids(V))
            b = sorted(cur.allowed_token_ids(V))
            assert a == b, (pattern, step, a[:8], b[:8])
            assert (gs.done, gs.exhausted) == (cur.done, cur.exhausted)
            if not a or gs.done or gs.exhausted:
                break
            t = a[-1] if pick_last else a[0]
            gs.advance(t)
            cur.advance(t)


def test_arena_segments_share_and_evict():
    rt = StructuredRuntime(2, 64)
    tm = TokenMachine(CharDfa("ab"), ["a", "b"])
    fsm = compile_fsm(tm, [], 2, 32)
    s1 = rt.acquire("k1", fsm)
    s2 = rt.acquire("k1", fsm)
    assert s1 is s2 and s1.refs == 2  # same constraint shares a segment
    rt.release(s1)
    rt.release(s1)
    # a zero-ref segment is evictable: fill the arena past capacity
    big_tm = TokenMachine(CharDfa("a{40}"), ["a", "b"])
    big = compile_fsm(big_tm, [], 2, 63)
    s3 = rt.acquire("k2", big)
    assert s3 is not None
    assert rt.evictions >= 1 or rt.stats()["segments"] == 2


def test_budget_fallback_to_host_oracle():
    """A constraint whose closure exceeds the arena falls back to the
    host oracle — and still serves correct streams (engine test below)."""
    rt = StructuredRuntime(len(CHAR_VOCAB), 33)  # min arena, 32 usable
    gs = build_guided_state({"regex": "a{64}"}, CHAR_VOCAB, [2], rt)
    assert not getattr(gs, "device", False)
    assert rt.rows_host == 1


def test_compile_cache_counts_hits_and_misses():
    vocab = ["x", "y", "z"]
    rt = StructuredRuntime(len(vocab), 64)
    before = dict(COMPILE_STATS)
    build_guided_state({"regex": "xy+z"}, vocab, [], rt)
    mid = dict(COMPILE_STATS)
    assert mid["miss"] == before["miss"] + 1
    build_guided_state({"regex": "xy+z"}, vocab, [], rt)
    after = dict(COMPILE_STATS)
    assert after["hit"] == mid["hit"] + 1 and after["miss"] == mid["miss"]


def test_free_state_is_identity():
    """Arena row 0 (FREE) must allow every token and self-loop, so an
    unconstrained row through the fused dispatch is untouched."""
    import jax.numpy as jnp

    from dynamo_tpu.engine.sampling import apply_fsm_mask

    rt = StructuredRuntime(37, 64)
    mask_t, next_t = rt.device_tables()
    logits = jnp.asarray(np.linspace(-3, 3, 2 * 37,
                                     dtype=np.float32).reshape(2, 37))
    out = apply_fsm_mask(logits, jnp.zeros((2,), jnp.int32), mask_t)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(logits))
    assert int(next_t[0, 5]) == 0  # self-loop


# ---------------------------------------------------- engine path identity

def _req(guided, seed=None, temp=0.0, mt=24, eos=(2,), min_tokens=None):
    return PreprocessedRequest(
        model="t", token_ids=[1, 2, 3],
        sampling_options=SamplingOptions(temperature=temp, seed=seed,
                                         guided=guided),
        stop_conditions=StopConditions(max_tokens=mt, min_tokens=min_tokens),
        eos_token_ids=list(eos))


async def _collect(eng, r):
    toks = []
    async for out in eng.generate(r):
        toks.extend(out.token_ids)
        if out.finish_reason is not None:
            return toks, out.finish_reason
    return toks, None


def _engine(**kw):
    base = dict(block_size=16, num_blocks=64, max_num_seqs=4,
                max_num_batched_tokens=128, max_model_len=128)
    base.update(kw)
    return AsyncJaxEngine(CFG, EngineArgs(**base), guided_vocab=VOCAB)


GUIDEDS = [
    {"regex": r"[ab]{6}"},
    {"choice": ["apple", "banana"]},
    {"json": {"type": "object", "properties": {"ok": {"type": "boolean"},
                                               "k": {"enum": ["x", "yz"]}}}},
]


async def test_device_fsm_bit_identical_to_host_oracle():
    """The acceptance gate: device-FSM streams must equal the host-DFA
    oracle's bit for bit — greedy AND seeded — while actually riding the
    pipelined decode loop (both ``guided_state is None`` pipeline guards
    are gone)."""
    dev = _engine()
    host = _engine(structured_device=False)
    try:
        assert dev.structured is not None and host.structured is None
        for g in GUIDEDS:
            for seed, temp in [(None, 0.0), (7, 0.9), (123, 0.5)]:
                a = await _collect(dev, _req(g, seed, temp, 32))
                b = await _collect(host, _req(g, seed, temp, 32))
                assert a == b, (g, seed, temp, a, b)
        st = dev.structured.stats()
        assert st["rows_device"] > 0 and st["rows_host"] == 0
        assert dev.pipelined_steps > 0, \
            "constrained rows never rode the pipelined decode loop"
        assert host.pipelined_steps == 0  # oracle rows still force off it
    finally:
        await dev.close()
        await host.close()


async def test_constrained_rows_ride_ragged_mixed_step():
    """A constrained row and a free prefill must co-schedule into ONE
    ragged launch (no bucketed demotion for the constrained row)."""
    dev = _engine(max_num_batched_tokens=64)
    try:
        a, b = await asyncio.gather(
            _collect(dev, _req({"regex": r"[ab]{6}"}, mt=16)),
            _collect(dev, PreprocessedRequest(
                model="t", token_ids=list(range(3, 40)),
                sampling_options=SamplingOptions(temperature=0.0),
                stop_conditions=StopConditions(max_tokens=8,
                                               ignore_eos=True),
                eos_token_ids=[2])))
        txt = "".join(VOCAB[t] for t in a[0] if t != 2)
        assert re.fullmatch(r"[ab]{6}", txt), txt
        assert any(k[0] in ("ragged", "ragged_dec")
                   for k in dev.compiled_signatures)
    finally:
        await dev.close()


async def test_multi_step_burst_constrained_identical():
    host = _engine(structured_device=False)
    multi = _engine(multi_step_decode=4)
    try:
        for g in GUIDEDS:
            assert (await _collect(multi, _req(g))
                    == await _collect(host, _req(g))), g
            assert (await _collect(multi, _req(g, seed=11, temp=0.8))
                    == await _collect(host, _req(g, seed=11, temp=0.8))), g
        kinds = {k[0] for k in multi.compiled_signatures if "multi" in k[0]}
        assert kinds == {"multi_fsm"}, kinds  # burst stayed fused
    finally:
        await host.close()
        await multi.close()


async def test_spec_decode_constrained_identical():
    host = _engine(structured_device=False)
    spec = _engine(speculative_tokens=3)
    try:
        for g in GUIDEDS:
            assert (await _collect(spec, _req(g))
                    == await _collect(host, _req(g))), g
        assert any(k[0] == "verify_fsm" for k in spec.compiled_signatures)
    finally:
        await host.close()
        await spec.close()


async def test_min_tokens_falls_back_to_host_oracle():
    """min_tokens EOS gating is per-step dynamic — those rows must use
    the oracle (documented fallback rule) and still defer EOS."""
    dev = _engine()
    try:
        toks, reason = await _collect(
            dev, _req({"choice": ["hi", "hiyo"]}, mt=16, eos=(5,),
                      min_tokens=4))
        assert "".join(VOCAB[t] for t in toks if t != 5).startswith("hiyo")
        assert dev.structured.stats()["rows_host"] >= 1
    finally:
        await dev.close()


async def test_budget_fallback_engine_stream_still_valid():
    dev = _engine(structured_table_mb=0.0001)  # arena too small to build
    try:
        assert dev.structured is None
        toks, _ = await _collect(dev, _req({"regex": r"[ab]{4}"}))
        txt = "".join(VOCAB[t] for t in toks if t != 2)
        assert re.fullmatch(r"[ab]{4}", txt), txt
    finally:
        await dev.close()


async def test_schema_validity_property():
    """Property over generated schemas: greedy constrained output always
    parses and type-checks against its schema."""
    rng = np.random.default_rng(7)
    schemas = []
    for _ in range(6):
        props = {}
        for pi in range(int(rng.integers(1, 3))):
            kind = int(rng.integers(0, 4))
            name = f"f{pi}"
            if kind == 0:
                props[name] = {"type": "boolean"}
            elif kind == 1:
                props[name] = {"type": "integer"}
            elif kind == 2:
                props[name] = {"enum": ["a", "bc"]}
            else:
                props[name] = {"type": "array", "items": {"type": "boolean"},
                               "minItems": 1, "maxItems": 2}
        schemas.append({"type": "object", "properties": props})
    dev = _engine()
    try:
        for schema in schemas:
            toks, _ = await _collect(dev, _req({"json": schema}, mt=64))
            txt = "".join(VOCAB[t] for t in toks if t != 2)
            obj = json.loads(txt)
            for name, sub in schema["properties"].items():
                v = obj[name]
                if sub.get("type") == "boolean":
                    assert isinstance(v, bool)
                elif sub.get("type") == "integer":
                    assert isinstance(v, int)
                elif "enum" in sub:
                    assert v in sub["enum"]
                else:
                    assert isinstance(v, list) and 1 <= len(v) <= 2
    finally:
        await dev.close()


async def test_flight_records_tag_constrained_rows():
    dev = _engine()
    try:
        await _collect(dev, _req({"regex": r"[ab]{6}"}))
        recs = dev.flight.snapshot()
        assert any(r.get("constrained_rows") for r in recs), \
            "no flight record carried constrained_rows"
    finally:
        await dev.close()


async def test_arena_released_on_finish():
    dev = _engine()
    try:
        await _collect(dev, _req({"regex": r"[ab]{4}"}))
        segs = list(dev.structured._segments.values())
        assert segs and all(s.refs == 0 for s in segs)
    finally:
        await dev.close()


def test_unsatisfiable_constraint_is_typed_invalid_request():
    """The vocabulary-refusal is DETERMINISTIC fleet-wide, so it must be
    a typed terminal error (never migrated, frontend 400) that survives
    the wire — review-round fix."""
    from dynamo_tpu.runtime.context import (
        InvalidRequestError, stream_error_from_wire,
    )

    rt = StructuredRuntime(3, 64)
    with pytest.raises(InvalidRequestError):
        build_guided_state({"regex": r"\d+"}, ["a", "b"], [], rt)
    e = stream_error_from_wire("x", "invalid_request", True)
    assert isinstance(e, InvalidRequestError) and not e.retryable


# -------------------------------------------------- tool_choice enforcement

TOOLS = [
    {"type": "function", "function": {
        "name": "get_weather",
        "parameters": {"type": "object",
                       "properties": {"city": {"enum": ["paris", "nyc"]}}}}},
    {"type": "function", "function": {
        "name": "get_time", "parameters": {
            "type": "object", "properties": {"tz": {"type": "integer"}}}}},
]


def _chat(**kw):
    body = {"model": "m", "messages": [{"role": "user", "content": "hi"}]}
    body.update(kw)
    from dynamo_tpu.protocols.openai import parse_chat_request
    return parse_chat_request(body)


def test_tool_choice_parse_matrix():
    from dynamo_tpu.protocols.openai import RequestError

    assert _chat(tools=TOOLS, tool_choice="required").tool_choice \
        == "required"
    assert _chat(tools=TOOLS).tool_choice is None
    named = {"type": "function", "function": {"name": "get_time"}}
    assert _chat(tools=TOOLS, tool_choice=named).tool_choice == named
    for bad, msg in [
            (dict(tools=TOOLS, tool_choice="banana"), "must be"),
            (dict(tool_choice="required"), "requires 'tools'"),
            (dict(tools=TOOLS, tool_choice={"type": "function",
                                            "function": {"name": "nope"}}),
             "unknown tool"),
            (dict(tools=TOOLS, tool_choice="required",
                  guided_regex="a+"), "cannot be combined"),
            (dict(tools=[{"function": {}}], tool_choice="auto"),
             "each tool"),
    ]:
        with pytest.raises(RequestError, match=msg):
            _chat(**bad)


def test_tool_constraint_grammar_per_parser():
    pat = tool_constraint(TOOLS, "required", None)
    d = CharDfa(pat)
    assert d.fullmatch('{"name":"get_weather","arguments":{"city":"nyc"}}')
    assert d.fullmatch('{"name":"get_time","arguments":{"tz":-5}}')
    assert not d.fullmatch('{"name":"evil","arguments":{}}')
    # named tool restricts the union
    named = {"type": "function", "function": {"name": "get_time"}}
    dn = CharDfa(tool_constraint(TOOLS, named, None))
    assert dn.fullmatch('{"name":"get_time","arguments":{"tz":0}}')
    assert not dn.fullmatch(
        '{"name":"get_weather","arguments":{"city":"nyc"}}')
    # parser wrappers round-trip through the real parsers
    from dynamo_tpu.parsers import parse_tool_calls
    h = '<tool_call>{"name":"get_time","arguments":{"tz":1}}</tool_call>'
    assert CharDfa(tool_constraint(TOOLS, "required", "hermes")).fullmatch(h)
    _, calls = parse_tool_calls("hermes", h)
    assert calls and calls[0].name == "get_time"
    m = '[TOOL_CALLS][{"name":"get_time","arguments":{"tz":1}}]'
    assert CharDfa(tool_constraint(TOOLS, "required",
                                   "mistral")).fullmatch(m)
    _, calls = parse_tool_calls("mistral", m)
    assert calls and calls[0].name == "get_time"
    with pytest.raises(ValueError, match="not supported"):
        tool_constraint(TOOLS, "required", "harmony")


def test_pipeline_enforces_tool_choice():
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.llm.pipeline import OpenAIPreprocessor
    from dynamo_tpu.llm.tokenizer import make_test_tokenizer

    tk = make_test_tokenizer()
    pipe = OpenAIPreprocessor(
        ModelDeploymentCard(display_name="m",
                            eos_token_ids=[tk.eos_token_id]), tk, None)
    # "none" strips tools before the template renders
    r, enforced = pipe._apply_tool_choice(
        _chat(tools=TOOLS, tool_choice="none"))
    assert r.tools is None and not enforced and r.sampling.guided is None
    # "required" attaches the constraint; the original request is untouched
    orig = _chat(tools=TOOLS, tool_choice="required")
    r, enforced = pipe._apply_tool_choice(orig)
    assert enforced and r.sampling.guided and "regex" in r.sampling.guided
    assert orig.sampling.guided is None
    # auto passes through unconstrained
    r, enforced = pipe._apply_tool_choice(_chat(tools=TOOLS))
    assert not enforced and r.sampling.guided is None


async def test_tool_choice_required_end_to_end():
    """Frontend-shaped flow: required → constrained generation → the tool
    parser surfaces the call with finish_reason tool_calls."""
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.llm.pipeline import OpenAIPreprocessor
    from dynamo_tpu.llm.tokenizer import make_test_tokenizer
    from dynamo_tpu.runtime.context import Context

    tk = make_test_tokenizer()
    eng = _engine()

    async def downstream(pre, ctx):
        async for out in eng.generate(pre, ctx):
            # pipeline expects detokenized text on each output; decode via
            # the engine's guided vocab (test tokenizer has no JSON chars)
            out.text = "".join(VOCAB[t] for t in out.token_ids
                               if t not in pre.eos_token_ids)
            yield out

    pipe = OpenAIPreprocessor(
        ModelDeploymentCard(display_name="m", eos_token_ids=[2]), tk,
        downstream)
    parsed = _chat(tools=TOOLS, tool_choice="required", max_tokens=64)
    chunks = []
    try:
        async for wire in pipe.generate(parsed, Context()):
            chunks.append(wire)
    finally:
        await eng.close()
    finals = [c["data"] for c in chunks if c.get("data")]
    tool_calls = [tc for ch in finals
                  for choice in ch.get("choices", [])
                  for tc in (choice.get("delta") or {}).get("tool_calls",
                                                            [])]
    finish = [choice.get("finish_reason")
              for ch in finals for choice in ch.get("choices", [])
              if choice.get("finish_reason")]
    assert tool_calls, finals
    fn = tool_calls[0]["function"]
    assert fn["name"] in ("get_weather", "get_time")
    json.loads(fn["arguments"])
    assert finish == ["tool_calls"]


def test_qos_tool_class_mapping():
    from dynamo_tpu.qos import ConfigError, QosConfig

    cfg = QosConfig.load(env={"DYN_QOS_TOOL_CLASS": "interactive"})
    assert cfg.tool_class == "interactive"
    with pytest.raises(ConfigError, match="unknown class"):
        QosConfig.load(env={"DYN_QOS_TOOL_CLASS": "vip"})

    # frontend resolution: tools adopt the class, explicit header wins
    from dynamo_tpu.frontend.http import HttpService

    class FakeReq:
        def __init__(self, headers):
            self.headers = headers

    svc = HttpService.__new__(HttpService)
    svc.qos = cfg
    svc._adhoc_tenants = set()
    svc._adhoc_overflow_warned = False
    t, c = HttpService._resolve_qos(svc, FakeReq({}), has_tools=True)
    assert c == "interactive"
    t, c = HttpService._resolve_qos(
        svc, FakeReq({"x-dynamo-priority": "batch"}), has_tools=True)
    assert c == "batch"
    t, c = HttpService._resolve_qos(svc, FakeReq({}), has_tools=False)
    assert c == "standard"


# --------------------------------------------------------------- mocker

async def test_mocker_guided_parity():
    from dynamo_tpu.mocker.engine import (
        MockEngine, MockEngineArgs, mock_guided_vocab,
    )
    from dynamo_tpu.runtime.context import Context

    eng = MockEngine(MockEngineArgs(speedup_ratio=100.0))
    await eng.start()
    try:
        req = PreprocessedRequest(
            model="m", token_ids=[1, 2, 3],
            sampling_options=SamplingOptions(
                temperature=0.0,
                guided={"json": {"type": "object", "properties": {
                    "ok": {"type": "boolean"}}}}),
            stop_conditions=StopConditions(max_tokens=64),
            eos_token_ids=[2])
        toks, reasons = [], []
        async for out in eng.generate(req, Context()):
            toks.extend(out.get("token_ids") or [])
            if out.get("finish_reason"):
                reasons.append(out["finish_reason"])
                break
        v = mock_guided_vocab()
        obj = json.loads("".join(v[t] for t in toks if t != 2))
        assert isinstance(obj.get("ok"), bool)
        # two identical requests emit identical canned streams
        toks2 = []
        async for out in eng.generate(req, Context()):
            toks2.extend(out.get("token_ids") or [])
            if out.get("finish_reason"):
                break
        assert toks2 == toks
        # timeline records carry the per-row constraint shape
        assert any(r.get("constrained_rows")
                   for r in eng.flight.snapshot())
    finally:
        await eng.stop()


# ------------------------------------------------------------ bench smoke

async def test_tools_bench_smoke():
    """The tier-1 wiring for bench.py --tools (full gates run in CI's
    bench-gains step; this keeps the phase green at reduced size)."""
    import bench

    out = await bench.tools_bench(False, reps=1, sessions=1, turns=2)
    assert out["schema_valid_rate"] == 1.0, out
    assert out["turn2_prefix_hit_tokens"] > 0, out
    assert out["structured_rows_host"] == 0, out
    assert out["structured_rows_device"] > 0, out
    peer = out.get("peer") or {}
    assert peer.get("pulled_blocks", 0) > 0 and peer.get("complete"), out
