"""Engine configuration: model architecture + engine runtime knobs.

The knob set mirrors the reference's engine-arg surface (ref:
components/backends/vllm/src/dynamo/vllm/args.py, mocker/protocols.rs:67-100)
— block_size / num blocks / max_num_seqs / max_num_batched_tokens /
enable_prefix_caching / enable_chunked_prefill — plus TPU-native additions
(mesh shape, dtype, bucketing).
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
from dataclasses import dataclass, field
from typing import Optional


#: static cap on prefill chunks co-scheduled into one ragged step (the
#: chunk grid sizes for exactly this many — model.ragged_grid_shape);
#: extra chunks wait a step
RAGGED_MAX_CHUNKS = 4


@dataclass
class ModelConfig:
    """Llama-family decoder architecture (covers Llama 2/3, Mistral, Qwen2,
    TinyLlama; MoE via n_routed_experts for Mixtral/DeepSeek-style models)."""

    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32
    head_dim: Optional[int] = None  # defaults to hidden_size // num_heads
    rope_theta: float = 10000.0
    #: HF rope_scaling dict (yarn / llama3 supported — model.rope_params);
    #: unsupported types fail loudly at trace time
    rope_scaling: Optional[dict] = None
    rms_norm_eps: float = 1e-5
    max_position_embeddings: int = 8192
    tie_word_embeddings: bool = False
    dtype: str = "bfloat16"
    # MoE (0 experts = dense MLP)
    num_experts: int = 0
    num_experts_per_tok: int = 2
    #: EP dispatch capacity: each expert takes up to ceil(N*K/E * this)
    #: tokens per step (Switch-style dropping past that; >= E/K disables
    #: dropping entirely)
    moe_capacity_factor: float = 2.0
    #: expert MLP width (DeepSeek's moe_intermediate_size); None = use
    #: intermediate_size (Mixtral-style)
    moe_intermediate_size: Optional[int] = None
    #: always-on shared experts (DeepSeek): dense SwiGLU of width
    #: n_shared_experts * moe_intermediate_size added to the routed output
    n_shared_experts: int = 0
    #: leading dense (non-MoE) decoder layers (DeepSeek first_k_dense_replace)
    first_k_dense_replace: int = 0
    #: router scoring: "softmax" (Mixtral: softmax over top-k logits) or
    #: "sigmoid" (DeepSeek-V3: sigmoid scores + e_score_correction_bias for
    #: expert choice, gathered raw scores as weights)
    scoring_func: str = "softmax"
    norm_topk_prob: bool = False
    routed_scaling_factor: float = 1.0
    # group-limited routing (DeepSeek: experts in n_group groups, routing
    # restricted to the best topk_group groups)
    n_group: int = 1
    topk_group: int = 1
    # attention extras
    qkv_bias: bool = False  # Qwen2-style
    #: per-head RMSNorm on q and k before RoPE (Qwen3 / Qwen3-MoE); the
    #: learned scale has head_dim width, shared across heads
    qk_norm: bool = False
    o_bias: bool = False  # gpt-oss: o_proj carries a bias too
    sliding_window: Optional[int] = None
    #: per-layer sliding windows (gpt-oss alternates sliding/full layers);
    #: entries are window sizes with 0 = full attention. Overrides
    #: ``sliding_window`` when set; length must equal num_layers.
    layer_windows: Optional[tuple] = None
    #: learned per-head attention-sink logits (gpt-oss): an extra softmax
    #: slot that absorbs probability mass without contributing output
    attention_sinks: bool = False
    #: expert MLP activation: "swiglu" (llama/mixtral/deepseek) or
    #: "swiglu_oss" (gpt-oss clamped variant with biases and (up+1) gating)
    moe_activation: str = "swiglu"
    #: add the router bias to the logits BEFORE top-k in softmax scoring
    #: (gpt-oss's router has a true bias; DeepSeek's e_score_correction_bias
    #: only steers expert CHOICE and is handled in the sigmoid branch)
    router_logit_bias: bool = False
    # --- Gemma family -----------------------------------------------------
    #: scale token embeddings by sqrt(hidden_size) (Gemma; NOT folded into
    #: the weights — the tied lm_head reads them unscaled)
    embed_scale: bool = False
    #: RMSNorm scales by (1 + w) (Gemma); folded into the stored weights at
    #: LOAD time (loader.norm_get), so the forward never branches on it
    norm_plus_one: bool = False
    #: dense-MLP activation: "silu" (llama-family SwiGLU) or "gelu_tanh"
    #: (Gemma GeGLU). Distinct from moe_activation.
    hidden_activation: str = "silu"
    #: Gemma-2 soft capping: s = cap·tanh(s/cap) on attention scores and on
    #: final logits; 0 = off. Nonzero attn cap forces the XLA attention
    #: path (the Pallas kernels' online softmax has no tanh stage).
    attn_logit_softcap: float = 0.0
    final_logit_softcap: float = 0.0
    #: Gemma-2 sandwich norms: post-norms applied to each sublayer's OUTPUT
    #: before the residual add (extra per-layer weights post_attn_norm /
    #: post_mlp_norm; mlp_norm holds pre_feedforward_layernorm)
    sandwich_norms: bool = False
    #: attention scale = query_pre_attn_scalar^-0.5 instead of head_dim^-0.5
    #: (Gemma-2; folded into q so every attention path inherits it)
    query_pre_attn_scalar: Optional[float] = None
    # --- MLA (multi-head latent attention, DeepSeek V2/V3) ---------------
    #: latent rank of the compressed KV; >0 switches attention to MLA and
    #: the paged cache to the latent layout (see kv_cache_spec)
    kv_lora_rank: int = 0
    q_lora_rank: Optional[int] = None  # None = full q projection (V2-Lite)
    qk_nope_head_dim: int = 128
    qk_rope_head_dim: int = 64
    v_head_dim: int = 128

    def __post_init__(self):
        if self.head_dim is None:
            self.head_dim = self.hidden_size // self.num_heads
        if self.layer_windows is not None:
            self.layer_windows = tuple(int(w or 0) for w in self.layer_windows)
            if len(self.layer_windows) != self.num_layers:
                raise ValueError(
                    f"layer_windows has {len(self.layer_windows)} entries "
                    f"for {self.num_layers} layers")

    @property
    def is_moe(self) -> bool:
        return self.num_experts > 0

    @property
    def is_mla(self) -> bool:
        return self.kv_lora_rank > 0

    @property
    def qk_head_dim(self) -> int:
        return self.qk_nope_head_dim + self.qk_rope_head_dim

    @property
    def rope_cache_dim(self) -> int:
        """MLA rope-part cache width: qk_rope_head_dim rounded up to a
        128-lane multiple (TPU DMA tile alignment)."""
        return -(-self.qk_rope_head_dim // 128) * 128

    @property
    def moe_ffn_size(self) -> int:
        return self.moe_intermediate_size or self.intermediate_size

    @property
    def num_dense_prefix_layers(self) -> int:
        """Layers in the separate ``dense_layers`` param stack. THE single
        source of the dense-prefix rule — loader, init, shardings, and
        forward all key off this, so the pytree contract cannot drift."""
        return self.first_k_dense_replace if self.is_moe else 0

    @property
    def kv_cache_spec(self) -> tuple[tuple[int, int], tuple[int, int]]:
        """((heads, dim) of k_cache, (heads, dim) of v_cache) per slot.

        MHA/GQA: both caches hold [num_kv_heads, head_dim]. MLA stores the
        compressed latent instead — k_cache [1, kv_lora_rank] (normalized
        c_kv) and v_cache [1, rope_pad] (the shared post-RoPE k_rot, zero-
        padded to a 128-lane multiple so the Pallas decode kernel can DMA
        cache pages tile-aligned) — the memory win that makes DeepSeek-class
        models servable (ref behavior delegated to engines; e.g. vLLM's MLA
        cache does the same).
        """
        if self.is_mla:
            return ((1, self.kv_lora_rank), (1, self.rope_cache_dim))
        return ((self.num_kv_heads, self.head_dim),
                (self.num_kv_heads, self.head_dim))

    @staticmethod
    def from_hf_config(d: dict) -> "ModelConfig":
        """Map a HuggingFace ``config.json`` dict onto ModelConfig.

        Handles llama/mistral/qwen2/mixtral keys (ref parity: the reference
        loads the same file into its ModelDeploymentCard — model_card.rs:93).
        """
        arch = (d.get("architectures") or [""])[0].lower()
        is_deepseek = "deepseek" in arch
        is_gpt_oss = "gptoss" in arch
        is_gemma2 = "gemma2" in arch
        is_gemma = "gemma" in arch  # gemma-1 OR gemma-2
        if "gemma3" in arch:
            raise NotImplementedError(
                "Gemma-3 (dual-base rope, plus-one qk-norm) is not "
                "supported yet; Gemma 1/2 are")
        is_phi3 = "phi3" in arch  # Phi-3 family AND Phi-4 (same arch class)
        if is_phi3:
            if float(d.get("partial_rotary_factor") or 1.0) != 1.0:
                raise NotImplementedError(
                    "partial rotary (phi-4-mini style) is not supported")
            sc = d.get("rope_scaling")
            if sc and sc.get("rope_type", sc.get("type")) == "longrope":
                # longrope factors live in the scaling dict but the window
                # sizes live on the top-level config — carry them together
                # (model.rope_params reads only the dict)
                sc = dict(sc)
                sc["max_position_embeddings"] = d.get(
                    "max_position_embeddings", 4096)
                sc["original_max_position_embeddings"] = d.get(
                    "original_max_position_embeddings",
                    sc.get("original_max_position_embeddings",
                           sc["max_position_embeddings"]))
                d = {**d, "rope_scaling": sc}
        if "qwen3moe" in arch:
            # the uniform layer stack (lax.scan) requires every non-prefix
            # layer to be MoE; refuse irregular sparsity loudly rather than
            # serving a silently-wrong forward
            if d.get("mlp_only_layers") or d.get("decoder_sparse_step", 1) != 1:
                raise ValueError(
                    "Qwen3-MoE checkpoints with mlp_only_layers or "
                    "decoder_sparse_step != 1 interleave dense layers mid-"
                    "stack, which the stacked-layer forward does not support")
        mla = is_deepseek and d.get("kv_lora_rank") is not None
        layer_windows = None
        if is_gemma2:
            # HF Gemma2: sliding attention on EVEN layer indices
            # (Gemma2DecoderLayer: is_sliding = not bool(layer_idx % 2))
            L = d.get("num_hidden_layers", 26)
            w = d.get("sliding_window", 4096)
            layer_windows = tuple(w if i % 2 == 0 else 0 for i in range(L))
        if is_gpt_oss:
            L = d.get("num_hidden_layers", 36)
            types = d.get("layer_types") or [
                "sliding_attention" if i % 2 == 0 else "full_attention"
                for i in range(L)]
            layer_windows = tuple(
                d.get("sliding_window", 128) if t == "sliding_attention" else 0
                for t in types)
        return ModelConfig(
            vocab_size=d.get("vocab_size", 32000),
            hidden_size=d.get("hidden_size", 4096),
            intermediate_size=d.get("intermediate_size", 11008),
            num_layers=d.get("num_hidden_layers", 32),
            num_heads=d.get("num_attention_heads", 32),
            num_kv_heads=d.get("num_key_value_heads", d.get("num_attention_heads", 32)),
            head_dim=d.get("head_dim") if not is_deepseek else None,
            embed_scale=is_gemma,
            norm_plus_one=is_gemma,
            hidden_activation=("gelu_tanh" if is_gemma else "silu"),
            attn_logit_softcap=(d.get("attn_logit_softcapping") or 0.0)
            if is_gemma2 else 0.0,
            final_logit_softcap=(d.get("final_logit_softcapping") or 0.0)
            if is_gemma2 else 0.0,
            sandwich_norms=is_gemma2,
            query_pre_attn_scalar=(d.get("query_pre_attn_scalar")
                                   if is_gemma2 else None),
            rope_theta=d.get("rope_theta", 10000.0),
            rope_scaling=d.get("rope_scaling"),
            rms_norm_eps=d.get("rms_norm_eps", 1e-5),
            max_position_embeddings=d.get("max_position_embeddings", 8192),
            tie_word_embeddings=d.get("tie_word_embeddings", False),
            num_experts=(d.get("num_local_experts")       # mixtral
                         or d.get("n_routed_experts")      # deepseek
                         or d.get("num_experts", 0)        # qwen3-moe
                         or 0),
            num_experts_per_tok=d.get("num_experts_per_tok", 2),
            moe_intermediate_size=d.get("moe_intermediate_size"),
            n_shared_experts=d.get("n_shared_experts", 0) or 0,
            first_k_dense_replace=d.get("first_k_dense_replace", 0) or 0,
            scoring_func=d.get("scoring_func",
                               "sigmoid" if "deepseekv3" in arch else "softmax"),
            # Mixtral and gpt-oss renormalize their top-k gates (their HF
            # configs have no such key); DeepSeek carries the flag explicitly
            norm_topk_prob=d.get("norm_topk_prob",
                                 "mixtral" in arch or is_gpt_oss),
            routed_scaling_factor=d.get("routed_scaling_factor", 1.0),
            n_group=d.get("n_group", 1) or 1,
            topk_group=d.get("topk_group", 1) or 1,
            kv_lora_rank=d.get("kv_lora_rank", 0) if mla else 0,
            q_lora_rank=d.get("q_lora_rank") if mla else None,
            qk_nope_head_dim=d.get("qk_nope_head_dim", 128),
            qk_rope_head_dim=d.get("qk_rope_head_dim", 64),
            v_head_dim=d.get("v_head_dim", 128),
            qkv_bias=("qwen2" in arch
                      or (is_gpt_oss and d.get("attention_bias", True))),
            qk_norm="qwen3" in arch,
            o_bias=is_gpt_oss and d.get("attention_bias", True),
            layer_windows=layer_windows,
            attention_sinks=is_gpt_oss,
            moe_activation="swiglu_oss" if is_gpt_oss else "swiglu",
            router_logit_bias=is_gpt_oss,
            # qwen2 writes sliding_window but gates it behind
            # use_sliding_window, whose HF default is False; mistral-style
            # configs apply the window unconditionally; gpt-oss windows are
            # per-layer (layer_windows above)
            sliding_window=(d.get("sliding_window")
                            if not is_gpt_oss
                            and d.get("use_sliding_window",
                                      "qwen2" not in arch) else None),
        )

    @staticmethod
    def from_pretrained(path: str) -> "ModelConfig":
        with open(os.path.join(path, "config.json")) as f:
            return ModelConfig.from_hf_config(json.load(f))

    # ---- canned architectures for tests / benches -------------------------

    @staticmethod
    def tiny(vocab_size: int = 256) -> "ModelConfig":
        return ModelConfig(
            vocab_size=vocab_size, hidden_size=64, intermediate_size=128,
            num_layers=2, num_heads=4, num_kv_heads=2, rope_theta=10000.0,
            max_position_embeddings=512, dtype="float32",
        )

    @staticmethod
    def llama3_8b() -> "ModelConfig":
        return ModelConfig(
            vocab_size=128256, hidden_size=4096, intermediate_size=14336,
            num_layers=32, num_heads=32, num_kv_heads=8, rope_theta=500000.0,
            max_position_embeddings=8192,
        )

    @staticmethod
    def llama3_70b() -> "ModelConfig":
        return ModelConfig(
            vocab_size=128256, hidden_size=8192, intermediate_size=28672,
            num_layers=80, num_heads=64, num_kv_heads=8, rope_theta=500000.0,
            max_position_embeddings=8192,
        )

    @staticmethod
    def llama3_1b() -> "ModelConfig":
        """Llama-3.2-1B shape — fits a single v5e chip comfortably in bf16."""
        return ModelConfig(
            vocab_size=128256, hidden_size=2048, intermediate_size=8192,
            num_layers=16, num_heads=32, num_kv_heads=8, head_dim=64,
            rope_theta=500000.0, max_position_embeddings=8192,
            tie_word_embeddings=True,
        )


@dataclass
class EngineArgs:
    """Engine runtime knobs (ref: vllm/args.py + mocker/protocols.rs:67-100)."""

    block_size: int = 16
    num_blocks: Optional[int] = None  # None = size from HBM budget
    max_num_seqs: int = 64
    max_num_batched_tokens: int = 2048
    max_model_len: int = 4096
    enable_prefix_caching: bool = True
    enable_chunked_prefill: bool = True
    watermark: float = 0.01
    # TPU-native:
    tp_size: int = 1  # tensor parallel (mesh "tp" axis)
    dp_size: int = 1  # batch shards inside one engine (mesh "dp" axis)
    #: pipeline stages (mesh "pp" axis, outermost): stage-sliced layer stack
    #: + GPipe microbatching (parallel/pipeline.py). Dense GQA families only;
    #: disables multi-step decode / spec decode / int8 KV for the engine.
    pp_size: int = 1
    kv_cache_memory_fraction: float = 0.6  # of free HBM, when num_blocks is None
    decode_batch_buckets: tuple = ()  # () = powers of two up to max_num_seqs
    prefill_buckets: tuple = ()  # () = powers of two up to max_num_batched_tokens
    #: packed-token buckets for the ragged step (docs/performance.md):
    #: prefill chunks and decode rows of a plan ride ONE packed token batch
    #: served by the ragged paged-attention path (ops/ragged_attention.py)
    #: — the engine's only step path. Compiled-signature count collapses to
    #: the token buckets below (R and W derive statically from T), warmup
    #: shrinks to a handful of traces, and the scheduler plans a token
    #: budget per step. () = powers of two from 8 up to
    #: max_num_batched_tokens
    ragged_token_buckets: tuple = ()
    use_pallas_attention: bool = False  # Pallas paged-attention kernel (TPU only)
    #: decode steps fused into one jitted call when only decode work exists
    #: (amortizes per-dispatch latency; tokens deliver in bursts of this size)
    multi_step_decode: int = 1
    #: depth-2 software pipelining of single-step decode: step N+1 is
    #: dispatched with step N's sampled tokens fed device-to-device, so the
    #: host copy + commit/emit of step N overlap step N+1's device time
    #: (engine._run_decode_pipelined). Applies when multi_step_decode == 1,
    #: no speculative decoding, single host. Greedy-invariant: emits exactly
    #: the tokens the serial loop would.
    pipeline_decode: bool = True
    #: AOT bucket warmup at startup (engine.warmup()): precompile the jitted
    #: step for every configured prefill/decode bucket so the first real
    #: request does not eat XLA compilation (the TTFT p95-vs-p50 cliff).
    #: Opt-in — warmup costs one compile per bucket up front.
    warmup_buckets: bool = False
    #: speculative decoding: draft up to this many tokens and verify them in
    #: ONE forward — greedy-invariant (identical tokens to plain decode).
    #: 0 = off. Applies to temperature-0 batches without logprobs; the
    #: reference delegates spec decode to its engines and reports it via
    #: SpecDecodeStats (kv_router/protocols.rs:48-84)
    speculative_tokens: int = 0
    #: how drafts are produced: "prompt_lookup" (n-gram match in the
    #: sequence's own history — free, shines on repetitive text) or
    #: "draft_layers" (layer-skip self-drafting: the first
    #: speculative_draft_layers layers + shared LM head run as the draft
    #: model — model.make_draft_fn; drafts every step, costs
    #: draft_layers/num_layers of a forward per drafted token)
    speculative_method: str = "prompt_lookup"
    #: layer count of the layer-skip draft model (speculative_method=
    #: "draft_layers"); must be in (0, num_layers)
    speculative_draft_layers: int = 0
    # KVBM tiers (0 = tier disabled; ref: block_manager.rs:62-75 G2/G3)
    kvbm_host_bytes: int = 0
    kvbm_disk_dir: Optional[str] = None
    kvbm_disk_bytes: int = 0
    #: preempt-to-swap: under KV pressure the scheduler swaps a victim's
    #: device pages to host DRAM (gather → host bundle, same value/packed
    #: quant format the G2 tier carries) and swaps them back before the
    #: sequence's next step, instead of releasing the blocks and
    #: re-prefilling from scratch. Recompute preemption remains the
    #: fallback when the host-byte budget is exhausted or a bundle is torn
    #: down. Disabled automatically under multi-host step replication.
    preempt_swap: bool = True
    #: host-byte budget for swapped-out KV. None = share the G2 tier's
    #: budget when kvbm_host_bytes > 0 (available swap bytes shrink as G2
    #: fills), else a standalone 1 GiB allowance.
    swap_host_bytes: Optional[int] = None
    #: publish one KV stored event per prefill CHUNK instead of one per
    #: request. Per-request batching is the default — per-chunk publishing
    #: measured 11% under the 70B fleet's stored-blocks/s requirement
    #: (docs/PERF_NOTES.md fleet_bench table: 47.3k vs 53k needed; per-
    #: request reaches 119.5k). None = read the DYN_KV_EVENT_PER_CHUNK
    #: env escape hatch (unset/0/false = batched).
    kv_event_per_chunk: Optional[bool] = None
    #: speculative-decode auto-disable: when the rolling measured gain over
    #: spec_gain_window verify dispatches stays < 1 (drafts cost more than
    #: they accept — BENCH_r05: accept 0.019, gain 0.729, a 27% slowdown
    #: with nothing turning it off), fall back to plain decode and re-probe
    #: after spec_reprobe_steps engine steps. 0 disables the governor.
    spec_gain_window: int = 64
    spec_reprobe_steps: int = 4096
    #: on-device weight quantization: None (model dtype) | "int8" (per-out-
    #: channel) | "int8-gN" / "int4-gN" (grouped, N along the contraction
    #: dim). Weights stay quantized in HBM; dequant rides the matmul
    #: (engine/quant.py). GGUF/MXFP4 checkpoints can also load pre-quantized
    #: (loader keeps native groups). Ref capability: FP8 70B recipe,
    #: recipes/llama-3-70b/vllm/disagg-single-node/deploy.yaml:21-86
    quantization: Optional[str] = None
    #: paged KV cache dtype: None/"auto" (model dtype) | "int8" (symmetric
    #: per-(slot, head) scales; ~2x KV capacity and half the decode kernel's
    #: HBM page traffic — engine/cache.py int8 notes). KV-capacity role of
    #: the reference's G1 tier (lib/llm/src/block_manager/). Not yet
    #: supported for MLA latent caches (falls back to model dtype).
    kv_cache_dtype: Optional[str] = None
    #: disagg KV transfer: offer direct device-to-device page pulls
    #: (same-process registry / jax.experimental.transfer over ICI) when the
    #: decode worker advertises reach — the NIXL analog (disagg/transfer.py).
    #: False = always host-staged bundles over the response plane.
    kv_transfer_direct: bool = True
    #: layer-interleaved disagg transfer (docs/disagg.md): the TAIL chunk's
    #: bundle — the one whole-bundle transfer serializes after prefill
    #: completes — is split into this many layer groups and streamed as the
    #: gathers land, so early layers' wire/scatter overlaps later layers'
    #: host staging and decode's first step launches before the last layer
    #: arrives. Capability-negotiated per request (``kv_layers``); clamped
    #: to the model's layer count. <= 1 restores whole-bundle tails.
    kv_transfer_layer_groups: int = 4
    #: multi-tenant QoS scheduling (docs/qos.md): per-class waiting queues
    #: drained by weighted-fair virtual token counters, class-aware
    #: preemption victims, aging. With one tenant/class the drain order is
    #: exact FIFO, so this default changes nothing for untagged traffic;
    #: False restores the flat FIFO drain/victim order (bench baseline) —
    #: the swap-in starvation guard (head-of-line skip-ahead after
    #: repeated failed reservations) stays active in both modes, it is a
    #: bugfix to the swap tier, not a QoS policy.
    qos_scheduling: bool = True
    #: QoS policy override (dynamo_tpu.qos.QosConfig); None = load from the
    #: DYN_QOS_* environment at scheduler construction
    qos: Optional[object] = None
    #: structured decoding (docs/structured.md): compile guided-decoding
    #: constraints into dense device tables and run the FSM inside the
    #: sampling dispatch, so constrained rows ride the ragged step, the
    #: pipelined decode loop, the fused multi-step burst, and spec decode
    #: with no host sync. False (--no-structured-device) keeps every
    #: constraint on the host-oracle path (the pre-PR behavior). Also
    #: gated by DYN_STRUCTURED=0 at runtime.
    structured_device: bool = True
    #: byte budget (MiB) for the device FSM arena (mask bitmask + next-
    #: state tables; the next table costs 4·vocab bytes per state). None =
    #: DYN_STRUCTURED_TABLE_MB, default 64. Constraints whose reachable
    #: state closure does not fit fall back to the host oracle.
    structured_table_mb: Optional[float] = None
    seed: int = 0

    def __post_init__(self):
        if self.speculative_method not in ("prompt_lookup", "draft_layers"):
            raise ValueError(
                f"speculative_method={self.speculative_method!r} unknown "
                "(prompt_lookup or draft_layers)")
        if (self.speculative_method == "draft_layers"
                and self.speculative_tokens > 0
                and self.speculative_draft_layers < 1):
            raise ValueError("speculative_method='draft_layers' needs "
                             "speculative_draft_layers >= 1")
        if self.kv_event_per_chunk is None:
            self.kv_event_per_chunk = os.environ.get(
                "DYN_KV_EVENT_PER_CHUNK", "").lower() not in ("", "0", "false")
        if self.kv_cache_dtype not in (None, "auto", "int8"):
            # an unknown value silently serving full-precision would run a
            # deployment at half its planned KV capacity — fail loudly
            raise ValueError(
                f"kv_cache_dtype={self.kv_cache_dtype!r} not supported "
                "(None/'auto' = model dtype, or 'int8')")
        if self.quantization is not None:
            # validate the spec HERE, not at weight-load time deep in the
            # loader: int4 without grouping and unknown "-gN" grammars must
            # surface as a field-named config error, not a raw traceback
            # mid-initialization
            from dynamo_tpu.engine.quant import parse_spec
            try:
                parse_spec(self.quantization)
            except ValueError as e:
                raise ValueError(
                    f"quantization={self.quantization!r} invalid: {e}"
                ) from None
        if not self.decode_batch_buckets:
            b = [2**i for i in range(0, max(1, self.max_num_seqs).bit_length())
                 if 2**i <= self.max_num_seqs] or [1]
            if b[-1] < self.max_num_seqs:  # non-power-of-two max must be covered
                b.append(self.max_num_seqs)
            self.decode_batch_buckets = tuple(b)
        if not self.prefill_buckets:
            lo = self.block_size.bit_length()
            hi = self.max_num_batched_tokens.bit_length()
            b = [2**i for i in range(lo - 1, hi) if 2**i <= self.max_num_batched_tokens]
            b = [x for x in b if x >= self.block_size] or [self.block_size]
            if b[-1] < self.max_num_batched_tokens:
                b.append(self.max_num_batched_tokens)
            self.prefill_buckets = tuple(b)
        if not self.ragged_token_buckets:
            cap = max(8, self.max_num_batched_tokens)
            b = [2**i for i in range(3, cap.bit_length()) if 2**i <= cap]
            if b[-1] < cap:  # non-power-of-two budget must be covered
                b.append(cap)
            self.ragged_token_buckets = tuple(b)

    @property
    def max_blocks_per_seq(self) -> int:
        return math.ceil(self.max_model_len / self.block_size)

    def bucket_tokens(self, n: int) -> int:
        for b in self.prefill_buckets:
            if n <= b:
                return b
        return self.prefill_buckets[-1]

    def bucket_batch(self, n: int) -> int:
        for b in self.decode_batch_buckets:
            if n <= b:
                return b
        return self.decode_batch_buckets[-1]

    def bucket_ragged_tokens(self, n: int) -> int:
        """Packed-token bucket for a ragged step of ``n`` real tokens."""
        for b in self.ragged_token_buckets:
            if n <= b:
                return b
        return self.ragged_token_buckets[-1]

    def ragged_rows(self, t_bucket: int) -> int:
        """Row count of the ragged step's metadata arrays — derived
        STATICALLY from the token bucket (each row holds ≥ 1 token), so the
        compiled signature is keyed by T alone."""
        return max(1, min(self.max_num_seqs, t_bucket))

    def bucket_table_width(self, max_kv_len: int) -> int:
        """Block-table width bucket (powers of two) for a batch's longest kv."""
        need = math.ceil(max(1, max_kv_len) / self.block_size)
        w = 1
        while w < need:
            w *= 2
        return min(w, self.max_blocks_per_seq) if self.max_blocks_per_seq >= need else need

    def replace(self, **kw) -> "EngineArgs":
        return dataclasses.replace(self, **kw)
