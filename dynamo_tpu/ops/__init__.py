"""TPU Pallas kernels for the hot ops.

The reference's single CUDA kernel is a paged-KV block copy
(ref: lib/llm/src/kernels/block_copy.cu:40); its engines' paged attention
lives in vLLM. Here both are native: a paged-attention decode kernel and a
block gather/scatter copy kernel, each with an XLA fallback so every code
path also runs on CPU (interpret mode covers kernel tests in CI).
"""

from dynamo_tpu.ops.paged_attention import paged_attention_decode
from dynamo_tpu.ops.block_copy import gather_blocks, scatter_blocks

__all__ = ["paged_attention_decode", "gather_blocks", "scatter_blocks"]
