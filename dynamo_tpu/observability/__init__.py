"""Distributed request tracing + request-lifecycle SLO metrics + the
per-step fleet flight recorder.

Span/Tracer recorder keyed by the runtime's existing W3C trace ids
(tracing.py, with DYN_TRACE_SAMPLE head-sampling), cross-process stitching
over the control plane (collector.py), the per-worker step flight recorder
with anomaly tagging + fleet fan-out (flight.py), and the env-gated
jax.profiler correlation hook (profiler.py).
See docs/observability.md.
"""

from dynamo_tpu.observability.tracing import (
    CURRENT_SPAN,
    Span,
    Tracer,
    configure_tracer,
    get_tracer,
    parse_traceparent,
    stitch,
    trace_sample_rate,
    trace_sampled,
)
from dynamo_tpu.observability.collector import (
    TRACER_PREFIX,
    ensure_trace_endpoint,
    fetch_trace,
    serve_traces,
)
from dynamo_tpu.observability.flight import (
    FLIGHT_PREFIX,
    FlightRecorder,
    StepRecord,
    ensure_flight_endpoint,
    fetch_fleet_steps,
    flight_enabled,
    flight_instance,
    register_recorder,
    serve_flight,
)
from dynamo_tpu.observability.attribution import (
    BUCKETS,
    SloBurnTracker,
    attribute,
    gather_attribution,
)
from dynamo_tpu.observability.stats import histogram_quantile, quantile
from dynamo_tpu.observability.kvaudit import (
    KV_AUDIT_SUSPECT_SUBJECT,
    KV_DIGEST_PREFIX,
    AuditConfig,
    KvAuditor,
    WorkerKvLedger,
    fetch_kv_chain,
    fetch_kv_digest,
    list_digest_workers,
    serve_kv_digest,
)

__all__ = [
    "CURRENT_SPAN", "Span", "Tracer", "configure_tracer", "get_tracer",
    "parse_traceparent", "stitch", "trace_sample_rate", "trace_sampled",
    "TRACER_PREFIX", "ensure_trace_endpoint", "fetch_trace", "serve_traces",
    "FLIGHT_PREFIX", "FlightRecorder", "StepRecord",
    "ensure_flight_endpoint", "fetch_fleet_steps", "flight_enabled",
    "flight_instance", "register_recorder", "serve_flight",
    "BUCKETS", "SloBurnTracker", "attribute", "gather_attribution",
    "histogram_quantile", "quantile",
    "KV_AUDIT_SUSPECT_SUBJECT", "KV_DIGEST_PREFIX", "AuditConfig",
    "KvAuditor", "WorkerKvLedger", "fetch_kv_chain", "fetch_kv_digest",
    "list_digest_workers", "serve_kv_digest",
]
