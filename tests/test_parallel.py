"""parallel/: mesh construction + ring-attention numerics vs dense reference.

Runs on the virtual 8-device CPU mesh (conftest.py) — the same validation
path the driver's dryrun uses for multi-chip shardings.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.parallel import MeshConfig, make_mesh, ring_attention_sharded


def dense_attention(q, k, v, causal=True, kv_len=None):
    """Reference: plain masked attention, GQA-aware. q:[B,S,H,hd] k/v:[B,S,KV,hd]."""
    B, S, H, hd = q.shape
    KV = k.shape[2]
    G = H // KV
    qg = q.reshape(B, S, KV, G, hd).astype(jnp.float32)
    s = jnp.einsum("bskgd,btkd->bkgst", qg, k.astype(jnp.float32)) / np.sqrt(hd)
    pos = jnp.arange(S)
    mask = jnp.ones((S, S), bool)
    if causal:
        mask = mask & (pos[None, :] <= pos[:, None])
    if kv_len is not None:
        mask = mask & (pos[None, :] < kv_len)
    s = jnp.where(mask[None, None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bkgst,btkd->bskgd", p, v.astype(jnp.float32))
    return o.reshape(B, S, H, hd).astype(q.dtype)


def _qkv(key, B=2, S=64, H=4, KV=2, hd=16, dtype=jnp.float32):
    kq, kk, kv_ = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, S, H, hd), dtype)
    k = jax.random.normal(kk, (B, S, KV, hd), dtype)
    v = jax.random.normal(kv_, (B, S, KV, hd), dtype)
    return q, k, v


def test_mesh_config_infer():
    cfg = MeshConfig.for_devices(8, sp=2, dp=2)
    assert (cfg.dp, cfg.sp, cfg.tp) == (2, 2, 2)
    cfg = MeshConfig.for_devices(8)
    assert (cfg.dp, cfg.sp, cfg.tp) == (1, 1, 8)
    with pytest.raises(ValueError):
        MeshConfig.for_devices(8, tp=3)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_dense(causal):
    mesh = make_mesh(MeshConfig(dp=1, sp=8, tp=1))
    q, k, v = _qkv(jax.random.key(0))
    want = dense_attention(q, k, v, causal=causal)
    got = ring_attention_sharded(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_ring_attention_kv_len_padding():
    mesh = make_mesh(MeshConfig(dp=1, sp=4, tp=1))
    q, k, v = _qkv(jax.random.key(1), S=32)
    want = dense_attention(q, k, v, causal=True, kv_len=20)
    got = ring_attention_sharded(q, k, v, mesh, causal=True, kv_len=20)
    # only the first kv_len query rows are meaningful
    np.testing.assert_allclose(np.asarray(got)[:, :20], np.asarray(want)[:, :20],
                               atol=2e-5, rtol=2e-5)


def test_ring_attention_on_submesh_with_dp_tp():
    """sp ring composes with dp/tp axes present in the same mesh."""
    mesh = make_mesh(MeshConfig(dp=2, sp=2, tp=2))
    q, k, v = _qkv(jax.random.key(2), B=2, S=32, H=4, KV=4)
    want = dense_attention(q, k, v)
    got = ring_attention_sharded(q, k, v, mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)
