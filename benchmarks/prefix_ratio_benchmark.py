"""Prefix-ratio router benchmark: measure KV-aware routing's TTFT win.

ref: benchmarks/router/prefix_ratio_benchmark.py:1-447 — requests share a
common prefix with probability ``--prefix-ratio``; with KV-aware routing,
shared-prefix requests should land on workers already holding the prefix
blocks (higher cache-hit rate, lower TTFT) vs. round-robin.

Usage: python -m benchmarks.prefix_ratio_benchmark --url http://... \
           --model demo --prefix-ratio 0.8
"""

from __future__ import annotations

import argparse
import asyncio
import json
import random

import aiohttp

from benchmarks.client import make_prompt, stream_request, summarize


async def amain():
    ap = argparse.ArgumentParser(description="prefix-ratio routing benchmark")
    ap.add_argument("--url", default="http://127.0.0.1:8000")
    ap.add_argument("--model", required=True)
    ap.add_argument("--num-requests", type=int, default=64)
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--prefix-ratio", type=float, default=0.5,
                    help="fraction of requests sharing the common prefix")
    ap.add_argument("--prefix-words", type=int, default=256)
    ap.add_argument("--unique-words", type=int, default=64)
    ap.add_argument("--osl", type=int, default=32)
    ap.add_argument("--seed", type=int, default=0)
    cli = ap.parse_args()

    rng = random.Random(cli.seed)
    shared_prefix = make_prompt(rng, cli.prefix_words)
    prompts = []
    for _ in range(cli.num_requests):
        if rng.random() < cli.prefix_ratio:
            prompts.append(shared_prefix + " " +
                           make_prompt(rng, cli.unique_words))
        else:
            prompts.append(make_prompt(rng, cli.prefix_words + cli.unique_words))

    q: asyncio.Queue = asyncio.Queue()
    for p in prompts:
        q.put_nowait(p)
    results = []
    async with aiohttp.ClientSession() as session:
        async def worker():
            while True:
                try:
                    p = q.get_nowait()
                except asyncio.QueueEmpty:
                    return
                results.append(await stream_request(
                    session, cli.url, cli.model, p, cli.osl))

        await asyncio.gather(*(worker() for _ in range(cli.concurrency)))

    print(json.dumps({"prefix_ratio": cli.prefix_ratio, **summarize(results)}))


if __name__ == "__main__":
    asyncio.run(amain())
