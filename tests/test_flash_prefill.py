"""Flash-prefill kernel: numerics vs a dense numpy oracle, plus the
shard_map-wrapped Pallas paths (decode + prefill) on the virtual 8-CPU mesh.

The XLA CPU backend in this image emulates MXU bf16 matmul precision, so the
oracle is plain numpy (exact f32) and the kernel runs its f32
Precision.HIGHEST path — mismatches surface at 1e-5, not inside bf16 noise.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dynamo_tpu.engine.config import ModelConfig
from dynamo_tpu.ops.flash_prefill import flash_prefill, flash_prefill_paged


def dense_oracle(q, k, v, pos_base, kv_lens, window=None):
    B, S, H, hd = q.shape
    T, KV = k.shape[1], k.shape[2]
    G = H // KV
    out = np.zeros_like(q)
    for b in range(B):
        for s in range(S):
            qpos = pos_base[b] + s
            for h in range(H):
                g = h // G
                sc = (q[b, s, h] @ k[b, :, g].T) / np.sqrt(hd)
                mask = (np.arange(T) <= qpos) & (np.arange(T) < kv_lens[b])
                if window:
                    mask &= np.arange(T) > qpos - window
                if not mask.any():
                    continue
                sc = np.where(mask, sc, -1e30)
                p = np.exp(sc - sc.max())
                p /= p.sum()
                out[b, s, h] = p @ v[b, :, g]
    return out


def make_inputs(B=2, S=24, H=8, KV=2, hd=64, T=64, seed=0):
    rng = np.random.RandomState(seed)
    q = rng.randn(B, S, H, hd).astype(np.float32)
    k = rng.randn(B, T, KV, hd).astype(np.float32)
    v = rng.randn(B, T, KV, hd).astype(np.float32)
    pos_base = np.array([30, 0][:B], np.int32)
    kv_lens = np.array([54, 10][:B], np.int32)
    return q, k, v, pos_base, kv_lens


def test_flash_prefill_vs_oracle():
    q, k, v, pos_base, kv_lens = make_inputs()
    got = np.asarray(flash_prefill(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
        jnp.asarray(pos_base), jnp.asarray(kv_lens), interpret=True))
    want = dense_oracle(q, k, v, pos_base, kv_lens)
    # rows past kv_len are padding
    np.testing.assert_allclose(got[0], want[0], atol=1e-5)
    np.testing.assert_allclose(got[1, :10], want[1, :10], atol=1e-5)


def test_flash_prefill_sliding_window():
    q, k, v, pos_base, kv_lens = make_inputs()
    got = np.asarray(flash_prefill(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
        jnp.asarray(pos_base), jnp.asarray(kv_lens),
        sliding_window=16, interpret=True))
    want = dense_oracle(q, k, v, pos_base, kv_lens, window=16)
    np.testing.assert_allclose(got[0], want[0], atol=1e-5)


@pytest.mark.parametrize("window", [None, 96])
def test_flash_prefill_multi_tile_accumulation(window):
    """T and S large enough to force several k/q tiles (online softmax
    corrections across tiles — and, with a window, the tile-liveness skip
    condition — are the error-prone parts)."""
    rng = np.random.RandomState(3)
    B, S, H, KV, hd, T = 1, 256, 4, 2, 64, 1024
    q = rng.randn(B, S, H, hd).astype(np.float32)
    k = rng.randn(B, T, KV, hd).astype(np.float32)
    v = rng.randn(B, T, KV, hd).astype(np.float32)
    pos_base = np.array([700], np.int32)
    kv_lens = np.array([956], np.int32)
    got = np.asarray(flash_prefill(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
        jnp.asarray(pos_base), jnp.asarray(kv_lens),
        sliding_window=window, interpret=True))
    want = dense_oracle(q, k, v, pos_base, kv_lens, window=window)
    np.testing.assert_allclose(got, want, atol=2e-5)


def test_paged_wrapper_gathers_right_layer():
    rng = np.random.RandomState(1)
    B, S, H, KV, hd, bs, W, L = 1, 16, 4, 2, 64, 8, 4, 3
    kc = rng.randn(L, 40 * bs, KV, hd).astype(np.float32)
    vc = rng.randn(L, 40 * bs, KV, hd).astype(np.float32)
    q = rng.randn(B, S, H, hd).astype(np.float32)
    bt = np.asarray([[5, 9, 2, 7]], np.int32)
    positions = np.arange(S, dtype=np.int32)[None]
    kv_lens = np.array([S], np.int32)
    got = np.asarray(flash_prefill_paged(
        jnp.asarray(q), jnp.asarray(kc), jnp.asarray(vc), jnp.int32(2),
        jnp.asarray(bt), jnp.asarray(positions), jnp.asarray(kv_lens),
        block_size=bs, interpret=True))
    slot_idx = (bt[:, :, None] * bs + np.arange(bs)[None, None]).reshape(B, -1)
    want = dense_oracle(q, kc[2][slot_idx], vc[2][slot_idx],
                        np.zeros(B, np.int32), kv_lens)
    np.testing.assert_allclose(got, want, atol=1e-5)


@pytest.mark.parametrize("which", ["decode", "prefill"])
def test_kernels_under_mesh_shard_map(which):
    """make_step_fn with a (dp=2, tp=2) mesh must take the Pallas path via
    shard_map and match the XLA-path output (r1 weakness: kernels were
    force-disabled whenever mesh was not None)."""
    from dynamo_tpu.engine import model as M
    from dynamo_tpu.parallel import MeshConfig, make_mesh

    # hd=64: decode kernel local KV·hd = 2·64 = 128 lanes; prefill kernel
    # needs hd % 64 == 0
    cfg = ModelConfig(vocab_size=128, hidden_size=8 * 64,
                      intermediate_size=2 * 8 * 64, num_layers=2,
                      num_heads=8, num_kv_heads=4, head_dim=64,
                      dtype="float32")
    mesh = make_mesh(MeshConfig(dp=2, sp=1, tp=2))
    key = jax.random.key(0)
    params = M.init_params(cfg, key, dtype=jnp.float32)

    L, bs, nb = cfg.num_layers, 8, 32
    B, W = 4, 4
    kc = jnp.zeros((L, nb * bs, cfg.num_kv_heads, cfg.head_dim), jnp.float32)
    vc = jnp.zeros_like(kc)
    rng = np.random.RandomState(0)
    if which == "decode":
        S = 1
        kv_lens = np.array([9, 17, 5, 25], np.int32)
        positions = (kv_lens - 1)[:, None].astype(np.int32)
    else:
        S = 16
        kv_lens = np.full((B,), S, np.int32)
        positions = np.tile(np.arange(S, dtype=np.int32), (B, 1))
    tokens = rng.randint(1, 128, size=(B, S)).astype(np.int32)
    bt = np.stack([rng.choice(np.arange(1, nb), W, replace=False)
                   for _ in range(B)]).astype(np.int32)
    slot_map = np.zeros((B, S), np.int32)
    for b in range(B):
        for i in range(S):
            pos = positions[b, i]
            slot_map[b, i] = bt[b, pos // bs] * bs + pos % bs
    last_idx = np.full((B,), S - 1, np.int32)

    def run(step_fn):
        # packed step layout (model.make_step_fn)
        ints3 = jnp.asarray(np.stack([tokens, positions, slot_map], axis=1))
        lens_last = jnp.asarray(np.stack([kv_lens, last_idx], axis=1))
        logits, kc2, vc2 = step_fn(
            params, ints3, lens_last, jnp.asarray(bt),
            jnp.array(kc), jnp.array(vc))
        return np.asarray(logits)

    use_pallas = which == "decode"
    use_flash = which == "prefill"
    fast = M.make_step_fn(cfg, bs, mesh=mesh, use_pallas=use_pallas,
                          use_flash_prefill=use_flash)
    slow = M.make_step_fn(cfg, bs, mesh=mesh, use_pallas=False,
                          use_flash_prefill=False)
    # sanity: the fast path actually resolved to a kernel
    dec, pre = M._resolve_kernel_flags(cfg, mesh, use_pallas, use_flash)
    if which == "decode":
        assert dec, "decode Pallas path did not engage under the mesh"
    else:
        assert pre, "flash prefill path did not engage under the mesh"
    np.testing.assert_allclose(run(fast), run(slow), atol=2e-2, rtol=2e-2)
