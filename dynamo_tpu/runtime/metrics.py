"""Prometheus-style metrics registry (no external deps).

Rebuild of the reference's hierarchical metrics registry (ref: lib/runtime/src/
metrics.rs, metrics/prometheus_names.rs): counters/gauges/histograms with
labels, auto-prefixed ``dynamo_*`` names, rendered in Prometheus text
exposition format at the frontend's ``/metrics``.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

_DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


def _escape_label_value(v) -> str:
    """Prometheus exposition escaping for label values: backslash, double
    quote, and newline must be escaped or the scrape output is corrupt
    (e.g. a model name containing ``"``)."""
    return (str(v).replace("\\", "\\\\")
            .replace('"', '\\"').replace("\n", "\\n"))


def _fmt_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _merge_callback_values(values: dict, callbacks: list, name: str,
                           base: Optional[dict] = None) -> dict:
    """Fold scrape-time callback samples into ``values`` (shared by Counter
    and Gauge render). Each callback returns dict[labels, value]; ``labels``
    is None (no labels) or a TUPLE of (name, value) pairs — a dict cannot
    key a dict. Keys must be None or ((name, value), ...) pairs — an
    iterable of anything else (e.g. a bare string, whose sort would
    silently yield characters) is a caller bug. ``base`` labels (the
    registry's default labels, e.g. the frontend replica id) merge under
    the callback's own labels."""
    for cb in callbacks:
        try:
            for labels, v in cb().items():
                d = {str(k): str(bv) for k, bv in (base or {}).items()}
                if labels is not None:
                    d.update((str(n), str(lv)) for n, lv in labels)
                values[tuple(sorted(d.items()))] = v
        except Exception:
            logging.getLogger("dynamo.metrics").exception(
                "metric %s scrape callback failed", name)
    return values


class Counter:
    def __init__(self, name: str, help_: str, base: Optional[dict] = None):
        self.name = name
        self.help = help_
        self._base = dict(base or {})
        self._values: dict[tuple, float] = {}
        self._callbacks: list = []
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0, **labels):
        key = tuple(sorted({**self._base, **labels}.items()))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def add_callback(self, fn):
        """fn() -> dict[labels, value] evaluated at scrape time (the
        _merge_callback_values contract, shared with Gauge). For monotonic
        totals OWNED elsewhere (e.g. the engine's swap/preempt counters) —
        the callback value replaces the stored sample so the series stays
        a true counter."""
        self._callbacks.append(fn)

    def render(self) -> str:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        values = _merge_callback_values(dict(self._values), self._callbacks,
                                        self.name, self._base)
        for key, v in sorted(values.items()):
            lines.append(f"{self.name}{_fmt_labels(dict(key))} {v}")
        return "\n".join(lines)


class Gauge:
    def __init__(self, name: str, help_: str, base: Optional[dict] = None):
        self.name = name
        self.help = help_
        self._base = dict(base or {})
        self._values: dict[tuple, float] = {}
        self._callbacks: list = []
        self._lock = threading.Lock()

    def set(self, value: float, **labels):
        key = tuple(sorted({**self._base, **labels}.items()))
        with self._lock:
            self._values[key] = value

    def remove(self, **labels):
        """Drop one labeled series (label-churn hygiene: a departed
        worker's gauge must leave /metrics, not linger as a 0-valued
        series forever — unbounded cardinality under fleet churn)."""
        key = tuple(sorted({**self._base, **labels}.items()))
        with self._lock:
            self._values.pop(key, None)

    def add_callback(self, fn):
        """fn() -> dict[labels, value] evaluated at scrape time (the
        _merge_callback_values contract, shared with Counter)."""
        self._callbacks.append(fn)

    def render(self) -> str:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} gauge"]
        values = _merge_callback_values(dict(self._values), self._callbacks,
                                        self.name, self._base)
        for key, v in sorted(values.items()):
            lines.append(f"{self.name}{_fmt_labels(dict(key))} {v}")
        return "\n".join(lines)


class Histogram:
    def __init__(self, name: str, help_: str, buckets=_DEFAULT_BUCKETS,
                 base: Optional[dict] = None):
        self.name = name
        self.help = help_
        self.buckets = tuple(buckets)
        self._base = dict(base or {})
        self._counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = {}
        self._lock = threading.Lock()

    def observe(self, value: float, **labels):
        key = tuple(sorted({**self._base, **labels}.items()))
        with self._lock:
            counts = self._counts.setdefault(key, [0] * (len(self.buckets) + 1))
            self._sums[key] = self._sums.get(key, 0.0) + value
            # per-bucket (non-cumulative) counts: render() cumulates.
            # Incrementing EVERY matching bucket here double-counted once
            # render added them up (le="1.0" could exceed the total count)
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
                    break
            counts[-1] += 1  # +Inf (total observations)

    def time(self, **labels):
        return _Timer(self, labels)

    def render(self) -> str:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        for key, counts in sorted(self._counts.items()):
            labels = dict(key)
            cum = 0
            for i, b in enumerate(self.buckets):
                cum += counts[i]
                lines.append(f'{self.name}_bucket{_fmt_labels({**labels, "le": str(b)})} {cum}')
            lines.append(f'{self.name}_bucket{_fmt_labels({**labels, "le": "+Inf"})} {counts[-1]}')
            lines.append(f"{self.name}_sum{_fmt_labels(labels)} {self._sums.get(key, 0.0)}")
            lines.append(f"{self.name}_count{_fmt_labels(labels)} {counts[-1]}")
        return "\n".join(lines)


class _Timer:
    def __init__(self, hist: Histogram, labels: dict):
        self._hist = hist
        self._labels = labels

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._hist.observe(time.perf_counter() - self._t0, **self._labels)


class MetricsRegistry:
    def __init__(self, prefix: str = "dynamo",
                 default_labels: Optional[dict] = None):
        self.prefix = prefix
        #: labels stamped on EVERY sample this registry records (e.g.
        #: ``{"replica": "fe-1"}`` in multi-frontend deployments, so a
        #: fleet scrape can sum per-replica series instead of letting
        #: identical label sets clobber each other). Empty by default —
        #: single-replica exposition stays byte-identical.
        self.default_labels = dict(default_labels or {})
        self._metrics: dict[str, object] = {}
        self._start = time.time()

    def counter(self, name: str, help_: str = "") -> Counter:
        full = f"{self.prefix}_{name}"
        if full not in self._metrics:
            self._metrics[full] = Counter(full, help_ or name,
                                          base=self.default_labels)
        return self._metrics[full]  # type: ignore[return-value]

    def gauge(self, name: str, help_: str = "") -> Gauge:
        full = f"{self.prefix}_{name}"
        if full not in self._metrics:
            self._metrics[full] = Gauge(full, help_ or name,
                                        base=self.default_labels)
        return self._metrics[full]  # type: ignore[return-value]

    def histogram(self, name: str, help_: str = "", buckets=_DEFAULT_BUCKETS) -> Histogram:
        full = f"{self.prefix}_{name}"
        if full not in self._metrics:
            self._metrics[full] = Histogram(full, help_ or name, buckets,
                                            base=self.default_labels)
        return self._metrics[full]  # type: ignore[return-value]

    def render(self) -> str:
        up = (f"# HELP {self.prefix}_uptime_seconds "
              f"Seconds since this registry was created\n"
              f"# TYPE {self.prefix}_uptime_seconds gauge\n"
              f"{self.prefix}_uptime_seconds {time.time() - self._start}")
        parts = [m.render() for m in self._metrics.values()]  # type: ignore[attr-defined]
        return "\n".join([up] + parts) + "\n"


def render_registries(*registries: "MetricsRegistry") -> str:
    """Render several registries as ONE exposition document.

    Prometheus forbids repeated ``# TYPE``/``# HELP`` headers for the same
    metric, which naturally happens when two registries share a prefix (the
    HTTP service's registry + the tracer's SLO registry both emit
    ``dynamo_uptime_seconds``). Headers after the first are dropped, and so
    are duplicate UNLABELED samples of an already-seen metric (the uptime
    case) — label-distinct series from different registries merge under the
    first header untouched.
    """
    seen_headers: set[tuple[str, str]] = set()
    seen_metrics: set[str] = set()
    out: list[str] = []
    for reg in registries:
        pending: set[str] = set()  # metric names this registry introduced
        for line in reg.render().splitlines():
            if line.startswith("# "):
                fields = line.split()
                if len(fields) < 3:
                    out.append(line)
                    continue
                kind, name = fields[1], fields[2]
                if (kind, name) in seen_headers:
                    continue
                seen_headers.add((kind, name))
                pending.add(name)
                out.append(line)
                continue
            if not line:
                continue
            name = line.split("{", 1)[0].split(" ", 1)[0]
            base = name
            for suf in ("_bucket", "_sum", "_count"):
                if base.endswith(suf):
                    base = base[: -len(suf)]
                    break
            if base in seen_metrics and base not in pending:
                # duplicate UNLABELED series from a later registry (e.g.
                # uptime, or an unlabeled histogram whose only label is the
                # synthetic ``le``) — emitting them twice makes Prometheus
                # reject the whole scrape
                if "{" not in line:
                    continue
                labels = line.split("{", 1)[1].rsplit("}", 1)[0]
                if all(p.startswith("le=")
                       for p in labels.split(",") if p):
                    continue
            out.append(line)
        seen_metrics |= pending
    return "\n".join(out) + "\n"
