"""Recorders: capture request/response streams and KV events to JSONL.

Rebuild of the reference's Recorder/KvRecorder (ref: lib/llm/src/
recorder.rs:26-667, kv_router/recorder.rs:1-134): every recorded line is
``{"ts": float, "kind": str, "data": ...}``; replay yields them back with
optional timing preservation — used for router benchmarks and postmortem
debugging.
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Any, AsyncIterator, Optional


class Recorder:
    """Append-only JSONL event recorder."""

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "a", encoding="utf-8")
        self._t0 = time.monotonic()

    def record(self, kind: str, data: Any) -> None:
        line = json.dumps({"ts": round(time.monotonic() - self._t0, 6),
                           "kind": kind, "data": data})
        self._f.write(line + "\n")

    def flush(self) -> None:
        self._f.flush()

    def close(self) -> None:
        self._f.close()

    async def wrap_stream(self, stream: AsyncIterator, kind: str = "response"
                          ) -> AsyncIterator:
        """Tee an async stream into the log."""
        async for item in stream:
            self.record(kind, item)
            yield item


def load_events(path: str) -> list[dict]:
    out = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


async def replay(path: str, speed: float = 0.0) -> AsyncIterator[dict]:
    """Yield recorded events; ``speed`` > 0 preserves inter-event timing
    scaled by 1/speed (2.0 = twice as fast), 0 = as fast as possible."""
    prev_ts: Optional[float] = None
    for ev in load_events(path):
        if speed > 0 and prev_ts is not None:
            delay = (ev["ts"] - prev_ts) / speed
            if delay > 0:
                await asyncio.sleep(delay)
        prev_ts = ev["ts"]
        yield ev


class KvRecorder:
    """Records RouterEvents from the kv_events stream for later replay."""

    def __init__(self, plane, path: str, stream: Optional[str] = None):
        from dynamo_tpu.router.protocols import KV_EVENTS_STREAM

        self.plane = plane
        self.recorder = Recorder(path)
        self.stream = stream or KV_EVENTS_STREAM
        self._task = None
        self._sub = None

    async def start(self) -> "KvRecorder":
        import msgpack

        self._sub = await self.plane.stream_subscribe(self.stream)

        async def loop():
            try:
                async for _seq, payload in self._sub:
                    self.recorder.record(
                        "kv_event", msgpack.unpackb(payload, raw=False))
            except asyncio.CancelledError:
                pass

        self._task = asyncio.get_running_loop().create_task(loop())
        return self

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
        if self._sub:
            await self._sub.cancel()
        self.recorder.flush()
        self.recorder.close()
