"""Multimodal runway: image parts → placeholders → encode worker →
embedding injection (ref surface: trtllm multimodal encode helper +
nixl_connect embedding transfer, SURVEY §2.6)."""

import asyncio

import numpy as np
import pytest

from dynamo_tpu.engine.config import EngineArgs, ModelConfig
from dynamo_tpu.engine.engine import AsyncJaxEngine
from dynamo_tpu.protocols import (
    PreprocessedRequest, SamplingOptions, StopConditions,
)

pytestmark = pytest.mark.anyio


def engine_args(**kw):
    d = dict(block_size=4, num_blocks=128, max_num_seqs=4,
             max_num_batched_tokens=64, max_model_len=256,
             prefill_buckets=(8, 16, 32, 64), decode_batch_buckets=(1, 2, 4))
    d.update(kw)
    return EngineArgs(**d)


def mm_req(prompt, embeds_segments, max_tokens=6):
    return PreprocessedRequest(
        model="t", token_ids=list(prompt),
        stop_conditions=StopConditions(max_tokens=max_tokens, ignore_eos=True),
        sampling_options=SamplingOptions(temperature=0.0),
        mm_embeds=embeds_segments)


async def collect(eng, req):
    toks = []
    async for out in eng.generate(req):
        toks.extend(out.token_ids)
    return toks


async def test_mm_embeds_change_output_deterministically():
    """Injected embeddings must change generation (vs placeholder tokens)
    and be deterministic for identical content."""
    cfg = ModelConfig.tiny()
    eng = AsyncJaxEngine(cfg, engine_args())
    D = cfg.hidden_size
    prompt = [5, 0, 0, 0, 0, 9, 11, 3]  # 4 placeholder positions
    rng = np.random.default_rng(1)
    emb_a = (rng.standard_normal((4, D)) * 0.05).tolist()
    emb_b = (rng.standard_normal((4, D)) * 0.05).tolist()

    plain = await collect(eng, mm_req(prompt, None))
    with_a1 = await collect(eng, mm_req(prompt, [{"start": 1, "embeds": emb_a}]))
    with_a2 = await collect(eng, mm_req(prompt, [{"start": 1, "embeds": emb_a}]))
    with_b = await collect(eng, mm_req(prompt, [{"start": 1, "embeds": emb_b}]))
    assert with_a1 == with_a2          # deterministic
    assert with_a1 != plain            # injection matters
    assert with_a1 != with_b           # content matters
    await eng.close()


async def test_mm_salts_prefix_cache():
    """Identical placeholder TOKENS with different images must not share
    prefix-cache blocks; the same image must."""
    cfg = ModelConfig.tiny()
    eng = AsyncJaxEngine(cfg, engine_args())
    D = cfg.hidden_size
    prompt = list(range(1, 17))  # 4 full blocks
    rng = np.random.default_rng(2)
    emb_a = (rng.standard_normal((4, D)) * 0.05).tolist()
    emb_b = (rng.standard_normal((4, D)) * 0.05).tolist()
    seg_a = [{"start": 0, "embeds": emb_a}]
    seg_b = [{"start": 0, "embeds": emb_b}]

    await collect(eng, mm_req(prompt, seg_a))
    base_hits = eng.scheduler.prefix_hit_tokens
    # same image again → prefix hit
    await collect(eng, mm_req(prompt, seg_a))
    assert eng.scheduler.prefix_hit_tokens > base_hits
    hits_after_same = eng.scheduler.prefix_hit_tokens
    # DIFFERENT image, same tokens → must NOT hit the cache
    await collect(eng, mm_req(prompt, seg_b))
    assert eng.scheduler.prefix_hit_tokens == hits_after_same
    await eng.close()


def test_preprocessor_expands_image_parts():
    """image_url content parts become placeholder runs + positioned refs."""
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.llm.pipeline import OpenAIPreprocessor
    from dynamo_tpu.llm.tokenizer import make_test_tokenizer
    from dynamo_tpu.protocols.openai import parse_chat_request

    tk = make_test_tokenizer()
    mdc = ModelDeploymentCard(display_name="t", eos_token_ids=[],
                              tokenizer_ref="test", mm_placeholder_tokens=4)
    pre = OpenAIPreprocessor(mdc, tk, None)
    parsed = parse_chat_request({
        "model": "t",
        "messages": [{"role": "user", "content": [
            {"type": "text", "text": "describe "},
            {"type": "image_url", "image_url": {"url": "img://cat"}},
            {"type": "text", "text": " and "},
            {"type": "image_url", "image_url": {"url": "img://dog"}},
        ]}],
        "max_tokens": 4,
    })
    req, _prompt = pre.preprocess(parsed)
    assert req.mm_refs is not None and len(req.mm_refs) == 2
    a, b = req.mm_refs
    assert a["ref"] == "img://cat" and b["ref"] == "img://dog"
    assert a["tokens"] == b["tokens"] == 4
    # placeholder runs of exactly 4 zeros sit at the recorded positions
    for seg in (a, b):
        s = seg["start"]
        assert req.token_ids[s:s + 4] == [0, 0, 0, 0]
    assert b["start"] >= a["start"] + 4
    assert req.mm_digest() is not None


async def test_encode_worker_resolution_e2e():
    """Full loop: encode worker serves embeddings; the decode handler
    resolves refs and generates — same ref twice gives identical output,
    different refs differ (StubEncoder is content-stable)."""
    from dynamo_tpu.disagg.handlers import DecodeWorkerHandler
    from dynamo_tpu.multimodal import EncodeWorker
    from dynamo_tpu.multimodal.encoder import ENCODE_COMPONENT
    from dynamo_tpu.runtime import DistributedRuntime

    rt = await DistributedRuntime.create()
    cfg = ModelConfig.tiny()
    eng = AsyncJaxEngine(cfg, engine_args())
    worker = await EncodeWorker(rt).start()
    client = await rt.namespace("dynamo").component(
        ENCODE_COMPONENT).endpoint("encode").client().start()
    handler = DecodeWorkerHandler(eng, mm_client=client)

    async def run(ref):
        req = PreprocessedRequest(
            model="t", token_ids=[5, 0, 0, 0, 0, 9, 11, 3],
            stop_conditions=StopConditions(max_tokens=5, ignore_eos=True),
            sampling_options=SamplingOptions(temperature=0.0),
            mm_refs=[{"start": 1, "ref": ref, "tokens": 4}])
        toks = []
        async for out in handler.generate(req.to_wire(), None):
            from dynamo_tpu.protocols import LLMEngineOutput
            o = LLMEngineOutput.from_wire(out)
            toks.extend(o.token_ids)
            assert o.finish_reason != "error", o.text
        return toks

    try:
        cat1 = await run("img://cat")
        cat2 = await run("img://cat")
        dog = await run("img://dog")
        assert cat1 == cat2
        assert cat1 != dog
    finally:
        await worker.stop()
        await eng.close()
        await rt.shutdown()


def test_sentinel_injection_is_neutralized():
    """User text containing literal NUL sentinels must not crash or alias
    image placement (security: forged '\\x00mmN\\x00' in a text part)."""
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.llm.pipeline import OpenAIPreprocessor
    from dynamo_tpu.llm.tokenizer import make_test_tokenizer
    from dynamo_tpu.protocols.openai import parse_chat_request

    tk = make_test_tokenizer()
    mdc = ModelDeploymentCard(display_name="t", eos_token_ids=[],
                              tokenizer_ref="test", mm_placeholder_tokens=4)
    pre = OpenAIPreprocessor(mdc, tk, None)
    parsed = parse_chat_request({
        "model": "t",
        "messages": [{"role": "user", "content": [
            {"type": "text", "text": "evil \x00mm7\x00 and \x00mm0\x00 text "},
            {"type": "image_url", "image_url": {"url": "img://real"}},
        ]}],
        "max_tokens": 4,
    })
    req, _ = pre.preprocess(parsed)  # must not raise
    assert len(req.mm_refs) == 1
    assert req.mm_refs[0]["ref"] == "img://real"


# ----------------------------------------------------- real ViT vision tower

def _tiny_clip(tmp_path):
    """Save a tiny random CLIPVisionModel checkpoint; returns (model, path)."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    from transformers import CLIPVisionConfig, CLIPVisionModel

    torch.manual_seed(0)
    hf_cfg = CLIPVisionConfig(
        hidden_size=32, intermediate_size=64, num_hidden_layers=3,
        num_attention_heads=4, image_size=16, patch_size=4)
    m = CLIPVisionModel(hf_cfg).eval()
    path = str(tmp_path / "clip")
    m.save_pretrained(path, safe_serialization=True)
    return m, path


def test_vit_golden_parity_vs_hf(tmp_path):
    """JAX ViT last_hidden_state vs transformers CLIPVisionModel — the
    conformance pattern of tests/test_parity.py applied to the tower."""
    torch = pytest.importorskip("torch")
    m, path = _tiny_clip(tmp_path)

    from dynamo_tpu.multimodal.vit import (
        VitConfig, load_clip_vision_params, vit_forward,
    )

    cfg = VitConfig.from_hf(path)
    assert cfg.num_patches == 16
    params = load_clip_vision_params(path)

    rng = np.random.RandomState(3)
    pixels = rng.randn(2, 16, 16, 3).astype(np.float32)
    with torch.no_grad():
        want = m(torch.tensor(pixels.transpose(0, 3, 1, 2))
                 ).last_hidden_state.numpy()
    import jax.numpy as jnp

    got = np.asarray(vit_forward(params, jnp.asarray(pixels), cfg=cfg))
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=1e-3)


def test_vit_encoder_projector_and_contract(tmp_path):
    """VitEncoder honors the (n_tokens, dim) contract: native shapes pass,
    a projector re-dims, mismatches fail loudly."""
    _, path = _tiny_clip(tmp_path)
    import jax.numpy as jnp

    from dynamo_tpu.multimodal.vit import VitEncoder

    enc = VitEncoder.from_pretrained(path)
    img = (np.random.RandomState(0).rand(16, 16, 3) * 255).astype(np.uint8)
    npy = str(tmp_path / "img.npy")
    np.save(npy, img)

    out = enc.encode(npy, enc.tokens_per_image, enc.output_dim)
    assert out.shape == (16, 32)
    # content-stable: the prefix-cache property the router relies on
    np.testing.assert_array_equal(
        out, enc.encode(npy, enc.tokens_per_image, enc.output_dim))

    with pytest.raises(ValueError, match="mismatch"):
        enc.encode(npy, 99, enc.output_dim)

    # llava-style projector maps the tower dim onto the LM's hidden size
    rng = np.random.RandomState(1)
    proj = {"w1": jnp.asarray(rng.randn(32, 24), jnp.float32) * 0.1,
            "b1": jnp.zeros((24,), jnp.float32),
            "w2": jnp.asarray(rng.randn(24, 64), jnp.float32) * 0.1,
            "b2": jnp.zeros((64,), jnp.float32)}
    enc2 = VitEncoder(enc.params, enc.cfg, projector=proj)
    out2 = enc2.encode(npy, enc2.tokens_per_image, 64)
    assert out2.shape == (16, 64)


async def test_vit_encode_worker_hidden_state_parity_e2e(tmp_path):
    """Image request through the FULL runway — encode worker (real ViT +
    projector) → response-plane transfer → decode handler injection — must
    deliver embeddings bit-identical to the tower's direct output, and the
    engine must generate from them (hidden-state parity e2e)."""
    _, path = _tiny_clip(tmp_path)
    import jax.numpy as jnp
    from PIL import Image

    from dynamo_tpu.disagg.handlers import DecodeWorkerHandler
    from dynamo_tpu.multimodal import EncodeWorker
    from dynamo_tpu.multimodal.encoder import ENCODE_COMPONENT
    from dynamo_tpu.multimodal.vit import VitEncoder
    from dynamo_tpu.runtime import DistributedRuntime

    cfg = ModelConfig.tiny()  # hidden_size 64
    rng = np.random.RandomState(1)
    proj = {"w1": jnp.asarray(rng.randn(32, 24), jnp.float32) * 0.1,
            "b1": jnp.zeros((24,), jnp.float32),
            "w2": jnp.asarray(rng.randn(24, cfg.hidden_size),
                              jnp.float32) * 0.1,
            "b2": jnp.zeros((cfg.hidden_size,), jnp.float32)}
    enc = VitEncoder(VitEncoder.from_pretrained(path).params,
                     VitEncoder.from_pretrained(path).cfg, projector=proj)

    png = str(tmp_path / "cat.png")
    Image.fromarray((np.random.RandomState(7).rand(20, 20, 3) * 255)
                    .astype(np.uint8)).save(png)
    want = enc.encode(png, enc.tokens_per_image, cfg.hidden_size)

    rt = await DistributedRuntime.create()
    eng = AsyncJaxEngine(cfg, engine_args())
    worker = await EncodeWorker(rt, encoder=enc).start()
    client = await rt.namespace("dynamo").component(
        ENCODE_COMPONENT).endpoint("encode").client().start()

    captured = {}
    orig_generate = eng.generate

    def spy_generate(req, ctx=None):
        if req.mm_embeds:
            captured["segs"] = req.mm_embeds
        return orig_generate(req, ctx)

    eng.generate = spy_generate
    handler = DecodeWorkerHandler(eng, mm_client=client)

    n = enc.tokens_per_image
    req = PreprocessedRequest(
        model="t", token_ids=[5] + [0] * n + [9, 11, 3],
        stop_conditions=StopConditions(max_tokens=4, ignore_eos=True),
        sampling_options=SamplingOptions(temperature=0.0),
        mm_refs=[{"start": 1, "ref": png, "tokens": n}])
    toks = []
    try:
        async for out in handler.generate(req.to_wire(), None):
            from dynamo_tpu.protocols import LLMEngineOutput
            o = LLMEngineOutput.from_wire(out)
            assert o.finish_reason != "error", o.text
            toks.extend(o.token_ids)
        assert len(toks) == 4
        seg = captured["segs"][0]
        got = np.asarray(seg["embeds"], np.float32)
        # transfer fidelity: what the engine injects IS the tower output
        np.testing.assert_allclose(got, want, atol=1e-6, rtol=1e-6)
    finally:
        await worker.stop()
        await eng.close()
        await rt.shutdown()
