"""Flagship fleet drive: the 70B-on-v5e-64 placement, everything on at once.

ROADMAP item 2's closing proof (ISSUE 16): instead of per-subsystem
tiny-cpu benches, ONE multihost-sim run instantiates the
``benchmarks/plan_70b.py`` placement — 2×TP8 prefill + 6×TP8 decode on a
v5e-64 — as a mocker fleet spawned by the process operator, with
DCN-class topology labels (prefill and decode pools on different slices
of one pod) and PLAN-derived step timings (``--decode-base-ms`` etc. from
the solved 17 ms roofline step), and drives one diurnal QoS-mixed cycle
through it with every plane live simultaneously:

- KV routing + the event-fed radix index (+ its auditor at a 2 s cadence
  so divergence from kills heals *within* the run),
- the autoscale controller + operator closed loop (scale up at the peak,
  back down overnight),
- seeded chaos ``worker.kill`` on the decode pool: ≥2 mid-decode deaths
  the fleet must absorb with ZERO lost tokens (migration + restarts),
- the frontend's attribution sampler (``DYN_ATTR_FEED_S``) feeding the
  scorecard's per-request reconciliation,
- the fleet scorecard (``observability/scorecard.py``) marking the
  diurnal phases and cross-checking every rollup against the frontend's
  own histograms,
- ``dynamo_hub_saturation_ratio{kind}`` live on /metrics, measured
  against the ceilings in docs/PERF_NOTES.md.

The drive is falsifiable end to end: it FAILS unless completion is 100%
with zero lost tokens, the autoscaler scaled up AND down, audit
divergence healed to zero with at least one heal, every scorecard check
passed, and the saturation gauge carried live rates.

Run standalone::

    python -m benchmarks.flagship_drive [--duration 40] [--scale 1.0] \
        [--json out.json]

or as the ``flagship`` bench phase (``bench.py --flagship``). The tier-1
smoke (tests/test_scorecard.py) runs a scaled-down bounded cycle.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import math
import os
import time
from typing import Optional

#: diurnal phase boundaries as fractions of the traffic window — each one
#: closes a scorecard phase card with its own falsifiability checks
PHASES = (("morning-ramp", 0.35), ("peak", 0.65), ("evening", 1.0))


def plan_timing_args(solved: dict) -> list[str]:
    """Mocker step-timing flags derived from the plan's solved roofline.

    The solved decode step (17 ms at the 217-seq max batch for
    tp8_wint4_kvint8) splits into a fixed dispatch cost and a per-sequence
    cost; prefill tokens cost the roofline-rate per token. The mocker then
    exhibits the PLAN's step economics instead of the generic tiny-model
    defaults."""
    step_ms = float(solved["step_ms_roofline"])
    max_batch = int(solved["max_batch_per_worker"])
    tok_s_worker = float(solved["tok_s_per_chip_roofline"]) * int(solved["tp"])
    return [
        "--decode-base-ms", f"{0.2 * step_ms:.4f}",
        "--decode-per-seq-ms", f"{0.8 * step_ms / max_batch:.5f}",
        "--prefill-base-ms", f"{step_ms:.4f}",
        "--prefill-per-token-ms", f"{1000.0 / tok_s_worker:.5f}",
    ]


async def drive(duration_s: float = 40.0, scale: float = 1.0,
                seed: int = 1234, kill_error: float = 0.0015,
                autoscale: bool = True) -> dict:
    """One full diurnal cycle at the (possibly scaled) 70B placement.

    ``scale`` shrinks the fleet for bounded smokes (0.5 → 1 prefill +
    3 decode); 1.0 is the flagship 2+6 placement. ``autoscale=False``
    pins the fleet (smoke mode: no controller, shorter run)."""
    import sys
    import tempfile

    import aiohttp
    import numpy as np
    import yaml

    from benchmarks.client import Mix, make_prompt, qos_headers, stream_request
    from benchmarks.plan_70b import placement
    from dynamo_tpu.deploy.operator import ProcessOperator
    from dynamo_tpu.frontend.http import HttpService
    from dynamo_tpu.llm.discovery import ModelManager, ModelWatcher
    from dynamo_tpu.runtime import DistributedRuntime
    from dynamo_tpu.runtime.control_plane import ControlPlaneServer

    plan = placement()
    MODEL = "llama3-70b-sim"
    OSL, ISL_WORDS = 24, 48
    n_prefill = max(1, round(plan["prefill"]["workers"] * scale))
    n_decode = max(2, round(plan["decode"]["workers"] * scale))
    min_decode = max(1, n_decode - 2)
    max_decode = n_decode + 2
    # traffic sine sized so the planner's claimed ~2 req/s per replica
    # demands more than n_decode at the peak and fewer at the trough
    base_rps = 0.9 * n_decode
    amp_rps = 0.8 * base_rps
    period = duration_s
    INT_TTFT_SLO_MS = 1500.0

    server = ControlPlaneServer(port=0)
    addr = await server.start()
    env_overrides = {
        "DYN_CONTROL_PLANE": addr,
        # audit cadence fast enough that kill-induced divergence heals
        # INSIDE the run (default 30 s would outlive the whole cycle)
        "DYN_KV_AUDIT_INTERVAL": "2",
        "DYN_KV_AUDIT_SETTLE": "0.1",
        # continuous attribution sampling feeds the scorecard's
        # per-request e2e reconciliation
        "DYN_ATTR_FEED_S": "0.5",
        # frontend + controller read the SAME SLO spec from env
        "DYN_SLO_INTERACTIVE_TTFT_P95_MS": str(INT_TTFT_SLO_MS),
        "DYN_SLO_INTERACTIVE_ITL_MS": "80",
        "DYN_SLO_STANDARD_TTFT_P95_MS": "6000",
        "DYN_SLO_STANDARD_ITL_MS": "120",
        "DYN_SLO_MIN_REPLICAS": str(min_decode),
        "DYN_SLO_MAX_REPLICAS": str(max_decode),
        "DYN_SLO_COOLDOWN_UP_S": "2",
        "DYN_SLO_COOLDOWN_DOWN_S": "6",
        "DYN_SLO_INTERVAL_S": "1",
        "DYN_SLO_PREDICTOR": "arima",
        "DYN_SLO_BACKLOG_PER_REPLICA": "3",
    }
    saved_env = {k: os.environ.get(k) for k in env_overrides}
    os.environ.update(env_overrides)

    tmp = tempfile.mkdtemp(prefix="flagship-drive-")
    spec_path = os.path.join(tmp, "graph.yaml")
    timing = plan_timing_args(plan["decode"])

    def worker_cmd(component: str) -> list[str]:
        return [
            sys.executable, "-m", "dynamo_tpu.mocker.main",
            "--model", MODEL, "--component", component,
            "--block-size", "16", "--num-gpu-blocks", "4096",
            "--max-num-seqs", "8",
            # wall-clock compression: plan step economics, sim'd faster
            # than real time so one diurnal cycle fits a bench budget
            "--speedup-ratio", "4.0",
            "--migration-limit", "50",
            *timing,
        ]

    common_env = {
        "DYN_CONTROL_PLANE": addr,
        "PYTHONPATH": os.pathsep.join(sys.path),
        "JAX_PLATFORMS": "cpu",
        "DYN_DRAIN_TIMEOUT": "8",
        "DYN_LOG": "warning",
        "DYN_TOPO_POD": "pod0",
    }
    services = {
        "prefill": {
            "replicas": n_prefill, "plannerRole": "prefill",
            "command": worker_cmd("prefill"),
            "env": {**common_env, "DYN_TOPO_SLICE": "v5e-64-pf",
                    "DYN_TOPO_HOST": "host-pf"},
        },
        "decode": {
            "replicas": n_decode, "plannerRole": "decode",
            "command": worker_cmd("decode"),
            # seeded mid-decode kills live in the DECODE pool: that is
            # where in-flight streams break and migration must absorb
            # ...plus seeded KV-event loss: dropped stored-block publishes
            # are invisible to the router's gap detection (lost BEFORE the
            # hub assigns a seq), so only the auditor's resync heals the
            # resulting divergence — the drive exercises that plane too
            "env": {**common_env, "DYN_TOPO_SLICE": "v5e-64-dec",
                    "DYN_TOPO_HOST": "host-dec",
                    "DYN_CHAOS": (f"worker.kill:error={kill_error};"
                                  "plane.publish:drop=0.02"),
                    "DYN_CHAOS_SEED": str(seed)},
        },
    }
    with open(spec_path, "w") as f:
        yaml.safe_dump({
            "apiVersion": "dynamo.tpu/v1alpha1",
            "kind": "DynamoGraphDeployment",
            "metadata": {"name": "flagship-drive"},
            "spec": {"services": services},
        }, f)

    rt = await DistributedRuntime.create()
    manager = ModelManager()
    watcher = service = operator = aggregator = runner = None
    controller = None
    results: list = []
    by_class: dict = {}
    metrics_scrapes = 0
    saturation_seen = False
    last_metrics_text = ""
    try:
        watcher = await ModelWatcher(rt, manager, router_mode="kv").start()
        service = HttpService(manager, port=0, runtime=rt)
        await service.start()
        operator = await ProcessOperator(
            spec_path, plane=rt.plane, tick_s=0.25, drain_timeout=10.0
        ).start()
        frontend_url = f"http://127.0.0.1:{service.port}"

        if autoscale:
            from dynamo_tpu.autoscale import (
                AutoscaleController, AutoscaleRunner, ObservationFuser,
                SloConfig, make_planner, plane_readiness,
            )
            from dynamo_tpu.planner.perf_interpolation import PerfInterpolator
            from dynamo_tpu.planner.prometheus import PrometheusMetricsSource
            from dynamo_tpu.planner.virtual_connector import VirtualConnector
            from dynamo_tpu.router.publisher import MetricsAggregator

            slo = SloConfig.load()
            # planner sweep claiming ~36 decode tok/s per replica at the
            # 80 ms ITL target (≈1.5 req/s at OSL 24): the sine's peak
            # (~9.7 req/s → 7 replicas) then demands well above the
            # min_decode floor and the overnight trough falls back to it.
            # no_correction: the mocker's wall-clock-compressed ITL would
            # otherwise feed the adaptive correction an absurdly fast
            # observation and inflate per-replica capacity past the sweep
            prefill_perf = PerfInterpolator([(1.0, 200.0), (2.0, 700.0),
                                             (4.0, 2500.0)])
            decode_perf = PerfInterpolator([(24.0, 20.0), (36.0, 80.0),
                                            (72.0, 400.0)])
            aggregator = await MetricsAggregator(
                rt.plane, stale_after_s=3.0).start()
            fuser = ObservationFuser(
                PrometheusMetricsSource(frontend_url), aggregator)
            planner = make_planner(slo, prefill_perf, decode_perf,
                                   min_prefill_replicas=n_prefill,
                                   max_prefill_replicas=n_prefill,
                                   no_correction=True)

            async def readiness():
                return await plane_readiness(rt.plane, "dynamo")

            controller = AutoscaleController(
                slo, planner, fuser, VirtualConnector(rt.plane),
                readiness=readiness, metrics=rt.metrics, plane=rt.plane)
            runner = await AutoscaleRunner(controller).start()

        for _ in range(300):  # fleet registered + model discovered
            if manager.list_models():
                break
            await asyncio.sleep(0.1)
        else:
            raise RuntimeError("mocker fleet never appeared in discovery")

        mix = Mix("interactive=0.5,standard=0.3,batch=0.2")
        rng = np.random.default_rng(seed)
        import random as _random

        prompt_rng = _random.Random(seed)
        inflight: set = set()
        phantom_injected = False

        def _inject_phantom() -> bool:
            """Plant the canonical INVISIBLE loss shape directly: stored
            adverts in the radix for blocks no worker holds (exactly what
            a removal event dropped before the hub assigned it a seq
            leaves behind). Gap detection can never see it — only the
            auditor's digest sweep — so injecting one mid-drive makes the
            heal gate deterministic instead of riding on the chaos drop
            happening to hit a KV event this particular run."""
            from dynamo_tpu.router.protocols import (
                KvCacheEvent, RouterEvent, StoredBlock,
            )
            sm = manager.get(MODEL)
            router = getattr(sm, "router", None) if sm else None
            indexer = getattr(router, "indexer", None)
            tree = getattr(indexer, "tree", None)
            if tree is None:
                return False
            live = [w for w, c in tree.worker_counts().items()
                    if w >= 0 and c > 0]
            if not live:
                return False
            blocks = [StoredBlock(block_hash=0x7E57_0000 + i,
                                  tokens_hash=0x7E57_1000 + i)
                      for i in range(6)]
            tree.apply_event(RouterEvent(
                live[0], KvCacheEvent.stored(0, None, blocks)))
            return True

        await service.scorecard.mark_phase(PHASES[0][0])
        phase_idx = 0
        t0 = time.monotonic()
        tail_budget = (3 * 6.0 + 12.0) if autoscale else 4.0
        async with aiohttp.ClientSession() as session:
            while (now := time.monotonic() - t0) < duration_s + tail_budget:
                # advance the diurnal phase markers (scorecard cards)
                while (phase_idx < len(PHASES) - 1
                       and now >= PHASES[phase_idx][1] * duration_s):
                    phase_idx += 1
                    await service.scorecard.mark_phase(PHASES[phase_idx][0])
                if phase_idx >= 2 and not phantom_injected:
                    # post-peak: the fleet is warm and advertising — seed
                    # the divergence the audit plane must detect and heal
                    # before the run's final snapshot
                    phantom_injected = _inject_phantom()
                if now < duration_s:
                    rate = max(0.1, base_rps + amp_rps * math.sin(
                        2 * math.pi * now / period - math.pi / 2))
                else:
                    if phase_idx == len(PHASES) - 1:
                        phase_idx += 1
                        await service.scorecard.mark_phase("overnight")
                    rate = 0.4
                    if (controller is not None
                            and controller.applied.decode_replicas
                            == min_decode
                            and operator._status()["services"]["decode"]
                            ["ready"] == min_decode):
                        break  # settled at the overnight floor
                    if controller is None:
                        break  # pinned fleet: no scale-down to wait for
                cls = mix.pick(prompt_rng)
                task = asyncio.get_running_loop().create_task(
                    stream_request(
                        session, frontend_url, MODEL,
                        make_prompt(prompt_rng, ISL_WORDS), OSL,
                        headers=qos_headers(None, cls)))
                inflight.add(task)

                def _done(t, cls=cls):
                    inflight.discard(t)
                    results.append(t.result())
                    by_class.setdefault(cls, []).append(t.result())

                task.add_done_callback(_done)
                # periodic /metrics scrape: keeps the saturation window
                # fed and proves the gauge is live DURING the drive
                if int(now * 2) > metrics_scrapes:
                    metrics_scrapes = int(now * 2)
                    try:
                        async with session.get(
                                f"{frontend_url}/metrics") as resp:
                            last_metrics_text = await resp.text()
                        if "dynamo_hub_saturation_ratio{" \
                                in last_metrics_text:
                            saturation_seen = True
                    except Exception:
                        pass
                await asyncio.sleep(float(rng.exponential(1.0 / rate)))
            if inflight:
                await asyncio.gather(*inflight, return_exceptions=True)
            # let the audit plane converge before the final snapshot: the
            # last kills/drops can leave divergence the auditor has
            # DETECTED but not yet resynced (heals land one cadence after
            # detection) — the gate is "healed to zero inside the run",
            # so grant it a few cycles, bounded
            for _ in range(40):
                div = sum(
                    sum((a.get("divergence_blocks") or {}).values())
                    for a in service.scorecard.audit_rollup().values())
                if div == 0:
                    break
                await asyncio.sleep(0.25)
            # close the final scorecard phase and pull the document + one
            # last /metrics scrape while the fleet is still up
            await service.scorecard.mark_phase(None)
            scorecard_doc = await service.scorecard.document()
            async with session.get(f"{frontend_url}/metrics") as resp:
                last_metrics_text = await resp.text()
            if "dynamo_hub_saturation_ratio{" in last_metrics_text:
                saturation_seen = True
        final_status = operator._status()
        hub_stats = await rt.plane.hub_stats() \
            if hasattr(rt.plane, "hub_stats") else {}
    finally:
        if runner is not None:
            await runner.stop()
        if aggregator is not None:
            await aggregator.stop()
        if operator is not None:
            await operator.stop()
        if service is not None:
            await service.stop()
        if watcher is not None:
            await watcher.stop()
        await rt.shutdown()
        await server.stop()
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    ok = [r for r in results if r.ok]
    lost_tokens = sum(OSL - r.completion_tokens for r in ok)
    if os.environ.get("DYN_DRIVE_DEBUG"):
        for r in ok:
            if r.completion_tokens != OSL:
                print(f"DRIVE_DEBUG short stream: usage={r.completion_tokens}"
                      f" chunks={r.tokens} err={r.error}", flush=True)
    int_res = by_class.get("interactive", [])
    int_ttfts = sorted(r.ttft_s for r in int_res if r.ttft_s is not None)
    int_p95 = (int_ttfts[max(0, math.ceil(0.95 * len(int_ttfts)) - 1)]
               if int_ttfts else None)
    restarts = sum(s.get("restarts", 0)
                   for s in final_status["services"].values())
    audit_now = scorecard_doc["now"]["audit"]
    divergence_end = sum(sum((a.get("divergence_blocks") or {}).values())
                         for a in audit_now.values())
    heals = sum(sum((a.get("heals_total") or {}).values())
                for a in audit_now.values())
    failed_checks = [c["name"] for c in scorecard_doc["checks"]
                     if not c["ok"]]
    for p in scorecard_doc["phases"]:
        failed_checks += [f"{p['phase']}:{c['name']}"
                          for c in p["checks"] if not c["ok"]]
    hub_now = scorecard_doc["now"]["hub"]
    events = (hub_stats or {}).get("events") or {}
    total_ev = sum(events.values()) or 1
    out = {
        "placement": {
            "combo": plan["combo"], "prefill_workers": n_prefill,
            "decode_workers": f"{min_decode}-{max_decode}",
            "scale": scale,
            "step_ms_roofline": plan["decode"]["step_ms_roofline"],
        },
        "workload": (f"sine {base_rps:.1f}±{amp_rps:.1f} req/s x "
                     f"{duration_s:.0f}s, OSL {OSL}, "
                     f"mix int/std/batch .5/.3/.2, "
                     f"chaos worker.kill:error={kill_error}"),
        "requests": len(results), "ok": len(ok),
        "failed": len(results) - len(ok),
        "lost_tokens": lost_tokens,
        "int_ttft_p95_ms": (round(int_p95 * 1000, 1)
                            if int_p95 is not None else None),
        "worker_restarts": restarts,
        "migrations": scorecard_doc["now"]["migrations"],
        "scale_ups": controller.scale_ups if controller else 0,
        "scale_downs": controller.scale_downs if controller else 0,
        "audit_divergence_end": divergence_end,
        "audit_heals": heals,
        "phantom_injected": phantom_injected,
        "scorecard_phases": len(scorecard_doc["phases"]),
        "scorecard_checks": len(scorecard_doc["checks"]) + sum(
            len(p["checks"]) for p in scorecard_doc["phases"]),
        "scorecard_failed_checks": failed_checks,
        "hub_rpc_per_s": (hub_now.get("rates") or {}).get("rpc"),
        "hub_blocks_per_s": (hub_now.get("rates") or {}).get("blocks"),
        "hub_saturation": hub_now.get("saturation"),
        "hub_event_mix": {k: round(v / total_ev, 4)
                          for k, v in sorted(events.items())},
        "saturation_gauge_live": saturation_seen,
        "scorecard": scorecard_doc,
    }
    gates = [
        out["failed"] == 0,
        lost_tokens == 0,
        divergence_end == 0,
        not failed_checks,
        out["scorecard_phases"] >= (4 if autoscale else 3),
        saturation_seen,
    ]
    if autoscale:
        gates += [
            restarts >= 2,          # ≥2 chaos kills absorbed
            phantom_injected,       # the seeded divergence went in...
            heals > 0,              # ...and the auditor healed it
            out["scale_ups"] >= 1 and out["scale_downs"] >= 1,
        ]
    out["flagship_ok"] = all(gates)
    return out


async def _spawn_frontend(idx: int, env: dict, timeout_s: float = 40.0):
    """Launch ``python -m dynamo_tpu.frontend.main`` as replica ``fe-<idx>``
    and wait for its FRONTEND_READY line. Returns (proc, port, drain_task);
    the drain task keeps consuming stdout so the pipe can never backpressure
    the child."""
    import sys

    debug = bool(os.environ.get("DYN_DRIVE_DEBUG"))
    proc = await asyncio.create_subprocess_exec(
        sys.executable, "-m", "dynamo_tpu.frontend.main",
        "--port", "0", "--replica-id", f"fe-{idx}", "--router-mode", "kv",
        env=env, stdout=asyncio.subprocess.PIPE,
        stderr=(None if debug else asyncio.subprocess.DEVNULL))
    port = None
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            line = await asyncio.wait_for(proc.stdout.readline(),
                                          deadline - time.monotonic())
        except asyncio.TimeoutError:
            break
        if not line:
            break
        text = line.decode(errors="replace").strip()
        if text.startswith("FRONTEND_READY"):
            port = int(text.rpartition("=")[2])
            break
    if port is None:
        proc.kill()
        raise RuntimeError(f"frontend fe-{idx} never became ready")

    async def _drain():
        while await proc.stdout.readline():
            pass

    return proc, port, asyncio.get_running_loop().create_task(_drain())


async def frontdoor_drive(duration_s: float = 30.0, seed: int = 1234,
                          n_frontends: int = 3) -> dict:
    """Front-door chaos leg (ISSUE 18, docs/robustness.md "Front door").

    N frontend REPLICA subprocesses share one hub-fed KV routing view over
    a primary+standby hub pair; a mocker fleet serves behind them. The
    client drives QoS-less traffic through ``stream_request_ha`` (all
    replica URLs, bounded retries). Mid-peak one frontend is SIGKILLed;
    shortly after, the hub PRIMARY dies and the standby promotes under
    live load. Falsifiable gates:

    - 100% client completion within the bounded retry budget, with zero
      lost and zero duplicated tokens (usage.completion_tokens == OSL
      exactly, every stream);
    - the surviving replicas' per-worker radix digests agree after settle
      (``/v1/kv/digest``), and each survivor force-resynced on the hub
      epoch change (the in-band epoch marker — no silent seq-continuity
      loss from the promoted standby);
    - zero leaked seqs/blocks on the workers once traffic stops (a worker
      still stepping an orphaned seq keeps publishing fresh metrics —
      idle-stale aggregation is the no-leak signal);
    - the KV auditor and the autoscale loop keep cycling AFTER promotion;
    - the dead replica ages out of the front-door listing while the
      survivors stay ready.
    """
    import sys

    import aiohttp
    import numpy as np
    import yaml

    from benchmarks.client import make_prompt, stream_request_ha
    from dynamo_tpu.deploy.operator import ProcessOperator
    from dynamo_tpu.runtime import DistributedRuntime, RemoteControlPlane
    from dynamo_tpu.runtime.control_plane import ControlPlaneServer

    MODEL = "llama3-ha-sim"
    OSL, ISL_WORDS = 16, 32
    # bounded failover budget: wide enough that a request landing exactly
    # on the frontend-kill + hub-promotion overlap can ride out the
    # reconnect window (attempt backoff spans ~7s), still a hard cap
    MAX_ATTEMPTS = 6
    n_prefill, n_decode = 1, 3

    primary = ControlPlaneServer(port=0)
    p_addr = await primary.start()
    standby = ControlPlaneServer(port=0, standby_of=p_addr,
                                 takeover_after=0.8, replicate_interval=0.1)
    s_addr = await standby.start()
    addrs = f"{p_addr},{s_addr}"

    env_overrides = {
        "DYN_CONTROL_PLANE": addrs,
        "DYN_LEASE_TTL": "2",
        "DYN_KV_AUDIT_INTERVAL": "2",
        "DYN_KV_AUDIT_SETTLE": "0.1",
        "DYN_SLO_MIN_REPLICAS": str(n_decode),
        "DYN_SLO_MAX_REPLICAS": str(n_decode),
        "DYN_SLO_INTERVAL_S": "1",
    }
    saved_env = {k: os.environ.get(k) for k in env_overrides}
    os.environ.update(env_overrides)

    import tempfile
    tmp = tempfile.mkdtemp(prefix="frontdoor-drive-")
    spec_path = os.path.join(tmp, "graph.yaml")

    def worker_cmd(component: str) -> list[str]:
        return [
            sys.executable, "-m", "dynamo_tpu.mocker.main",
            "--model", MODEL, "--component", component,
            "--block-size", "16", "--num-gpu-blocks", "2048",
            "--max-num-seqs", "8", "--speedup-ratio", "4.0",
            "--migration-limit", "50",
        ]

    common_env = {
        "DYN_CONTROL_PLANE": addrs,
        "PYTHONPATH": os.pathsep.join(sys.path),
        "JAX_PLATFORMS": "cpu",
        "DYN_LEASE_TTL": "2",
        "DYN_DRAIN_TIMEOUT": "8",
        "DYN_LOG": "warning",
    }
    with open(spec_path, "w") as f:
        yaml.safe_dump({
            "apiVersion": "dynamo.tpu/v1alpha1",
            "kind": "DynamoGraphDeployment",
            "metadata": {"name": "frontdoor-drive"},
            "spec": {"services": {
                "prefill": {"replicas": n_prefill, "plannerRole": "prefill",
                            "command": worker_cmd("prefill"),
                            "env": dict(common_env)},
                "decode": {"replicas": n_decode, "plannerRole": "decode",
                           "command": worker_cmd("decode"),
                           "env": dict(common_env)},
            }},
        }, f)

    rt = await DistributedRuntime.create(
        plane=await RemoteControlPlane(addrs).connect())
    operator = aggregator = runner = None
    fe_procs: list = []
    drains: list = []
    results: list = []
    promoted_at: Optional[float] = None
    ticks_at_promotion = 0
    audit_cycles_post = (0, 0)
    kill_idx = 1
    hub_killed = False
    try:
        from dynamo_tpu.autoscale import (
            AutoscaleController, AutoscaleRunner, ObservationFuser,
            SloConfig, make_planner, plane_readiness,
        )
        from dynamo_tpu.planner.perf_interpolation import PerfInterpolator
        from dynamo_tpu.planner.prometheus import MultiPrometheusSource
        from dynamo_tpu.planner.virtual_connector import VirtualConnector
        from dynamo_tpu.router.publisher import MetricsAggregator

        operator = await ProcessOperator(
            spec_path, plane=rt.plane, tick_s=0.25, drain_timeout=10.0
        ).start()

        fe_env = {**os.environ, **common_env, **env_overrides}
        for i in range(n_frontends):
            proc, port, drain = await _spawn_frontend(i, fe_env)
            fe_procs.append((proc, port))
            drains.append(drain)
        urls = [f"http://127.0.0.1:{p}" for _, p in fe_procs]

        aggregator = await MetricsAggregator(
            rt.plane, stale_after_s=3.0).start()
        # the autoscale loop rides the FLEET scrape (MultiPrometheusSource:
        # per-replica deltas summed, dead replicas dropping out) — pinned
        # replica bounds, so the gate is "the loop keeps ticking through
        # both kills", not a scaling decision
        fuser = ObservationFuser(MultiPrometheusSource(urls), aggregator)
        slo = SloConfig.load()
        planner = make_planner(
            slo, PerfInterpolator([(1.0, 200.0), (4.0, 2500.0)]),
            PerfInterpolator([(24.0, 20.0), (72.0, 400.0)]),
            min_prefill_replicas=n_prefill, max_prefill_replicas=n_prefill,
            no_correction=True)

        async def readiness():
            return await plane_readiness(rt.plane, "dynamo")

        controller = AutoscaleController(
            slo, planner, fuser, VirtualConnector(rt.plane),
            readiness=readiness, metrics=rt.metrics, plane=rt.plane)
        runner = await AutoscaleRunner(controller).start()

        async with aiohttp.ClientSession() as session:
            # every replica must discover the model before traffic starts
            for url in urls:
                for _ in range(300):
                    try:
                        async with session.get(f"{url}/v1/models") as r:
                            doc = await r.json()
                        if any(m.get("id") == MODEL
                               for m in doc.get("data", [])):
                            break
                    except Exception:
                        pass
                    await asyncio.sleep(0.1)
                else:
                    raise RuntimeError(f"{url} never discovered {MODEL}")

            rng = np.random.default_rng(seed)
            import random as _random
            prompt_rng = _random.Random(seed)
            inflight: set = set()
            issued = 0
            fe_killed = False
            t0 = time.monotonic()
            while (now := time.monotonic() - t0) < duration_s:
                if not fe_killed and now >= 0.40 * duration_s:
                    # SIGKILL one replica mid-peak: no drain, no goodbye —
                    # its in-flight streams break and must be retried by
                    # the client, its worker-side seqs cancelled by
                    # response-plane peer death
                    os.kill(fe_procs[kill_idx][0].pid, 9)
                    fe_killed = True
                if not hub_killed and now >= 0.55 * duration_s:
                    await primary.stop()  # standby promotes under load
                    hub_killed = True
                    ticks_at_promotion = fuser.ticks
                    promoted_at = now
                rate = max(0.5, 2.0 + 3.0 * math.sin(
                    math.pi * now / duration_s))
                task = asyncio.get_running_loop().create_task(
                    stream_request_ha(
                        session, urls, MODEL,
                        make_prompt(prompt_rng, ISL_WORDS), OSL,
                        max_attempts=MAX_ATTEMPTS, backoff_s=0.5,
                        start=issued))
                issued += 1
                inflight.add(task)
                task.add_done_callback(
                    lambda t: (inflight.discard(t),
                               results.append(t.result())))
                await asyncio.sleep(float(rng.exponential(1.0 / rate)))
            if inflight:
                await asyncio.gather(*inflight, return_exceptions=True)

            await _wait_for_async(lambda: not standby.is_standby,
                                  10.0, "standby promotion")

            survivors = [u for i, u in enumerate(urls) if i != kill_idx]

            async def _digest(url: str) -> Optional[dict]:
                try:
                    async with session.get(
                            f"{url}/v1/kv/digest",
                            timeout=aiohttp.ClientTimeout(total=3)) as r:
                        return await r.json()
                except Exception:
                    return None

            # settle: the survivors' per-worker radix digests must agree
            digests_agree = False
            resyncs_each: list = []
            last_docs: list = []
            for _ in range(60):
                docs = [await _digest(u) for u in survivors]
                last_docs = docs
                if all(d is not None for d in docs):
                    views = [d.get("models", {}).get(MODEL, {})
                             for d in docs]
                    if views[0] and all(v == views[0] for v in views[1:]):
                        digests_agree = True
                        resyncs_each = [
                            (d.get("cursors", {}).get(MODEL, {})
                             .get("resyncs_requested", 0)) for d in docs]
                        break
                await asyncio.sleep(0.25)
            if not digests_agree and os.environ.get("DYN_DRIVE_DEBUG"):
                print(f"DRIVE_DEBUG digests: {json.dumps(last_docs)}",
                      flush=True)

            # auditor continuing post-promotion: cycles advance
            async def _audit_cycles(url: str) -> int:
                try:
                    async with session.get(
                            f"{url}/v1/kv/audit",
                            timeout=aiohttp.ClientTimeout(total=3)) as r:
                        doc = await r.json()
                    return sum(int(m.get("cycles", 0))
                               for m in (doc.get("models") or doc).values()
                               if isinstance(m, dict))
                except Exception:
                    return -1

            c0 = await _audit_cycles(survivors[0])
            await asyncio.sleep(3.0)
            c1 = await _audit_cycles(survivors[0])
            audit_cycles_post = (c0, c1)

            # the dead replica's lease expires; survivors stay ready
            frontends_ready = -1
            fe_doc: dict = {}
            for _ in range(40):
                try:
                    async with session.get(
                            f"{survivors[0]}/v1/fleet/frontends") as r:
                        fe_doc = await r.json()
                    if fe_doc.get("count") == n_frontends - 1:
                        frontends_ready = fe_doc.get("ready", -1)
                        break
                except Exception:
                    pass
                await asyncio.sleep(0.25)
            if frontends_ready < 0 and os.environ.get("DYN_DRIVE_DEBUG"):
                print(f"DRIVE_DEBUG frontends: {json.dumps(fe_doc)}",
                      flush=True)

            # fleet scorecard's cross-replica convergence check, from a
            # survivor's own point of view
            radix_check_ok = None
            try:
                async with session.get(
                        f"{survivors[0]}/v1/fleet/scorecard") as r:
                    scorecard_doc = await r.json()
                for c in scorecard_doc.get("checks", []):
                    if c.get("name") == "radix_replica_agreement":
                        radix_check_ok = bool(c.get("ok"))
            except Exception:
                pass

            # no-leak settle: with traffic stopped, a worker still
            # stepping an orphaned seq keeps publishing fresh metrics —
            # after the stale window, any non-stale active/waiting slot IS
            # a leak
            await asyncio.sleep(4.0)
            agg = aggregator.aggregate()
            leaked_seqs = (agg["requests_active"] + agg["requests_waiting"]
                           if agg["workers"] else 0)
            leaked_blocks = agg["kv_active_blocks"] if agg["workers"] else 0
        ticks_end = fuser.ticks
        fe_rc = fe_procs[kill_idx][0].returncode
    finally:
        if runner is not None:
            await runner.stop()
        if aggregator is not None:
            await aggregator.stop()
        for proc, _ in fe_procs:
            if proc.returncode is None:
                proc.terminate()
        for proc, _ in fe_procs:
            if proc.returncode is None:
                try:
                    await asyncio.wait_for(proc.wait(), 15.0)
                except asyncio.TimeoutError:
                    proc.kill()
        for d in drains:
            d.cancel()
        if operator is not None:
            await operator.stop()
        await rt.shutdown()
        await standby.stop()
        if not hub_killed:
            await primary.stop()
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    ok = [r for r in results if r.ok]
    lost_tokens = sum(max(0, OSL - r.completion_tokens) for r in ok)
    dup_tokens = sum(max(0, r.completion_tokens - OSL) for r in ok)
    retried = [r for r in results if r.attempts > 1]
    errors: dict = {}
    for r in results:
        if not r.ok:
            key = (r.error or "?")[:80]
            errors[key] = errors.get(key, 0) + 1
    out = {
        "workload": (f"{len(results)} reqs over {duration_s:.0f}s, "
                     f"OSL {OSL}, {n_frontends} frontend replicas, "
                     f"fe-{kill_idx} SIGKILLed @40%, hub primary killed "
                     f"@55%"),
        "requests": len(results), "ok": len(ok),
        "failed": len(results) - len(ok),
        "failure_errors": errors,
        "retried": len(retried),
        "max_attempts_seen": max((r.attempts for r in results), default=0),
        "lost_tokens": lost_tokens,
        "dup_tokens": dup_tokens,
        "frontend_killed_rc": fe_rc,
        "hub_promoted": not standby.is_standby,
        "promoted_at_s": round(promoted_at, 2) if promoted_at else None,
        "digests_agree": digests_agree,
        "replica_resyncs": resyncs_each,
        "radix_check_ok": radix_check_ok,
        "frontends_ready_after": frontends_ready,
        "leaked_seqs": leaked_seqs,
        "leaked_blocks": leaked_blocks,
        "audit_cycles_post_promotion": list(audit_cycles_post),
        "autoscale_ticks_post_promotion": ticks_end - ticks_at_promotion,
    }
    gates = [
        out["failed"] == 0,
        len(retried) >= 1,                       # failover exercised
        out["max_attempts_seen"] <= MAX_ATTEMPTS,
        lost_tokens == 0 and dup_tokens == 0,
        out["hub_promoted"],
        digests_agree,
        all(r >= 1 for r in resyncs_each) and bool(resyncs_each),
        radix_check_ok is True,
        frontends_ready == n_frontends - 1,
        leaked_seqs == 0 and leaked_blocks == 0,
        audit_cycles_post[1] > audit_cycles_post[0] >= 0,
        out["autoscale_ticks_post_promotion"] >= 2,
    ]
    out["frontdoor_ok"] = all(gates)
    return out


async def _wait_for_async(predicate, timeout: float, msg: str):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        await asyncio.sleep(0.05)
    raise RuntimeError(f"timed out waiting for {msg}")


def main() -> None:
    from dynamo_tpu.runtime.config import setup_logging

    setup_logging()
    ap = argparse.ArgumentParser(
        description="flagship 70B-placement fleet drive (ISSUE 16)")
    ap.add_argument("--duration", type=float, default=40.0,
                    help="diurnal cycle seconds (default 40)")
    ap.add_argument("--scale", type=float, default=1.0,
                    help="fleet scale vs the 2+6 placement (default 1.0)")
    ap.add_argument("--seed", type=int, default=1234)
    ap.add_argument("--kill-error", type=float, default=0.0015,
                    help="per-step worker.kill probability on decode")
    ap.add_argument("--no-autoscale", action="store_true",
                    help="pin the fleet (bounded smoke mode)")
    ap.add_argument("--frontdoor", action="store_true",
                    help="run the front-door chaos leg instead (ISSUE 18: "
                         "3 frontend replicas, one SIGKILLed mid-peak, hub "
                         "primary killed once under live load)")
    ap.add_argument("--json", default=None, metavar="FILE",
                    help="also write the result document to FILE")
    cli = ap.parse_args()
    if cli.frontdoor:
        out = asyncio.run(frontdoor_drive(cli.duration, cli.seed))
        gate = out["frontdoor_ok"]
    else:
        out = asyncio.run(drive(cli.duration, cli.scale, cli.seed,
                                cli.kill_error,
                                autoscale=not cli.no_autoscale))
        gate = out["flagship_ok"]
    doc = json.dumps(out, indent=2, default=str)
    if cli.json:
        with open(cli.json, "w") as f:
            f.write(doc)
    # summary line without the full embedded scorecard
    slim = {k: v for k, v in out.items() if k != "scorecard"}
    print(json.dumps(slim, indent=2, default=str))
    raise SystemExit(0 if gate else 1)


if __name__ == "__main__":
    main()
