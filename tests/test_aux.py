"""Aux subsystems: canary health checks, recorders, metrics aggregation."""

import asyncio
import json

import pytest

from dynamo_tpu.llm.recorder import Recorder, KvRecorder, load_events, replay
from dynamo_tpu.runtime.health_check import HealthCheckConfig, HealthCheckManager

pytestmark = pytest.mark.anyio


class FakeClient:
    """Minimal Client surface for the health manager."""

    def __init__(self, healthy: set, all_ids):
        self.healthy = healthy
        self.ids = list(all_ids)
        self._down = set()

    def instance_ids(self):
        return list(self.ids)

    def report_instance_down(self, iid):
        self._down.add(iid)

    def report_instance_up(self, iid):
        self._down.discard(iid)

    async def generate(self, payload, mode="direct", instance_id=None):
        if instance_id not in self.healthy:
            raise RuntimeError("no responders")

        async def stream():
            yield {"ok": True}
        return stream()


async def test_health_check_marks_down_and_restores():
    client = FakeClient(healthy={1}, all_ids=[1, 2])
    cfg = HealthCheckConfig(check_interval_s=0.05, timeout_s=0.5,
                            failure_threshold=2)
    mgr = await HealthCheckManager(client, cfg).start()
    for _ in range(100):
        if 2 in client._down:
            break
        await asyncio.sleep(0.02)
    assert 2 in client._down and 1 not in client._down

    client.healthy.add(2)  # instance recovers → canary restores routing
    for _ in range(100):
        if 2 not in client._down:
            break
        await asyncio.sleep(0.02)
    assert 2 not in client._down
    await mgr.stop()


async def test_health_check_hung_stream_counts_as_failure():
    """A worker that accepts the canary but never yields must be marked down
    (timeout covers connect + first frame, not just obtaining the stream)."""

    class HangClient(FakeClient):
        async def generate(self, payload, mode="direct", instance_id=None):
            async def stream():
                await asyncio.sleep(3600)
                yield {}
            return stream()

    client = HangClient(healthy={1}, all_ids=[1])
    cfg = HealthCheckConfig(check_interval_s=0.05, timeout_s=0.1,
                            failure_threshold=2)
    mgr = await HealthCheckManager(client, cfg).start()
    for _ in range(100):
        if 1 in client._down:
            break
        await asyncio.sleep(0.02)
    assert 1 in client._down
    await mgr.stop()


async def test_default_canary_is_valid_request():
    """The default canary must parse as a real PreprocessedRequest and be
    servable by a real engine handler (ADVICE r1: {"health_check": true}
    failed from_wire on every probe)."""
    from dynamo_tpu.mocker.engine import MockEngine, MockEngineArgs
    from dynamo_tpu.protocols import PreprocessedRequest
    from dynamo_tpu.runtime.context import Context
    from dynamo_tpu.runtime.health_check import default_canary_payload

    payload = default_canary_payload()
    req = PreprocessedRequest.from_wire(payload)  # must not raise
    assert req.stop_conditions.max_tokens == 1

    engine = await MockEngine(MockEngineArgs()).start()
    got = []
    async for out in engine.generate(payload, Context()):
        got.append(out)
    assert got, "canary produced no frames from a real handler"
    await engine.stop()


async def test_recorder_roundtrip(tmp_path):
    path = str(tmp_path / "events.jsonl")
    r = Recorder(path)
    r.record("request", {"prompt": "hi"})
    r.record("response", {"token_ids": [1, 2]})
    r.flush()
    evs = load_events(path)
    assert [e["kind"] for e in evs] == ["request", "response"]
    got = []
    async for ev in replay(path):
        got.append(ev["data"])
    assert got[0] == {"prompt": "hi"}


async def test_kv_recorder_captures_stream(tmp_path):
    import msgpack

    from dynamo_tpu.router.protocols import KvCacheEvent, RouterEvent, StoredBlock
    from dynamo_tpu.runtime.control_plane import LocalControlPlane

    plane = LocalControlPlane()
    path = str(tmp_path / "kv.jsonl")
    rec = await KvRecorder(plane, path).start()
    ev = RouterEvent(7, KvCacheEvent.stored(
        1, None, [StoredBlock(block_hash=11, tokens_hash=22)]))
    await plane.stream_publish("kv_events", msgpack.packb(ev.to_wire()))
    for _ in range(50):
        await asyncio.sleep(0.01)
        rec.recorder.flush()
        if load_events(path):
            break
    await rec.stop()
    evs = load_events(path)
    assert evs and evs[0]["data"]["worker_id"] == 7


@pytest.mark.anyio
async def test_run_batch_entrypoint(tmp_path):
    """``run.py in=batch``: JSONL in → JSONL out through the full pipeline
    (ref: entrypoint/input.rs:32 batch mode)."""
    import asyncio
    import json
    import os
    import sys

    inp = tmp_path / "reqs.jsonl"
    outp = tmp_path / "resp.jsonl"
    reqs = [
        {"messages": [{"role": "user", "content": "hello world"}],
         "max_tokens": 4},
        {"prompt": "the quick brown fox", "max_tokens": 3},
        {"messages": [{"role": "user", "content": "tell me about tokens"}],
         "max_tokens": 2},
    ]
    inp.write_text("".join(json.dumps(r) + "\n" for r in reqs))

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PYTHONPATH=repo, JAX_PLATFORMS="cpu",
               DYN_LOG="warning")
    env.pop("DYN_CONTROL_PLANE", None)  # in-process plane
    proc = await asyncio.create_subprocess_exec(
        sys.executable, "-m", "dynamo_tpu.run", "in=batch", "out=mocker",
        "--model", "mock", "--input-file", str(inp),
        "--output-file", str(outp),
        env=env, stdout=asyncio.subprocess.PIPE,
        stderr=asyncio.subprocess.STDOUT)
    out, _ = await asyncio.wait_for(proc.communicate(), 120)
    assert proc.returncode == 0, out.decode()
    assert b"BATCH_DONE 3/3 ok" in out, out.decode()

    lines = [json.loads(line) for line in outp.read_text().splitlines()]
    assert len(lines) == 3
    assert lines[0]["object"] == "chat.completion"
    assert lines[0]["choices"][0]["finish_reason"] == "length"
    assert lines[1]["object"] == "text_completion"
    assert lines[1]["choices"][0]["finish_reason"] == "length"


async def test_system_status_server_and_config_wiring():
    """DYN_SYSTEM_PORT starts the /health /live /metrics server on the
    runtime (ref: system_status_server.rs); health-check knobs flow from
    RuntimeConfig into HealthCheckConfig.from_runtime."""
    import aiohttp

    from dynamo_tpu.runtime.config import RuntimeConfig
    from dynamo_tpu.runtime.health_check import HealthCheckConfig
    from dynamo_tpu.runtime.runtime import DistributedRuntime

    rc = RuntimeConfig.load(env={"DYN_SYSTEM_PORT": "18977",
                                 "DYN_HEALTH_CHECK_INTERVAL": "7.5",
                                 "DYN_HEALTH_CHECK_FAILURES": "5"})
    hc = HealthCheckConfig.from_runtime(rc)
    assert hc.check_interval_s == 7.5 and hc.failure_threshold == 5

    rt = await DistributedRuntime.create(config=rc)
    try:
        rt.metrics.counter("aux_test_total", "test").inc(3)
        async with aiohttp.ClientSession() as s:
            async with s.get("http://127.0.0.1:18977/health") as r:
                assert (await r.json())["status"] == "ready"
            async with s.get("http://127.0.0.1:18977/live") as r:
                assert (await r.json())["live"] is True
            async with s.get("http://127.0.0.1:18977/metrics") as r:
                body = await r.text()
                assert "dynamo_aux_test_total 3" in body
                assert "dynamo_uptime_seconds" in body
    finally:
        await rt.shutdown()


async def test_tracker_child_after_join_is_closed():
    """A child created after join() must refuse spawns (structured
    concurrency cannot leak past the shutdown drain)."""
    import pytest as _pytest

    from dynamo_tpu.runtime.tasks import TaskTracker

    t = TaskTracker("root")
    ran = []

    async def work():
        ran.append(1)

    t.spawn(work())
    await t.join()
    late = t.child("late")

    async def never():
        ran.append(2)

    with _pytest.raises(RuntimeError):
        late.spawn(never())
    assert ran == [1]


def test_trace_replay_blocks_are_shared_and_deterministic():
    """Two trace records sharing hash_ids must expand to identical token
    prefixes (that's the whole prefix-caching signal), and expansion is
    stable across calls."""
    from benchmarks.trace_replay import block_tokens_for, prompt_for, synthesize

    assert block_tokens_for(42, 16) == block_tokens_for(42, 16)
    assert block_tokens_for(42, 16) != block_tokens_for(43, 16)

    a = {"timestamp": 0, "input_length": 140, "output_length": 8,
         "hash_ids": [7, 8]}
    b = {"timestamp": 999, "input_length": 150, "output_length": 8,
         "hash_ids": [7, 8, 9]}
    pa, pb = prompt_for(a, 64), prompt_for(b, 64)
    assert len(pa) == 140 and len(pb) == 150
    assert pa[:128] == pb[:128]          # shared 2-block prefix
    assert pa[128:] != pb[128:140]       # unique tails diverge

    tr = synthesize(50, block_tokens=32, seed=1)
    assert len(tr) == 50
    assert tr == synthesize(50, block_tokens=32, seed=1)  # reproducible
    ts = [r["timestamp"] for r in tr]
    assert ts == sorted(ts)
    # prefix sharing exists in the synthetic tree
    from collections import Counter
    first_blocks = Counter(tuple(r["hash_ids"][:1]) for r in tr)
    assert max(first_blocks.values()) > 1


def test_gauge_scrape_callbacks_with_labels():
    """Scrape-time gauge callbacks carry labeled samples (the engine's
    step-trace wiring in engine/main relies on this)."""
    from dynamo_tpu.runtime.metrics import MetricsRegistry

    m = MetricsRegistry()
    g = m.gauge("engine_step_mean_ms", "x")
    state = {"decode": 12.5, "prefill": 230.0}
    g.add_callback(lambda: {(("kind", k),): v for k, v in state.items()})
    out = m.render()
    assert 'dynamo_engine_step_mean_ms{kind="decode"} 12.5' in out
    assert 'dynamo_engine_step_mean_ms{kind="prefill"} 230.0' in out
    state["decode"] = 99.0  # live: re-evaluated per scrape
    assert 'kind="decode"} 99.0' in m.render()
