"""Deploy layer: process operator reconciliation, Kubernetes connector,
Prometheus metrics source (ref: deploy/cloud/operator reconcilers,
planner kubernetes_connector.py, planner/utils/prometheus.py)."""

import asyncio
import json
import os
import sys
import time

import pytest

from dynamo_tpu.deploy.kubernetes_connector import KubernetesConnector
from dynamo_tpu.deploy.operator import ProcessOperator, parse_spec
from dynamo_tpu.planner.planner_core import Decision, Observation
from dynamo_tpu.planner.prometheus import (
    PrometheusMetricsSource, parse_prometheus_text,
)

pytestmark = pytest.mark.anyio

SLEEPER = [sys.executable, "-c",
           "import time\nwhile True: time.sleep(0.2)"]


def write_spec(path, services: dict) -> None:
    import yaml

    doc = {"apiVersion": "dynamo.tpu/v1alpha1",
           "kind": "DynamoGraphDeployment",
           "metadata": {"name": "t"},
           "spec": {"services": services}}
    with open(path, "w") as f:
        yaml.safe_dump(doc, f)


def alive(op: ProcessOperator, svc: str) -> int:
    return sum(1 for r in op.replicas[svc] if r.proc.poll() is None)


async def test_operator_scale_and_crash_restart(tmp_path):
    spec = str(tmp_path / "graph.yaml")
    write_spec(spec, {"work": {"replicas": 2, "command": SLEEPER,
                               "env": {"X_TEST": "1"}}})
    op = ProcessOperator(spec, tick_s=0.1)
    try:
        op.reconcile_once()
        assert alive(op, "work") == 2
        status = json.load(open(spec + ".status.json"))
        assert status["services"]["work"]["ready"] == 2

        # crash one replica → reaped, restart counted, respawned (after
        # backoff; force the clock past it)
        op.replicas["work"][0].proc.kill()
        op.replicas["work"][0].proc.wait()
        op.reconcile_once()
        assert op.restarts["work"] == 1
        op._next_start[("work", 0)] = 0.0
        op.reconcile_once()
        assert alive(op, "work") == 2

        # spec edit → scale down to 1 (newest killed first)
        write_spec(spec, {"work": {"replicas": 1, "command": SLEEPER}})
        os.utime(spec, (time.time() + 2, time.time() + 2))
        op.reconcile_once()
        assert alive(op, "work") == 1
    finally:
        await op.stop()
    assert alive(op, "work") == 0  # drained


async def test_operator_backoff_is_per_slot_not_per_service(tmp_path):
    """Flagship-drive regression: chaos kills spread across a pool must
    not accumulate into one service-wide crash streak that freezes ALL
    respawns (observed as the decode pool collapsing to 1 alive while
    desired was 4). Each replica slot carries its own backoff."""
    spec = str(tmp_path / "graph.yaml")
    write_spec(spec, {"work": {"replicas": 3, "command": SLEEPER}})
    op = ProcessOperator(spec, tick_s=0.1)
    try:
        op.reconcile_once()
        assert alive(op, "work") == 3
        t0 = time.monotonic()
        for i in range(3):  # one independent death per slot
            victim = next(r for r in op.replicas["work"] if r.index == i)
            victim.proc.kill()
            victim.proc.wait()
            op.reconcile_once()
        # every slot is a FIRST offense (~1s delay each) — no shared
        # streak escalating toward the 5s/10s/30s tiers
        for i in range(3):
            assert op._crash_streak[("work", i)] == 1
            assert op._next_start[("work", i)] - t0 < 3.0
        # a slot whose delay elapsed respawns even while the others are
        # still backing off
        op._next_start[("work", 0)] = 0.0
        op.reconcile_once()
        assert alive(op, "work") == 1
        assert {r.index for r in op.replicas["work"]
                if r.proc.poll() is None} == {0}
        for slot in list(op._next_start):
            op._next_start[slot] = 0.0
        op.reconcile_once()
        assert alive(op, "work") == 3
    finally:
        await op.stop()


async def test_operator_follows_planner_target(tmp_path):
    from dynamo_tpu.planner.virtual_connector import VirtualConnector
    from dynamo_tpu.runtime import DistributedRuntime

    spec = str(tmp_path / "graph.yaml")
    write_spec(spec, {
        "decode": {"replicas": 1, "command": SLEEPER, "plannerRole": "decode"},
        "aux": {"replicas": 1, "command": SLEEPER},
    })
    rt = await DistributedRuntime.create()
    op = await ProcessOperator(spec, plane=rt.plane, tick_s=0.05).start()
    try:
        for _ in range(40):
            if alive(op, "decode") == 1:
                break
            await asyncio.sleep(0.05)
        assert alive(op, "decode") == 1

        # the planner writes a target; the operator must realize it
        await VirtualConnector(rt.plane).apply(
            Decision(prefill_replicas=0, decode_replicas=3))
        for _ in range(100):
            if alive(op, "decode") == 3:
                break
            await asyncio.sleep(0.05)
        assert alive(op, "decode") == 3
        assert alive(op, "aux") == 1  # non-planner service untouched
    finally:
        await op.stop()
        await rt.shutdown()


def test_spec_validation(tmp_path):
    bad = tmp_path / "bad.yaml"
    bad.write_text("kind: Nope\n")
    with pytest.raises(ValueError):
        parse_spec(str(bad))
    bad.write_text(
        "kind: DynamoGraphDeployment\nspec:\n  services:\n    a: {replicas: 1}\n")
    with pytest.raises(ValueError):  # no command
        parse_spec(str(bad))


async def test_kubernetes_connector_patches():
    calls = []
    state = {"prefill": 1, "decode": 1}

    async def fake_kubectl(argv):
        calls.append(argv)
        if argv[2] == "patch":
            patch = json.loads(argv[-1])
            for name, svc in patch["spec"]["services"].items():
                state[name] = svc["replicas"]
            return 0, "patched"
        if argv[2] == "get":
            return 0, json.dumps({"spec": {"services": {
                n: {"replicas": r} for n, r in state.items()}}})
        return 1, "unknown"

    c = KubernetesConnector("graph", k8s_namespace="serving",
                            runner=fake_kubectl)
    await c.apply(Decision(prefill_replicas=2, decode_replicas=5))
    assert state == {"prefill": 2, "decode": 5}
    assert calls[0][:2] == ["-n", "serving"]

    # unchanged decision → no second patch
    await c.apply(Decision(prefill_replicas=2, decode_replicas=5))
    assert len(calls) == 1
    assert await c.read_replicas() == {"prefill": 2, "decode": 5}

    # failed patch keeps .applied unset so the next tick retries
    async def failing(argv):
        return 1, "rbac denied"

    c2 = KubernetesConnector("graph", runner=failing)
    await c2.apply(Decision(prefill_replicas=3, decode_replicas=3))
    assert c2.applied is None


async def test_prometheus_source_deltas():
    samples = []

    def text(finished, prompt, completion, lat_sum, lat_cnt, ttft_sum, ttft_cnt):
        return "\n".join([
            f'dynamo_llm_requests_finished_total{{model="m"}} {finished}',
            f'dynamo_llm_prompt_tokens_total{{model="m"}} {prompt}',
            f'dynamo_llm_completion_tokens_total{{model="m"}} {completion}',
            f"dynamo_http_request_duration_seconds_sum {lat_sum}",
            f"dynamo_http_request_duration_seconds_count {lat_cnt}",
            f"dynamo_http_time_to_first_token_seconds_sum {ttft_sum}",
            f"dynamo_http_time_to_first_token_seconds_count {ttft_cnt}",
        ])

    src = PrometheusMetricsSource("http://unused:0")

    async def fake_fetch():
        return parse_prometheus_text(samples.pop(0))

    src._fetch = fake_fetch
    samples.append(text(10, 5000, 1000, 10.0, 10, 1.0, 10))
    assert await src() is None  # first sample: no deltas
    # +20 requests, +16000 prompt tokens, +4000 completion tokens
    samples.append(text(30, 21000, 5000, 110.0, 30, 3.0, 30))
    src._prev_t -= 10.0  # pretend 10s elapsed
    obs = await src()
    assert obs is not None
    assert abs(obs.request_rate - 2.0) < 0.2
    assert abs(obs.isl - 800.0) < 1e-6
    assert abs(obs.osl - 200.0) < 1e-6
    assert abs(obs.ttft_ms - 100.0) < 1e-6  # 2s Δsum / 20 Δcount
    # mean latency 5000ms; (5000-100)/(200-1) ≈ 24.6ms ITL
    assert 20.0 < obs.itl_ms < 30.0


def test_recipes_parse():
    for name in ("mocker-demo", "llama3-70b-v5e64-disagg",
                 "deepseek-r1-wideep"):
        svcs = parse_spec(f"deploy/recipes/{name}.yaml")
        assert svcs and all(s.command for s in svcs.values())
    assert parse_spec(
        "deploy/recipes/llama3-70b-v5e64-disagg.yaml")["decode"].planner_role == "decode"


async def test_operator_restarts_on_command_change(tmp_path):
    spec = str(tmp_path / "graph.yaml")
    write_spec(spec, {"work": {"replicas": 1, "command": SLEEPER}})
    op = ProcessOperator(spec, tick_s=0.1)
    try:
        op.reconcile_once()
        pid_before = op.replicas["work"][0].proc.pid
        # change the env (same replica count): replica must be replaced
        write_spec(spec, {"work": {"replicas": 1, "command": SLEEPER,
                                   "env": {"NEW": "cfg"}}})
        os.utime(spec, (time.time() + 2, time.time() + 2))
        op.reconcile_once()
        assert alive(op, "work") == 1
        assert op.replicas["work"][0].proc.pid != pid_before
    finally:
        await op.stop()


async def test_kubectl_contract_full_surface(tmp_path, monkeypatch):
    """The k8s path with a REAL subprocess against a fake kubectl binary
    (r2 verdict #10: no cluster in this environment, so the full CLI/JSON
    surface is pinned by contract): CRD + recipe manifests apply, the
    connector's merge patches mutate the stored resource, reads observe
    them, and the recorded argv sequence is exactly what a cluster would
    receive."""
    import subprocess

    import yaml

    state = tmp_path / "k8s-state.json"
    log = tmp_path / "kubectl-argv.jsonl"
    fake = tmp_path / "bin" / "kubectl"
    fake.parent.mkdir()
    fake.write_text(f"""#!{sys.executable}
import json, sys, yaml
STATE, LOG = {str(state)!r}, {str(log)!r}
args = sys.argv[1:]
open(LOG, "a").write(json.dumps(args) + "\\n")
try:
    store = json.load(open(STATE))
except FileNotFoundError:
    store = {{}}

def merge(dst, src):
    for k, v in src.items():
        if isinstance(v, dict) and isinstance(dst.get(k), dict):
            merge(dst[k], v)
        else:
            dst[k] = v

ns = "default"
if args[:1] == ["-n"]:
    ns, args = args[1], args[2:]
cmd = args[0]
if cmd == "apply" and args[1] == "-f":
    for doc in yaml.safe_load_all(open(args[2])):
        if not doc:
            continue
        key = f"{{ns}}/{{doc['kind'].lower()}}/{{doc['metadata']['name']}}"
        store[key] = doc
        print(f"{{doc['kind'].lower()}}/{{doc['metadata']['name']}} configured")
elif cmd == "patch":
    key = f"{{ns}}/{{args[1]}}/{{args[2]}}"
    assert args[3:5] == ["--type", "merge"], args
    assert args[5] == "-p"
    if key not in store:
        print(f"Error: {{args[1]}} {{args[2]}} not found"); sys.exit(1)
    merge(store[key], json.loads(args[6]))
    print("patched")
elif cmd == "get":
    key = f"{{ns}}/{{args[1]}}/{{args[2]}}"
    assert args[3:5] == ["-o", "json"], args
    if key not in store:
        print("NotFound"); sys.exit(1)
    print(json.dumps(store[key]))
else:
    print(f"unknown command {{cmd}}"); sys.exit(1)
json.dump(store, open(STATE, "w"))
""")
    fake.chmod(0o755)
    monkeypatch.setenv("PATH", f"{fake.parent}:{os.environ['PATH']}")

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    crd = os.path.join(repo, "deploy", "recipes", "k8s", "crd.yaml")
    gke = os.path.join(repo, "deploy", "recipes", "k8s",
                       "llama3-70b-gke.yaml")
    graph = os.path.join(repo, "deploy", "recipes",
                         "llama3-70b-v5e64-disagg.yaml")
    # the real yamls (CRD + raw GKE resources + the graph CR) apply
    # cleanly through the fake cluster
    for f in (crd, gke, graph):
        r = subprocess.run(["kubectl", "-n", "serving", "apply", "-f", f],
                           capture_output=True, text=True)
        assert r.returncode == 0, r.stdout + r.stderr
    # the graph resource's kind matches the CRD it rides on
    crd_doc = next(iter(yaml.safe_load_all(open(crd))))
    graph_doc = next(iter(yaml.safe_load_all(open(graph))))
    assert graph_doc["kind"] == crd_doc["spec"]["names"]["kind"]
    graph_name = graph_doc["metadata"]["name"]

    # the connector's DEFAULT runner (real kubectl subprocess) scales it
    c = KubernetesConnector(graph_name, k8s_namespace="serving")
    await c.apply(Decision(prefill_replicas=4, decode_replicas=12))
    got = await c.read_replicas()
    assert got and got.get(c.prefill_service) == 4
    assert got.get(c.decode_service) == 12

    # pin the exact wire surface the cluster saw
    argvs = [json.loads(line) for line in open(log)]
    patch_argv = next(a for a in argvs if "patch" in a)
    assert patch_argv[:6] == ["-n", "serving", "patch",
                              "dynamographdeployment", graph_name, "--type"]
    assert json.loads(patch_argv[-1]) == {"spec": {"services": {
        "prefill": {"replicas": 4}, "decode": {"replicas": 12}}}}
    get_argv = argvs[-1]
    assert get_argv == ["-n", "serving", "get", "dynamographdeployment",
                        graph_name, "-o", "json"]


async def test_operator_scale_down_revokes_leases(tmp_path):
    """The reference's etcd-cleanup-on-scale-down contract: killing a
    replica must revoke its leases so discovery forgets the instance
    (ref: deploy/cloud/operator — here it falls out of lease semantics)."""
    from dynamo_tpu.runtime.control_plane import ControlPlaneServer

    server = ControlPlaneServer(port=0)
    addr = await server.start()
    worker_py = (
        "import asyncio\n"
        "from dynamo_tpu.runtime import DistributedRuntime\n"
        "async def main():\n"
        "    rt = await DistributedRuntime.create()\n"
        "    ep = rt.namespace('prod').component('w').endpoint('gen')\n"
        "    async def h(req, ctx):\n"
        "        yield {}\n"
        "    await ep.serve_endpoint(h)\n"
        "    await asyncio.sleep(120)\n"
        "asyncio.run(main())\n")
    spec = str(tmp_path / "graph.yaml")
    write_spec(spec, {"w": {
        "replicas": 2, "command": [sys.executable, "-c", worker_py],
        "env": {"DYN_CONTROL_PLANE": addr,
                "PYTHONPATH": os.pathsep.join(sys.path)}}})

    from dynamo_tpu.runtime import DistributedRuntime
    os.environ["DYN_CONTROL_PLANE"] = addr
    try:
        rt = await DistributedRuntime.create()
        client = await rt.namespace("prod").component("w").endpoint(
            "gen").client().start()
        op = ProcessOperator(spec, tick_s=0.1)
        op.reconcile_once()
        for _ in range(200):
            if len(client.instance_ids()) == 2:
                break
            await asyncio.sleep(0.05)
        assert len(client.instance_ids()) == 2

        write_spec(spec, {"w": {
            "replicas": 1, "command": [sys.executable, "-c", worker_py],
            "env": {"DYN_CONTROL_PLANE": addr,
                    "PYTHONPATH": os.pathsep.join(sys.path)}}})
        os.utime(spec, (time.time() + 2, time.time() + 2))
        op.reconcile_once()
        # the killed replica's disconnect revokes its lease → discovery
        # forgets the instance without any explicit cleanup call
        for _ in range(200):
            if len(client.instance_ids()) == 1:
                break
            await asyncio.sleep(0.05)
        assert len(client.instance_ids()) == 1
        await op.stop()
        await rt.shutdown()
    finally:
        os.environ.pop("DYN_CONTROL_PLANE", None)
        await server.stop()
