"""Replay a mooncake-style request trace against the OpenAI HTTP endpoint.

Analog of the reference's real-data router benchmark
(ref: benchmarks/router/real_data_benchmark.py + prefix_data_generator/
synthesizer.py): trace records are JSONL

    {"timestamp": ms, "input_length": n, "output_length": m,
     "hash_ids": [b0, b1, ...]}

where ``hash_ids`` name prefix blocks of ``--block-tokens`` tokens each,
shared across requests (the prefix-caching/KV-routing signal). Each hash id
expands to a DETERMINISTIC token block (seeded by the id), so two requests
sharing hash ids share real token prefixes end to end — the radix index,
prefix cache, and KV-aware routing all see genuine overlap.

No genai-perf in this image (zero egress): the replay client is
asyncio+aiohttp, open-loop at trace timestamps (scaled by ``--speedup``),
streaming, reporting TTFT/ITL percentiles and aggregate throughput as one
JSON line.

``--synthesize N`` generates a small built-in trace (prefix tree: roots ×
depth chains, Poisson arrivals) when no real mooncake file is at hand.

Usage:
    python -m benchmarks.trace_replay --url http://127.0.0.1:8000 \
        --model mock --trace mooncake_trace.jsonl [--speedup 10]
    python -m benchmarks.trace_replay --model mock --synthesize 200
"""

from __future__ import annotations

import argparse
import asyncio
import json
import random
import time

import numpy as np

from benchmarks.client import Mix

VOCAB_LOW, VOCAB_HIGH = 10, 30000


def block_tokens_for(hash_id: int, n: int) -> list[int]:
    """The deterministic token block a hash id names (same id → same
    tokens, across requests and processes)."""
    rng = np.random.default_rng(0xC0FFEE ^ (hash_id * 2654435761 % 2**32))
    return rng.integers(VOCAB_LOW, VOCAB_HIGH, n).tolist()


def prompt_for(rec: dict, block_tokens: int) -> list[int]:
    toks: list[int] = []
    for h in rec.get("hash_ids", []):
        toks.extend(block_tokens_for(int(h), block_tokens))
    n = int(rec["input_length"])
    if len(toks) < n:  # unique tail: the un-shared part of the prompt
        rng = np.random.default_rng(rec.get("timestamp", 0) * 31 + n)
        toks.extend(rng.integers(VOCAB_LOW, VOCAB_HIGH,
                                 n - len(toks)).tolist())
    return toks[:n]


def synthesize(n: int, *, block_tokens: int, seed: int = 0,
               roots: int = 8, depth: int = 6,
               mean_iat_ms: float = 120.0) -> list[dict]:
    """Prefix-tree trace: each request walks a root chain to a random
    depth (shared prefix) and adds a unique tail; Poisson arrivals.
    Mirrors the synthesizer's tree-walk model at toy scale."""
    rng = np.random.default_rng(seed)
    out, t = [], 0.0
    for i in range(n):
        root = int(rng.integers(roots))
        d = int(rng.integers(1, depth + 1))
        # chain ids are globally unique per (root, level)
        hash_ids = [root * 1000 + lvl for lvl in range(d)]
        isl = d * block_tokens + int(rng.integers(8, 64))
        out.append({
            "timestamp": int(t),
            "input_length": isl,
            "output_length": int(rng.integers(16, 96)),
            "hash_ids": hash_ids,
        })
        t += float(rng.exponential(mean_iat_ms))
    return out


async def replay(url: str, model: str, trace: list[dict], *,
                 block_tokens: int, speedup: float) -> dict:
    import aiohttp

    results: list[tuple] = []  # (ttft, n_tok, itls, qos_class)
    errors: list[str] = []

    async def one(session, rec):
        prompt = prompt_for(rec, block_tokens)
        # QoS identity stamped onto the record by --tenant-mix /
        # --priority-mix (or carried by a real trace's own fields)
        headers = {}
        if rec.get("tenant"):
            headers["x-dynamo-tenant"] = str(rec["tenant"])
        if rec.get("priority"):
            headers["x-dynamo-priority"] = str(rec["priority"])
        cls = rec.get("priority") or "default"
        t0 = time.perf_counter()
        ttft, last, itls, n_tok = None, None, [], 0
        try:
            async with session.post(f"{url}/v1/completions", json={
                    "model": model, "prompt": prompt, "stream": True,
                    "max_tokens": int(rec["output_length"]),
                    "ignore_eos": True, "temperature": 0.0},
                    headers=headers) as resp:
                if resp.status != 200:
                    errors.append(f"HTTP {resp.status}: "
                                  f"{(await resp.text())[:200]}")
                    results.append((None, 0, [], cls))
                    return
                async for raw in resp.content:
                    line = raw.decode()
                    if not line.startswith("data: ") or line.startswith("data: [DONE]"):
                        continue
                    payload = json.loads(line[6:])
                    if "error" in payload:
                        errors.append(f"SSE error: {str(payload)[:200]}")
                        results.append((None, 0, [], cls))
                        return
                    now = time.perf_counter()
                    if ttft is None:
                        ttft = now - t0
                    elif last is not None:
                        itls.append(now - last)
                    last = now
                    n_tok += 1
        except aiohttp.ClientError as e:
            errors.append(f"client error: {e!r}"[:200])
            results.append((None, 0, [], cls))
            return
        results.append((ttft, n_tok, itls, cls))

    t_start = time.perf_counter()
    base_ts = trace[0]["timestamp"]
    conn = aiohttp.TCPConnector(limit=0)
    async with aiohttp.ClientSession(connector=conn) as session:
        tasks = []
        for rec in trace:
            target = (rec["timestamp"] - base_ts) / 1000.0 / speedup
            delay = target - (time.perf_counter() - t_start)
            if delay > 0:
                await asyncio.sleep(delay)
            tasks.append(asyncio.get_running_loop().create_task(
                one(session, rec)))
        await asyncio.gather(*tasks)
    wall = time.perf_counter() - t_start

    ok = [r for r in results if r[0] is not None]
    ttfts = sorted(r[0] for r in ok)
    itls = sorted(x for r in ok for x in r[2])
    total_tok = sum(r[1] for r in ok)

    def pct(xs, p):
        # shared interpolated estimator (observability/stats.quantile) —
        # the same math the flight summaries and autoscaler use
        from dynamo_tpu.observability.stats import quantile

        q = quantile(xs, p)
        return round(1000 * q, 1) if q is not None else None

    out = {
        "requests": len(trace), "ok": len(ok),
        "failed": len(results) - len(ok),
        "errors": errors[:5],
        "wall_s": round(wall, 2),
        "output_tok_s": round(total_tok / wall, 1),
        "ttft_p50_ms": pct(ttfts, 0.50), "ttft_p95_ms": pct(ttfts, 0.95),
        "itl_p50_ms": pct(itls, 0.50), "itl_p95_ms": pct(itls, 0.95),
        "speedup": speedup,
    }
    classes = {r[3] for r in results}
    if classes - {"default"}:
        per = {}
        for c in sorted(classes):
            cok = [r for r in ok if r[3] == c]
            ct = sorted(r[0] for r in cok)
            per[c] = {"ok": len(cok),
                      "requests": sum(1 for r in results if r[3] == c),
                      "ttft_p50_ms": pct(ct, 0.50),
                      "ttft_p95_ms": pct(ct, 0.95)}
        out["by_class"] = per
    return out


async def amain():
    ap = argparse.ArgumentParser(description="mooncake-style trace replay")
    ap.add_argument("--url", default="http://127.0.0.1:8000")
    ap.add_argument("--model", required=True)
    ap.add_argument("--trace", default=None,
                    help="mooncake-style JSONL; omit with --synthesize")
    ap.add_argument("--synthesize", type=int, default=None, metavar="N",
                    help="generate an N-request prefix-tree trace instead")
    ap.add_argument("--block-tokens", type=int, default=64,
                    help="tokens per hash-id block (mooncake block_size "
                         "is 512; smaller suits toy models)")
    ap.add_argument("--speedup", type=float, default=1.0,
                    help="replay timestamps this many times faster")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--tenant-mix", default="",
                    help='weighted x-dynamo-tenant mix stamped onto records '
                         'lacking their own "tenant" field, e.g. '
                         '"acme=0.7,free=0.3" (empty = no header)')
    ap.add_argument("--priority-mix", default="",
                    help='weighted x-dynamo-priority mix stamped onto '
                         'records lacking their own "priority" field, e.g. '
                         '"interactive=0.5,standard=0.3,batch=0.2"; note '
                         'escalation above a tenant\'s configured class '
                         'needs DYN_QOS_TENANTS/API-key auth (docs/qos.md)')
    cli = ap.parse_args()

    if cli.trace:
        with open(cli.trace) as f:
            trace = [json.loads(ln) for ln in f if ln.strip()]
    elif cli.synthesize:
        trace = synthesize(cli.synthesize, block_tokens=cli.block_tokens,
                           seed=cli.seed)
    else:
        ap.error("pass --trace FILE or --synthesize N")
    trace.sort(key=lambda r: r["timestamp"])
    # QoS identity assignment is seeded and happens AFTER the timestamp
    # sort so the same (trace, seed, mixes) always drives the same classed
    # request sequence — a real trace's own tenant/priority fields win
    tenant_mix, priority_mix = Mix(cli.tenant_mix), Mix(cli.priority_mix)
    if tenant_mix or priority_mix:
        qrng = random.Random(cli.seed ^ 0x9E3779B9)
        for rec in trace:
            if tenant_mix and not rec.get("tenant"):
                rec["tenant"] = tenant_mix.pick(qrng)
            if priority_mix and not rec.get("priority"):
                rec["priority"] = priority_mix.pick(qrng)
    out = await replay(cli.url, cli.model, trace,
                       block_tokens=cli.block_tokens, speedup=cli.speedup)
    print(json.dumps(out))


if __name__ == "__main__":
    asyncio.run(amain())
