"""Closed-loop SLA autoscaler (docs/autoscaling.md): SLO spec, fused
observation feed, controller decision logic (cooldown / readiness gate /
reactive terms), drain-safe operator scale-down, and the planner-loop
telemetry the ISSUE 6 satellites pinned.

All loop tests are deterministic: fake metrics sources, fake clocks, and
(for the operator) real subprocesses with scripted SIGTERM behavior."""

import asyncio
import json
import os
import signal
import sys
import time

import msgpack
import pytest

from benchmarks.client import Mix
from dynamo_tpu.autoscale import (
    AutoscaleController, ClassTtftTracker, FusedObservation,
    ObservationFuser, SloConfig, histogram_p95, make_planner,
    plane_readiness,
)
from dynamo_tpu.autoscale.observe import TTFT_CLASS_METRIC
from dynamo_tpu.deploy.operator import ProcessOperator
from dynamo_tpu.planner.perf_interpolation import PerfInterpolator
from dynamo_tpu.planner.planner_core import (
    Decision, Observation, PlannerRunner,
)
from dynamo_tpu.planner.prometheus import (
    PrometheusMetricsSource, parse_prometheus_text,
)
from dynamo_tpu.runtime.config import ConfigError

pytestmark = pytest.mark.anyio

# single-replica profiling sweeps (same shape as tests/test_planner.py):
# at the default interactive SLO (TTFT 200ms / ITL 20ms) one replica holds
# 1.0 req/s of prefill and ~2235 decode tok/s
PREFILL_SWEEP = [(0.5, 80), (1.0, 100), (2.0, 150), (4.0, 300), (8.0, 900)]
DECODE_SWEEP = [(500, 8), (1000, 12), (2000, 18), (4000, 35), (8000, 80)]


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t


class FakeFuser:
    """async () -> FusedObservation from a scripted queue (last repeats)."""

    def __init__(self, *fused):
        self.queue = list(fused)
        self.scrape_failures = 0

    def push(self, f: FusedObservation) -> None:
        self.queue.append(f)

    async def __call__(self) -> FusedObservation:
        if len(self.queue) > 1:
            return self.queue.pop(0)
        return self.queue[0]


class FakeConnector:
    def __init__(self):
        self.applied: list[Decision] = []

    async def apply(self, decision: Decision) -> None:
        self.applied.append(decision)


def obs(rate: float, **kw) -> FusedObservation:
    return FusedObservation(
        observation=Observation(request_rate=rate, isl=1000, osl=250, **kw))


def controller(slo=None, *, readiness=None, clock=None,
               **planner_overrides):
    slo = slo or SloConfig(cooldown_up_s=10.0, cooldown_down_s=30.0)
    planner_overrides.setdefault("predictor", "constant")
    planner = make_planner(slo, PerfInterpolator(PREFILL_SWEEP),
                           PerfInterpolator(DECODE_SWEEP),
                           **planner_overrides)
    conn = FakeConnector()
    fuser = FakeFuser(obs(0.1))
    ctl = AutoscaleController(slo, planner, fuser, conn,
                              readiness=readiness,
                              now_fn=clock or FakeClock())
    return ctl, conn, fuser


# ------------------------------------------------------------ SLO config

def test_slo_config_env_loading():
    cfg = SloConfig.load(env={
        "DYN_SLO_INTERACTIVE_TTFT_P95_MS": "120",
        "DYN_SLO_BATCH_TTFT_P95_MS": "9000",
        "DYN_SLO_STANDARD_TTFT_P95_MS": "",  # empty CLEARS the default
        "DYN_SLO_MAX_REPLICAS": "5",
        "DYN_SLO_COOLDOWN_UP_S": "3",
        "DYN_SLO_PREDICTOR": "arima",
    })
    assert cfg.slo_for("interactive").ttft_p95_ms == 120.0
    assert cfg.slo_for("batch").ttft_p95_ms == 9000.0
    assert cfg.slo_for("standard").ttft_p95_ms is None
    assert cfg.max_replicas == 5 and cfg.cooldown_up_s == 3.0
    assert cfg.predictor == "arima"
    # the governing class parameterizes the planner inversion
    assert cfg.governing.ttft_p95_ms == 120.0

    with pytest.raises(ConfigError):
        SloConfig.load(env={"DYN_SLO_MIN_REPLICAS": "nope"})
    with pytest.raises(ConfigError):
        SloConfig(min_replicas=5, max_replicas=2)
    with pytest.raises(ConfigError):
        SloConfig(governing_class="platinum")
    with pytest.raises(ConfigError):
        SloConfig(predictor="oracle")


# --------------------------------------------------- per-class TTFT feed

def _exposition(per_class: dict) -> str:
    lines = []
    for cls, buckets in per_class.items():
        for le, cum in buckets.items():
            le_s = "+Inf" if le == float("inf") else str(le)
            lines.append(
                f'{TTFT_CLASS_METRIC}_bucket{{qos="{cls}",le="{le_s}"}} '
                f"{cum}")
    return "\n".join(lines)


def test_histogram_p95_interpolates_crossing_bucket():
    inf = float("inf")
    # crossing inside [0.1, 0.5): target 95 of 100, 60 below 0.1
    assert histogram_p95({0.1: 60, 0.5: 90, 1.0: 99, inf: 100}) == \
        pytest.approx(1.0 - (4 / 9) * 0.5)
    # everything in the first bucket: linear from 0
    assert histogram_p95({0.1: 100, inf: 100}) == pytest.approx(0.095)
    # p95 lands in the +Inf tail: best lower bound is the last finite edge
    assert histogram_p95({0.1: 60, 0.5: 90, inf: 100}) == 0.5
    assert histogram_p95({inf: 0}) is None  # idle interval
    assert histogram_p95({0.1: 5}) is None  # malformed: no +Inf


def test_class_ttft_tracker_interval_p95_and_reset():
    inf = float("inf")
    tr = ClassTtftTracker()
    assert tr.feed(None) == {}
    assert tr.feed(_exposition(
        {"interactive": {0.1: 0, 0.2: 0, inf: 0}})) == {}  # first scrape
    out = tr.feed(_exposition(
        {"interactive": {0.1: 10, 0.2: 19, inf: 20},
         "batch": {0.1: 0, 0.2: 0, inf: 0}}))
    # 19/20 cumulative at 0.2 → p95 target 19 lands exactly on 0.2s
    assert out == {"interactive": 200.0}  # idle batch class omitted
    # frontend restart: counters go BACKWARD → per-bucket deltas clamp at
    # 0 → idle interval, not a poisoned one
    assert tr.feed(_exposition(
        {"interactive": {0.1: 1, 0.2: 2, inf: 2}})) == {}


async def test_fuser_tolerates_frontend_scrape_failure():
    class DeadFrontend:
        async def __call__(self):
            raise OSError("connection refused")

    class Agg:
        def aggregate(self):
            return {"requests_waiting": 17, "requests_active": 3,
                    "workers": 2, "total_slots": 8}

    fuser = ObservationFuser(DeadFrontend(), Agg())
    fused = await fuser()
    assert fused.frontend_down and fused.observation is None
    assert fused.queue_depth == 17 and fused.workers == 2
    assert fuser.scrape_failures == 1


async def test_fuser_threads_queue_depth_into_observation():
    class Frontend:
        last_text = None

        async def __call__(self):
            return Observation(request_rate=2.0, isl=100, osl=10)

    class Agg:
        def aggregate(self):
            return {"requests_waiting": 9, "requests_active": 1,
                    "workers": 1, "total_slots": 4}

    fused = await ObservationFuser(Frontend(), Agg())()
    assert fused.observation.queue_depth == 9


# --------------------------------------------------------- controller core

async def test_scale_up_on_predicted_ramp():
    ctl, conn, fuser = controller()
    fuser.push(obs(9.0))
    r1 = await ctl.tick()  # primer obs (rate 0.1): hold at (1,1)
    assert r1.direction == "hold" and not conn.applied
    r2 = await ctl.tick()
    assert r2.applied and r2.direction == "up" and r2.reason == "predicted"
    assert conn.applied[-1] == Decision(4, 2)  # 9 req/s over the sweeps
    assert ctl.scale_ups == 1 and ctl.applied == Decision(4, 2)


async def test_cooldown_suppresses_flapping():
    clock = FakeClock()
    ctl, conn, fuser = controller(clock=clock, scale_down_patience=1)
    fuser.queue = [obs(9.0)]
    await ctl.tick()  # up to (4,2) at t=0
    assert ctl.scale_ups == 1
    # demand oscillates every tick, 1s apart: inside both cooldown
    # windows nothing further may actuate
    for i in range(8):
        clock.t += 1.0
        fuser.queue = [obs(0.2 if i % 2 == 0 else 9.0)]
        await ctl.tick()
    assert len(conn.applied) == 1  # the initial up only
    assert ctl.held_for_cooldown > 0
    # past the down-cooldown with demand steadily low → one scale-down
    clock.t += 60.0
    fuser.queue = [obs(0.2)]
    r = await ctl.tick()
    assert r.applied and r.direction == "down"
    assert ctl.scale_downs == 1 and ctl.applied == Decision(1, 1)


async def test_readiness_gate_defers_scale_up():
    clock = FakeClock()
    ready = {"decode": 1, "prefill": 1}

    async def readiness():
        return dict(ready)

    # max_replicas=4 pins the prefill fleet so the decode gate is isolated
    ctl, conn, fuser = controller(
        SloConfig(cooldown_up_s=10.0, cooldown_down_s=30.0, max_replicas=4),
        clock=clock, readiness=readiness)
    fuser.queue = [obs(9.0)]
    await ctl.tick()  # up to (4,2); replicas now materializing
    assert ctl.applied == Decision(4, 2)
    # demand rises further while ready(1) < applied(2): the controller
    # must NOT stack another decode scale-up onto a fleet still starting
    clock.t += 60.0
    fuser.queue = [obs(18.0)]  # wants decode 3
    r = await ctl.tick()
    assert r.reason == "deferred_unready"
    assert ctl.applied.decode_replicas == 2
    assert ctl.deferred_for_readiness == 1
    # capacity materializes → the deferred step is taken
    ready["decode"] = 2
    clock.t += 60.0
    r2 = await ctl.tick()
    assert r2.applied and ctl.applied.decode_replicas >= 3


async def test_backlog_scales_reactively_with_frontend_down():
    """A dead frontend scrape must not blind the loop: worker queue depth
    alone forces scale-up (the reactive half of the feed)."""
    ctl, conn, fuser = controller(
        SloConfig(cooldown_up_s=0.0, backlog_per_replica=8.0))
    fuser.queue = [FusedObservation(observation=None, frontend_down=True,
                                    queue_depth=40)]
    r = await ctl.tick()
    assert r.applied and r.reason == "backlog"
    assert ctl.applied.decode_replicas == 5  # ceil(40/8)


async def test_slo_breach_adds_replica():
    ctl, conn, fuser = controller(SloConfig(cooldown_up_s=0.0))
    fused = obs(0.1)
    fused.ttft_p95_ms = {"interactive": 500.0}  # target 200ms → breach
    fuser.queue = [fused]
    r = await ctl.tick()
    assert r.applied and r.reason == "slo_breach"
    assert not r.breaches["interactive"]["ok"]
    assert ctl.applied.decode_replicas == 2  # applied+1, not a jump
    # TTFT is prefill-bound in disagg: a scalable prefill fleet steps too
    assert ctl.applied.prefill_replicas == 2

    # with the prefill dimension pinned (aggregated fleet), only decode
    ctl2, _, fuser2 = controller(SloConfig(cooldown_up_s=0.0),
                                 min_prefill_replicas=1,
                                 max_prefill_replicas=1)
    f2 = obs(0.1)
    f2.ttft_p95_ms = {"interactive": 500.0}
    fuser2.queue = [f2]
    await ctl2.tick()
    assert ctl2.applied.prefill_replicas == 1
    assert ctl2.applied.decode_replicas == 2


async def test_scale_bounds_clamp():
    slo = SloConfig(cooldown_up_s=0.0, max_replicas=3)
    ctl, conn, fuser = controller(slo)
    fuser.queue = [FusedObservation(observation=None, queue_depth=1000)]
    await ctl.tick()
    assert ctl.applied.decode_replicas == 3


async def test_status_published_to_plane():
    class PlaneStub:
        def __init__(self):
            self.put = {}

        async def kv_put(self, key, value, lease_id=None):
            self.put[key] = value

    plane = PlaneStub()
    slo = SloConfig(cooldown_up_s=0.0)
    planner = make_planner(slo, PerfInterpolator(PREFILL_SWEEP),
                           PerfInterpolator(DECODE_SWEEP),
                           predictor="constant")
    ctl = AutoscaleController(slo, planner, FakeFuser(obs(9.0)),
                              FakeConnector(), plane=plane,
                              namespace="t", now_fn=FakeClock())
    await ctl.tick()
    status = json.loads(plane.put["public/autoscale/t/status"])
    assert status["desired"] == {"prefill": 4, "decode": 2}
    assert status["lastDecision"]["direction"] == "up"
    assert status["counters"]["ticks"] == 1


async def test_plane_readiness_rolls_up_by_role():
    class PlaneStub:
        async def kv_get(self, key):
            return json.dumps({
                "services": {
                    "decode-a": {"plannerRole": "decode", "ready": 2},
                    "decode-b": {"plannerRole": "decode", "ready": 1},
                    "front": {"plannerRole": None, "ready": 1},
                },
                "drainSecondsTotal": 3.5,
            }).encode()

    out = await plane_readiness(PlaneStub(), "ns")
    assert out["decode"] == 3 and "front" not in out
    assert out["_drain_seconds_total"] == 3.5

    class EmptyPlane:
        async def kv_get(self, key):
            return None

    assert await plane_readiness(EmptyPlane()) is None


async def test_correction_runaway_does_not_pin_fleet_at_max():
    """Regression (found driving the live loop): an ITL target the engine
    can never meet per-replica (raw SLA 20 ms vs ~23 ms true ITL) grows
    the correction factor until the CORRECTED target falls below the
    profile's idle latency — max_load_under then answers 0 ("impossible")
    and the planner pinned the fleet at max through an entire load
    trough. Scale-out cannot improve per-replica latency, so the capacity
    lookup must fall back to the profile's most pessimistic measured
    point, not to max replicas."""
    slo = SloConfig(cooldown_up_s=0.0, cooldown_down_s=0.0, max_replicas=3)
    decode = PerfInterpolator([(24.0, 10.0), (48.0, 40.0), (96.0, 300.0)])
    planner = make_planner(slo, PerfInterpolator(PREFILL_SWEEP), decode,
                           predictor="constant", scale_down_patience=1)
    # trough traffic, engine ITL ~23 ms vs the 20 ms governing target:
    # the EMA drives d_correction well past 2 (corrected target < 10 ms)
    for _ in range(10):
        planner.observe(Observation(request_rate=1.0, isl=60, osl=24,
                                    ttft_ms=40.0, itl_ms=23.0))
    d = planner.compute()
    assert planner.d_correction_factor > 2.0  # runaway happened…
    # …but demand 24 tok/s against the 24 tok/s floor capacity = 1
    assert d.decode_replicas == 1

    # a RAW SLA below the profile floor still honestly pins to max
    # (ref behavior: test_impossible_sla_pins_to_max)
    hard = make_planner(slo, PerfInterpolator(PREFILL_SWEEP), decode,
                        predictor="constant", itl_sla_ms=5.0)
    hard.observe(Observation(request_rate=1.0, isl=60, osl=24))
    assert hard.compute().decode_replicas == 3


# ------------------------------------------------- PlannerRunner telemetry

async def test_planner_runner_tick_cadence_and_empty_ticks():
    calls = {"n": 0}

    async def source():
        calls["n"] += 1
        return None  # idle interval: no observation

    planner = make_planner(SloConfig(), PerfInterpolator(PREFILL_SWEEP),
                           PerfInterpolator(DECODE_SWEEP),
                           predictor="constant")
    conn = FakeConnector()
    runner = PlannerRunner(planner, source, conn, interval_s=0.01)
    await runner.start()
    await asyncio.sleep(0.15)
    await runner.stop()
    assert runner.ticks >= 3
    assert runner.ticks == calls["n"]
    assert runner.empty_ticks == runner.ticks  # every interval was idle
    assert not conn.applied  # an idle source must not actuate


async def test_planner_runner_survives_scrape_failures():
    state = {"n": 0}

    async def flaky_source():
        state["n"] += 1
        if state["n"] <= 2:
            raise OSError("scrape refused")
        return Observation(request_rate=9.0, isl=1000, osl=250)

    planner = make_planner(SloConfig(), PerfInterpolator(PREFILL_SWEEP),
                           PerfInterpolator(DECODE_SWEEP),
                           predictor="constant")
    conn = FakeConnector()
    runner = PlannerRunner(planner, flaky_source, conn, interval_s=0.01)
    await runner.start()
    for _ in range(100):
        if conn.applied:
            break
        await asyncio.sleep(0.01)
    await runner.stop()
    assert runner.tick_errors == 2  # both failures counted…
    assert conn.applied  # …and the loop went on to actuate


# ------------------------------------- prometheus counter-reset (satellite)

def _prom_text(finished, prompt, completion, lat_sum, lat_cnt,
               ttft_sum, ttft_cnt):
    return "\n".join([
        f"dynamo_llm_requests_finished_total {finished}",
        f"dynamo_llm_prompt_tokens_total {prompt}",
        f"dynamo_llm_completion_tokens_total {completion}",
        f"dynamo_http_request_duration_seconds_sum {lat_sum}",
        f"dynamo_http_request_duration_seconds_count {lat_cnt}",
        f"dynamo_http_time_to_first_token_seconds_sum {ttft_sum}",
        f"dynamo_http_time_to_first_token_seconds_count {ttft_cnt}",
    ])


async def test_counter_reset_does_not_poison_deltas():
    """Satellite bugfix: a frontend restart resets its counters; the delta
    source must skip that interval (flagging the reset) instead of feeding
    the predictor a negative or partial-window rate."""
    samples = []
    src = PrometheusMetricsSource("http://unused:0")

    async def fake_fetch():
        return parse_prometheus_text(samples.pop(0))

    src._fetch = fake_fetch
    samples.append(_prom_text(100, 50000, 10000, 100.0, 100, 10.0, 100))
    assert await src() is None  # first sample

    # frontend restarted: every counter is back near zero
    samples.append(_prom_text(3, 1500, 300, 3.0, 3, 0.3, 3))
    src._prev_t -= 10.0
    assert await src() is None  # reset interval skipped…
    assert src.resets == 1

    # …and the NEXT interval rebases cleanly on the fresh counters
    samples.append(_prom_text(23, 17500, 4300, 23.0, 23, 2.3, 23))
    src._prev_t -= 10.0
    o = await src()
    assert o is not None and o.request_rate == pytest.approx(2.0, abs=0.2)
    assert o.isl == pytest.approx(800.0)
    assert o.osl == pytest.approx(200.0)


# --------------------------------------------- operator: drain-safe scaling

# both workers touch READY_MARKER only AFTER installing their SIGTERM
# handler — the tests must not scale down while the child is still in
# interpreter startup (default SIGTERM disposition: die instantly)
GRACEFUL_WORKER = [sys.executable, "-c", """
import os, signal, sys, time
marker = os.environ["DRAIN_MARKER"]
def on_term(signum, frame):
    time.sleep(0.3)                       # "finish the in-flight stream"
    open(marker, "w").write("drained")
    sys.exit(0)
signal.signal(signal.SIGTERM, on_term)
open(os.environ["READY_MARKER"], "w").write("up")
while True:
    time.sleep(0.05)
"""]

STUBBORN_WORKER = [sys.executable, "-c", """
import os, signal, time
signal.signal(signal.SIGTERM, signal.SIG_IGN)
open(os.environ["READY_MARKER"], "w").write("up")
while True:
    time.sleep(0.05)
"""]


async def _await_file(path: str, timeout_s: float = 10.0) -> None:
    deadline = time.monotonic() + timeout_s
    while not os.path.exists(path):
        assert time.monotonic() < deadline, f"{path} never appeared"
        await asyncio.sleep(0.02)

SLEEPER = [sys.executable, "-c", "import time\nwhile True: time.sleep(0.2)"]


def write_spec(path, services: dict) -> None:
    import yaml

    doc = {"apiVersion": "dynamo.tpu/v1alpha1",
           "kind": "DynamoGraphDeployment",
           "metadata": {"name": "t"},
           "spec": {"services": services}}
    with open(path, "w") as f:
        yaml.safe_dump(doc, f)


def alive(op: ProcessOperator, svc: str) -> int:
    return sum(1 for r in op.replicas[svc] if r.proc.poll() is None)


async def test_drain_safe_scale_down_completes_in_flight(tmp_path):
    """Satellite bugfix regression: scale-down must SIGTERM + wait the
    drain window ASYNCHRONOUSLY — reconcile keeps ticking, and a victim
    that finishes its work inside the window is never SIGKILLed."""
    marker = str(tmp_path / "drained.txt")
    ready = str(tmp_path / "ready.txt")
    spec = str(tmp_path / "graph.yaml")
    env = {"DRAIN_MARKER": marker, "READY_MARKER": ready}
    write_spec(spec, {"w": {"replicas": 1, "command": GRACEFUL_WORKER,
                            "env": env}})
    op = ProcessOperator(spec, tick_s=0.05, drain_timeout=5.0)
    try:
        op.reconcile_once()
        assert alive(op, "w") == 1
        victim = op.replicas["w"][0].proc
        await _await_file(ready)  # SIGTERM handler installed

        write_spec(spec, {"w": {"replicas": 0, "command": GRACEFUL_WORKER,
                                "env": env}})
        os.utime(spec, (time.time() + 2, time.time() + 2))
        t0 = time.monotonic()
        op.reconcile_once()
        reconcile_took = time.monotonic() - t0
        # the old code blocked reconcile in proc.wait(timeout=10); the
        # fix returns immediately with the victim still draining
        assert reconcile_took < 0.25
        assert victim.poll() is None  # still finishing its stream
        assert len(op._draining["w"]) == 1
        status = json.load(open(spec + ".status.json"))
        assert status["services"]["w"]["draining"] == 1

        for _ in range(200):  # keep reconciling while the drain completes
            op.reconcile_once()
            if op.drains_completed == 1:
                break
            await asyncio.sleep(0.02)
        assert op.drains_completed == 1 and op.drains_killed == 0
        assert open(marker).read() == "drained"  # graceful, not SIGKILL
        assert op.drain_seconds_total > 0.0
    finally:
        await op.stop(drain=False)


async def test_stubborn_victim_killed_after_window(tmp_path):
    spec = str(tmp_path / "graph.yaml")
    ready = str(tmp_path / "ready.txt")
    env = {"READY_MARKER": ready}
    write_spec(spec, {"w": {"replicas": 1, "command": STUBBORN_WORKER,
                            "env": env}})
    op = ProcessOperator(spec, tick_s=0.05, drain_timeout=0.4)
    try:
        op.reconcile_once()
        await _await_file(ready)  # SIG_IGN installed
        write_spec(spec, {"w": {"replicas": 0, "command": STUBBORN_WORKER,
                                "env": env}})
        os.utime(spec, (time.time() + 2, time.time() + 2))
        op.reconcile_once()
        assert len(op._draining["w"]) == 1
        for _ in range(200):
            op.reconcile_once()
            if op.drains_killed == 1:
                break
            await asyncio.sleep(0.02)
        assert op.drains_killed == 1 and op.drains_completed == 0
    finally:
        await op.stop(drain=False)


def test_drain_timeout_env_honored(tmp_path, monkeypatch):
    spec = str(tmp_path / "graph.yaml")
    write_spec(spec, {"w": {"replicas": 0, "command": SLEEPER}})
    monkeypatch.setenv("DYN_DRAIN_TIMEOUT", "7.5")
    assert ProcessOperator(spec).drain_timeout == 7.5
    monkeypatch.setenv("DYN_DRAIN_TIMEOUT", "junk")
    with pytest.raises(ValueError):
        ProcessOperator(spec)


def test_status_file_written_atomically(tmp_path):
    """Satellite bugfix: status lands via temp file + os.replace, so a
    reader can never observe a torn JSON document."""
    spec = str(tmp_path / "graph.yaml")
    write_spec(spec, {"w": {"replicas": 2, "command": SLEEPER}})
    op = ProcessOperator(spec, tick_s=0.05)
    try:
        real_replace, seen = os.replace, []

        def spying_replace(src, dst):
            # the temp file must already hold COMPLETE valid JSON when it
            # is atomically swapped into place
            seen.append(json.load(open(src)))
            real_replace(src, dst)

        os.replace = spying_replace
        try:
            op.reconcile_once()
        finally:
            os.replace = real_replace
        assert seen and seen[-1]["services"]["w"]["alive"] == 2
        assert not os.path.exists(spec + ".status.json.tmp")
        assert json.load(open(spec + ".status.json"))
    finally:
        op._scale_to(op.services["w"], 0)
        for r in op._draining["w"]:
            r.proc.kill()
            r.proc.wait()


async def test_victim_selection_fewest_inflight(tmp_path):
    """Scale-down victims: unregistered first, then fewest in-flight
    streams, newest-first on ties — shedding capacity disturbs the least
    work."""
    spec = str(tmp_path / "graph.yaml")
    write_spec(spec, {"w": {"replicas": 3, "command": SLEEPER}})
    op = ProcessOperator(spec, tick_s=0.05, drain_timeout=2.0)
    try:
        op.reconcile_once()
        r0, r1, r2 = op.replicas["w"]
        # r0 carries 5 streams, r2 carries 1; r1 never registered (-1)
        op._registered_pods = {r0.pod_name: 100, r2.pod_name: 102}
        op._inflight_by_instance = {100: 5, 102: 1}

        write_spec(spec, {"w": {"replicas": 2, "command": SLEEPER}})
        os.utime(spec, (time.time() + 2, time.time() + 2))
        op.reconcile_once()
        assert {r.pod_name for r in op.replicas["w"]} == \
            {r0.pod_name, r2.pod_name}  # the unregistered one went first

        write_spec(spec, {"w": {"replicas": 1, "command": SLEEPER}})
        os.utime(spec, (time.time() + 2, time.time() + 2))
        op.reconcile_once()
        # the busy replica survives; the 1-stream one drains
        assert [r.pod_name for r in op.replicas["w"]] == [r0.pod_name]
    finally:
        await op.stop(drain=False)
        for rs in op._draining.values():
            for r in rs:
                r.proc.kill()


async def test_readiness_gate_counts_registered_only(tmp_path):
    """A planner-role replica counts as ready only once REGISTERED on the
    control plane (registration happens after AOT warmup, so 'registered'
    subsumes 'warm') — Popen returning is not capacity."""
    spec = str(tmp_path / "graph.yaml")
    write_spec(spec, {"w": {"replicas": 2, "command": SLEEPER,
                            "plannerRole": "decode"}})

    class PlaneStub:  # only attached, never ticked (no start())
        pass

    op = ProcessOperator(spec, plane=PlaneStub(), tick_s=0.05)
    try:
        op._planner_target = {"decode": 2}
        op.reconcile_once()
        st = op._status()["services"]["w"]
        assert st["alive"] == 2 and st["ready"] == 0  # phantom capacity
        assert st["readinessGated"]

        op._registered_pods = {op.replicas["w"][0].pod_name: 7}
        assert op._status()["services"]["w"]["ready"] == 1
        op._registered_pods.update(
            {op.replicas["w"][1].pod_name: 8})
        assert op._status()["services"]["w"]["ready"] == 2
    finally:
        op.plane = None  # stop() must not touch the stub
        await op.stop(drain=False)
        for r in op.replicas["w"]:
            r.proc.kill()


async def test_refresh_observed_parses_registrations(tmp_path):
    spec = str(tmp_path / "graph.yaml")
    write_spec(spec, {"w": {"replicas": 0, "command": SLEEPER,
                            "plannerRole": "decode"}})

    class PlaneStub:
        async def kv_get_prefix(self, prefix):
            assert prefix == "instances/"
            return {
                "instances/ns/w/gen:2a": msgpack.packb({
                    "namespace": "ns", "component": "w", "endpoint": "gen",
                    "instance_id": 42, "metadata": {"pod": "w-0-1"}}),
                "instances/ns/w/gen:2b": msgpack.packb({
                    "namespace": "ns", "component": "w", "endpoint": "gen",
                    "instance_id": 43, "metadata": {}}),  # no pod: ignored
                "instances/ns/w/gen:2c": b"not msgpack",  # tolerated
            }

    op = ProcessOperator(spec, plane=PlaneStub(), tick_s=0.05)
    await op._refresh_observed()
    assert op._registered_pods == {"w-0-1": 42}


# ------------------------------------------------------- bench-side helpers

def test_mix_parser():
    import random

    m = Mix("interactive=0.5,batch=0.5")
    rng = random.Random(7)
    picks = [m.pick(rng) for _ in range(400)]
    assert 120 < picks.count("interactive") < 280  # both sides sampled
    assert set(picks) == {"interactive", "batch"}
    # bare names = uniform weights; empty = no header
    assert Mix("a,b").choices == [("a", 1.0), ("b", 1.0)]
    assert not Mix("") and Mix("").pick(rng) is None
    with pytest.raises(ValueError):
        Mix("a=x")
    with pytest.raises(ValueError):
        Mix("a=0,b=0")
    with pytest.raises(ValueError):
        Mix("a=-1")


def test_metrics_aggregator_expires_stale_workers():
    """A drained/crashed worker's last report must age out of the
    aggregate, or the autoscaler reads phantom backlog forever."""
    from dynamo_tpu.router.protocols import (
        ForwardPassMetrics, KvStats, SpecDecodeStats, WorkerStats,
    )
    from dynamo_tpu.router.publisher import MetricsAggregator

    agg = MetricsAggregator(plane=None, stale_after_s=0.05)
    m = ForwardPassMetrics(
        worker_stats=WorkerStats(request_active_slots=2,
                                 request_total_slots=4,
                                 num_requests_waiting=6),
        kv_stats=KvStats(), spec_decode_stats=SpecDecodeStats())
    agg.latest[1] = m
    agg._seen_at[1] = time.monotonic()
    assert agg.aggregate()["requests_waiting"] == 6
    assert agg.aggregate()["total_slots"] == 4
    agg._seen_at[1] = time.monotonic() - 1.0  # worker went silent
    assert agg.aggregate()["workers"] == 0
    assert agg.aggregate()["requests_waiting"] == 0
