"""Stream-gap detection and radix resync.

The hub's durable streams are ring buffers (runtime/control_plane.py
STREAM_MAX_LEN): past the cap the oldest entries silently vanish. Round-3
verdict: a slow or restarted router could lose KV events with no resync
signal, leaving its radix index silently stale. The recovery protocol
mirrors the reference's durable-consumer resync
(ref: lib/llm/src/kv_router/subscriber.rs:30-65):

- the plane exposes ``stream_first_seq`` (JetStream FirstSeq analog),
- the indexer detects gaps at subscribe time (truncated past resume point)
  and mid-stream (seq discontinuity, incl. hub-restart regression),
- on gap it drops the tree and publishes on ``kv_resync.<stream>``,
- every worker's KvEventPublisher answers by re-announcing its mirror of
  currently-held blocks (idempotent stored upserts).
"""

import asyncio

import pytest

from dynamo_tpu.router.indexer import KvIndexer
from dynamo_tpu.router.publisher import KvEventPublisher
from dynamo_tpu.router.protocols import StoredBlock
from dynamo_tpu.runtime.control_plane import LocalControlPlane

pytestmark = pytest.mark.anyio

BS = 4  # kv block size for these tests


async def _announce_chain(pub: KvEventPublisher, hashes: list[int], base: int = 0):
    """Announce a chain of blocks whose tokens_hash == block_hash + base."""
    blocks = [StoredBlock(block_hash=h, tokens_hash=h + base) for h in hashes]
    await pub.publish_stored(None, blocks)


async def _drain(indexer: KvIndexer, timeout: float = 2.0):
    """Wait until the indexer has consumed everything currently in the stream."""
    last = await indexer.plane.stream_last_seq(indexer.stream)
    deadline = asyncio.get_running_loop().time() + timeout
    while indexer._last_seq < last:
        if asyncio.get_running_loop().time() > deadline:
            raise TimeoutError(f"indexer stuck at {indexer._last_seq} < {last}")
        await asyncio.sleep(0.01)
        last = await indexer.plane.stream_last_seq(indexer.stream)


async def test_stream_first_seq_tracks_truncation():
    plane = LocalControlPlane(stream_max_len=4)
    assert await plane.stream_first_seq("s") == 1  # empty stream: next seq
    for i in range(10):
        await plane.stream_publish("s", bytes([i]))
    assert await plane.stream_last_seq("s") == 10
    assert await plane.stream_first_seq("s") == 7  # 4 retained: 7..10
    await plane.close()


async def test_subscribe_time_gap_triggers_resync():
    """A router that subscribes after the ring truncated must not serve a
    silently-empty index: it asks workers to re-announce."""
    plane = LocalControlPlane(stream_max_len=4)
    pub = await KvEventPublisher(plane, worker_id=7, kv_block_size=BS).start_resync_responder()

    # worker announces 3 chains; ring cap 4 then floods with removals of
    # unknown blocks so every stored event is truncated out of the ring
    await _announce_chain(pub, [1, 2, 3])
    await _announce_chain(pub, [10, 11])
    await pub.publish_removed([999])  # no-op remove, just stream traffic
    for _ in range(8):
        await plane.stream_publish("kv_events", b"\x81\xa1x\x01")  # junk filler

    # fresh router joins late: resume point 0 but first retained seq > 1
    idx = await KvIndexer(plane, kv_block_size=BS).start()
    try:
        assert idx.gaps_detected == 1
        assert idx.resyncs_requested == 1
        # resync replay flows through the stream; wait for it
        for _ in range(200):
            if idx.tree.find_matches([1, 2, 3]).best() == 3:
                break
            await asyncio.sleep(0.01)
        scores = idx.tree.find_matches([1, 2, 3])
        assert scores.scores == {7: 3}
        assert idx.tree.find_matches([10, 11]).scores == {7: 2}
        assert pub.resyncs_served == 1
    finally:
        await idx.stop()
        await pub.stop()
        await plane.close()


async def test_midlife_gap_triggers_resync():
    """A seq discontinuity on a live subscription (overflow outran the
    consumer, or the hub restarted and seqs regressed) drops + rebuilds."""
    plane = LocalControlPlane()
    pub = await KvEventPublisher(plane, worker_id=3, kv_block_size=BS).start_resync_responder()
    idx = await KvIndexer(plane, kv_block_size=BS).start()
    try:
        await _announce_chain(pub, [5, 6])
        await _drain(idx)
        assert idx.tree.find_matches([5, 6]).best() == 2

        # simulate the consumer having missed 100 events: jump the stream seq
        seq, entries = plane._streams["kv_events"]
        plane._streams["kv_events"] = (seq + 100, entries)
        await _announce_chain(pub, [20, 21])

        for _ in range(200):
            if (idx.tree.find_matches([5, 6]).best() == 2
                    and idx.tree.find_matches([20, 21]).best() == 2):
                break
            await asyncio.sleep(0.01)
        assert idx.gaps_detected == 1
        # tree was rebuilt from the worker's mirror: old AND new chains present
        assert idx.tree.find_matches([5, 6]).scores == {3: 2}
        assert idx.tree.find_matches([20, 21]).scores == {3: 2}
    finally:
        await idx.stop()
        await pub.stop()
        await plane.close()


async def test_publisher_mirror_tracks_removals():
    """The resync replay must not resurrect blocks the worker evicted."""
    plane = LocalControlPlane()
    pub = await KvEventPublisher(plane, worker_id=9, kv_block_size=BS).start_resync_responder()
    idx = await KvIndexer(plane, kv_block_size=BS).start()
    try:
        await _announce_chain(pub, [1, 2, 3])
        await pub.publish_removed([3])
        await _drain(idx)
        assert idx.tree.find_matches([1, 2, 3]).best() == 2

        # force a gap → resync; evicted block 3 must NOT come back
        seq, entries = plane._streams["kv_events"]
        plane._streams["kv_events"] = (seq + 50, entries)
        await _announce_chain(pub, [40])
        for _ in range(200):
            if idx.tree.find_matches([40]).best() == 1:
                break
            await asyncio.sleep(0.01)
        assert idx.tree.find_matches([1, 2, 3]).scores == {9: 2}
        assert (9, 3) not in idx.tree._lookup
    finally:
        await idx.stop()
        await pub.stop()
        await plane.close()


async def test_eviction_racing_replay_cannot_resurrect_block():
    """A removed(h) issued WHILE a resync replay is in flight must land
    after the replay's stored(h) on the stream (publish-lock atomicity) —
    otherwise the router would believe h exists after the worker evicted it."""

    class SlowStreamPlane(LocalControlPlane):
        async def stream_publish(self, stream, payload):
            await asyncio.sleep(0.01)  # widen the interleaving window
            return await super().stream_publish(stream, payload)

    plane = SlowStreamPlane()
    pub = await KvEventPublisher(plane, worker_id=4, kv_block_size=BS).start_resync_responder()
    idx = await KvIndexer(plane, kv_block_size=BS).start()
    try:
        # many single-block chains → replay spans many stream appends
        for h in range(100, 120):
            await _announce_chain(pub, [h])
        await _drain(idx)

        replay = asyncio.get_running_loop().create_task(pub._replay_announced())
        await asyncio.sleep(0.03)  # replay is mid-flight now
        await pub.publish_removed([110])
        await replay
        await _drain(idx)
        assert idx.tree.find_matches([110]).best() == 0
        assert (4, 110) not in idx.tree._lookup
        assert idx.tree.find_matches([111]).scores == {4: 1}
    finally:
        await idx.stop()
        await pub.stop()
        await plane.close()


async def test_orphan_chain_triggers_resync_without_tree_reset():
    """A stored event with an unknown parent is dropped (no phantom
    root-anchored prefix matches) and provokes a worker replay."""
    plane = LocalControlPlane()
    pub = await KvEventPublisher(plane, worker_id=6, kv_block_size=BS).start_resync_responder()
    idx = await KvIndexer(plane, kv_block_size=BS).start()
    try:
        await _announce_chain(pub, [1, 2])
        await _drain(idx)
        # mid-chain announcement whose parent the INDEXER never saw: publish
        # directly, bypassing the mirror bookkeeping of a real parent
        import msgpack
        from dynamo_tpu.router.protocols import KvCacheEvent, RouterEvent
        ev = RouterEvent(6, KvCacheEvent.stored(
            999, 12345, [StoredBlock(block_hash=50, tokens_hash=50)]))
        await plane.stream_publish("kv_events", msgpack.packb(ev.to_wire()))
        for _ in range(200):
            if idx.resyncs_requested >= 1 and idx.tree.find_matches([1, 2]).best() == 2:
                break
            await asyncio.sleep(0.01)
        assert idx.tree.orphan_events == 1
        assert idx.gaps_detected == 0          # tree was NOT reset
        assert idx.resyncs_requested == 1
        # the orphan block never shows up as a false FIRST-block match
        assert idx.tree.find_matches([50]).best() == 0
        assert idx.tree.find_matches([1, 2]).scores == {6: 2}
    finally:
        await idx.stop()
        await pub.stop()
        await plane.close()


async def test_replay_skips_chains_with_evicted_ancestors():
    """A dangling mirror entry (parent evicted, child surviving) must NOT be
    replayed: it would be an eternal orphan at every indexer, re-triggering
    fleet-wide replays forever."""
    plane = LocalControlPlane()
    pub = await KvEventPublisher(plane, worker_id=2, kv_block_size=BS).start_resync_responder()
    idx = await KvIndexer(plane, kv_block_size=BS).start()
    try:
        await _announce_chain(pub, [1, 2, 3])
        await pub.publish_removed([2])  # middle eviction: 3 is now dangling
        await _drain(idx)

        await pub._replay_announced()
        await _drain(idx)
        assert idx.tree.orphan_events == 0          # nothing undeliverable emitted
        assert idx.resyncs_requested == 0           # and no resync storm
        assert idx.tree.find_matches([1]).scores == {2: 1}
    finally:
        await idx.stop()
        await pub.stop()
        await plane.close()


async def test_hub_restart_epoch_change_detected_at_subscribe():
    """A router resuming from a pre-restart snapshot (old epoch, seq 500)
    against a reset stream (new epoch, seqs 1..N) must resync and consume
    the whole backlog — not filter it all as already-seen. Seqs alone can't
    distinguish this from a legitimate past-the-end subscribe; the snapshot
    epoch can."""
    import msgpack

    from dynamo_tpu.router.indexer import RADIX_BUCKET, RadixTree

    plane = LocalControlPlane()
    pub = await KvEventPublisher(plane, worker_id=8, kv_block_size=BS).start_resync_responder()
    # pre-restart snapshot: stale tree state at seq 500 in a PRIOR epoch
    stale = RadixTree()
    await plane.object_put(RADIX_BUCKET, "kv_events", msgpack.packb(
        {"seq": 500, "epoch": "dead-epoch", "tree": stale.dump()}))
    # post-restart world: the stream starts over at seq 1
    await _announce_chain(pub, [70, 71])

    idx = await KvIndexer(plane, kv_block_size=BS, snapshot_threshold=10000).start()
    try:
        assert idx.gaps_detected == 1
        for _ in range(200):
            if idx.tree.find_matches([70, 71]).best() == 2:
                break
            await asyncio.sleep(0.01)
        assert idx.tree.find_matches([70, 71]).scores == {8: 2}
        assert idx._last_seq >= 1  # cursor rebased into the new epoch
    finally:
        await idx.stop()
        await pub.stop()
        await plane.close()


async def test_subscribe_past_end_with_snapshot_is_not_a_gap():
    """Same-epoch snapshot resuming past the stream end = quiescent resume,
    NOT a hub restart — the restored tree must survive (regression guard
    for the r4 epoch check; this exact pattern broke once)."""
    import msgpack

    from dynamo_tpu.router.indexer import RADIX_BUCKET, RadixTree

    plane = LocalControlPlane()
    pub = KvEventPublisher(plane, worker_id=5, kv_block_size=BS)
    await _announce_chain(pub, [30, 31])
    idx = await KvIndexer(plane, kv_block_size=BS, snapshot_threshold=1).start()
    for _ in range(200):
        if idx.snapshots_written:
            break
        await asyncio.sleep(0.01)
    await idx.stop()

    last = await plane.stream_last_seq("kv_events")
    idx2 = await KvIndexer(plane, kv_block_size=BS,
                           snapshot_threshold=1).start(start_seq=last + 1)
    try:
        assert idx2.gaps_detected == 0
        assert idx2.tree.find_matches([30, 31]).scores == {5: 2}
    finally:
        await idx2.stop()
        await plane.close()


async def test_replay_survives_parent_reinsertion_behind_child():
    """remove-then-re-store moves a parent BEHIND its child in mirror
    order (dict re-insertion); the replay must still announce the full
    chain (fixpoint, not single-pass)."""
    plane = LocalControlPlane()
    pub = await KvEventPublisher(plane, worker_id=11, kv_block_size=BS).start_resync_responder()
    idx = await KvIndexer(plane, kv_block_size=BS).start()
    try:
        await _announce_chain(pub, [1])
        await pub.publish_stored(1, [StoredBlock(block_hash=2, tokens_hash=2)])
        await pub.publish_removed([1])
        await _announce_chain(pub, [1])  # parent now AFTER child in mirror
        await _drain(idx)

        # force a gap → full resync from the mirror
        seq, entries = plane._streams["kv_events"]
        plane._streams["kv_events"] = (seq + 50, entries)
        await _announce_chain(pub, [99])
        for _ in range(200):
            if (idx.tree.find_matches([1, 2]).best() == 2
                    and idx.tree.find_matches([99]).best() == 1):
                break
            await asyncio.sleep(0.01)
        assert idx.tree.find_matches([1, 2]).scores == {11: 2}
    finally:
        await idx.stop()
        await pub.stop()
        await plane.close()


async def test_no_spurious_resync_on_clean_stream():
    plane = LocalControlPlane()
    pub = KvEventPublisher(plane, worker_id=1, kv_block_size=BS)
    await _announce_chain(pub, [1])
    idx = await KvIndexer(plane, kv_block_size=BS).start()
    try:
        await _announce_chain(pub, [2])
        await _drain(idx)
        assert idx.gaps_detected == 0
        assert idx.resyncs_requested == 0
    finally:
        await idx.stop()
        await plane.close()
