"""Per-chip capacity interpolation from pre-deployment profiling.

ref: planner/utils/perf_interpolation.py + benchmarks/profiler/profile_sla.py
— the profiler sweeps a single prefill replica (TTFT vs request rate) and a
single decode replica (ITL vs per-chip token throughput at varying
concurrency); the planner inverts those curves: "what per-replica load keeps
us inside the SLA?"
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np


@dataclass
class ProfilePoint:
    load: float  # requests/s (prefill) or tokens/s (decode) per replica
    latency_ms: float  # TTFT (prefill) or ITL (decode)


@dataclass
class PerfInterpolator:
    """Monotone latency-vs-load curve with inversion."""

    points: list = field(default_factory=list)

    def __post_init__(self):
        self.points = sorted(
            (p if isinstance(p, ProfilePoint) else ProfilePoint(*p)
             for p in self.points),
            key=lambda p: p.load)

    @property
    def loads(self):
        return np.asarray([p.load for p in self.points])

    @property
    def lats(self):
        return np.asarray([p.latency_ms for p in self.points])

    def latency_at(self, load: float) -> float:
        """Interpolated latency at a per-replica load (clamped to the sweep)."""
        return float(np.interp(load, self.loads, self.lats))

    def min_load(self) -> float:
        """The sweep's lowest measured load — the most pessimistic
        capacity the profile can honestly claim for one replica."""
        return float(self.loads[0])

    def max_load_under(self, latency_target_ms: float) -> float:
        """Largest per-replica load whose latency stays ≤ target.

        0 means even an idle replica misses the SLA (impossible target);
        the last sweep point means the target never binds in-range.
        """
        loads, lats = self.loads, self.lats
        if latency_target_ms < lats[0]:
            return 0.0
        if latency_target_ms >= lats[-1]:
            return float(loads[-1])
        # walk segments; curve is assumed non-decreasing in load
        idx = int(np.searchsorted(lats, latency_target_ms, side="right")) - 1
        lo, hi = self.points[idx], self.points[idx + 1]
        if hi.latency_ms == lo.latency_ms:
            return float(hi.load)
        frac = (latency_target_ms - lo.latency_ms) / (hi.latency_ms - lo.latency_ms)
        return float(lo.load + frac * (hi.load - lo.load))


@dataclass
class PerfInterpolator2D:
    """Latency over (ISL, load): one monotone curve per profiled ISL.

    The reference interpolates TTFT over the ISL dimension too (ref:
    planner/utils/perf_interpolation.py:48); r1 approximated it with a
    single linear rescale. Queries between profiled ISLs blend the two
    neighbouring curves linearly; outside the profiled range the nearest
    curve is used (clamped — extrapolating a superlinear prefill cost from
    two points misleads more than it helps).
    """

    curves: dict = field(default_factory=dict)  # isl -> PerfInterpolator|points

    def __post_init__(self):
        self.curves = {
            float(isl): (c if isinstance(c, PerfInterpolator)
                         else PerfInterpolator(points=list(c)))
            for isl, c in self.curves.items()
        }
        self._isls = sorted(self.curves)
        if not self._isls:
            raise ValueError("PerfInterpolator2D needs at least one ISL sweep")

    def _neighbors(self, isl: float):
        isls = self._isls
        if isl <= isls[0]:
            return isls[0], isls[0], 0.0
        if isl >= isls[-1]:
            return isls[-1], isls[-1], 0.0
        idx = int(np.searchsorted(isls, isl, side="right")) - 1
        lo, hi = isls[idx], isls[idx + 1]
        return lo, hi, (isl - lo) / (hi - lo)

    def max_load_under(self, latency_target_ms: float, isl: float) -> float:
        lo, hi, t = self._neighbors(isl)
        a = self.curves[lo].max_load_under(latency_target_ms)
        b = self.curves[hi].max_load_under(latency_target_ms)
        return float(a + t * (b - a))

    def latency_at(self, load: float, isl: float) -> float:
        lo, hi, t = self._neighbors(isl)
        a = self.curves[lo].latency_at(load)
        b = self.curves[hi].latency_at(load)
        return float(a + t * (b - a))

    def min_load(self, isl: float) -> float:
        """Blended lowest measured load at this ISL (see
        :meth:`PerfInterpolator.min_load`)."""
        lo, hi, t = self._neighbors(isl)
        a = self.curves[lo].min_load()
        b = self.curves[hi].min_load()
        return float(a + t * (b - a))

    @staticmethod
    def from_profile(profile: dict) -> "PerfInterpolator2D":
        """Build from profile_sla.py output's ``prefill_by_isl`` table."""
        return PerfInterpolator2D(curves={
            float(isl): pts for isl, pts in profile["prefill_by_isl"].items()
        })
