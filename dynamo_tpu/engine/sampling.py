"""On-device sampling: greedy / temperature / top-k / top-p, per-row params.

Sampling runs inside jit on the [B, V] logits produced by the step fn, so
only B sampled token ids (plus optional logprobs) cross the device→host
boundary per step — never the logits. Per-row PRNG keys make per-request
``seed`` deterministic regardless of batch composition (ref parity:
SamplingOptions — lib/llm/src/protocols/common.rs:275-330).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

#: static cap for top-k masking (rows with top_k<=0 or >= cap are unrestricted)
TOP_K_CAP = 64


def _mask_top_k(logits, top_k):
    """Keep each row's top-k logits (k dynamic per row, capped at TOP_K_CAP;
    the cap clamps to the vocab for toy models smaller than it)."""
    cap = min(TOP_K_CAP, logits.shape[-1])
    vals, _ = jax.lax.top_k(logits, cap)  # [B, cap] sorted desc
    k = jnp.clip(top_k, 1, cap)
    kth = vals[jnp.arange(logits.shape[0]), k - 1]  # [B]
    use = (top_k > 0) & (top_k <= cap)
    cut = jnp.where(use, kth, -jnp.inf)
    return jnp.where(logits >= cut[:, None], logits, -jnp.inf)


def _mask_top_p(logits, top_p):
    """Nucleus: keep the smallest prefix of sorted probs with mass >= top_p."""
    sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # keep positions where the cumulative mass *before* this token < top_p
    keep_sorted = (cum - probs) < top_p[:, None]
    # threshold logit = smallest kept logit per row
    thresh = jnp.min(jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1)
    use = (top_p > 0.0) & (top_p < 1.0)
    cut = jnp.where(use, thresh, -jnp.inf)
    return jnp.where(logits >= cut[:, None], logits, -jnp.inf)


def sample(logits, temperature, top_k, top_p, keys):
    """Sample one token per row.

    The expensive paths (categorical draw; full-vocab sort for top-p) are
    gated behind ``lax.cond`` on whether ANY row needs them — an all-greedy
    decode batch (the common serving case) pays only the argmax, not a
    128k-wide sort per row per step.

    Args:
      logits: [B, V] f32.
      temperature: [B] f32 (0 → greedy).
      top_k: [B] i32 (0 → off). top_p: [B] f32 (0 or 1 → off).
      keys: [B] uint32 pair folded — jax PRNG keys, shape [B, 2].
    Returns: (tokens [B] i32, logprob_of_token [B] f32)
    """
    greedy_tok = jnp.argmax(logits, axis=-1)

    def sampled(_):
        temp = jnp.maximum(temperature, 1e-6)[:, None]
        scaled = logits / temp
        scaled = _mask_top_k(scaled, top_k)
        any_top_p = jnp.any((top_p > 0.0) & (top_p < 1.0))
        scaled = jax.lax.cond(any_top_p,
                              lambda s: _mask_top_p(s, top_p),
                              lambda s: s, scaled)
        sampled_tok = jax.vmap(_cat)(keys, scaled)
        return jnp.where(temperature <= 0.0, greedy_tok, sampled_tok)

    any_sampling = jnp.any(temperature > 0.0)
    tokens = jax.lax.cond(any_sampling, sampled, lambda _: greedy_tok, None)
    logp_all = jax.nn.log_softmax(logits, axis=-1)
    logp = logp_all[jnp.arange(logits.shape[0]), tokens]
    return tokens.astype(jnp.int32), logp


def _cat(key_data, row_logits):
    key = jax.random.wrap_key_data(key_data, impl="threefry2x32")
    return jax.random.categorical(key, row_logits)


@functools.partial(jax.jit, static_argnames=())
def sample_jit(logits, temperature, top_k, top_p, keys):
    return sample(logits, temperature, top_k, top_p, keys)


# ------------------------------------------------- structured decoding FSM

#: the same fill the host guided path uses (engine._sample) — NOT -inf, so
#: device-FSM and host-oracle streams stay bit-identical
FSM_MASK_FILL = -1e30


def apply_fsm_mask(logits, states, mask_table):
    """Mask each row to its FSM state's allowed-token set.

    ``states`` [B] int32 indexes ``mask_table`` [S, ceil(V/32)] uint32 —
    the structured runtime's packed bitmask arena (structured/runtime.py).
    State 0 is the all-allowed FREE row, making this an exact identity for
    unconstrained rows. One gather + a broadcast shift: no [B, V] host
    materialization, no per-row Python.
    """
    V = logits.shape[-1]
    words = mask_table[states]                       # [B, W32]
    ids = jnp.arange(V, dtype=jnp.uint32)
    bits = (words[:, (ids // 32).astype(jnp.int32)]
            >> (ids % 32)) & jnp.uint32(1)           # [B, V]
    return jnp.where(bits.astype(bool), logits, FSM_MASK_FILL)


def sample_masked(logits, temperature, top_k, top_p, keys, states,
                  mask_table, next_table):
    """FSM-constrained sampling: mask → sample → advance, all on device.

    Returns (tokens [B], logps [B], new_states [B]) — ``new_states`` is
    ``next_table[state, token]``, fed device-to-device by the pipelined
    decode loop exactly like the token column, so a constrained row costs
    no host sync between steps.
    """
    lg = apply_fsm_mask(logits, states, mask_table)
    toks, logps = sample(lg, temperature, top_k, top_p, keys)
    new_states = next_table[states, toks]
    return toks, logps, new_states


@functools.partial(jax.jit, static_argnames=())
def sample_masked_jit(logits, temperature, top_k, top_p, keys, states,
                      mask_table, next_table):
    return sample_masked(logits, temperature, top_k, top_p, keys, states,
                         mask_table, next_table)


def make_keys(seeds, steps):
    """Host helper: per-row threefry key data from (seed, step). [B,2] uint32.

    Pure numpy — any distinct (seed, step) pair is a distinct valid key, so no
    per-row jax dispatch is needed on the hot decode path.
    """
    import numpy as np

    out = np.zeros((len(seeds), 2), dtype=np.uint32)
    for i, (s, st) in enumerate(zip(seeds, steps)):
        out[i, 0] = int(s) & 0xFFFFFFFF
        out[i, 1] = int(st) & 0xFFFFFFFF
    return out


@functools.lru_cache(maxsize=8)
def make_topk_logprobs_fn(k: int):
    """Jitted (logits [B,V], toks [B]) -> (top ids [B,k], top logprobs [B,k],
    selected logprob [B]) — all from ONE device log_softmax, so the selected
    value and its own top-k entry can never disagree by an ulp. Device-side
    top-k keeps the host transfer at O(B*k) instead of copying the whole
    padded [B,V] logits batch (perf/logprobs capture path)."""

    def fn(logits, toks):
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        vals, ids = jax.lax.top_k(lp, min(k, logits.shape[-1]))
        sel = jnp.take_along_axis(lp, toks[:, None], axis=1)[:, 0]
        return ids, vals, sel

    return jax.jit(fn)
